#!/usr/bin/env python
"""Benchmark: training throughput of the flagship config on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: point-pairs/sec/chip for the reference training configuration
(8,192 points, 8 GRU iterations, full train step incl. backward+Adam).

Baseline (BASELINE.md): the reference trains 20 epochs x 17,640 samples in
~53 h on 2x RTX 2080 Ti => 1.849 samples/s total, 0.925 samples/s per GPU
= 7,575 point-pairs/s per GPU at 8,192 points/sample. vs_baseline is our
per-chip rate over that per-GPU rate.

Tries the fastest numerics first (bf16 + Pallas voxel kernel + approximate
top-k) and falls back to progressively safer configurations if a variant
fails to compile/run, so a kernel regression can never zero the benchmark.
"""

from __future__ import annotations

import json
import os
import time
import traceback

import numpy as np

BASELINE_PAIRS_PER_SEC_PER_CHIP = 17640 * 20 / (53 * 3600) / 2 * 8192  # ~7575

# Env overrides are for smoke-testing the bench itself on small hosts; the
# driver runs the defaults (the reference training configuration).
N_POINTS = int(os.environ.get("PVRAFT_BENCH_POINTS", 8192))
ITERS = int(os.environ.get("PVRAFT_BENCH_ITERS", 8))
BATCH = int(os.environ.get("PVRAFT_BENCH_BATCH", 2))  # reference run.sh bs
TRUNCATE_K = int(os.environ.get("PVRAFT_BENCH_K", 512))


def _unit() -> str:
    return (
        f"point-pairs/s/chip ({N_POINTS} pts, {ITERS} iters, "
        f"bs={BATCH}, fwd+bwd+adam)"
    )


def _devices_with_watchdog(timeout_s: float = 600.0):
    """Initialize the backend with a timeout: a wedged remote TPU claim
    (observed when a client dies mid-compile) would otherwise hang forever."""
    import threading

    import jax

    result = {}

    def probe():
        try:
            result["devices"] = jax.devices()
        except Exception as e:  # pragma: no cover
            result["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" not in result:
        print(
            json.dumps(
                {
                    "metric": "train_point_pairs_per_sec_per_chip",
                    "value": 0.0,
                    "unit": _unit(),
                    "vs_baseline": 0.0,
                    "note": f"backend init failed/hung ({result.get('error', 'timeout')})",
                }
            )
        )
        raise SystemExit(0)
    return result["devices"]


def _run_variant(model_kwargs: dict) -> float:
    """Steady-state seconds per train step for one model configuration."""
    import jax
    import jax.numpy as jnp
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.models import PVRaft

    cfg = ModelConfig(truncate_k=TRUNCATE_K, **model_kwargs)
    model = PVRaft(cfg)

    rng = np.random.default_rng(0)
    pc1 = jnp.asarray(rng.uniform(-1, 1, (BATCH, N_POINTS, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (BATCH, N_POINTS, 3)).astype(np.float32))
    gt = pc2 - pc1
    mask = jnp.ones((BATCH, N_POINTS), jnp.float32)

    params = model.init(jax.random.key(0), pc1[:, :256], pc2[:, :256], 2)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, pc1, pc2, mask, gt):
        def loss_fn(p):
            flows, _ = model.apply(p, pc1, pc2, ITERS)
            return sequence_loss(flows, mask, gt, 0.8)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    # Warmup / compile.
    params, opt_state, loss = step(params, opt_state, pc1, pc2, mask, gt)
    jax.block_until_ready(loss)
    if not np.isfinite(float(loss)):
        raise FloatingPointError("non-finite loss")

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, pc1, pc2, mask, gt)
    jax.block_until_ready(loss)
    return (time.perf_counter() - t0) / n_steps


VARIANTS = [
    ("bf16+pallas+approx", dict(compute_dtype="bfloat16", use_pallas=True,
                                approx_topk=True)),
    ("bf16+approx", dict(compute_dtype="bfloat16", approx_topk=True)),
    ("bf16", dict(compute_dtype="bfloat16")),
    ("fp32", dict()),
]


def main() -> None:
    _devices_with_watchdog()

    # First success wins: variants are ordered fastest-expected first, so
    # benching later ones would only add compile time.
    best = None
    note = []
    for name, kwargs in VARIANTS:
        try:
            dt = _run_variant(kwargs)
            note.append(f"{name}:{dt*1e3:.0f}ms")
            best = (name, dt)
            break
        except Exception:
            note.append(f"{name}:failed")
            traceback.print_exc()

    if best is None:
        print(
            json.dumps(
                {
                    "metric": "train_point_pairs_per_sec_per_chip",
                    "value": 0.0,
                    "unit": _unit(),
                    "vs_baseline": 0.0,
                    "note": "all variants failed: " + ",".join(note),
                }
            )
        )
        return

    name, dt = best
    pairs_per_sec = BATCH * N_POINTS / dt
    out = {
        "metric": "train_point_pairs_per_sec_per_chip",
        "value": round(pairs_per_sec, 1),
        "unit": _unit(),
        "vs_baseline": round(pairs_per_sec / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3),
        "variant": name,
    }
    if len(note) > 1:  # earlier variants failed — surface the degradation
        out["note"] = ",".join(note)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
