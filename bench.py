#!/usr/bin/env python
"""Benchmark: training throughput of the flagship config on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Metric: point-pairs/sec/chip for the reference training configuration
(8,192 points, 8 GRU iterations, full train step incl. backward+Adam).

Baseline (BASELINE.md): the reference trains 20 epochs x 17,640 samples in
~53 h on 2x RTX 2080 Ti => 1.849 samples/s total, 0.925 samples/s per GPU
= 7,575 point-pairs/s per GPU at 8,192 points/sample. vs_baseline is our
per-chip rate over that per-GPU rate.
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_PAIRS_PER_SEC_PER_CHIP = 17640 * 20 / (53 * 3600) / 2 * 8192  # ~7575


def _devices_with_watchdog(timeout_s: float = 600.0):
    """Initialize the backend with a timeout: a wedged remote TPU claim
    (observed when a client dies mid-compile) would otherwise hang forever."""
    import threading

    import jax

    result = {}

    def probe():
        try:
            result["devices"] = jax.devices()
        except Exception as e:  # pragma: no cover
            result["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" not in result:
        print(
            json.dumps(
                {
                    "metric": "train_point_pairs_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "point-pairs/s/chip (8192 pts, 8 iters, bs=2, fwd+bwd+adam)",
                    "vs_baseline": 0.0,
                    "note": f"backend init failed/hung ({result.get('error', 'timeout')})",
                }
            )
        )
        raise SystemExit(0)
    return result["devices"]


def main() -> None:
    _devices_with_watchdog()

    import jax
    import jax.numpy as jnp
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.models import PVRaft

    n_points = 8192
    iters = 8
    batch = 2  # reference run.sh batch size

    cfg = ModelConfig(truncate_k=512)
    model = PVRaft(cfg)

    rng = np.random.default_rng(0)
    pc1 = jnp.asarray(rng.uniform(-1, 1, (batch, n_points, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (batch, n_points, 3)).astype(np.float32))
    gt = pc2 - pc1
    mask = jnp.ones((batch, n_points), jnp.float32)

    params = model.init(jax.random.key(0), pc1[:, :256], pc2[:, :256], 2)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    @jax.jit
    def step(params, opt_state, pc1, pc2, mask, gt):
        def loss_fn(p):
            flows, _ = model.apply(p, pc1, pc2, iters)
            return sequence_loss(flows, mask, gt, 0.8)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    # Warmup / compile.
    params, opt_state, loss = step(params, opt_state, pc1, pc2, mask, gt)
    jax.block_until_ready(loss)

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        params, opt_state, loss = step(params, opt_state, pc1, pc2, mask, gt)
    jax.block_until_ready(loss)
    dt = (time.perf_counter() - t0) / n_steps

    pairs_per_sec = batch * n_points / dt
    print(
        json.dumps(
            {
                "metric": "train_point_pairs_per_sec_per_chip",
                "value": round(pairs_per_sec, 1),
                "unit": "point-pairs/s/chip (8192 pts, 8 iters, bs=2, fwd+bwd+adam)",
                "vs_baseline": round(
                    pairs_per_sec / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
