#!/usr/bin/env python
"""Benchmark: training throughput of the flagship config on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.

Metric: point-pairs/sec/chip for the reference training configuration
(8,192 points, 8 GRU iterations, full train step incl. backward+Adam).

Baseline (BASELINE.md): the reference trains 20 epochs x 17,640 samples in
~53 h on 2x RTX 2080 Ti => 1.849 samples/s total, 0.925 samples/s per GPU
= 7,575 point-pairs/s per GPU at 8,192 points/sample. vs_baseline is our
per-chip rate over that per-GPU rate.

Structure: the parent process NEVER imports jax. Every backend probe and
every measured variant runs in its own child process with a hard timeout;
a wedged TPU claim (observed round 1: backend init hung past a 600 s
watchdog) dies with its child and the parent retries in a fresh process.
Variants are ordered fastest-expected first (bf16 + Pallas voxel kernel +
approximate top-k) and fall back to progressively safer configurations, so
a kernel regression can never zero the benchmark. If the accelerator stays
unreachable after genuine retries, a CPU-backend measurement is reported
(clearly labeled) rather than a zero.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_PAIRS_PER_SEC_PER_CHIP = 17640 * 20 / (53 * 3600) / 2 * 8192  # ~7575

# Env overrides are for smoke-testing the bench itself on small hosts; the
# driver runs the defaults (the reference training configuration).
N_POINTS = int(os.environ.get("PVRAFT_BENCH_POINTS", 8192))
ITERS = int(os.environ.get("PVRAFT_BENCH_ITERS", 8))
BATCH = int(os.environ.get("PVRAFT_BENCH_BATCH", 2))  # reference run.sh bs
TRUNCATE_K = int(os.environ.get("PVRAFT_BENCH_K", 512))

# Global wall-clock budget for the whole bench (probes + all retries).
DEADLINE = time.monotonic() + float(os.environ.get("PVRAFT_BENCH_BUDGET_S", 2700))

PROBE_TIMEOUT_S = float(os.environ.get("PVRAFT_BENCH_PROBE_TIMEOUT_S", 240))
# First compile of the full model through the remote-compile tunnel has been
# observed to take several minutes; killing a child mid-compile can wedge
# the TPU claim, so variant children get a generous window.
VARIANT_TIMEOUT_S = float(os.environ.get("PVRAFT_BENCH_VARIANT_TIMEOUT_S", 1200))

# Variant ladder and A/B lever enumeration come from the program
# registry's data module (pvraft_tpu/programs/geometries.py — pure data,
# no jax import, so the parent process stays jax-free). The registry
# also AOT-certifies the flagship subset of these same dicts
# (programs/catalog.py), so the ladder bench measures and the programs
# the readiness sweep compiles cannot drift apart.
from pvraft_tpu.programs.geometries import AB_LEVERS, BENCH_VARIANTS

VARIANTS = list(BENCH_VARIANTS)


def _unit(points: int = N_POINTS, iters: int = ITERS,
          batch: int = BATCH) -> str:
    return (
        f"point-pairs/s/chip ({points} pts, {iters} iters, "
        f"bs={batch}, fwd+bwd+adam)"
    )


# ---------------------------------------------------------------- child ----


def _maybe_pin_cpu() -> None:
    """Child-side CPU pin. Must use the config API: the TPU plugin's
    sitecustomize forces jax_platforms at interpreter start, so a
    JAX_PLATFORMS env var set by the parent is silently overridden."""
    if "--cpu" in sys.argv:
        import jax

        jax.config.update("jax_platforms", "cpu")


def _child_probe() -> None:
    """Initialize the backend and report the platform. Hangs die with us."""
    _maybe_pin_cpu()
    import jax

    devices = jax.devices()
    print(json.dumps({"ok": True, "platform": devices[0].platform,
                      "n_devices": len(devices)}))


def _child_variant(name: str) -> None:
    """Measure steady-state seconds/step for one variant; print one line."""
    _maybe_pin_cpu()
    kwargs = dict(VARIANTS)[name]

    # Backward-path A/B levers (PR "scatter-free VJPs + remat policy"):
    # opt-in env flags so the same variant ladder can be re-measured with
    # the optimized backward and the pair recorded side by side
    # (BENCHMARKS.md "Backward-path A/B"). The lever records — env var,
    # target field, arming rule — are registry declarations (AB_LEVERS);
    # "flag" levers arm on the literal "1", "str" levers on any
    # non-empty value, and "step_arg" levers feed the step factory
    # (grad_dtype) instead of ModelConfig.
    ab_flags = {}
    grad_dtype = None
    for lever in AB_LEVERS:
        raw = os.environ.get(lever["env"], "")
        if lever["kind"] == "flag":
            if raw != "1":
                continue
            val = True
        else:
            if not raw:
                continue
            val = raw
        ab_flags[lever["field"]] = val
        if lever.get("step_arg"):
            grad_dtype = val
        else:
            kwargs = dict(kwargs, **{lever["field"]: val})

    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.models import PVRaft

    platform = jax.devices()[0].platform
    unroll = int(os.environ.get("PVRAFT_BENCH_UNROLL", 1))
    if (platform == "tpu" and name == "fp32"
            and N_POINTS >= 8192 and BATCH >= 2):
        # Plain fp32 fwd+bwd+adam needs 19.5 GiB HBM at the flagship
        # shape — over a 16 GiB v5e chip (AOT-certified,
        # artifacts/aot_readiness.json) — so the fp32 rung checkpoints
        # each GRU iteration on TPU. Identical floats, extra recompute
        # FLOPs: acceptable in a last-rung fallback that otherwise OOMs.
        # CPU fallback keeps remat off for round-over-round continuity.
        kwargs = dict(kwargs, remat=True)
    cfg = ModelConfig(truncate_k=TRUNCATE_K, scan_unroll=unroll, **kwargs)
    model = PVRaft(cfg)

    rng = np.random.default_rng(0)
    pc1 = jnp.asarray(rng.uniform(-1, 1, (BATCH, N_POINTS, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (BATCH, N_POINTS, 3)).astype(np.float32))
    gt = pc2 - pc1
    mask = jnp.ones((BATCH, N_POINTS), jnp.float32)

    # Init on a small cloud (params are point-count independent) — but it
    # must still hold >= truncate_k candidate points for corr_init.
    n_init = min(N_POINTS, max(256, TRUNCATE_K))
    params = model.init(jax.random.key(0), pc1[:, :n_init], pc2[:, :n_init], 2)
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)

    import functools

    from pvraft_tpu.engine.steps import maybe_cast_grads

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, pc1, pc2, mask, gt):
        def loss_fn(p):
            flows, _ = model.apply(p, pc1, pc2, ITERS)
            return sequence_loss(flows, mask, gt, 0.8)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        grads = maybe_cast_grads(grads, grad_dtype)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    # Warmup / compile.
    params, opt_state, loss = step(params, opt_state, pc1, pc2, mask, gt)
    jax.block_until_ready(loss)
    if not np.isfinite(float(loss)):
        raise FloatingPointError("non-finite loss")

    def time_pytree(n):
        # Host fetch of the final loss, not just block_until_ready: the
        # tunnel has satisfied block_until_ready before execution (the
        # 115 us/scene eval artifact). The chain's dataflow makes one
        # scalar D2H force every step; its cost is per-measurement, not
        # per-step.
        nonlocal params, opt_state, loss
        t0 = time.perf_counter()
        for _ in range(n):
            params, opt_state, loss = step(params, opt_state, pc1, pc2,
                                           mask, gt)
        float(np.asarray(loss))
        return (time.perf_counter() - t0) / n

    # CPU fallback steps are minutes each at 8,192 points — keep it short.
    n_steps = 10 if platform != "cpu" else 2
    strategy = "pytree"
    # Default K=8: the configuration PROVEN to execute on chip at the
    # flagship shape; K=32 is the exact config multistep_probe.jsonl
    # records as crashing the TPU worker there (a device fault in this
    # optional probe can leave the child's client unusable, degrading the
    # valid measurement already in hand). 32 remains an explicit override.
    fuse_k = int(os.environ.get("PVRAFT_BENCH_FUSE", 8))
    dt = time_pytree(2 if platform != "cpu" else n_steps)
    if platform == "cpu":
        # Repeat the measurement so the artifact records run-to-run spread
        # (a ~10% round-over-round drift in the CPU fallback was
        # unclassifiable as noise vs regression without it — round-3
        # verdict). Each rep re-times the SAME chained loop.
        dt_reps = [dt, time_pytree(n_steps)]
    if platform != "cpu" and dt > 0.5:
        # Chained-dispatch overhead detected (device step time is single-
        # digit ms at this config — BENCHMARKS.md): retime with the packed
        # single-buffer train step, which carries params+opt_state as one
        # flat array between steps (numerically identical; Trainer supports
        # it via ParallelConfig.packed_state). Keep whichever loop is
        # genuinely faster — both are real state-chained training loops.
        from pvraft_tpu.engine.steps import make_packed_train_step

        batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}
        pstep, flat, _ = make_packed_train_step(
            model, tx, 0.8, ITERS, params, opt_state, donate=True,
            grad_dtype=grad_dtype,
        )
        flat, m = pstep(flat, batch)  # warmup/compile
        jax.block_until_ready(m["loss"])

        def time_packed(n, roundtrip=False):
            nonlocal flat
            t0 = time.perf_counter()
            for _ in range(n):
                if roundtrip:
                    # D2H (sync point) + fresh H2D: breaks the chained-
                    # executable dependency through the host.
                    flat = jnp.asarray(np.asarray(flat))
                flat, m = pstep(flat, batch)
            # Host fetch: forces the full chain (see time_pytree).
            float(np.asarray(m["loss"]))
            return (time.perf_counter() - t0) / n

        dt_packed = time_packed(n_steps)
        if dt_packed < dt:
            strategy, dt = "packed", dt_packed
        else:
            # Keep sample counts consistent: the 2-step probe decided the
            # strategy; the reported number gets the full n_steps.
            dt = time_pytree(n_steps)
        if dt > 0.5:
            # Both chained loops still hit the tunnel's chained-dispatch
            # artifact: round-trip the single flat state buffer through
            # the host each step. A D2H+H2D of a few MB costs far less
            # than the multi-second chained dispatch, and the loop is
            # still a true training loop — identical floats, state
            # evolving every step, fresh (non-chained) device input.
            dt_rt = time_packed(n_steps, roundtrip=True)
            if dt_rt < dt:
                strategy, dt = "packed_host_roundtrip", dt_rt
        if dt > 0.5 and fuse_k > 1:
            # The decisive lever: fuse K optimizer steps into ONE dispatch
            # (lax.scan over the packed step — engine/steps.py:
            # make_multistep_train_step, Trainer --steps_per_dispatch).
            # Per-dispatch overhead is amortized K-fold; every step is
            # still a genuine fwd+bwd+adam with state carried step-to-step
            # and K DISTINCT pre-staged batches per dispatch.
            # Guarded: a failure of this OPTIONAL probe (the scan program
            # is far larger than the single step, and the tunnel's
            # remote-compile has been observed to 500 — eval_tpu.json)
            # must not destroy the packed measurement already in hand.
            # PVRAFT_BENCH_FUSE=1 disables the probe.
            try:
                from pvraft_tpu.engine.steps import make_multistep_train_step

                mstep, _, _ = make_multistep_train_step(
                    model, tx, 0.8, ITERS, params, opt_state, fuse_k,
                    donate=True, grad_dtype=grad_dtype,
                )
                stacked = [
                    {"pc1": jnp.asarray(rng.uniform(-1, 1, pc1.shape)
                                        .astype(np.float32)),
                     "pc2": jnp.asarray(rng.uniform(-1, 1, pc2.shape)
                                        .astype(np.float32)),
                     "mask": mask, "flow": gt}
                    for _ in range(fuse_k)
                ]
                mbatches = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *stacked
                )
                from jax.flatten_util import ravel_pytree

                mflat, _ = ravel_pytree((params, opt_state))
                mflat, mm = mstep(mflat, mbatches)  # warmup/compile
                jax.block_until_ready(mm["loss"])
                if not np.all(np.isfinite(np.asarray(mm["loss"]))):
                    raise FloatingPointError("non-finite loss in fused steps")

                def time_multi(n_dispatch):
                    nonlocal mflat
                    t0 = time.perf_counter()
                    for _ in range(n_dispatch):
                        mflat, mm = mstep(mflat, mbatches)
                    # Host fetch: forces the full chain (see time_pytree).
                    float(np.asarray(mm["loss"][-1]))
                    return (time.perf_counter() - t0) / (n_dispatch * fuse_k)

                dt_multi = time_multi(3)
                if dt_multi < dt:
                    strategy, dt = f"multistep{fuse_k}", dt_multi
            except Exception as e:  # noqa: BLE001 — report, keep packed dt
                sys.stderr.write(f"multistep probe failed: {e!r}\n")
    elif platform != "cpu":
        dt = time_pytree(n_steps)
    if platform != "cpu":
        # Second rep of the CHOSEN strategy so the artifact records
        # run-to-run spread (same rationale as the CPU branch above).
        try:
            if strategy == "pytree":
                dt2 = time_pytree(n_steps)
            elif strategy.startswith("multistep"):
                dt2 = time_multi(3)
            else:
                dt2 = time_packed(n_steps,
                                  roundtrip=strategy == "packed_host_roundtrip")
            dt_reps = [dt, dt2]
        except Exception as e:  # noqa: BLE001 — rep 1 is already valid
            sys.stderr.write(f"rep-2 timing failed: {e!r}\n")
            dt_reps = [dt]
    dt_mean = sum(dt_reps) / len(dt_reps)
    spread = (max(dt_reps) - min(dt_reps)) / max(dt_mean, 1e-12)
    # Optimizer steps behind each rep (multistep reps run 3 dispatches of
    # fuse_k fused steps each; every other path times n_steps).
    rep_steps = 3 * fuse_k if strategy.startswith("multistep") else n_steps
    print(json.dumps({"ok": True, "dt": dt_mean,
                      "dt_reps": [round(d, 6) for d in dt_reps],
                      "dt_spread": round(spread, 4),
                      "timing_reps": len(dt_reps),
                      **({"ab_flags": ab_flags} if ab_flags else {}),
                      # Per-rep optimizer-step counts, so a mixed-step-count
                      # rep list can never masquerade as run-to-run spread.
                      # Both reps of the chosen strategy run the same count:
                      # n_steps for the loop strategies, 3 dispatches x
                      # fuse_k for multistep.
                      "steps_per_rep": [rep_steps] * len(dt_reps),
                      "platform": platform, "strategy": strategy,
                      "points": N_POINTS, "batch": BATCH, "iters": ITERS,
                      "remat": cfg.remat}))


def _child_eval(name: str) -> None:
    """Eval-protocol throughput: scenes/s at bs=1, 32 GRU iters
    (``test.py:92,120``) — the other half of the capability story."""
    _maybe_pin_cpu()
    kwargs = dict(VARIANTS)[name]

    import numpy as np

    import jax
    import jax.numpy as jnp

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.steps import make_eval_step
    from pvraft_tpu.models import PVRaft

    platform = jax.devices()[0].platform
    cfg = ModelConfig(truncate_k=TRUNCATE_K, **kwargs)
    model = PVRaft(cfg)
    eval_iters = int(os.environ.get("PVRAFT_BENCH_EVAL_ITERS", 32))

    rng = np.random.default_rng(0)

    def make_batch():
        pc1 = jnp.asarray(
            rng.uniform(-1, 1, (1, N_POINTS, 3)).astype(np.float32))
        pc2 = jnp.asarray(
            rng.uniform(-1, 1, (1, N_POINTS, 3)).astype(np.float32))
        return {"pc1": pc1, "pc2": pc2,
                "mask": jnp.ones((1, N_POINTS), jnp.float32),
                "flow": pc2 - pc1}

    # One DISTINCT batch per timed call: the axon remote executor memoizes
    # executions with identical inputs (a repeat "runs" in ~0.1 ms no matter
    # the program), so a same-batch loop times cache hits, not eval.
    n_steps = 10
    batches = [make_batch() for _ in range(n_steps + 1)]

    n_init = min(N_POINTS, max(256, TRUNCATE_K))
    pc1 = batches[0]["pc1"]
    params = model.init(jax.random.key(0), pc1[:, :n_init],
                        batches[0]["pc2"][:, :n_init], 2)
    step = make_eval_step(model, eval_iters, 0.8)

    metrics, flow = step(params, batches[0])  # warmup/compile
    jax.block_until_ready(flow)
    if platform == "cpu":  # minutes/step at full config — keep it short
        batches = batches[:3]
    # Host fetch per scene, not just block_until_ready: the remote tunnel
    # has been observed to satisfy block_until_ready before the work ran
    # (a 115 us/step "eval" at a config whose train step is seconds). A
    # host scalar fetch cannot be faked, and the eval protocol needs the
    # metrics on host for its running means anyway (test.py:128-142).
    t0 = time.perf_counter()
    for b in batches[1:]:
        m, _ = step(params, b)
        float(np.asarray(m["loss"]))
    dt = (time.perf_counter() - t0) / (len(batches) - 1)
    strategy = "per_scene_host_sync"
    dt_scanned = None
    if platform != "cpu" and dt > 0.2:
        # Per-dispatch tunnel overhead dominates: scan S scenes per
        # dispatch (bs=1 each — protocol-exact) and fetch all S metric
        # sets at once. Every timed dispatch gets DISTINCT pre-staged
        # scenes so the remote executor's result memoization cannot
        # satisfy it from cache. Guarded: this optional leg compiles a
        # much larger program on a remote-compile path that has been
        # observed to 500 (eval_tpu.json's batched leg) — a failure must
        # not discard the per-scene measurement already in hand.
        try:
            n_scan, n_disp = len(batches) - 1, 3
            stacks = []
            for _ in range(n_disp + 1):
                group = [make_batch() for _ in range(n_scan)]
                stacks.append(
                    jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *group)
                )

            @jax.jit
            def fused(params, sb):
                def body(c, b):
                    m, _ = step(params, b)
                    return c, m

                return jax.lax.scan(body, 0, sb)[1]

            ms = fused(params, stacks[0])  # warmup/compile
            np.asarray(ms["loss"])
            t0 = time.perf_counter()
            for i in range(n_disp):
                ms = fused(params, stacks[1 + i])
                np.asarray(ms["loss"])
            dt_f = (time.perf_counter() - t0) / (n_disp * n_scan)
            # Reported SEPARATELY, never as the headline: the reference
            # protocol's running means need per-scene host fetches
            # (test.py:128-142), so the headline scenes/s stays the
            # per-scene-synced rate; the scanned rate shows what our
            # Evaluator's pre-staged scan mode reaches on this tunnel.
            dt_scanned = dt_f
        except Exception as e:  # noqa: BLE001 — keep the per-scene dt
            sys.stderr.write(f"scanned-eval probe failed: {e!r}\n")
    out = {"ok": True, "dt": dt, "platform": platform,
           "points": N_POINTS, "iters": eval_iters,
           "eval_strategy": strategy, "host_synced": True}
    if dt_scanned is not None:
        out["dt_scanned"] = dt_scanned
    print(json.dumps(out))


# --------------------------------------------------------------- parent ----


def _spawn(child_args: list, timeout_s: float, cpu: bool = False,
           env_overrides: dict = None):
    """Run a bench child; return (parsed JSON line or None, timed_out)."""
    env = dict(os.environ)
    if env_overrides:
        env.update(env_overrides)
    if cpu:
        child_args = list(child_args) + ["--cpu"]  # config-API pin (see _maybe_pin_cpu)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), *child_args],
            timeout=timeout_s,
            capture_output=True,
            text=True,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, True
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-2000:])
        return None, False
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            parsed = json.loads(line)
        except json.JSONDecodeError:
            continue
        if parsed.get("ok"):
            return parsed, False
    return None, False


def _remaining() -> float:
    return DEADLINE - time.monotonic()


def _emit(value: float, extra: dict, comparable: bool = True,
          platform: str = None) -> None:
    """One ``pvraft_bench/v1`` line (schema + validator:
    ``pvraft_tpu/obs/bench.py``; regression gate:
    ``scripts/bench_compare.py``).

    ``platform`` and ``comparable`` are first-class, validated fields —
    never note strings. ``comparable`` means "may be ratioed against the
    reference per-GPU baseline": it requires BOTH the flagship measured
    config (a rate from half the GRU iters and a quarter of the points
    must not be ratioed against the full-config baseline) AND the tpu
    platform (a CPU-fallback run must never read a nonzero vs_baseline —
    the BENCH_r05.json failure mode). Incomparable runs report 0.0."""
    platform = platform or extra.get("platform") or "unknown"
    comparable = bool(comparable) and platform == "tpu"
    out = {
        "schema": "pvraft_bench/v1",
        "metric": "train_point_pairs_per_sec_per_chip",
        "value": round(value, 1),
        "unit": _unit(),
        "platform": platform,
        "comparable": comparable,
        "vs_baseline": (
            round(value / BASELINE_PAIRS_PER_SEC_PER_CHIP, 3)
            if comparable else 0.0
        ),
    }
    out.update(extra)
    print(json.dumps(out))


def main() -> None:
    notes = []

    # Persistent executable cache shared with the TPU queue's jobs
    # (scripts/tpu_batch.sh): a driver-launched bench reuses executables
    # compiled earlier in the round instead of paying multi-minute remote
    # compiles inside its own budget. setdefault so an operator override
    # wins.
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "artifacts", "xla_cache"),
    )

    # 1. Backend probe, retried in fresh processes: a hung claim dies with
    #    its child and the next attempt gets a clean client.
    probe = None
    for attempt in range(3):
        budget = min(PROBE_TIMEOUT_S, max(_remaining(), 30.0))
        probe, _ = _spawn(["--child-probe"], budget)
        if probe is not None:
            break
        notes.append(f"probe{attempt + 1}:failed")
        if _remaining() < 120:
            break
    use_cpu_fallback = probe is None

    # 2. Measure variants, fastest-expected first; first success wins.
    #    A timed-out child (wedged claim / slow remote compile) earns one
    #    retry; a fast nonzero exit is deterministic — move on immediately.
    # After the first accelerator attempt, keep enough budget in reserve
    # that the CPU fallback (~5 min at the shrunk config incl. compile) can
    # still run after a worst-case string of hanging children — a zeroed
    # benchmark is the one outcome this structure exists to prevent. The
    # first attempt is exempt: with a small total budget the full-config
    # accelerator measurement is worth spending the reserve on.
    FALLBACK_RESERVE_S = 600.0
    reserve = 0.0

    best = None
    if not use_cpu_fallback:
        for name, _ in VARIANTS:
            if _remaining() < 60 + reserve:
                notes.append("deadline")
                break
            for attempt in range(2):
                budget = min(
                    VARIANT_TIMEOUT_S,
                    max(_remaining() - reserve, 60.0),
                )
                res, timed_out = _spawn(["--child-variant", name], budget)
                reserve = FALLBACK_RESERVE_S
                if (res is not None or not timed_out
                        or _remaining() < 120 + reserve):
                    break
                notes.append(f"{name}:timeout")
            if res is not None:
                notes.append(f"{name}:{res['dt'] * 1e3:.0f}ms")
                best = (name, res)
                break
            notes.append(f"{name}:failed")
        if best is None:
            use_cpu_fallback = True

    # 3. Last resort: a real measurement on the CPU backend — an honest
    #    (clearly labeled) number beats a zeroed benchmark.
    if use_cpu_fallback and best is None:
        notes.append(
            "accelerator unreachable after retries; cpu fallback"
            if probe is None else
            "budget exhausted before an accelerator variant completed; "
            "cpu fallback"
        )
        # A CPU step at the flagship config takes minutes; measure a smaller
        # labeled config rather than timing out to a zero.
        shrink = {
            "PVRAFT_BENCH_POINTS": str(min(N_POINTS, 2048)),
            "PVRAFT_BENCH_ITERS": str(min(ITERS, 4)),
            "PVRAFT_BENCH_K": str(min(TRUNCATE_K, 256)),
        }
        for name in ("bf16", "fp32"):
            budget = min(VARIANT_TIMEOUT_S, max(_remaining(), 60.0))
            res, _ = _spawn(["--child-variant", name], budget, cpu=True,
                            env_overrides=shrink)
            if res is not None:
                best = (name, res)
                break
            notes.append(f"cpu/{name}:failed")

    if best is None:
        _emit(0.0, {"note": "all variants failed: " + ",".join(notes)})
        return

    name, res = best
    points = int(res.get("points", N_POINTS))
    batch = int(res.get("batch", BATCH))
    iters = int(res.get("iters", ITERS))
    pairs_per_sec = batch * points / res["dt"]
    comparable = (points, iters) == (N_POINTS, ITERS)
    extra = {"variant": name, "platform": res.get("platform", "unknown"),
             "unit": _unit(points, iters, batch)}  # overrides the default
    if res.get("strategy") and res["strategy"] != "pytree":
        extra["step_strategy"] = res["strategy"]
    if res.get("ab_flags"):
        # Backward-path A/B levers active in this run — the headline must
        # carry them so an optimized run can never pass as the baseline.
        extra["ab_flags"] = res["ab_flags"]
    # Repeat spread: lets a future reader classify round-over-round drift
    # as measurement noise vs regression (round-3 verdict weak #1).
    for k in ("dt_reps", "dt_spread", "timing_reps", "steps_per_rep"):
        if k in res:
            extra[k] = res[k]
    if not comparable:
        extra["baseline_note"] = (
            "measured config differs from the baseline config; "
            "vs_baseline not comparable"
        )

    # Secondary metric: eval-protocol throughput (bs=1, 32 iters).
    if _remaining() > 120:
        on_cpu = res.get("platform") == "cpu"
        ev, _ = _spawn(
            ["--child-eval", name],
            min(VARIANT_TIMEOUT_S, _remaining()),
            cpu=on_cpu,
            # CPU eval steps are minutes at full config — shrink hard.
            env_overrides={
                "PVRAFT_BENCH_POINTS": str(min(points, 2048)),
                "PVRAFT_BENCH_K": str(min(TRUNCATE_K, 256)),
                "PVRAFT_BENCH_EVAL_ITERS": "8",
            } if on_cpu else None,
        )
        if ev is not None:
            extra["eval_scenes_per_sec"] = round(1.0 / ev["dt"], 3)
            if ev.get("eval_strategy"):
                extra["eval_strategy"] = ev["eval_strategy"]
            if ev.get("dt_scanned"):
                extra["eval_scenes_per_sec_scanned"] = round(
                    1.0 / ev["dt_scanned"], 3
                )
            ev_pts, ev_it = ev.get("points"), ev.get("iters")
            if (ev_pts, ev_it) != (N_POINTS, 32):
                extra["eval_detail"] = (
                    f"{ev_pts} pts, {ev_it} iters (shrunk, not the "
                    "reference eval protocol)"
                )
        else:
            notes.append("eval:failed")

    if len(notes) > 1 or res.get("platform") == "cpu":
        extra["note"] = ",".join(notes)
    _emit(pairs_per_sec, extra, comparable=comparable)


if __name__ == "__main__":
    if "--child-probe" in sys.argv:
        _child_probe()
    elif "--child-variant" in sys.argv:
        _child_variant(sys.argv[sys.argv.index("--child-variant") + 1])
    elif "--child-eval" in sys.argv:
        _child_eval(sys.argv[sys.argv.index("--child-eval") + 1])
    else:
        main()
