#!/usr/bin/env python
"""Visualize a dumped scene-flow result.

Equivalent of the reference ``visual.py`` (mayavi 3-cloud render of
``result/<dataset>/<idx>/{pc1,pc2,flow}.npy``, ``visual.py:11-30``) in two
forms, both headless-friendly (no mayavi/X server):

- default: a static matplotlib PNG (pc1 red, pc2 green, pc1+flow blue);
- ``--html``: a self-contained interactive HTML viewer (drag to orbit,
  wheel to zoom, per-cloud toggles) with the clouds embedded inline —
  the interactive parity for the reference's mayavi window, viewable in
  any browser with zero dependencies.

Produce the inputs with ``test.py --dump_dir result``.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def render(scene_dir: str, out_path: str, point_size: float = 0.5) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    pc1 = np.load(os.path.join(scene_dir, "pc1.npy"))
    pc2 = np.load(os.path.join(scene_dir, "pc2.npy"))
    flow = np.load(os.path.join(scene_dir, "flow.npy"))

    fig = plt.figure(figsize=(10, 8))
    ax = fig.add_subplot(111, projection="3d")
    ax.scatter(*pc1.T, s=point_size, c="#d62728", label="pc1 (t)")
    ax.scatter(*pc2.T, s=point_size, c="#2ca02c", label="pc2 (t+1)")
    warped = pc1 + flow
    ax.scatter(*warped.T, s=point_size, c="#1f77b4", label="pc1 + flow")
    ax.legend(loc="upper right")
    ax.set_box_aspect((1, 1, 1))
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    plt.close(fig)
    return out_path


_HTML_TEMPLATE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>PV-RAFT scene flow</title>
<style>
 body {{ margin:0; background:#111; color:#ddd; font:13px sans-serif; }}
 #hud {{ position:fixed; top:8px; left:8px; background:rgba(0,0,0,.6);
        padding:8px 10px; border-radius:6px; }}
 label {{ margin-right:10px; cursor:pointer; }}
 canvas {{ display:block; }}
</style></head><body>
<div id="hud">
 <b>{title}</b> &nbsp; drag: orbit &middot; wheel: zoom<br>
 <label><input type="checkbox" id="c0" checked>
   <span style="color:#ff5a4d">pc1 (t)</span></label>
 <label><input type="checkbox" id="c1" checked>
   <span style="color:#4dd15a">pc2 (t+1)</span></label>
 <label><input type="checkbox" id="c2" checked>
   <span style="color:#5a9bff">pc1 + flow</span></label>
</div>
<canvas id="cv"></canvas>
<script>
const CLOUDS = {clouds_json};
const COLORS = ["#ff5a4d", "#4dd15a", "#5a9bff"];
const cv = document.getElementById("cv"), ctx = cv.getContext("2d");
let yaw = 0.6, pitch = 0.3, zoom = 1.0, drag = null;
// Center and scale once so every scene fits the view.
let lo = [1e9,1e9,1e9], hi = [-1e9,-1e9,-1e9];
for (const c of CLOUDS) for (const p of c)
  for (let i = 0; i < 3; i++) {{
    lo[i] = Math.min(lo[i], p[i]); hi[i] = Math.max(hi[i], p[i]);
  }}
const mid = lo.map((v, i) => (v + hi[i]) / 2);
const span = Math.max(hi[0]-lo[0], hi[1]-lo[1], hi[2]-lo[2], 1e-6);
function draw() {{
  cv.width = innerWidth; cv.height = innerHeight;
  ctx.fillStyle = "#111"; ctx.fillRect(0, 0, cv.width, cv.height);
  const s = 0.8 * Math.min(cv.width, cv.height) / span * zoom;
  const cy = Math.cos(yaw), sy = Math.sin(yaw);
  const cp = Math.cos(pitch), sp = Math.sin(pitch);
  for (let ci = 0; ci < CLOUDS.length; ci++) {{
    if (!document.getElementById("c" + ci).checked) continue;
    ctx.fillStyle = COLORS[ci];
    for (const p of CLOUDS[ci]) {{
      const x = p[0]-mid[0], y = p[1]-mid[1], z = p[2]-mid[2];
      const rx = cy*x + sy*z, rz = -sy*x + cy*z;
      const ry = cp*y - sp*rz;
      ctx.fillRect(cv.width/2 + rx*s, cv.height/2 - ry*s, 2, 2);
    }}
  }}
}}
cv.onmousedown = e => drag = [e.clientX, e.clientY];
onmouseup = () => drag = null;
onmousemove = e => {{
  if (!drag) return;
  yaw += (e.clientX - drag[0]) * 0.01;
  pitch += (e.clientY - drag[1]) * 0.01;
  drag = [e.clientX, e.clientY]; draw();
}};
cv.onwheel = e => {{
  e.preventDefault();
  zoom *= Math.exp(-e.deltaY * 0.001); draw();
}};
onresize = draw;
for (const id of ["c0", "c1", "c2"])
  document.getElementById(id).onchange = draw;
draw();
</script></body></html>
"""


def render_html(scene_dir: str, out_path: str, max_points: int = 8192) -> str:
    """Write a dependency-free interactive HTML viewer for one scene.

    Embeds pc1 / pc2 / pc1+flow (subsampled to ``max_points`` each to keep
    the file small) as inline JSON with a canvas orbit/zoom renderer —
    the interactive counterpart of the reference's mayavi window
    (``visual.py:14-21``).
    """
    import json

    pc1 = np.load(os.path.join(scene_dir, "pc1.npy"))
    pc2 = np.load(os.path.join(scene_dir, "pc2.npy"))
    flow = np.load(os.path.join(scene_dir, "flow.npy"))

    def sub(a: np.ndarray) -> list:
        if len(a) > max_points:
            idx = np.linspace(0, len(a) - 1, max_points).astype(np.int64)
            a = a[idx]
        return np.round(a.astype(np.float64), 4).tolist()

    clouds = [sub(pc1), sub(pc2), sub(pc1 + flow)]
    html = _HTML_TEMPLATE.format(
        title=os.path.basename(os.path.abspath(scene_dir)),
        clouds_json=json.dumps(clouds, separators=(",", ":")),
    )
    with open(out_path, "w") as f:
        f.write(html)
    return out_path


def main(argv=None) -> None:
    p = argparse.ArgumentParser("pvraft_tpu visual")
    p.add_argument("--result_root", default="result")
    p.add_argument("--dataset", default="FT3D")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--out", default=None)
    p.add_argument("--html", action="store_true",
                   help="write an interactive HTML viewer instead of a PNG")
    a = p.parse_args(argv)
    scene = os.path.join(a.result_root, a.dataset, str(a.index))
    if a.html:
        out = a.out or os.path.join(scene, "render.html")
        print(render_html(scene, out))
    else:
        out = a.out or os.path.join(scene, "render.png")
        print(render(scene, out))


if __name__ == "__main__":
    main()
