#!/usr/bin/env python
"""Visualize a dumped scene-flow result.

Equivalent of the reference ``visual.py`` (mayavi 3-cloud render of
``result/<dataset>/<idx>/{pc1,pc2,flow}.npy``, ``visual.py:11-30``) using
matplotlib (headless-friendly): pc1 red, pc2 green, pc1+flow blue, written
to a PNG. Produce the inputs with ``test.py --dump_dir result``.
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def render(scene_dir: str, out_path: str, point_size: float = 0.5) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    pc1 = np.load(os.path.join(scene_dir, "pc1.npy"))
    pc2 = np.load(os.path.join(scene_dir, "pc2.npy"))
    flow = np.load(os.path.join(scene_dir, "flow.npy"))

    fig = plt.figure(figsize=(10, 8))
    ax = fig.add_subplot(111, projection="3d")
    ax.scatter(*pc1.T, s=point_size, c="#d62728", label="pc1 (t)")
    ax.scatter(*pc2.T, s=point_size, c="#2ca02c", label="pc2 (t+1)")
    warped = pc1 + flow
    ax.scatter(*warped.T, s=point_size, c="#1f77b4", label="pc1 + flow")
    ax.legend(loc="upper right")
    ax.set_box_aspect((1, 1, 1))
    fig.tight_layout()
    fig.savefig(out_path, dpi=150)
    plt.close(fig)
    return out_path


def main(argv=None) -> None:
    p = argparse.ArgumentParser("pvraft_tpu visual")
    p.add_argument("--result_root", default="result")
    p.add_argument("--dataset", default="FT3D")
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--out", default=None)
    a = p.parse_args(argv)
    scene = os.path.join(a.result_root, a.dataset, str(a.index))
    out = a.out or os.path.join(scene, "render.png")
    print(render(scene, out))


if __name__ == "__main__":
    main()
