"""Gradient / optimizer-step parity vs the torch reference (slow tier).

Three decoupled claims (see scripts/grad_parity.py):
  1. grads of ``sequence_loss`` through the scan-GRU match the reference's
     ``loss.backward()`` per leaf (``tools/engine.py:135-143``,
     ``tools/loss.py:4-13``);
  2. identical grads -> identical Adam step (optax vs torch defaults: both
     add eps AFTER the sqrt; optax ``eps_root=0``);
  3. the coupled end-to-end step stays within the lr-scaled bound that
     near-zero-grad sign flips allow.

A forward-parity-only divergence (e.g. a stop_gradient where the reference
backprops, or vice versa) would pass every forward test and still sink the
FT3D EPE target — this is the test that would catch it.
"""

import os

import pytest

REF_ROOT = "/root/reference"

pytestmark = [
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_ROOT, "model")),
        reason="reference checkout not available",
    ),
    pytest.mark.slow,
]


def test_grads_and_adam_step_match_reference():
    from scripts.grad_parity import run

    rec = run(seed=5, n=256, iters=4, truncate_k=64)
    assert rec["loss"]["abs_delta"] <= 1e-5, rec["loss"]
    assert rec["grad_cosine_min"] >= 0.9999, rec
    assert rec["grad_rel_max"] <= 1e-3, rec
    assert rec["optimizer_step_max_abs"] <= 1e-6, rec
    assert rec["coupled_step_max_abs"] <= 2.5e-3, rec
