"""Parity of the fused MotionEncoder+ConvGRU Pallas kernel
(``ops/pallas/gru_iter.py``) against the unfused flax path, plus the
flag-off jaxpr-unchanged guarantee and the tile-policy geometry.

Tolerances are pinned at ~3-10x the measured CPU (interpret-mode)
diffs so toolchain noise does not flake while a real regression (a
mis-sliced gate, a dropped operand) still fails by orders of magnitude:

  * op level, Pallas vs the pure-XLA twin: fp32 measured ~5e-7 (the
    kernel body and the twin run the same ``_gru_math``; only block
    tiling differs);
  * model level, fused vs unfused flax: fp32 fwd ~1.2e-6 / grads
    ~1.5e-5 at 2 iterations (the lane-stacked gate matmuls and
    decomposed concat-dots reassociate float adds); bf16 fwd ~0.009 at
    1 iteration (bf16 rounding feeds back through the discrete kNN
    candidate selection across iterations, so multi-iteration bf16
    diffs are selection flips, not kernel error — 1 iteration pins the
    arithmetic itself).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.ops.pallas.gru_iter import (
    _gru_reference,
    _gru_tile,
    fused_gru_update,
    pack_gru_weights,
    pad_flow,
)


# --- tile policy ------------------------------------------------------------


def test_gru_tile_geometry():
    # The committed kernel_plan.json geometry at flagship sizes...
    assert _gru_tile(8192, 512) == 1024
    assert _gru_tile(8192, 128) == 2048
    assert _gru_tile(8192, 16) == 2048
    # ...clamped 8-aligned for small clouds (never exceeds the cloud).
    assert _gru_tile(37, 512) == 32
    assert _gru_tile(20, 16) == 16
    assert _gru_tile(5, 512) == 8


# --- op level: the Pallas program vs its pure-XLA twin ----------------------


H, C, D = 8, 8, 16


def _raw_params(rng):
    def a(*s):
        return jnp.asarray(0.5 * rng.normal(size=s).astype(np.float32))

    me = (a(D, H), a(H), a(3, H), a(H), a(2 * H, H - 3), a(H - 3))
    hx = 2 * H + C
    gru = (a(hx, H), a(H), a(hx, H), a(H), a(hx, H), a(H))
    return me, gru


def _op_inputs(rng, n):
    # flow enters the op pre-padded (pad_flow runs outside the custom
    # VJP — the kernel operand IS the program argument).
    net = jnp.asarray(np.tanh(rng.normal(size=(1, n, H))).astype(np.float32))
    inp = jnp.asarray(np.abs(rng.normal(size=(1, n, C))).astype(np.float32))
    cor = jnp.asarray(rng.normal(size=(1, n, D)).astype(np.float32))
    flow = jnp.asarray(rng.normal(size=(1, n, 3)).astype(np.float32))
    return net, inp, cor, pad_flow(flow)


@pytest.mark.parametrize("n,k", [
    (37, 512),      # tail tile: tile=32, grid 2, 5-point remainder
    (2056, 512),    # K>128 target: tile=1024, grid 3, 8-point tail
    (2056, 16),     # K<=128 target: tile=2048, grid 2, 8-point tail
])
def test_op_forward_parity_fp32(n, k):
    rng = np.random.default_rng(0)
    me, gru = _raw_params(rng)
    w = pack_gru_weights(me, gru, H, C)
    net, inp, cor, flow = _op_inputs(rng, n)
    got = fused_gru_update(net, inp, cor, flow, w, "float32", k)
    want = _gru_reference(net, inp, cor, flow, w, "float32")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_op_forward_parity_bf16():
    rng = np.random.default_rng(1)
    me, gru = _raw_params(rng)
    w = pack_gru_weights(me, gru, H, C)
    net, inp, cor, flow = _op_inputs(rng, 37)
    got = fused_gru_update(net, inp, cor, flow, w, "bfloat16", 16)
    want = _gru_reference(net, inp, cor, flow, w, "bfloat16")
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-2, atol=1e-2)


def test_op_grad_parity():
    # The custom VJP differentiates _gru_reference itself, so this pins
    # the defvjp plumbing (residuals, cotangent tree incl. the 8-tuple
    # weights) rather than arithmetic — expect near-exact agreement.
    rng = np.random.default_rng(2)
    me, gru = _raw_params(rng)
    w = pack_gru_weights(me, gru, H, C)
    net, inp, cor, flow = _op_inputs(rng, 37)

    def fused(ne, i, c, f, wt):
        return jnp.sum(jnp.sin(
            fused_gru_update(ne, i, c, f, wt, "float32", 16)))

    def ref(ne, i, c, f, wt):
        return jnp.sum(jnp.sin(_gru_reference(ne, i, c, f, wt, "float32")))

    g_new = jax.grad(fused, (0, 1, 2, 3, 4))(net, inp, cor, flow, w)
    g_ref = jax.grad(ref, (0, 1, 2, 3, 4))(net, inp, cor, flow, w)
    for a, b in zip(jax.tree_util.tree_leaves(g_new),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


# --- model level: fused vs unfused flax -------------------------------------


@pytest.fixture(scope="module")
def clouds():
    rng = np.random.default_rng(0)
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, 40, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, 40, 3)).astype(np.float32))
    base = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                       use_pallas=False)
    return pc1, pc2, base


def _apply(cfg, pc1, pc2, iters=2, masks=None):
    from pvraft_tpu.models import PVRaft

    model = PVRaft(cfg)
    params = model.init(jax.random.key(0), pc1, pc2, iters)
    args = (pc1, pc2, iters) + (masks if masks else ())
    return model, params, model.apply(params, *args)[0]


def test_model_init_identical(clouds):
    # fused_gru must not change the param tree: the holder modules
    # declare the same (path, shape, init) leaves the flax Dense stack
    # does, so checkpoints swap freely between the two paths.
    pc1, pc2, base = clouds
    from pvraft_tpu.models import PVRaft

    p_off = PVRaft(base).init(jax.random.key(0), pc1, pc2, 2)
    p_on = PVRaft(dataclasses.replace(base, fused_gru=True)).init(
        jax.random.key(0), pc1, pc2, 2)
    leaves_off = jax.tree_util.tree_leaves_with_path(p_off)
    leaves_on = jax.tree_util.tree_leaves_with_path(p_on)
    assert [k for k, _ in leaves_off] == [k for k, _ in leaves_on]
    for (_, a), (_, b) in zip(leaves_off, leaves_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("masked", [False, True])
def test_model_forward_parity_fp32(clouds, masked):
    pc1, pc2, base = clouds
    masks = None
    if masked:
        valid = jnp.arange(40) < 36
        masks = (jnp.broadcast_to(valid, (1, 40)),) * 2
    _, _, f_off = _apply(base, pc1, pc2, masks=masks)
    _, _, f_on = _apply(dataclasses.replace(base, fused_gru=True),
                        pc1, pc2, masks=masks)
    np.testing.assert_allclose(np.asarray(f_on), np.asarray(f_off),
                               rtol=1e-4, atol=1e-5)


def test_model_grad_parity_fp32(clouds):
    pc1, pc2, base = clouds
    from pvraft_tpu.models import PVRaft

    def grads(cfg):
        model = PVRaft(cfg)
        params = model.init(jax.random.key(0), pc1, pc2, 2)

        def loss(p):
            flows, _ = model.apply(p, pc1, pc2, 2)
            return jnp.sum(flows ** 2)

        return jax.grad(loss)(params)

    g_off = grads(base)
    g_on = grads(dataclasses.replace(base, fused_gru=True))
    for a, b in zip(jax.tree_util.tree_leaves(g_off),
                    jax.tree_util.tree_leaves(g_on)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-3, atol=1e-4)


def test_model_forward_parity_bf16_one_iter(clouds):
    pc1, pc2, base = clouds
    cfg = dataclasses.replace(base, compute_dtype="bfloat16")
    _, _, f_off = _apply(cfg, pc1, pc2, iters=1)
    _, _, f_on = _apply(dataclasses.replace(cfg, fused_gru=True),
                        pc1, pc2, iters=1)
    np.testing.assert_allclose(np.asarray(f_on), np.asarray(f_off),
                               rtol=0.05, atol=0.03)


# --- flag off: jaxpr untouched ----------------------------------------------


def test_model_jaxpr_fused_only_when_opted_in(clouds):
    pc1, pc2, base = clouds
    from pvraft_tpu.models import PVRaft

    def traced(cfg):
        model = PVRaft(cfg)
        params = jax.eval_shape(
            lambda: model.init(jax.random.key(0), pc1, pc2, 2))
        return str(jax.make_jaxpr(
            lambda p: model.apply(p, pc1, pc2, 2))(params))

    off = traced(base)
    on = traced(dataclasses.replace(base, fused_gru=True))
    # The default path traces no custom_vjp at all (same guarantee
    # test_scatter_free pins) — fused_gru=False cannot have touched it.
    assert "custom_vjp" not in off
    assert "custom_vjp" in on
