"""shardcheck: model extraction vs the real call sites, GS rules
red/green over the fixture corpus (incl. the PR-2 pre-guard eager-stack
shape pinned DETECTED), the pragma grammar + `lint --stats` GS debt,
the clean-tree zero-findings gate, the CLI, and the pod planner
(schema, drift detection, fits-verdict pins, the sharded-step
cross-check). Pure host-side — no jax import (tier-1 on CPU)."""

import ast
import contextlib
import io
import json
import os

import pytest

from pvraft_tpu.analysis.__main__ import main as analysis_main
from pvraft_tpu.analysis.engine import known_rule_ids
from pvraft_tpu.analysis.sharding.check import (
    check_paths,
    check_source,
    declared_axes,
    default_param_leaves,
    default_scope,
)
from pvraft_tpu.analysis.sharding.model import build_module_shard_model
from pvraft_tpu.analysis.sharding.planner import (
    CANDIDATE_MESHES,
    CROSS_CHECK_BAND,
    PLAN_SCHEMA,
    SCENE_POINTS,
    build_plan,
    check_plan_file,
    param_bytes_per_device,
    ring_comms,
)
from pvraft_tpu.analysis.sharding.rules import all_sharding_rules
from pvraft_tpu.programs.partitioning import (
    PARTITION_RULES,
    load_params_tree,
    match_partition_rules,
    match_report,
    validate_params_tree,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "shardcheck")
COSTS = os.path.join(REPO, "artifacts", "programs_costs.json")
PARAMS = os.path.join(REPO, "artifacts", "params_tree.json")
PLAN = os.path.join(REPO, "artifacts", "pod_plan.json")

AXES = {"data", "seq"}
LEAVES = ["params/enc/kernel", "params/head/kernel"]


def fixture_ids(name, leaves=LEAVES):
    path = os.path.join(FIXTURES, name)
    with open(path, "r", encoding="utf-8") as f:
        src = f.read()
    return [d.rule_id for d in check_source(
        src, path=path, declared=AXES, param_leaves=leaves)]


# --- model extraction -------------------------------------------------------

def test_declared_axes_are_the_mesh_builders():
    assert declared_axes() == {"data", "seq"}


def test_real_ring_module_axis_sites():
    """ring.py's shard_map specs and mesh.shape lookups all spell the
    declared `seq`/`data` axes — the sites GS002 would anchor at."""
    path = os.path.join(REPO, "pvraft_tpu", "parallel", "ring.py")
    with open(path, "r", encoding="utf-8") as f:
        model = build_module_shard_model(ast.parse(f.read()))
    axes = {s.axis for s in model.axis_sites}
    assert axes and axes <= {"data", "seq"}
    apis = {s.api for s in model.axis_sites}
    assert "PartitionSpec" in apis
    assert "mesh.shape" in apis
    assert model.fragile == []  # axis_size routes through compat


def test_real_trainer_model_guard_and_stack():
    """The trainer's eager-stack site and its constructor guard are
    both extracted and associated with the same class — the pairing
    GS003 enforces."""
    path = os.path.join(REPO, "pvraft_tpu", "engine", "trainer.py")
    with open(path, "r", encoding="utf-8") as f:
        model = build_module_shard_model(ast.parse(f.read()))
    assert any(s.owner == "Trainer" for s in model.stack_sites)
    assert any(g.owner == "Trainer" for g in model.process_guards)
    assert model.batch_arith == []  # the batch contract moved to mesh.py


def test_real_checkpoint_writes_all_guarded():
    """checkpoint.py's helper chain (_write/_swap_in/_promote_ckpt/
    _copy_extras) is guard-dominated through its call sites — the
    interprocedural half of the GS004 model."""
    path = os.path.join(REPO, "pvraft_tpu", "engine", "checkpoint.py")
    with open(path, "r", encoding="utf-8") as f:
        model = build_module_shard_model(ast.parse(f.read()))
    unguarded = [w for w in model.write_sites if not w.guarded]
    assert unguarded == []
    assert len(model.write_sites) >= 10  # the chain is actually modeled


# --- partition-rule matching ------------------------------------------------

def test_match_report_semantics():
    rules = ((r"^a/", ()), (r"^b/", ("data",)), (r"^dead/", ()))
    mapping, unmatched, multi, unused = match_report(
        rules, ["a/x", "b/y", "c/z"])
    assert mapping == {"a/x": (), "b/y": ("data",)}
    assert unmatched == ["c/z"]
    assert multi == []
    assert unused == [r"^dead/"]


def test_match_partition_rules_raises_on_violations():
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules(((r"^a/", ()),), ["b/x"])
    with pytest.raises(ValueError, match="matched 2 rules"):
        match_partition_rules(((r"^a/", ()), (r"a/x", ())), ["a/x"])


def test_committed_rules_cover_committed_inventory_exactly_once():
    """THE GS001 invariant, asserted directly against both committed
    data planes."""
    doc = load_params_tree(PARAMS)
    paths = [leaf["path"] for leaf in doc["leaves"]]
    mapping = match_partition_rules(PARTITION_RULES, paths)
    assert len(mapping) == len(paths) == 95


def test_params_tree_validator_red():
    doc = json.loads(open(PARAMS, encoding="utf-8").read())
    assert validate_params_tree(doc) == []
    bad = dict(doc, total_parameters=doc["total_parameters"] + 1)
    assert any("total_parameters" in p for p in validate_params_tree(bad))
    assert validate_params_tree({"schema": "nope"})


def test_catalog_declares_no_axis_literals():
    """Satellite single-source guard (the serve bucket-literal ban
    precedent): catalog.py builds every PartitionSpec from
    partitioning.py data — no inline axis-name strings in P() calls."""
    path = os.path.join(REPO, "pvraft_tpu", "programs", "catalog.py")
    with open(path, "r", encoding="utf-8") as f:
        model = build_module_shard_model(ast.parse(f.read()))
    literal_axes = [s for s in model.axis_sites
                    if s.api == "PartitionSpec"]
    assert literal_axes == [], (
        "programs/catalog.py grew inline PartitionSpec axis literals; "
        "route them through programs/partitioning.py "
        f"({[(s.line, s.axis) for s in literal_axes]})")


# --- per-rule red/green -----------------------------------------------------

def test_gs001_red_green():
    ids = fixture_ids("gs001_coverage_red.py")
    assert ids.count("GS001") >= 2
    assert fixture_ids("gs001_coverage_green.py") == []


def test_gs001_reports_missing_inventory():
    ds = check_source("PARTITION_RULES = ((r'^a', ()),)\n",
                      declared=AXES, param_leaves=None)
    assert [d.rule_id for d in ds] == ["GS001"]
    assert "inventory unavailable" in ds[0].message


def test_gs002_red():
    ids = fixture_ids("gs002_axis_red.py")
    assert ids == ["GS002"] * 4


def test_gs003_pr2_eager_stack_red_green():
    """The pre-guard PR-2 fused-dispatch shape is DETECTED; the current
    guarded shape is clean (the ROADMAP item-2 contract)."""
    assert fixture_ids("gs003_eager_stack_red.py") == ["GS003"]
    assert fixture_ids("gs003_eager_stack_green.py") == []


def test_gs004_red_green():
    ids = fixture_ids("gs004_unguarded_io_red.py")
    assert ids == ["GS004"] * 4
    assert fixture_ids("gs004_unguarded_io_green.py") == []


def test_gs005_red():
    ids = fixture_ids("gs005_batch_contract_red.py")
    assert ids == ["GS005"] * 2


def test_gs000_syntax_error():
    ds = check_source("def broken(:\n", declared=AXES, param_leaves=[])
    assert [d.rule_id for d in ds] == ["GS000"]


def test_gs004_module_level_and_nested_def_writes():
    """Review-found blind spots, pinned: an import-time write in the
    module body and a writer def'd under a compound statement are both
    scanned (they run on every host like any other write)."""
    top = ("import numpy as np\n"
           "np.save('warm.npy', [1])\n")
    ds = check_source(top, path="/x/pvraft_tpu/obs/foo.py",
                      declared=AXES, param_leaves=[])
    assert [d.rule_id for d in ds] == ["GS004"]
    assert "<module>" in ds[0].message
    nested = ("import numpy as np\n"
              "if True:\n"
              "    def writer(x):\n"
              "        np.save('x.npy', x)\n")
    ds = check_source(nested, path="/x/pvraft_tpu/obs/foo.py",
                      declared=AXES, param_leaves=[])
    assert [d.rule_id for d in ds] == ["GS004"]


def test_gs004_mutual_recursion_not_proven_guarded():
    """Review-found blind spot, pinned: a mutually-recursive writer
    pair with no outside callers must NOT dominate itself into a guard
    (least- not greatest-fixpoint)."""
    src = ("import numpy as np\n"
           "def a(x):\n"
           "    np.save('a.npy', x)\n"
           "    b(x)\n"
           "def b(x):\n"
           "    np.save('b.npy', x)\n"
           "    a(x)\n")
    ds = check_source(src, path="/x/pvraft_tpu/obs/foo.py",
                      declared=AXES, param_leaves=[])
    assert [d.rule_id for d in ds] == ["GS004", "GS004"]


def test_gs002_axis_keyword_argument():
    """Review-found blind spot, pinned: `axis_name=` keyword spellings
    carry axis names too."""
    src = ("from jax import lax\n"
           "def f(x):\n"
           "    return lax.psum(x, axis_name='typo_axis')\n")
    ds = check_source(src, declared=AXES, param_leaves=[])
    assert [d.rule_id for d in ds] == ["GS002"]
    assert "typo_axis" in ds[0].message


def test_rules_path_scoped_inside_package():
    """GS004 only applies to engine/ + obs/ inside the package (the
    serve plane is threadcheck's turf) but applies everywhere outside
    it — fixtures stay testable."""
    src = ("import numpy as np\n"
           "def f(x):\n"
           "    np.save('x.npy', x)\n")
    flagged = check_source(src, path="/x/pvraft_tpu/obs/foo.py",
                           declared=AXES, param_leaves=[])
    assert [d.rule_id for d in flagged] == ["GS004"]
    skipped = check_source(src, path="/x/pvraft_tpu/serve/foo.py",
                           declared=AXES, param_leaves=[])
    assert skipped == []


# --- suppressions + the shared pragma grammar -------------------------------

def test_gs_ids_known_to_stats():
    ids = known_rule_ids()
    for rid in ("GS000", "GS001", "GS002", "GS003", "GS004", "GS005"):
        assert rid in ids


def test_gs_suppression_honored():
    src = ("import numpy as np\n"
           "def f(x):\n"
           "    np.save('x.npy', x)"
           "  # graftlint: disable=GS004 -- fixture\n")
    assert check_source(src, path="/x/pvraft_tpu/engine/foo.py",
                        declared=AXES, param_leaves=[]) == []


def test_reasonless_gs_pragma_fails_stats(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1  # graftlint: disable=GS004\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
        rc = analysis_main(["lint", "--stats", str(bad)])
    assert rc == 1
    assert "reason-less" in buf.getvalue()
    good = tmp_path / "good.py"
    good.write_text("x = 1  # graftlint: disable=GS004 -- pinned fixture\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
        rc = analysis_main(["lint", "--stats", str(good)])
    assert rc == 0
    assert "unknown rule" not in buf.getvalue()


# --- the clean-tree gate ----------------------------------------------------

def test_clean_tree_zero_findings():
    """The lint.sh stage in test form: the shipped tree carries zero GS
    findings with the real declared axes + the committed inventory."""
    findings, nfiles = check_paths(list(default_scope()))
    assert findings == [], [d.format() for d in findings]
    assert nfiles > 40


def test_default_inventory_loads():
    leaves = default_param_leaves()
    assert leaves and len(leaves) == 95
    assert all(p.startswith("params/") for p in leaves)


# --- CLI --------------------------------------------------------------------

def test_cli_list_rules():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["sharding", "--list-rules"])
    assert rc == 0
    out = buf.getvalue()
    for rid in ("GS001", "GS002", "GS003", "GS004", "GS005"):
        assert rid in out


def test_cli_red_fixture_and_select():
    buf = io.StringIO()
    path = os.path.join(FIXTURES, "gs005_batch_contract_red.py")
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
        rc = analysis_main(["sharding", path])
    assert rc == 1
    assert "GS005" in buf.getvalue()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
        rc = analysis_main(["sharding", "--select", "GS002", path])
    assert rc == 0  # GS005 findings filtered out


# --- pod planner ------------------------------------------------------------

@pytest.fixture(scope="module")
def plan():
    """One plan build shared by every planner assertion (each build
    re-scans the whole gate scope — no reason to pay that per test)."""
    return build_plan(COSTS, PARAMS)


def test_plan_schema_and_structure(plan):
    assert plan["schema"] == PLAN_SCHEMA
    assert [(m["dp"], m["sp"]) for m in plan["meshes"]] == \
        list(CANDIDATE_MESHES)
    for mesh in plan["meshes"]:
        assert [s["n_points"] for s in mesh["scenes"]] == \
            list(SCENE_POINTS)
        assert mesh["params_bytes_per_device"] > 0
        assert mesh["optimizer_bytes_per_device"] == \
            2 * mesh["params_bytes_per_device"]


def test_plan_fits_verdicts_pinned(plan):
    """The committed answers ROADMAP item 2 cites: the 16k scene fits
    every candidate mesh per-device; the 100k scene needs the seq=4
    meshes (4x4, 8x4) — seq=2 does not fit."""

    def fits(dp, sp, n):
        mesh = next(m for m in plan["meshes"]
                    if m["dp"] == dp and m["sp"] == sp)
        return next(s for s in mesh["scenes"]
                    if s["n_points"] == n)["fits_16GiB_hbm"]

    for dp, sp in CANDIDATE_MESHES:
        assert fits(dp, sp, 16384)
    assert not fits(2, 2, 100000)
    assert not fits(4, 2, 100000)
    assert fits(4, 4, 100000)
    assert fits(8, 4, 100000)
    assert "4x4, 8x4" in plan["scene_verdicts"]["100000"]


def test_plan_cross_check_in_band(plan):
    cross = plan["sharded_step_cross_check"]
    lo, hi = CROSS_CHECK_BAND
    assert cross["program"] == "dp_sp_2x2_train_step"
    assert lo <= cross["model_vs_compiled_ratio"] <= hi
    assert cross["compiled_live_bytes_per_device"] > \
        cross["model_bytes_per_device"]


def test_plan_ring_accounting():
    """Ring traffic follows the ring.py geometry: sp-1 hops (the last
    fold never forwards its chunk — the GJ002 fix), chunk bytes =
    points/sp x (feature_dim + 3) floats."""
    comms = ring_comms(4096, 4, 128)
    assert comms["hops"] == 3
    assert comms["corr_per_hop_bytes"] == 4096 * 131 * 4
    assert comms["knn_per_hop_bytes"] == 4096 * 3 * 4
    assert comms["total_bytes_per_step"] == \
        3 * (2 * comms["knn_per_hop_bytes"]
             + 2 * comms["corr_per_hop_bytes"])
    assert ring_comms(4096, 1, 128)["total_bytes_per_step"] == 0


def test_plan_param_bytes_honor_rules():
    doc = load_params_tree(PARAMS)
    # All rules replicate today: per-device bytes == total on any mesh.
    assert param_bytes_per_device(doc["leaves"], {"data": 8, "seq": 4}) \
        == doc["total_bytes"]


def test_committed_plan_drift_detected(tmp_path):
    doc = json.loads(open(PLAN, encoding="utf-8").read())
    doc["scene_verdicts"]["100000"] = "fits everywhere, trust me"
    edited = tmp_path / "pod_plan.json"
    edited.write_text(json.dumps(doc))
    problems = check_plan_file(str(edited), COSTS, PARAMS)
    assert problems and "drifted" in problems[0]
    assert "scene_verdicts" in problems[0]


def test_plan_refuses_on_findings(tmp_path):
    """A broken costs artifact (no activation basis) refuses the plan
    instead of committing fiction."""
    crippled = tmp_path / "costs.json"
    crippled.write_text(json.dumps({"programs": []}))
    with pytest.raises(ValueError, match="cannot be built"):
        build_plan(str(crippled), PARAMS)


def test_cli_plan_check_committed_up_to_date():
    """The lint.sh regenerate-and-compare stage in test form (also THE
    committed-plan freshness pin)."""
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
        rc = analysis_main(["sharding", "--check", PLAN,
                            "--costs", COSTS, "--params", PARAMS])
    assert rc == 0
    assert "OK" in buf.getvalue()


def test_rule_table_complete():
    rules = all_sharding_rules()
    assert [r.id for r in rules] == \
        ["GS001", "GS002", "GS003", "GS004", "GS005"]
    for r in rules:
        assert r.title and (r.__doc__ or "").strip()
