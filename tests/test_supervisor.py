"""Replica supervision + deterministic fault injection (ISSUE 13):
the FaultPlan schedule semantics, the zero-residue disarmed path, the
health state machine (threshold trips, probe revival, wedge scan), and
the batcher's retry-once-on-another-replica with exactly-once outcome
accounting.

Everything here is host-side (fake replicas, real threads, no XLA) —
the state machine must be testable at state-machine cost. The real-AOT
end-to-end story lives in tests/test_serve_chaos.py.
"""

import ast
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from pvraft_tpu.obs.events import FAULT_POINTS, REPLICA_STATES
from pvraft_tpu.serve import faults
from pvraft_tpu.serve.batcher import BatcherConfig, MicroBatcher
from pvraft_tpu.serve.engine import RequestError
from pvraft_tpu.serve.faults import FaultPlan, FaultRule, InjectedFaultError
from pvraft_tpu.serve.metrics import ServeMetrics
from pvraft_tpu.serve.supervisor import ReplicaSupervisor, SupervisorConfig


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    """A test that fails mid-plan must not poison its neighbors."""
    yield
    faults.clear_plan()


# ----------------------------------------------------------- fake pool --


class _Replica:
    """Fake single-device executor; fails when its flag is set (real
    failures, distinct from injected ones)."""

    def __init__(self, index):
        self.index = index
        self.device_id = index
        self.calls = 0
        self.fail = False

    def predict_batch(self, requests, bucket):
        self.calls += 1
        if self.fail:
            raise RuntimeError(f"replica {self.index} broke")
        return [np.asarray(pc2[: pc1.shape[0]] - pc1, np.float32)
                for pc1, pc2 in requests]


class _Engine:
    def __init__(self, buckets=(32, 64), batch_sizes=(1, 2), n=2):
        self.cfg = SimpleNamespace(
            buckets=buckets, batch_sizes=batch_sizes, min_points=4,
            coord_limit=100.0, dtype="float32")
        self.replicas = [_Replica(i) for i in range(n)]

    def validate_request(self, pc1, pc2):
        m = max(pc1.shape[0], pc2.shape[0])
        for b in self.cfg.buckets:
            if m <= b:
                return b
        raise RequestError("too_large", "too large")

    def batch_size_for(self, n):
        for bs in self.cfg.batch_sizes:
            if n <= bs:
                return bs
        return self.cfg.batch_sizes[-1]

    def compile_report(self):
        return []


def _pc(n, seed=0):
    return np.random.default_rng(seed).uniform(
        -1, 1, (n, 3)).astype(np.float32)


def _poll(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


TIGHT = SupervisorConfig(degraded_after=1, quarantine_after=2,
                         probe_interval_s=0, wedge_timeout_s=0.2,
                         latency_min_samples=3, latency_outlier_after=2,
                         latency_outlier_factor=3.0)


# ------------------------------------------------------- fault schedule --


def test_fault_rule_validation():
    with pytest.raises(ValueError):
        FaultRule("not_a_point")
    with pytest.raises(ValueError):
        FaultRule("queue_stall", nth=0)
    with pytest.raises(ValueError):
        FaultRule("queue_stall", every=-1)
    with pytest.raises(ValueError):
        FaultPlan([])
    assert FAULT_POINTS == tuple(
        FaultRule(p).point for p in FAULT_POINTS)


def test_fire_nth_once_every_max():
    fired = []
    plan = FaultPlan([
        FaultRule("queue_stall", nth=2),                    # once, at 2
        FaultRule("compile_trip", nth=1, every=2,
                  max_fires=2),                             # 1, 3 then capped
    ])
    with faults.injected(plan):
        for _ in range(6):
            fired.extend(r["traversal"] for r in faults.fire("queue_stall"))
        assert fired == [2]
        trips = []
        for _ in range(6):
            trips.extend(r["traversal"] for r in faults.fire("compile_trip"))
        assert trips == [1, 3]                              # max_fires=2


def test_fire_counts_per_replica():
    plan = FaultPlan([FaultRule("replica_predict_error", nth=2, replica=1)])
    with faults.injected(plan):
        # Replica 0 traversals never advance replica 1's schedule.
        for _ in range(5):
            faults.fire("replica_predict_error", replica=0)
        faults.fire("replica_predict_error", replica=1)     # traversal 1
        with pytest.raises(InjectedFaultError):
            faults.fire("replica_predict_error", replica=1)  # traversal 2


def test_fire_after_s_window():
    plan = FaultPlan([FaultRule("queue_stall", nth=1, every=1,
                                after_s=30.0)])
    with faults.injected(plan):
        assert faults.fire("queue_stall") == ()             # still dormant


def test_install_is_exclusive_and_clear_unblocks_wedge():
    plan = FaultPlan([FaultRule("replica_wedge", nth=1, replica=0)])
    faults.install_plan(plan)
    with pytest.raises(RuntimeError):
        faults.install_plan(plan)
    released = threading.Event()

    def wedged():
        faults.fire("replica_wedge", replica=0)             # blocks
        released.set()

    t = threading.Thread(target=wedged, daemon=True)
    t.start()
    time.sleep(0.1)
    assert not released.is_set()
    faults.clear_plan()                                     # releases
    assert released.wait(5)
    t.join(5)


def test_disarmed_zero_residue():
    """No FaultPlan installed: fire() is inert — returns (), allocates
    no counters, leaves no observable state. The fault points live in
    host-side code only (this module never imports jax), so the
    default-path jaxpr guarantee is structural, not incidental."""
    for point in FAULT_POINTS:
        assert faults.fire(point, replica=0) == ()
    snap = faults.plan_snapshot()
    assert snap == {"armed": False, "rules": [], "fired_total": 0,
                    "rule_fires": []}
    # Structural jaxpr guarantee: faults.py is jax-free by construction.
    import pvraft_tpu.serve.faults as mod

    tree = ast.parse(open(mod.__file__, encoding="utf-8").read())
    imports = [n.names[0].name for n in ast.walk(tree)
               if isinstance(n, ast.Import)] + \
              [n.module for n in ast.walk(tree)
               if isinstance(n, ast.ImportFrom)]
    assert not any(name == "jax" or name.startswith("jax.")
                   for name in imports if name)


# --------------------------------------------------------- state machine --


def test_supervisor_config_validation():
    with pytest.raises(ValueError):
        SupervisorConfig(degraded_after=0)
    with pytest.raises(ValueError):
        SupervisorConfig(degraded_after=3, quarantine_after=2)
    with pytest.raises(ValueError):
        SupervisorConfig(latency_outlier_factor=1.0)
    assert SupervisorConfig(probe_interval_s=0.3).retry_after_s == 1
    assert SupervisorConfig(probe_interval_s=2.5).retry_after_s == 3


def test_failure_streak_degrades_then_quarantines():
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    assert [r["state"] for r in sup.states()] == ["healthy", "healthy"]
    sup.record_failure(1)
    assert sup.state_of(1) == "degraded"
    assert sup.in_rotation(1)                   # degraded still serves
    sup.record_failure(1)
    assert sup.state_of(1) == "quarantined"
    assert not sup.in_rotation(1)
    assert sup.serving_count() == 1
    assert sup.retry_target(exclude=0) is None  # 1 is out, no one else
    assert sup.retry_target(exclude=1) == 0
    # A success on a quarantined replica (straggler dispatch) does NOT
    # revive it — only the probe may.
    sup.record_success(1, 32, 0.001)
    assert sup.state_of(1) == "quarantined"
    health = sup.pool_health()
    assert health["state"] == "degraded"
    assert health["healthy_replicas"] == 1


def test_success_resets_streak_and_recovers_degraded():
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    sup.record_failure(0)
    assert sup.state_of(0) == "degraded"
    sup.record_success(0, 32, 0.001)
    assert sup.state_of(0) == "healthy"
    # Streak reset: one more failure degrades again but does not
    # quarantine (the consecutive count restarted).
    sup.record_failure(0)
    assert sup.state_of(0) == "degraded"


def test_latency_outliers_degrade_but_never_quarantine():
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    for _ in range(TIGHT.latency_min_samples):  # EWMA warmup ~10ms
        sup.record_success(0, 32, 0.010)
    for _ in range(TIGHT.latency_outlier_after):
        sup.record_success(0, 32, 0.200)        # 20x the baseline
    assert sup.state_of(0) == "degraded"
    for _ in range(10):                         # keep being slow
        sup.record_success(0, 32, 0.200)
    assert sup.state_of(0) == "degraded"        # slow is not dead
    sup.record_success(0, 32, 0.010)
    assert sup.state_of(0) == "healthy"         # normal sample recovers


def test_probe_revives_quarantined_replica():
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    engine.replicas[1].fail = True
    sup.record_failure(1)
    sup.record_failure(1)
    assert sup.state_of(1) == "quarantined"
    sup.poll()                                  # probe fails: still broken
    assert sup.state_of(1) == "quarantined"
    assert sup.counts["probe_failures"] == 1
    engine.replicas[1].fail = False
    sup.poll()                                  # probe succeeds: revived
    assert sup.state_of(1) == "healthy"
    assert sup.counts["probes"] == 2
    # The probe ran a real synthetic request through the replica.
    assert engine.replicas[1].calls >= 2


def test_probe_traverses_fault_points():
    """An armed replica fault fails the probe too: revival happens only
    once the fault actually clears (the chaos-recovery contract)."""
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    sup.record_failure(1)
    sup.record_failure(1)
    with faults.injected(FaultPlan([
            FaultRule("replica_predict_error", nth=1, every=1,
                      replica=1)])):
        sup.poll()
        assert sup.state_of(1) == "quarantined"  # probe hit the fault
    sup.poll()                                   # fault cleared
    assert sup.state_of(1) == "healthy"


def test_wedge_scan_quarantines_stuck_dispatch():
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)   # wedge_timeout_s=0.2
    token = sup.note_dispatch_start(0, time.monotonic() - 1.0)
    sup._scan_wedged()
    assert sup.state_of(0) == "quarantined"
    # The stuck dispatch eventually finishing must not auto-revive.
    sup.note_dispatch_end(0, token)
    sup.record_success(0, 32, 0.5)
    assert sup.state_of(0) == "quarantined"


def test_wedge_survives_concurrent_dispatch_on_same_replica():
    """Review-found (ISSUE 13 code review): a sibling executor's retry
    runs on this replica concurrently with its own dispatch — with one
    start slot, the retry's note_dispatch_end clobbered the wedged
    dispatch's record and the wedge was never detected. Tokened
    tracking keeps every in-flight dispatch individually visible."""
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)   # wedge_timeout_s=0.2
    wedged = sup.note_dispatch_start(0, time.monotonic() - 1.0)
    retry = sup.note_dispatch_start(0, time.monotonic())
    sup.note_dispatch_end(0, retry)              # the quick retry ends
    sup._scan_wedged()
    assert sup.state_of(0) == "quarantined"      # the wedge is still seen
    sup.note_dispatch_end(0, wedged)


def test_probe_skips_replica_with_stuck_dispatch():
    """Review-found (ISSUE 13 code review): probing a replica whose
    dispatch is still wedged would hang the supervisor loop on the same
    stuck device — the probe waits until the in-flight set drains."""
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    token = sup.note_dispatch_start(0, time.monotonic() - 1.0)
    sup.poll()                                   # wedge scan quarantines
    assert sup.state_of(0) == "quarantined"
    calls = engine.replicas[0].calls
    sup.poll()                                   # still stuck: no probe
    assert engine.replicas[0].calls == calls
    assert sup.state_of(0) == "quarantined"
    sup.note_dispatch_end(0, token)              # the dispatch returns
    sup.poll()                                   # now probe-eligible
    assert sup.state_of(0) == "healthy"
    assert engine.replicas[0].calls == calls + 1


def test_hung_probe_is_bounded_and_does_not_block_siblings():
    """Review-found (ISSUE 13 code review): a probe against a device
    that hangs BETWEEN dispatches must cost one probe_timeout_s, not
    the supervisor loop — other quarantined replicas still get probed
    and revived in the same pass."""
    engine = _Engine()
    hang = threading.Event()
    orig = engine.replicas[0].predict_batch

    def hanging_predict(requests, bucket):
        hang.wait(30)
        return orig(requests, bucket)

    engine.replicas[0].predict_batch = hanging_predict
    cfg = SupervisorConfig(degraded_after=1, quarantine_after=1,
                           probe_interval_s=0, probe_timeout_s=0.2)
    sup = ReplicaSupervisor(engine, cfg=cfg)
    sup.record_failure(0)
    sup.record_failure(1)
    t0 = time.monotonic()
    sup.poll()
    elapsed = time.monotonic() - t0
    hang.set()
    assert elapsed < 2.0                         # bounded, not 30 s
    assert sup.state_of(0) == "quarantined"      # timed out = failed
    assert sup.state_of(1) == "healthy"          # sibling still revived


def test_transitions_ride_the_event_stream(tmp_path):
    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.serve.events import ServeTelemetry

    telemetry = ServeTelemetry(str(tmp_path / "sup.events.jsonl"))
    engine = _Engine()
    sup = ReplicaSupervisor(engine, cfg=TIGHT, telemetry=telemetry)
    engine.replicas[1].fail = True
    sup.record_failure(1, reason="boom")
    sup.record_failure(1, reason="boom")
    sup.poll()                                   # probing -> probe_failed
    engine.replicas[1].fail = False
    sup.poll()                                   # probing -> healthy
    telemetry.close()
    path = str(tmp_path / "sup.events.jsonl")
    assert validate_events_file(path) == []
    import json

    recs = [json.loads(line) for line in open(path, encoding="utf-8")
            if '"replica_state"' in line]
    walk = [(r["from_state"], r["state"], r["reason"]) for r in recs]
    assert walk == [
        ("healthy", "degraded", "boom"),
        ("degraded", "quarantined", "boom"),
        ("quarantined", "probing", "probe"),
        ("probing", "quarantined", "probe_failed"),
        ("quarantined", "probing", "probe"),
        ("probing", "healthy", "probe_ok"),
    ]
    assert all(r["state"] in REPLICA_STATES for r in recs)


def test_probe_thread_lifecycle_restartable():
    engine = _Engine()
    sup = ReplicaSupervisor(
        engine, cfg=SupervisorConfig(probe_interval_s=0.02))
    engine.replicas[0].fail = True
    sup.record_failure(0)
    sup.record_failure(0)
    sup.record_failure(0)
    assert sup.state_of(0) == "quarantined"
    sup.start()
    try:
        assert _poll(lambda: sup.counts["probes"] >= 1)
        sup.stop()
        n = sup.counts["probes"]
        time.sleep(0.1)
        assert sup.counts["probes"] == n         # really stopped
        engine.replicas[0].fail = False
        sup.start()                              # restartable
        assert _poll(lambda: sup.state_of(0) == "healthy")
    finally:
        sup.stop()


# ------------------------------------------- batcher retry + degradation --


def test_retry_once_on_other_replica_no_double_resolve():
    """A dispatch failing on one replica is retried exactly once on a
    different one: the client still gets its flow, the retry counter
    bumps, nothing is double-resolved, and the metrics identity holds
    with zero rejects."""
    engine = _Engine(n=2)
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        metrics=metrics,
        supervisor=ReplicaSupervisor(engine, cfg=TIGHT))
    plan = FaultPlan([FaultRule("replica_predict_error", nth=1, every=1,
                                replica=1)])
    with faults.injected(plan):
        served = 0
        for seed in range(6):
            h = batcher.submit(_pc(20, seed), _pc(20, seed))
            flow = h.wait(10)                    # retried if it hit r1
            assert flow.shape == (20, 3)
            served += 1
    batcher.shutdown(drain=True)
    counts = batcher.counts
    assert counts["served"] == served == 6
    assert counts["rejected"] == 0
    # Work-stealing is nondeterministic, but any dispatch that landed on
    # replica 1 was retried — and replica 0 answered every request.
    snap = metrics.snapshot()
    assert snap["requests_total"] == 6
    assert snap["responses_total"] == 6
    assert metrics.in_flight == 0
    assert counts["retries"] == metrics.retries_total >= 0


def test_failed_retry_fails_group_once():
    """Both replicas broken: the one retry fails too, the request
    errors exactly once (no infinite retry loop), and the supervisor
    walks both replicas toward quarantine."""
    engine = _Engine(n=2)
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        supervisor=sup)
    plan = FaultPlan([FaultRule("replica_predict_error", nth=1, every=1)])
    with faults.injected(plan):
        h = batcher.submit(_pc(20), _pc(20))
        with pytest.raises(InjectedFaultError):
            h.wait(10)
        assert batcher.counts["retries"] == 1
    batcher.shutdown(drain=True)


def test_quarantined_replica_leaves_rotation():
    """Once quarantined, a replica's executor pulls no more work: every
    subsequent request is served by the healthy sibling."""
    engine = _Engine(n=2)
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=16),
        supervisor=sup)
    plan = FaultPlan([FaultRule("replica_predict_error", nth=1, every=1,
                                replica=1)])
    with faults.injected(plan):
        for seed in range(12):
            batcher.submit(_pc(20, seed), _pc(20, seed)).wait(10)
        assert _poll(lambda: sup.state_of(1) == "quarantined")
        calls_at_quarantine = engine.replicas[1].calls
        for seed in range(12, 20):
            batcher.submit(_pc(20, seed), _pc(20, seed)).wait(10)
        # Parked: no new dispatches reached replica 1 (the executor
        # checks rotation before pulling).
        assert engine.replicas[1].calls == calls_at_quarantine
    batcher.shutdown(drain=True)


def test_all_quarantined_rejects_unavailable():
    from pvraft_tpu.serve.batcher import PoolUnavailableError

    engine = _Engine(n=2)
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        metrics=metrics, supervisor=sup)
    for i in range(2):
        sup.record_failure(i)
        sup.record_failure(i)
    assert sup.pool_health()["state"] == "unavailable"
    with pytest.raises(PoolUnavailableError):
        batcher.submit(_pc(20), _pc(20))
    snap = metrics.snapshot()
    assert snap["rejected"] == {"unavailable": 1}
    # Identity: the shed request was counted, nothing is in flight.
    assert snap["requests_total"] == 1
    assert metrics.in_flight == 0
    batcher.shutdown(drain=True)


def test_degraded_pool_shrinks_admission():
    """Admission capacity scales with the serving-replica count: with
    half the pool quarantined, the effective queue depth halves."""
    from pvraft_tpu.serve.batcher import QueueFullError

    engine = _Engine(n=2)
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    # Block both replicas' executors from draining: park them by
    # quarantining replica 1 and wedging the queue with a stopped
    # collector? Simpler: no executors at all — submit-only batcher via
    # a full queue. Use queue_depth=4 and a gate-less engine whose
    # replicas are slow by fault latency.
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=4),
        supervisor=sup)
    sup.record_failure(1)
    sup.record_failure(1)                        # quarantined: 1 of 2
    assert sup.serving_count() == 1
    with faults.injected(FaultPlan([
            FaultRule("replica_latency_ms", nth=1, every=1, replica=0,
                      value=300.0)])):
        accepted, shed = 0, 0
        for seed in range(8):                    # flood faster than drain
            try:
                batcher.submit(_pc(20, seed), _pc(20, seed))
                accepted += 1
            except QueueFullError as e:
                shed += 1
                # The reject names the SCALED capacity (2 of 4 slots).
                assert "2 of 4" in str(e)
        assert shed >= 1
    batcher.shutdown(drain=True)


def test_replica_stats_carry_state_and_prometheus_series():
    engine = _Engine(n=2)
    sup = ReplicaSupervisor(engine, cfg=TIGHT)
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        metrics=metrics, supervisor=sup)
    sup.record_failure(1)
    sup.record_failure(1)
    rows = batcher.replica_stats()
    assert [r["state"] for r in rows] == ["healthy", "quarantined"]
    text = metrics.prometheus(replica_stats=rows)
    assert ('pvraft_serve_replica_state{replica="1",'
            'state="quarantined"} 1') in text
    assert ('pvraft_serve_replica_state{replica="1",'
            'state="healthy"} 0') in text
    assert "pvraft_serve_retries_total 0" in text
    batcher.shutdown(drain=True)


def test_unsupervised_batcher_unchanged():
    """supervisor=None: replica_stats rows keep the pre-supervision
    shape (no state key) and admission is the plain queue_depth check —
    the None path is the PR-8 batcher bit-for-bit."""
    engine = _Engine(n=2)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8))
    h = batcher.submit(_pc(20), _pc(20))
    assert h.wait(10).shape == (20, 3)
    assert all(set(r) == {"replica", "device_id", "in_flight",
                          "batches_total"}
               for r in batcher.replica_stats())
    batcher.shutdown(drain=True)
