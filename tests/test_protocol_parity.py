"""End-to-end eval-protocol parity (slow tier): the reference's complete
standalone eval pipeline (``test.py:82-156`` — FT3D dataset subsampling,
``Batch`` collate, bs=1 DataLoader, 32-iter RSF forward, ``sequence_loss``
+ ``compute_epe`` running means) against our ``Evaluator`` over the same
on-disk FT3D-layout scenes with the same weights imported from a real
``.params`` file.

Forward-flow parity is covered by tests/test_reference_parity.py; this
certifies everything AROUND the model too: dataset load + x/z flip +
subsampling, the 32-iteration protocol, metric formulas, and the
running-mean accumulation. See scripts/protocol_parity.py for the scene
construction (threshold-margin flows) that makes the Acc/Outlier
comparisons exact rather than tolerance-based.
"""

import os

import pytest

REF_ROOT = "/root/reference"

pytestmark = [
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_ROOT, "model")),
        reason="reference checkout not available",
    ),
    pytest.mark.slow,
]


def test_eval_protocol_matches_reference(tmp_path):
    from scripts.protocol_parity import run_parity

    rec = run_parity(str(tmp_path), n_scenes=3, n_points=256, iters=32,
                     truncate_k=64, seed=2024)
    d = rec["abs_delta"]
    # Continuous metrics: fp reassociation across permuted point orders is
    # the only allowed divergence.
    assert d["loss"] <= 1e-4, rec
    assert d["epe3d"] <= 1e-4, rec
    # Threshold metrics: the generated scenes keep every per-point error
    # >=0.02 away from each 0.05/0.1/0.3 boundary, so classification flips
    # would mean a semantic divergence, not fp noise.
    assert d["acc3d_strict"] <= 1e-6, rec
    assert d["acc3d_relax"] <= 1e-6, rec
    assert d["outlier"] <= 1e-6, rec
    # Sanity: the comparison is non-degenerate (not 0% / 100% everywhere).
    ref = rec["reference"]
    assert 0.0 < ref["acc3d_relax"] < 1.0, ref
