"""End-to-end eval-protocol parity (slow tier): the reference's complete
standalone eval pipeline (``test.py:82-156`` — FT3D dataset subsampling,
``Batch`` collate, bs=1 DataLoader, 32-iter RSF forward, ``sequence_loss``
+ ``compute_epe`` running means) against our ``Evaluator`` over the same
on-disk FT3D-layout scenes with the same weights imported from a real
``.params`` file.

Forward-flow parity is covered by tests/test_reference_parity.py; this
certifies everything AROUND the model too: dataset load + x/z flip +
subsampling, the 32-iteration protocol, metric formulas, and the
running-mean accumulation. See scripts/protocol_parity.py for the scene
construction (threshold-margin flows) that makes the Acc/Outlier
comparisons exact rather than tolerance-based.
"""

import os

import pytest

REF_ROOT = "/root/reference"

pytestmark = [
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_ROOT, "model")),
        reason="reference checkout not available",
    ),
    pytest.mark.slow,
]


def test_eval_protocol_matches_reference(tmp_path):
    from scripts.protocol_parity import run_parity

    rec = run_parity(str(tmp_path), n_scenes=3, n_points=256, iters=32,
                     truncate_k=64, seed=2024)
    d = rec["abs_delta"]
    # Continuous metrics: fp reassociation across permuted point orders is
    # the only allowed divergence.
    assert d["loss"] <= 1e-4, rec
    assert d["epe3d"] <= 1e-4, rec
    # Threshold metrics: the generated scenes keep every per-point error
    # >=0.02 away from each 0.05/0.1/0.3 boundary, so classification flips
    # would mean a semantic divergence, not fp noise.
    assert d["acc3d_strict"] <= 1e-6, rec
    assert d["acc3d_relax"] <= 1e-6, rec
    assert d["outlier"] <= 1e-6, rec
    # Sanity: the comparison is non-degenerate (not 0% / 100% everywhere).
    ref = rec["reference"]
    assert 0.0 < ref["acc3d_relax"] < 1.0, ref


def test_kitti_eval_protocol_matches_reference(tmp_path):
    """Zero-shot KITTI leg: the reference's ``Kitti`` dataset applies
    ground/far filters (``kitti_hplflownet.py:81-87``) before subsampling;
    the generated scenes make the filters provably fire (a quarter of the
    rows each) and still leave exactly nb_points survivors on both
    sides."""
    from scripts.protocol_parity import run_parity

    rec = run_parity(str(tmp_path), n_scenes=2, n_points=128, iters=8,
                     truncate_k=64, seed=2024, pretrain_steps=10,
                     dataset="KITTI")
    d = rec["abs_delta"]
    assert d["loss"] <= 1e-4 and d["epe3d"] <= 1e-4, rec
    assert all(d[k] <= 1e-6
               for k in ("acc3d_strict", "acc3d_relax", "outlier")), rec


def test_refine_eval_protocol_matches_reference(tmp_path):
    """Stage-2 leg: ``RSF_refine`` at 32 iters with ``compute_loss`` on
    the single refined flow (``test.py:124-126``) vs our refine
    Evaluator."""
    from scripts.protocol_parity import run_parity

    rec = run_parity(str(tmp_path), n_scenes=2, n_points=128, iters=8,
                     truncate_k=64, seed=2024, pretrain_steps=10,
                     refine=True)
    d = rec["abs_delta"]
    assert d["loss"] <= 1e-4 and d["epe3d"] <= 1e-4, rec
    assert all(d[k] <= 1e-6
               for k in ("acc3d_strict", "acc3d_relax", "outlier")), rec
