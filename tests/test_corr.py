"""Tests for the truncated correlation cache (ops/corr.py)."""

import numpy as np
import pytest
import jax.numpy as jnp

from pvraft_tpu.ops.corr import corr_init, corr_volume, knn_lookup


def _rand(shape, seed):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def test_corr_volume_matches_numpy():
    f1, f2 = _rand((2, 5, 8), 0), _rand((2, 7, 8), 1)
    got = np.asarray(corr_volume(jnp.asarray(f1), jnp.asarray(f2)))
    want = np.einsum("bnd,bmd->bnm", f1, f2) / np.sqrt(8.0)
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_corr_init_topk_values_sorted_descending():
    f1, f2 = _rand((1, 6, 4), 2), _rand((1, 32, 4), 3)
    xyz2 = _rand((1, 32, 3), 4)
    st = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 8)
    vals = np.asarray(st.corr)
    assert vals.shape == (1, 6, 8)
    assert np.all(np.diff(vals, axis=-1) <= 1e-6)
    full = np.asarray(corr_volume(jnp.asarray(f1), jnp.asarray(f2)))
    np.testing.assert_allclose(vals, -np.sort(-full, axis=-1)[..., :8], atol=1e-5)


def test_corr_init_xyz_gather():
    f1, f2 = _rand((1, 4, 4), 5), _rand((1, 16, 4), 6)
    xyz2 = _rand((1, 16, 3), 7)
    st = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 5)
    full = np.asarray(corr_volume(jnp.asarray(f1), jnp.asarray(f2)))
    idx = np.argsort(-full, axis=-1)[..., :5]
    want = xyz2[0][idx[0]]
    np.testing.assert_allclose(np.asarray(st.xyz)[0], want, atol=1e-5)


def test_chunked_equals_full():
    f1, f2 = _rand((2, 8, 16), 8), _rand((2, 64, 16), 9)
    xyz2 = _rand((2, 64, 3), 10)
    full = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 12)
    chunked = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 12, chunk=16)
    np.testing.assert_allclose(
        np.asarray(full.corr), np.asarray(chunked.corr), atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(full.xyz), np.asarray(chunked.xyz), atol=1e-5
    )


def test_knn_lookup_picks_nearest():
    f1, f2 = _rand((1, 3, 4), 11), _rand((1, 32, 4), 12)
    xyz2 = _rand((1, 32, 3), 13)
    st = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 16)
    coords = jnp.asarray(_rand((1, 3, 3), 14))
    rel = st.xyz - coords[:, :, None, :]
    knn_corr, rel_xyz = knn_lookup(st, rel, 4)
    assert knn_corr.shape == (1, 3, 4)
    assert rel_xyz.shape == (1, 3, 4, 3)
    # Every selected distance must be <= every unselected distance.
    rel_all = np.asarray(st.xyz) - np.asarray(coords)[:, :, None, :]
    d_all = (rel_all**2).sum(-1)
    d_sel = (np.asarray(rel_xyz) ** 2).sum(-1)
    for ni in range(3):
        assert d_sel[0, ni].max() <= np.sort(d_all[0, ni])[3] + 1e-5


def test_chunk_larger_than_points_falls_back():
    f1, f2 = _rand((1, 6, 8), 20), _rand((1, 16, 8), 21)
    xyz2 = _rand((1, 16, 3), 22)
    a = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 4,
                  chunk=64)
    b = corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2), 4)
    np.testing.assert_allclose(np.asarray(a.corr), np.asarray(b.corr), atol=1e-6)


def test_approx_with_chunk_rejected_regardless_of_size():
    f1, f2 = _rand((1, 4, 4), 30), _rand((1, 16, 4), 31)
    xyz2 = _rand((1, 16, 3), 32)
    for chunk in (8, 64):  # smaller and larger than N2
        with pytest.raises(ValueError, match="approx_topk"):
            corr_init(jnp.asarray(f1), jnp.asarray(f2), jnp.asarray(xyz2),
                      4, chunk=chunk, approx=True)


def test_chunked_equals_full_randomized_shapes():
    """Streaming top-k sweep over random (N1, N2, K, chunk) combinations:
    the chunked scan must be exactly the dense truncation for every
    divisor chunk size, including chunk == K and single-chunk edges."""
    rng = np.random.default_rng(123)
    for trial in range(8):
        n1 = int(rng.integers(4, 40))
        n2 = int(rng.choice([32, 48, 64, 96]))
        k = int(rng.integers(4, min(24, n2) + 1))
        # c < n2 keeps every trial genuinely chunked (chunk >= N2 falls
        # back to the dense path); chunk < k is a supported regime and
        # the sentinel-handling edge case, so it is NOT filtered out.
        divisors = [c for c in (4, 8, 16, 24, 32, 48)
                    if n2 % c == 0 and c < n2]
        if not divisors:
            continue
        chunk = int(rng.choice(divisors))
        f1 = jnp.asarray(rng.normal(size=(1, n1, 8)).astype(np.float32))
        f2 = jnp.asarray(rng.normal(size=(1, n2, 8)).astype(np.float32))
        xyz2 = jnp.asarray(rng.normal(size=(1, n2, 3)).astype(np.float32))
        full = corr_init(f1, f2, xyz2, k)
        chunked = corr_init(f1, f2, xyz2, k, chunk=chunk)
        np.testing.assert_allclose(
            np.asarray(full.corr), np.asarray(chunked.corr), atol=1e-5,
            err_msg=f"trial {trial}: n1={n1} n2={n2} k={k} chunk={chunk}",
        )
        np.testing.assert_allclose(
            np.asarray(full.xyz), np.asarray(chunked.xyz), atol=1e-5,
            err_msg=f"trial {trial}: n1={n1} n2={n2} k={k} chunk={chunk}",
        )
