"""Loss/metric parity with fancy-indexing numpy oracles (tools/loss.py, tools/metric.py)."""

import numpy as np
import jax.numpy as jnp

from pvraft_tpu.engine.loss import compute_loss, sequence_loss
from pvraft_tpu.engine.metrics import epe_train, flow_metrics


def _data(seed, b=2, n=17):
    rng = np.random.default_rng(seed)
    est = rng.normal(size=(b, n, 3)).astype(np.float32)
    gt = rng.normal(size=(b, n, 3)).astype(np.float32)
    mask = (rng.uniform(size=(b, n)) > 0.3).astype(np.float32)
    return est, gt, mask


def test_compute_loss_oracle():
    est, gt, mask = _data(0)
    got = float(compute_loss(jnp.asarray(est), jnp.asarray(mask), jnp.asarray(gt)))
    err = (est - gt)[mask > 0]  # (sel, 3) then mean over all elements
    np.testing.assert_allclose(got, np.abs(err).mean(), atol=1e-6)


def test_sequence_loss_weighting():
    est, gt, mask = _data(1)
    flows = np.stack([est, est + 0.1, est - 0.2])
    got = float(
        sequence_loss(jnp.asarray(flows), jnp.asarray(mask), jnp.asarray(gt), 0.8)
    )
    want = sum(
        0.8 ** (3 - i - 1) * np.abs((flows[i] - gt)[mask > 0]).mean()
        for i in range(3)
    )
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_epe_train_oracle():
    est, gt, mask = _data(2)
    got = float(epe_train(jnp.asarray(est), jnp.asarray(mask), jnp.asarray(gt)))
    err = (est - gt)[mask > 0]
    np.testing.assert_allclose(got, np.linalg.norm(err, axis=-1).mean(), atol=1e-6)


def test_flow_metrics_oracle():
    est, gt, mask = _data(3)
    est = gt + np.random.default_rng(4).normal(scale=0.08, size=gt.shape).astype(
        np.float32
    )
    got = {
        k: float(v)
        for k, v in flow_metrics(
            jnp.asarray(est), jnp.asarray(mask), jnp.asarray(gt)
        ).items()
    }
    sf_gt = gt[mask > 0]
    sf_pred = est[mask > 0]
    l2 = np.linalg.norm(sf_gt - sf_pred, axis=-1)
    rel = l2 / (np.linalg.norm(sf_gt, axis=-1) + 1e-4)
    np.testing.assert_allclose(got["epe3d"], l2.mean(), atol=1e-6)
    np.testing.assert_allclose(
        got["acc3d_strict"], np.logical_or(l2 < 0.05, rel < 0.05).mean(), atol=1e-6
    )
    np.testing.assert_allclose(
        got["acc3d_relax"], np.logical_or(l2 < 0.1, rel < 0.1).mean(), atol=1e-6
    )
    np.testing.assert_allclose(
        got["outlier"], np.logical_or(l2 > 0.3, rel > 0.1).mean(), atol=1e-6
    )
