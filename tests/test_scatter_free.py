"""Grad parity of the scatter-free custom VJPs vs the XLA defaults, plus
the flags-off jaxpr-unchanged guarantee and the remat-policy numerics."""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pvraft_tpu.config import ModelConfig, resolve_remat_policy
from pvraft_tpu.ops import scatter_free as sf
from pvraft_tpu.ops.corr import CorrState, knn_lookup
from pvraft_tpu.ops.geometry import gather_neighbors


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# --- op-level grad parity ---------------------------------------------------


def test_gather_neighbors_grad_parity(rng):
    feats = jnp.asarray(rng.normal(size=(2, 13, 5)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, 13, size=(2, 7, 4)).astype(np.int32))
    w = jnp.asarray(rng.normal(size=(2, 7, 4, 5)).astype(np.float32))

    def loss(f, dense):
        return jnp.sum(jnp.sin(gather_neighbors(f, idx, dense_vjp=dense)) * w)

    g_ref = jax.grad(lambda f: loss(f, False))(feats)
    g_new = jax.grad(lambda f: loss(f, True))(feats)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_gather_neighbors_grad_parity_chunked(rng, monkeypatch):
    # Force the streaming (lax.scan) backward, incl. a ragged final chunk.
    monkeypatch.setattr(sf, "ONEHOT_ELEM_BUDGET", 64)
    feats = jnp.asarray(rng.normal(size=(2, 13, 5)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, 13, size=(2, 7, 4)).astype(np.int32))

    def loss(f, dense):
        return jnp.sum(jnp.cos(gather_neighbors(f, idx, dense_vjp=dense)))

    g_ref = jax.grad(lambda f: loss(f, False))(feats)
    g_new = jax.grad(lambda f: loss(f, True))(feats)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_knn_lookup_grad_parity(rng):
    corr = jnp.asarray(rng.normal(size=(2, 6, 9)).astype(np.float32))
    xyz = jnp.asarray(rng.normal(size=(2, 6, 9, 3)).astype(np.float32))
    coords = jnp.asarray(rng.normal(size=(2, 6, 3)).astype(np.float32))

    def loss(c, co, dense):
        rel = xyz - co[:, :, None, :]
        kc, rx = knn_lookup(CorrState(corr=c, xyz=xyz), rel, 4,
                            dense_vjp=dense)
        return jnp.sum(jnp.sin(kc)) + jnp.sum(jnp.cos(rx))

    g_ref = jax.grad(lambda c, co: loss(c, co, False), (0, 1))(corr, coords)
    g_new = jax.grad(lambda c, co: loss(c, co, True), (0, 1))(corr, coords)
    for a, b in zip(g_new, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_take_pair_grad_parity_chunked(rng, monkeypatch):
    monkeypatch.setattr(sf, "ONEHOT_ELEM_BUDGET", 32)
    corr = jnp.asarray(rng.normal(size=(2, 7, 9)).astype(np.float32))
    rel = jnp.asarray(rng.normal(size=(2, 7, 9, 3)).astype(np.float32))
    nbr = jnp.asarray(rng.integers(0, 9, size=(2, 7, 4)).astype(np.int32))

    def ref(c, r):
        kc = jnp.take_along_axis(c, nbr, axis=-1)
        rx = jnp.take_along_axis(r, nbr[..., None], axis=2)
        return jnp.sum(jnp.sin(kc)) + jnp.sum(jnp.cos(rx))

    def new(c, r):
        kc, rx = sf.take_pair_onehot(c, r, nbr)
        return jnp.sum(jnp.sin(kc)) + jnp.sum(jnp.cos(rx))

    g_ref = jax.grad(ref, (0, 1))(corr, rel)
    g_new = jax.grad(new, (0, 1))(corr, rel)
    for a, b in zip(g_new, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_max_pool_grad_parity(rng):
    # Continuous random data: maxima unique with probability 1, where the
    # XLA default (tie-splitting) and the argmax VJP agree exactly.
    h = jnp.asarray(rng.normal(size=(2, 6, 4, 5)).astype(np.float32))
    g_ref = jax.grad(lambda x: jnp.sum(jnp.sin(jnp.max(x, axis=2))))(h)
    g_new = jax.grad(lambda x: jnp.sum(jnp.sin(sf.max_pool_argmax(x))))(h)
    np.testing.assert_allclose(np.asarray(g_new), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_max_pool_tie_goes_to_first():
    # Documented tie semantics: full cotangent to the FIRST max (torch),
    # where the XLA default splits it.
    h = jnp.zeros((1, 1, 3, 1), jnp.float32)
    g = jax.grad(lambda x: jnp.sum(sf.max_pool_argmax(x)))(h)
    np.testing.assert_array_equal(
        np.asarray(g)[0, 0, :, 0], np.asarray([1.0, 0.0, 0.0]))


def test_scatter_free_forward_identical(rng):
    feats = jnp.asarray(rng.normal(size=(2, 13, 5)).astype(np.float32))
    idx = jnp.asarray(rng.integers(0, 13, size=(2, 7, 4)).astype(np.int32))
    np.testing.assert_array_equal(
        np.asarray(gather_neighbors(feats, idx)),
        np.asarray(gather_neighbors(feats, idx, dense_vjp=True)),
    )


# --- flags-off jaxpr unchanged ----------------------------------------------


def test_gather_neighbors_default_jaxpr_unchanged(rng):
    feats = jnp.zeros((2, 13, 5), jnp.float32)
    idx = jnp.zeros((2, 7, 4), jnp.int32)
    got = jax.make_jaxpr(gather_neighbors)(feats, idx)
    # The pre-PR implementation, verbatim.
    want = jax.make_jaxpr(jax.vmap(lambda f, i: f[i]))(feats, idx)
    assert str(got) == str(want)


def test_knn_lookup_default_jaxpr_unchanged(rng):
    state = CorrState(corr=jnp.zeros((2, 6, 9), jnp.float32),
                      xyz=jnp.zeros((2, 6, 9, 3), jnp.float32))
    rel = jnp.zeros((2, 6, 9, 3), jnp.float32)

    def pre_pr(corr, rel):
        from jax import lax

        dist = jnp.sum(rel * rel, axis=-1)
        _, nbr = lax.top_k(-dist, 4)
        knn_corr = jnp.take_along_axis(corr, nbr, axis=-1)
        rel_xyz = jnp.take_along_axis(rel, nbr[..., None], axis=2)
        return knn_corr, rel_xyz

    got = jax.make_jaxpr(lambda c, r: knn_lookup(
        CorrState(corr=c, xyz=state.xyz), r, 4))(state.corr, rel)
    want = jax.make_jaxpr(pre_pr)(state.corr, rel)
    assert str(got) == str(want)


def test_model_jaxpr_custom_vjp_only_when_opted_in():
    cfg_off = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                          use_pallas=False)
    cfg_on = dataclasses.replace(cfg_off, scatter_free_vjp=True)
    from pvraft_tpu.models import PVRaft

    pc = jnp.zeros((1, 32, 3), jnp.float32)

    def traced(cfg):
        model = PVRaft(cfg)
        params = jax.eval_shape(
            lambda: model.init(jax.random.key(0), pc, pc, 2))
        return str(jax.make_jaxpr(
            lambda p: model.apply(p, pc, pc, 2))(params))

    assert "custom_vjp" not in traced(cfg_off)
    assert "custom_vjp" in traced(cfg_on)


@pytest.fixture(scope="module")
def ref_grads():
    """Inputs + the default-backward fp32 reference grads, shared by the
    five end-to-end parity tests below — they all use the same seed-0
    clouds and base config, so the reference is identical and computing
    it once saves four model init + backward compiles of tier-1 time."""
    rng = np.random.default_rng(0)
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, 40, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, 40, 3)).astype(np.float32))
    base = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                       use_pallas=False)
    return pc1, pc2, base, _tiny_grads(base, pc1, pc2)


def test_model_grads_scatter_free_match_default(ref_grads):
    # End to end through PVRaft: every wired-in VJP (encoder + update
    # SetConv gathers and max-pools, graph build, knn_lookup) against the
    # XLA default backward. fp32: the formulations are reassociation-free,
    # so parity is essentially exact.
    pc1, pc2, base, g0 = ref_grads
    g1 = _tiny_grads(dataclasses.replace(base, scatter_free_vjp=True),
                     pc1, pc2)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


# --- remat policies ---------------------------------------------------------


def _tiny_grads(cfg, pc1, pc2):
    from pvraft_tpu.models import PVRaft

    model = PVRaft(cfg)
    params = model.init(jax.random.key(0), pc1, pc2, 2)

    def loss(p):
        flows, _ = model.apply(p, pc1, pc2, 2)
        return jnp.sum(flows ** 2)

    return jax.grad(loss)(params)


@pytest.mark.parametrize("policy", ["full", "dots", "dots_no_batch",
                                    "save_corr"])
def test_remat_policy_grads_match_no_remat(policy, ref_grads):
    pc1, pc2, base, g0 = ref_grads
    g1 = _tiny_grads(dataclasses.replace(base, remat_policy=policy),
                     pc1, pc2)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_resolve_remat_policy():
    base = ModelConfig(truncate_k=16, corr_knn=8)
    assert resolve_remat_policy(base) is None
    assert resolve_remat_policy(
        dataclasses.replace(base, remat=True)) == "full"
    assert resolve_remat_policy(
        dataclasses.replace(base, remat_policy="dots")) == "dots"
    # Policy wins over the legacy bool.
    assert resolve_remat_policy(
        dataclasses.replace(base, remat=True, remat_policy="save_corr")
    ) == "save_corr"


def test_invalid_remat_policy_rejected():
    with pytest.raises(ValueError, match="remat_policy"):
        ModelConfig(truncate_k=16, corr_knn=8, remat_policy="everything")


# --- bf16 gradients ---------------------------------------------------------


def test_grad_dtype_cast():
    from pvraft_tpu.engine.steps import maybe_cast_grads

    g = {"w": jnp.asarray([1.0 + 1e-7], jnp.float32)}
    out = maybe_cast_grads(g, "bfloat16")
    assert out["w"].dtype == jnp.float32            # restored for optax
    # Value went through bf16 (1 + 1e-7 is not representable there).
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray([1.0]))
    # float32 default is the identity — same object, unchanged jaxpr.
    assert maybe_cast_grads(g, None) is g
    assert maybe_cast_grads(g, "float32") is g


def test_grad_dtype_config_validation():
    from pvraft_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="grad_dtype"):
        TrainConfig(grad_dtype="float16")
    assert TrainConfig(grad_dtype="bfloat16").grad_dtype == "bfloat16"
