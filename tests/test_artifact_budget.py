"""Artifact size budget + trace downsampler: the evidence files stay
bounded, and shrinking them preserves validator-clean artifacts."""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


def test_budget_table_first_match_wins():
    from artifact_budget import budget_for

    glob, cap = budget_for("artifacts/serve_ab_pool.trace.json")
    assert glob == "artifacts/*.trace.json"
    glob2, cap2 = budget_for("artifacts/serve_ab_pool.json")
    assert glob2 == "artifacts/*.json" and cap2 >= cap / 2
    glob3, _ = budget_for("artifacts/train_cpu_synthetic.events.jsonl")
    assert glob3 == "artifacts/*.events.jsonl"
    # Anything new under artifacts/ falls into the catch-all.
    glob4, cap4 = budget_for("artifacts/whatever.bin")
    assert glob4 == "artifacts/*" and cap4 > 0


def test_committed_artifacts_within_budget(capsys):
    """The lint.sh invariant as a test: every git-tracked artifact fits
    its cap (if this fails, downsample/regenerate — see the script's
    docstring — rather than raising caps casually)."""
    from artifact_budget import main

    assert main([]) == 0, capsys.readouterr().err


def test_downsample_preserves_validity(tmp_path):
    from downsample_trace import downsample, main

    from pvraft_tpu.obs.trace import validate_trace_artifact

    src = os.path.join(REPO, "artifacts", "serve_cpu_synthetic.trace.json")
    doc = json.load(open(src, encoding="utf-8"))
    original_of = doc.get("downsampled", {}).get(
        "of", doc["counts"]["traces"])
    out = downsample(doc, 5)
    assert validate_trace_artifact(out) == []
    assert out["counts"]["traces"] == 5
    assert len(out["traces"]) == 5
    # The marker survives repeated shrinking: "of" stays the ORIGINAL
    # capture size, so the artifact never pretends to be the full run.
    again = downsample(out, 3)
    assert again["downsampled"] == {"kept": 3, "of": original_of}
    assert validate_trace_artifact(again) == []

    # CLI round-trip via --out; refuses an invalid artifact.
    dst = tmp_path / "sub.trace.json"
    assert main([src, "--keep", "4", "--out", str(dst)]) == 0
    sub = json.load(open(dst, encoding="utf-8"))
    assert validate_trace_artifact(sub) == []
    bad = tmp_path / "bad.trace.json"
    bad.write_text(json.dumps({"schema": "nope"}))
    assert main([str(bad), "--keep", "2"]) == 1


def test_downsample_keep_all_is_identity():
    from downsample_trace import downsample

    src = os.path.join(REPO, "artifacts", "serve_ab_pool.trace.json")
    doc = json.load(open(src, encoding="utf-8"))
    assert downsample(doc, 10 ** 6) is doc
