"""Serve subsystem gate: padding invariance, batcher correctness under
real thread concurrency, HTTP smoke (the CI serve smoke test), serve
telemetry schema, and the default-path jaxpr guarantee of the mask
plumbing.

The engine fixture AOT-compiles 2 tiny programs (2 buckets x one
batch size) once per module; every test that needs a real model shares
it (compile cost paid once, conftest.py discipline). batch_sizes=(2,)
keeps the program count at the tier-1 budget's mercy: single predicts
route through the bs-2 program with a filled slot — which is itself the
exactness property test_batch_slot_fill_exact gates — and the bs-1
program family still compiles in test_serve_compile_events."""

import json
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.models import PVRaft
from pvraft_tpu.serve import (
    BatcherConfig,
    InferenceEngine,
    MicroBatcher,
    QueueFullError,
    RequestError,
    ServeConfig,
    ServeHTTPServer,
    ServeMetrics,
    ServeTelemetry,
    ShutdownError,
)

TINY_MODEL = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)
# dtype pinned fp32: these tests compare against an fp32 model.apply
# reference (the bf16 default's accuracy bound has its own gate in
# tests/test_serve_pool.py). replicas=1: single-executor semantics; the
# pool paths are covered by test_serve_pool.py.
TINY_SERVE = ServeConfig(model=TINY_MODEL, buckets=(32, 64),
                         batch_sizes=(2,), num_iters=2,
                         dtype="float32", replicas=1)
ITERS = TINY_SERVE.num_iters


def _cloud(rng, n):
    return rng.uniform(-1, 1, (n, 3)).astype(np.float32)


@pytest.fixture(scope="module")
def served():
    """(engine, params, model): one AOT engine for the whole module."""
    rng = np.random.default_rng(0)
    model = PVRaft(TINY_MODEL)
    pc = jnp.asarray(_cloud(rng, 24)[None])
    params = model.init(jax.random.key(0), pc, pc, ITERS)
    engine = InferenceEngine(params, TINY_SERVE)
    return engine, params, model


# ------------------------------------------------------------ invariance --


def test_padding_invariance(served):
    """Padded-bucket predictions match unpadded single-example inference.

    The bound is float reassociation only (masked GroupNorm reductions
    sum extra zeros): measured max abs diff ~2e-6 on this geometry; 1e-5
    is the seed-stable ceiling."""
    engine, params, model = served
    rng = np.random.default_rng(1)
    # Three shapes cover the contract's corners: the min_points boundary,
    # cross-bucket n1 != n2, and an exact largest-bucket fit (each
    # distinct unpadded shape is a fresh reference compile — keep few).
    for n1, n2 in ((16, 16), (33, 40), (64, 64)):
        pc1, pc2 = _cloud(rng, n1), _cloud(rng, n2)
        got = engine.predict(pc1, pc2)
        want = np.asarray(
            model.apply(params, pc1[None], pc2[None], ITERS)[0][-1][0])
        assert got.shape == (n1, 3)
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=0)


def test_batch_slot_fill_exact(served):
    """Unused batch slots (repeat of request 0) cannot perturb real
    slots: a 2-request group equals each request served alone."""
    engine, _, _ = served
    rng = np.random.default_rng(2)
    reqs = [(_cloud(rng, 20), _cloud(rng, 20)),
            (_cloud(rng, 28), _cloud(rng, 30))]
    together = engine.predict_batch(reqs, 32)
    for (pc1, pc2), flow in zip(reqs, together):
        alone = engine.predict(pc1, pc2)
        np.testing.assert_array_equal(flow, alone)


# ------------------------------------------------------------- contract --


def test_request_validation(served):
    engine, _, _ = served
    rng = np.random.default_rng(3)
    ok = _cloud(rng, 20)
    with pytest.raises(RequestError) as e:
        engine.validate_request(_cloud(rng, 8), ok)   # < min_points (16)
    assert e.value.reason == "too_small"
    with pytest.raises(RequestError) as e:
        engine.validate_request(_cloud(rng, 100), _cloud(rng, 100))
    assert e.value.reason == "too_large"
    bad = ok.copy()
    bad[0, 0] = 1e6                                   # beyond coord_limit
    with pytest.raises(RequestError) as e:
        engine.validate_request(bad, ok)
    assert e.value.reason == "bad_request"
    nan = ok.copy()
    nan[0, 0] = np.nan
    with pytest.raises(RequestError) as e:
        engine.validate_request(nan, ok)
    assert e.value.reason == "bad_request"
    assert engine.validate_request(ok, _cloud(rng, 60)) == 64


def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(model=TINY_MODEL, buckets=(64, 32))     # not ascending
    with pytest.raises(ValueError):
        ServeConfig(model=TINY_MODEL, buckets=(8,))         # < min_points
    with pytest.raises(ValueError):
        ServeConfig(model=TINY_MODEL, buckets=(32,), batch_sizes=())
    with pytest.raises(ValueError):
        ServeConfig(model=TINY_MODEL, buckets=(32,), dtype="float64")
    with pytest.raises(ValueError):
        ServeConfig(model=TINY_MODEL, buckets=(32,), replicas=-1)
    cfg = ServeConfig(model=TINY_MODEL, buckets=(32, 64))
    assert cfg.min_points == 16
    # The declared serving defaults: bf16 dtype, whole-pool replicas.
    assert cfg.dtype == "bfloat16"
    assert cfg.replicas == 0


# ---------------------------------------------- batcher (threaded, real) --


def test_batcher_buckets_and_exact_flow(served):
    """Concurrent requests across point counts land in the right buckets
    and come back as the exact un-padded flow of the single path."""
    engine, _, _ = served
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=20, queue_depth=16),
        metrics=metrics)
    rng = np.random.default_rng(4)
    sizes = [20, 28, 40, 64, 17, 50]
    reqs = [(_cloud(rng, n), _cloud(rng, n)) for n in sizes]
    want = [engine.predict(pc1, pc2) for pc1, pc2 in reqs]

    handles = [None] * len(reqs)

    def client(i):
        handles[i] = batcher.submit(*reqs[i])

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, h in enumerate(handles):
        got = h.wait(60)
        assert got.shape == (sizes[i], 3)
        # Same compiled program, same padded inputs -> the batched
        # dispatch reproduces the single path (only the sibling slot's
        # contents differ, and batch-parallel ops make that irrelevant —
        # the slot-fill exactness test gates it).
        np.testing.assert_allclose(got, want[i], atol=1e-5, rtol=0)
    batcher.shutdown(drain=True)
    snap = metrics.snapshot(batcher.queue_depths())
    assert snap["responses_total"] == len(reqs)
    assert snap["per_bucket_requests"]["32"] == 3   # n in {20, 28, 17}
    assert snap["per_bucket_requests"]["64"] == 3   # n in {40, 64, 50}
    assert snap["queue_depth"] == {"32": 0, "64": 0}


class _FakeEngine:
    """Batcher-logic double: real routing/validation shape, no XLA. A
    gate event makes dispatch block on demand, so queue-full and drain
    states are reachable deterministically."""

    def __init__(self, buckets=(32, 64), batch_sizes=(1, 2)):
        self.cfg = SimpleNamespace(
            buckets=buckets, batch_sizes=batch_sizes, min_points=4,
            coord_limit=100.0)
        self.gate = threading.Event()
        self.gate.set()
        self.dispatched = []

    def validate_request(self, pc1, pc2):
        n = max(pc1.shape[0], pc2.shape[0])
        for b in self.cfg.buckets:
            if n <= b:
                return b
        raise RequestError("too_large", "too large")

    def batch_size_for(self, n):
        for bs in self.cfg.batch_sizes:
            if n <= bs:
                return bs
        return self.cfg.batch_sizes[-1]

    def predict_batch(self, requests, bucket):
        self.gate.wait(30)
        self.dispatched.append((bucket, len(requests)))
        return [np.asarray(pc2[: pc1.shape[0]] - pc1, np.float32)
                for pc1, pc2 in requests]

    def compile_report(self):
        return []

    def weights_info(self):
        return {"path": "", "digest": "fake", "epoch": -1, "swaps": 0}


def _pc(n, seed=0):
    return np.random.default_rng(seed).uniform(
        -1, 1, (n, 3)).astype(np.float32)


def test_backpressure_full_queue_raises_not_blocks():
    engine = _FakeEngine()
    engine.gate.clear()                    # dispatcher hangs mid-flight
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=2))
    # Pipeline capacity ahead of the bucket queue (max_batch=1, one
    # executor): 1 executing + 1 in the batch queue + 1 formed group in
    # the collector's hands. Fill those, then the queue_depth=2 bucket
    # queue, and the NEXT submit must shed load.
    first = batcher.submit(_pc(20), _pc(20))
    time.sleep(0.2)                        # executor picks it up, blocks
    for seed in range(1, 5):
        batcher.submit(_pc(20, seed), _pc(20, seed))
        time.sleep(0.1)    # let the collector advance the pipeline
    # Now saturated: 1 executing, 1 formed batch queued, 1 group in the
    # collector's hands, bucket queue full (2/2).
    t0 = time.monotonic()
    with pytest.raises(QueueFullError):
        batcher.submit(_pc(20, 5), _pc(20, 5))
    # The whole point of explicit backpressure: the reject is immediate,
    # not a blocked put under the queue lock.
    assert time.monotonic() - t0 < 1.0
    assert batcher.counts["rejected"] == 1
    engine.gate.set()
    assert first.wait(30).shape == (20, 3)
    batcher.shutdown(drain=True)
    assert batcher.counts["served"] == 5


def test_shutdown_drains_in_flight():
    engine = _FakeEngine()
    engine.gate.clear()
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=1, queue_depth=16))
    handles = [batcher.submit(_pc(20, i), _pc(20, i)) for i in range(6)]
    done = threading.Event()

    def stopper():
        batcher.shutdown(drain=True)
        done.set()

    t = threading.Thread(target=stopper)
    t.start()
    time.sleep(0.2)
    with pytest.raises(ShutdownError):     # intake closed immediately
        batcher.submit(_pc(20, 99), _pc(20, 99))
    assert not done.is_set()               # drain waits for the gate
    engine.gate.set()
    t.join(30)
    assert done.is_set()
    for h in handles:                      # every accepted request served
        assert h.wait(1).shape == (20, 3)
    assert batcher.counts["served"] == 6


def test_shutdown_without_drain_fails_queued():
    engine = _FakeEngine()
    engine.gate.clear()                    # worker blocks on request 0
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=16))
    handles = [batcher.submit(_pc(20, i), _pc(20, i)) for i in range(4)]
    time.sleep(0.2)
    # Stop WITHOUT drain while requests 1-3 are still queued; release the
    # gate only after the stop flag is set, so the queued ones cannot be
    # served in the window (the in-flight request 0 may still finish).
    stopper = threading.Thread(
        target=lambda: batcher.shutdown(drain=False))
    stopper.start()
    time.sleep(0.2)
    engine.gate.set()
    stopper.join(30)
    assert not stopper.is_alive()
    outcomes = []
    for h in handles:
        try:
            h.wait(5)
            outcomes.append("ok")
        except ShutdownError:
            outcomes.append("shutdown")
    assert outcomes.count("shutdown") >= 3  # queued work failed, not served
    assert outcomes.count("ok") <= 1        # at most the in-flight request
    # Accepted-then-failed requests are accounted: every shutdown-failed
    # handle shows up in the reject ledger, so served + rejected still
    # covers all four accepted submits.
    assert batcher.counts["rejected"] == outcomes.count("shutdown")
    assert batcher.counts["served"] == outcomes.count("ok")


def test_metrics_failure_accounting_reconciles():
    """record_failure keeps the reconciliation identity for accepted
    requests that never produce a response (504/500): requests_total ==
    responses_total + sum(rejected) once nothing is in flight."""
    m = ServeMetrics(buckets=(32,))
    m.record_submit(32)                      # -> 200
    m.record_submit(32)                      # -> 504
    m.record_reject("bad_request")           # never accepted
    assert m.in_flight == 2                  # both accepted, no outcome yet
    m.record_batch(1, 0.5, [3.0])
    m.record_failure("timeout")
    snap = m.snapshot()
    assert snap["requests_total"] == 3
    assert snap["responses_total"] + sum(snap["rejected"].values()) == 3
    assert snap["rejected"] == {"bad_request": 1, "timeout": 1}
    # Every accepted request has an outcome -> the live gauge is back to
    # zero and the identity holds with in_flight included.
    assert m.in_flight == 0


# ------------------------------------------------- HTTP smoke (CI gate) --


def _http(method, host, port, path, body=None, ctype="application/json"):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        headers = {"Content-Type": ctype} if body is not None else {}
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read()
    finally:
        conn.close()


def test_http_smoke_one_request_per_bucket(served, tmp_path):
    """The CI serve smoke: start on an ephemeral port, one padded
    request per bucket, health + metrics, clean drain shutdown."""
    engine, params, model = served
    telemetry = ServeTelemetry(str(tmp_path / "serve.events.jsonl"),
                               cfg=TINY_SERVE)
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=5, queue_depth=16),
        telemetry=telemetry, metrics=metrics)
    server = ServeHTTPServer(batcher, port=0, metrics=metrics)
    server.start()
    rng = np.random.default_rng(5)
    try:
        for n in (20, 48):                 # one per bucket (32, 64)
            pc1, pc2 = _cloud(rng, n), _cloud(rng, n)
            status, body = _http(
                "POST", server.host, server.port, "/predict",
                json.dumps({"pc1": pc1.tolist(), "pc2": pc2.tolist()}))
            assert status == 200
            doc = json.loads(body)
            assert doc["n"] == n
            np.testing.assert_allclose(
                np.asarray(doc["flow"], np.float32),
                engine.predict(pc1, pc2), atol=1e-5, rtol=0)

        # msgpack fast path mirrors the JSON answer.
        import msgpack

        pc1, pc2 = _cloud(rng, 24), _cloud(rng, 24)
        status, body = _http(
            "POST", server.host, server.port, "/predict",
            msgpack.packb({"pc1": pc1.tobytes(), "pc2": pc2.tobytes()}),
            ctype="application/msgpack")
        assert status == 200
        doc = msgpack.unpackb(body, raw=False)
        flow = np.frombuffer(doc["flow"], np.float32).reshape(-1, 3)
        np.testing.assert_allclose(flow, engine.predict(pc1, pc2),
                                   atol=1e-5, rtol=0)

        status, body = _http("GET", server.host, server.port, "/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok"
        assert health["buckets"] == [32, 64]
        assert len(health["programs"]) == 2      # 2 buckets x 1 batch size
        assert all(p["compile_s"] >= 0 for p in health["programs"])

        status, body = _http("GET", server.host, server.port, "/metrics")
        snap = json.loads(body)
        assert status == 200
        assert snap["responses_total"] == 3
        assert snap["latency"]["count"] == 3

        # Contract errors surface as status codes, not 500s.
        status, _ = _http(
            "POST", server.host, server.port, "/predict",
            json.dumps({"pc1": [[0, 0, 0]] * 8, "pc2": [[0, 0, 0]] * 8}))
        assert status == 400                     # too_small
        status, _ = _http(
            "POST", server.host, server.port, "/predict",
            json.dumps({"pc1": [[0, 0, 0]] * 100, "pc2": [[0, 0, 0]] * 100}))
        assert status == 413                     # too_large
        status, _ = _http(
            "POST", server.host, server.port, "/predict", "not json")
        assert status == 400
    finally:
        server.shutdown(drain=True)
        telemetry.close()

    # The serve event log is schema-valid and complete: header, one
    # compile-free run (engine was prebuilt), batches, rejects, shutdown.
    from pvraft_tpu.obs.events import validate_events_file

    path = str(tmp_path / "serve.events.jsonl")
    assert validate_events_file(path) == []
    types = [json.loads(line)["type"]
             for line in open(path, encoding="utf-8")]
    assert types[0] == "run_header"
    assert "serve_batch" in types
    assert "serve_reject" in types
    assert types[-1] == "serve_shutdown"


# ------------------------------------- tracing + Prometheus (HTTP layer) --


def _fake_server(tmp_path, sample_every=1):
    """Full HTTP stack over the engine double: real sockets, real
    tracer/telemetry, no XLA — the tracing/exposition layer is
    host-side and must be testable at host-side cost."""
    from pvraft_tpu.obs.trace import Tracer

    engine = _FakeEngine()
    telemetry = ServeTelemetry(str(tmp_path / "serve.events.jsonl"))
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=2, queue_depth=16),
        telemetry=telemetry, metrics=metrics)
    tracer = Tracer(sample_every=sample_every, emit=telemetry.emit_span)
    server = ServeHTTPServer(
        batcher, port=0, metrics=metrics, tracer=tracer,
        telemetry=telemetry, trace_dir=str(tmp_path / "xla_traces"))
    server.start()
    return server, telemetry


def _http_full(method, host, port, path, body=None,
               ctype="application/json"):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        headers = {"Content-Type": ctype} if body is not None else {}
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_traced_request_spans_and_prometheus(tmp_path):
    """A traced request answers with its trace id, lands a COMPLETE
    span tree on the event stream (ingress through respond), and shows
    up in the Prometheus per-stage histograms — while the JSON /metrics
    keeps its frozen shape and /healthz reports the tracing config."""
    from pvraft_tpu.obs.trace import SERVE_STAGES, collect_traces

    server, telemetry = _fake_server(tmp_path, sample_every=1)
    try:
        status, body, headers = _http_full(
            "POST", server.host, server.port, "/predict",
            json.dumps({"pc1": _pc(20).tolist(),
                        "pc2": _pc(20, 1).tolist()}))
        assert status == 200
        trace_id = headers.get("X-Pvraft-Trace")
        assert trace_id

        # Span assembly runs AFTER the reply bytes hit the socket (by
        # design: tracing never sits between the engine and the client),
        # so an immediate scrape can beat _finish_trace — poll briefly.
        deadline = time.monotonic() + 5.0
        while True:
            status, body, headers = _http_full(
                "GET", server.host, server.port,
                "/metrics?format=prometheus")
            assert status == 200
            text = body.decode()
            if ('stage="respond"' in text
                    or time.monotonic() > deadline):
                break
            time.sleep(0.02)
        assert headers["Content-Type"] == "text/plain; version=0.0.4"
        assert "pvraft_serve_requests_total 1" in text
        for stage in SERVE_STAGES:
            assert f'stage="{stage}"' in text, stage
        assert "pvraft_serve_request_points_count 1" in text

        status, body, _ = _http_full(
            "GET", server.host, server.port, "/metrics")
        snap = json.loads(body)
        assert set(snap) == {
            "requests_total", "responses_total", "rejected",
            "batches_total", "batch_fill_mean", "per_bucket_requests",
            "latency", "queue_depth"}          # frozen pre-PR shape

        status, body, _ = _http_full(
            "GET", server.host, server.port, "/metrics?format=nope")
        assert status == 400

        status, body, _ = _http_full(
            "GET", server.host, server.port, "/healthz")
        tele = json.loads(body)["telemetry"]
        assert tele["tracing"] is True
        assert tele["trace_sample_every"] == 1
        assert tele["events_path"].endswith("serve.events.jsonl")
    finally:
        server.shutdown(drain=True)
        telemetry.close()

    from pvraft_tpu.obs.events import validate_events_file

    path = str(tmp_path / "serve.events.jsonl")
    assert validate_events_file(path) == []
    records = [json.loads(line) for line in open(path, encoding="utf-8")]
    doc = collect_traces(records)
    assert doc["counts"]["traces"] == 1
    assert doc["counts"]["complete"] == 1
    assert doc["counts"]["orphan_spans"] == 0
    assert doc["traces"][0]["trace_id"] == trace_id
    root = [s for s in doc["traces"][0]["spans"]
            if "parent_id" not in s][0]
    assert root["attrs"]["status"] == 200
    exec_span = [s for s in doc["traces"][0]["spans"]
                 if s["name"] == "device_execute"][0]
    assert exec_span["attrs"]["bucket"] == 32


def test_tracing_off_emits_nothing(tmp_path):
    """sample_every=0: no trace header, no span events — the off path
    is the default serve posture and must leave zero residue."""
    server, telemetry = _fake_server(tmp_path, sample_every=0)
    try:
        status, _, headers = _http_full(
            "POST", server.host, server.port, "/predict",
            json.dumps({"pc1": _pc(20).tolist(),
                        "pc2": _pc(20, 1).tolist()}))
        assert status == 200
        assert "X-Pvraft-Trace" not in headers
    finally:
        server.shutdown(drain=True)
        telemetry.close()
    records = [json.loads(line) for line in
               open(str(tmp_path / "serve.events.jsonl"), encoding="utf-8")]
    assert not [r for r in records if r["type"] == "span"]


def test_debug_trace_endpoint(tmp_path):
    """/debug/trace captures a real jax.profiler window from the live
    server: 200 with the trace dir, trace_window start/stop on the
    event stream, input validation on seconds."""
    import os

    server, telemetry = _fake_server(tmp_path)
    try:
        status, body, _ = _http_full(
            "GET", server.host, server.port, "/debug/trace?seconds=bogus")
        assert status == 400
        status, body, _ = _http_full(
            "GET", server.host, server.port, "/debug/trace?seconds=999")
        assert status == 400
        status, body, _ = _http_full(
            "GET", server.host, server.port, "/debug/trace?seconds=0.1")
        assert status == 200, body
        doc = json.loads(body)
        assert os.path.isdir(doc["trace_dir"])
        assert doc["trace_dir"].startswith(str(tmp_path / "xla_traces"))
    finally:
        server.shutdown(drain=True)
        telemetry.close()
    records = [json.loads(line) for line in
               open(str(tmp_path / "serve.events.jsonl"), encoding="utf-8")]
    windows = [r for r in records if r["type"] == "trace_window"]
    assert [w["action"] for w in windows] == ["start", "stop"]
    assert all(w["trace_dir"] == doc["trace_dir"] for w in windows)


# ----------------------------------------------------- telemetry schema --


def test_serve_compile_events(served, tmp_path):
    """A telemetry-attached engine records every AOT program before the
    first request (startup cost is in the ledger, not folklore). One
    (bucket, batch) keeps this a single extra compile — the emission
    path is the same for N."""
    _, params, _ = served
    path = str(tmp_path / "compile.events.jsonl")
    one = ServeConfig(model=TINY_MODEL, buckets=(32,), batch_sizes=(1,),
                      num_iters=ITERS, dtype="float32", replicas=1)
    telemetry = ServeTelemetry(path, cfg=one)
    InferenceEngine(params, one, telemetry=telemetry)
    telemetry.close()
    from pvraft_tpu.obs.events import validate_events_file

    assert validate_events_file(path) == []
    recs = [json.loads(line) for line in open(path, encoding="utf-8")]
    compiles = [r for r in recs if r["type"] == "serve_compile"]
    assert {(r["bucket"], r["batch"]) for r in compiles} == {(32, 1)}
    assert all(r["compile_s"] >= 0 for r in compiles)
    # Replica-pool provenance rides every compile record.
    assert all(r["dtype"] == "float32" and r["replica"] == 0
               and isinstance(r["device_id"], int) for r in compiles)


# ------------------------------------------------- load artifact schema --


def _minimal_artifact():
    return {
        "schema": "pvraft_serve_load/v1",
        "config": {},
        "compile": [],
        "requests": {"total": 4, "ok": 3, "rejected": 1, "errors": 0},
        "latency_ms": {"p50": 10.0, "p95": 20.0, "p99": 30.0,
                       "mean": 12.0, "max": 31.0},
        "throughput_rps": 5.0,
        "duration_s": 1.0,
        "server_metrics": {},
    }


def test_load_artifact_validator():
    from pvraft_tpu.serve.loadgen import validate_load_artifact

    assert validate_load_artifact(_minimal_artifact()) == []
    bad = _minimal_artifact()
    bad["requests"]["ok"] = 99               # ok+rejected+errors != total
    assert validate_load_artifact(bad)
    bad = _minimal_artifact()
    del bad["latency_ms"]
    assert validate_load_artifact(bad)
    bad = _minimal_artifact()
    bad["latency_ms"]["p50"] = 99.0          # quantiles must be ordered
    assert validate_load_artifact(bad)
    bad = _minimal_artifact()
    bad["schema"] = "pvraft_serve_load/v0"
    assert validate_load_artifact(bad)


def test_committed_load_artifact_validates():
    """The committed CPU-synthetic evidence parses against all four
    schemas (same gates scripts/lint.sh runs): load artifact, events,
    trace artifact, SLO report — and the SLO evidence actually carries
    what the serving ROADMAP item needs (complete traces, a per-stage
    decomposition whose p99 sum tracks the end-to-end p99)."""
    import os

    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.obs.slo import validate_slo_report_file
    from pvraft_tpu.obs.trace import validate_trace_artifact_file
    from pvraft_tpu.serve.loadgen import validate_load_artifact_file

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = os.path.join(root, "artifacts", "serve_cpu_synthetic.json")
    events = os.path.join(root, "artifacts",
                          "serve_cpu_synthetic.events.jsonl")
    trace = os.path.join(root, "artifacts",
                         "serve_cpu_synthetic.trace.json")
    slo = os.path.join(root, "artifacts", "serve_cpu_synthetic.slo.json")
    assert validate_load_artifact_file(art) == []
    assert validate_events_file(events) == []
    assert validate_trace_artifact_file(trace) == []
    assert validate_slo_report_file(slo) == []
    doc = json.load(open(trace, encoding="utf-8"))
    assert doc["counts"]["complete"] == doc["counts"]["traces"] > 0
    assert doc["counts"]["orphan_spans"] == 0
    report = json.load(open(slo, encoding="utf-8"))
    assert report["totals"]["complete"] == report["totals"]["ok"]
    # The stage-sum honesty ratio is held to the band the report itself
    # declares (slo.ratio_band, what slo_report --check enforced): the
    # committed c1 run measures 1.05-1.12 — short requests leave
    # un-instrumented scheduler gaps a larger share of per-stage p99s
    # (BENCHMARKS.md "SLO evidence").
    lo, hi = report["slo"]["ratio_band"]
    for row in report["programs"]:
        assert lo <= row["stage_sum_ratio"] <= hi


# --------------------------------------- default-path jaxpr (convention) --


def test_mask_off_jaxpr_identity():
    """The mask plumbing is a Python-level branch: with masks absent the
    SetConv jaxpr is byte-identical to a verbatim pre-mask replica
    (repo convention: opt-in features leave the default path untouched)."""
    import flax.linen as nn

    from pvraft_tpu.analysis.jaxpr.rules import normalize_jaxpr_str
    from pvraft_tpu.models.layers import SetConv
    from pvraft_tpu.ops.geometry import Graph, build_graph, gather_neighbors

    class OldSetConv(nn.Module):
        """Pre-PR SetConv body, replicated verbatim (mask-free)."""

        out_ch: int

        @nn.compact
        def __call__(self, x, graph):
            b, n, c = x.shape
            mid = (self.out_ch + c) // 2 if c % 2 == 0 else self.out_ch // 2
            nb = gather_neighbors(x, graph.neighbors)
            edge = nb - x[:, :, None, :]
            h = jnp.concatenate(
                [edge, graph.rel_pos.astype(x.dtype)], axis=-1)
            h = nn.Dense(mid, use_bias=False, name="fc1")(h)
            h = nn.GroupNorm(num_groups=8, epsilon=1e-5, name="gn1")(h)
            h = jax.nn.leaky_relu(h, 0.1)
            h = jnp.max(h, axis=2)
            h = nn.Dense(self.out_ch, use_bias=False, name="fc2")(h)
            h = nn.GroupNorm(num_groups=8, epsilon=1e-5, name="gn2")(h)
            h = jax.nn.leaky_relu(h, 0.1)
            h = nn.Dense(self.out_ch, use_bias=False, name="fc3")(h)
            h = nn.GroupNorm(num_groups=8, epsilon=1e-5, name="gn3")(h)
            h = jax.nn.leaky_relu(h, 0.1)
            return h

    rng = np.random.default_rng(0)
    pc = jnp.asarray(rng.uniform(-1, 1, (2, 24, 3)).astype(np.float32))
    graph = build_graph(pc, 4)

    def jaxpr_of(module):
        params = module.init(jax.random.key(0), pc, graph)
        return normalize_jaxpr_str(str(jax.make_jaxpr(
            lambda p, x: module.apply(p, x, graph))(params, pc)))

    assert jaxpr_of(SetConv(16)) == jaxpr_of(OldSetConv(16))
