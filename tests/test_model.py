"""End-to-end model tests on tiny shapes."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.models.raft import PVRaft, PVRaftRefine

CFG = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8, encoder_width=32)


def _clouds(seed, b=2, n=64):
    rng = np.random.default_rng(seed)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, size=(b, n, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, size=(b, n, 3)).astype(np.float32))
    return xyz1, xyz2


def test_forward_shapes():
    xyz1, xyz2 = _clouds(0)
    model = PVRaft(CFG)
    params = model.init(jax.random.key(0), xyz1, xyz2, 2)
    flows, graph1 = model.apply(params, xyz1, xyz2, num_iters=3)
    assert flows.shape == (3, 2, 64, 3)
    assert graph1.neighbors.shape == (2, 64, 8)
    assert np.all(np.isfinite(np.asarray(flows)))


def test_iters_change_prediction_but_not_params():
    xyz1, xyz2 = _clouds(1)
    model = PVRaft(CFG)
    p2 = model.init(jax.random.key(0), xyz1, xyz2, 2)
    p4 = model.init(jax.random.key(0), xyz1, xyz2, 4)
    # Same parameter structure regardless of scan length.
    assert jax.tree_util.tree_structure(p2) == jax.tree_util.tree_structure(p4)
    f2, _ = model.apply(p2, xyz1, xyz2, num_iters=2)
    f4, _ = model.apply(p2, xyz1, xyz2, num_iters=4)
    # First two iterations of the longer run equal the shorter run.
    np.testing.assert_allclose(np.asarray(f2), np.asarray(f4[:2]), atol=1e-5)


@pytest.mark.slow
def test_backbone_gradients_flow():
    xyz1, xyz2 = _clouds(2)
    model = PVRaft(CFG)
    params = model.init(jax.random.key(0), xyz1, xyz2, 2)

    def loss(p):
        flows, _ = model.apply(p, xyz1, xyz2, num_iters=2)
        return jnp.mean(flows[-1] ** 2)

    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_leaves_with_path(g)
    nonzero = [
        jax.tree_util.keystr(k) for k, v in flat if np.abs(np.asarray(v)).max() > 0
    ]
    # Update block, correlation convs and both encoders all receive gradient.
    assert any("update_block" in k for k in nonzero)
    assert any("corr_lookup" in k for k in nonzero)
    assert any("feature_extractor" in k for k in nonzero)
    assert any("context_extractor" in k for k in nonzero)


@pytest.mark.slow
def test_refine_freezes_backbone():
    xyz1, xyz2 = _clouds(3)
    model = PVRaftRefine(CFG)
    params = model.init(jax.random.key(0), xyz1, xyz2, 2)
    out = model.apply(params, xyz1, xyz2, num_iters=2)
    assert out.shape == (2, 64, 3)

    def loss(p):
        return jnp.mean(model.apply(p, xyz1, xyz2, num_iters=2) ** 2)

    g = jax.grad(loss)(params)
    flat = jax.tree_util.tree_leaves_with_path(g)
    for k, v in flat:
        key = jax.tree_util.keystr(k)
        mx = np.abs(np.asarray(v)).max()
        if "backbone" in key:
            assert mx == 0.0, f"backbone param {key} got gradient"
    nonzero = [
        jax.tree_util.keystr(k) for k, v in flat if np.abs(np.asarray(v)).max() > 0
    ]
    assert any("ref_conv" in k for k in nonzero)
    assert any("fc" in k for k in nonzero)


@pytest.mark.slow
def test_remat_matches_baseline():
    xyz1, xyz2 = _clouds(4)
    base = PVRaft(CFG)
    remat = PVRaft(ModelConfig(**{**CFG.__dict__, "remat": True}))
    params = base.init(jax.random.key(0), xyz1, xyz2, 2)
    f1, _ = base.apply(params, xyz1, xyz2, num_iters=2)
    f2, _ = remat.apply(params, xyz1, xyz2, num_iters=2)
    np.testing.assert_allclose(np.asarray(f1), np.asarray(f2), atol=1e-5)


@pytest.mark.slow
def test_bf16_forward_close_to_fp32():
    import dataclasses

    xyz1, xyz2 = _clouds(5)
    base = PVRaft(CFG)
    bf16 = PVRaft(dataclasses.replace(CFG, compute_dtype="bfloat16"))
    params = base.init(jax.random.key(0), xyz1, xyz2, 2)
    f32, _ = base.apply(params, xyz1, xyz2, num_iters=2)
    f16, _ = bf16.apply(params, xyz1, xyz2, num_iters=2)
    assert f16.dtype == jnp.float32  # flow deltas emitted in f32
    # bf16 matmuls: loose agreement with the fp32 path.
    err = np.abs(np.asarray(f16) - np.asarray(f32)).max()
    scale = np.abs(np.asarray(f32)).max()
    assert err < 0.1 * max(1.0, scale), (err, scale)
