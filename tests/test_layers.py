"""Layer tests: PReLU, GroupNorm parity with torch, SetConv shapes/perm-equivariance."""

import numpy as np
import jax
import jax.numpy as jnp

from pvraft_tpu.models.layers import PReLU, SetConv, group_norm
from pvraft_tpu.ops.geometry import build_graph


def test_prelu_matches_definition():
    x = jnp.asarray([-2.0, -0.5, 0.0, 1.5])
    mod = PReLU()
    params = mod.init(jax.random.key(0), x)
    y = np.asarray(mod.apply(params, x))
    np.testing.assert_allclose(y, [-0.5, -0.125, 0.0, 1.5], atol=1e-6)


def test_group_norm_matches_torch():
    import torch
    import flax.linen as nn

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 11, 5, 16)).astype(np.float32)  # (B, N, k, C)

    class M(nn.Module):
        @nn.compact
        def __call__(self, x):
            return group_norm(x, "gn")

    m = M()
    params = m.init(jax.random.key(0), jnp.asarray(x))
    got = np.asarray(m.apply(params, jnp.asarray(x)))

    # torch layout (B, C, k, N); GroupNorm(8, 16) default affine=1/0 matches init.
    tx = torch.from_numpy(x).permute(0, 3, 2, 1)
    tg = torch.nn.GroupNorm(8, 16)
    want = tg(tx).detach().numpy().transpose(0, 3, 2, 1)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_setconv_shapes_and_grads():
    rng = np.random.default_rng(1)
    pc = jnp.asarray(rng.normal(size=(2, 32, 3)).astype(np.float32))
    graph = build_graph(pc, 8)
    mod = SetConv(32)
    params = mod.init(jax.random.key(0), pc, graph)
    out = mod.apply(params, pc, graph)
    assert out.shape == (2, 32, 32)

    def loss(p):
        return jnp.sum(mod.apply(p, pc, graph) ** 2)

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g)
    assert all(np.all(np.isfinite(np.asarray(l))) for l in leaves)
    assert any(np.abs(np.asarray(l)).max() > 0 for l in leaves)


def test_setconv_mid_width_rule():
    """gconv.py:21-24: mid = (in+out)//2 if in even else out//2."""
    rng = np.random.default_rng(2)
    pc = jnp.asarray(rng.normal(size=(1, 16, 3)).astype(np.float32))
    graph = build_graph(pc, 4)
    mod = SetConv(32)
    params = mod.init(jax.random.key(0), pc, graph)
    # input 3 channels (odd) -> mid = 16
    assert params["params"]["fc1"]["kernel"].shape == (6, 16)

    feats = jnp.asarray(rng.normal(size=(1, 16, 32)).astype(np.float32))
    mod2 = SetConv(64)
    params2 = mod2.init(jax.random.key(0), feats, graph)
    # input 32 (even) -> mid = (64+32)//2 = 48
    assert params2["params"]["fc1"]["kernel"].shape == (35, 48)
