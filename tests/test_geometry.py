"""Unit tests for pvraft_tpu.ops.geometry against tiny numpy oracles."""

import numpy as np
import jax.numpy as jnp

from pvraft_tpu.ops.geometry import (
    build_graph,
    gather_neighbors,
    knn_indices,
    pairwise_sqdist,
)


def _np_sqdist(a, b):
    return ((a[:, :, None, :] - b[:, None, :, :]) ** 2).sum(-1)


def test_pairwise_sqdist_matches_bruteforce():
    rng = np.random.default_rng(0)
    a = rng.normal(size=(2, 7, 3)).astype(np.float32)
    b = rng.normal(size=(2, 5, 3)).astype(np.float32)
    got = np.asarray(pairwise_sqdist(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got, _np_sqdist(a, b), atol=1e-4)


def test_knn_indices_matches_argsort():
    rng = np.random.default_rng(1)
    q = rng.normal(size=(1, 9, 3)).astype(np.float32)
    p = rng.normal(size=(1, 12, 3)).astype(np.float32)
    idx = np.asarray(knn_indices(jnp.asarray(q), jnp.asarray(p), 4))
    want = np.argsort(_np_sqdist(q, p), axis=-1)[..., :4]
    # Compare distance sets (tie order may differ between backends).
    d = _np_sqdist(q, p)
    got_d = np.take_along_axis(d, idx, -1)
    want_d = np.take_along_axis(d, want, -1)
    np.testing.assert_allclose(np.sort(got_d, -1), np.sort(want_d, -1), atol=1e-5)


def test_self_is_first_neighbor():
    rng = np.random.default_rng(2)
    pc = rng.normal(size=(2, 16, 3)).astype(np.float32)
    g = build_graph(jnp.asarray(pc), 4)
    np.testing.assert_array_equal(
        np.asarray(g.neighbors)[..., 0], np.tile(np.arange(16), (2, 1))
    )
    # Self edge has zero relative position.
    np.testing.assert_allclose(np.asarray(g.rel_pos)[..., 0, :], 0.0, atol=1e-6)


def test_gather_neighbors():
    feats = jnp.arange(2 * 5 * 3, dtype=jnp.float32).reshape(2, 5, 3)
    idx = jnp.asarray([[[0, 4], [1, 1]], [[2, 3], [0, 0]]], dtype=jnp.int32)
    out = np.asarray(gather_neighbors(feats, idx))
    assert out.shape == (2, 2, 2, 3)
    np.testing.assert_array_equal(out[0, 0, 1], np.asarray(feats)[0, 4])
    np.testing.assert_array_equal(out[1, 0, 0], np.asarray(feats)[1, 2])


def test_graph_rel_pos_consistency():
    rng = np.random.default_rng(3)
    pc = rng.normal(size=(1, 10, 3)).astype(np.float32)
    g = build_graph(jnp.asarray(pc), 3)
    nb = np.asarray(g.neighbors)
    rel = np.asarray(g.rel_pos)
    for i in range(10):
        for kk in range(3):
            np.testing.assert_allclose(
                rel[0, i, kk], pc[0, nb[0, i, kk]] - pc[0, i], atol=1e-6
            )


def test_chunked_knn_matches_full():
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(2, 24, 3)).astype(np.float32))
    p = jnp.asarray(rng.normal(size=(2, 48, 3)).astype(np.float32))
    full = np.asarray(knn_indices(q, p, 6))
    chunked = np.asarray(knn_indices(q, p, 6, chunk=16))
    # Same neighbor sets and (no ties in random data) same order.
    np.testing.assert_array_equal(full, chunked)


def test_chunked_graph_in_model():
    import jax
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models.raft import PVRaft

    rng = np.random.default_rng(8)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8)
    cfgc = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                       graph_chunk=16, corr_chunk=16)
    params = PVRaft(cfg).init(jax.random.key(0), xyz1, xyz2, 2)
    f1, _ = PVRaft(cfg).apply(params, xyz1, xyz2, num_iters=2)
    f2, _ = PVRaft(cfgc).apply(params, xyz1, xyz2, num_iters=2)
    np.testing.assert_allclose(np.asarray(f1), np.asarray(f2), atol=1e-4)


def test_chunked_knn_randomized_shapes():
    """Streaming kNN sweep over random (Nq, Np, k, chunk): the chunked
    merge must reproduce the dense result exactly (continuous random
    coordinates make ties improbable, so order must match too)."""
    rng = np.random.default_rng(321)
    for trial in range(8):
        nq = int(rng.integers(4, 40))
        npts = int(rng.choice([32, 48, 64, 96]))
        k = int(rng.integers(2, 13))
        # c < npts keeps trials genuinely chunked; chunk < k (the
        # sentinel-merge edge) is supported and deliberately included.
        divisors = [c for c in (4, 8, 16, 24, 32, 48)
                    if npts % c == 0 and c < npts]
        if not divisors:
            continue
        chunk = int(rng.choice(divisors))
        q = jnp.asarray(rng.normal(size=(1, nq, 3)).astype(np.float32))
        p = jnp.asarray(rng.normal(size=(1, npts, 3)).astype(np.float32))
        full = np.asarray(knn_indices(q, p, k))
        chunked = np.asarray(knn_indices(q, p, k, chunk=chunk))
        np.testing.assert_array_equal(
            full, chunked,
            err_msg=f"trial {trial}: nq={nq} np={npts} k={k} chunk={chunk}",
        )


def test_approx_knn_recall_and_config():
    """approx=True returns valid indices with high recall vs exact; the
    config layer rejects the combinations the op cannot honor."""
    import pytest

    rng = np.random.default_rng(7)
    pc = rng.uniform(-1, 1, (2, 256, 3)).astype(np.float32)
    k = 16
    exact = np.asarray(knn_indices(jnp.asarray(pc), jnp.asarray(pc), k))
    approx = np.asarray(
        knn_indices(jnp.asarray(pc), jnp.asarray(pc), k, approx=True)
    )
    assert approx.shape == exact.shape and approx.dtype == np.int32
    assert approx.min() >= 0 and approx.max() < 256
    recall = np.mean([
        len(set(approx[b, i]) & set(exact[b, i])) / k
        for b in range(2) for i in range(256)
    ])
    assert recall >= 0.9, recall

    g = build_graph(jnp.asarray(pc), k, approx=True)
    assert g.neighbors.shape == (2, 256, k)

    with pytest.raises(ValueError):
        knn_indices(jnp.asarray(pc), jnp.asarray(pc), k, chunk=64,
                    approx=True)

    from pvraft_tpu.config import ModelConfig

    with pytest.raises(ValueError):
        ModelConfig(approx_knn=True, graph_chunk=64)
    with pytest.raises(ValueError):
        ModelConfig(approx_knn=True, seq_shard=True)
    ModelConfig(approx_knn=True)  # ok


def test_approx_knn_through_model():
    """cfg.approx_knn must reach the encoder graph build and produce a
    finite forward."""
    import jax

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models import PVRaft

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4, approx_knn=True)
    model = PVRaft(cfg)
    rng = np.random.default_rng(3)
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    params = model.init(jax.random.key(0), pc1, pc2, 2)
    flows, _ = model.apply(params, pc1, pc2, 2)
    assert np.all(np.isfinite(np.asarray(flows)))
