"""Cost-calibration plane (ISSUE 14): CostSurface lookups, the serve
pricing model's zero-residue contract, the cost_calibration event
schema, the capacity planner (pvraft_capacity/v1) and the calibration
evidence validator (pvraft_cost_calibration/v1) — red/green for every
validator, determinism for the committed plan, and the platform-honesty
rule (comparable=true off-TPU is unrepresentable) at every layer."""

import copy
import json
import os

import pytest

from pvraft_tpu.obs.calibration import (
    CALIBRATION_SCHEMA,
    validate_calibration,
    validate_calibration_file,
)
from pvraft_tpu.obs.capacity import (
    CAPACITY_SCHEMA,
    build_capacity_report,
    chips_needed,
    validate_capacity,
    validate_capacity_file,
)
from pvraft_tpu.obs.events import validate_event
from pvraft_tpu.obs.loading import load_json_artifact
from pvraft_tpu.programs.costs import (
    CostSurface,
    hardware_utilization,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rec(name, flops=1e9, bytes_=2e9, opt=None, target="v5e:2x2x1"):
    rec = {"name": name, "target": target, "tags": [], "ok": True,
           "flops": flops, "bytes_accessed": bytes_,
           "memory": {"live_bytes_estimate": 1024,
                      "fits_16GiB_hbm": True}}
    if opt is not None:
        rec["optimal_seconds"] = opt
    return rec


def _surface(records):
    return CostSurface({"schema": "pvraft_costs/v1",
                        "programs": records})


SERVE_RECORDS = [
    _rec("serve_predict_bf16_pallas_b2048_bs1", flops=4e9, opt=0.01),
    _rec("serve_predict_bf16_pallas_b8192_bs4", flops=6.4e10, opt=0.08),
    _rec("serve_predict_fp32_b2048_bs1", flops=4e9, opt=0.02),
    _rec("flagship_train_step_fp32_remat", flops=2.7e11, bytes_=2.9e11,
         opt=-100.0),  # XLA's nonsense negative optimal (real artifact)
    _rec("engine.train_step", flops=1e9, target="host"),
]


# ------------------------------------------------------------ CostSurface --


def test_surface_lookup_and_basis():
    s = _surface(SERVE_RECORDS)
    est = s.lookup("serve_predict_bf16_pallas_b2048_bs1")
    assert est.device_seconds == 0.01 and est.basis == "xla_optimal"
    assert est.comparable is True
    # Negative optimal_seconds never propagates: roofline fallback.
    train = s.lookup("flagship_train_step_fp32_remat")
    assert train.basis == "roofline" and train.device_seconds > 0
    # Host-target records predict but are never comparable.
    host = s.lookup("engine.train_step")
    assert host.comparable is False
    assert s.lookup("nonexistent") is None


def test_surface_serve_lookup_exact_and_extrapolated():
    s = _surface(SERVE_RECORDS)
    exact = s.lookup_serve(2048, 1, "bfloat16")
    assert exact.name == "serve_predict_bf16_pallas_b2048_bs1"
    assert exact.extrapolated is False and exact.scale == 1.0
    assert s.lookup_serve(4096, 4, "bfloat16") is None
    est = s.estimate_serve(4096, 4, "bfloat16")
    assert est.extrapolated is True
    assert est.reference == "serve_predict_bf16_pallas_b8192_bs4"
    assert est.scale == pytest.approx(0.5)
    assert est.device_seconds == pytest.approx(0.04)
    # dtype routing: fp32 variant resolves separately.
    assert s.lookup_serve(2048, 1, "float32").name == \
        "serve_predict_fp32_b2048_bs1"
    assert s.estimate_serve(2048, 1, "bfloat16").device_seconds == 0.01


def test_surface_seconds_per_request_exact_coverage_only():
    s = _surface(SERVE_RECORDS)
    assert s.serve_seconds_per_request(8192, "bfloat16") == \
        pytest.approx(0.02)   # 0.08 / bs 4
    assert s.serve_seconds_per_request(4096, "bfloat16") is None


def test_surface_train_step_and_utilization():
    s = _surface(SERVE_RECORDS)
    assert s.lookup_train_step("float32").name == \
        "flagship_train_step_fp32_remat"
    assert s.lookup_train_step("bfloat16") is None
    util = hardware_utilization(1e12, 0.1, "bfloat16")
    assert util == pytest.approx(1e12 / (0.1 * 197e12))
    assert hardware_utilization(0.0, 0.1, "bfloat16") is None


def test_surface_rejects_wrong_schema_and_loads_committed():
    with pytest.raises(ValueError):
        CostSurface({"schema": "nope"})
    s = CostSurface.load()          # the committed inventory
    assert len(s) > 40
    assert s.serve_coverage("bfloat16") == [(2048, 1), (8192, 4)]
    assert s.lookup_train_step("bfloat16") is not None


# ----------------------------------------------------- cost_calibration --


def _cal_event(**over):
    rec = {"schema": "pvraft_events/v1", "type": "cost_calibration",
           "time": 1.0, "seq": 0, "bucket": 2048, "batch": 1,
           "dtype": "bfloat16", "predicted_s": 0.01, "measured_s": 0.02,
           "platform": "cpu", "comparable": False}
    rec.update(over)
    return rec


def test_cost_calibration_event_green_and_red():
    assert validate_event(_cal_event()) == []
    assert validate_event(_cal_event(platform="tpu", comparable=True,
                                     basis="roofline",
                                     extrapolated=True, replica=1)) == []
    # The platform-honesty rule: comparable=true off-TPU is invalid.
    assert validate_event(_cal_event(comparable=True))
    assert validate_event(_cal_event(comparable="yes"))
    assert validate_event(_cal_event(predicted_s=-1.0))
    assert validate_event(_cal_event(basis="guess"))
    assert validate_event(_cal_event(dtype=""))
    bad = _cal_event()
    del bad["platform"]
    assert validate_event(bad)


# ------------------------------------------------------- capacity plan --


def _load_doc():
    return {"schema": "pvraft_serve_load/v1",
            "config": {"platform": "cpu"},
            "request_points": {
                "edges": [1024, 2048, 8192],
                "counts": [50, 100, 50, 0]}}


def _slo_doc():
    return {"schema": "pvraft_slo/v1", "slo": {"p99_ms": 2000.0},
            "max_qps_under_slo": 30.0}


def test_capacity_build_validates_and_is_deterministic():
    s = _surface(SERVE_RECORDS)
    kwargs = dict(buckets=(2048, 8192), batch_sizes=(1, 4),
                  dtype="bfloat16", qps_ladder=(10.0, 100.0),
                  inputs={"costs": "c", "load": "l", "slo": "s"})
    a = build_capacity_report(s, _load_doc(), _slo_doc(), **kwargs)
    b = build_capacity_report(s, _load_doc(), _slo_doc(), **kwargs)
    assert a == b                      # pure function of inputs
    assert validate_capacity(a) == []
    assert a["measured_evidence"]["comparable"] is False
    # Mix: 150 requests land in bucket 2048 (0.01 s/request), 50 in
    # 8192 (0.08 at bs 4 -> 0.02 s/request).
    by_bucket = {r["bucket"]: r for r in a["per_bucket"]}
    assert by_bucket[2048]["requests"] == 150
    assert by_bucket[8192]["requests"] == 50
    assert by_bucket[8192]["seconds_per_request"] == pytest.approx(0.02)
    demand = {r["qps"]: r for r in a["demand"]}
    mean = a["traffic"]["mean_device_seconds_per_request"]
    assert demand[100.0]["device_seconds_per_sec"] == \
        pytest.approx(100.0 * mean, rel=1e-5)
    assert demand[100.0]["chips_needed"] == chips_needed(
        demand[100.0]["device_seconds_per_sec"], 0.7)


def test_capacity_validator_red():
    s = _surface(SERVE_RECORDS)
    good = build_capacity_report(
        s, _load_doc(), _slo_doc(), buckets=(2048, 8192),
        batch_sizes=(1, 4), dtype="bfloat16")
    assert validate_capacity(good) == []
    # Hand-edited chips-needed contradicting its own demand row.
    bad = copy.deepcopy(good)
    bad["demand"][0]["chips_needed"] += 5
    assert any("chips_needed" in p for p in validate_capacity(bad))
    # comparable=true on non-TPU evidence.
    bad = copy.deepcopy(good)
    bad["measured_evidence"]["comparable"] = True
    assert any("comparable" in p for p in validate_capacity(bad))
    # Traffic fractions exceeding 1.
    bad = copy.deepcopy(good)
    bad["per_bucket"][0]["traffic_fraction"] = 0.9
    bad["per_bucket"][-1]["traffic_fraction"] = 0.9
    assert any("fractions" in p for p in validate_capacity(bad))
    assert validate_capacity([]) and validate_capacity({"schema": "x"})


def test_committed_capacity_artifact_checks():
    """The committed plan validates AND regenerates byte-identically
    from its recorded inputs (the lint.sh stage, in test form)."""
    path = os.path.join(REPO, "artifacts", "capacity_report.json")
    assert validate_capacity_file(path) == []
    committed, problems = load_json_artifact(path)
    assert problems == []
    surface = CostSurface.load()
    inputs = committed["inputs"]
    load_doc, _ = load_json_artifact(os.path.join(REPO, inputs["load"]))
    slo_doc, _ = load_json_artifact(os.path.join(REPO, inputs["slo"]))
    from pvraft_tpu.programs import geometries as g

    regenerated = build_capacity_report(
        surface, load_doc, slo_doc,
        buckets=g.SERVE_DEFAULT_BUCKETS,
        batch_sizes=g.SERVE_DEFAULT_BATCH_SIZES,
        dtype=committed["dtype"],
        qps_ladder=tuple(r["qps"] for r in committed["demand"]),
        utilization_ceiling=committed["utilization_ceiling"],
        inputs=inputs)
    assert regenerated == committed


# -------------------------------------------------- calibration evidence --


def _cal_doc(**over):
    doc = {
        "schema": CALIBRATION_SCHEMA,
        "surface": "artifacts/programs_costs.json",
        "platform": "cpu",
        "dtype": "float32",
        "config": {},
        "identity": {"snapshots": 40, "violations": 0},
        "records": [{"bucket": 128, "batch": 1, "dtype": "float32",
                     "n": 30, "predicted_s": 0.01, "measured_s": 0.02,
                     "ratio": 2.0, "comparable": False}],
    }
    doc.update(over)
    return doc


def test_calibration_validator_green_and_red():
    assert validate_calibration(_cal_doc()) == []
    # The identity must have held at every polled snapshot.
    assert any("violations" in p for p in validate_calibration(
        _cal_doc(identity={"snapshots": 40, "violations": 1})))
    assert any("snapshots" in p for p in validate_calibration(
        _cal_doc(identity={"snapshots": 0, "violations": 0})))
    # A forged ratio is recomputed, not trusted.
    forged = _cal_doc()
    forged["records"][0]["ratio"] = 0.5
    assert any("ratio" in p for p in validate_calibration(forged))
    # comparable=true off-TPU is unrepresentable.
    dishonest = _cal_doc()
    dishonest["records"][0]["comparable"] = True
    assert any("comparable" in p for p in validate_calibration(dishonest))
    assert validate_calibration(_cal_doc(records=[]))
    assert validate_calibration({"schema": "x"})


def test_committed_calibration_artifact():
    """The committed evidence run validates, held the identity at every
    snapshot, and (being CPU-tier) claims nothing enforceable."""
    path = os.path.join(REPO, "artifacts", "serve_calibration.json")
    assert validate_calibration_file(path) == []
    doc, _ = load_json_artifact(path)
    assert doc["identity"]["violations"] == 0
    assert doc["identity"]["snapshots"] > 0
    assert doc["records"]
    assert all(r["comparable"] is False for r in doc["records"])
    # The sibling event stream carries the per-dispatch ledger.
    from pvraft_tpu.obs.events import validate_events_file

    events = os.path.join(REPO, "artifacts",
                          "serve_calibration.events.jsonl")
    assert validate_events_file(events) == []
    recs = [json.loads(line) for line in open(events, encoding="utf-8")]
    cal = [r for r in recs if r["type"] == "cost_calibration"]
    assert cal and all(r["comparable"] is False for r in cal)
    assert sum(1 for r in cal) == sum(r["n"] for r in doc["records"])


# ----------------------------------------------- serve residue + advisor --


def test_surface_disabled_service_has_zero_residue(tmp_path):
    """build_service without a cost surface: costing is None on the
    batcher (one attribute check per dispatch), the metrics store stays
    disarmed, /healthz reports cost: null, and the exposition carries
    no cost family."""
    from types import SimpleNamespace

    import numpy as np

    from pvraft_tpu.serve import build_service
    from pvraft_tpu.serve.engine import RequestError

    class _Replica:
        def __init__(self, i):
            self.index = i
            self.device_id = i

        def predict_batch(self, requests, bucket):
            return [np.zeros((p1.shape[0], 3), np.float32)
                    for p1, _ in requests]

    class _Engine:
        def __init__(self):
            self.cfg = SimpleNamespace(
                buckets=(32,), batch_sizes=(1, 2), min_points=4,
                coord_limit=100.0, dtype="float32")
            self.replicas = [_Replica(0)]

        def validate_request(self, pc1, pc2):
            if max(pc1.shape[0], pc2.shape[0]) > 32:
                raise RequestError("too_large", "too large")
            return 32

        def batch_size_for(self, n):
            return 1 if n <= 1 else 2

        def compile_report(self):
            return []

    server = build_service(_Engine(), trace_sample_every=0)
    server.start()
    try:
        assert server.batcher.costing is None
        metrics = server.batcher.metrics
        assert metrics.cost_armed is False
        assert metrics.cost_snapshot() is None
        assert "pvraft_serve_predicted_device_seconds_total" \
            not in metrics.prometheus()
    finally:
        server.shutdown(drain=True)


def test_replica_utilization_covers_full_window():
    """The rolling utilization divides by the full window, so the
    interval history must always SPAN the window: a replica busy for
    the whole trailing window reads ~1.0 no matter how many small
    dispatches filled it (age-pruned history, not a fixed-size deque
    that could silently cover less than the window)."""
    from pvraft_tpu.serve.metrics import (
        UTILIZATION_WINDOW_S,
        ServeMetrics,
    )

    m = ServeMetrics(buckets=(32,))
    m.arm_cost()
    now = 1000.0
    n = 400
    step = UTILIZATION_WINDOW_S / n
    for i in range(n):   # n back-to-back dispatches tile the window
        t0 = now - UTILIZATION_WINDOW_S + i * step
        m.record_cost(bucket=32, batch=1, dtype="float32", replica=0,
                      predicted_s=0.01, measured_s=step, t_start=t0,
                      t_end=t0 + step, comparable=False,
                      extrapolated=False)
    snap = m.cost_snapshot(now=now)
    assert snap["utilization"]["0"] == pytest.approx(1.0, abs=0.02)
    # A full window later the same history reads idle.
    assert m.cost_snapshot(
        now=now + 2 * UTILIZATION_WINDOW_S)["utilization"]["0"] == 0.0


def test_advisor_device_seconds_objective_and_fallback():
    from pvraft_tpu.serve.advisor import build_advisor_report

    s = _surface(SERVE_RECORDS)
    edges = [2048.0, 8192.0]
    counts = [100, 50, 0]
    # Full exact coverage -> seconds objective.
    rep = build_advisor_report(edges, counts, (2048, 8192),
                               cost_surface=s, dtype="bfloat16")
    assert rep["objective"]["unit"] == "device_seconds"
    assert "device_seconds_per_request" in rep["proposed"]
    assert "device_seconds_per_request" in rep["current"]
    assert rep["improvement"]["population"] == \
        "traffic served by the current table"
    # Seconds objective actually changes the verdict points can't see:
    # per-request seconds at 8192 (0.02) is 2x 2048's (0.01), while
    # points says 4x — the DP trades them differently under tight k.
    assert rep["current"]["device_seconds_per_request"] == \
        pytest.approx((100 * 0.01 + 50 * 0.02) / 150, abs=1e-6)
    # Any uncovered candidate -> loud fallback to points.
    rep2 = build_advisor_report([1024.0, 8192.0], counts, (2048, 8192),
                                cost_surface=s, dtype="bfloat16")
    assert rep2["objective"]["unit"] == "device_points"
    assert "1024" in rep2["objective"]["note"]
    assert "points_per_request" in rep2["proposed"]
    # No surface at all -> points, no note.
    rep3 = build_advisor_report(edges, counts, (2048, 8192))
    assert rep3["objective"] == {"unit": "device_points"}


def test_shared_artifact_loader_contracts(tmp_path):
    good = tmp_path / "good.json"
    good.write_text('{"a": 1}\n')
    assert load_json_artifact(str(good)) == ({"a": 1}, [])
    doc, problems = load_json_artifact(str(tmp_path / "missing.json"))
    assert doc is None and "unreadable" in problems[0]
    pretty = tmp_path / "pretty.json"
    pretty.write_text('{\n  "a": 1\n}\n')
    assert load_json_artifact(str(pretty)) == ({"a": 1}, [])  # whole-file
    doc, problems = load_json_artifact(str(pretty), one_line=True)
    assert doc is None and "exactly one JSON line" in problems[0]
    two = tmp_path / "two.json"
    two.write_text('{"a": 1}\n{"b": 2}\n')
    doc, problems = load_json_artifact(str(two), one_line=True)
    assert doc is None and "got 2" in problems[0]
    # bench.load_bench_file rides THIS loader (the dedupe satellite).
    from pvraft_tpu.obs.bench import load_bench_file

    assert load_bench_file(str(two))[0] is None


def test_obs_cli_validates_capacity_and_calibration(tmp_path, capsys):
    from pvraft_tpu.obs.__main__ import main

    cap = os.path.join(REPO, "artifacts", "capacity_report.json")
    cal = os.path.join(REPO, "artifacts", "serve_calibration.json")
    assert main(["validate-capacity", cap]) == 0
    assert main(["validate-calibration", cal]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert main(["validate-capacity", str(bad)]) == 1
    assert main(["validate-calibration", str(bad)]) == 1