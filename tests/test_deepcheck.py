"""deepcheck (GJ rules): red/green per rule, golden report, clean pass
over the real audit corpus, and the suppression/ring regressions."""

import os
import sys

import jax
import pytest

FIXDIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fixtures")
if FIXDIR not in sys.path:
    sys.path.insert(0, FIXDIR)

import deepcheck_corpus as corpus  # noqa: E402

from pvraft_tpu.analysis.audit import AuditEntry  # noqa: E402
from pvraft_tpu.analysis.jaxpr import (  # noqa: E402
    all_jaxpr_rules,
    format_report,
    run_deepcheck,
    walk,
)
from pvraft_tpu.analysis.jaxpr.rules import (  # noqa: E402
    EntryContext,
    UnboundCollectiveAxis,
)

SDS = jax.ShapeDtypeStruct


def make_entry(name, thunk, **kw):
    code = thunk.__code__
    return AuditEntry(name=name, thunk=thunk, path=code.co_filename,
                      line=code.co_firstlineno, **kw)


def run(*entries):
    return run_deepcheck(entries={e.name: e for e in entries})


def rule_ids(report):
    return [d.rule_id for d in report.diagnostics]


def red_corpus_entries():
    """The full red corpus, in the shape the golden fixture pins."""
    return [
        make_entry("corpus.clean", corpus.clean),
        make_entry("corpus.dead_psum", corpus.dead_psum),
        make_entry("corpus.fp[nocoll]", corpus.fp_without_collective,
                   spmd_group="fp-pair"),
        make_entry("corpus.fp[psum]", corpus.fp_with_psum,
                   spmd_group="fp-pair"),
        make_entry("corpus.inert_bf16_lever", corpus.inert_bf16_lever,
                   precision="bf16_grads"),
        make_entry("corpus.last_hop_ring", corpus.last_hop_ring),
        make_entry("corpus.nondeterministic_trace",
                   corpus.nondeterministic_trace),
        make_entry("corpus.stray_bf16", corpus.stray_bf16),
        make_entry("corpus.unaliasable_donation",
                   corpus.unaliasable_donation),
        make_entry("corpus.undonated_state", corpus.undonated_state),
        make_entry("corpus.weak_type_sensitive", corpus.weak_type_sensitive,
                   precision="any"),
    ]


# --- rule table -----------------------------------------------------------

def test_gj_rule_table_complete():
    rules = all_jaxpr_rules()
    assert [r.id for r in rules] == [f"GJ00{i}" for i in range(1, 8)]
    for r in rules:
        assert r.title, r.id
        assert r.__doc__ and r.__doc__.strip(), r.id


# --- GJ001: positive detection needs an ambient axis_env (a program
# with a truly unbound collective cannot trace at all) ---------------------

def _direct_ctx(fn, in_sds, axis_env=None, precision="any"):
    closed = jax.make_jaxpr(fn, axis_env=axis_env)(*in_sds)
    return EntryContext(
        name="direct", precision=precision, spmd_group=None,
        anchor_path="<direct>", anchor_line=1, fn=fn, args=in_sds,
        closed=closed, sites=walk(closed), thunk=None,
    )


def test_gj001_red_ambient_axis():
    from jax import lax

    def fn(x):
        return lax.psum(x, "ring")

    ectx = _direct_ctx(fn, (SDS((4,), "float32"),),
                       axis_env=[("ring", 2)])
    diags = list(UnboundCollectiveAxis().check(ectx))
    assert [d.rule_id for d in diags] == ["GJ001"]
    assert "'ring'" in diags[0].message


def test_gj001_green_shard_map_bound():
    # Bound by shard_map: the same psum must NOT fire (corpus member).
    rep = run(make_entry("c.fp", corpus.fp_with_psum))
    assert "GJ001" not in rule_ids(rep)


# --- GJ002 ----------------------------------------------------------------

def test_gj002_red_dead_psum():
    rep = run(make_entry("c.dead", corpus.dead_psum))
    assert rule_ids(rep) == ["GJ002"]
    assert "dead `psum`" in rep.diagnostics[0].message


def test_gj002_red_last_hop_carry():
    rep = run(make_entry("c.ring", corpus.last_hop_ring))
    assert rule_ids(rep) == ["GJ002"]
    assert "final value is discarded" in rep.diagnostics[0].message


def test_gj002_green_live_collectives():
    rep = run(make_entry("c.fp", corpus.fp_with_psum),
              make_entry("c.clean", corpus.clean))
    assert rep.diagnostics == [] and not rep.failures


def test_gj002_green_ring_paths_two_devices():
    """The fixed ring fns at a real 2-shard seq axis: every hop's result
    is consumed (p-1 hops + peeled final fold), so GJ002 stays quiet."""
    from jax.sharding import PartitionSpec as P

    from pvraft_tpu.compat import shard_map
    from pvraft_tpu.ops.corr import CorrState
    from pvraft_tpu.parallel.mesh import make_mesh
    from pvraft_tpu.parallel.ring import ring_corr_init, ring_knn_indices

    mesh = make_mesh(n_data=1, n_seq=2)

    def corr_thunk():
        def fn(f1, f2, x2):
            return shard_map(
                lambda a, b, c: ring_corr_init(a, b, c, 4, "seq"),
                mesh=mesh,
                in_specs=(P(None, "seq", None),) * 3,
                out_specs=CorrState(corr=P(None, "seq", None),
                                    xyz=P(None, "seq", None, None)),
                check_vma=False,
            )(f1, f2, x2)

        return fn, (SDS((1, 8, 6), "float32"), SDS((1, 8, 6), "float32"),
                    SDS((1, 8, 3), "float32"))

    def knn_thunk():
        def fn(q, d):
            return shard_map(
                lambda a, b: ring_knn_indices(a, b, 4, "seq"),
                mesh=mesh,
                in_specs=(P(None, "seq", None),) * 2,
                out_specs=P(None, "seq", None),
                check_vma=False,
            )(q, d)

        return fn, (SDS((1, 8, 3), "float32"), SDS((1, 8, 3), "float32"))

    rep = run(make_entry("t.ring_corr", corr_thunk),
              make_entry("t.ring_knn", knn_thunk))
    assert rep.diagnostics == [] and not rep.failures
    # Both programs DO still communicate (p-1 = 1 hop per circulating
    # array) — quiet because the traffic is consumed, not absent.
    stats = {e.name: e.n_collectives for e in rep.entries}
    assert stats["t.ring_corr"] >= 2 and stats["t.ring_knn"] >= 1


# --- GJ003 ----------------------------------------------------------------

def test_gj003_red_fingerprint_drift():
    rep = run(make_entry("a", corpus.fp_with_psum, spmd_group="g"),
              make_entry("b", corpus.fp_without_collective, spmd_group="g"))
    assert rule_ids(rep) == ["GJ003"]


def test_gj003_green_matching_fingerprints():
    rep = run(make_entry("a", corpus.fp_with_psum, spmd_group="g"),
              make_entry("b", corpus.fp_with_psum, spmd_group="g"))
    assert rep.diagnostics == []


# --- GJ004 / GJ005 --------------------------------------------------------

def test_gj004_red_unaliasable_donation():
    rep = run(make_entry("c.don", corpus.unaliasable_donation))
    assert rule_ids(rep) == ["GJ004"]


def test_gj005_red_undonated_state():
    rep = run(make_entry("c.und", corpus.undonated_state))
    assert rule_ids(rep) == ["GJ005"]


def test_gj004_gj005_green_full_donation():
    def thunk():
        g = jax.jit(lambda x, y: (x + 1.0, y * 2.0), donate_argnums=(0, 1))

        def fn(x, y):
            return g(x, y)

        return fn, (SDS((8,), "float32"), SDS((8,), "float32"))

    rep = run(make_entry("t.ok", thunk))
    assert rep.diagnostics == []


# --- GJ006 ----------------------------------------------------------------

def test_gj006_red_stray_bf16():
    rep = run(make_entry("c.bf16", corpus.stray_bf16))
    assert rule_ids(rep) == ["GJ006"]


def test_gj006_red_inert_lever():
    rep = run(make_entry("c.inert", corpus.inert_bf16_lever,
                         precision="bf16_grads"))
    assert rule_ids(rep) == ["GJ006"]
    assert "inert" in rep.diagnostics[0].message


def test_gj006_green_declared_bf16_grads():
    import jax.numpy as jnp

    def thunk():
        # The maybe_cast_grads shape: truncate then restore, f32 out.
        def fn(g):
            return g.astype(jnp.bfloat16).astype(jnp.float32) * 2.0

        return fn, (SDS((8,), "float32"),)

    rep = run(make_entry("t.lever", thunk, precision="bf16_grads"))
    assert rep.diagnostics == []


# --- GJ007 ----------------------------------------------------------------

def test_gj007_red_nondeterministic_trace():
    rep = run(make_entry("c.nondet", corpus.nondeterministic_trace))
    assert rule_ids(rep) == ["GJ007"]


def test_gj007_red_weak_type_sensitive():
    rep = run(make_entry("c.weak", corpus.weak_type_sensitive,
                         precision="any"))
    assert rule_ids(rep) == ["GJ007"]
    assert "Python scalars" in rep.diagnostics[0].message


def test_gj007_green_deterministic():
    rep = run(make_entry("c.clean", corpus.clean))
    assert rep.diagnostics == []


# --- suppressions ---------------------------------------------------------

def test_gj_suppression_at_issuing_line(tmp_path):
    """A `# graftlint: disable=GJ002 -- reason` on the line that issued
    the primitive suppresses the jaxpr-level finding, exactly like an
    AST finding."""
    mod = tmp_path / "suppressed_corpus.py"
    mod.write_text(
        "import jax\n"
        "from jax import lax\n"
        "from jax.sharding import PartitionSpec as P\n"
        "from pvraft_tpu.compat import shard_map\n"
        "from pvraft_tpu.parallel.mesh import make_mesh\n"
        "def thunk():\n"
        "    mesh = make_mesh(n_data=1, n_seq=1)\n"
        "    def inner(x):\n"
        "        _ = lax.psum(x, 'seq')  "
        "# graftlint: disable=GJ002 -- deliberate, exercise comm path\n"
        "        return x * 2.0\n"
        "    def fn(x):\n"
        "        return shard_map(inner, mesh=mesh, in_specs=P(None, 'seq'),"
        " out_specs=P(None, 'seq'), check_vma=False)(x)\n"
        "    return fn, (jax.ShapeDtypeStruct((2, 4), 'float32'),)\n"
    )
    import importlib.util

    spec = importlib.util.spec_from_file_location("suppressed_corpus", mod)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    rep = run(make_entry("t.supp", m.thunk))
    assert rep.diagnostics == []
    assert rep.suppressed == 1


# --- golden report --------------------------------------------------------

def test_golden_report_fixture():
    rep = run_deepcheck(
        entries={e.name: e for e in red_corpus_entries()})
    got = format_report(rep) + "\n"
    with open(os.path.join(FIXDIR, "deepcheck_report.golden")) as fh:
        want = fh.read()
    assert got == want
    assert not rep.failures


# --- the real corpus ------------------------------------------------------

def test_real_ops_entries_clean():
    """Cheap real entries (ops + ring + scatter_free) deepcheck clean."""
    rep = run_deepcheck(entry_filter=(
        "ring.", "corr.", "geometry.", "scatter_free.", "voxel."))
    assert rep.diagnostics == []
    assert not rep.failures
    assert len(rep.entries) >= 12
    # With the test harness's 8 virtual devices the ring entries shard
    # seq over 2 devices, so the CORPUS programs really contain the ring
    # ppermutes — the collective rules must not be vacuously green over
    # the exact code they exist to guard (and lint.sh forces the same
    # device count for the gate).
    ring_coll = {e.name: e.n_collectives for e in rep.entries
                 if e.name.startswith("ring.")}
    assert jax.device_count() >= 2, "conftest must force 8 CPU devices"
    assert all(n >= 1 for n in ring_coll.values()), ring_coll


def test_real_optimized_train_step_clean():
    """The full optimized train step (scatter-free VJPs + dots remat +
    bf16 grads) traces clean: donation fully aliasable, the declared
    bf16_grads truncation present and restored, no retrace hazard."""
    rep = run_deepcheck(
        entry_filter=("engine.train_step[optimized_backward]",))
    assert rep.diagnostics == []
    assert not rep.failures
    [entry] = rep.entries
    assert entry.conversions.get(("float32", "bfloat16"), 0) > 0


@pytest.mark.slow
def test_full_audit_corpus_clean():
    """Every registered audit entry deepchecks clean — the lint.sh gate,
    as a test."""
    rep = run_deepcheck()
    assert rep.diagnostics == []
    assert not rep.failures


def test_gj002_red_dead_collective_behind_call_boundary():
    """A collective returned through a jit call but discarded by the
    caller is still dead — per-output liveness must see through the
    pjit boundary (a live sibling output must not shield it)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from pvraft_tpu.compat import shard_map
    from pvraft_tpu.parallel.mesh import make_mesh

    def thunk():
        mesh = make_mesh(n_data=1, n_seq=1)
        pair = jax.jit(lambda x: (x * 2.0, lax.psum(x, "seq")))

        def inner(x):
            useful, _unused = pair(x)
            return useful

        def fn(x):
            return shard_map(inner, mesh=mesh, in_specs=P(None, "seq"),
                             out_specs=P(None, "seq"), check_vma=False)(x)

        return fn, (SDS((2, 4), "float32"),)

    rep = run(make_entry("t.boundary", thunk))
    assert rule_ids(rep) == ["GJ002"]
    assert "dead `psum`" in rep.diagnostics[0].message


def test_gj_suppression_covers_decorated_anchor(tmp_path):
    """Entry-level GJ findings anchor at the thunk's first decorator
    line; a pragma anywhere in the decorated header (e.g. on the `def`
    line) must cover it — same header-region semantics as AST findings."""
    mod = tmp_path / "deco_corpus.py"
    mod.write_text(
        "import jax\n"
        "def deco(f):\n"
        "    return f\n"
        "@deco\n"
        "def thunk():  "
        "# graftlint: disable=GJ006 -- lever exercised in the slow tier\n"
        "    def fn(x):\n"
        "        return x * 2.0\n"
        "    return fn, (jax.ShapeDtypeStruct((4,), 'float32'),)\n"
    )
    import importlib.util

    spec = importlib.util.spec_from_file_location("deco_corpus", mod)
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    # bf16_grads intent with no cast -> GJ006 anchored at the @deco line.
    rep = run(make_entry("t.deco", m.thunk, precision="bf16_grads"))
    assert rep.diagnostics == []
    assert rep.suppressed == 1
