"""Preprocessing tests: format readers (round-trip against written files)
and back-projection geometry."""

import os
import struct

import numpy as np
import pytest

from pvraft_tpu.data.preprocess.io_formats import (
    read_flo,
    read_kitti_disparity,
    read_kitti_flow,
    read_pfm,
)
from pvraft_tpu.data.preprocess.flyingthings3d import backproject
from pvraft_tpu.data.preprocess.kitti import (
    backproject_kitti,
    disparity_to_depth,
    read_calib,
)


def _write_pfm(path, img, scale=-1.0):
    h, w = img.shape
    with open(path, "wb") as f:
        f.write(b"Pf\n")
        f.write(f"{w} {h}\n".encode())
        f.write(f"{scale}\n".encode())
        f.write(np.flipud(img).astype("<f4").tobytes())


def _write_flo(path, flow):
    h, w, _ = flow.shape
    with open(path, "wb") as f:
        f.write(struct.pack("<f", 202021.25))
        f.write(struct.pack("<i", w))
        f.write(struct.pack("<i", h))
        f.write(flow.astype("<f4").tobytes())


def test_pfm_roundtrip(tmp_path):
    img = np.random.default_rng(0).normal(size=(6, 9)).astype(np.float32)
    p = str(tmp_path / "x.pfm")
    _write_pfm(p, img)
    np.testing.assert_allclose(read_pfm(p), img, atol=1e-6)


def test_flo_roundtrip(tmp_path):
    flow = np.random.default_rng(1).normal(size=(5, 7, 2)).astype(np.float32)
    p = str(tmp_path / "x.flo")
    _write_flo(p, flow)
    np.testing.assert_allclose(read_flo(p), flow, atol=1e-6)


def test_kitti_png_decoding(tmp_path):
    import imageio.v2 as imageio

    disp = np.zeros((4, 6), np.uint16)
    disp[1, 2] = 256 * 10  # 10 px disparity
    p = str(tmp_path / "d.png")
    imageio.imwrite(p, disp)
    d, valid = read_kitti_disparity(p)
    assert d[1, 2] == pytest.approx(10.0)
    assert valid[1, 2] and not valid[0, 0]
    assert d[0, 0] == -1.0

    import cv2

    fl = np.zeros((4, 6, 3), np.uint16)
    fl[2, 3, 0] = 2**15 + 64 * 3  # u = +3 px
    fl[2, 3, 1] = 2**15 - 64 * 2  # v = -2 px
    fl[2, 3, 2] = 1
    pf = str(tmp_path / "f.png")
    cv2.imwrite(pf, fl[..., ::-1])  # cv2 writes BGR -> file stores RGB
    flow, vmask = read_kitti_flow(pf)
    assert flow[2, 3, 0] == pytest.approx(3.0)
    assert flow[2, 3, 1] == pytest.approx(-2.0)
    assert vmask[2, 3] and not vmask[0, 0]


def test_ft3d_backprojection_geometry():
    # A pixel at the principal point with disparity d: x=y=0, z=1050/d.
    disp = np.full((540, 960), 10.0, np.float32)
    pc = backproject(disp)
    cy, cx = 269, 479  # just left/above the principal point (cx=479.5)
    assert pc[cy, cx, 2] == pytest.approx(-(-1050.0) / 10.0)
    assert abs(pc[cy, cx, 0]) < 0.06  # 0.5 px / 10 disparity
    assert abs(pc[cy, cx, 1]) < 0.06
    # Flow advects the projected pixel.
    flow = np.zeros((540, 960, 2), np.float32)
    flow[..., 0] = 10.0
    pc2 = backproject(disp, flow)
    np.testing.assert_allclose(pc2[..., 0], pc[..., 0] - 1.0, atol=1e-5)


def test_kitti_calib_and_backprojection(tmp_path):
    calib = tmp_path / "000000.txt"
    f = 721.5377
    calib.write_text(
        "P_rect_02: "
        f"{f} 0.0 609.5593 44.85728 0.0 {f} 172.854 0.2163791 0.0 0.0 1.0 0.002745884\n"
    )
    p = read_calib(str(calib))
    assert p[0, 0] == pytest.approx(f)

    disp = np.full((8, 10), 2.0, np.float32)
    valid = np.ones((8, 10), bool)
    depth = disparity_to_depth(disp, valid, p[0, 0])
    assert depth[0, 0] == pytest.approx(f * 0.54 / 2.0, rel=1e-4)
    pc = backproject_kitti(depth, p)
    assert pc.shape == (8, 10, 3)
    assert np.all(pc[..., 2] == depth)


def test_ft3d_process_scene_end_to_end(tmp_path):
    """Synthesize a miniature raw FT3D tree and check the written scene."""
    import imageio.v2 as imageio
    from pvraft_tpu.data.preprocess.flyingthings3d import process_scene

    raw = tmp_path / "raw"
    h, w = 12, 16
    rng = np.random.default_rng(3)
    disp = rng.uniform(5, 20, (h, w)).astype(np.float32)
    dchange = rng.uniform(-1, 1, (h, w)).astype(np.float32)
    flow = rng.uniform(-2, 2, (h, w, 2)).astype(np.float32)
    occ = np.zeros((h, w), np.uint8)
    occ[0, :] = 255  # first row occluded

    base = raw / "train"
    for sub in [
        "disparity/left", "disparity_occlusions/left",
        "disparity_change/left/into_future", "flow/left/into_future",
        "flow_occlusions/left/into_future",
    ]:
        (base / sub).mkdir(parents=True)
    _write_pfm(str(base / "disparity/left/0000000.pfm"), disp)
    _write_pfm(str(base / "disparity_change/left/into_future/0000000.pfm"), dchange)
    _write_flo(str(base / "flow/left/into_future/0000000.flo"), flow)
    imageio.imwrite(str(base / "disparity_occlusions/left/0000000.png"), occ)
    imageio.imwrite(
        str(base / "flow_occlusions/left/into_future/0000000.png"),
        np.zeros((h, w), np.uint8),
    )

    out = tmp_path / "out"
    n1, n2 = process_scene(str(raw), str(out), "train", "0000000")
    assert n1 == n2 == (h - 1) * w  # occluded row dropped
    pc1 = np.load(out / "train" / "0000000" / "pc1.npy")
    pc2 = np.load(out / "train" / "0000000" / "pc2.npy")
    assert pc1.shape == pc2.shape == ((h - 1) * w, 3)
    assert np.all(np.isfinite(pc1)) and np.all(np.isfinite(pc2))
