"""graftlint engine + rules: every rule has a red/green fixture, the
suppression syntax works, and the shipped package lints clean."""

import os
import subprocess
import sys

import pytest

from pvraft_tpu.analysis.engine import all_rules, lint_paths, lint_source

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ids(src, path="x.py"):
    return [d.rule_id for d in lint_source(src, path=path)]


# --- one red fixture per rule (must trigger EXACTLY that rule) ------------

RED = {
    "GL001": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item()\n"
    ),
    "GL002": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n"
    ),
    "GL003": (
        "import jax.numpy as jnp\n"
        "OFFSETS = jnp.arange(27)\n"
    ),
    "GL004": "from jax import shard_map\n",
    "GL005": "import jax.numpy as jnp\n",  # linted under pvraft_tpu/data/
    "GL006": (
        "def f(x, cache={}):\n"
        "    return cache\n"
    ),
    "GL007": (
        "import jax\n"
        "def f(x):\n"
        "    jax.debug.print(f\"x={x}\")\n"
    ),
    "GL008": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    assert x > 0\n"
        "    return x\n"
    ),
    "GL009": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    jax.debug.print(\"x={x}\", x=x)\n"  # format string: GL007 quiet
        "    return x\n"
    ),
}

# The same code, corrected (not suppressed): the rule must NOT fire.
GREEN = {
    "GL001": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x * 2\n"
        "def host(y):\n"
        "    return y.item()\n"  # outside jit: fine
    ),
    "GL002": (
        "import jax\n"
        "from jax import lax\n"
        "@jax.jit\n"
        "def f(x, flag=None):\n"
        "    if flag is None:\n"          # static: is None
        "        return x\n"
        "    if x.shape[0] > 2:\n"        # static: shape metadata
        "        return x + 1\n"
        "    return lax.cond(True, lambda: x, lambda: -x)\n"
    ),
    "GL003": (
        "import numpy as np\n"
        "import jax.numpy as jnp\n"
        "OFFSETS = np.arange(27)\n"       # np at module scope: fine
        "def f():\n"
        "    return jnp.arange(27)\n"     # jnp inside a function: fine
    ),
    "GL004": "from pvraft_tpu.compat import shard_map\n",
    "GL005": "import numpy as np\n",
    "GL006": (
        "def f(x, cache=None):\n"
        "    cache = {} if cache is None else cache\n"
        "    return cache\n"
    ),
    "GL007": (
        "import jax\n"
        "def f(x):\n"
        "    jax.debug.print(\"x={x}\", x=x)\n"
    ),
    "GL008": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    assert x.shape[0] > 0\n"     # static shape assert: fine
        "    return x\n"
    ),
    "GL009": (
        "import jax\n"
        "DEBUG = False\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if DEBUG:\n"                 # static gate: trace-time dead
        "        jax.debug.print(\"x={x}\", x=x)\n"
        "    return x\n"
        "def host(x):\n"
        "    jax.debug.print(\"x={x}\", x=x)\n"  # outside jit: fine
    ),
}


@pytest.mark.parametrize("rule_id", sorted(RED))
def test_rule_fires_exactly_once(rule_id):
    path = "pvraft_tpu/data/x.py" if rule_id == "GL005" else "x.py"
    assert ids(RED[rule_id], path=path) == [rule_id]


@pytest.mark.parametrize("rule_id", sorted(GREEN))
def test_rule_green_fixture_clean(rule_id):
    path = "pvraft_tpu/data/x.py" if rule_id == "GL005" else "x.py"
    assert ids(GREEN[rule_id], path=path) == []


# --- suppressions ---------------------------------------------------------

def test_line_suppression_with_reason():
    src = "from jax import shard_map  # graftlint: disable=GL004 -- pinned\n"
    assert ids(src) == []


def test_line_suppression_multiple_ids():
    src = (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    assert x > 0  # graftlint: disable=GL008,GL001\n"
        "    return x\n"
    )
    assert ids(src) == []


def test_line_suppression_wrong_id_does_not_silence():
    src = "from jax import shard_map  # graftlint: disable=GL001\n"
    assert ids(src) == ["GL004"]


def test_disable_next_line_suppression():
    src = (
        "# graftlint: disable-next=GL004 -- no stable home for topologies\n"
        "from jax.experimental import topologies\n"
        "from jax.experimental import pallas\n"  # next line only
    )
    assert ids(src) == ["GL004"]


def test_file_suppression():
    src = (
        "# graftlint: disable-file=GL004 -- version pin escape hatch\n"
        "from jax import shard_map\n"
        "from jax.experimental import pallas\n"
    )
    assert ids(src) == []


def test_suppression_is_per_line():
    src = (
        "from jax import shard_map  # graftlint: disable=GL004\n"
        "from jax.experimental import pallas\n"  # not suppressed
    )
    assert ids(src) == ["GL004"]


def test_suppression_in_docstring_is_inert():
    """Documenting the suppression syntax must not disable rules: only
    real comment tokens count (the engine's own docstring shows
    `# graftlint: disable-file=...` as an example)."""
    src = (
        '"""Docs.\n'
        "\n"
        "    # graftlint: disable-file=GL004 -- just an example\n"
        '"""\n'
        "from jax import shard_map\n"
    )
    assert ids(src) == ["GL004"]


def test_path_scoping_is_invocation_independent(tmp_path):
    """GL004's compat exemption and GL005's data/ scoping key off the
    resolved path, not the spelling the linter was invoked with."""
    pkg = tmp_path / "pvraft_tpu"
    (pkg / "data").mkdir(parents=True)
    fragile = "from jax.experimental import pallas\n"
    (pkg / "compat.py").write_text(fragile)
    (tmp_path / "compat.py").write_text(fragile)  # NOT the shim
    (pkg / "data" / "aug.py").write_text("import jax.numpy as jnp\n")

    diags, _ = lint_paths([str(pkg / "compat.py")])
    assert diags == []  # the real shim is exempt
    diags, _ = lint_paths([str(tmp_path / "compat.py")])
    assert [d.rule_id for d in diags] == ["GL004"]
    diags, _ = lint_paths([str(pkg / "data" / "aug.py")])
    assert [d.rule_id for d in diags] == ["GL005"]


# --- registry / engine ----------------------------------------------------

def test_rule_table_complete():
    rules = all_rules()
    assert [r.id for r in rules] == sorted(RED)  # GL001..GL008, unique
    for r in rules:
        assert r.title
        assert r.__doc__ and r.__doc__.strip(), f"{r.id} needs a docstring"


def test_syntax_error_reported_not_raised():
    out = lint_source("def f(:\n", path="bad.py")
    assert [d.rule_id for d in out] == ["GL000"]


def test_compat_module_exempt_from_gl004():
    src = "from jax.experimental.shard_map import shard_map\n"
    assert ids(src, path="pvraft_tpu/compat.py") == []
    assert ids(src, path="pvraft_tpu/other.py") == ["GL004"]


# --- the gate: the shipped package lints clean ----------------------------

def test_shipped_package_lints_clean():
    diags, nfiles = lint_paths(
        [os.path.join(REPO, "pvraft_tpu"), os.path.join(REPO, "tests")]
    )
    assert nfiles > 50
    assert diags == [], "\n".join(d.format() for d in diags)


def test_cli_lint_exits_zero_on_package():
    proc = subprocess.run(
        [sys.executable, "-m", "pvraft_tpu.analysis", "lint",
         "pvraft_tpu/", "tests/"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_lint_exits_one_on_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("from jax import shard_map\n")
    proc = subprocess.run(
        [sys.executable, "-m", "pvraft_tpu.analysis", "lint", str(bad)],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 1
    assert "GL004" in proc.stdout


# --- engine edge cases: BOM / CRLF / decorated defs -----------------------

def test_bom_file_lints_instead_of_syntax_error(tmp_path):
    f = tmp_path / "bom.py"
    f.write_bytes("﻿import jax.numpy as jnp\nX = jnp.arange(3)\n"
                  .encode("utf-8"))
    diags, n = lint_paths([str(f)])
    assert n == 1
    assert [d.rule_id for d in diags] == ["GL003"]  # not GL000


def test_crlf_source_suppression_works(tmp_path):
    f = tmp_path / "crlf.py"
    f.write_bytes(
        b"import jax.numpy as jnp\r\n"
        b"X = jnp.arange(3)  # graftlint: disable=GL003 -- test\r\n"
    )
    diags, _ = lint_paths([str(f)])
    assert diags == []


def test_bom_crlf_file_level_suppression(tmp_path):
    f = tmp_path / "both.py"
    f.write_bytes(
        "﻿# graftlint: disable-file=GL003 -- test\r\n"
        "import jax.numpy as jnp\r\nX = jnp.arange(3)\r\n".encode("utf-8")
    )
    diags, _ = lint_paths([str(f)])
    assert diags == []


def test_disable_next_covers_decorated_def():
    # GL006 anchors at the `def` line, two below the pragma: the header
    # region (decorator..signature) counts as one suppression target.
    src = (
        "def deco(f):\n"
        "    return f\n"
        "# graftlint: disable-next=GL006 -- test\n"
        "@deco\n"
        "def f(x, cache={}):\n"
        "    return cache\n"
    )
    assert ids(src) == []


def test_disable_next_on_undecorated_def_still_exact():
    # Without a decorator the pragma still targets exactly the next line.
    src = (
        "# graftlint: disable-next=GL006 -- test\n"
        "def f(x, cache={}):\n"
        "    return cache\n"
        "def g(x, cache={}):\n"
        "    return cache\n"
    )
    assert ids(src) == ["GL006"]  # g still fires


# --- suppression-debt report (`lint --stats`) -----------------------------

def test_stats_reports_counts_and_passes_with_reasons(tmp_path, capsys):
    from pvraft_tpu.analysis.__main__ import main

    f = tmp_path / "a.py"
    f.write_text(
        "import jax.numpy as jnp\n"
        "X = jnp.arange(3)  # graftlint: disable=GL003 -- precomputed\n"
        "# graftlint: disable-file=GL004 -- pinned version\n"
    )
    rc = main(["lint", "--stats", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "GL003" in out and "GL004" in out


def test_stats_fails_on_reasonless_suppression(tmp_path, capsys):
    from pvraft_tpu.analysis.__main__ import main

    f = tmp_path / "a.py"
    f.write_text(
        "import jax.numpy as jnp\n"
        "X = jnp.arange(3)  # graftlint: disable=GL003\n"
    )
    rc = main(["lint", "--stats", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "reason-less" in out


def test_stats_warns_on_unknown_rule_id(tmp_path, capsys):
    from pvraft_tpu.analysis.__main__ import main

    f = tmp_path / "a.py"
    f.write_text("x = 1  # graftlint: disable=GL999 -- typo'd id\n")
    rc = main(["lint", "--stats", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0  # reasoned, so it passes — but the typo is surfaced
    assert "unknown rule GL999" in out


def test_repo_suppression_debt_is_reasoned():
    """The shipped tree carries no reason-less suppressions — the gate's
    blind spots stay enumerable (and justified)."""
    from pvraft_tpu.analysis.engine import collect_suppressions

    pragmas = collect_suppressions(
        [os.path.join(REPO, "pvraft_tpu"), os.path.join(REPO, "tests"),
         os.path.join(REPO, "scripts")]
    )
    missing = [p for p in pragmas if not p.reason]
    assert missing == [], missing


def test_stats_counts_trailing_text_as_reasonless(tmp_path, capsys):
    """An active suppression with trailing text NOT introduced by `--`
    must be counted (the engine honors it!) and flagged reason-less —
    not silently missed by the debt report."""
    from pvraft_tpu.analysis.__main__ import main
    from pvraft_tpu.analysis.engine import lint_paths

    f = tmp_path / "a.py"
    f.write_text(
        "import jax.numpy as jnp\n"
        "X = jnp.arange(3)  # graftlint: disable=GL003 see NOTES.md\n"
    )
    diags, _ = lint_paths([str(f)])
    assert diags == []  # the engine DOES honor this pragma
    rc = main(["lint", "--stats", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "reason-less" in out
