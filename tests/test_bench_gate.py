"""pvraft_bench/v1 + the regression gate: validator red/green, the
comparability rules (CPU fallback can never ratio against a TPU
baseline), and bench_compare.py's exit codes on an injected regression
and a platform-mismatched comparison (the acceptance criteria)."""

import copy
import json
import os
import subprocess
import sys

import pytest

from pvraft_tpu.obs.bench import (
    BENCH_SCHEMA,
    compare,
    validate_bench,
    validate_bench_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _doc(**over):
    doc = {
        "schema": BENCH_SCHEMA,
        "metric": "train_point_pairs_per_sec_per_chip",
        "value": 50000.0,
        "unit": "point-pairs/s/chip (8192 pts, 8 iters, bs=2, "
                "fwd+bwd+adam)",
        "platform": "tpu",
        "comparable": True,
        "vs_baseline": 6.6,
        "variant": "bf16+pallas+approx",
        "dt_spread": 0.03,
    }
    doc.update(over)
    return doc


# --- validator --------------------------------------------------------------


def test_validate_green():
    assert validate_bench(_doc()) == []
    assert validate_bench(_doc(platform="cpu", comparable=False,
                               vs_baseline=0.0,
                               note="cpu fallback")) == []


@pytest.mark.parametrize("over, fragment", [
    ({"schema": "pvraft_bench/v0"}, "schema"),
    ({"value": -1.0}, "value"),
    ({"value": "fast"}, "value"),
    ({"platform": ""}, "platform"),
    ({"comparable": "yes"}, "comparable must be a bool"),
    ({"surprise": 1}, "unknown field"),
    ({"dt_reps": [0.5, -0.1]}, "dt_reps"),
])
def test_validate_red(over, fragment):
    problems = validate_bench(_doc(**over))
    assert problems and any(fragment in p for p in problems), problems


def test_validate_red_missing_required():
    for key in ("platform", "comparable", "vs_baseline", "unit"):
        doc = _doc()
        del doc[key]
        assert any(f"missing required field {key!r}" in p
                   for p in validate_bench(doc)), key


def test_incomparable_must_zero_vs_baseline():
    # The BENCH_r05 failure mode, now a schema violation: an
    # incomparable (CPU-fallback) run carrying a baseline ratio.
    problems = validate_bench(_doc(platform="cpu", comparable=False,
                                   vs_baseline=0.5))
    assert any("may never carry a baseline ratio" in p for p in problems)
    # …and comparable=true off-TPU is itself a violation.
    problems = validate_bench(_doc(platform="cpu", comparable=True,
                                   vs_baseline=6.6))
    assert any("only TPU measurements" in p for p in problems)


def test_validate_file_single_line(tmp_path):
    path = tmp_path / "bench.json"
    path.write_text(json.dumps(_doc()) + "\n")
    assert validate_bench_file(str(path)) == []
    path.write_text(json.dumps(_doc()) + "\n" + json.dumps(_doc()) + "\n")
    assert any("exactly one JSON line" in p
               for p in validate_bench_file(str(path)))
    path.write_text("not json\n")
    assert any("not valid JSON" in p
               for p in validate_bench_file(str(path)))


# --- compare ----------------------------------------------------------------


def test_compare_within_band_ok():
    verdict, msgs = compare(_doc(), _doc(value=48000.0))
    assert verdict == "ok"
    assert any("within the noise band" in m for m in msgs)


def test_compare_regression():
    verdict, msgs = compare(_doc(), _doc(value=40000.0))  # -20% > 10% band
    assert verdict == "regression"
    assert any("REGRESSION" in m for m in msgs)


def test_compare_improvement_suggests_promotion():
    verdict, msgs = compare(_doc(), _doc(value=60000.0))
    assert verdict == "ok"
    assert any("promoting the candidate" in m for m in msgs)


def test_compare_spread_widens_band():
    # A candidate whose own recorded spread exceeds the band must not
    # flag its own jitter: 15% drop inside an 18% recorded spread.
    verdict, _ = compare(_doc(), _doc(value=42500.0, dt_spread=0.18))
    assert verdict == "ok"
    verdict, _ = compare(_doc(), _doc(value=42500.0, dt_spread=0.01))
    assert verdict == "regression"


def test_compare_refuses_cross_platform():
    cpu = _doc(platform="cpu", comparable=False, vs_baseline=0.0)
    verdict, msgs = compare(_doc(), cpu)
    assert verdict == "refused"
    assert any("platform mismatch" in m for m in msgs)
    assert any("CPU-fallback" in m for m in msgs)


def test_compare_refuses_config_and_lever_mismatch():
    verdict, msgs = compare(
        _doc(), _doc(unit="point-pairs/s/chip (2048 pts, 4 iters, bs=2, "
                          "fwd+bwd+adam)"))
    assert verdict == "refused" and any("unit mismatch" in m for m in msgs)
    verdict, msgs = compare(_doc(), _doc(variant="fp32"))
    assert verdict == "refused" and any("variant" in m for m in msgs)
    verdict, msgs = compare(
        _doc(), _doc(ab_flags={"scatter_free_vjp": True}))
    assert verdict == "refused" and any("ab_flags" in m for m in msgs)


def test_compare_refuses_zero_measurement():
    verdict, msgs = compare(_doc(value=0.0, vs_baseline=0.0,
                                 comparable=False, platform="tpu"),
                            _doc())
    # comparable=False + platform tpu is legal schema-wise (a failed TPU
    # run), but a zero baseline carries no information.
    assert verdict == "refused"
    assert any("zero/failed measurement" in m for m in msgs)


# --- the CLI (acceptance: nonzero on regression AND platform mismatch) ------


def _run_cli(baseline, candidate, tmp_path, *extra):
    bp = tmp_path / "baseline.json"
    cp = tmp_path / "candidate.json"
    bp.write_text(json.dumps(baseline) + "\n")
    cp.write_text(json.dumps(candidate) + "\n")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_compare.py"),
         str(bp), str(cp), *extra],
        capture_output=True, text=True, timeout=120)


def test_cli_ok_and_injected_regression_and_platform_mismatch(tmp_path):
    out = _run_cli(_doc(), _doc(value=49000.0), tmp_path)
    assert out.returncode == 0, out.stderr
    # Injected regression: exit 1.
    out = _run_cli(_doc(), _doc(value=30000.0), tmp_path)
    assert out.returncode == 1, (out.stdout, out.stderr)
    assert "REGRESSION" in out.stderr
    # Platform-mismatched comparison: exit 2, loud refusal.
    cpu = _doc(platform="cpu", comparable=False, vs_baseline=0.0)
    out = _run_cli(_doc(), cpu, tmp_path)
    assert out.returncode == 2, (out.stdout, out.stderr)
    assert "platform mismatch" in out.stderr
    # Schema-invalid candidate: exit 2 as well.
    bad = copy.deepcopy(_doc())
    del bad["comparable"]
    out = _run_cli(_doc(), bad, tmp_path)
    assert out.returncode == 2


def test_committed_baseline_validates_and_self_compares():
    """The committed baseline artifact is schema-valid and the gate's
    wiring is sound: self-comparison is trivially within any band."""
    path = os.path.join(REPO, "artifacts", "bench_baseline.json")
    assert os.path.exists(path), (
        "artifacts/bench_baseline.json is missing — regenerate with "
        "bench.py and commit (see artifacts/README.md)")
    assert validate_bench_file(path) == []
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "bench_compare.py"),
         path, path], capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, (out.stdout, out.stderr)
