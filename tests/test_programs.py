"""Tests for the typed program registry (``pvraft_tpu/programs``).

Covers the registry mechanics (duplicate collision, decorator anchors),
the audit-view projection (spec <-> AuditEntry round-trip, zero entries
lost in the migration), the golden ``programs list`` inventory (pinned
to the committed ``artifacts/programs_list.txt`` so the artifact cannot
go stale), and the single-source guards: no (bucket, batch) geometry
literals outside the registry, bench's variant/A-B enumeration and the
profiler ladder both mirror registry records.
"""

import ast
import contextlib
import io
import os

import pytest

from pvraft_tpu.programs import (
    DuplicateProgramError,
    ProgramSpec,
    by_tag,
    get,
    load_catalog,
    register,
    register_spec,
)
from pvraft_tpu.programs import geometries as g
from pvraft_tpu.programs import spec as spec_mod
from pvraft_tpu.programs.__main__ import main as programs_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

load_catalog()


# ------------------------------------------------------------ registry ----

def test_duplicate_name_collision():
    existing = get("engine.train_step")
    with pytest.raises(DuplicateProgramError) as exc:
        register_spec(existing)
    # The error names the prior declaration site (actionable collision).
    assert "engine.train_step" in str(exc.value)
    assert existing.path in str(exc.value)


def test_register_decorator_anchors_and_cleanup():
    name = "test.tmp_registry_probe"
    try:
        @register(name, tags=("tmp",), donate_argnums=(0,))
        def _probe():
            """Probe spec for decorator metadata."""
            return (lambda x: x), (None,)

        s = get(name)
        assert s.tags == ("tmp",)
        assert s.donate_argnums == (0,)
        assert s.path.endswith("test_programs.py") and s.line > 0
        assert s.description == "Probe spec for decorator metadata."
    finally:
        spec_mod._REGISTRY.pop(name, None)  # keep the golden list clean


def test_get_unknown_name_is_actionable():
    with pytest.raises(KeyError) as exc:
        get("no.such.program")
    assert "programs list" in str(exc.value)


# ------------------------------------------------- audit view migration ----

# The full 29-entry corpus at the migration (PR 5 close) — the refactor
# must lose none of these (new entries may be added on top).
PRE_MIGRATION_CORPUS = {
    "corr.corr_init", "corr.corr_init[chunked]", "corr.corr_volume",
    "corr.knn_lookup",
    "engine.eval_step", "engine.eval_step[refine]",
    "engine.refine_train_step", "engine.train_step",
    "engine.train_step[optimized_backward]", "engine.train_step[telemetry]",
    "engine.train_step[telemetry_off_jaxpr]",
    "geometry.build_graph", "geometry.gather_neighbors",
    "geometry.knn_indices", "geometry.knn_indices[chunked]",
    "geometry.pairwise_sqdist",
    "models.PVRaft", "models.PVRaftRefine",
    "models.PVRaft[scatter_free+save_corr]",
    "pallas.fused_corr_lookup", "pallas.voxel_bin_means_pallas",
    "ring.ring_corr_init", "ring.ring_knn_indices",
    "scatter_free.gather_neighbors_onehot[grad]",
    "scatter_free.max_pool_argmax[grad]",
    "scatter_free.take_pair_onehot[grad]",
    "serve.predict", "serve.predict[bf16]",
    "voxel.voxel_bin_means",
}


def test_audit_corpus_complete():
    from pvraft_tpu.analysis.audit import entries

    names = set(entries())
    assert len(PRE_MIGRATION_CORPUS) == 29
    missing = PRE_MIGRATION_CORPUS - names
    assert not missing, f"audit entries lost in the migration: {missing}"


def test_audit_entry_is_view_of_program_spec():
    from pvraft_tpu.analysis.audit import entries

    ent = entries()
    audit_specs = {s.name: s for s in by_tag("audit")}
    assert set(ent) == set(audit_specs)
    for name, e in ent.items():
        s = audit_specs[name]
        assert e.thunk is s.thunk
        assert e.precision == s.precision
        assert e.spmd_group == s.spmd_group
        assert (e.path, e.line) == (s.path, s.line)


def test_deepcheck_reads_the_registry_corpus():
    """deepcheck's default corpus is audit.entries() — which is the
    registry view; a registry-only entry filter must therefore see it."""
    from pvraft_tpu.analysis.jaxpr.deepcheck import run_deepcheck

    report = run_deepcheck(entry_filter=("geometry.pairwise_sqdist",),
                           retrace=False)
    assert [e.name for e in report.entries] == ["geometry.pairwise_sqdist"]
    assert report.ok


# ------------------------------------------------------- golden inventory --

def test_programs_list_matches_committed_artifact():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = programs_main(["list"])
    assert rc == 0
    golden_path = os.path.join(REPO, "artifacts", "programs_list.txt")
    with open(golden_path) as f:
        golden = f.read()
    assert buf.getvalue() == golden, (
        "program inventory drifted from artifacts/programs_list.txt — "
        "regenerate it: python -m pvraft_tpu.programs list > "
        "artifacts/programs_list.txt")


def test_describe_cli():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = programs_main(["describe", "serve_predict_fp32_b2048_bs1"])
    assert rc == 0
    out = buf.getvalue()
    assert "donate:      1" in out
    assert "v5e:2x2x1" in out
    assert "float32(1, 2048, 3)" in out  # the declared out geometry
    assert programs_main(["describe", "no.such.program"]) == 2


def test_verify_cli_subset():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = programs_main(["verify", "--only", "geometry.pairwise_sqdist"])
    assert rc == 0
    assert "[PASS] geometry.pairwise_sqdist" in buf.getvalue()


# ------------------------------------------------- single-source guards ----

def _code_int_literals(path):
    """Every int literal in actual code. Docstrings/comments may still
    *mention* geometry (they are str constants / not AST constants);
    only executable code is held to the no-duplication rule."""
    with open(path, "rb") as f:
        tree = ast.parse(f.read(), filename=path)
    lits = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                and not isinstance(node.value, bool):
            lits.append(node.value)
    return lits


@pytest.mark.parametrize("relpath", [
    "pvraft_tpu/serve/engine.py",
    "pvraft_tpu/serve/__main__.py",
    "scripts/aot_readiness.py",
])
def test_no_duplicated_bucket_geometry_literals(relpath):
    """The (bucket, batch, dtype) program tables live ONLY in
    programs/geometries.py; the old enumeration sites must hold no
    bucket-size literals of their own."""
    banned = set(g.SERVE_DEFAULT_BUCKETS) | {g.FLAGSHIP_POINTS}
    lits = _code_int_literals(os.path.join(REPO, relpath))
    dupes = sorted(set(lits) & banned)
    assert not dupes, (
        f"{relpath} re-grows geometry literals {dupes}; declare them in "
        f"pvraft_tpu/programs/geometries.py instead")


def test_kernel_tag_covers_every_pallas_entry_point():
    """The Mosaic-drift gate (`programs compile --tag kernel`) must
    sweep every Pallas kernel, forward AND backward."""
    names = {s.name for s in by_tag("kernel") if s.topology}
    assert names == {"pallas_voxel_fwd", "pallas_voxel_grad",
                     "pallas_fused_lookup_fwd", "pallas_fused_lookup_grad",
                     "pallas_gru_iter_fwd", "pallas_gru_iter_grad"}


def test_bench_enumeration_mirrors_registry():
    import dataclasses

    import bench

    from pvraft_tpu.config import ModelConfig

    assert bench.VARIANTS == list(g.BENCH_VARIANTS)
    names = [n for n, _ in g.BENCH_VARIANTS]
    assert len(names) == len(set(names))
    cfg_fields = {f.name for f in dataclasses.fields(ModelConfig)}
    for _, kwargs in g.BENCH_VARIANTS:
        unknown = set(kwargs) - cfg_fields
        assert not unknown, f"bench variant kwargs not in ModelConfig: {unknown}"
    for lever in g.AB_LEVERS:
        if not lever.get("step_arg"):
            assert lever["field"] in cfg_fields
    # The audited A/B configuration arms every declared lever.
    assert set(g.AB_PRIMARY) == {lv["field"] for lv in g.AB_LEVERS}


def test_compile_topology_mismatch_is_loud(monkeypatch):
    """Specs are certified for their DECLARED topology; a different
    --topology must exit cleanly with the --force-topology hint, never
    silently certify the wrong slice (and never traceback)."""
    monkeypatch.setenv("PVRAFT_PALLAS_INTERPRET", "1")  # pin_cpu_host sets 0
    monkeypatch.setenv("TPU_SKIP_MDS_QUERY", "1")
    buf = io.StringIO()
    with contextlib.redirect_stderr(buf):
        try:
            rc = programs_main(["compile", "--tag", "kernel",
                                "--topology", "v5e:2x2x2"])
        except Exception:  # pragma: no cover - the bug this test pins
            pytest.fail("mismatched --topology must not raise")
    err = buf.getvalue()
    if "cannot build" in err:
        pytest.skip("no TPU compile toolchain on this host")
    assert rc == 2
    assert "--force-topology" in err


def test_catalog_import_is_jax_free():
    """The registry's data surface (list CLI, bench's parent process)
    must be readable before a backend is pinned: importing the full
    catalog may not drag jax in (thunks stay lazy)."""
    import subprocess
    import sys

    out = subprocess.run(
        [sys.executable, "-c",
         "import sys; import pvraft_tpu.programs.catalog; "
         "sys.exit(1 if 'jax' in sys.modules else 0)"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, (
        "importing pvraft_tpu.programs.catalog pulled in jax "
        f"(stderr: {out.stderr[-300:]})")


def test_profile_ladder_mirrors_registry():
    from pvraft_tpu.profiling.step_profiler import MEASUREMENTS

    prof = [s.name for s in by_tag("profile")]
    assert prof == [f"profile.{m}" for m in MEASUREMENTS]


def test_serve_program_key_enumeration():
    assert list(g.serve_program_keys((32, 64), (2,))) == [(32, 2), (64, 2)]
    # fp32 keeps the historical spelling (committed artifacts join on
    # it); other dtypes splice their short tag.
    assert g.predict_program_name(32, 2) == "predict_b32_bs2"
    assert g.predict_program_name(32, 2, "float32") == "predict_b32_bs2"
    assert g.predict_program_name(32, 2, "bfloat16") == \
        "predict_bf16_b32_bs2"
    with pytest.raises(KeyError):
        g.predict_program_name(32, 2, "float64")
    # ServeConfig defaults are the registry-declared production table.
    from pvraft_tpu.serve.engine import ServeConfig

    cfg = ServeConfig()
    assert cfg.buckets == g.SERVE_DEFAULT_BUCKETS
    assert cfg.batch_sizes == g.SERVE_DEFAULT_BATCH_SIZES


def test_certified_serve_geometries_are_registered():
    """Every SERVE_CERTIFIED (tag, bucket, batch) row has exactly one
    registered AOT spec with the serve donation intent."""
    specs = {s.name: s for s in by_tag("serve", "aot")}
    want = {f"serve_predict_{tag}_b{bucket}_bs{bs}"
            for tag, _, geoms in g.SERVE_CERTIFIED for bucket, bs in geoms}
    assert set(specs) == want
    for s in specs.values():
        assert s.donate_argnums == g.SERVE_PREDICT_DONATE
        assert s.topology == g.TOPOLOGY


def test_params_tree_artifact_pinned_both_directions():
    """The committed pvraft_params_tree/v1 inventory IS the registry's
    eval_shape param tree (regenerate via `python -m pvraft_tpu.programs
    params --out artifacts/params_tree.json`) — the jax-free cache the
    shardcheck GS001 gate and the pod planner read; drift in either
    direction (a model change, a hand-edit) fails here."""
    from pvraft_tpu.programs.partitioning import (
        build_params_tree,
        load_params_tree,
    )

    committed = load_params_tree(
        os.path.join(REPO, "artifacts", "params_tree.json"))
    fresh = build_params_tree()
    assert committed == fresh, (
        "artifacts/params_tree.json drifted from the registry's "
        "eval_shape param tree — regenerate it (and then the pod plan: "
        "python -m pvraft_tpu.analysis sharding --plan --out "
        "artifacts/pod_plan.json)")


def test_dp_sp_spec_consumes_partition_rules():
    """Single-source discipline (satellite of ISSUE 15): the sharded
    registry spec builds its param shardings from the declared
    PARTITION_RULES — every leaf of its param tree carries a sharding
    whose spec matches the ladder's answer for that path."""
    import jax

    from pvraft_tpu.programs import get
    from pvraft_tpu.programs.partitioning import (
        PARTITION_RULES,
        match_partition_rules,
    )

    _fn, args = get("dp_sp_2x2_train_step").build()
    params = args[0]
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    paths = ["/".join(str(getattr(k, "key", k)) for k in p)
             for p, _ in flat]
    spec_of = match_partition_rules(PARTITION_RULES, paths)
    for path, leaf in zip(paths, (l for _, l in flat)):
        want = tuple(spec_of[path])
        got = tuple(leaf.sharding.spec)
        assert got == want or (want == () and got in ((), (None,))), \
            f"{path}: sharding spec {got} != rules answer {want}"
