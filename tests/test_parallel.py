"""Mesh / sharding / ring-correlation tests on the 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # jitted train steps over the 8-device mesh
from jax.sharding import PartitionSpec as P

from pvraft_tpu.compat import shard_map
from pvraft_tpu.ops.corr import CorrState, corr_init
from pvraft_tpu.parallel.mesh import make_mesh, replicate, shard_batch
from pvraft_tpu.parallel.ring import ring_corr_init


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    mesh2 = make_mesh(n_data=4, n_seq=2)
    assert mesh2.shape == {"data": 4, "seq": 2}
    # Smaller-than-host meshes take a device prefix (tests, single chip).
    assert make_mesh(n_data=1).devices.size == 1
    with pytest.raises(ValueError):
        make_mesh(n_data=5, n_seq=2)  # 10 > 8 devices
    with pytest.raises(ValueError):
        make_mesh(n_data=3, n_seq=2, devices=jax.devices())  # explicit: exact


def test_shard_batch_and_replicate():
    mesh = make_mesh(n_data=8)
    batch = {"pc1": jnp.zeros((8, 16, 3)), "mask": jnp.zeros((8, 16))}
    sharded = shard_batch(batch, mesh)
    assert sharded["pc1"].sharding.spec == P("data")
    params = replicate({"w": jnp.ones((4, 4))}, mesh)
    assert params["w"].sharding.spec == P()


def test_shard_batch_indivisible_modes():
    """A batch that can't split over the data axis must never replicate
    silently on the training path (VERDICT r1: silent 8x-FLOPs DP fallback)."""
    mesh = make_mesh(n_data=8)
    batch = {"pc1": jnp.zeros((2, 16, 3))}  # 2 % 8 != 0
    with pytest.raises(ValueError, match="does not divide"):
        shard_batch(batch, mesh, on_indivisible="error")
    with pytest.warns(UserWarning, match="does not divide"):
        out = shard_batch(batch, mesh, on_indivisible="warn")
    assert out["pc1"].sharding.spec == P()
    # Explicit replicate mode (bs=1 eval protocol) stays silent.
    out = shard_batch(batch, mesh, on_indivisible="replicate")
    assert out["pc1"].sharding.spec == P()


def test_ring_corr_matches_single_device():
    mesh = make_mesh(n_data=1, n_seq=8)
    rng = np.random.default_rng(0)
    b, n1, n2, d, k = 2, 16, 64, 8, 8
    f1 = jnp.asarray(rng.normal(size=(b, n1, d)).astype(np.float32))
    f2 = jnp.asarray(rng.normal(size=(b, n2, d)).astype(np.float32))
    x2 = jnp.asarray(rng.normal(size=(b, n2, 3)).astype(np.float32))

    ref = corr_init(f1, f2, x2, k)

    ring = shard_map(
        lambda a, bb, c: ring_corr_init(a, bb, c, k, "seq"),
        mesh=mesh,
        in_specs=(P(None, "seq", None), P(None, "seq", None), P(None, "seq", None)),
        out_specs=CorrState(
            corr=P(None, "seq", None), xyz=P(None, "seq", None, None)
        ),
        check_vma=False,
    )
    got = ring(f1, f2, x2)
    np.testing.assert_allclose(np.asarray(got.corr), np.asarray(ref.corr), atol=1e-5)
    np.testing.assert_allclose(np.asarray(got.xyz), np.asarray(ref.xyz), atol=1e-5)


def test_dp_train_step_matches_single_device():
    """Gradient all-reduce over the mesh must equal single-device training."""
    import optax
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.models import PVRaft

    cfg = ModelConfig(truncate_k=8, corr_knn=4, graph_k=4)
    model = PVRaft(cfg)
    rng = np.random.default_rng(1)
    b, n = 8, 32
    pc1 = jnp.asarray(rng.uniform(-1, 1, (b, n, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (b, n, 3)).astype(np.float32))
    mask = jnp.ones((b, n), jnp.float32)
    gt = pc2 - pc1

    params = model.init(jax.random.key(0), pc1, pc2, 2)
    tx = optax.sgd(1e-2)
    opt_state = tx.init(params)

    def step(params, opt_state, pc1, pc2, mask, gt):
        def loss_fn(p):
            flows, _ = model.apply(p, pc1, pc2, 2)
            return sequence_loss(flows, mask, gt, 0.8)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state)
        return optax.apply_updates(params, updates), opt_state, loss

    # Single device.
    p1, _, loss1 = jax.jit(step)(params, opt_state, pc1, pc2, mask, gt)

    # 8-way data parallel via shardings.
    mesh = make_mesh(n_data=8)
    pr = replicate(params, mesh)
    opr = replicate(opt_state, mesh)
    batch = shard_batch({"pc1": pc1, "pc2": pc2, "mask": mask, "gt": gt}, mesh)
    p2, _, loss2 = jax.jit(step)(
        pr, opr, batch["pc1"], batch["pc2"], batch["mask"], batch["gt"]
    )
    np.testing.assert_allclose(float(loss1), float(loss2), atol=1e-5)
    l1 = jax.tree_util.tree_leaves(p1)
    l2 = jax.tree_util.tree_leaves(p2)
    for a, b_ in zip(l1, l2):
        # Cross-device gradient accumulation reorders fp32 sums; observed
        # max |diff| ~1e-4 after one sgd step on this tiny model.
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-4)


def test_ring_knn_matches_dense():
    """ring_knn_indices must reproduce the dense kNN graph (global
    indices, nearest first, self included)."""
    from pvraft_tpu.ops.geometry import knn_indices
    from pvraft_tpu.parallel.ring import seq_sharded_graph
    from pvraft_tpu.ops.geometry import build_graph

    mesh = make_mesh(n_data=1, n_seq=8)
    rng = np.random.default_rng(6)
    pc = jnp.asarray(rng.uniform(-1, 1, (2, 64, 3)).astype(np.float32))
    dense = build_graph(pc, 8)
    ring = seq_sharded_graph(pc, 8, mesh)
    np.testing.assert_array_equal(
        np.asarray(ring.neighbors), np.asarray(dense.neighbors)
    )
    np.testing.assert_allclose(
        np.asarray(ring.rel_pos), np.asarray(dense.rel_pos), atol=1e-6
    )


def test_seq_shard_model_matches_dense():
    """cfg.seq_shard routes the model's corr_init through the ppermute ring
    (VERDICT r1 item 6): a 1x8 seq mesh forward must match the dense
    single-device forward."""
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models import PVRaft

    rng = np.random.default_rng(3)
    n = 64
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8)
    dense = PVRaft(cfg)
    params = dense.init(jax.random.key(0), pc1, pc2, 2)
    ref, _ = jax.jit(lambda p: dense.apply(p, pc1, pc2, 2))(params)

    mesh = make_mesh(n_data=1, n_seq=8)
    import dataclasses
    sharded = PVRaft(dataclasses.replace(cfg, seq_shard=True), mesh=mesh)
    got, _ = jax.jit(lambda p: sharded.apply(p, pc1, pc2, 2))(params)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


def test_seq_shard_train_step_2x4_matches_8x1():
    """A 2x4 (data x seq) mesh training step must match the 8x1 pure-DP
    result: batch parallelism and the correlation ring compose."""
    import optax
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.models import PVRaft

    rng = np.random.default_rng(4)
    b, n = 8, 32
    pc1 = jnp.asarray(rng.uniform(-1, 1, (b, n, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (b, n, 3)).astype(np.float32))
    mask = jnp.ones((b, n), jnp.float32)
    gt = pc2 - pc1
    base = ModelConfig(truncate_k=8, corr_knn=4, graph_k=4)

    def run(mesh, cfg):
        model = PVRaft(cfg, mesh=mesh if cfg.seq_shard else None)
        params = model.init(jax.random.key(0), pc1, pc2, 2)
        tx = optax.sgd(1e-2)

        def step(params, opt_state, pc1, pc2, mask, gt):
            def loss_fn(p):
                flows, _ = model.apply(p, pc1, pc2, 2)
                return sequence_loss(flows, mask, gt, 0.8)

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state, loss

        pr = replicate(params, mesh)
        opr = replicate(tx.init(params), mesh)
        batch = shard_batch({"pc1": pc1, "pc2": pc2, "mask": mask, "gt": gt},
                            mesh)
        p, _, loss = jax.jit(step)(
            pr, opr, batch["pc1"], batch["pc2"], batch["mask"], batch["gt"]
        )
        return p, float(loss)

    p_dp, loss_dp = run(make_mesh(n_data=8), base)
    import dataclasses
    p_sp, loss_sp = run(
        make_mesh(n_data=2, n_seq=4),
        dataclasses.replace(base, seq_shard=True),
    )
    np.testing.assert_allclose(loss_sp, loss_dp, atol=1e-5)
    for a, b_ in zip(jax.tree_util.tree_leaves(p_dp),
                     jax.tree_util.tree_leaves(p_sp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-4)


def test_seq_shard_bs1_eval_replicates_batch():
    """bs=1 eval on a data>1 mesh must not try to split the batch axis:
    the ring spec keeps the batch replicated when it doesn't divide the
    data axis (the reference's bs=1 protocol, test.py:92)."""
    import dataclasses
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models import PVRaft

    rng = np.random.default_rng(5)
    n = 32
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    cfg = dataclasses.replace(
        ModelConfig(truncate_k=8, corr_knn=4, graph_k=4), seq_shard=True
    )
    mesh = make_mesh(n_data=2, n_seq=4)
    model = PVRaft(cfg, mesh=mesh)
    params = model.init(jax.random.key(0), pc1, pc2, 2)
    flows, _ = jax.jit(lambda p: model.apply(p, pc1, pc2, 2))(params)
    assert np.all(np.isfinite(np.asarray(flows)))


def test_make_mesh_rejects_zero():
    with pytest.raises(ValueError, match=">= 1 device"):
        make_mesh(n_data=0)


def test_graft_entry_dryrun():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_graft_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (1, 1024, 3)
    assert np.all(np.isfinite(np.asarray(out)))


def test_graft_entry_dryrun_small_counts():
    """The driver may probe various device counts; 2 (1-D mesh) and 4
    (2x2 mesh with a real seq axis) must both work."""
    import __graft_entry__ as ge

    ge.dryrun_multichip(2)
    ge.dryrun_multichip(4)


def test_multihost_validation_paths(monkeypatch):
    """The multi-process guards in shard_batch: the mesh data axis must be
    a multiple of the process count, and indivisible batches must
    hard-error instead of assembling per-process-different data into a
    'replicated' array. (Single-process simulation: only the validation
    layer is reachable.)"""
    mesh = make_mesh(n_data=8, n_seq=1)
    batch = {"pc1": np.zeros((3, 16, 3), np.float32)}

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    with pytest.raises(ValueError, match="multiple of the process count"):
        shard_batch(batch, mesh)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # local_data = 4; leading axis 3 is indivisible -> hard error even in
    # the default "warn" mode when multi-process.
    with pytest.raises(ValueError, match="diverge"):
        shard_batch(batch, mesh, on_indivisible="warn")


def test_trainer_rejects_indivisible_global_batch_per_process(monkeypatch, tmp_path):
    from conftest import tiny_trainer_cfg
    from pvraft_tpu.engine.trainer import Trainer

    monkeypatch.setattr(jax, "process_count", lambda: 3)
    cfg = tiny_trainer_cfg(tmp_path)  # batch_size=2 -> global batch 2 on 1-device mesh
    with pytest.raises(ValueError, match="multiple of .* process count"):
        Trainer(cfg, mesh=make_mesh(n_data=1))


def test_eval_scene_shard_gates(monkeypatch):
    """Scene-sharding must engage only when every per-process step is a
    full, locally-shardable batch; anything else falls back to (0, 1)
    (all processes feed the same scenes — redundant but exact)."""
    from pvraft_tpu.parallel.mesh import eval_scene_shard

    mesh = make_mesh(n_data=8)
    # Single process: never shards.
    assert eval_scene_shard(400, 8, mesh) == (0, 1)

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    # 400 scenes, eval_batch 8, 2 procs: 400 % 16 == 0 and 8 % 4 == 0.
    assert eval_scene_shard(400, 8, mesh) == (1, 2)
    # Partial tail (402 % 16 != 0): no shard.
    assert eval_scene_shard(402, 8, mesh) == (0, 1)
    # eval_batch 2 not a multiple of local_data 4: per-process batches
    # would hit the replicate path with distinct rows — no shard.
    assert eval_scene_shard(400, 2, mesh) == (0, 1)
