"""The packed (single-buffer) train step must match the pytree train step
exactly: same losses and same parameters after several chained steps."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # chained full train steps, both stages

import jax
import jax.numpy as jnp
import optax

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.engine.steps import make_packed_train_step, make_train_step
from pvraft_tpu.models import PVRaft


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)
    model = PVRaft(cfg)
    rng = np.random.default_rng(0)
    n = 64
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    batch = {"pc1": pc1, "pc2": pc2,
             "mask": jnp.ones((1, n), jnp.float32), "flow": pc2 - pc1}
    params = model.init(jax.random.key(0), pc1, pc2, 2)
    tx = optax.adam(1e-3)
    return model, tx, params, batch


def test_packed_matches_pytree_step(setup):
    model, tx, params, batch = setup
    opt_state = tx.init(params)

    ref_step = make_train_step(model, tx, 0.8, 2, donate=False)
    p, o = params, opt_state
    ref_losses = []
    for _ in range(3):
        p, o, m = ref_step(p, o, batch)
        ref_losses.append(float(m["loss"]))

    step, flat, unravel = make_packed_train_step(
        model, tx, 0.8, 2, params, opt_state, donate=False
    )
    packed_losses = []
    for _ in range(3):
        flat, m = step(flat, batch)
        packed_losses.append(float(m["loss"]))

    np.testing.assert_allclose(packed_losses, ref_losses, rtol=1e-5)
    p_packed, o_packed = unravel(flat)
    # Param tolerance is absolute-dominated by design: the packed step is
    # the same math but a different XLA program (ravel/unravel + different
    # fusion), so three chained adam steps reassociate. Measured at HEAD
    # on this host: losses bit-identical, worst param abs diff 6.0e-5 —
    # concentrated on near-zero params where rtol=1e-5/atol=1e-6 was
    # borderline and flaked (CHANGES.md PR 4). atol=2e-4 is the
    # seed-stable ceiling with ~3x margin; rtol still pins the large
    # params.
    for a, b in zip(jax.tree.leaves(p_packed), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=2e-4)
    # The optax step count must survive the dtype round-trip exactly.
    counts = [x for x in jax.tree.leaves(o_packed)
              if np.asarray(x).dtype == np.int32]
    assert counts and all(int(c) == 3 for c in counts)


def test_multistep_matches_sequential_packed(setup):
    """K scan-fused steps == K sequential packed steps, bit-for-bit: same
    per-step losses, same final flat state (the fused program runs the
    SAME packed step body under lax.scan)."""
    from pvraft_tpu.engine.steps import make_multistep_train_step

    model, tx, params, batch = setup
    opt_state = tx.init(params)
    k = 4

    step, flat, _ = make_packed_train_step(
        model, tx, 0.8, 2, params, opt_state, donate=False
    )
    seq_losses = []
    for i in range(k):
        # Distinct per-step batches so the test would catch a wrong scan
        # xs-ordering, not just a wrong carry.
        b = {**batch, "flow": batch["flow"] * (1.0 + 0.1 * i)}
        flat, m = step(flat, b)
        seq_losses.append(float(m["loss"]))

    mstep, mflat, unravel = make_multistep_train_step(
        model, tx, 0.8, 2, params, opt_state, k, donate=False
    )
    batches = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[{**batch, "flow": batch["flow"] * (1.0 + 0.1 * i)}
          for i in range(k)],
    )
    mflat, ms = mstep(mflat, batches)

    assert np.asarray(ms["loss"]).shape == (k,)
    # Same step body, but XLA may fuse a scan-wrapped program differently
    # from the standalone executable — tight tolerance, not bitwise.
    np.testing.assert_allclose(np.asarray(ms["loss"]),
                               np.asarray(seq_losses, np.float32),
                               rtol=1e-6, atol=0)
    np.testing.assert_allclose(np.asarray(mflat), np.asarray(flat),
                               rtol=1e-5, atol=1e-7)
    counts = [x for x in jax.tree.leaves(unravel(mflat)[1])
              if np.asarray(x).dtype == np.int32]
    assert counts and all(int(c) == k for c in counts)


def test_steps_per_dispatch_config_validation():
    from pvraft_tpu.config import ParallelConfig

    with pytest.raises(ValueError):
        ParallelConfig(steps_per_dispatch=2)  # requires packed_state
    with pytest.raises(ValueError):
        ParallelConfig(steps_per_dispatch=0, packed_state=True)
    with pytest.raises(ValueError):
        ParallelConfig(steps_per_dispatch=2, packed_state=True,
                       host_roundtrip=True)
    ParallelConfig(steps_per_dispatch=4, packed_state=True)  # ok


def test_packed_refine_matches_pytree_step():
    """Stage-2: packed step through optax.masked state + compute_loss."""
    from pvraft_tpu.engine.steps import make_refine_train_step
    from pvraft_tpu.models import PVRaftRefine

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)
    model = PVRaftRefine(cfg)
    rng = np.random.default_rng(1)
    n = 64
    pc1 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    pc2 = jnp.asarray(rng.uniform(-1, 1, (1, n, 3)).astype(np.float32))
    batch = {"pc1": pc1, "pc2": pc2,
             "mask": jnp.ones((1, n), jnp.float32), "flow": pc2 - pc1}
    params = model.init(jax.random.key(0), pc1, pc2, 2)

    def mark(path, _):
        return not any(getattr(k, "key", None) == "backbone" for k in path)

    tx = optax.masked(optax.adam(1e-3),
                      jax.tree_util.tree_map_with_path(mark, params))
    opt_state = tx.init(params)

    ref_step = make_refine_train_step(model, tx, 2, donate=False)
    p, o = params, opt_state
    ref_losses = []
    for _ in range(3):
        p, o, m = ref_step(p, o, batch)
        ref_losses.append(float(m["loss"]))

    step, flat, unravel = make_packed_train_step(
        model, tx, 0.8, 2, params, opt_state, donate=False, refine=True
    )
    packed_losses = []
    for _ in range(3):
        flat, m = step(flat, batch)
        packed_losses.append(float(m["loss"]))

    np.testing.assert_allclose(packed_losses, ref_losses, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(unravel(flat)[0]), jax.tree.leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
