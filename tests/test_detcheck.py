"""detcheck: the GD rules red/green over the fixture corpus, the rng
stream contract (derivation determinism, tag uniqueness, declared
vocabulary), the registry hazard closure, the clean-tree zero-findings
gate, the CLI, the replay-report machinery (drift detection with an
injected fresh report — no program execution) and the loader
reproducibility guarantees the contract exists for."""

import ast
import contextlib
import io
import json
import os

import numpy as np
import pytest

from pvraft_tpu.analysis.__main__ import main as analysis_main
from pvraft_tpu.analysis.engine import known_rule_ids
from pvraft_tpu.analysis.determinism.check import (
    check_paths,
    check_source,
    declared_streams,
    default_scope,
    hazard_spec_records,
)
from pvraft_tpu.analysis.determinism.model import build_module_det_model
from pvraft_tpu.analysis.determinism.replay import (
    REPLAY_PROGRAMS,
    SCHEMA_VERSION,
    check_report,
    load_report,
    write_report,
)
from pvraft_tpu.analysis.determinism.rules import (
    HazardSpec,
    all_determinism_rules,
)
from pvraft_tpu import rng

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "detcheck")
REPORT = os.path.join(REPO, "artifacts", "determinism_report.json")

# The vocabulary the GD002 fixtures are checked against.
TEST_STREAMS = ("model.init", "data.shuffle")


def _fixture(name):
    with open(os.path.join(FIXTURES, name), "r", encoding="utf-8") as f:
        return f.read()


def _check(name, rule, streams=TEST_STREAMS, hazard_specs=()):
    return check_source(_fixture(name), path=os.path.join(FIXTURES, name),
                        rule_ids=(rule,), streams=streams,
                        hazard_specs=hazard_specs)


# ------------------------------------------------------------- registry --

def test_rule_table():
    rules = all_determinism_rules()
    assert [r.id for r in rules] == [
        "GD001", "GD002", "GD003", "GD004", "GD005"]
    for r in rules:
        assert r.title and r.__doc__


def test_gd_ids_in_shared_pragma_namespace():
    ids = known_rule_ids()
    assert {"GD000", "GD001", "GD002", "GD003", "GD004", "GD005"} <= ids


# ------------------------------------------------------- the rng contract --

def test_streams_declared_and_tags_unique():
    assert len(rng.STREAM_NAMES) == len(set(rng.STREAM_NAMES))
    tags = [rng.stream_tag(s) for s in rng.STREAM_NAMES]
    assert len(tags) == len(set(tags))
    with pytest.raises(ValueError):
        rng.stream_tag("not.a.stream")


def test_declared_streams_match_runtime():
    assert declared_streams() == rng.STREAM_NAMES


def test_derive_deterministic_and_stream_separated():
    import jax

    a = jax.random.key_data(rng.derive(0, "model.init"))
    b = jax.random.key_data(rng.derive(0, "model.init"))
    c = jax.random.key_data(rng.derive(0, "encoder.init"))
    d = jax.random.key_data(rng.derive(1, "model.init"))
    assert (np.asarray(a) == np.asarray(b)).all()
    assert not (np.asarray(a) == np.asarray(c)).all()
    assert not (np.asarray(a) == np.asarray(d)).all()


def test_derive_rejects_undeclared_stream_and_bad_parts():
    with pytest.raises(ValueError):
        rng.derive(0, "no.such.stream")
    with pytest.raises(ValueError):
        rng.host_rng(0)          # stream name is mandatory
    with pytest.raises(TypeError):
        rng.host_rng(0, "model.init", True)  # bool is not an index


def test_host_rng_deterministic_with_indices():
    a = rng.host_rng(3, "data.shuffle", 7).random(8)
    b = rng.host_rng(3, "data.shuffle", 7).random(8)
    c = rng.host_rng(3, "data.shuffle", 8).random(8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)


# ------------------------------------------------------- fixtures: GD001 --

def test_gd001_red():
    diags = _check("gd001_key_reuse_red.py", "GD001")
    assert len(diags) == 2
    assert "already consumed" in diags[0].message
    assert "consumed inside" in diags[1].message


def test_gd001_green():
    assert _check("gd001_key_reuse_green.py", "GD001") == []


# ------------------------------------------------------- fixtures: GD002 --

def test_gd002_red():
    diags = _check("gd002_entropy_red.py", "GD002")
    msgs = "\n".join(d.message for d in diags)
    assert sum("raw RNG constructor" in d.message for d in diags) == 3
    assert "time/entropy source `time.time`" in msgs
    assert "no stream name literal" in msgs
    assert "undeclared stream 'not.a.stream'" in msgs
    # the declared-stream host_rng call is NOT flagged
    assert "data.shuffle" not in msgs.replace(
        "known: model.init, data.shuffle", "")


def test_gd002_green():
    assert _check("gd002_entropy_green.py", "GD002") == []


def test_gd002_unreadable_vocabulary_is_a_finding():
    diags = _check("gd002_entropy_green.py", "GD002", streams=None)
    assert diags and "unverifiable" in diags[0].message


# ------------------------------------------------------- fixtures: GD003 --

def _hazard_spec(path, determinism):
    return HazardSpec(
        name="fixture.hazard_program", determinism=determinism,
        path=path, line=9, via="pvraft_tpu/ops/pallas/corr_lookup.py",
        kinds=("scatter-accumulate",))


def test_gd003_red_and_green():
    red = os.path.join(FIXTURES, "gd003_hazard_red.py")
    diags = _check("gd003_hazard_red.py", "GD003",
                   hazard_specs=(_hazard_spec(red, ""),))
    assert len(diags) == 1
    assert diags[0].line == 9
    assert "determinism= stance" in diags[0].message

    green = os.path.join(FIXTURES, "gd003_hazard_green.py")
    assert _check("gd003_hazard_green.py", "GD003",
                  hazard_specs=(_hazard_spec(
                      green, "unique-index-scatter"),)) == []


def test_gd003_other_files_unaffected():
    # A hazard spec anchored elsewhere must not leak findings here.
    spec = _hazard_spec("/somewhere/else/catalog.py", "")
    assert _check("gd001_key_reuse_green.py", "GD003",
                  hazard_specs=(spec,)) == []


# ------------------------------------------------------- fixtures: GD004 --

def test_gd004_red():
    diags = _check("gd004_flags_red.py", "GD004")
    keys = sorted(d.message.split("`")[1] for d in diags)
    assert keys == ["PYTHONHASHSEED", "XLA_FLAGS",
                    "jax_default_matmul_precision", "jax_enable_x64"]


def test_gd004_green():
    assert _check("gd004_flags_green.py", "GD004") == []


# ------------------------------------------------------- fixtures: GD005 --

def test_gd005_red():
    diags = _check("gd005_iteration_red.py", "GD005")
    msgs = "\n".join(d.message for d in diags)
    assert len(diags) == 4
    assert "set literal" in msgs
    assert "set() result" in msgs
    assert "glob.glob" in msgs
    assert ".rglob()" in msgs


def test_gd005_green():
    assert _check("gd005_iteration_green.py", "GD005") == []


# ------------------------------------------------- model extraction unit --

def test_alias_resolution_distinguishes_jax_from_stdlib_random():
    src = ("from jax import random\n"
           "import random as pyrandom\n"
           "def f(key, seed):\n"
           "    a = random.normal(key, (2,))\n"
           "    b = pyrandom.Random(seed)\n")
    model = build_module_det_model(ast.parse(src))
    assert [s.resolved for s in model.rng_constructors] == ["random.Random"]


def test_suppression_pragma_honored():
    src = ("import numpy as np\n"
           "rng = np.random.default_rng(0)"
           "  # graftlint: disable=GD002 -- fixture\n")
    assert check_source(src, rule_ids=("GD002",), streams=TEST_STREAMS) == []
    bare = "import numpy as np\nrng = np.random.default_rng(0)\n"
    assert len(check_source(bare, rule_ids=("GD002",),
                            streams=TEST_STREAMS)) == 1


def test_syntax_error_is_gd000():
    diags = check_source("def broken(:\n", streams=TEST_STREAMS)
    assert [d.rule_id for d in diags] == ["GD000"]


# ------------------------------------------------- registry hazard closure --

def test_hazard_closure_covers_the_real_programs():
    records = {r.name: r for r in hazard_spec_records()}
    # The replay corpus must be hazard-bearing (that is WHY it replays).
    for name in REPLAY_PROGRAMS:
        assert name in records, sorted(records)
    assert "ring.ring_corr_init" in records
    assert records["ring.ring_corr_init"].kinds == ("ring-fold",)
    assert "scatter-accumulate" in records["engine.train_step"].kinds


def test_hazard_closure_all_declared():
    # The GD003 clean-tree condition, stated directly: every
    # hazard-bearing registered program carries a stance.
    undeclared = [r.name for r in hazard_spec_records() if not r.determinism]
    assert undeclared == []


# ------------------------------------------------------------ clean tree --

def test_clean_tree_zero_findings():
    """The lint.sh stage in test form: the shipped tree carries zero GD
    findings with the live stream vocabulary + registry closure."""
    findings, nfiles = check_paths(list(default_scope()))
    assert findings == [], [d.format() for d in findings]
    assert nfiles > 100


# ------------------------------------------------------------------- CLI --

def test_cli_list_rules():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["determinism", "--list-rules"])
    assert rc == 0
    out = buf.getvalue()
    for rid in ("GD001", "GD002", "GD003", "GD004", "GD005"):
        assert rid in out


def test_cli_red_fixture_and_select():
    path = os.path.join(FIXTURES, "gd004_flags_red.py")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(io.StringIO()):
        rc = analysis_main(["determinism", "--select", "GD004", path])
    assert rc == 1
    assert "GD004" in buf.getvalue()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), \
            contextlib.redirect_stderr(io.StringIO()):
        rc = analysis_main(["determinism", "--select", "GD001", path])
    assert rc == 0


# ---------------------------------------------------------- replay report --

def _committed():
    return load_report(REPORT)


def test_committed_report_schema_and_verdict():
    doc = _committed()
    assert doc["schema"] == SCHEMA_VERSION
    assert doc["verdict"] == "bitwise"
    assert doc["streams"] == list(rng.STREAM_NAMES)
    names = [e["name"] for e in doc["programs"]]
    assert names == list(REPLAY_PROGRAMS)
    for e in doc["programs"]:
        assert e["bitwise_identical"]
        assert e["digest"] == e["digest_rerun"]
        assert e["determinism"]  # the stance rides into the evidence


def test_check_report_accepts_identical_fresh():
    assert check_report(REPORT, fresh=_committed()) == []


def test_check_report_flags_divergence_and_drift(tmp_path):
    committed = _committed()

    fresh = json.loads(json.dumps(committed))
    fresh["programs"][0]["digest_rerun"] = "0" * 64
    fresh["programs"][0]["bitwise_identical"] = False
    fresh["verdict"] = "divergent"
    problems = check_report(REPORT, fresh=fresh)
    assert any("does NOT replay bitwise" in p for p in problems)

    fresh = json.loads(json.dumps(committed))
    fresh["streams"] = fresh["streams"] + ["new.stream"]
    assert any("stream vocabulary drift" in p
               for p in check_report(REPORT, fresh=fresh))

    fresh = json.loads(json.dumps(committed))
    fresh["programs"][0]["name"] = "engine.other_step"
    assert any("program set drift" in p
               for p in check_report(REPORT, fresh=fresh))

    # Digest drift fails on the same platform, passes cross-platform.
    fresh = json.loads(json.dumps(committed))
    fresh["programs"][0]["digest"] = "f" * 64
    same = check_report(REPORT, fresh=fresh)
    assert any("digest drift" in p for p in same)
    fresh["platform"] = "tpu"
    cross = check_report(REPORT, fresh=fresh)
    assert not any("digest drift" in p for p in cross)

    # A committed report that itself claims divergence always fails.
    bad = json.loads(json.dumps(committed))
    bad["verdict"] = "divergent"
    path = tmp_path / "divergent.json"
    write_report(str(path), bad)
    assert any("committed verdict" in p
               for p in check_report(str(path), fresh=committed))


# --------------------------------------- loader reproducibility guarantees --

def test_loader_order_invariant_to_num_workers():
    from pvraft_tpu.data import PrefetchLoader, SyntheticDataset

    ds = SyntheticDataset(size=12, nb_points=16, seed=9)
    runs = []
    for workers in (0, 1, 3):
        loader = PrefetchLoader(ds, 3, shuffle=True, num_workers=workers,
                                seed=11)
        runs.append([b["pc1"] for b in loader.epoch(2)])
    for other in runs[1:]:
        assert len(runs[0]) == len(other)
        for a, b in zip(runs[0], other):
            np.testing.assert_array_equal(a, b)


def test_loader_epoch_replay_bitwise():
    from pvraft_tpu.data import PrefetchLoader, SyntheticDataset

    ds = SyntheticDataset(size=8, nb_points=16, seed=5)
    loader = PrefetchLoader(ds, 2, shuffle=True, num_workers=2, seed=13)
    first = [b["pc1"] for b in loader.epoch(4)]
    again = [b["pc1"] for b in loader.epoch(4)]
    other = [b["pc1"] for b in loader.epoch(5)]
    for a, b in zip(first, again):
        np.testing.assert_array_equal(a, b)
    assert not all(np.array_equal(a, b) for a, b in zip(first, other))


def test_generic_subsample_replays_bitwise():
    from pvraft_tpu.data import SyntheticDataset

    ds = SyntheticDataset(size=4, nb_points=32, extra_points=16, seed=2)
    ds.set_epoch(3)
    a = ds[1]
    b = ds[1]
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])
    ds.set_epoch(4)  # per-epoch resampling: a DIFFERENT draw, replayable
    c = ds[1]
    assert not np.array_equal(a["pc1"], c["pc1"])
    d = ds[1]
    np.testing.assert_array_equal(c["pc1"], d["pc1"])
