"""Engine tests: schedules, checkpointing, trainer end-to-end on the tiny
synthetic config, refine-stage freezing, evaluator."""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pvraft_tpu.engine.schedule import make_lr_schedule
from pvraft_tpu.parallel.mesh import make_mesh


def _tiny_cfg(tmp_path, refine=False, epochs=1):
    from conftest import tiny_trainer_cfg

    return tiny_trainer_cfg(tmp_path, refine=refine, epochs=epochs)


def _tiny_trainer(cfg):
    """batch_size is per-device: 4-sample synthetic datasets need a 1-device
    mesh (the 8-device default would ask for a global batch of 16)."""
    from pvraft_tpu.engine.trainer import Trainer

    return Trainer(cfg, mesh=make_mesh(n_data=1))


def test_parity_schedule_is_near_constant():
    s = make_lr_schedule("parity", 1e-3, 20, 100, 17640)
    lrs = [float(s(i * 100)) for i in range(20)]
    assert all(abs(l - 1e-3) / 1e-3 < 1e-5 for l in lrs)


def test_cosine_schedule_decays():
    s = make_lr_schedule("cosine", 1e-3, 2, 100, 200)
    assert float(s(0)) == pytest.approx(1e-3)
    assert float(s(200)) == pytest.approx(0.0, abs=1e-9)
    assert float(s(100)) == pytest.approx(5e-4, rel=1e-3)


def test_checkpoint_roundtrip(tmp_path):
    import optax
    from pvraft_tpu.engine.checkpoint import load_checkpoint, save_checkpoint

    params = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
              "b": {"c": np.ones(4, np.float32)}}
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    save_checkpoint(str(tmp_path), params, opt_state, epoch=4,
                    checkpoint_interval=5, best=True)
    assert os.path.exists(tmp_path / "last_checkpoint.msgpack")
    assert os.path.exists(tmp_path / "004.msgpack")
    assert os.path.exists(tmp_path / "best_checkpoint.msgpack")

    tmpl = jax.tree_util.tree_map(np.zeros_like, params)
    p2, o2, epoch = load_checkpoint(
        str(tmp_path / "last_checkpoint.msgpack"), tmpl, tx.init(tmpl)
    )
    assert epoch == 4
    np.testing.assert_array_equal(p2["a"], params["a"])
    np.testing.assert_array_equal(p2["b"]["c"], params["b"]["c"])
    assert o2 is not None


@pytest.mark.slow
def test_trainer_end_to_end(tmp_path):
    cfg = _tiny_cfg(tmp_path, epochs=2)
    tr = _tiny_trainer(cfg)
    m0 = tr.training(0)
    v0 = tr.val_test(0, "val")
    m1 = tr.training(1)
    assert np.isfinite(m0["loss"]) and np.isfinite(v0["epe3d"])
    assert m1["loss"] < m0["loss"]  # learning on a 4-sample dataset
    # Checkpoints written with the reference naming scheme.
    ckpts = os.listdir(os.path.join(cfg.exp_path, "checkpoints"))
    assert "last_checkpoint.msgpack" in ckpts
    assert "best_checkpoint.msgpack" in ckpts
    # TB history recorded with reference tag names.
    assert tr.tb.history["Train/Loss"]
    assert tr.tb.history["Val/EPE"]


@pytest.mark.slow
def test_trainer_resume(tmp_path):
    cfg = _tiny_cfg(tmp_path, epochs=2)
    tr = _tiny_trainer(cfg)
    tr.training(0)
    last = os.path.join(cfg.exp_path, "checkpoints", "last_checkpoint.msgpack")

    tr2 = _tiny_trainer(cfg)
    tr2.load_weights(last, resume=True)
    assert tr2.begin_epoch == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(tr.params), jax.tree_util.tree_leaves(tr2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_refine_trainer_freezes_backbone(tmp_path):
    cfg = _tiny_cfg(tmp_path, refine=True)
    tr = _tiny_trainer(cfg)
    before = jax.tree_util.tree_map(np.asarray, tr.params)
    tr.training(0)
    after = jax.tree_util.tree_map(np.asarray, tr.params)
    b_back = before["params"]["backbone"]
    a_back = after["params"]["backbone"]
    for x, y in zip(jax.tree_util.tree_leaves(b_back), jax.tree_util.tree_leaves(a_back)):
        np.testing.assert_array_equal(x, y)  # frozen
    # refine head must move
    moved = False
    for key in ("ref_conv1", "ref_conv2", "ref_conv3", "fc"):
        for x, y in zip(
            jax.tree_util.tree_leaves(before["params"][key]),
            jax.tree_util.tree_leaves(after["params"][key]),
        ):
            moved |= not np.allclose(x, y)
    assert moved


@pytest.mark.slow
def test_stage1_weight_import(tmp_path):
    cfg1 = _tiny_cfg(tmp_path)
    tr1 = _tiny_trainer(cfg1)
    tr1.training(0)
    last = os.path.join(cfg1.exp_path, "checkpoints", "last_checkpoint.msgpack")

    cfg2 = _tiny_cfg(tmp_path / "r", refine=True)
    tr2 = _tiny_trainer(cfg2)
    tr2.load_stage1_weights(last)
    s1 = jax.tree_util.tree_map(np.asarray, tr1.params)["params"]
    s2 = jax.tree_util.tree_map(np.asarray, tr2.params)["params"]["backbone"]
    for x, y in zip(jax.tree_util.tree_leaves(s1), jax.tree_util.tree_leaves(s2)):
        np.testing.assert_array_equal(x, y)


@pytest.mark.slow
def test_trainer_per_device_batch_scales_with_mesh(tmp_path):
    """bs is per-device: an 8-way data mesh trains 8x the samples per step
    (the role DataParallel's split plays at tools/engine.py:63-64)."""
    from pvraft_tpu.engine.trainer import Trainer

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(
        data=cfg.data.__class__(dataset="synthetic", max_points=64,
                                synthetic_size=16, num_workers=0),
        train=cfg.train.__class__(batch_size=1, num_epochs=1, iters=2,
                                  eval_iters=2, checkpoint_interval=1),
    )
    tr = Trainer(cfg, mesh=make_mesh(n_data=8))
    assert tr.global_batch == 8
    assert len(tr.train_loader) == 2  # 16 samples / (1 per device * 8)
    m = tr.training(0)
    assert np.isfinite(m["loss"])


def test_trainer_rejects_oversized_global_batch(tmp_path):
    """A mesh asking for more samples per step than the dataset holds must
    fail loudly, not silently produce zero steps."""
    from pvraft_tpu.engine.trainer import Trainer

    cfg = _tiny_cfg(tmp_path)  # synthetic_size=4, bs=2/device
    with pytest.raises(ValueError, match="global batch"):
        Trainer(cfg, mesh=make_mesh(n_data=8))  # wants 16 > 4


@pytest.mark.slow
def test_trainer_seq_shard_end_to_end(tmp_path):
    """Full Trainer epoch on a 2x2 (data x seq) mesh with the ring
    correlation + ring kNN active inside the jitted train step."""
    import dataclasses

    from pvraft_tpu.engine.trainer import Trainer

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(
        model=dataclasses.replace(cfg.model, seq_shard=True),
        train=cfg.train.__class__(batch_size=1, num_epochs=1, iters=2,
                                  eval_iters=2, checkpoint_interval=1),
    )
    tr = Trainer(cfg, mesh=make_mesh(n_data=2, n_seq=2))
    assert tr.global_batch == 2
    m = tr.training(0)
    assert np.isfinite(m["loss"])
    v = tr.val_test(0, "val")
    assert np.isfinite(v["epe3d"])


@pytest.mark.slow
def test_evaluator_runs_and_dumps(tmp_path):
    from pvraft_tpu.engine.evaluator import Evaluator

    cfg = _tiny_cfg(tmp_path)
    ev = Evaluator(cfg)
    means = ev.run(dump_dir=str(tmp_path / "result"))
    for k in ("epe3d", "acc3d_strict", "acc3d_relax", "outlier", "loss"):
        assert k in means and np.isfinite(means[k])
    scene0 = tmp_path / "result" / "synthetic" / "0"
    assert (scene0 / "pc1.npy").exists()
    assert (scene0 / "flow.npy").exists()
    assert np.load(scene0 / "flow.npy").shape == (64, 3)


@pytest.mark.slow
def test_evaluator_sharded_batch_matches_protocol(tmp_path):
    """eval_batch>1 shards scenes over the mesh data axis with per-scene
    metrics: running means must equal the reference bs=1 protocol's
    (incl. a tail batch smaller than the mesh, which replicates)."""
    import dataclasses

    from pvraft_tpu.engine.evaluator import Evaluator

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, synthetic_size=6)
    )
    base = Evaluator(cfg).run()

    cfg4 = cfg.replace(
        train=dataclasses.replace(cfg.train, eval_batch=4),
        exp_path=str(tmp_path / "exp4"),
    )
    ev4 = Evaluator(cfg4)
    assert ev4.eval_batch == 4 and len(ev4.loader) == 2  # 4 + tail of 2
    batched = ev4.run(dump_dir=str(tmp_path / "result4"))

    for k in base:
        assert batched[k] == pytest.approx(base[k], rel=1e-5), k
    # Dump indices stay per-scene across batches.
    for idx in range(6):
        assert (tmp_path / "result4" / "synthetic" / str(idx) / "flow.npy").exists()


@pytest.mark.slow
def test_trainer_steps_per_dispatch_on_data_mesh(tmp_path):
    """Fused dispatch composes with data-parallel sharding: on a 2-device
    data mesh the stacked (K, B, ...) batches keep their batch-axis
    sharding through jnp.stack and the scanned step's losses equal the
    K=1 packed run's."""
    import dataclasses

    from pvraft_tpu.config import ParallelConfig
    from pvraft_tpu.engine.trainer import Trainer

    def mk(path, **par):
        c = _tiny_cfg(path, epochs=1)
        # global batch 4 (2/device x 2 devices); 8 samples -> 2 steps.
        return dataclasses.replace(
            c,
            data=dataclasses.replace(c.data, synthetic_size=8),
            parallel=ParallelConfig(packed_state=True, **par),
        )

    tr = Trainer(mk(tmp_path / "a"), mesh=make_mesh(n_data=2))
    m = tr.training(0)

    tr_f = Trainer(mk(tmp_path / "b", steps_per_dispatch=2),
                   mesh=make_mesh(n_data=2))
    m_f = tr_f.training(0)

    assert m_f["loss"] == pytest.approx(m["loss"], rel=1e-5)
    assert m_f["epe"] == pytest.approx(m["epe"], rel=1e-4)

    # Pin the sharding invariant itself (equality above cannot detect a
    # silent gather-to-one-device): a (K, B, ...) stack of data-sharded
    # batches must still be sharded over the data axis, not replicated.
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    host = {
        "pc1": rng.uniform(-1, 1, (4, 64, 3)).astype(np.float32),
        "pc2": rng.uniform(-1, 1, (4, 64, 3)).astype(np.float32),
        "mask": np.ones((4, 64), np.float32),
        "flow": np.zeros((4, 64, 3), np.float32),
    }
    b1 = tr_f._device_batch(host)
    b2 = tr_f._device_batch(host)
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), b1, b2)
    sh = stacked["pc1"].sharding
    assert not sh.is_fully_replicated, sh
    assert len(sh.device_set) == 2, sh


@pytest.mark.slow
def test_evaluator_eval_scan_matches_loop(tmp_path):
    """eval_scan>1 fuses full batches into one scanned dispatch; the
    running means must equal the per-batch loop's, including a partial
    final group (5 batches of 2 at scan=2 -> 2 fused dispatches + 1
    partial routed through the per-batch step)."""
    import dataclasses

    from pvraft_tpu.engine.evaluator import Evaluator

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(
        data=dataclasses.replace(cfg.data, synthetic_size=10),
        train=dataclasses.replace(cfg.train, eval_batch=2),
    )
    base = Evaluator(cfg).run()

    cfg_s = cfg.replace(
        train=dataclasses.replace(cfg.train, eval_batch=2, eval_scan=2),
        exp_path=str(tmp_path / "exp_scan"),
    )
    ev = Evaluator(cfg_s)
    assert ev.eval_scan == 2
    scanned = ev.run()
    for k in base:
        assert scanned[k] == pytest.approx(base[k], rel=1e-5), k

    # --dump_dir forces the per-batch path (the fused program never
    # materializes flows) and still works with eval_scan configured.
    dumped = ev.run(dump_dir=str(tmp_path / "result_scan"))
    for k in base:
        assert dumped[k] == pytest.approx(base[k], rel=1e-5), k
    assert (tmp_path / "result_scan" / "synthetic" / "9" / "flow.npy").exists()


def test_trace_context_writes_profile(tmp_path):
    import jax.numpy as jnp
    from pvraft_tpu.utils.profiling import StepTimer, trace_context

    with trace_context(str(tmp_path / "prof")):
        _ = jnp.ones((8, 8)) @ jnp.ones((8, 8))
    assert any((tmp_path / "prof").rglob("*"))  # trace events written

    t = StepTimer()
    t.start()
    x = jnp.ones((4,)) * 2
    dt = t.stop(x)
    assert dt >= 0 and t.mean >= 0


def test_visual_render(tmp_path):
    import visual

    rng = np.random.default_rng(0)
    scene = tmp_path / "result" / "FT3D" / "0"
    scene.mkdir(parents=True)
    np.save(scene / "pc1.npy", rng.normal(size=(50, 3)).astype(np.float32))
    np.save(scene / "pc2.npy", rng.normal(size=(50, 3)).astype(np.float32))
    np.save(scene / "flow.npy", rng.normal(size=(50, 3)).astype(np.float32))
    out = visual.render(str(scene), str(scene / "render.png"))
    assert os.path.exists(out) and os.path.getsize(out) > 1000


def test_visual_render_html(tmp_path):
    import visual

    rng = np.random.default_rng(0)
    scene = tmp_path / "result" / "FT3D" / "0"
    scene.mkdir(parents=True)
    np.save(scene / "pc1.npy", rng.normal(size=(60, 3)).astype(np.float32))
    np.save(scene / "pc2.npy", rng.normal(size=(60, 3)).astype(np.float32))
    np.save(scene / "flow.npy", rng.normal(size=(60, 3)).astype(np.float32))
    out = visual.render_html(str(scene), str(scene / "render.html"),
                             max_points=32)
    html = open(out).read()
    # Self-contained: inline data + renderer, no external resources.
    assert "CLOUDS" in html and "<script>" in html
    assert "http://" not in html and "https://" not in html
    # Subsampling honored: 3 clouds of exactly max_points entries.
    import json as _json

    payload = html.split("const CLOUDS = ", 1)[1].split(";\n", 1)[0]
    clouds = _json.loads(payload)
    assert len(clouds) == 3 and all(len(c) == 32 for c in clouds)


@pytest.mark.slow
def test_trainer_packed_state_matches_unpacked(tmp_path):
    import dataclasses

    from pvraft_tpu.config import ParallelConfig

    cfg = _tiny_cfg(tmp_path / "a", epochs=1)
    tr = _tiny_trainer(cfg)
    m = tr.training(0)
    v = tr.val_test(0, "val")

    cfg_p = dataclasses.replace(
        _tiny_cfg(tmp_path / "b", epochs=1),
        parallel=ParallelConfig(packed_state=True),
    )
    tr_p = _tiny_trainer(cfg_p)
    assert tr_p.packed
    m_p = tr_p.training(0)
    v_p = tr_p.val_test(0, "val")

    # Same data order (seeded loader) + numerically identical step
    # (tests/test_packed_step.py) => same epoch metrics and eval result.
    assert m_p["loss"] == pytest.approx(m["loss"], rel=1e-5)
    assert v_p["epe3d"] == pytest.approx(v["epe3d"], rel=1e-4)
    # And the packed trainer resumes through the pack/unpack boundary.
    last = os.path.join(cfg_p.exp_path, "checkpoints", "last_checkpoint.msgpack")
    tr_p.load_weights(last, resume=True)
    assert tr_p.begin_epoch == 1


@pytest.mark.slow
def test_trainer_steps_per_dispatch_matches_single(tmp_path):
    """A fused-dispatch epoch (K=2, including a tail batch when the epoch
    length is odd) must reproduce the K=1 packed epoch: same per-step
    losses, same val metrics."""
    import dataclasses

    from pvraft_tpu.config import ParallelConfig

    def mk(path, **par):
        c = _tiny_cfg(path, epochs=1)
        # 6 samples / bs=2 -> 3 steps: K=2 exercises one fused dispatch
        # AND the odd tail batch through the single packed step.
        return dataclasses.replace(
            c,
            data=dataclasses.replace(c.data, synthetic_size=6),
            parallel=ParallelConfig(packed_state=True, **par),
        )

    cfg = mk(tmp_path / "a")
    tr = _tiny_trainer(cfg)
    m = tr.training(0)
    v = tr.val_test(0, "val")

    cfg_f = mk(tmp_path / "b", steps_per_dispatch=2)
    tr_f = _tiny_trainer(cfg_f)
    assert hasattr(tr_f, "multi_step")
    m_f = tr_f.training(0)
    v_f = tr_f.val_test(0, "val")

    assert m_f["loss"] == pytest.approx(m["loss"], rel=1e-5)
    assert v_f["epe3d"] == pytest.approx(v["epe3d"], rel=1e-4)


@pytest.mark.slow
def test_trainer_val_sharded_matches_bs1_protocol(tmp_path):
    """The trainer's per-epoch val loop shards eval_batch scenes over the
    mesh data axis (per-scene metrics); its means must equal the bs=1
    reference protocol's (tools/engine.py:197-198) up to float
    reassociation."""
    import dataclasses

    from pvraft_tpu.engine.trainer import Trainer
    from pvraft_tpu.parallel.mesh import replicate

    cfg = _tiny_cfg(tmp_path)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, batch_size=1)
    )
    tr_sharded = Trainer(cfg, mesh=make_mesh(n_data=4))  # eval_batch 0 -> 4
    assert tr_sharded.eval_batch == 4

    cfg1 = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, eval_batch=1),
        exp_path=str(tmp_path / "exp_bs1"),
    )
    tr_bs1 = Trainer(cfg1, mesh=make_mesh(n_data=1))
    assert tr_bs1.eval_batch == 1
    # Identical weights in both trainers so the comparison is pure loop
    # semantics.
    host = jax.tree_util.tree_map(np.asarray, tr_sharded.params)
    tr_bs1.params = replicate(host, tr_bs1.mesh)

    m_sharded = tr_sharded.val_test(0, "val")
    m_bs1 = tr_bs1.val_test(0, "val")
    assert set(m_sharded) == set(m_bs1)
    for k in m_bs1:
        np.testing.assert_allclose(m_sharded[k], m_bs1[k], rtol=1e-5,
                                   atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_trainer_host_roundtrip_matches_packed(tmp_path):
    """--host_roundtrip round-trips the flat state through the host each
    step; the floats must be bit-identical to the plain packed loop."""
    import dataclasses

    from pvraft_tpu.engine.trainer import Trainer

    cfg = _tiny_cfg(tmp_path)
    cfg_p = dataclasses.replace(
        cfg, parallel=dataclasses.replace(cfg.parallel, packed_state=True)
    )
    cfg_rt = dataclasses.replace(
        cfg_p,
        parallel=dataclasses.replace(cfg_p.parallel, host_roundtrip=True),
        exp_path=str(tmp_path / "exp_rt"),
    )
    tr_p = Trainer(cfg_p, mesh=make_mesh(n_data=1))
    tr_rt = Trainer(cfg_rt, mesh=make_mesh(n_data=1))
    m_p = tr_p.training(0)
    m_rt = tr_rt.training(0)
    assert np.isclose(m_p["loss"], m_rt["loss"])
    for a, b in zip(jax.tree_util.tree_leaves(tr_p.params),
                    jax.tree_util.tree_leaves(tr_rt.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_host_roundtrip_requires_packed():
    from pvraft_tpu.config import ParallelConfig

    with pytest.raises(ValueError, match="packed_state"):
        ParallelConfig(host_roundtrip=True)


def test_trainer_rejects_multistep_multiprocess(tmp_path, monkeypatch):
    """steps_per_dispatch>1 stacks device batches eagerly — illegal on
    non-fully-addressable arrays in multi-process JAX, so construction
    must fail fast (ADVICE.md)."""
    import dataclasses

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(parallel=dataclasses.replace(
        cfg.parallel, packed_state=True, steps_per_dispatch=2))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        _tiny_trainer(cfg)
    # Single-process is unaffected (covered end-to-end elsewhere; here
    # just the guard's polarity).
    monkeypatch.undo()
    _tiny_trainer(cfg)


def test_evaluator_eval_scan_falls_back_multiprocess(tmp_path, monkeypatch):
    """eval_scan>1 also stacks eagerly; the per-batch path is protocol-
    identical, so the Evaluator downgrades instead of failing."""
    import dataclasses

    from pvraft_tpu.engine.evaluator import Evaluator

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, eval_batch=2, eval_scan=2))
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    ev = Evaluator(cfg, mesh=make_mesh(n_data=1))
    assert ev.eval_scan == 1
    assert not hasattr(ev, "eval_scan_step")


def test_trainer_grad_dtype_bf16_end_to_end(tmp_path):
    """The bf16-gradient lever trains: loss finite, params move."""
    import dataclasses

    cfg = _tiny_cfg(tmp_path)
    cfg = cfg.replace(train=dataclasses.replace(
        cfg.train, grad_dtype="bfloat16"))
    tr = _tiny_trainer(cfg)
    before = jax.tree_util.tree_map(np.asarray, tr.params)
    out = tr.training(0)
    assert np.isfinite(out["loss"])
    moved = any(
        not np.array_equal(a, np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(before),
                        jax.tree_util.tree_leaves(tr.params))
    )
    assert moved
