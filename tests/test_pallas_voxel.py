"""Pallas voxel kernel vs the XLA fallback (interpret mode on CPU)."""

import numpy as np
import jax
import pytest

pytestmark = pytest.mark.slow  # interpret-mode Pallas on CPU (~1.5 min)
import jax.numpy as jnp

from pvraft_tpu.ops.voxel import voxel_bin_means
from pvraft_tpu.ops.pallas.voxel_corr import voxel_bin_means_pallas


def _data(seed, b=2, n=16, k=24):
    rng = np.random.default_rng(seed)
    corr = rng.normal(size=(b, n, k)).astype(np.float32)
    rel = rng.uniform(-1.5, 1.5, size=(b, n, k, 3)).astype(np.float32)
    return jnp.asarray(corr), jnp.asarray(rel)


def test_pallas_matches_fallback():
    corr, rel = _data(0)
    got = np.asarray(voxel_bin_means_pallas(corr, rel, 3, 0.25, 3))
    want = np.asarray(voxel_bin_means(corr, rel, 3, 0.25, 3))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pallas_odd_tile_sizes():
    corr, rel = _data(1, b=1, n=10, k=8)   # n with no multiple-of-8 divisor > 2
    got = np.asarray(voxel_bin_means_pallas(corr, rel, 2, 0.5, 3))
    want = np.asarray(voxel_bin_means(corr, rel, 2, 0.5, 3))
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_pallas_gradient_matches_fallback():
    corr, rel = _data(2)

    def f_pallas(c):
        return jnp.sum(voxel_bin_means_pallas(c, rel, 3, 0.25, 3) ** 2)

    def f_ref(c):
        return jnp.sum(voxel_bin_means(c, rel, 3, 0.25, 3) ** 2)

    g1 = np.asarray(jax.grad(f_pallas)(corr))
    g2 = np.asarray(jax.grad(f_ref)(corr))
    np.testing.assert_allclose(g1, g2, atol=1e-4)


def test_pallas_no_gradient_to_rel():
    corr, rel = _data(3)

    def f(r):
        return jnp.sum(voxel_bin_means_pallas(corr, r, 2, 0.25, 3))

    g = np.asarray(jax.grad(f)(rel))
    np.testing.assert_array_equal(g, 0.0)


def test_model_with_pallas_flag():
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models.raft import PVRaft

    rng = np.random.default_rng(4)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 32, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 32, 3)).astype(np.float32))
    cfg = ModelConfig(truncate_k=8, corr_knn=4, graph_k=4)
    cfgp = ModelConfig(truncate_k=8, corr_knn=4, graph_k=4, use_pallas=True)
    params = PVRaft(cfg).init(jax.random.key(0), xyz1, xyz2, 2)
    f_ref, _ = PVRaft(cfg).apply(params, xyz1, xyz2, num_iters=2)
    f_pal, _ = PVRaft(cfgp).apply(params, xyz1, xyz2, num_iters=2)
    np.testing.assert_allclose(np.asarray(f_ref), np.asarray(f_pal), atol=1e-5)
