"""gate runner: content-hash caching (both directions), dependency
ordering and dep-failure skips, parallel output isolation, env and
virtual-device pins, --changed-only against a real git tree, the
pvraft_gate/v1 report validator red/green, committed-report discipline,
and the stage-set identity pin between the registry and the real
lint.sh/ci.yml manifests."""

import json
import os
import subprocess

from pvraft_tpu.analysis.gate.runner import (
    check_report_file,
    expand_inputs,
    run_gate,
    stage_cache_key,
    validate_gate_report,
)
from pvraft_tpu.analysis.gate.stages import (
    GATE_STAGES,
    GateStage,
    parse_manifest,
    stage_names,
    stage_problems,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stage(name, command, inputs=(), **kw):
    return GateStage(name=name, command=command, inputs=tuple(inputs), **kw)


def _statuses(report):
    return {r["name"]: r["status"] for r in report["stages"]}


# ------------------------------------------------------------- caching ---


def test_cache_hits_when_inputs_unchanged_and_misses_on_edit(tmp_path):
    root = str(tmp_path)
    (tmp_path / "input.txt").write_text("v1\n", encoding="utf-8")
    stages = [_stage("copy", "cat input.txt > out.txt", ["input.txt"])]

    first = run_gate(root=root, stages=stages, echo=lambda _line: None)
    assert _statuses(first) == {"copy": "ok"}

    second = run_gate(root=root, stages=stages, echo=lambda _line: None)
    assert _statuses(second) == {"copy": "cached"}
    assert second["stages"][0]["duration_s"] == 0.0

    (tmp_path / "input.txt").write_text("v2\n", encoding="utf-8")
    third = run_gate(root=root, stages=stages, echo=lambda _line: None)
    assert _statuses(third) == {"copy": "ok"}
    assert (tmp_path / "out.txt").read_text(encoding="utf-8") == "v2\n"


def test_failed_stage_is_never_cached(tmp_path):
    root = str(tmp_path)
    stages = [_stage("bad", "exit 3")]
    for _ in range(2):
        report = run_gate(root=root, stages=stages, echo=lambda _line: None)
        assert _statuses(report) == {"bad": "failed"}
        assert report["ok"] is False
    record = report["stages"][0]
    assert record["returncode"] == 3


def test_cache_key_covers_command_env_and_content(tmp_path):
    root = str(tmp_path)
    (tmp_path / "a.txt").write_text("x", encoding="utf-8")
    base = _stage("s", "true", ["a.txt"])
    key = stage_cache_key(root, base, ["a.txt"])
    assert stage_cache_key(root, base, ["a.txt"]) == key
    assert stage_cache_key(
        root, _stage("s", "false", ["a.txt"]), ["a.txt"]
    ) != key
    assert stage_cache_key(
        root, _stage("s", "true", ["a.txt"], env=(("K", "v"),)), ["a.txt"]
    ) != key
    (tmp_path / "a.txt").write_text("y", encoding="utf-8")
    assert stage_cache_key(root, base, ["a.txt"]) != key


def test_no_cache_mode_always_runs_and_writes_no_cache(tmp_path):
    root = str(tmp_path)
    stages = [_stage("s", "true")]
    for _ in range(2):
        report = run_gate(
            root=root, stages=stages, use_cache=False, echo=lambda _line: None
        )
        assert _statuses(report) == {"s": "ok"}
    assert not os.path.isdir(os.path.join(root, ".gate_cache"))


# -------------------------------------------------------- dependencies ---


def test_dependency_runs_before_dependent(tmp_path):
    root = str(tmp_path)
    stages = [
        _stage("b", "echo b >> order.txt", deps=("a",)),
        _stage("a", "echo a >> order.txt"),
    ]
    report = run_gate(
        root=root, stages=stages, jobs=4, use_cache=False,
        echo=lambda _line: None,
    )
    assert report["ok"] is True
    order = (tmp_path / "order.txt").read_text(encoding="utf-8").split()
    assert order == ["a", "b"]


def test_failed_dependency_skips_dependents_with_reason(tmp_path):
    root = str(tmp_path)
    stages = [
        _stage("a", "exit 1"),
        _stage("b", "true", deps=("a",)),
        _stage("c", "true", deps=("b",)),
    ]
    report = run_gate(
        root=root, stages=stages, use_cache=False, echo=lambda _line: None
    )
    assert _statuses(report) == {"a": "failed", "b": "skipped", "c": "skipped"}
    by_name = {r["name"]: r for r in report["stages"]}
    assert "dependency not green: a" in by_name["b"]["reason"]
    assert report["counts"] == {"ok": 0, "cached": 0, "failed": 1,
                                "skipped": 2}


def test_only_selection_runs_exactly_those_stages(tmp_path):
    root = str(tmp_path)
    stages = [
        _stage("a", "echo a >> order.txt"),
        _stage("b", "echo b >> order.txt", deps=("a",)),
    ]
    report = run_gate(
        root=root, stages=stages, only=("b",), use_cache=False,
        echo=lambda _line: None,
    )
    assert _statuses(report) == {"b": "ok"}
    order = (tmp_path / "order.txt").read_text(encoding="utf-8").split()
    assert order == ["b"]


def test_parallel_stage_output_is_not_interleaved(tmp_path):
    root = str(tmp_path)
    stages = [
        _stage("one", "echo one-1; echo one-2; echo one-3"),
        _stage("two", "echo two-1; echo two-2; echo two-3"),
    ]
    lines = []
    report = run_gate(
        root=root, stages=stages, jobs=2, use_cache=False, verbose=True,
        echo=lines.append,
    )
    assert report["ok"] is True
    # Each stage's buffered output appears as one contiguous block —
    # never mixed with the other stage's lines.
    owners = [
        line.strip().split("-", 1)[0]
        for line in lines
        if line.strip().startswith(("one-", "two-"))
    ]
    assert sorted(owners) == ["one"] * 3 + ["two"] * 3
    runs = 1 + sum(1 for a, b in zip(owners, owners[1:]) if a != b)
    assert runs == 2


# ------------------------------------------------------- env & devices ---


def test_env_pin_and_virtual_devices_reach_the_stage(tmp_path):
    root = str(tmp_path)
    stages = [
        _stage(
            "env-probe",
            'printf "%s|%s" "$JAX_PLATFORMS" "$XLA_FLAGS" > probe.txt',
            env=(("JAX_PLATFORMS", "cpu"),),
            virtual_devices=8,
        ),
    ]
    report = run_gate(
        root=root, stages=stages, use_cache=False, echo=lambda _line: None
    )
    assert report["ok"] is True
    probe = (tmp_path / "probe.txt").read_text(encoding="utf-8")
    platform, flags = probe.split("|")
    assert platform == "cpu"
    assert "--xla_force_host_platform_device_count=8" in flags


def test_expand_inputs_prunes_ephemeral_and_dirs(tmp_path):
    (tmp_path / "artifacts" / "xla_cache").mkdir(parents=True)
    (tmp_path / "artifacts" / "xla_cache" / "blob").write_text("x")
    (tmp_path / "artifacts" / "real.json").write_text("{}")
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "m.py").write_text("pass\n")
    rels = expand_inputs(str(tmp_path), ["artifacts/**", "pkg/**/*.py"])
    assert rels == ["artifacts/real.json", "pkg/m.py"]


# --------------------------------------------------------- changed-only --


def test_changed_only_skips_unchanged_and_runs_changed(tmp_path):
    root = str(tmp_path)
    (tmp_path / "input.txt").write_text("v1\n", encoding="utf-8")
    git = ["git", "-C", root, "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "-C", root, "init", "-q"], check=True)
    subprocess.run(["git", "-C", root, "add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "seed"], check=True)

    stages = [_stage("copy", "cat input.txt > out.txt", ["input.txt"])]
    report = run_gate(
        root=root, stages=stages, changed_only=True, use_cache=False,
        echo=lambda _line: None,
    )
    assert _statuses(report) == {"copy": "skipped"}
    assert "no changed input" in report["stages"][0]["reason"]
    assert report["changed_only"] is True

    (tmp_path / "input.txt").write_text("v2\n", encoding="utf-8")
    report = run_gate(
        root=root, stages=stages, changed_only=True, use_cache=False,
        echo=lambda _line: None,
    )
    assert _statuses(report) == {"copy": "ok"}


def test_changed_only_skip_of_dep_still_satisfies_dependents(tmp_path):
    """An unchanged dependency's previous green result stands: its
    --changed-only skip must not cascade into a dependency-not-green
    skip of a dependent whose own inputs DID change."""
    root = str(tmp_path)
    (tmp_path / "dep_in.txt").write_text("v1\n", encoding="utf-8")
    (tmp_path / "child_in.txt").write_text("v1\n", encoding="utf-8")
    git = ["git", "-C", root, "-c", "user.email=t@t", "-c", "user.name=t"]
    subprocess.run(["git", "-C", root, "init", "-q"], check=True)
    subprocess.run(["git", "-C", root, "add", "-A"], check=True)
    subprocess.run(git + ["commit", "-qm", "seed"], check=True)

    stages = [
        _stage("dep", "cat dep_in.txt > dep_out.txt", ["dep_in.txt"]),
        _stage("child", "cat child_in.txt > child_out.txt",
               ["child_in.txt"], deps=("dep",)),
    ]
    (tmp_path / "child_in.txt").write_text("v2\n", encoding="utf-8")
    report = run_gate(
        root=root, stages=stages, changed_only=True, use_cache=False,
        echo=lambda _line: None,
    )
    assert _statuses(report) == {"dep": "skipped", "child": "ok"}
    by_name = {r["name"]: r for r in report["stages"]}
    assert "no changed input" in by_name["dep"]["reason"]

    # A dep skipped because ITS dependency failed still cascades.
    stages = [
        _stage("bad", "exit 1", ["child_in.txt"]),
        _stage("mid", "true", ["child_in.txt"], deps=("bad",)),
        _stage("leaf", "true", ["child_in.txt"], deps=("mid",)),
    ]
    report = run_gate(
        root=root, stages=stages, changed_only=True, use_cache=False,
        echo=lambda _line: None,
    )
    assert _statuses(report) == {
        "bad": "failed", "mid": "skipped", "leaf": "skipped"
    }


# ----------------------------------------------------- report validator --


def test_validate_gate_report_green_then_tampered(tmp_path):
    root = str(tmp_path)
    stages = [_stage("a", "true"), _stage("b", "true", deps=("a",))]
    report = run_gate(
        root=root, stages=stages, use_cache=False, echo=lambda _line: None
    )
    assert validate_gate_report(report) == []

    bad = json.loads(json.dumps(report))
    bad["counts"]["ok"] = 99
    assert any("do not recompute" in p for p in validate_gate_report(bad))

    bad = json.loads(json.dumps(report))
    bad["stages"][0]["status"] = "failed"
    bad["counts"] = {"ok": 1, "cached": 0, "failed": 1, "skipped": 0}
    assert any("ok flag" in p for p in validate_gate_report(bad))

    bad = json.loads(json.dumps(report))
    bad["stages"][0]["duration_s"] = 50.0
    assert any("wall clock" in p for p in validate_gate_report(bad))

    bad = json.loads(json.dumps(report))
    del bad["total_s"]
    assert any("total_s" in p for p in validate_gate_report(bad))


def test_check_report_file_discipline(tmp_path):
    root = str(tmp_path)
    (tmp_path / "in.txt").write_text("x", encoding="utf-8")
    stages = [_stage("a", "true", ["in.txt"]), _stage("b", "true", ["in.txt"])]
    report = run_gate(
        root=root, stages=stages, use_cache=False, echo=lambda _line: None
    )
    path = tmp_path / "gate_report.json"
    path.write_text(json.dumps(report), encoding="utf-8")
    assert check_report_file(str(path), stages=stages) == []

    # A --changed-only or selected run is not committable evidence.
    partial = dict(report, changed_only=True)
    path.write_text(json.dumps(partial), encoding="utf-8")
    assert any("--changed-only" in p
               for p in check_report_file(str(path), stages=stages))

    partial = dict(report, only=["a"])
    path.write_text(json.dumps(partial), encoding="utf-8")
    assert any("selection" in p
               for p in check_report_file(str(path), stages=stages))

    # Stage-set identity: a report from another stage era is rejected.
    extra = stages + [_stage("c", "true")]
    assert any("missing from the report" in p
               for p in check_report_file(str(path), stages=extra))


def test_check_report_file_rejects_synthesized_records(tmp_path):
    """A report not produced by the runner — ok/cached rows with no
    input provenance and zero wall clock — is not committable evidence."""
    root = str(tmp_path)
    (tmp_path / "in.txt").write_text("x", encoding="utf-8")
    stages = [_stage("a", "true", ["in.txt"]), _stage("b", "true", ["in.txt"])]
    report = run_gate(
        root=root, stages=stages, use_cache=False, echo=lambda _line: None
    )
    path = tmp_path / "gate_report.json"

    fake = json.loads(json.dumps(report))
    for record in fake["stages"]:
        record.pop("input_hash", None)
        record["n_inputs"] = 0
        record["duration_s"] = 0.0
        record["status"] = "cached"
    fake["counts"] = {"ok": 0, "cached": 2, "failed": 0, "skipped": 0}
    fake["total_s"] = 0.0
    path.write_text(json.dumps(fake), encoding="utf-8")
    problems = check_report_file(str(path), stages=stages)
    assert any("total_s" in p for p in problems)
    assert any("n_inputs" in p for p in problems)
    assert any("input_hash" in p for p in problems)

    # Each provenance field is independently required.
    fake = json.loads(json.dumps(report))
    fake["stages"][0]["n_inputs"] = 0
    path.write_text(json.dumps(fake), encoding="utf-8")
    problems = check_report_file(str(path), stages=stages)
    assert any("n_inputs" in p for p in problems)
    assert not any("input_hash" in p for p in problems)

    fake = json.loads(json.dumps(report))
    fake["stages"][1]["input_hash"] = "not-a-hash"
    path.write_text(json.dumps(fake), encoding="utf-8")
    problems = check_report_file(str(path), stages=stages)
    assert any("input_hash" in p for p in problems)
    assert not any("n_inputs" in p for p in problems)

    # The real report still passes untouched.
    path.write_text(json.dumps(report), encoding="utf-8")
    assert check_report_file(str(path), stages=stages) == []


# --------------------------------------------------- stage-set identity --


def test_registry_is_well_formed():
    assert stage_problems(GATE_STAGES) == []
    names = stage_names()
    assert len(names) == len(set(names))
    assert len(GATE_STAGES) >= 25


def test_real_manifests_match_registry_exactly():
    declared = set(stage_names())
    for rel in ("scripts/lint.sh", ".github/workflows/ci.yml"):
        with open(os.path.join(REPO, rel), encoding="utf-8") as fh:
            entries = parse_manifest(fh.read())
        manifest_names = [name for _line, name in entries]
        assert len(manifest_names) == len(set(manifest_names)), rel
        assert set(manifest_names) == declared, rel


def test_registry_dependency_and_cycle_detection():
    bad = (
        _stage("a", "true", deps=("ghost",)),
        _stage("b", "true", deps=("c",)),
        _stage("c", "true", deps=("b",)),
        _stage("b", "true"),
    )
    problems = stage_problems(bad)
    assert any("ghost" in p for p in problems)
    assert any("more than once" in p for p in problems)
    cyc = (_stage("x", "true", deps=("y",)), _stage("y", "true", deps=("x",)))
    assert any("cycle" in p for p in stage_problems(cyc))
