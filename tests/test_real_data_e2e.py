"""End-to-end over on-disk dataset trees: FT3D training (native loader) and
zero-shot KITTI evaluation — the real-data paths the CLIs exercise."""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # trainer/evaluator e2e over on-disk trees

from pvraft_tpu.config import Config, DataConfig, ModelConfig, TrainConfig


def _make_ft3d_tree(root, n_train=6, n_test=2, n_points=96, seed=0):
    rng = np.random.default_rng(seed)
    for split, count in [("train", n_train), ("val", n_test)]:
        for i in range(count):
            scene = root / split / f"{i:07d}"
            scene.mkdir(parents=True)
            pc1 = rng.uniform(-1, 1, (n_points + 10 * i, 3)).astype(np.float32)
            pc2 = pc1 + rng.normal(0, 0.05, pc1.shape).astype(np.float32)
            np.save(scene / "pc1.npy", pc1)
            np.save(scene / "pc2.npy", pc2)


def _make_kitti_tree(root, n_points=128, seed=1):
    rng = np.random.default_rng(seed)
    for i in [2, 3, 7]:  # members of the 142-scene eval subset
        scene = root / f"{i:06d}"
        scene.mkdir(parents=True)
        pc1 = rng.uniform(-1, 1, (n_points, 3)).astype(np.float32)
        pc1[:, 2] = rng.uniform(1, 30, n_points)   # depths within 35 m
        pc1[:, 1] = rng.uniform(-1, 1, n_points)   # above ground
        pc2 = pc1 + rng.normal(0, 0.05, pc1.shape).astype(np.float32)
        np.save(scene / "pc1.npy", pc1)
        np.save(scene / "pc2.npy", pc2)


def test_ft3d_trainer_end_to_end(tmp_path):
    from pvraft_tpu.engine.trainer import Trainer

    _make_ft3d_tree(tmp_path / "data")
    cfg = Config(
        model=ModelConfig(truncate_k=16, corr_knn=8, graph_k=8),
        data=DataConfig(dataset="FT3D", root=str(tmp_path / "data"),
                        max_points=64, num_workers=2, strict_sizes=False),
        train=TrainConfig(batch_size=2, num_epochs=1, iters=2, eval_iters=2,
                          checkpoint_interval=1),
        exp_path=str(tmp_path / "exp"),
    )
    from pvraft_tpu.parallel.mesh import make_mesh

    tr = Trainer(cfg, mesh=make_mesh(n_data=1))  # 6-sample tree: 1-device mesh
    # The FT3D train loader must be on the native C++ path when available.
    from pvraft_tpu import native

    assert tr.train_loader.native == native.native_available()
    m = tr.training(0)
    v = tr.val_test(0, "val")
    assert np.isfinite(m["loss"])
    assert np.isfinite(v["epe3d"])
    assert os.path.exists(
        os.path.join(cfg.exp_path, "checkpoints", "last_checkpoint.msgpack")
    )


def test_kitti_evaluator_end_to_end(tmp_path):
    from pvraft_tpu.engine.evaluator import Evaluator

    _make_kitti_tree(tmp_path / "kitti")
    cfg = Config(
        model=ModelConfig(truncate_k=16, corr_knn=8, graph_k=8),
        data=DataConfig(dataset="KITTI", root=str(tmp_path / "kitti"),
                        max_points=64, num_workers=0, strict_sizes=False),
        train=TrainConfig(eval_iters=2),
        exp_path=str(tmp_path / "exp"),
    )
    ev = Evaluator(cfg)
    means = ev.run()
    assert len(ev.dataset) == 3
    for k in ("epe3d", "acc3d_strict", "acc3d_relax", "outlier"):
        assert k in means and np.isfinite(means[k])


def test_kitti_trainer_refuses(tmp_path):
    """Training on KITTI raises, matching tools/engine.py:40-41."""
    from pvraft_tpu.engine.trainer import build_datasets

    cfg = Config(
        data=DataConfig(dataset="KITTI", root=str(tmp_path)),
    )
    with pytest.raises(NotImplementedError):
        build_datasets(cfg)
