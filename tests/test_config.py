"""Config validation and CLI argument plumbing."""

import pytest

from pvraft_tpu.config import Config, ModelConfig, compute_dtype, tiny_config


def test_corr_knn_validation():
    with pytest.raises(ValueError, match="corr_knn"):
        ModelConfig(truncate_k=16, corr_knn=32)
    ModelConfig(truncate_k=32, corr_knn=32)  # boundary OK


def test_seq_shard_rejects_contradictory_corr_knobs():
    # The ring path would silently ignore approx_topk / corr_chunk
    # (models/raft.py routes past them); the config must refuse instead
    # (PARITY.md "Correlation-path config matrix").
    with pytest.raises(ValueError, match="approx_topk.*seq_shard"):
        ModelConfig(approx_topk=True, seq_shard=True)
    with pytest.raises(ValueError, match="corr_chunk.*seq_shard"):
        ModelConfig(corr_chunk=1024, seq_shard=True)
    ModelConfig(seq_shard=True)  # alone: fine
    ModelConfig(approx_topk=True)  # alone: fine
    import dataclasses

    # replace() re-runs validation on frozen dataclasses.
    with pytest.raises(ValueError, match="seq_shard"):
        dataclasses.replace(ModelConfig(approx_topk=True), seq_shard=True)


def test_compute_dtype_mapping():
    import jax.numpy as jnp

    assert compute_dtype(ModelConfig()) is None
    assert compute_dtype(ModelConfig(compute_dtype="bfloat16")) == jnp.bfloat16


def test_tiny_config_valid():
    cfg = tiny_config()
    assert cfg.data.dataset == "synthetic"
    assert cfg.model.corr_knn <= cfg.model.truncate_k


def test_cli_config_roundtrip():
    import train as train_cli

    args = train_cli.parse_args(
        ["--dataset", "synthetic", "--truncate_k", "64", "--corr_knn", "16",
         "--bf16", "--use_pallas", "--approx_topk", "--corr_chunk", "128",
         "--graph_chunk", "256", "--remat", "--lr_schedule", "cosine",
         "--no_strict_sizes"]
    )
    cfg = train_cli.config_from_args(args)
    assert cfg.model.truncate_k == 64
    assert cfg.model.corr_knn == 16
    assert cfg.model.compute_dtype == "bfloat16"
    assert cfg.model.use_pallas and cfg.model.approx_topk and cfg.model.remat
    assert cfg.model.corr_chunk == 128 and cfg.model.graph_chunk == 256
    assert cfg.train.lr_schedule == "cosine"
    assert not cfg.data.strict_sizes


def test_cli_test_config_roundtrip():
    import test as test_cli

    args = test_cli.parse_args(
        ["--dataset", "KITTI", "--truncate_k", "32", "--corr_knn", "8",
         "--eval_iters", "4", "--refine", "--bf16"]
    )
    # The config is built inside main(); replicate the construction here by
    # checking the parsed namespace drives ModelConfig without error.
    cfg = ModelConfig(
        truncate_k=args.truncate_k, corr_knn=args.corr_knn,
        compute_dtype="bfloat16" if args.bf16 else "float32",
    )
    assert cfg.truncate_k == 32 and cfg.corr_knn == 8
    assert args.refine and args.eval_iters == 4


def test_use_pallas_auto_default_resolves_by_platform():
    """use_pallas=None means Pallas-on-TPU / XLA-elsewhere; on the CPU
    test backend it must resolve False (the oracle path), and explicit
    settings must pass through untouched."""
    from pvraft_tpu.config import ModelConfig, resolve_use_pallas

    assert ModelConfig().use_pallas is None
    assert resolve_use_pallas(ModelConfig()) is False  # CPU backend here
    assert resolve_use_pallas(ModelConfig(use_pallas=True)) is True
    assert resolve_use_pallas(ModelConfig(use_pallas=False)) is False
