"""utils/logging: TBWriter in-memory fallback + exception-safe close,
ExperimentLog handler dedup + close (the file-descriptor leak across
Trainer re-instantiations)."""

import logging
import os

from pvraft_tpu.utils.logging import ExperimentLog, TBWriter


def test_tbwriter_in_memory_history(tmp_path, monkeypatch):
    # Even with a real backend importable, history records everything —
    # and with the import broken the writer must degrade, not die.
    import builtins

    real_import = builtins.__import__

    def no_torch(name, *a, **k):
        if name.startswith("torch"):
            raise ImportError("forced for test")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_torch)
    tb = TBWriter(str(tmp_path / "logs"))
    assert tb._writer is None
    tb.add_scalar("Train/Loss", 1.5, 1)
    tb.add_scalar("Train/Loss", 1.25, 2)
    assert tb.history["Train/Loss"] == [(1, 1.5), (2, 1.25)]
    tb.close()  # no-op without a backend


def test_tbwriter_close_is_exception_safe_and_idempotent(tmp_path):
    tb = TBWriter(str(tmp_path / "logs"))
    tb.add_scalar("x", 1.0, 0)

    class Dying:
        def flush(self):
            raise RuntimeError("disk full")

        def close(self):
            raise RuntimeError("already torn down")

    tb._writer = Dying()
    tb.close()  # must not raise
    assert tb._writer is None
    tb.close()  # idempotent
    assert tb.history["x"] == [(0, 1.0)]


def test_experimentlog_handler_dedup(tmp_path):
    exp = str(tmp_path / "exp")
    a = ExperimentLog(exp, "Train", "synthetic")
    n = len(a.logger.handlers)
    b = ExperimentLog(exp, "Train", "synthetic")
    # Same experiment dir + mode: the second instantiation must reuse
    # the handler, not stack a duplicate (double-logged lines).
    assert len(b.logger.handlers) == n
    a.close()


def test_experimentlog_close_releases_handlers(tmp_path):
    exp = str(tmp_path / "exp")
    log = ExperimentLog(exp, "Train", "synthetic")
    log.info("hello")
    assert any(isinstance(h, logging.FileHandler)
               for h in log.logger.handlers)
    log.close()
    assert not any(isinstance(h, logging.FileHandler)
                   for h in log.logger.handlers)
    log.close()  # idempotent
    # A fresh instance re-attaches exactly one handler and logs fine.
    log2 = ExperimentLog(exp, "Train", "synthetic")
    assert sum(isinstance(h, logging.FileHandler)
               for h in log2.logger.handlers) == 1
    log2.info("again")
    log2.close()
    path = os.path.join(exp, "logs", "Train_synthetic.log")
    with open(path) as f:
        content = f.read()
    assert "hello" in content and "again" in content
