"""Data pipeline tests: subsampling, collation, loaders, FT3D/KITTI on a
synthetic on-disk dataset."""

import os

import numpy as np
import pytest

from pvraft_tpu.data import (
    FT3D,
    KITTI,
    PrefetchLoader,
    SyntheticDataset,
    batches,
    collate,
)


def test_item_shapes_and_exact_n():
    ds = SyntheticDataset(size=4, nb_points=128, extra_points=64, seed=0)
    it = ds[0]
    assert it["pc1"].shape == (128, 3)
    assert it["pc2"].shape == (128, 3)
    assert it["mask"].shape == (128,)
    assert it["flow"].shape == (128, 3)
    assert it["pc1"].dtype == np.float32


def test_flow_follows_pc1_permutation():
    # With zero noise and no extra points the synthetic flow is pc2@R+t-pc1;
    # after independent subsampling flow must still correspond to pc1's rows.
    ds = SyntheticDataset(size=2, nb_points=64, seed=1)
    pc1_full, pc2_full, mask, flow_full = ds.load_sequence(0)
    it = ds[0]
    # every sampled (pc1, flow) row pair must exist in the full set
    full = {tuple(np.round(r, 5)) for r in np.concatenate([pc1_full, flow_full], 1)}
    got = {tuple(np.round(r, 5)) for r in np.concatenate([it["pc1"], it["flow"]], 1)}
    assert got <= full


def test_collate_stacks():
    ds = SyntheticDataset(size=4, nb_points=32, seed=2)
    b = collate([ds[0], ds[1], ds[2]])
    assert b["pc1"].shape == (3, 32, 3)
    assert b["mask"].shape == (3, 32)


def test_batches_lazy_and_epoch_reshuffle():
    ds = SyntheticDataset(size=8, nb_points=16, seed=3)
    b0 = [b["pc1"] for b in batches(ds, 2, shuffle=True, seed=5, epoch=0)]
    b0_again = [b["pc1"] for b in batches(ds, 2, shuffle=True, seed=5, epoch=0)]
    b1 = [b["pc1"] for b in batches(ds, 2, shuffle=True, seed=5, epoch=1)]
    assert len(b0) == 4
    np.testing.assert_allclose(np.stack(b0), np.stack(b0_again))
    assert not np.allclose(np.stack(b0), np.stack(b1))


def test_prefetch_loader_matches_serial():
    ds = SyntheticDataset(size=10, nb_points=16, seed=4)
    serial = list(batches(ds, 2, shuffle=True, seed=7, epoch=3))
    loader = PrefetchLoader(ds, 2, shuffle=True, num_workers=3, seed=7)
    threaded = list(loader.epoch(3))
    assert len(serial) == len(threaded) == len(loader)
    for a, b in zip(serial, threaded):
        for k in a:
            np.testing.assert_allclose(a[k], b[k])


def test_prefetch_loader_propagates_errors():
    class Broken(SyntheticDataset):
        def load_sequence(self, idx):
            raise RuntimeError("boom")

    ds = Broken(size=4, nb_points=16)
    loader = PrefetchLoader(ds, 2, num_workers=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(loader.epoch(0))


def _write_scene(path, n, rng):
    os.makedirs(path, exist_ok=True)
    pc1 = rng.uniform(-1, 1, (n, 3)).astype(np.float32)
    pc2 = pc1 + rng.normal(0, 0.05, (n, 3)).astype(np.float32)
    np.save(os.path.join(path, "pc1.npy"), pc1)
    np.save(os.path.join(path, "pc2.npy"), pc2)
    return pc1, pc2


def test_ft3d_loading_and_sign_flip(tmp_path):
    rng = np.random.default_rng(0)
    scenes = {}
    for i in range(10):
        scenes[i] = _write_scene(str(tmp_path / "train" / f"{i:07d}"), 64, rng)
    ds = FT3D(str(tmp_path), nb_points=32, mode="train", strict_sizes=False)
    val = FT3D(str(tmp_path), nb_points=32, mode="val", strict_sizes=False)
    assert len(ds) + len(val) == 10
    pc1, pc2, mask, flow = ds.load_sequence(0)
    scene_idx = int(os.path.basename(ds.filenames[0]))
    raw1, raw2 = scenes[scene_idx]
    np.testing.assert_allclose(pc1[:, 0], -raw1[:, 0])  # x flip
    np.testing.assert_allclose(pc1[:, 1], raw1[:, 1])   # y kept
    np.testing.assert_allclose(pc1[:, 2], -raw1[:, 2])  # z flip
    np.testing.assert_allclose(flow, pc2 - pc1, atol=1e-6)
    assert mask.min() == 1.0


def test_ft3d_train_val_disjoint(tmp_path):
    rng = np.random.default_rng(1)
    for i in range(10):
        _write_scene(str(tmp_path / "train" / f"{i:07d}"), 16, rng)
    tr = FT3D(str(tmp_path), 8, "train", strict_sizes=False)
    va = FT3D(str(tmp_path), 8, "val", strict_sizes=False)
    assert set(tr.filenames).isdisjoint(va.filenames)


def test_kitti_filters(tmp_path):
    rng = np.random.default_rng(2)
    # Scene dirs named by index; only some are in the 142-scene eval set.
    for i in [2, 3, 4, 5, 7]:  # 2,3,7 in eval set; 4,5 not
        path = str(tmp_path / f"{i:06d}")
        os.makedirs(path)
        n = 64
        pc1 = rng.uniform(-1, 1, (n, 3)).astype(np.float32)
        pc2 = pc1 + 0.01
        # Make a few ground points (y < -1.4 in both) and far points (z>=35).
        pc1[:4, 1] = pc2[:4, 1] = -2.0
        pc1[4:8, 2] = 40.0
        np.save(os.path.join(path, "pc1.npy"), pc1)
        np.save(os.path.join(path, "pc2.npy"), pc2)
    ds = KITTI(str(tmp_path), nb_points=16, strict_sizes=False)
    assert [int(os.path.basename(p)) for p in ds.paths] == [2, 3, 7]
    pc1, pc2, mask, flow = ds.load_sequence(0)
    assert pc1.shape[0] == 64 - 8  # ground + far removed
    assert (pc1[:, 2] < 35).all()


def test_loader_shard_disjoint_and_covering():
    """shard=(rank, world) splits each (identically shuffled) epoch into
    disjoint per-rank sample sets covering the dataset — the multi-host
    epoch split (DistributedSampler's role)."""
    from pvraft_tpu.data import PrefetchLoader, SyntheticDataset

    ds = SyntheticDataset(size=12, nb_points=32, seed=0)
    world = 3
    seen = []
    for rank in range(world):
        loader = PrefetchLoader(ds, 2, shuffle=True, num_workers=0,
                                seed=7, shard=(rank, world))
        assert len(loader) == 2  # 4 local samples / batch 2
        ids = []
        for b in loader.epoch(0):
            assert b["pc1"].shape == (2, 32, 3)
            ids.append(b["pc1"][:, 0, :].copy())
        seen.append(np.concatenate(ids))
    flat = np.concatenate(seen)
    # All 12 samples appear exactly once across ranks (rows unique).
    assert flat.shape[0] == 12
    assert len(np.unique(np.round(flat, 6), axis=0)) == 12

    with pytest.raises(ValueError):
        PrefetchLoader(ds, 2, shard=(3, 3))

    # Uneven dataset: every rank still gets the SAME batch count (epoch
    # truncated to a multiple of world) — unequal per-rank step counts
    # would deadlock multi-host collectives.
    ds13 = SyntheticDataset(size=13, nb_points=32, seed=0)
    counts = []
    for rank in range(world):
        loader = PrefetchLoader(ds13, 2, shuffle=True, num_workers=0,
                                seed=7, shard=(rank, world))
        counts.append((len(loader), sum(1 for _ in loader.epoch(0))))
    assert counts == [(2, 2)] * world


def test_loader_shard_rejects_indivisible_drop_last_false():
    """Sharded epochs keep only full global batches; with drop_last=False
    and an indivisible dataset that would silently skip tail samples
    (biased eval means) — the loader must refuse up front."""
    from pvraft_tpu.data import PrefetchLoader, SyntheticDataset

    ds13 = SyntheticDataset(size=13, nb_points=32, seed=0)
    with pytest.raises(ValueError, match="drop_last"):
        PrefetchLoader(ds13, 2, drop_last=False, num_workers=0, shard=(0, 3))
    # Exactly divisible: allowed (the eval_scene_shard pattern).
    ds12 = SyntheticDataset(size=12, nb_points=32, seed=0)
    PrefetchLoader(ds12, 2, drop_last=False, num_workers=0, shard=(0, 3))
    # Unsharded: drop_last=False keeps its normal meaning.
    PrefetchLoader(ds13, 2, drop_last=False, num_workers=0)


def test_device_prefetch_order_and_pipelining():
    """device_prefetch yields every item in order and issues the put for
    the NEXT item before the current one is consumed (the H2D overlap)."""
    from pvraft_tpu.data.loader import device_prefetch

    put_log = []

    def put(x):
        put_log.append(x)
        return x * 10

    out = []
    ahead = []
    for y in device_prefetch(iter(range(6)), put, depth=2):
        ahead.append(len(put_log) - len(out))
        out.append(y)
    assert out == [x * 10 for x in range(6)]
    # While the stream is live the put side runs one batch ahead.
    assert all(a >= 2 for a in ahead[:4]), ahead

    # depth=1 degenerates to the unpipelined loop, still order-preserving.
    assert list(device_prefetch(iter(range(4)), lambda x: x, depth=1)) == [0, 1, 2, 3]
    assert list(device_prefetch(iter([]), lambda x: x)) == []


def test_synthetic_multi_object_scenes():
    """n_objects>1 produces piecewise-rigid scenes: per-point flows are
    index-aligned (flow == pc2 - pc1), deterministic per (seed, idx), and
    genuinely multi-motion (flow variance far above the rigid case)."""
    from pvraft_tpu.data.synthetic import SyntheticDataset

    ds = SyntheticDataset(size=4, nb_points=256, n_objects=3, seed=5)
    pc1, pc2, mask, flow = ds.load_sequence(0)
    assert pc1.shape == (256, 3) and flow.shape == (256, 3)
    np.testing.assert_allclose(flow, pc2 - pc1, atol=1e-6)
    assert mask.all()

    # Deterministic per (seed, idx).
    again = SyntheticDataset(size=4, nb_points=256, n_objects=3, seed=5)
    np.testing.assert_array_equal(again.load_sequence(0)[0], pc1)

    # Multiple independent motions: a single rigid (affine-in-position)
    # model must NOT explain the flow field. Fit flow ~ A @ x + b by
    # least squares; the rigid scene's residual is ~0, the multi-object
    # scene's is large.
    def affine_residual(pts, fl):
        X = np.concatenate([pts, np.ones((len(pts), 1), np.float32)], axis=1)
        coef, *_ = np.linalg.lstsq(X, fl, rcond=None)
        return float(np.abs(fl - X @ coef).mean())

    rigid = SyntheticDataset(size=4, nb_points=256, n_objects=1, seed=5)
    r1, _, _, f_rigid = rigid.load_sequence(0)
    assert affine_residual(r1, f_rigid) < 1e-3
    # Absolute floor: the motions must genuinely differ per object, not
    # merely exceed float noise (measured: rigid ~2e-8, multi ~0.05).
    assert affine_residual(pc1, flow) > 0.01

    with pytest.raises(ValueError, match="n_objects"):
        SyntheticDataset(n_objects=0)
