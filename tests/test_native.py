"""Native data-plane tests (built on demand with g++; skipped without it)."""

import os
import shutil

import numpy as np
import pytest

native = pytest.importorskip("pvraft_tpu.native")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and not native.native_available(),
    reason="no compiler and no prebuilt native library",
)


def test_build_and_available():
    assert native.native_available()


def test_npy_read_f32(tmp_path):
    arr = np.random.default_rng(0).normal(size=(37, 3)).astype(np.float32)
    p = str(tmp_path / "a.npy")
    np.save(p, arr)
    got = native.npy_read(p)
    np.testing.assert_array_equal(got, arr)
    assert native.npy_shape(p) == (37, 3)


def test_npy_read_f64_converts(tmp_path):
    arr = np.random.default_rng(1).normal(size=(5, 3))
    p = str(tmp_path / "b.npy")
    np.save(p, arr)
    got = native.npy_read(p)
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, arr.astype(np.float32), atol=1e-6)


def test_load_scene_batch(tmp_path):
    rng = np.random.default_rng(2)
    paths1, paths2 = [], []
    fulls = []
    for i in range(3):
        pc1 = rng.normal(size=(50 + i * 10, 3)).astype(np.float32)
        pc2 = pc1 + 0.5
        p1 = str(tmp_path / f"s{i}_pc1.npy")
        p2 = str(tmp_path / f"s{i}_pc2.npy")
        np.save(p1, pc1)
        np.save(p2, pc2)
        paths1.append(p1)
        paths2.append(p2)
        fulls.append((pc1, pc2))

    n_pts = 32
    pc1, pc2, mask, flow, status = native.load_scene_batch(
        paths1, paths2, [0, 1, 2], n_pts, 256, seed=7, epoch=0,
        flip_xz=False, n_threads=2,
    )
    assert status.tolist() == [1, 1, 1]
    assert pc1.shape == (3, n_pts, 3)
    np.testing.assert_array_equal(mask, 1.0)
    for i in range(3):
        full1, full2 = fulls[i]
        # every sampled pc1 row exists in the full cloud
        full_set = {tuple(np.round(r, 5)) for r in full1}
        got_set = {tuple(np.round(r, 5)) for r in pc1[i]}
        assert got_set <= full_set
        # no duplicate rows (sampling without replacement)
        assert len(got_set) == n_pts
        # flow is index-aligned with pc1's sampling: pc2_full - pc1_full = 0.5
        np.testing.assert_allclose(flow[i], 0.5, atol=1e-6)


def test_load_scene_batch_flip_and_reject(tmp_path):
    rng = np.random.default_rng(3)
    big = rng.normal(size=(64, 3)).astype(np.float32)
    small = rng.normal(size=(8, 3)).astype(np.float32)
    for name, arr in [("big_pc1", big), ("big_pc2", big + 1),
                      ("small_pc1", small), ("small_pc2", small)]:
        np.save(str(tmp_path / f"{name}.npy"), arr)

    pc1, _, _, _, status = native.load_scene_batch(
        [str(tmp_path / "big_pc1.npy"), str(tmp_path / "small_pc1.npy")],
        [str(tmp_path / "big_pc2.npy"), str(tmp_path / "small_pc2.npy")],
        [0, 1], 32, 256, seed=1, epoch=0, flip_xz=True, n_threads=1,
    )
    assert status.tolist() == [1, 0]  # small scene rejected
    # flip applied to x and z, not y: the sampled rows must be in the
    # flipped full set.
    flipped = big.copy()
    flipped[:, 0] *= -1
    flipped[:, 2] *= -1
    full_set = {tuple(np.round(r, 5)) for r in flipped}
    got_set = {tuple(np.round(r, 5)) for r in pc1[0]}
    assert got_set <= full_set


def test_determinism_across_calls(tmp_path):
    rng = np.random.default_rng(4)
    pc = rng.normal(size=(40, 3)).astype(np.float32)
    np.save(str(tmp_path / "pc1.npy"), pc)
    np.save(str(tmp_path / "pc2.npy"), pc + 1)
    args = ([str(tmp_path / "pc1.npy")], [str(tmp_path / "pc2.npy")], [5],
            16, 64)
    a = native.load_scene_batch(*args, seed=9, epoch=3, flip_xz=False)
    b = native.load_scene_batch(*args, seed=9, epoch=3, flip_xz=False)
    c = native.load_scene_batch(*args, seed=9, epoch=4, flip_xz=False)
    np.testing.assert_array_equal(a[0], b[0])
    assert not np.array_equal(a[0], c[0])


def test_kitti_filter_matches_python(tmp_path):
    """filter_mode=1 must drop exactly the rows the python KITTI path drops
    (ground in both frames, or far in either frame)."""
    rng = np.random.default_rng(6)
    n = 200
    pc1 = rng.uniform(-5, 5, (n, 3)).astype(np.float32)
    pc2 = (pc1 + 0.1).astype(np.float32)
    # Plant ground rows (y < -1.4 both frames) and far rows (z >= 35).
    pc1[:20, 1] = -2.0
    pc2[:20, 1] = -2.0
    pc1[20:30, 2] = 40.0
    np.save(str(tmp_path / "pc1.npy"), pc1)
    np.save(str(tmp_path / "pc2.npy"), pc2)

    not_ground = ~np.logical_and(pc1[:, 1] < -1.4, pc2[:, 1] < -1.4)
    keep1, keep2 = pc1[not_ground], pc2[not_ground]
    near = np.logical_and(keep1[:, 2] < 35.0, keep2[:, 2] < 35.0)
    keep1, keep2 = keep1[near], keep2[near]

    n_pts = keep1.shape[0]  # ask for exactly the surviving rows
    got1, got2, _, flow, status = native.load_scene_batch(
        [str(tmp_path / "pc1.npy")], [str(tmp_path / "pc2.npy")], [0],
        n_pts, 256, seed=0, epoch=0, flip_xz=False, filter_mode=1,
    )
    assert status.tolist() == [1]
    want = {tuple(np.round(r, 5)) for r in keep1}
    got = {tuple(np.round(r, 5)) for r in got1[0]}
    assert got == want  # sampled every surviving row, none of the dropped
    np.testing.assert_allclose(flow[0], 0.1, atol=1e-6)
    # Asking for one more point than survives the filter must reject.
    _, _, _, _, status = native.load_scene_batch(
        [str(tmp_path / "pc1.npy")], [str(tmp_path / "pc2.npy")], [0],
        n_pts + 1, 256, seed=0, epoch=0, flip_xz=False, filter_mode=1,
    )
    assert status.tolist() == [0]


def test_native_loader_per_item_retry(tmp_path):
    """A batch with one undersized scene keeps the good rows and re-requests
    only the bad one (reject-and-advance, generic.py:101-110)."""
    from pvraft_tpu.data import FT3D, PrefetchLoader

    rng = np.random.default_rng(7)
    # FT3D holds out scene 0 for val; the train list is scenes 1..4 with
    # flow offsets 1, 2, 3, 4. Scene 2 is too small for 32 points.
    sizes = [64, 64, 8, 64, 64]
    for i, n in enumerate(sizes):
        scene = tmp_path / "train" / f"{i:07d}"
        scene.mkdir(parents=True)
        pc1 = rng.normal(size=(n, 3)).astype(np.float32)
        np.save(scene / "pc1.npy", pc1)
        np.save(scene / "pc2.npy", pc1 + float(i))

    ds = FT3D(str(tmp_path), nb_points=32, mode="train", strict_sizes=False)
    assert len(ds) == 4
    loader = PrefetchLoader(ds, 4, shuffle=False, num_workers=1, native=True)
    assert loader.native
    (batch,) = list(loader.epoch(0))
    assert batch["pc1"].shape == (4, 32, 3)
    # Batch row 1 (small scene 2) is replaced by the next dataset item
    # (scene 3); the other rows keep their original scenes. The FT3D x/z
    # sign flip turns a +i offset into flow (-i, i, -i).
    def expect(i):
        return np.broadcast_to(np.asarray([-i, i, -i], np.float32), (32, 3))

    np.testing.assert_allclose(batch["flow"][0], expect(1), atol=1e-5)
    np.testing.assert_allclose(batch["flow"][1], expect(3), atol=1e-5)
    np.testing.assert_allclose(batch["flow"][2], expect(3), atol=1e-5)
    np.testing.assert_allclose(batch["flow"][3], expect(4), atol=1e-5)


def test_kitti_native_loader_end_to_end(tmp_path):
    """KITTI eval through the native path: batches equal the python path's
    content (same filter + sampler semantics)."""
    from pvraft_tpu.data import KITTI, PrefetchLoader

    rng = np.random.default_rng(8)
    for i in range(200):
        scene = tmp_path / f"{i:06d}"
        scene.mkdir(parents=True)
        n = 96
        pc1 = rng.uniform(-5, 5, (n, 3)).astype(np.float32)
        pc1[:, 2] = np.abs(pc1[:, 2])  # keep z near
        pc2 = pc1 + 0.25
        np.save(scene / "pc1.npy", pc1)
        np.save(scene / "pc2.npy", pc2)

    ds = KITTI(str(tmp_path), nb_points=48)
    assert len(ds) == 142
    loader = PrefetchLoader(ds, 1, num_workers=1, native=True)
    assert loader.native
    batches = list(loader.epoch(0))
    assert len(batches) == 142
    for b in batches[:5]:
        assert b["pc1"].shape == (1, 48, 3)
        np.testing.assert_allclose(b["flow"], 0.25, atol=1e-6)


def test_ft3d_native_loader_end_to_end(tmp_path):
    from pvraft_tpu.data import FT3D, PrefetchLoader

    rng = np.random.default_rng(5)
    fulls = {}
    for i in range(6):
        scene = tmp_path / "train" / f"{i:07d}"
        scene.mkdir(parents=True)
        n = 48 + 8 * i
        pc1 = rng.normal(size=(n, 3)).astype(np.float32)
        pc2 = pc1 + rng.normal(0, 0.1, size=(n, 3)).astype(np.float32)
        np.save(scene / "pc1.npy", pc1)
        np.save(scene / "pc2.npy", pc2)
        fulls[str(scene)] = (pc1, pc2)

    ds = FT3D(str(tmp_path), nb_points=32, mode="train", strict_sizes=False)
    loader = PrefetchLoader(ds, 2, shuffle=True, num_workers=2, native=True)
    assert loader.native
    batches = list(loader.epoch(0))
    assert len(batches) == len(ds) // 2
    for b in batches:
        assert b["pc1"].shape == (2, 32, 3)
        assert b["flow"].shape == (2, 32, 3)
        np.testing.assert_array_equal(b["mask"], 1.0)
        # flow is index-aligned: pc1 + flow must equal the flipped full pc2
        # at the matching row.
        for bi in range(2):
            warped = b["pc1"][bi] + b["flow"][bi]
            # find which scene this came from by matching against fulls
            matched = False
            for scene, (f1, f2) in fulls.items():
                flip1 = f1 * np.asarray([-1, 1, -1], np.float32)
                flip2 = f2 * np.asarray([-1, 1, -1], np.float32)
                rows = {tuple(np.round(r, 4)) for r in flip1}
                if {tuple(np.round(r, 4)) for r in b["pc1"][bi]} <= rows:
                    lookup = {
                        tuple(np.round(flip1[j], 4)): flip2[j]
                        for j in range(flip1.shape[0])
                    }
                    for r in range(32):
                        key = tuple(np.round(b["pc1"][bi][r], 4))
                        np.testing.assert_allclose(
                            warped[r], lookup[key], atol=1e-4
                        )
                    matched = True
                    break
            assert matched
