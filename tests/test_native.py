"""Native data-plane tests (built on demand with g++; skipped without it)."""

import os
import shutil

import numpy as np
import pytest

native = pytest.importorskip("pvraft_tpu.native")

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None and not native.native_available(),
    reason="no compiler and no prebuilt native library",
)


def test_build_and_available():
    assert native.native_available()


def test_npy_read_f32(tmp_path):
    arr = np.random.default_rng(0).normal(size=(37, 3)).astype(np.float32)
    p = str(tmp_path / "a.npy")
    np.save(p, arr)
    got = native.npy_read(p)
    np.testing.assert_array_equal(got, arr)
    assert native.npy_shape(p) == (37, 3)


def test_npy_read_f64_converts(tmp_path):
    arr = np.random.default_rng(1).normal(size=(5, 3))
    p = str(tmp_path / "b.npy")
    np.save(p, arr)
    got = native.npy_read(p)
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, arr.astype(np.float32), atol=1e-6)


def test_load_scene_batch(tmp_path):
    rng = np.random.default_rng(2)
    paths1, paths2 = [], []
    fulls = []
    for i in range(3):
        pc1 = rng.normal(size=(50 + i * 10, 3)).astype(np.float32)
        pc2 = pc1 + 0.5
        p1 = str(tmp_path / f"s{i}_pc1.npy")
        p2 = str(tmp_path / f"s{i}_pc2.npy")
        np.save(p1, pc1)
        np.save(p2, pc2)
        paths1.append(p1)
        paths2.append(p2)
        fulls.append((pc1, pc2))

    n_pts = 32
    pc1, pc2, mask, flow, status = native.load_scene_batch(
        paths1, paths2, [0, 1, 2], n_pts, 256, seed=7, epoch=0,
        flip_xz=False, n_threads=2,
    )
    assert status.tolist() == [1, 1, 1]
    assert pc1.shape == (3, n_pts, 3)
    np.testing.assert_array_equal(mask, 1.0)
    for i in range(3):
        full1, full2 = fulls[i]
        # every sampled pc1 row exists in the full cloud
        full_set = {tuple(np.round(r, 5)) for r in full1}
        got_set = {tuple(np.round(r, 5)) for r in pc1[i]}
        assert got_set <= full_set
        # no duplicate rows (sampling without replacement)
        assert len(got_set) == n_pts
        # flow is index-aligned with pc1's sampling: pc2_full - pc1_full = 0.5
        np.testing.assert_allclose(flow[i], 0.5, atol=1e-6)


def test_load_scene_batch_flip_and_reject(tmp_path):
    rng = np.random.default_rng(3)
    big = rng.normal(size=(64, 3)).astype(np.float32)
    small = rng.normal(size=(8, 3)).astype(np.float32)
    for name, arr in [("big_pc1", big), ("big_pc2", big + 1),
                      ("small_pc1", small), ("small_pc2", small)]:
        np.save(str(tmp_path / f"{name}.npy"), arr)

    pc1, _, _, _, status = native.load_scene_batch(
        [str(tmp_path / "big_pc1.npy"), str(tmp_path / "small_pc1.npy")],
        [str(tmp_path / "big_pc2.npy"), str(tmp_path / "small_pc2.npy")],
        [0, 1], 32, 256, seed=1, epoch=0, flip_xz=True, n_threads=1,
    )
    assert status.tolist() == [1, 0]  # small scene rejected
    # flip applied to x and z, not y: the sampled rows must be in the
    # flipped full set.
    flipped = big.copy()
    flipped[:, 0] *= -1
    flipped[:, 2] *= -1
    full_set = {tuple(np.round(r, 5)) for r in flipped}
    got_set = {tuple(np.round(r, 5)) for r in pc1[0]}
    assert got_set <= full_set


def test_determinism_across_calls(tmp_path):
    rng = np.random.default_rng(4)
    pc = rng.normal(size=(40, 3)).astype(np.float32)
    np.save(str(tmp_path / "pc1.npy"), pc)
    np.save(str(tmp_path / "pc2.npy"), pc + 1)
    args = ([str(tmp_path / "pc1.npy")], [str(tmp_path / "pc2.npy")], [5],
            16, 64)
    a = native.load_scene_batch(*args, seed=9, epoch=3, flip_xz=False)
    b = native.load_scene_batch(*args, seed=9, epoch=3, flip_xz=False)
    c = native.load_scene_batch(*args, seed=9, epoch=4, flip_xz=False)
    np.testing.assert_array_equal(a[0], b[0])
    assert not np.array_equal(a[0], c[0])


def test_ft3d_native_loader_end_to_end(tmp_path):
    from pvraft_tpu.data import FT3D, PrefetchLoader

    rng = np.random.default_rng(5)
    fulls = {}
    for i in range(6):
        scene = tmp_path / "train" / f"{i:07d}"
        scene.mkdir(parents=True)
        n = 48 + 8 * i
        pc1 = rng.normal(size=(n, 3)).astype(np.float32)
        pc2 = pc1 + rng.normal(0, 0.1, size=(n, 3)).astype(np.float32)
        np.save(scene / "pc1.npy", pc1)
        np.save(scene / "pc2.npy", pc2)
        fulls[str(scene)] = (pc1, pc2)

    ds = FT3D(str(tmp_path), nb_points=32, mode="train", strict_sizes=False)
    loader = PrefetchLoader(ds, 2, shuffle=True, num_workers=2, native=True)
    assert loader.native
    batches = list(loader.epoch(0))
    assert len(batches) == len(ds) // 2
    for b in batches:
        assert b["pc1"].shape == (2, 32, 3)
        assert b["flow"].shape == (2, 32, 3)
        np.testing.assert_array_equal(b["mask"], 1.0)
        # flow is index-aligned: pc1 + flow must equal the flipped full pc2
        # at the matching row.
        for bi in range(2):
            warped = b["pc1"][bi] + b["flow"][bi]
            # find which scene this came from by matching against fulls
            matched = False
            for scene, (f1, f2) in fulls.items():
                flip1 = f1 * np.asarray([-1, 1, -1], np.float32)
                flip2 = f2 * np.asarray([-1, 1, -1], np.float32)
                rows = {tuple(np.round(r, 4)) for r in flip1}
                if {tuple(np.round(r, 4)) for r in b["pc1"][bi]} <= rows:
                    lookup = {
                        tuple(np.round(flip1[j], 4)): flip2[j]
                        for j in range(flip1.shape[0])
                    }
                    for r in range(32):
                        key = tuple(np.round(b["pc1"][bi][r], 4))
                        np.testing.assert_allclose(
                            warped[r], lookup[key], atol=1e-4
                        )
                    matched = True
                    break
            assert matched
