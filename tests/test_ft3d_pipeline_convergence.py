"""Smoke the real-pipeline convergence recorder (slow tier): corpus
written in the FT3D layout, trained through the FT3D dataset + prefetch
loader + Trainer, honest n/a gates at smoke length. The full-length gates
are exercised by the committed artifacts
(artifacts/ft3d_pipeline_convergence*.json)."""

import json
import subprocess
import sys
import os

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_pipeline_recorder_smoke(tmp_path):
    out = tmp_path / "rec.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "ft3d_pipeline_convergence.py"),
         "--cpu", "--points", "128", "--extra", "32",
         "--train_scenes", "10", "--test_scenes", "3",
         "--epochs", "2", "--out", str(out)],
        capture_output=True, text=True, timeout=1200,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    # Smoke length: the halving gate must record n/a, not a vacuous pass,
    # and must not be counted in the aggregate.
    assert rec["checks"]["val_epe_halves"] == "n/a"
    assert "val_epe_halves" not in rec["applied_checks"]
    assert rec["ok"], rec["checks"]
    assert rec["checks"]["finite"] is True
    # The corpus really went through the dataset's exact-N subsampling
    # (oversized scenes) and produced per-epoch val numbers.
    assert rec["config"]["extra"] > 0
    assert len(rec["epochs"]) == 2
    assert rec["val_epe3d_untrained"] > rec["epochs"][-1]["val_epe3d"]
