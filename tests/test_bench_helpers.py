"""Unit tests for benchmark helpers: honest-timing wrapper and baseline
comparability labeling (no accelerator required)."""

import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
sys.path.insert(0, REPO)


def test_kernel_bench_timeit_runs_and_preserves_semantics():
    """timeit perturbs float inputs per call but must still execute the
    function (finite positive ms) and work for pytree args."""
    from kernel_bench import timeit

    import jax.numpy as jnp

    def fn(state, idx):
        corr, xyz = state
        return jnp.sum(corr * corr) + jnp.sum(xyz) + idx.sum()

    state = (jnp.ones((2, 8, 4)), jnp.zeros((2, 8, 4, 3)))
    idx = jnp.zeros((2, 8), jnp.int32)   # int leaves must pass untouched
    ms = timeit(fn, state, idx, iters=3)
    assert np.isfinite(ms) and ms > 0


def test_bench_emit_comparability():
    """vs_baseline must be zeroed when the measured config is not the
    flagship config (shrunk CPU fallback) OR the platform is not tpu —
    and every line must validate as pvraft_bench/v1."""
    out = subprocess.run(
        [sys.executable, "-c", (
            "import bench; "
            "bench._emit(1000.0, {'variant': 'x'}, comparable=False); "
            "bench._emit(bench.BASELINE_PAIRS_PER_SEC_PER_CHIP, {}, "
            "comparable=True, platform='tpu'); "
            # A CPU-fallback run at the FULL config still may not be
            # ratioed against the TPU baseline (BENCH_r05 failure mode).
            "bench._emit(2000.0, {'platform': 'cpu'}, comparable=True)"
        )],
        capture_output=True, text=True, cwd=REPO, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    lines = [json.loads(l) for l in out.stdout.strip().splitlines()]
    assert lines[0]["vs_baseline"] == 0.0
    assert lines[0]["value"] == 1000.0
    assert lines[0]["platform"] == "unknown"
    assert lines[0]["comparable"] is False
    assert abs(lines[1]["vs_baseline"] - 1.0) < 1e-6
    assert lines[1]["comparable"] is True
    assert lines[2]["platform"] == "cpu"
    assert lines[2]["comparable"] is False
    assert lines[2]["vs_baseline"] == 0.0
    from pvraft_tpu.obs.bench import validate_bench

    for doc in lines:
        assert validate_bench(doc) == [], doc
