"""Unit tests for the convergence-artifact gate logic (round-4 honesty
fixes): not-applied checks record "n/a" (never a vacuous pass), `ok`
aggregates only the applied checks, and per-task calibrated thresholds
are selected correctly. Pure-python — no model, no jax arrays."""

import json

from scripts.convergence_record import (
    EPE_ABS_THRESHOLD,
    EPE_ABS_THRESHOLD_MULTIOBJ,
    make_record,
    recheck,
    tail_best,
    write_and_report,
)


def _traj(epes):
    return [{"step": i, "loss": e, "epe": e} for i, e in enumerate(epes)]


def _results(fp32_epes, fast_epes):
    return [
        {"variant": "fp32", "trajectory": _traj(fp32_epes),
         "initial_epe": fp32_epes[0], "final_epe": fp32_epes[-1]},
        {"variant": "bf16+approx", "trajectory": _traj(fast_epes),
         "initial_epe": fast_epes[0], "final_epe": fast_epes[-1]},
    ]


def _good_run(floor, n=32):
    # n logged points, monotone 2.0 -> floor: passes every shape gate.
    return [2.0 - (2.0 - floor) * i / (n - 1) for i in range(n)]


def test_short_run_abs_gate_is_na_not_true():
    short = _good_run(0.5, n=8)
    rec = make_record("cpu", {"steps": 60}, _results(short, short))
    assert rec["checks"]["fp32_abs"] == "n/a"
    assert rec["checks"]["fp32_quarters_nonincreasing"] == "n/a"  # <16 pts
    assert "fp32_abs" not in rec["applied_checks"]
    assert rec["ok"]  # rel + fast gates still applied and pass


def test_full_run_applies_all_gates():
    rec = make_record(
        "cpu", {"steps": 200}, _results(_good_run(0.2), _good_run(0.22))
    )
    assert rec["checks"]["fp32_abs"] is True
    # Threshold-metric gates only apply on the calibrated profile
    # (config["threshold_gates"]); off it they are honest "n/a".
    heldout = [k for k in rec["checks"] if k.startswith("fp32_heldout_")]
    assert len(heldout) == 3
    assert all(rec["checks"][k] == "n/a" for k in heldout)
    assert sorted(rec["applied_checks"]) == sorted(
        k for k in rec["checks"] if k not in heldout)
    assert rec["ok"]
    assert rec["thresholds"]["epe_abs"] == EPE_ABS_THRESHOLD


def test_thresholds_profile_gates_heldout_metrics():
    res = _results(_good_run(0.05), _good_run(0.05))
    res[0]["heldout_metrics"] = {"epe3d": 0.03, "acc3d_strict": 0.4,
                                 "acc3d_relax": 0.9, "outlier": 0.2}
    cfg = {"steps": 400, "threshold_gates": True}
    rec = make_record("cpu", cfg, res)
    assert rec["checks"]["fp32_heldout_acc3d_relax"] is True
    assert rec["checks"]["fp32_heldout_outlier"] is True
    assert "fp32_heldout_acc3d_strict" in rec["applied_checks"]
    assert rec["ok"]
    # A saturated outlier (the round-4 failure mode) must FAIL the gate.
    res[0]["heldout_metrics"]["outlier"] = 0.99
    rec = make_record("cpu", cfg, res)
    assert rec["checks"]["fp32_heldout_outlier"] is False
    assert not rec["ok"]
    # Without held-out metrics the gates stay n/a even on the profile.
    del res[0]["heldout_metrics"]
    rec = make_record("cpu", cfg, res)
    assert rec["checks"]["fp32_heldout_outlier"] == "n/a"


def test_multiobj_uses_its_own_calibrated_threshold():
    # 0.28 fails the 1-object gate (0.25) but passes multi-object (0.30).
    rec1 = make_record(
        "cpu", {"steps": 200, "n_objects": 1},
        _results(_good_run(0.28), _good_run(0.28)))
    assert rec1["checks"]["fp32_abs"] is False and not rec1["ok"]
    rec3 = make_record(
        "cpu", {"steps": 200, "n_objects": 3},
        _results(_good_run(0.28), _good_run(0.28)))
    assert rec3["thresholds"]["epe_abs"] == EPE_ABS_THRESHOLD_MULTIOBJ
    assert rec3["checks"]["fp32_abs"] is True and rec3["ok"]


def test_failed_check_fails_ok_and_divergence_caught():
    # Diverging tail: quarter medians increase.
    up = _good_run(0.2)[:24] + [1.5] * 8
    rec = make_record("cpu", {"steps": 200}, _results(up, up))
    assert rec["checks"]["fp32_quarters_nonincreasing"] is False
    assert not rec["ok"]


def test_tail_best_ignores_final_spike():
    epes = _good_run(0.1)
    epes[-1] = 0.9  # batch-noise spike on the literal last step
    assert tail_best(_traj(epes)) < 0.25


def test_recheck_failure_writes_side_file_and_pass_cleans_it(tmp_path):
    path = str(tmp_path / "conv.json")
    bad = {
        "platform": "cpu", "config": {"steps": 200},
        "results": _results(_good_run(0.9), _good_run(0.9)),
    }
    with open(path, "w") as f:
        json.dump(bad, f)
    assert recheck(path) == 1
    side = path + ".recheck_failed.json"
    with open(side) as f:
        assert not json.load(f)["ok"]
    with open(path) as f:  # committed evidence untouched
        assert "checks" not in json.load(f)

    good = make_record("cpu", {"steps": 200},
                       _results(_good_run(0.2), _good_run(0.2)))
    assert write_and_report(good, path) == 0
    import os

    assert not os.path.exists(side)  # stale failure evidence removed
