"""threadcheck: model extraction, GC rules red/green, the historical
race fixture corpus, the clean-tree gate, and the runtime lock-order
sanitizer. Pure host-side — no jax tracing anywhere (tier-1 on CPU)."""

import ast
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pvraft_tpu.analysis.concurrency.check import (
    check_paths,
    check_source,
    default_scope,
)
from pvraft_tpu.analysis.concurrency.model import build_module_model
from pvraft_tpu.analysis.concurrency.rules import all_concurrency_rules
from pvraft_tpu.analysis.concurrency.sanitizer import (
    LockOrderError,
    OrderedLock,
    order_edges,
    ordered_lock,
    reset_order_graph,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "threadcheck")


def ids(src, path="x.py"):
    return [d.rule_id for d in check_source(src, path=path)]


def model_of(src, path="x.py"):
    return build_module_model(ast.parse(src), src, path)


# --- model extraction -----------------------------------------------------

MODEL_SRC = '''
import queue
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._q = queue.Queue(maxsize=4)
        self._n = 0  # guarded-by: _lock
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        with self._lock:
            self._n += 1

    def hook(self):
        def inner():
            return self._n
        return inner
'''


def test_model_classifies_fields():
    cls = model_of(MODEL_SRC).class_named("C")
    assert set(cls.locks) == {"_lock"}
    assert set(cls.events) == {"_stop"}
    assert set(cls.queues) == {"_q"}
    assert cls.guard_of("_n") == "_lock"
    assert cls.concurrent
    assert [s.target for s in cls.spawns] == ["_run"]
    assert "_run" in cls.thread_entry_methods()


def test_model_held_tracking_and_nested_def():
    cls = model_of(MODEL_SRC).class_named("C")
    run_writes = [a for a in cls.accesses
                  if a.method == "_run" and a.attr == "_n"]
    assert run_writes and all("_lock" in a.held for a in run_writes)
    # A closure body runs after the enclosing with exits: empty held set.
    inner_reads = [a for a in cls.accesses
                   if a.method.startswith("hook") and a.attr == "_n"]
    assert inner_reads and all(not a.held for a in inner_reads)


def test_guard_comment_does_not_leak_to_next_line():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.a = 0  # guarded-by: _lock\n"
        "        self.b = 0\n"
    )
    cls = model_of(src).class_named("C")
    assert cls.guard_of("a") == "_lock"
    assert cls.guard_of("b") is None


def test_guard_comment_on_own_line_annotates_below():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        # guarded-by: _lock\n"
        "        self.a = 0\n"
    )
    assert model_of(src).class_named("C").guard_of("a") == "_lock"


# --- per-rule red/green ---------------------------------------------------

GC001_RED = '''
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0  # guarded-by: _lock
    def bump(self):
        self.n += 1
'''

GC001_GREEN = '''
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0  # guarded-by: _lock
    def bump(self):
        with self._lock:
            self.n += 1
'''

GC002_RED = '''
import threading
class C:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
    def f(self):
        with self.a:
            with self.b:
                pass
    def g(self):
        with self.b:
            with self.a:
                pass
'''

GC002_GREEN = '''
import threading
class C:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
    def f(self):
        with self.a:
            with self.b:
                pass
    def g(self):
        with self.a:
            with self.b:
                pass
'''

GC002_CALL_RED = '''
import threading
class C:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
    def f(self):
        with self.a:
            self.g()
    def g(self):
        with self.b:
            self.h()
    def h(self):
        with self.a:
            pass
'''

GC002_MULTI_ITEM_RED = '''
import threading
class C:
    def __init__(self):
        self.a = threading.Lock()
        self.b = threading.Lock()
    def f(self):
        with self.a, self.b:
            pass
    def g(self):
        with self.b:
            with self.a:
                pass
'''

GC004_STRING_JOIN_RED = '''
import os
import threading
class C:
    def __init__(self):
        self._t = threading.Thread(target=self.run)
        self._t.start()
    def run(self):
        return ", ".join(["a", "b"]) + os.path.join("x", "y")
'''

GC003_QUEUE_RED = '''
import queue
import threading
class C:
    def __init__(self):
        self._q = queue.Queue(maxsize=2)
        self._t = threading.Thread(target=self.run, daemon=True)
    def run(self):
        pass
    def submit(self, item):
        if not self._q.full():
            self._q.put_nowait(item)
'''

GC003_QUEUE_GREEN = '''
import queue
import threading
class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue(maxsize=2)
        self._t = threading.Thread(target=self.run, daemon=True)
    def run(self):
        pass
    def submit(self, item):
        with self._lock:
            if not self._q.full():
                self._q.put_nowait(item)
'''

GC004_RED = '''
import threading
class C:
    def __init__(self):
        self._t = threading.Thread(target=self.run)
        self._t.start()
    def run(self):
        pass
'''

GC004_GREEN = '''
import threading
class C:
    def __init__(self):
        self._t = threading.Thread(target=self.run)
        self._t.start()
    def run(self):
        pass
    def close(self):
        self._t.join()
'''


@pytest.mark.parametrize("rule,red,green", [
    ("GC001", GC001_RED, GC001_GREEN),
    ("GC002", GC002_RED, GC002_GREEN),
    ("GC002", GC002_CALL_RED, GC002_GREEN),
    # `with self.a, self.b:` acquires left-to-right — a real ordering
    # constraint the graph must carry.
    ("GC002", GC002_MULTI_ITEM_RED, GC002_GREEN),
    ("GC003", GC003_QUEUE_RED, GC003_QUEUE_GREEN),
    ("GC004", GC004_RED, GC004_GREEN),
    # String/path joins must not satisfy the join requirement — one
    # `", ".join(...)` in a class would otherwise disarm GC004 wholesale.
    ("GC004", GC004_STRING_JOIN_RED, GC004_GREEN),
])
def test_rule_red_green(rule, red, green):
    assert rule in ids(red)
    assert ids(green) == []


def test_benign_consumer_loop_not_flagged():
    # `while not stopped: q.get(timeout=...)` is the standard worker
    # idiom — the event check gates only the producer side (GC003).
    src = '''
import queue
import threading
class C:
    def __init__(self):
        self._q = queue.Queue()
        self._stop = threading.Event()
        self._t = threading.Thread(target=self.run, daemon=True)
    def run(self):
        while not self._stop.is_set():
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                continue
    def close(self):
        self._t.join()
'''
    assert ids(src) == []


def test_single_threaded_class_skipped():
    # No locks, no spawns: not a concurrent class, nothing fires even
    # on shapes that would be flagged in one.
    src = '''
class C:
    def __init__(self):
        self._thread = None
    def start(self):
        if self._thread is None:
            self._thread = object()
'''
    assert ids(src) == []


def test_suppression_shared_pragma_grammar():
    red = GC001_RED.replace(
        "        self.n += 1",
        "        self.n += 1  # graftlint: disable=GC001 -- test-only")
    assert ids(red) == []


def test_syntax_error_is_gc000():
    assert ids("def broken(:\n") == ["GC000"]


# --- the historical race corpus ------------------------------------------

# fixture stem -> rule that must detect the PRE-fix shape.
CORPUS = {
    "pr5_submit_shutdown": "GC003",
    "pr5_record_submit": "GC001",
    "pr8_in_flight": "GC001",
    "pr5_mid_predict_504": "GC003",
    "pr9_monitor_restart": "GC003",
    # ISSUE 13: the fault-injector's naive install tested self._plan and
    # assigned it with no lock — two concurrent installers both pass the
    # exclusivity check (design-review find, serve/faults.py).
    "pr13_fault_install": "GC003",
    # ISSUE 20: the naive weight hot-swap tested _swap_pending for
    # exclusivity and assigned it with no lock — two concurrent
    # /admin/reload fan-outs both pass, interleaving pointer writes and
    # generation bumps so the drain barrier waits against the wrong
    # generation (design-review find, serve/engine.py swap_params).
    "pr20_weight_swap": "GC003",
}


@pytest.mark.parametrize("stem,rule", sorted(CORPUS.items()))
def test_corpus_red_detected(stem, rule):
    diags, n = check_paths([os.path.join(FIXTURES, f"{stem}_red.py")])
    assert n == 1
    assert rule in {d.rule_id for d in diags}, (
        f"historical race {stem} no longer detected by {rule}")


@pytest.mark.parametrize("stem", sorted(CORPUS))
def test_corpus_green_clean(stem):
    diags, _ = check_paths([os.path.join(FIXTURES, f"{stem}_green.py")])
    assert diags == []


def test_corpus_covers_at_least_four_races():
    # Acceptance bar (ISSUE 11): >= 4 of the six PR 5/8/9 races
    # reproduced as detections. Five are; the sixth (404 keep-alive
    # desync) is protocol-state, documented out of static reach and
    # pinned by the raw-socket test in test_serve.py instead.
    assert len(CORPUS) >= 4
    diags, _ = check_paths([os.path.join(FIXTURES, "pr5_keepalive_404.py")])
    assert diags == []


# --- the gate: clean tree at zero findings --------------------------------

def test_scope_checks_clean():
    """The lint.sh stage in test form: serve/ + obs/ + data/loader.py
    must be at zero findings (real violations get FIXED, not pragma'd —
    the deepcheck precedent)."""
    diags, nfiles = check_paths(default_scope())
    assert nfiles >= 15
    assert diags == [], "\n".join(d.format() for d in diags)


def test_no_reasonless_gc_pragmas_in_package():
    """GC suppressions ride the shared pragma grammar, so they feed the
    lint --stats debt gate; any GC pragma in the package must carry a
    reason."""
    from pvraft_tpu.analysis.engine import collect_suppressions

    pragmas = collect_suppressions([os.path.join(REPO, "pvraft_tpu")])
    gc = [p for p in pragmas if any(i.startswith("GC") for i in p.ids)]
    assert all(p.reason for p in gc)


def test_known_rule_ids_include_gc_family():
    from pvraft_tpu.analysis.engine import known_rule_ids

    known = known_rule_ids()
    for rule in all_concurrency_rules():
        assert rule.id in known


def test_rule_table_unique_and_documented():
    rules = all_concurrency_rules()
    assert len({r.id for r in rules}) == len(rules)
    for r in rules:
        assert r.__doc__ and r.title


# --- CLI ------------------------------------------------------------------

def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "pvraft_tpu.analysis", "concurrency", *args],
        capture_output=True, text=True, cwd=REPO)


def test_cli_list_rules():
    proc = _run_cli("--list-rules")
    assert proc.returncode == 0
    assert "GC001" in proc.stdout and "GC004" in proc.stdout


def test_cli_red_fixture_exits_nonzero():
    proc = _run_cli(os.path.join(FIXTURES, "pr8_in_flight_red.py"))
    assert proc.returncode == 1
    assert "GC001" in proc.stdout


def test_cli_default_scope_clean():
    proc = _run_cli()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stderr


def test_lint_stats_counts_gc_namespace(tmp_path):
    """`lint --stats` counts GC pragmas through the one shared grammar
    and does not warn about them as unknown rules."""
    f = tmp_path / "x.py"
    f.write_text("y = 1  # graftlint: disable=GC001 -- fixture reason\n")
    proc = subprocess.run(
        [sys.executable, "-m", "pvraft_tpu.analysis", "lint", "--stats",
         str(tmp_path)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "GC001" in proc.stdout
    assert "unknown" not in proc.stdout


# --- sanitizer ------------------------------------------------------------

@pytest.fixture(autouse=True)
def _fresh_graph():
    reset_order_graph()
    yield
    reset_order_graph()


def test_sanitizer_consistent_order_ok():
    a, b = OrderedLock("t.a"), OrderedLock("t.b")
    for _ in range(3):
        with a:
            with b:
                pass
    assert ("t.a", "t.b") in order_edges()


def test_sanitizer_inversion_raises_with_both_sites():
    a, b = OrderedLock("t.a"), OrderedLock("t.b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderError) as err:
            a.acquire()
    msg = str(err.value)
    assert "t.a" in msg and "t.b" in msg and "opposite order" in msg


def test_sanitizer_inversion_across_threads():
    a, b = OrderedLock("t.a"), OrderedLock("t.b")

    def leg_ab():
        with a:
            with b:
                pass

    t = threading.Thread(target=leg_ab)
    t.start()
    t.join()
    with b:
        with pytest.raises(LockOrderError):
            with a:
                pass


def test_sanitizer_recursive_acquire_raises():
    a = OrderedLock("t.a")
    with a:
        with pytest.raises(LockOrderError, match="recursive"):
            a.acquire()
    # The failed acquire must not have corrupted the held stack.
    with a:
        pass


def test_sanitizer_trylock_never_raises():
    a, b = OrderedLock("t.a"), OrderedLock("t.b")
    with a:
        with b:
            pass
    with b:
        assert a.acquire(blocking=False) is True
        a.release()
    # The trylock left no (t.b, t.a) edge behind: a leg that can never
    # wait must not constrain the opposite blocking order either.
    assert ("t.b", "t.a") not in order_edges()


def test_sanitizer_trylock_held_still_constrains():
    # A lock WON via trylock sits on the held stack normally: a blocking
    # acquire under it records the edge and inversions still raise.
    a, b = OrderedLock("t.a"), OrderedLock("t.b")
    assert a.acquire(blocking=False)
    with b:
        pass
    a.release()
    assert ("t.a", "t.b") in order_edges()
    with b:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_sanitizer_release_out_of_order():
    a, b = OrderedLock("t.a"), OrderedLock("t.b")
    a.acquire()
    b.acquire()
    a.release()  # hand-over-hand: release the outer lock first
    b.release()
    with a:
        pass


def test_ordered_lock_factory_gates_on_env(monkeypatch):
    monkeypatch.delenv("PVRAFT_CHECKS", raising=False)
    assert not isinstance(ordered_lock("t.x"), OrderedLock)
    monkeypatch.setenv("PVRAFT_CHECKS", "1")
    assert isinstance(ordered_lock("t.x"), OrderedLock)


# --- sanitizer end-to-end on the real batcher -----------------------------

class _FakeEngine:
    """Minimal engine double (same contract as test_serve's)."""

    def __init__(self, buckets=(32,), batch_sizes=(1, 2)):
        from types import SimpleNamespace

        self.cfg = SimpleNamespace(buckets=buckets,
                                   batch_sizes=batch_sizes,
                                   min_points=4, coord_limit=100.0)

    def validate_request(self, pc1, pc2):
        return self.cfg.buckets[0]

    def batch_size_for(self, n):
        for bs in self.cfg.batch_sizes:
            if n <= bs:
                return bs
        return self.cfg.batch_sizes[-1]

    def predict_batch(self, requests, bucket):
        return [np.zeros((pc1.shape[0], 3), np.float32)
                for pc1, _ in requests]

    def compile_report(self):
        return []


def test_sanitizer_live_batcher_run(monkeypatch):
    """PVRAFT_CHECKS=1 turns the adopted serve locks into OrderedLocks:
    a real MicroBatcher+ServeMetrics round-trip runs clean under the
    sanitizer and records the intake->metrics acquisition edge — the
    'threaded tier-1 tests double as a sanitizer run' wiring, proven
    in-process."""
    monkeypatch.setenv("PVRAFT_CHECKS", "1")
    from pvraft_tpu.serve.batcher import BatcherConfig, MicroBatcher
    from pvraft_tpu.serve.metrics import ServeMetrics

    engine = _FakeEngine()
    metrics = ServeMetrics(engine.cfg.buckets)
    assert isinstance(metrics._lock, OrderedLock)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=1.0, queue_depth=8),
        metrics=metrics)
    assert isinstance(batcher._intake_lock, OrderedLock)
    pc = np.zeros((8, 3), np.float32)
    handles = [batcher.submit(pc, pc) for _ in range(6)]
    for h in handles:
        h.wait(30)
    batcher.shutdown(drain=True)
    edges = order_edges()
    assert ("MicroBatcher._intake_lock", "ServeMetrics._lock") in edges
    snap = metrics.snapshot()
    assert snap["requests_total"] == 6
    assert snap["responses_total"] == 6


def test_sanitizer_devmem_lifecycle(monkeypatch):
    """The device-memory monitor's new lifecycle lock under the
    sanitizer: start/stop/start cycles are race-free and restartable."""
    monkeypatch.setenv("PVRAFT_CHECKS", "1")
    from pvraft_tpu.obs.device_memory import DeviceMemoryMonitor

    seen = []
    mon = DeviceMemoryMonitor(emit=lambda rows, context: seen.append(rows),
                              interval_s=0.01, devices=[])
    mon.start()
    time.sleep(0.05)
    mon.stop()
    mon.start()  # restart must re-arm (stop flag cleared under the lock)
    assert mon._thread is not None
    mon.stop()
    assert mon._thread is None
