"""Device-memory telemetry (obs/device_memory.py): row normalization,
the no-stats CPU path, the monitor's metrics/event fan-out, and the
Prometheus gauge — with the JSON /metrics shape untouched."""

import json

import pytest

jax = pytest.importorskip("jax")

from pvraft_tpu.obs.device_memory import (  # noqa: E402
    DeviceMemoryMonitor,
    device_memory_row,
    sample_device_memory,
)


class _FakeDevice:
    def __init__(self, device_id=0, stats=None, platform="tpu"):
        self.id = device_id
        self.platform = platform
        self._stats = stats

    def memory_stats(self):
        return self._stats


_STATS = {"bytes_in_use": 1 << 30, "peak_bytes_in_use": 2 << 30,
          "bytes_limit": 16 << 30, "largest_alloc_size": 123}


def test_row_normalizes_known_keys_only():
    row = device_memory_row(_FakeDevice(3, _STATS))
    assert row == {"device_id": 3, "platform": "tpu",
                   "bytes_in_use": 1 << 30,
                   "peak_bytes_in_use": 2 << 30,
                   "bytes_limit": 16 << 30}


def test_row_without_stats_is_none():
    assert device_memory_row(_FakeDevice(0, None)) is None
    assert device_memory_row(_FakeDevice(0, {})) is None
    # An allocator with no bytes_in_use has nothing to gauge.
    assert device_memory_row(_FakeDevice(0, {"bytes_limit": 4096})) is None

    class _Raises:
        id = 0

        def memory_stats(self):
            raise RuntimeError("no allocator")

    assert device_memory_row(_Raises()) is None


def test_cpu_backend_samples_to_nothing():
    # The tier-1 backend has no allocator stats: zero noise, no events.
    assert sample_device_memory(jax.local_devices()) == []


def test_sampled_rows_are_schema_valid(tmp_path):
    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.serve.events import ServeTelemetry

    devices = [_FakeDevice(0, _STATS), _FakeDevice(1, dict(_STATS))]
    path = str(tmp_path / "serve.events.jsonl")
    tel = ServeTelemetry(path, enabled=True)
    rows = sample_device_memory(devices)
    tel.emit_device_memory(rows, context="serve")
    tel.close()
    assert validate_events_file(path) == []
    records = [json.loads(l) for l in open(path)]
    dm = [r for r in records if r["type"] == "device_memory"]
    assert len(dm) == 1
    assert [d["device_id"] for d in dm[0]["devices"]] == [0, 1]


def test_monitor_feeds_metrics_and_events(tmp_path):
    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.serve.events import ServeTelemetry
    from pvraft_tpu.serve.metrics import ServeMetrics

    path = str(tmp_path / "serve.events.jsonl")
    tel = ServeTelemetry(path, enabled=True)
    metrics = ServeMetrics(buckets=(2048,))
    mon = DeviceMemoryMonitor(
        emit=tel.emit_device_memory, metrics=metrics, interval_s=0,
        devices=[_FakeDevice(0, _STATS), _FakeDevice(1, _STATS)])
    rows = mon.sample_once()
    assert len(rows) == 2 and mon.samples == 1
    tel.close()
    assert validate_events_file(path) == []
    # Prometheus gauge present with per-device labels…
    prom = metrics.prometheus()
    assert 'pvraft_device_hbm_bytes{device="0"} 1073741824' in prom
    assert 'pvraft_device_hbm_bytes{device="1"} 1073741824' in prom
    assert 'pvraft_device_hbm_peak_bytes{device="0"} 2147483648' in prom
    # …and the frozen JSON snapshot did NOT grow a new key.
    assert "device_memory" not in metrics.snapshot()


def test_monitor_interval_zero_never_starts_thread():
    mon = DeviceMemoryMonitor(interval_s=0)
    mon.start()
    assert mon._thread is None
    mon.stop()                     # no-op, must not raise


def test_monitor_cpu_emits_nothing(tmp_path):
    from pvraft_tpu.serve.events import ServeTelemetry

    path = str(tmp_path / "serve.events.jsonl")
    tel = ServeTelemetry(path, enabled=True)
    mon = DeviceMemoryMonitor(emit=tel.emit_device_memory,
                              interval_s=0)  # real (CPU) local devices
    assert mon.sample_once() == [] and mon.samples == 0
    tel.close()
    records = [json.loads(l) for l in open(path)]
    assert [r["type"] for r in records] == ["run_header"]


def test_monitor_thread_lifecycle():
    metrics_rows = []

    class _Sink:
        def record_device_memory(self, rows):
            metrics_rows.append(rows)

    mon = DeviceMemoryMonitor(metrics=_Sink(), interval_s=0.01,
                              devices=[_FakeDevice(0, _STATS)])
    mon.start()
    import time

    deadline = time.monotonic() + 5.0
    while not metrics_rows and time.monotonic() < deadline:
        time.sleep(0.01)
    mon.stop()
    assert metrics_rows, "monitor thread never sampled"
    assert mon._thread is None
