"""pvraft_costs/v1 (programs/costs.py): validator red/green, the
cost_analysis flattening, and the committed-artifact pin — full
registry coverage both directions, the same drift discipline as
``artifacts/programs_list.txt``."""

import copy
import json
import os

import pytest

jax = pytest.importorskip("jax")

from pvraft_tpu.programs.costs import (  # noqa: E402
    COSTS_SCHEMA,
    check_coverage,
    summarize_cost_analysis,
    validate_costs,
    validate_costs_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ARTIFACT = os.path.join(REPO, "artifacts", "programs_costs.json")


def _record(**over):
    rec = {
        "name": "corr.corr_init",
        "target": "host",
        "tags": ["audit", "op"],
        "ok": True,
        "lower_s": 0.1,
        "compile_s": 0.2,
        "flops": 64500.0,
        "bytes_accessed": 38400.0,
        "memory": {
            "argument_size_in_bytes": 1024,
            "output_size_in_bytes": 512,
            "temp_size_in_bytes": 256,
            "generated_code_size_in_bytes": 4096,
            "alias_size_in_bytes": 0,
            "live_bytes_estimate": 1792,
            "fits_16GiB_hbm": True,
        },
    }
    rec.update(over)
    return rec


def _doc(records=None, **over):
    doc = {
        "schema": COSTS_SCHEMA,
        "topology": "v5e:2x2x1",
        "hbm_limit_bytes": 16 * 1024 ** 3,
        "programs": [_record()] if records is None else records,
    }
    doc.update(over)
    return doc


# --- summarize_cost_analysis ------------------------------------------------


def test_summarize_flattens_multi_computation_lists():
    out = summarize_cost_analysis([
        {"flops": 100.0, "bytes accessed": 40.0, "optimal_seconds": 0.5},
        {"flops": 23.0, "bytes accessed": 2.0},
    ])
    assert out == {"flops": 123.0, "bytes_accessed": 42.0,
                   "optimal_seconds": 0.5}
    assert summarize_cost_analysis({"flops": 7.0}) == {
        "flops": 7.0, "bytes_accessed": 0.0}
    assert summarize_cost_analysis([]) == {"flops": 0.0,
                                           "bytes_accessed": 0.0}


def test_summarize_folds_unknown_sentinel():
    # XLA reports -1 for properties it cannot count (a program whose
    # only op is a Pallas custom call, e.g. pallas_gru_iter_fwd); the
    # sentinel folds to 0 instead of poisoning the schema-valid total.
    out = summarize_cost_analysis([
        {"flops": -1.0, "bytes accessed": 64.0},
        {"flops": 10.0, "bytes accessed": -1.0},
    ])
    assert out == {"flops": 10.0, "bytes_accessed": 64.0}


def test_summarize_matches_real_cpu_compile():
    f = jax.jit(lambda a, b: a @ b)
    compiled = f.lower(jax.ShapeDtypeStruct((8, 16), "float32"),
                       jax.ShapeDtypeStruct((16, 4), "float32")).compile()
    out = summarize_cost_analysis(compiled.cost_analysis())
    assert out["flops"] > 0 and out["bytes_accessed"] > 0


# --- validator --------------------------------------------------------------


def test_validate_green():
    assert validate_costs(_doc()) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda d: d.update(schema="pvraft_costs/v0"), "schema"),
    (lambda d: d.pop("topology"), "missing field 'topology'"),
    (lambda d: d.update(programs="lots"), "must be a list"),
    (lambda d: d["programs"][0].pop("flops"), "flops"),
    (lambda d: d["programs"][0].update(flops=-1.0), "flops"),
    (lambda d: d["programs"][0].update(bytes_accessed="many"),
     "bytes_accessed"),
    (lambda d: d["programs"][0].update(ok=False, error="boom"),
     "not ok"),
    (lambda d: d["programs"][0].update(target=""), "target"),
    (lambda d: d["programs"][0].pop("memory"), "missing memory"),
    (lambda d: d["programs"][0]["memory"].update(
        temp_size_in_bytes=-5), "temp_size_in_bytes"),
    (lambda d: d["programs"][0]["memory"].pop("live_bytes_estimate"),
     "live_bytes_estimate"),
    (lambda d: d["programs"][0]["memory"].update(fits_16GiB_hbm="yes"),
     "fits_16GiB_hbm"),
    (lambda d: d["programs"].append(
        copy.deepcopy(d["programs"][0])), "duplicate"),
])
def test_validate_red(mutate, fragment):
    doc = _doc()
    mutate(doc)
    problems = validate_costs(doc)
    assert problems and any(fragment in p for p in problems), problems


# --- registry coverage ------------------------------------------------------


def _registry_specs():
    from pvraft_tpu.programs import load_catalog, specs

    load_catalog()
    return list(specs().values())


def test_check_coverage_both_directions():
    specs = _registry_specs()
    covered = [_record(name=s.name) for s in specs if not s.expect_failure]
    doc = _doc(records=covered)
    assert check_coverage(doc, specs) == []
    # A missing spec is reported…
    missing = _doc(records=covered[1:])
    assert any(covered[0]["name"] in p
               for p in check_coverage(missing, specs))
    # …and so is a stale record naming no live spec.
    stale = _doc(records=covered + [_record(name="ghost_program")])
    assert any("ghost_program" in p and "stale" in p
               for p in check_coverage(stale, specs))


def test_committed_costs_artifact_pinned():
    """THE drift pin (mirrors test_programs_list_matches_committed_
    artifact): the committed inventory is schema-valid and covers every
    non-expect_failure registry spec, no more, no less. Regenerate with
    `python -m pvraft_tpu.programs costs --out
    artifacts/programs_costs.json` (needs the libtpu toolchain; ~30 min
    cold, much less on a warm artifacts/xla_cache)."""
    assert os.path.exists(ARTIFACT), (
        "artifacts/programs_costs.json is missing — regenerate (see "
        "artifacts/README.md)")
    assert validate_costs_file(ARTIFACT) == []
    doc = json.load(open(ARTIFACT, encoding="utf-8"))
    specs = _registry_specs()
    assert check_coverage(doc, specs, path=ARTIFACT) == [], (
        "cost inventory drifted from the program registry — regenerate "
        "artifacts/programs_costs.json")
    # The excluded list is exactly the expect_failure slice (documented
    # OOM programs are compile-gate evidence, not cost records).
    assert doc["excluded_expect_failure"] == sorted(
        s.name for s in specs if s.expect_failure)
    # Every topology record really came from the TPU pipeline and every
    # audit/profile record from the host leg.
    by_name = {r["name"]: r for r in doc["programs"]}
    for s in specs:
        if s.expect_failure:
            continue
        rec = by_name[s.name]
        assert rec["target"] == (s.topology if s.topology else "host"), (
            s.name)


def test_costs_check_cli(tmp_path, capsys):
    from pvraft_tpu.programs.__main__ import main

    assert main(["costs", "--check", ARTIFACT]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(_doc(records=[_record(ok=False,
                                                    error="x")])))
    assert main(["costs", "--check", str(bad)]) == 1
