"""Voxel binning vs a direct numpy oracle (semantics of model/corr.py:47-69)."""

import numpy as np
import jax.numpy as jnp

from pvraft_tpu.ops.voxel import voxel_bin_means


def _oracle(corr, rel, num_levels, base_scale, resolution):
    b, n, k = corr.shape
    half = resolution // 2
    r3 = resolution**3
    out = np.zeros((b, n, num_levels * r3), np.float32)
    for lvl in range(num_levels):
        r = base_scale * (2**lvl)
        for bi in range(b):
            for ni in range(n):
                sums = np.zeros(r3)
                cnts = np.zeros(r3)
                for ki in range(k):
                    dv = np.round(rel[bi, ni, ki] / r)
                    if np.all(np.abs(dv) <= half):
                        ix = int(
                            (dv[0] + half) * resolution**2
                            + (dv[1] + half) * resolution
                            + (dv[2] + half)
                        )
                        sums[ix] += corr[bi, ni, ki]
                        cnts[ix] += 1.0
                out[bi, ni, lvl * r3 : (lvl + 1) * r3] = sums / np.clip(cnts, 1, n)
    return out


def test_voxel_bin_means_matches_oracle():
    rng = np.random.default_rng(0)
    b, n, k = 2, 6, 40
    corr = rng.normal(size=(b, n, k)).astype(np.float32)
    rel = rng.uniform(-2.0, 2.0, size=(b, n, k, 3)).astype(np.float32)
    got = np.asarray(
        voxel_bin_means(jnp.asarray(corr), jnp.asarray(rel), 3, 0.25, 3)
    )
    want = _oracle(corr, rel, 3, 0.25, 3)
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_voxel_all_invalid_gives_zeros():
    # Candidates far outside every cube level: means must be exactly zero.
    corr = jnp.ones((1, 3, 8), jnp.float32)
    rel = jnp.full((1, 3, 8, 3), 100.0, jnp.float32)
    out = np.asarray(voxel_bin_means(corr, rel, 2, 0.25, 3))
    np.testing.assert_array_equal(out, 0.0)


def test_voxel_single_cell_mean():
    # All candidates at the query point -> center cell mean = mean(corr).
    # N >= K so the count clamp (corr.py:65 semantics: clip to [1, N]) is inert.
    rng = np.random.default_rng(1)
    corr = rng.normal(size=(1, 32, 16)).astype(np.float32)
    rel = np.zeros((1, 32, 16, 3), np.float32)
    out = np.asarray(voxel_bin_means(jnp.asarray(corr), jnp.asarray(rel), 1, 0.25, 3))
    center = 13  # (1,1,1) of a 3x3x3 cube
    np.testing.assert_allclose(out[:, :, center], corr.mean(-1), atol=1e-5)
    rest = np.delete(out, center, axis=-1)
    np.testing.assert_array_equal(rest, 0.0)
