"""Golden-value regression test.

Pins the full forward pass (fixed seeds, tiny config) to values captured on
the CPU backend. Catches unintended numerical drift anywhere in the
ops/model stack — the role the reference delegates to re-running published
checkpoints (SURVEY.md §4). Tolerances absorb backend differences (CPU vs
TPU matmul order), not semantic changes.
"""

import pytest

pytestmark = pytest.mark.slow  # full-model golden regressions (~2 min)

import numpy as np
import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.models.raft import PVRaft

GOLDEN_SUM = -214.65081787109375
GOLDEN_ABSMEAN = 0.5731257200241089
GOLDEN_LAST5 = np.asarray(
    [
        [-1.6915783882141113, 0.825812816619873, 0.03206080198287964],
        [-0.8794500827789307, -1.0033411979675293, -0.4174124002456665],
        [-1.8202546834945679, -0.9756306409835815, 0.33336758613586426],
        [-1.4932647943496704, -1.61688232421875, 0.23034626245498657],
        [-1.9090666770935059, -1.4565377235412598, 0.2609832286834717],
    ],
    np.float32,
)


def test_forward_matches_golden():
    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8)
    rng = np.random.default_rng(42)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    model = PVRaft(cfg)
    params = model.init(jax.random.key(7), xyz1, xyz2, 2)
    flows, _ = model.apply(params, xyz1, xyz2, num_iters=3)
    f = np.asarray(flows)
    assert f.shape == (3, 1, 64, 3)
    np.testing.assert_allclose(float(f.sum()), GOLDEN_SUM, rtol=1e-4)
    np.testing.assert_allclose(float(np.abs(f).mean()), GOLDEN_ABSMEAN, rtol=1e-4)
    np.testing.assert_allclose(f[-1, 0, :5, :], GOLDEN_LAST5, atol=1e-3)


GOLDEN_REFINE_SUM = 61.69562530517578
GOLDEN_REFINE_ABSMEAN = 0.5893515944480896


def test_refine_forward_matches_golden():
    from pvraft_tpu.models.raft import PVRaftRefine

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8)
    rng = np.random.default_rng(123)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    model = PVRaftRefine(cfg)
    params = model.init(jax.random.key(9), xyz1, xyz2, 2)
    out = np.asarray(model.apply(params, xyz1, xyz2, num_iters=2))
    assert out.shape == (1, 64, 3)
    np.testing.assert_allclose(float(out.sum()), GOLDEN_REFINE_SUM, rtol=1e-4)
    np.testing.assert_allclose(
        float(np.abs(out).mean()), GOLDEN_REFINE_ABSMEAN, rtol=1e-4
    )
