"""Golden-value regression test.

Pins the full forward pass (fixed seeds, tiny config) to values captured on
the CPU backend. Catches unintended numerical drift anywhere in the
ops/model stack — the role the reference delegates to re-running published
checkpoints (SURVEY.md §4). Tolerances absorb backend differences (CPU vs
TPU matmul order), not semantic changes.
"""

import pytest

pytestmark = pytest.mark.slow  # full-model golden regressions (~2 min)

import numpy as np
import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.models.raft import PVRaft

# Re-recorded 2026-08-03: the seed-era goldens (sum -214.65081787109375,
# absmean 0.5731257200241089) stopped reproducing on this toolchain —
# same drift family as the Mosaic integer-iota finding (PR 5): the
# values shifted wholesale (sum -187.09, 13% — init-RNG/toolchain, not
# accumulated rounding), identically at clean HEAD via stash, and the
# new values are bit-identical across repeated runs (measured twice,
# zero drift). Deterministic => re-record and keep the tight rtol; a
# future semantic regression still fails loudly.
GOLDEN_SUM = -187.0948944091797
GOLDEN_ABSMEAN = 0.8728618025779724
GOLDEN_LAST5 = np.asarray(
    [
        [0.132321, -2.4259493, 0.8612467],
        [0.6288971, -2.4792671, 1.4954656],
        [0.15185273, -2.0792136, 1.5277123],
        [0.61472976, -3.0350182, 0.65561765],
        [0.41993234, -3.167265, 0.33709383],
    ],
    np.float32,
)


def test_forward_matches_golden():
    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8)
    rng = np.random.default_rng(42)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    model = PVRaft(cfg)
    params = model.init(jax.random.key(7), xyz1, xyz2, 2)
    flows, _ = model.apply(params, xyz1, xyz2, num_iters=3)
    f = np.asarray(flows)
    assert f.shape == (3, 1, 64, 3)
    np.testing.assert_allclose(float(f.sum()), GOLDEN_SUM, rtol=1e-4)
    np.testing.assert_allclose(float(np.abs(f).mean()), GOLDEN_ABSMEAN, rtol=1e-4)
    np.testing.assert_allclose(f[-1, 0, :5, :], GOLDEN_LAST5, atol=1e-3)


# Re-recorded 2026-08-03 with the stage-1 goldens above (previous values
# sum 61.69562530517578, absmean 0.5893515944480896) — same measured
# toolchain drift, bit-identical across repeated runs after re-record.
GOLDEN_REFINE_SUM = -130.408447265625
GOLDEN_REFINE_ABSMEAN = 0.8762915730476379


def test_refine_forward_matches_golden():
    from pvraft_tpu.models.raft import PVRaftRefine

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8)
    rng = np.random.default_rng(123)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 64, 3)).astype(np.float32))
    model = PVRaftRefine(cfg)
    params = model.init(jax.random.key(9), xyz1, xyz2, 2)
    out = np.asarray(model.apply(params, xyz1, xyz2, num_iters=2))
    assert out.shape == (1, 64, 3)
    np.testing.assert_allclose(float(out.sum()), GOLDEN_REFINE_SUM, rtol=1e-4)
    np.testing.assert_allclose(
        float(np.abs(out).mean()), GOLDEN_REFINE_ABSMEAN, rtol=1e-4
    )
