"""Train-epoch data-path parity vs the reference loader (slow tier).

One lock-stepped epoch over the same on-disk FT3D tree: reference
``datasets/generic.py`` subsample/reject-advance + ``Batch`` + torch
``DataLoader`` vs our ``FT3D`` + ``PrefetchLoader``. See
scripts/loader_parity.py for the claim decomposition."""

import os

import pytest

REF_ROOT = "/root/reference"

pytestmark = [
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_ROOT, "datasets")),
        reason="reference checkout not available",
    ),
    pytest.mark.slow,
]


def test_train_epoch_data_path_matches_reference():
    from scripts.loader_parity import run

    rec = run(n_scenes=13, n_points=128)
    assert rec["ok"], rec["checks"]
    # 12 train scenes (1 val carve-out), one rejected + replaced: still a
    # full-length epoch with one duplicated successor on BOTH sides.
    assert rec["ref_scenes"] == rec["our_scenes"] == 12
    assert rec["max_scene_multiplicity"] == 2
    assert rec["tensor_mismatches"] == []
