"""pvraft_events/v1: schema validator red/green per event type, the
non-finite float encoding, the EventLog writer discipline, the committed
golden fixture, and the CLI gate."""

import json
import os

import pytest

from pvraft_tpu.obs import (
    EventLog,
    RunTelemetry,
    run_metadata,
    sanitize,
    validate_event,
    validate_events,
    validate_events_file,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "golden_run.events.jsonl")


# --- sanitize ---------------------------------------------------------------


def test_sanitize_nonfinite_and_numpy():
    import numpy as np

    out = sanitize({
        "a": float("nan"), "b": float("inf"), "c": float("-inf"),
        "d": np.float32(1.5), "e": np.arange(3), "f": [float("nan")],
    })
    assert out == {"a": "NaN", "b": "Infinity", "c": "-Infinity",
                   "d": 1.5, "e": [0, 1, 2], "f": ["NaN"]}
    # The result must be STRICT json (no bare NaN tokens).
    assert "NaN" not in json.dumps(out).replace('"NaN"', "")


# --- per-record validation --------------------------------------------------


def _record(etype, seq=0, **fields):
    base = {"schema": "pvraft_events/v1", "type": etype, "time": 1.0,
            "seq": seq}
    base.update(fields)
    return base


def test_validate_event_green():
    assert validate_event(
        _record("step", epoch=0, step=1, loss=0.5, epe=1.0), seq=0) == []
    assert validate_event(
        _record("step", epoch=0, step=1, loss="NaN", epe="Infinity"),
        seq=0) == []  # non-finite spellings are numbers
    assert validate_event(
        _record("epoch_summary", epoch=0, steps=0), seq=0) == []


@pytest.mark.parametrize("mutate, fragment", [
    (lambda r: r.pop("schema"), "missing base field"),
    (lambda r: r.update(schema="pvraft_events/v0"), "!="),
    (lambda r: r.update(type="nope"), "unknown event type"),
    (lambda r: r.pop("loss"), "missing field 'loss'"),
    (lambda r: r.update(loss="oops"), "not a number"),
    (lambda r: r.update(extra_field=1), "unknown field"),
    (lambda r: r.update(seq=7), "seq"),
])
def test_validate_event_red(mutate, fragment):
    record = _record("step", epoch=0, step=1, loss=0.5, epe=1.0)
    mutate(record)
    problems = validate_event(record, seq=0)
    assert problems and any(fragment in p for p in problems), problems


def test_validate_event_enum_fields():
    bad = _record("divergence", epoch=0, step=1, reason="bored", loss=1.0)
    assert any("reason" in p for p in validate_event(bad, seq=0))
    bad = _record("trace_window", action="pause", trace_dir="/x", epoch=0)
    assert any("action" in p for p in validate_event(bad, seq=0))


def test_validate_recompile_events():
    ok = _record("recompile", program="train_step", count=2, baseline=1,
                 signature="f32[2,8,3]", context="train")
    assert validate_event(ok, seq=0) == []
    bad = _record("recompile", program="", count=2)
    assert any("program" in p for p in validate_event(bad, seq=0))
    bad = _record("recompile", program="train_step", count=-1)
    assert any(">= 0" in p for p in validate_event(bad, seq=0))
    bad = _record("recompile", program="train_step")
    assert any("missing field 'count'" in p
               for p in validate_event(bad, seq=0))


def test_validate_device_memory_events():
    row = {"device_id": 0, "bytes_in_use": 1024,
           "peak_bytes_in_use": 2048, "bytes_limit": 4096,
           "platform": "tpu"}
    ok = _record("device_memory", devices=[row], context="serve")
    assert validate_event(ok, seq=0) == []
    # Negative byte counts are a writer bug, not data.
    bad = _record("device_memory",
                  devices=[dict(row, bytes_in_use=-1)])
    assert any("bytes_in_use" in p and ">= 0" in p
               for p in validate_event(bad, seq=0))
    # Unknown device: a row whose id is not a non-negative integer.
    for dev in (-1, "tpu:0", None, 1.5, True):
        bad = _record("device_memory",
                      devices=[dict(row, device_id=dev)])
        assert any("not a known device" in p
                   for p in validate_event(bad, seq=0)), dev
    # Missing rows / empty list / stray fields all fail.
    assert any("non-empty list" in p for p in validate_event(
        _record("device_memory", devices=[]), seq=0))
    assert any("non-empty list" in p for p in validate_event(
        _record("device_memory", devices={"0": row}), seq=0))
    bad = _record("device_memory", devices=[dict(row, hbm="big")])
    assert any("unknown field 'hbm'" in p
               for p in validate_event(bad, seq=0))
    bad = _record("device_memory", devices=[{"device_id": 0}])
    assert any("missing 'bytes_in_use'" in p
               for p in validate_event(bad, seq=0))


# --- stream-level validation ------------------------------------------------


def test_validate_events_header_first_and_seq():
    lines = [json.dumps(_record("step", seq=0, epoch=0, step=1, loss=1.0,
                                epe=1.0))]
    problems = validate_events(lines)
    assert any("first record must be run_header" in p for p in problems)


def test_validate_events_rejects_bare_nan_token():
    # json.dumps happily writes bare NaN — which is NOT strict JSON and
    # exactly what sanitize() exists to prevent.
    line = json.dumps(_record("step", epoch=0, step=1,
                              loss=float("nan"), epe=1.0))
    assert "NaN" in line
    problems = validate_events([line])
    assert any("not strict JSON" in p for p in problems)


def test_validate_events_blank_line_and_empty():
    assert any("empty" in p for p in validate_events([]))
    problems = validate_events(["", ""])
    assert any("blank line" in p for p in problems)


# --- EventLog writer --------------------------------------------------------


def test_eventlog_writes_valid_stream(tmp_path):
    path = str(tmp_path / "run.events.jsonl")
    log = EventLog(path, enabled=True)
    log.emit("run_header", **run_metadata({}, mode="train"))
    log.emit("step", epoch=0, step=1, loss=float("nan"), epe=0.5)
    log.emit("epoch_summary", epoch=0, steps=1, loss=0.5, epe=0.5)
    log.close()
    assert validate_events_file(path) == []
    records = [json.loads(l) for l in open(path)]
    assert [r["seq"] for r in records] == [0, 1, 2]
    assert records[1]["loss"] == "NaN"


def test_eventlog_rejects_invalid_emit(tmp_path):
    log = EventLog(str(tmp_path / "x.jsonl"), enabled=True)
    with pytest.raises(ValueError, match="invalid"):
        log.emit("step", epoch=0)  # missing required fields
    with pytest.raises(ValueError, match="unknown event type"):
        log.emit("definitely_not_a_type", foo=1)
    log.close()


def test_eventlog_resume_continues_seq(tmp_path):
    # A resumed run (train.py --resume reuses the exp dir) appends to the
    # same file; the seq chain must continue or the stream fails its own
    # validator.
    path = str(tmp_path / "run.events.jsonl")
    log = EventLog(path, enabled=True)
    log.emit("run_header", **run_metadata({}, mode="train"))
    log.emit("step", epoch=0, step=1, loss=1.0, epe=1.0)
    log.close()
    resumed = EventLog(path, enabled=True)
    assert resumed.seq == 2
    resumed.emit("run_header", **run_metadata({}, mode="train"))
    resumed.emit("step", epoch=1, step=2, loss=0.9, epe=0.9)
    resumed.close()
    assert validate_events_file(path) == []


def test_eventlog_disabled_is_noop(tmp_path):
    path = str(tmp_path / "x.jsonl")
    log = EventLog(path, enabled=False)  # the non-zero-rank role
    assert log.emit("step", epoch=0, step=1, loss=1.0, epe=1.0) is None
    log.close()
    assert not os.path.exists(path)


# --- RunTelemetry fan-out ---------------------------------------------------


def test_run_telemetry_fans_out_to_tb_and_events(tmp_path):
    sink = RunTelemetry(str(tmp_path / "exp"), "Train", "synthetic")
    sink.emit_header({}, mode="train")
    sink.emit_step(0, 1, 0.5, 1.0,
                   telemetry={"grad_norm": 2.0, "update_ratio": 1e-4})
    sink.emit_eval("val", 0, 4, {"epe3d": 0.9, "loss": 0.4})
    sink.close()
    # TB consumers saw the reference tags…
    assert sink.tb.history["Train/Loss"] == [(1, 0.5)]
    assert sink.tb.history["telemetry/grad_norm"] == [(1, 2.0)]
    assert sink.tb.history["Val/EPE"] == [(0, 0.9)]
    # …and the event stream is the same information, valid.
    path = str(tmp_path / "exp" / "train.events.jsonl")
    assert validate_events_file(path) == []
    types = [json.loads(l)["type"] for l in open(path)]
    assert types == ["run_header", "step", "eval"]


# --- golden fixture + CLI ---------------------------------------------------


def test_golden_fixture_validates():
    assert validate_events_file(FIXTURE) == []
    records = [json.loads(l) for l in open(FIXTURE)]
    types = {r["type"] for r in records}
    # The fixture exercises every event type the schema defines.
    from pvraft_tpu.obs import EVENT_TYPES

    assert types == set(EVENT_TYPES)


def test_cli_validate(tmp_path, capsys):
    from pvraft_tpu.obs.__main__ import main

    assert main(["validate", FIXTURE]) == 0
    bad = tmp_path / "bad.events.jsonl"
    bad.write_text('{"not": "an event"}\n')
    assert main(["validate", str(bad)]) == 1
    assert main(["validate", FIXTURE, str(bad)]) == 1
