"""REAL two-process distributed test (slow tier).

Launches 2 localhost processes (subprocess + ``jax.distributed.initialize``,
4 virtual CPU devices each -> one 8-device global mesh) running the full
Trainer recipe — sharded train batches, scene-sharded val, msgpack
checkpoints with the process-0 write + visibility barrier — and asserts
params and metrics equal a single-process 8-device run of the identical
config.

This executes the code the monkeypatched guards in tests/test_parallel.py
only simulate: the per-process loader shard, the
``make_array_from_process_local_data`` assembly (parallel/mesh.py:98-141),
``eval_scene_shard`` (mesh.py:57-75), and the checkpoint barrier
(engine/checkpoint.py). Reference analog: the single-process DataParallel
at ``tools/engine.py:51-64`` — this framework claims strictly more, so it
must prove strictly more.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "scripts", "two_process_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _env(n_devices: int) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n_devices}"
    # The conftest's 8-device setting must not leak into the workers.
    env.pop("JAX_COORDINATOR_ADDRESS", None)
    return env


def _run_worker_pair(tmp_path, tag, extra_args, out_for, timeout=1500):
    """Launch 2 lock-stepped workers and wait for both.

    Workers write stdout to FILES, not PIPEs: both processes run in
    collective lockstep, so if one blocked on a full 64 KB pipe buffer
    while the other was being drained first, both would deadlock until
    the timeout. A hung peer is killed so it can't leak past the test.
    ``out_for(i)`` gives worker i's --out value."""
    port = _free_port()
    log_paths = [tmp_path / f"{tag}_{i}.log" for i in range(2)]
    log_files = [open(p, "w") for p in log_paths]
    try:
        procs = [
            subprocess.Popen(
                [sys.executable, WORKER,
                 "--coordinator", f"localhost:{port}",
                 "--num_processes", "2", "--process_id", str(i),
                 "--out", out_for(i), *extra_args],
                env=_env(4), stdout=log_files[i],
                stderr=subprocess.STDOUT,
            )
            for i in range(2)
        ]
        try:
            for p in procs:
                p.wait(timeout=timeout)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
    finally:
        for f in log_files:
            f.close()
    for i, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {i} failed:\n{log_paths[i].read_text()[-4000:]}")


def test_two_process_matches_single_process(tmp_path):
    # --- 2 processes x 4 devices ------------------------------------------
    outs = [str(tmp_path / f"two_{i}.npz") for i in range(2)]
    _run_worker_pair(
        tmp_path, "worker",
        ["--exp_path", str(tmp_path / "exp_two")],
        out_for=lambda i: outs[i],
    )

    # --- 1 process x 8 devices (identical recipe) -------------------------
    single_out = str(tmp_path / "single.npz")
    p = subprocess.run(
        [sys.executable, WORKER,
         "--exp_path", str(tmp_path / "exp_single"), "--out", single_out],
        env=_env(8), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=1500,
    )
    assert p.returncode == 0, p.stdout.decode(errors="replace")[-4000:]

    two = np.load(outs[0])
    single = np.load(single_out)
    assert set(two.files) == set(single.files)

    # Metrics: train losses and the scene-sharded val means must agree.
    np.testing.assert_allclose(two["__train_loss"], single["__train_loss"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(two["__val_epe3d"], single["__val_epe3d"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(two["__val_loss"], single["__val_loss"],
                               rtol=1e-5, atol=1e-6)

    # Params after 2 epochs: the block-cyclic loader shard puts the SAME
    # rows on the SAME devices as the single-process run, so the only
    # remaining divergence source is the cross-process collective runtime
    # itself: an 8-way psum spanning 2 processes reduces in a different
    # order than the intra-process one, giving ~1e-7 fp noise in grads.
    # Adam turns near-zero-grad elements' sign flips into ~lr-scale update
    # differences (observed: 2/32 elements of one GN bias at 1.1e-4 after
    # 2 epochs, every other element bitwise-equal), so the gate is an
    # lr-amplification bound plus a mean bound that keeps the drift
    # confined to isolated near-zero elements — a sharding bug
    # (duplicated/missing rows) moves grads at O(grad) and fails both.
    for k in single.files:
        if k.startswith("__"):
            continue
        diff = np.abs(two[k] - single[k])
        assert diff.max() <= 5e-4, (
            f"param leaf {k} diverged between 2-process and single-process "
            f"runs: max {diff.max()}")
        assert diff.mean() <= 2e-5, (
            f"param leaf {k} drifted broadly (mean {diff.mean()}): not "
            f"isolated near-zero Adam flips")

    # The val pass really was scene-sharded in the 2-process run (the gate
    # fired), not silently redundant.
    import json

    with open(outs[0] + ".json") as f:
        meta = json.load(f)
    assert meta["process_count"] == 2
    assert meta["val_shard_world"] == 2, meta

    # The shared checkpoint dir was written by process 0 and passed the
    # post-barrier visibility check (no RuntimeError above); sanity that
    # the files exist for a future resume.
    ckpts = os.listdir(tmp_path / "exp_two" / "checkpoints")
    assert any(c.startswith("last_checkpoint") for c in ckpts), ckpts


def test_two_process_orbax_crash_recover_resume(tmp_path):
    """The orbax backend's multi-host selling point — async write, crash
    between commit and promote, recovery, resume — under REAL
    ``jax.distributed`` processes (round-4 verdict weak #7: this path was
    only ever tested single-process).

    Phase 1 (crash): a 2-process pair trains 1 epoch with
    ``ckpt_backend=orbax`` (no val, so the promote stays deferred), lets
    the async commit settle, and hard-exits WITHOUT promoting: the only
    checkpoint on disk is ``last_checkpoint.orbax.tmp`` + its
    ``.extras.json`` debt (the owed ``000.orbax`` copy) and
    ``.epoch.json`` sidecars.

    Phase 2 (recover+resume): a fresh 2-process pair resumes:
    ``latest_checkpoint`` runs ``_recover_leftover_tmp`` across both
    processes (process-0 adoption + ``_sync_hosts`` barriers), adopts the
    tmp, delivers the sidecar debt, and the Trainer continues from epoch
    1 to completion."""
    import json

    exp = str(tmp_path / "exp_orbax")
    ckdir = tmp_path / "exp_orbax" / "checkpoints"

    crash_outs = [str(tmp_path / f"crash_{i}") for i in range(2)]
    _run_worker_pair(
        tmp_path, "orbax_crash",
        ["--exp_path", exp, "--ckpt_backend", "orbax", "--epochs", "1",
         "--skip_val", "--die_before_promote"],
        out_for=lambda i: crash_outs[i],
        timeout=1500,
    )
    names = sorted(os.listdir(ckdir))
    assert "last_checkpoint.orbax.tmp" in names, names
    assert "last_checkpoint.orbax" not in names, names
    assert "last_checkpoint.orbax.tmp.extras.json" in names, names
    assert "last_checkpoint.orbax.tmp.epoch.json" in names, names
    assert "000.orbax" not in names, names  # the owed copy: not yet

    resume_outs = [str(tmp_path / f"resume_{i}.npz") for i in range(2)]
    _run_worker_pair(
        tmp_path, "orbax_resume",
        ["--exp_path", exp, "--ckpt_backend", "orbax", "--epochs", "2",
         "--resume"],
        out_for=lambda i: resume_outs[i],
        timeout=1500,
    )
    with open(resume_outs[0] + ".json") as f:
        meta = json.load(f)
    assert meta["process_count"] == 2
    # The adopted tmp held epoch 0 -> resume continues at epoch 1.
    assert meta["resumed_from_epoch"] == 1, meta
    assert len(meta["history"]) == 1

    names = sorted(os.listdir(ckdir))
    assert "last_checkpoint.orbax" in names, names
    assert "last_checkpoint.orbax.tmp" not in names, names
    assert "last_checkpoint.orbax.tmp.extras.json" not in names, names
    # The crashed run's sidecar debt (the 000 epoch copy) was delivered.
    assert "000.orbax" in names, names
    # wait_for_saves at worker exit promoted the final epoch's write too,
    # and the cheap-epoch sidecar travelled with it.
    with open(ckdir / "last_checkpoint.orbax.epoch.json") as f:
        assert json.load(f)["epoch"] == 1
    assert "001.orbax" in names, names


def test_two_process_evaluator_scene_sharding(tmp_path):
    """The STANDALONE Evaluator's multi-host scene-sharding
    (engine/evaluator.py + eval_scene_shard) under real processes: 2 x 4
    devices split the 16 scenes (shard gate fires), single-process runs
    them replicated — the mean*count accumulation must make the metric
    means identical up to fp reassociation."""
    import json

    out2 = str(tmp_path / "eval_two")
    _run_worker_pair(
        tmp_path, "evalw",
        ["--mode", "eval", "--exp_path", str(tmp_path / "exp_eval2")],
        out_for=lambda i: out2,
        timeout=900,
    )

    out1 = str(tmp_path / "eval_single")
    p = subprocess.run(
        [sys.executable, WORKER, "--mode", "eval",
         "--exp_path", str(tmp_path / "exp_eval1"), "--out", out1],
        env=_env(8), stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=900,
    )
    assert p.returncode == 0, p.stdout.decode(errors="replace")[-4000:]

    with open(out2 + ".json") as f:
        two = json.load(f)
    with open(out1 + ".json") as f:
        single = json.load(f)
    # The 2-process run really scene-sharded (the gate fired).
    assert two["process_count"] == 2 and two["shard_world"] == 2, two
    assert single["shard_world"] == 1
    assert set(two["means"]) == set(single["means"])
    for k in single["means"]:
        assert abs(two["means"][k] - single["means"][k]) <= 1e-5, (
            k, two["means"], single["means"])
