"""Round-trip gate for ``scripts/export_checkpoint.py``: a trained
msgpack checkpoint -> torch ``.params`` file -> ``load_torch_checkpoint``
reimport must reproduce the original parameter tree exactly. The
converter pair was previously only tested in-memory
(``test_reference_parity``); this drives the actual CLI file path,
including the payload-shape normalization (``load_params``) and the
epoch field."""

import runpy
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # torch + real model init (~1 min)

import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.engine.checkpoint import (
    load_torch_checkpoint,
    save_checkpoint,
)

CFG = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)


def _init_params(model, rng_seed=0):
    rng = np.random.default_rng(rng_seed)
    pc = jnp.asarray(rng.uniform(-1, 1, (1, 24, 3)).astype(np.float32))
    return model.init(jax.random.key(rng_seed), pc, pc, 2)


def _run_export(argv):
    old = sys.argv
    sys.argv = ["export_checkpoint.py"] + argv
    try:
        with pytest.raises(SystemExit) as e:
            runpy.run_path("scripts/export_checkpoint.py",
                           run_name="__main__")
        assert e.value.code in (0, None)
    finally:
        sys.argv = old


def _assert_tree_equal(got, want, path=""):
    assert set(got.keys()) == set(want.keys()), (
        f"{path}: {sorted(got)} != {sorted(want)}")
    for k in want:
        g, w = got[k], want[k]
        if isinstance(w, dict):
            _assert_tree_equal(g, w, f"{path}/{k}")
        else:
            np.testing.assert_array_equal(
                np.asarray(g), np.asarray(w), err_msg=f"{path}/{k}")


@pytest.mark.parametrize("refine", [False, True])
def test_export_roundtrip(tmp_path, refine):
    from pvraft_tpu.models.raft import PVRaft, PVRaftRefine

    model = (PVRaftRefine if refine else PVRaft)(CFG)
    params = _init_params(model)
    ckpt_dir = str(tmp_path / "ckpts")
    save_checkpoint(ckpt_dir, params, opt_state={}, epoch=7,
                    checkpoint_interval=0)
    src = str(tmp_path / "ckpts" / "last_checkpoint.msgpack")
    dst = str(tmp_path / "exported.params")
    _run_export([src, dst] + (["--refine"] if refine else []))

    tree, epoch = load_torch_checkpoint(dst, refine=refine)
    assert epoch == 7
    _assert_tree_equal(tree, params["params"])


def test_epochless_payload_yields_sentinel(tmp_path):
    """A payload with no 'epoch' key loads as epoch -1 — the explicit
    'unknown' sentinel — pinned so the pre-refactor default (a fake
    epoch 0, indistinguishable from a real first epoch) doesn't silently
    come back. Covers both payload shapes load_params normalizes."""
    from flax import serialization

    from pvraft_tpu.engine.checkpoint import load_params

    inner = {"dense": {"kernel": np.zeros((2, 2), np.float32)}}
    for payload in ({"params": {"params": inner}},   # full variables dict
                    {"params": inner}):              # bare inner tree
        src = tmp_path / "bare.msgpack"
        src.write_bytes(serialization.msgpack_serialize(payload))
        variables, epoch = load_params(str(src))
        assert epoch == -1
        assert set(variables.keys()) == {"params"}
        np.testing.assert_array_equal(
            np.asarray(variables["params"]["dense"]["kernel"]),
            inner["dense"]["kernel"])


def test_export_refine_flag_rejects_stage1(tmp_path):
    """--refine on a stage-1 checkpoint fails fast (no silent export of
    a mislabeled tree)."""
    from pvraft_tpu.models.raft import PVRaft

    params = _init_params(PVRaft(CFG))
    ckpt_dir = str(tmp_path / "ckpts")
    save_checkpoint(ckpt_dir, params, opt_state={}, epoch=0,
                    checkpoint_interval=0)
    src = str(tmp_path / "ckpts" / "last_checkpoint.msgpack")
    old = sys.argv
    sys.argv = ["export_checkpoint.py", src,
                str(tmp_path / "out.params"), "--refine"]
    try:
        with pytest.raises(SystemExit) as e:
            runpy.run_path("scripts/export_checkpoint.py",
                           run_name="__main__")
        assert e.value.code not in (0, None)
    finally:
        sys.argv = old
