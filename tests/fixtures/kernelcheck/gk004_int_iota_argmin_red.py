"""RED (GK004): the PR-5 silent Mosaic regression, pre-fix shape.

Parsed, never executed. This is the fused-lookup kernel's original
first-of-ties argmin: an INTEGER ``broadcasted_iota`` fed into a
``jnp.min`` reduction. It compiled for months, then Mosaic toolchain
drift removed the integer min-reduction lowering and the kernel
silently stopped compiling at HEAD (found and fixed in PR 5 by
generating the iota as i32 and casting to f32 — exact for candidate
indices up to 2^24). GK004's ``int-minmax-reduce`` hazard must keep
this shape DETECTED so the class can never return unnoticed.
"""

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _argmin_kernel(dist_ref, o_ref):
    dist = dist_ref[0]
    # Pre-fix shape: integer iota, integer min-reduction over it.
    iota = lax.broadcasted_iota(jnp.int32, dist.shape, 1)
    m = jnp.min(dist, axis=-1, keepdims=True)
    eq = dist == m
    first = jnp.min(jnp.where(eq, iota, dist.shape[-1]), axis=-1)
    o_ref[0] = first.astype(jnp.float32)


def int_argmin():
    x = jax.ShapeDtypeStruct((2, 64, 512), jnp.float32)
    return pl.pallas_call(
        _argmin_kernel,
        grid=(2, 1),
        in_specs=[pl.BlockSpec((1, 64, 512), lambda bi, ni: (bi, ni, 0))],
        out_specs=pl.BlockSpec((1, 64), lambda bi, ni: (bi, ni)),
        out_shape=jax.ShapeDtypeStruct((2, 64), jnp.float32),
        interpret=interpret_mode(),
    )(x)
