"""RED (GK000): a pallas_call whose geometry cannot be modeled.

Parsed, never executed. The dims come from an argument with no literal
value and no ``KERNEL_BINDINGS`` row — the extractor cannot evaluate
the grid or blocks, and the driver must fail the site loudly (a new
kernel either models cleanly or fails the gate; it cannot silently
skip analysis the way the PR-5 regression skipped the compile gate).
"""

import jax
import jax.numpy as jnp

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _copy_kernel(x_ref, o_ref):
    o_ref[0] = x_ref[0]


def dynamic_geometry(x, tile):
    b, n, k = x.shape
    spec = pl.BlockSpec((1, tile, k), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(b, n // tile),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((b, n, k), jnp.float32),
        interpret=interpret_mode(),
    )(x)
