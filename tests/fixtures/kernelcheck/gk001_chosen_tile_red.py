"""RED (GK001): chosen tiles that break the TPU (sublane, lane) layout.

Parsed, never executed. Two distinct misalignments, both on *chosen*
tiles of larger axes (so they are errors, not whole-axis layout notes):

* ``_sublane``: second-minor block dim 60 tiles an axis of 1920 — not a
  multiple of 8 for fp32;
* ``_lane``: last block dim 100 tiles an axis of 400 — not a multiple
  of 128.
"""

import jax
import jax.numpy as jnp

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _copy_kernel(x_ref, o_ref):
    o_ref[0] = x_ref[0]


def misaligned_sublane():
    x = jax.ShapeDtypeStruct((2, 1920, 128), jnp.float32)
    spec = pl.BlockSpec((1, 60, 128), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(2, 32),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 1920, 128), jnp.float32),
        interpret=interpret_mode(),
    )(x)


def misaligned_lane():
    x = jax.ShapeDtypeStruct((2, 64, 400), jnp.float32)
    spec = pl.BlockSpec((1, 64, 100), lambda bi, ki: (bi, 0, ki))
    return pl.pallas_call(
        _copy_kernel,
        grid=(2, 4),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 64, 400), jnp.float32),
        interpret=interpret_mode(),
    )(x)
