"""RED (GK006): pallas_call sites that leak the interpreter escape hatch.

Parsed, never executed. ``no_kwarg`` omits ``interpret=`` entirely (the
kernel can never run on CPU tier-1); ``hardcoded`` pins
``interpret=False`` (same, but looks deliberate); both must route
through ``pvraft_tpu.ops.pallas.interpret_mode()``.
"""

import jax
import jax.numpy as jnp

from pvraft_tpu.compat import import_pallas

pl = import_pallas()


def _copy_kernel(x_ref, o_ref):
    o_ref[0] = x_ref[0]


def no_kwarg():
    x = jax.ShapeDtypeStruct((2, 64, 128), jnp.float32)
    spec = pl.BlockSpec((1, 64, 128), lambda bi: (bi, 0, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(2,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 64, 128), jnp.float32),
    )(x)


def hardcoded():
    x = jax.ShapeDtypeStruct((2, 64, 128), jnp.float32)
    spec = pl.BlockSpec((1, 64, 128), lambda bi: (bi, 0, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(2,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 64, 128), jnp.float32),
        interpret=False,
    )(x)
