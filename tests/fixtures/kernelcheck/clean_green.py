"""GREEN: a fully aligned, fully covered, interpreter-gated kernel.

Parsed by kernelcheck tests, never executed. Literal dims so the static
model needs no geometry binding: blocks (1, 64, 128) over (2, 1024,
128) with grid (2, 16) — lane dim a multiple of 128, sublane a multiple
of 8, exact coverage, tiny VMEM footprint.
"""

import functools

import jax
import jax.numpy as jnp

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _scale_kernel(x_ref, o_ref, *, gain):
    o_ref[0] = x_ref[0] * gain


def clean_scale():
    x = jax.ShapeDtypeStruct((2, 1024, 128), jnp.float32)
    kernel = functools.partial(_scale_kernel, gain=2.0)
    spec = pl.BlockSpec((1, 64, 128), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        kernel,
        grid=(2, 16),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 1024, 128), jnp.float32),
        interpret=interpret_mode(),
    )(x)
