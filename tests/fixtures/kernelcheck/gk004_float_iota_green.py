"""GREEN (GK004): the current (PR-5 fixed) float-iota argmin shape.

Parsed, never executed. The sanctioned fix for the integer-min-
reduction hazard: the iota is *generated* as i32 (Mosaic only supports
32-bit integer iota generation) and immediately ``.astype`` to f32 at
the assignment, so every reduction over it is a float reduction — and
f32 represents candidate indices exactly up to 2^24, far beyond any K
here, so first-of-ties semantics are unchanged. Must stay CLEAN.
"""

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _argmin_kernel(dist_ref, o_ref):
    dist = dist_ref[0]
    iota = lax.broadcasted_iota(
        jnp.int32, dist.shape, 1).astype(jnp.float32)
    cap = jnp.asarray(float(dist.shape[-1]), jnp.float32)
    m = jnp.min(dist, axis=-1, keepdims=True)
    eq = dist == m
    first = jnp.min(jnp.where(eq, iota, cap), axis=-1)
    o_ref[0] = first


def float_argmin():
    x = jax.ShapeDtypeStruct((2, 64, 512), jnp.float32)
    return pl.pallas_call(
        _argmin_kernel,
        grid=(2, 1),
        in_specs=[pl.BlockSpec((1, 64, 512), lambda bi, ni: (bi, ni, 0))],
        out_specs=pl.BlockSpec((1, 64), lambda bi, ni: (bi, ni)),
        out_shape=jax.ShapeDtypeStruct((2, 64), jnp.float32),
        interpret=interpret_mode(),
    )(x)
