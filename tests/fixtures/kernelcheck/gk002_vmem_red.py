"""RED (GK002): double-buffered blocks that blow the VMEM budget.

Parsed, never executed. One (1, 1024, 2048) fp32 block is 8 MiB;
double-buffered in + out is 32 MiB against the ~16 MiB/core budget —
Mosaic would spill or refuse at lowering time; the gate refuses first.
"""

import jax
import jax.numpy as jnp

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _copy_kernel(x_ref, o_ref):
    o_ref[0] = x_ref[0]


def oversized_blocks():
    x = jax.ShapeDtypeStruct((4, 1024, 2048), jnp.float32)
    spec = pl.BlockSpec((1, 1024, 2048), lambda bi: (bi, 0, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(4,),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((4, 1024, 2048), jnp.float32),
        interpret=interpret_mode(),
    )(x)
