"""RED (GK003): grid x block under- and over-covering an operand.

Parsed, never executed. ``under_covered``: 15 grid steps of 64 rows
cover 960 of 1000 — the last 40 rows are never computed and the output
tail is garbage, silently. ``over_covered``: 16 steps of 64 cover 1024
of 1000 — the tail block reads out of bounds (padded) and its writes
are dropped, also silently. Neither kernel masks a remainder.
"""

import jax
import jax.numpy as jnp

from pvraft_tpu.compat import import_pallas
from pvraft_tpu.ops.pallas import interpret_mode

pl = import_pallas()


def _copy_kernel(x_ref, o_ref):
    o_ref[0] = x_ref[0]


def under_covered():
    x = jax.ShapeDtypeStruct((2, 1000, 128), jnp.float32)
    spec = pl.BlockSpec((1, 64, 128), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(2, 15),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 1000, 128), jnp.float32),
        interpret=interpret_mode(),
    )(x)


def over_covered():
    x = jax.ShapeDtypeStruct((2, 1000, 128), jnp.float32)
    spec = pl.BlockSpec((1, 64, 128), lambda bi, ni: (bi, ni, 0))
    return pl.pallas_call(
        _copy_kernel,
        grid=(2, 16),
        in_specs=[spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((2, 1000, 128), jnp.float32),
        interpret=interpret_mode(),
    )(x)
