"""CURRENT shape of the PR 8 in_flight gauge (clean).

The gauge moves under the SAME lock as every counter, so the identity
``requests_total == responses_total + rejected + in_flight`` holds at
EVERY snapshot — the in-tree fix (``serve/metrics.py``).
"""

import threading


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0   # guarded-by: _lock
        self.responses_total = 0  # guarded-by: _lock
        self.in_flight = 0        # guarded-by: _lock

    def record_submit(self):
        with self._lock:
            self.requests_total += 1
            self.in_flight += 1

    def record_batch(self, n):
        with self._lock:
            self.responses_total += n
            self.in_flight -= n

    def snapshot(self):
        with self._lock:
            return {"requests_total": self.requests_total,
                    "responses_total": self.responses_total,
                    "in_flight": self.in_flight}
