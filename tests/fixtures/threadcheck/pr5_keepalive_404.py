"""The sixth review-found race: the PR 5 404 keep-alive desync.

A POST to an unknown path was answered 404 with the request body left
unread; a pooled HTTP/1.1 client reusing the connection then had the
stale body parsed as its next request line — every subsequent request
on that connection failed in confusing ways.

This one is OUT OF STATIC REACH for the GC rules on purpose: the shared
mutable state is the socket stream's read cursor, a protocol-level
invariant ("answer only after consuming the body, or close") that no
lock discipline expresses. It is pinned dynamically instead: the
raw-socket keep-alive tests in ``tests/test_serve.py`` drive the
404-then-reuse sequence against the real server (the in-tree fix sets
``close_connection`` before replying — ``serve/server.py``).

The class below is the distilled FIXED shape, kept here so the corpus
enumerates all six races; ``tests/test_threadcheck.py`` asserts it
checks clean and documents why there is no red twin.
"""


class Connection:
    def __init__(self, stream):
        self.stream = stream
        self.close_connection = False

    def respond_404(self, content_length):
        # The body is left unread: a reused keep-alive connection would
        # parse it as the next request line, so close.
        self.close_connection = True
        return b"HTTP/1.1 404 Not Found\r\nConnection: close\r\n\r\n"
