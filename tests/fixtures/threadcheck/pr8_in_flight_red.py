"""PRE-fix shape of the PR 8 in_flight identity race (detected: GC001).

The in-flight gauge was updated outside the lock that guards every
counter, so the reconciliation identity ``requests_total ==
responses_total + rejected + in_flight`` failed at snapshots taken
mid-update — exactly the kind of "transient lie" a metrics surface
must never tell.
"""

import threading


class Metrics:
    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0   # guarded-by: _lock
        self.responses_total = 0  # guarded-by: _lock
        self.in_flight = 0        # guarded-by: _lock

    def record_submit(self):
        with self._lock:
            self.requests_total += 1
        self.in_flight += 1  # outside the counters' lock

    def record_batch(self, n):
        with self._lock:
            self.responses_total += n
        self.in_flight -= n  # outside the counters' lock

    def snapshot(self):
        with self._lock:
            return {"requests_total": self.requests_total,
                    "responses_total": self.responses_total,
                    "in_flight": self.in_flight}
