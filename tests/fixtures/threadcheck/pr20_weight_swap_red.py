"""PRE-fix shape of the ISSUE-20 concurrent weight-swap race
(detected: GC003).

The fleet router fans ``/admin/reload`` out to every backend, and two
reloads can land on the same replica concurrently (an operator retry
racing the fleet sweep). The naive swap tests ``self._swap_pending``
for exclusivity and assigns it later with no lock — both callers pass
the check, their pointer writes and generation bumps interleave, and
the drain barrier then waits against the WRONG generation: it reports
"drained" while a dispatch still runs on weights the first swap
claims retired. Found during the design review of
``serve/engine.py``'s ``swap_params``; the shipped shape runs the
exclusivity check, the pointer write and the generation bump as one
critical section under the replica lock.
"""

import threading


class Replica:
    def __init__(self, params):
        self._lock = threading.Lock()
        self._swap_pending = None
        self.params = params
        self.generation = 0
        self.in_flight = 0

    def swap_params(self, params):
        if self._swap_pending is not None:   # check...
            raise RuntimeError("a swap is already in flight")
        self._swap_pending = params          # ...then act, no lock held
        self.params = self._swap_pending
        self.generation += 1
        self._swap_pending = None

    def dispatch(self, batch, run):
        with self._lock:
            params = self.params
            self.in_flight += 1
        try:
            return run(params, batch)
        finally:
            with self._lock:
                self.in_flight -= 1
