"""CURRENT shape of the PR 9 monitor lifecycle (clean).

The whole start/stop transition — flag clear, thread swap, join — runs
under one lifecycle lock, so concurrent callers serialize and a
restart always sees a cleared stop flag — the in-tree fix
(``obs/device_memory.py``).
"""

import threading
import time


class Monitor:
    def __init__(self, interval_s=0.05):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._state_lock = threading.Lock()
        self._thread = None  # guarded-by: _state_lock
        self.samples = 0

    def start(self):
        with self._state_lock:
            if self._thread is not None:
                return
            self._stop.clear()  # restartable: stop() leaves it set
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()

    def _run(self):
        while not self._stop.is_set():
            self.samples += 1
            time.sleep(self.interval_s)

    def stop(self):
        with self._state_lock:
            thread = self._thread
            if thread is None:
                return
            self._thread = None
            self._stop.set()
            thread.join(timeout=5.0)
