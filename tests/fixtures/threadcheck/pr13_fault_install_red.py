"""PRE-fix shape of the ISSUE-13 fault-injector install race (detected:
GC003).

The fault injector is process-global: the chaos suite arms and clears
plans while batcher executors traverse fault points concurrently. The
naive ``install`` tests ``self._plan`` and assigns it later with no
lock — two concurrent installers both pass the exclusivity check and
both install, so the "exactly one deterministic schedule" contract
silently becomes last-writer-wins with interleaved counter resets (a
traversal between the two resets fires against half-initialized
state). Found during the design review of ``serve/faults.py``; the
shipped shape runs the whole check-reset-assign transition under the
injector lock.
"""

import threading


class Injector:
    def __init__(self):
        self._lock = threading.Lock()
        self._plan = None
        self._counts = {}
        self._fired_total = 0

    def install(self, plan):
        if self._plan is not None:     # check...
            raise RuntimeError("a plan is already installed")
        self._counts = {}
        self._fired_total = 0
        self._plan = plan              # ...then act, no lock held

    def clear(self):
        self._plan = None

    def fire(self, point):
        if self._plan is None:
            return ()
        with self._lock:
            self._counts[point] = self._counts.get(point, 0) + 1
            self._fired_total += 1
        return (point,)
