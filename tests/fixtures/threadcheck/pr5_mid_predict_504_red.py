"""PRE-fix shape of the PR 5 mid-predict 504 race (detected: GC003).

A waiter that timed out while the engine was mid-predict checked the
done flag and recorded a timeout; the dispatch loop, resolving in the
same instant, recorded a response for the same request. Both ledger
writes landed — the served count lied and the in-flight gauge skewed
permanently.
"""

import threading


class Dispatch:
    def __init__(self):
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._served = 0    # guarded-by: _lock
        self._timeouts = 0  # guarded-by: _lock
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def wait(self, timeout):
        self._done.wait(timeout)
        if not self._done.is_set():   # check: "not finished"...
            self._timeouts += 1       # ...but the worker can resolve and
            return False              # count a response concurrently
        return True

    def _run(self):
        with self._lock:
            self._served += 1
        self._done.set()

    def shutdown(self):
        self._worker.join(timeout=5.0)
