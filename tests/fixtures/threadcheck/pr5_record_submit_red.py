"""PRE-fix shape of the PR 5 record_submit race (detected: GC001).

The submit counter was bumped OUTSIDE the intake critical section: a
worker could dispatch the enqueued request and record its response
before the submit was counted, so a concurrent metrics snapshot saw
``responses_total > requests_total`` — a reconciliation identity no
dashboard should ever show.
"""

import queue
import threading


class Intake:
    def __init__(self):
        self._intake_lock = threading.Lock()
        self._q = queue.Queue(maxsize=8)
        self._accepted = 0  # guarded-by: _intake_lock
        self._worker = threading.Thread(target=self._serve, daemon=True)
        self._worker.start()

    def submit(self, item):
        with self._intake_lock:
            self._q.put_nowait(item)
        # Counted AFTER the enqueue is visible to a worker: the
        # response can reach the ledger first.
        self._accepted += 1

    def _serve(self):
        while True:
            item = self._q.get()
            if item is None:
                return

    def shutdown(self):
        self._q.put(None)
        self._worker.join(timeout=5.0)
