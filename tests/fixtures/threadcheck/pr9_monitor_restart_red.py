"""PRE-fix shape of the PR 9 monitor restart bug family (detected: GC003).

``start``/``stop`` test-then-assign the thread field with no lock: two
concurrent ``start`` calls both pass the ``_thread is not None`` check
and double-start the sampler; ``stop`` racing ``start`` joins a thread
the other call already replaced. (The PR 9 fix also made ``start``
clear the stop flag — ``stop()`` used to leave it set, so a restarted
monitor thread exited immediately; a flag-state bug the lifecycle lock
now makes atomic with the thread swap.)
"""

import threading
import time


class Monitor:
    def __init__(self, interval_s=0.05):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = None
        self.samples = 0

    def start(self):
        if self._thread is not None:   # check...
            return
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()           # ...then act, no lock

    def _run(self):
        while not self._stop.is_set():
            self.samples += 1
            time.sleep(self.interval_s)

    def stop(self):
        if self._thread is None:       # same shape on the stop side
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
