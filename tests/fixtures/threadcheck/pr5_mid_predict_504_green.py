"""CURRENT shape of the PR 5 mid-predict 504 accounting (clean).

Exactly ONE party records the request's outcome: whoever wins the
non-blocking finalize token does the ledger write under the lock, the
loser records nothing — the in-tree fix (``serve/batcher.py``
``_Request.finalize`` + ``record_failure_for``).
"""

import threading


class Dispatch:
    def __init__(self):
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._final = threading.Lock()  # outcome token (try-acquire)
        self._served = 0    # guarded-by: _lock
        self._timeouts = 0  # guarded-by: _lock
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def wait(self, timeout):
        if not self._done.wait(timeout):
            if self._final.acquire(blocking=False):
                with self._lock:
                    self._timeouts += 1
            return False
        return True

    def _run(self):
        if self._final.acquire(blocking=False):
            with self._lock:
                self._served += 1
        self._done.set()

    def shutdown(self):
        self._worker.join(timeout=5.0)
