"""CURRENT shape of the PR 5 submit/shutdown path (clean).

The stopping check and the enqueue are one critical section under the
intake lock, and shutdown sets the flag under the same lock: an
accepted enqueue happens-before the stop flag, so the workers (or the
drain sweep) are guaranteed to see it — the in-tree fix,
``serve/batcher.py``.
"""

import queue
import threading


class Batcher:
    def __init__(self):
        self._q = queue.Queue(maxsize=4)
        self._stopping = threading.Event()
        self._intake_lock = threading.Lock()
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def submit(self, item):
        with self._intake_lock:
            if self._stopping.is_set():
                raise RuntimeError("shutting down")
            if self._q.full():
                # Submitters are lock-serialized and workers only
                # remove, so full() here IS the admission decision.
                raise RuntimeError("queue full")
            self._q.put_nowait(item)
        return item

    def _drain(self):
        while not self._stopping.is_set():
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                continue

    def shutdown(self):
        with self._intake_lock:
            self._stopping.set()
        self._worker.join(timeout=5.0)
