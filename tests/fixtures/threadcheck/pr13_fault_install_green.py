"""CURRENT shape of the ISSUE-13 fault-injector install path (clean).

The exclusivity check, the schedule-state reset and the plan assignment
are ONE critical section under the injector lock — concurrent
installers serialize, exactly one wins, and no traversal can observe a
half-reset schedule. The armed-flag fast path reads ``_plan`` unlocked
(the benign-racy-flag idiom: a traversal racing a clear either sees the
plan or misses it, both legitimate schedules); every WRITE is locked.
"""

import threading


class Injector:
    def __init__(self):
        self._lock = threading.Lock()
        self._plan = None
        self._counts = {}
        self._fired_total = 0

    def install(self, plan):
        with self._lock:
            if self._plan is not None:
                raise RuntimeError("a plan is already installed")
            self._counts = {}
            self._fired_total = 0
            self._plan = plan

    def clear(self):
        with self._lock:
            self._plan = None
            self._counts = {}
            self._fired_total = 0

    def fire(self, point):
        if self._plan is None:         # benign-racy armed check (read)
            return ()
        with self._lock:
            if self._plan is None:     # re-check under the lock
                return ()
            self._counts[point] = self._counts.get(point, 0) + 1
            self._fired_total += 1
        return (point,)
