"""CURRENT shape of the ISSUE-20 weight-swap dispatch path (clean).

The swap's exclusivity check, pointer write and generation bump are
ONE critical section under the replica lock, and a dispatch's
params-pointer read + in-flight registration are another — a
concurrent swap either sees the dispatch registered (and drains it on
the OLD params) or the dispatch starts after the swap and runs wholly
on the NEW params. No torn view, no drain barrier passing while a
batch still holds retired weights; the old params object stays
referenced by in-flight calls until their ``finally`` runs. AOT
programs take params as a call argument, so the swap never recompiles
— the sealed RetraceWatchdog proves it.
"""

import threading


class Replica:
    def __init__(self, params):
        self._lock = threading.Lock()
        self.params = params
        self.generation = 0
        self.in_flight = 0

    def swap_params(self, params):
        with self._lock:
            self.params = params
            self.generation += 1

    def drained(self):
        with self._lock:
            return self.in_flight == 0

    def dispatch(self, batch, run):
        with self._lock:
            params = self.params       # pointer read + registration:
            self.in_flight += 1        # one lock hold, never torn
        try:
            return run(params, batch)
        finally:
            with self._lock:
                self.in_flight -= 1
