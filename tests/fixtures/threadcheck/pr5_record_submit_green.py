"""CURRENT shape of the PR 5 record_submit path (clean).

The submit is counted INSIDE the intake critical section, before the
enqueue becomes visible to a worker — the in-tree fix
(``serve/batcher.py``: counter increments only, no telemetry I/O under
the lock).
"""

import queue
import threading


class Intake:
    def __init__(self):
        self._intake_lock = threading.Lock()
        self._q = queue.Queue(maxsize=8)
        self._accepted = 0  # guarded-by: _intake_lock
        self._worker = threading.Thread(target=self._serve, daemon=True)
        self._worker.start()

    def submit(self, item):
        with self._intake_lock:
            self._accepted += 1
            self._q.put_nowait(item)

    def _serve(self):
        while True:
            item = self._q.get()
            if item is None:
                return

    def shutdown(self):
        self._q.put(None)
        self._worker.join(timeout=5.0)
