"""PRE-fix shape of the PR 5 submit/shutdown TOCTOU (detected: GC003).

``submit`` checks the stopping flag, then enqueues. Between the two, a
concurrent ``shutdown`` can set the flag, join the workers and sweep
the queues — the accepted request lands in a queue nobody will ever
read (client hangs to a 504 instead of getting the 503 it was owed).
"""

import queue
import threading


class Batcher:
    def __init__(self):
        self._q = queue.Queue(maxsize=4)
        self._stopping = threading.Event()
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def submit(self, item):
        if self._stopping.is_set():          # check...
            raise RuntimeError("shutting down")
        self._q.put_nowait(item)             # ...then act: the flag can
        return item                          # flip in between

    def _drain(self):
        while not self._stopping.is_set():
            try:
                self._q.get(timeout=0.05)
            except queue.Empty:
                continue

    def shutdown(self):
        self._stopping.set()
        self._worker.join(timeout=5.0)
