"""GD004 red: every watched flag-write shape outside compat.py —
subscript env write, setdefault, config.update and the config
attribute assignment."""

import os

import jax


def scatter_flags():
    os.environ["XLA_FLAGS"] = "--xla_foo"                   # GD004
    os.environ.setdefault("PYTHONHASHSEED", "0")            # GD004
    jax.config.update("jax_default_matmul_precision",       # GD004
                      "float32")
    jax.config.jax_enable_x64 = True                        # GD004
