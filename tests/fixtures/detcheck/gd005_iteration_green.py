"""GD005 green: sorted() at every enumeration; dicts (insertion-
ordered) iterate freely."""

import glob
import os
from pathlib import Path


def ordered(params, ckpt_dir):
    tree = {}
    for name in sorted({"encoder", "gru", "head"}):
        tree[name] = params[name]
    for name in params:          # dict iteration is insertion-ordered
        tree.setdefault(name, params[name])
    files = sorted(glob.glob(os.path.join(ckpt_dir, "*.ckpt")))
    latest = sorted(Path(ckpt_dir).rglob("*.orbax"))
    return tree, files, latest
