"""GD004 green: placement/caching knobs are NOT determinism levers —
the watched list is deliberately narrow."""

import os

import jax


def placement_knobs(cache_dir):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("PVRAFT_PALLAS_INTERPRET", "1")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
