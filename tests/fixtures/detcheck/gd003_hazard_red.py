"""GD003 red: a registration site for a program whose import closure
reaches a scatter-accumulate, with NO determinism= stance. The test
injects the matching HazardSpec (the registry inspection's output) with
``determinism=""`` — the finding must anchor at the register call."""

from pvraft_tpu.programs.spec import register


@register("fixture.hazard_program", tags=("kernel",))
def _hazard_thunk():
    from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup

    return fused_corr_lookup, ()
