"""GD005 red: set iteration feeding construction order, and
filesystem enumerations whose order is filesystem-dependent."""

import glob
import os
from pathlib import Path


def unordered(params, ckpt_dir):
    tree = {}
    for name in {"encoder", "gru", "head"}:        # GD005: set literal
        tree[name] = params[name]
    stale = [p for p in set(tree)]                 # GD005: set() iter
    files = glob.glob(os.path.join(ckpt_dir, "*.ckpt"))  # GD005
    latest = Path(ckpt_dir).rglob("*.orbax")       # GD005: Path.rglob
    return tree, stale, files, latest
