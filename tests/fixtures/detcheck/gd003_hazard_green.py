"""GD003 green: the same registration with the stance declared — the
test injects the HazardSpec carrying that non-empty determinism."""

from pvraft_tpu.programs.spec import register


@register("fixture.hazard_program", tags=("kernel",),
          determinism="unique-index-scatter; replay-certified")
def _hazard_thunk():
    from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup

    return fused_corr_lookup, ()
