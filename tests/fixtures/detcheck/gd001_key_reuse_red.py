"""GD001 red: one key consumed twice, and a loop-invariant key
consumed every iteration (both draw identical randomness)."""

import jax


def double_consume(shape):
    key = jax.random.key(0)
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)   # GD001: key already consumed
    return a, b


def loop_reuse(shape, n):
    key = jax.random.key(1)
    outs = []
    for _ in range(n):
        outs.append(jax.random.normal(key, shape))  # GD001: loop reuse
    return outs
