"""GD002 green: entropy only via the declared stream contract; jax
samplers CONSUME keys (they never mint entropy) so the `from jax
import random` alias must not be mistaken for the stdlib module."""

from jax import random

from pvraft_tpu.rng import derive, host_rng


def declared_streams(seed, shape):
    key = derive(seed, "model.init")
    noise = random.normal(key, shape)           # sampler, not a mint
    order = host_rng(seed, "data.shuffle", 0)
    return noise, order
