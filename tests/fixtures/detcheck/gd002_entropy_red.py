"""GD002 red: every undeclared-entropy shape — raw host/jax RNG
constructors, a wall-clock seed, a streamless derive and an undeclared
stream name (declared vocabulary in the test: model.init, data.shuffle)."""

import random
import time

import numpy as np

from pvraft_tpu.rng import derive, host_rng


def mint_entropy(seed):
    rng = np.random.default_rng(0)              # GD002: raw constructor
    jitter = random.Random(seed)                # GD002: stdlib random
    clock = np.random.default_rng(
        int(time.time()))                       # GD002: x2, time-seeded
    k = derive(seed)                            # GD002: no stream literal
    k2 = derive(seed, "not.a.stream")           # GD002: undeclared stream
    ok = host_rng(seed, "data.shuffle")         # fine: declared stream
    return rng, jitter, clock, k, k2, ok
