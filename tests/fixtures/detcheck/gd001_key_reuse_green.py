"""GD001 green: the split/fold_in discipline — every consumption gets
a fresh subkey; loops fold the iteration index in."""

import jax


def split_per_use(shape):
    key = jax.random.key(0)
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, shape)
    key, sub2 = jax.random.split(key)
    b = jax.random.uniform(sub2, shape)
    return a, b


def fold_per_iteration(shape, n):
    key = jax.random.key(1)
    outs = []
    for i in range(n):
        k = jax.random.fold_in(key, i)
        outs.append(jax.random.normal(k, shape))
    return outs
