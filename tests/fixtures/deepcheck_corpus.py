"""Deliberately-broken traced programs: deepcheck's red-test corpus.

Each thunk returns ``(fn, args)`` exactly like an audit-registry entry;
``tests/test_deepcheck.py`` wraps them in ``AuditEntry`` records and
runs ``run_deepcheck`` over them. The golden report fixture
(``deepcheck_report.golden``) pins the exact findings, so KEEP LINE
NUMBERS STABLE: append new cases at the end, never insert in the
middle.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pvraft_tpu.compat import shard_map
from pvraft_tpu.parallel.mesh import make_mesh

SDS = jax.ShapeDtypeStruct


def dead_psum():
    """GJ002(a): collective whose result nothing consumes."""
    mesh = make_mesh(n_data=1, n_seq=1)

    def inner(x):
        wasted = lax.psum(x, "seq")  # GOLDEN ANCHOR: corpus line 31
        _ = wasted + 1.0
        return x * 2.0

    def fn(x):
        return shard_map(inner, mesh=mesh, in_specs=P(None, "seq"),
                         out_specs=P(None, "seq"), check_vma=False)(x)

    return fn, (SDS((2, 4), "float32"),)


def last_hop_ring():
    """GJ002(b): ppermute feeds a carry whose final value is dropped —
    the pre-fix ring pattern, miniaturized."""
    mesh = make_mesh(n_data=1, n_seq=1)

    def inner(x):
        def body(i, st):
            acc, c = st
            acc = acc + c
            c = lax.ppermute(c, "seq", [(0, 0)])  # GOLDEN ANCHOR: line 51
            return acc, c

        acc, _ = lax.fori_loop(0, 2, body, (jnp.zeros_like(x), x))
        return acc

    def fn(x):
        return shard_map(inner, mesh=mesh, in_specs=P(None, "seq"),
                         out_specs=P(None, "seq"), check_vma=False)(x)

    return fn, (SDS((2, 4), "float32"),)


def unaliasable_donation():
    """GJ004: donated buffer with no same-aval output to alias."""
    g = jax.jit(lambda x: (x * 2.0).sum(), donate_argnums=(0,))

    def fn(x):
        return g(x)  # GOLDEN ANCHOR: line 69

    return fn, (SDS((8,), "float32"),)


def undonated_state():
    """GJ005: donation-opted-in program leaves a donatable input out."""
    g = jax.jit(lambda x, y: (x + 1.0, y * 2.0), donate_argnums=(0,))

    def fn(x, y):
        return g(x, y)  # GOLDEN ANCHOR: line 79

    return fn, (SDS((8,), "float32"), SDS((8,), "float32"))


def stray_bf16():
    """GJ006 (f32 intent): a 16-bit cast hiding in an f32 program."""

    def fn(x):
        return (x.astype(jnp.bfloat16) * 2).astype(jnp.float32)

    return fn, (SDS((4,), "float32"),)


def inert_bf16_lever():
    """GJ006 (bf16_grads intent): no truncation anywhere — the declared
    lever does nothing."""

    def fn(x):
        return x * 2.0

    return fn, (SDS((4,), "float32"),)


_counter = itertools.count()


def nondeterministic_trace():
    """GJ007(a): every rebuild embeds a fresh constant."""
    c = float(next(_counter))

    def fn(x):
        return x + c

    return fn, (SDS((4,), "float32"),)


def weak_type_sensitive():
    """GJ007(b): Python-scalar callers get different output dtypes."""

    def fn(s):
        return s * jnp.float16(1.0)

    return fn, (SDS((), "float32"),)


def fp_with_psum():
    """GJ003 pair, member A: one psum."""
    mesh = make_mesh(n_data=1, n_seq=1)

    def fn(x):
        return shard_map(lambda v: lax.psum(v, "seq"), mesh=mesh,
                         in_specs=P(None, "seq"), out_specs=P(None, None),
                         check_vma=False)(x)

    return fn, (SDS((2, 4), "float32"),)


def fp_without_collective():
    """GJ003 pair, member B: no collective — fingerprint drifts from A."""

    def fn(x):
        return x * 2.0

    return fn, (SDS((2, 4), "float32"),)


def clean():
    """Green control: no finding from any rule."""

    def fn(x):
        return (x * 2.0).sum()

    return fn, (SDS((8,), "float32"),)
