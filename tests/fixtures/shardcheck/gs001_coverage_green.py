"""GS001 green: a disjoint ladder covering every injected leaf once
(inventory: ``params/enc/kernel``, ``params/head/kernel``)."""

PARTITION_RULES = (
    (r"^params/enc/", ()),
    (r"^params/head/", ("data", None)),
)
