"""GS004 red: the pre-fix ``dump_snapshot`` shape — snapshot-dir
writes a module-level function performs with no process-0 dominator
(every host of a multi-process mesh would write the same paths)."""

import json
import os

import numpy as np


def dump_snapshot(snap_dir, batch, meta):
    out = os.path.join(snap_dir, "step_0000001")
    os.makedirs(out, exist_ok=True)          # exempt: idempotent ensure
    np.savez(os.path.join(out, "batch.npz"), **batch)        # unguarded
    tmp = os.path.join(out, "state.tmp")
    with open(tmp, "wb") as f:                               # unguarded
        f.write(b"state")
    os.replace(tmp, os.path.join(out, "state.msgpack"))      # unguarded
    with open(os.path.join(out, "meta.json"), "w") as f:     # unguarded
        json.dump(meta, f)
    return out
