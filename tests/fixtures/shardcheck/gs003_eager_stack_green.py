"""GS003 green: the current guarded shape — the eager stack stays, but
the class refuses to construct the fused mode on multi-process meshes
(the `trainer.py:100` constructor raise)."""

import jax
import jax.numpy as jnp


class FusedTrainer:
    def __init__(self, steps_per_dispatch):
        if steps_per_dispatch > 1 and jax.process_count() > 1:
            raise ValueError(
                "steps_per_dispatch > 1 is single-process only (the "
                "fused mode stacks sharded device batches eagerly)"
            )
        self.steps_per_dispatch = steps_per_dispatch

    def training(self, stream, multi_step, flat):
        pending = []
        for b in stream:
            pending.append(b)
            if len(pending) == self.steps_per_dispatch:
                batches = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *pending
                )
                pending = []
                flat, _ = multi_step(flat, batches)
        return flat
