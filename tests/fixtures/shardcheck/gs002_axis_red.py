"""GS002 red: an undeclared mesh-axis name and a fragile in-jit
spelling (declared axes in the test: {"data", "seq"})."""

from jax import lax
from jax.sharding import PartitionSpec as P


def bad_specs(x, mesh):
    spec = P("model", None)              # "model" is not a declared axis
    n = mesh.shape["model"]              # neither is this lookup
    folded = lax.psum(x, "tensor")       # nor this collective's axis
    size = lax.axis_size("seq")          # fragile: use compat.axis_size
    return spec, n, folded, size
