"""GS003 red: the PR 2 fused-dispatch trainer shape BEFORE the
multi-process guard (the exact bug `trainer.py:100` now guards): K
device batches stacked EAGERLY — on a multi-host mesh those are
non-fully-addressable global arrays and the stack raises mid-epoch."""

import jax
import jax.numpy as jnp


class FusedTrainer:
    def __init__(self, steps_per_dispatch):
        # No process_count guard anywhere in the class: deleting the
        # real trainer's constructor raise reintroduces this shape.
        self.steps_per_dispatch = steps_per_dispatch

    def training(self, stream, multi_step, flat):
        pending = []
        for b in stream:
            pending.append(b)
            if len(pending) == self.steps_per_dispatch:
                batches = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *pending
                )
                pending = []
                flat, _ = multi_step(flat, batches)
        return flat
