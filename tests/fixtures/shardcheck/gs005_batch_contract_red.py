"""GS005 red: the historical trainer shape — the per-host/global batch
relationship re-derived with ad-hoc process_count arithmetic, plus a
direct device placement that bypasses mesh.shard_batch/device_batch."""

import jax


class BadTrainer:
    def __init__(self, per_device_batch, mesh, sharding):
        n_proc = jax.process_count()
        self.global_batch = per_device_batch * mesh.ndev
        self.local_batch = self.global_batch // max(1, n_proc)  # GS005
        self.sharding = sharding

    def place(self, batch):
        return jax.device_put(batch, self.sharding)             # GS005
