"""GS004 green: every recognized guard shape at once — guard clause,
rank-0 ``if`` body, process-0 flag field (the ``EventLog.enabled``
pattern), single-process proof, and a module-local helper whose every
call site is guarded (the ``checkpoint.py`` ``_write`` shape)."""

import json
import os

import jax
import numpy as np


def _write(path, payload):
    # Helper with no guard of its own: dominated because its only call
    # sites sit under rank-0 tests.
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)


def guard_clause(snap_dir, batch):
    if jax.process_index() != 0:
        return None
    np.savez(os.path.join(snap_dir, "batch.npz"), **batch)
    return snap_dir


def rank0_body(path, payload):
    if jax.process_index() == 0:
        _write(path, payload)


def single_process_proof(dump_dir, rows):
    if dump_dir is not None and jax.process_count() > 1:
        raise ValueError("dumping is single-host only")
    for i, row in enumerate(rows):
        np.save(os.path.join(dump_dir, f"{i}.npy"), row)


class EventSink:
    def __init__(self, path, enabled=None):
        if enabled is None:
            enabled = jax.process_index() == 0
        self.enabled = bool(enabled)
        self.path = path
        if self.enabled:
            with open(path, "a", encoding="utf-8") as f:
                f.write("")

    def emit(self, record):
        if not self.enabled:
            return
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(record) + "\n")
