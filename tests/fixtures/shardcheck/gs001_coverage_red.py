"""GS001 red: an overlapping ladder plus a dead rule.

The test injects the leaf inventory ``params/enc/kernel``,
``params/head/kernel``: the catch-all second rule overlaps the first
(multiply-matched leaf), and the third rule matches nothing (dead)."""

PARTITION_RULES = (
    (r"^params/enc/", ()),
    (r"^params/", ("data", None)),
    (r"^params/never/", ()),
)
