#!/usr/bin/env bash
# fixture shim: manifest equals the fixture registry stage set.
#   # gate-stage: validate-report
exec true
