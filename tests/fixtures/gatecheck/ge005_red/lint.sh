#!/usr/bin/env bash
# fixture shim: names a stage the registry does not declare.
#   # gate-stage: validate-report
#   # gate-stage: phantom-stage
exec true
