"""kernelcheck: model extraction, GK rules red/green over the fixture
corpus (incl. the PR-5 integer-iota argmin pinned as DETECTED), the
clean-tree gate, the CLI, the VMEM/roofline planner, and the
static-vs-Mosaic cross-validation against the committed artifacts.
Pure host-side — no jax import anywhere (tier-1 on CPU)."""

import ast
import contextlib
import io
import json
import os

import pytest

from pvraft_tpu.analysis.__main__ import main as analysis_main
from pvraft_tpu.analysis.engine import known_rule_ids
from pvraft_tpu.analysis.kernels.check import (
    check_paths,
    check_source,
    default_scope,
    registered_kernel_modules,
)
from pvraft_tpu.analysis.kernels.model import (
    ArrayInfo,
    KERNEL_BINDINGS,
    _hbm_layout_bytes,
    build_module_kernel_model,
)
from pvraft_tpu.analysis.kernels.planner import (
    CROSS_VALIDATION_FACTOR,
    PLAN_SCHEMA,
    build_plan,
    check_plan_file,
    collect_models,
    fused_gru_residency,
    spec_module_map,
)
from pvraft_tpu.analysis.kernels.rules import (
    VMEM_BUDGET_BYTES,
    all_kernel_rules,
)
from pvraft_tpu.programs.compile import validate_kernels_artifact

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "kernelcheck")
COSTS = os.path.join(REPO, "artifacts", "programs_costs.json")
KERNELS_ARTIFACT = os.path.join(REPO, "artifacts", "programs_kernels.json")
PLAN_ARTIFACT = os.path.join(REPO, "artifacts", "kernel_plan.json")


def fixture_ids(name, **kw):
    path = os.path.join(FIXTURES, name)
    with open(path, "r", encoding="utf-8") as f:
        findings, notes = check_source(f.read(), path=path, **kw)
    return [d.rule_id for d in findings], [d.rule_id for d in notes]


def model_of(src, path="x.py"):
    return build_module_kernel_model(ast.parse(src), src, path)


# --- model extraction -------------------------------------------------------

def test_array_info_subscripting():
    a = ArrayInfo((2, 8192, 512, 3))
    assert a[..., 0].shape == (2, 8192, 512)
    assert a[..., 0:1].shape == (2, 8192, 512, 1)
    assert a.nbytes == 2 * 8192 * 512 * 3 * 4
    assert ArrayInfo((4, 4), "bfloat16").nbytes == 32


def test_hbm_layout_rank2_tiled_rank3_compact():
    # The XLA:TPU argument-layout rule the fwd exactness pin rides on:
    # rank-2 operands are (8, 128)-tiled with transpose-if-cheaper, so
    # the gru kernel's (128, 64) weight lands as 64x128 with zero pad
    # while (64, 192) pads its lanes to 256; rank>=3 stays compact.
    assert _hbm_layout_bytes(ArrayInfo((128, 64))) == 64 * 128 * 4
    assert _hbm_layout_bytes(ArrayInfo((64, 192))) == 64 * 256 * 4
    assert _hbm_layout_bytes(ArrayInfo((8, 64))) == 8 * 128 * 4
    assert _hbm_layout_bytes(ArrayInfo((8, 192), "bfloat16")) == 8 * 256 * 2
    assert _hbm_layout_bytes(ArrayInfo((2, 8192, 3))) == 2 * 8192 * 3 * 4


def test_real_voxel_kernel_models_concretely():
    """The voxel kernel at the flagship binding: grid, blocks, VMEM and
    HBM all concrete — the numbers the plan artifact commits."""
    models = collect_models()
    kms = models["pvraft_tpu/ops/pallas/voxel_corr.py"]
    assert len(kms) == 1
    km = kms[0]
    assert km.problems == []
    assert km.grid == (2, 128)
    assert km.kernel_fn_name == "_voxel_kernel"
    assert [s.block for s in km.in_specs] == [(1, 64, 512)] * 4
    assert [s.block for s in km.out_specs] == [(1, 64, 3 * 27)]
    # 4 in blocks of 128 KiB + 1 out block of 20.25 KiB, double-buffered.
    assert km.vmem_estimate_bytes() == 2 * (4 * 64 * 512 * 4
                                            + 64 * 81 * 4)
    assert km.hbm_operand_bytes() == (4 * 2 * 8192 * 512 * 4,
                                      2 * 8192 * 81 * 4)


def test_real_fused_kernel_models_concretely():
    """The fused kernel resolves the cross-module `_pick_tile` helper
    (imported from voxel_corr) and the `[spec]*4 + [spec]*3` list
    arithmetic."""
    models = collect_models()
    km = models["pvraft_tpu/ops/pallas/corr_lookup.py"][0]
    assert km.problems == []
    assert km.grid == (2, 128)
    assert len(km.in_specs) == 7
    assert [s.block for s in km.in_specs[:4]] == [(1, 64, 512)] * 4
    assert [s.block for s in km.in_specs[4:]] == [(1, 64, 1)] * 3
    assert len(km.out_specs) == 5
    assert km.operands[4].shape == (2, 8192, 1)  # coords[..., 0:1]


def test_bindings_cover_every_scanned_kernel_function():
    """Every real pallas_call site resolves through a KERNEL_BINDINGS
    row (or would need literal dims) — the clean-tree guarantee."""
    for suffix, kms in collect_models().items():
        for km in kms:
            assert km.problems == [], (suffix, km.func, km.problems)
            assert any(suffix.endswith(s) and km.func in funcs
                       for s, funcs in KERNEL_BINDINGS.items()), (
                f"{suffix}:{km.func} modeled without a binding row?")


def test_unmodelable_kernel_is_a_gk000_finding():
    findings, _ = fixture_ids("gk000_unmodelable_red.py")
    assert findings and set(findings) == {"GK000"}


# --- per-rule red/green -----------------------------------------------------

def test_gk001_red_chosen_tiles():
    findings, _ = fixture_ids("gk001_chosen_tile_red.py")
    assert set(findings) == {"GK001"}
    assert len(findings) == 4  # sublane + lane, each on in and out spec


def test_gk001_whole_axis_blocks_are_notes_not_findings():
    """The 81-cell voxel output / knn=32 blocks are geometry-inherent:
    layout notes, never gate failures."""
    findings, notes, _ = check_paths(list(default_scope()))
    assert [d for d in findings if d.rule_id == "GK001"] == []
    assert any(d.rule_id == "GK001" and "(1, 64, 81)" in d.message
               for d in notes)


def test_gk001_block_dim_one_is_exempt():
    src = _inline_kernel(block="(1, 64, 128)", grid="(2, 16)",
                         shape="(2, 1024, 128)",
                         index_map="lambda bi, ni: (bi, ni, 0)")
    findings, notes = check_source(src)
    assert [d for d in findings if d.rule_id == "GK001"] == []


def test_gk002_red_and_budget_number():
    findings, _ = fixture_ids("gk002_vmem_red.py")
    assert set(findings) == {"GK002"}
    assert VMEM_BUDGET_BYTES == 16 * 1024 * 1024


def test_gk003_red_under_and_over():
    findings, _ = fixture_ids("gk003_coverage_red.py")
    assert set(findings) == {"GK003"}
    path = os.path.join(FIXTURES, "gk003_coverage_red.py")
    with open(path) as f:
        diags, _ = check_source(f.read(), path=path)
    messages = " | ".join(d.message for d in diags)
    assert "under-coverage" in messages and "over-coverage" in messages


def test_gk004_pr5_int_iota_argmin_stays_detected():
    """The historical regression: the pre-fix integer-iota argmin must
    stay DETECTED (the threadcheck fixture discipline)."""
    findings, _ = fixture_ids("gk004_int_iota_argmin_red.py")
    assert "GK004" in findings
    assert set(findings) == {"GK004"}


def test_gk004_current_float_iota_shape_stays_clean():
    findings, _ = fixture_ids("gk004_float_iota_green.py")
    assert findings == []


def test_gk004_cast_iota_in_compound_expression_stays_clean():
    """The sanctioned fix must survive inside compound expressions: an
    `.astype(f32)` anywhere above the iota sanctions it, not only as
    the outermost call of the assignment."""
    src = _inline_kernel(
        block="(1, 64, 128)", grid="(2,)", shape="(2, 64, 128)",
        index_map="lambda bi: (bi, 0, 0)",
        body=("    idx = lax.broadcasted_iota(\n"
              "        jnp.int32, (64, 128), 1).astype(jnp.float32) + 0.5\n"
              "    o_ref[0] = jnp.min(idx, axis=-1, keepdims=True) + "
              "x_ref[0]\n"))
    findings, _ = check_source(src)
    assert [d for d in findings if d.rule_id == "GK004"] == []
    # And the inline form of the fix, inside the reduction itself.
    src = _inline_kernel(
        block="(1, 64, 128)", grid="(2,)", shape="(2, 64, 128)",
        index_map="lambda bi: (bi, 0, 0)",
        body=("    o_ref[0] = jnp.min(lax.broadcasted_iota(\n"
              "        jnp.int32, (64, 128), 1).astype(jnp.float32),\n"
              "        axis=-1, keepdims=True) + x_ref[0]\n"))
    findings, _ = check_source(src)
    assert [d for d in findings if d.rule_id == "GK004"] == []


def test_gk004_two_statement_cast_stays_clean():
    """The fix written as a reassignment (`idx = idx.astype(f32)`) must
    un-taint the name — the rule's own recommendation split over two
    statements cannot fail the gate."""
    src = _inline_kernel(
        block="(1, 64, 128)", grid="(2,)", shape="(2, 64, 128)",
        index_map="lambda bi: (bi, 0, 0)",
        body=("    idx = lax.broadcasted_iota(jnp.int32, (64, 128), 1)\n"
              "    idx = idx.astype(jnp.float32)\n"
              "    o_ref[0] = jnp.min(idx, axis=-1, keepdims=True) + "
              "x_ref[0]\n"))
    findings, _ = check_source(src)
    assert [d for d in findings if d.rule_id == "GK004"] == []


def test_gk004_hazard_table_1d_iota_and_f64():
    src = _inline_kernel(
        body=("    idx = lax.iota(jnp.int32, 128)\n"
              "    big = x_ref[0].astype(jnp.float64)\n"
              "    o_ref[0] = big.astype(jnp.float32) + idx[0]\n"))
    findings, _ = check_source(src)
    hazards = [d.message for d in findings if d.rule_id == "GK004"]
    assert any("iota-1d" in m for m in hazards)
    assert any("float64" in m for m in hazards)


def test_gk005_red_green_via_registry_set():
    path = os.path.join(FIXTURES, "clean_green.py")
    with open(path) as f:
        src = f.read()
    red, _ = check_source(src, path=path, registered_modules=set())
    assert [d.rule_id for d in red] == ["GK005"]
    green, _ = check_source(
        src, path=path,
        registered_modules={"tests/fixtures/kernelcheck/clean_green.py"})
    assert green == []
    # No registry context at all -> GK005 stays silent (unit-test mode).
    silent, _ = check_source(src, path=path)
    assert silent == []


def test_gk005_registry_set_covers_both_real_kernels():
    mods = registered_kernel_modules()
    assert "pvraft_tpu/ops/pallas/voxel_corr.py" in mods
    assert "pvraft_tpu/ops/pallas/corr_lookup.py" in mods


def test_gk006_red_missing_and_hardcoded():
    findings, _ = fixture_ids("gk006_interpret_red.py")
    assert findings == ["GK006", "GK006"]


def test_gk006_local_variable_spelling_stays_clean():
    """`interp = interpret_mode()` then `interpret=interp` is the same
    behavior as the inline call — the model's evaluator resolves it."""
    src = _inline_kernel(block="(1, 64, 128)", grid="(2, 16)",
                         shape="(2, 1024, 128)",
                         index_map="lambda bi, ni: (bi, ni, 0)")
    src = src.replace("    return pl.pallas_call(",
                      "    interp = interpret_mode()\n"
                      "    return pl.pallas_call(")
    src = src.replace("interpret=interpret_mode(),", "interpret=interp,")
    findings, _ = check_source(src)
    assert findings == []


def test_evaluator_failures_are_gk000_not_crashes():
    """TypeErrors/ZeroDivisionErrors inside geometry expressions must
    surface as GK000 model-incomplete findings, never tracebacks."""
    for broken in ("grid=(2, 16 // 0),",          # ZeroDivisionError
                   "grid=(2, (1, 2) * 1.5),"):    # TypeError
        src = _inline_kernel(block="(1, 64, 128)",
                             shape="(2, 1024, 128)",
                             index_map="lambda bi, ni: (bi, ni, 0)",
                             grid="IGNORED")
        src = src.replace("grid=IGNORED,", broken)
        findings, _ = check_source(src)
        assert any(d.rule_id == "GK000" for d in findings), broken


def test_whole_array_specs_are_single_buffered():
    """A block=None (whole-array resident) spec is not grid-streamed,
    so it must not be double-buffered in the VMEM estimate."""
    from pvraft_tpu.analysis.kernels.model import (
        BlockSpecModel,
        KernelModel,
    )

    km = KernelModel(path="x.py", line=1, col=0, func="f")
    km.in_specs = [BlockSpecModel(block=None, index_map=None,
                                  line=1, col=0)]
    km.operands = [ArrayInfo((64, 128))]
    km.out_specs = [BlockSpecModel(block=(8, 128), index_map=None,
                                   line=1, col=0)]
    km.out_info = [ArrayInfo((64, 128))]
    assert km.vmem_estimate_bytes() == 64 * 128 * 4 + 2 * 8 * 128 * 4


def test_clean_fixture_is_clean():
    findings, notes = fixture_ids("clean_green.py")
    assert findings == [] and notes == []


# --- suppressions + the shared pragma grammar -------------------------------

def _inline_kernel(block="(1, 1024, 2048)", grid="(4,)",
                   shape="(4, 1024, 2048)",
                   index_map="lambda bi: (bi, 0, 0)",
                   body="    o_ref[0] = x_ref[0]\n"):
    return (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "from pvraft_tpu.compat import import_pallas\n"
        "from pvraft_tpu.ops.pallas import interpret_mode\n"
        "pl = import_pallas()\n"
        "def _k(x_ref, o_ref):\n"
        f"{body}"
        "def run():\n"
        f"    x = jax.ShapeDtypeStruct({shape}, jnp.float32)\n"
        f"    spec = pl.BlockSpec({block}, {index_map})\n"
        "    return pl.pallas_call(\n"
        "        _k,\n"
        f"        grid={grid},\n"
        "        in_specs=[spec],\n"
        "        out_specs=spec,\n"
        f"        out_shape=jax.ShapeDtypeStruct({shape}, jnp.float32),\n"
        "        interpret=interpret_mode(),\n"
        "    )(x)\n")


def test_gk_suppression_pragma_applies():
    src = _inline_kernel()
    findings, _ = check_source(src)
    assert [d.rule_id for d in findings] == ["GK002"]
    line = findings[0].line
    lines = src.splitlines()
    lines[line - 1] += "  # graftlint: disable=GK002 -- fixture probe"
    suppressed, _ = check_source("\n".join(lines) + "\n")
    assert suppressed == []


def test_gk_ids_are_known_to_the_stats_grammar():
    """`lint --stats` must never flag a GK pragma as unknown: the GK
    family (plus GK000) lives in the one shared rule-id namespace."""
    known = known_rule_ids()
    for rule in all_kernel_rules():
        assert rule.id in known
    assert "GK000" in known
    assert "GK999" not in known


def test_reasonless_gk_pragma_fails_stats(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("x = 1  # graftlint: disable=GK002\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["lint", "--stats", str(tmp_path)])
    assert rc == 1
    assert "reason-less suppression" in buf.getvalue()
    good = tmp_path / "bad.py"
    good.write_text("x = 1  # graftlint: disable=GK002 -- probe reason\n")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["lint", "--stats", str(tmp_path)])
    assert rc == 0
    assert "unknown" not in buf.getvalue()


# --- the clean-tree gate, in test form --------------------------------------

def test_clean_tree_zero_findings():
    """The lint.sh stage as a test: zero GK findings over ops/pallas.
    Real violations get FIXED (the deepcheck/threadcheck precedent),
    not pragma'd — and never silently accumulated."""
    findings, _notes, nfiles = check_paths(list(default_scope()))
    assert nfiles >= 3
    assert findings == [], "\n".join(d.format() for d in findings)


# --- CLI --------------------------------------------------------------------

def test_cli_list_rules():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["kernels", "--list-rules"])
    assert rc == 0
    out = buf.getvalue()
    for rule in all_kernel_rules():
        assert rule.id in out
    assert len(all_kernel_rules()) >= 6


def test_cli_findings_and_select():
    red = os.path.join(FIXTURES, "gk003_coverage_red.py")
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["kernels", red])
    assert rc == 1
    assert "GK003" in buf.getvalue()
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["kernels", "--select", "GK001", red])
    assert rc == 0, buf.getvalue()


def test_cli_default_scope_is_clean():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["kernels"])
    assert rc == 0


def test_cli_plan_check_committed_artifact():
    """The lint.sh plan stage in test form: the committed kernel_plan
    regenerates byte-identically from the static models + costs."""
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["kernels", "--check", PLAN_ARTIFACT,
                            "--costs", COSTS])
    assert rc == 0, buf.getvalue()


def test_cli_plan_check_detects_drift(tmp_path):
    with open(PLAN_ARTIFACT) as f:
        doc = json.load(f)
    doc["vmem_budget_bytes"] = 123
    stale = tmp_path / "kernel_plan.json"
    stale.write_text(json.dumps(doc))
    problems = check_plan_file(str(stale), COSTS)
    assert problems and "drifted" in problems[0]


def test_plan_check_rejects_non_object_artifact(tmp_path):
    """Valid-JSON-but-not-an-object must be a clean diagnostic, not a
    traceback."""
    for payload in ("[1, 2]", "\"plan\""):
        bad = tmp_path / "kernel_plan.json"
        bad.write_text(payload)
        problems = check_plan_file(str(bad), COSTS)
        assert problems and "not a pvraft_kernel_plan/v1 object" \
            in problems[0]


def test_planner_refuses_multi_site_modules():
    """A second pallas_call in a module would make the single-site plan
    record silently wrong — the build must refuse loudly."""
    from pvraft_tpu.analysis.kernels.planner import _kernel_records

    models = collect_models()
    module = "pvraft_tpu/ops/pallas/voxel_corr.py"
    models[module] = models[module] * 2
    with open(COSTS) as f:
        costs = json.load(f)
    _, problems = _kernel_records(models, costs)
    assert any("2 pallas_call sites" in p for p in problems)


def test_spec_module_map_derives_from_gk005_inspection():
    """One catalog inspection feeds both GK005 and the planner — the
    two coverage views cannot drift."""
    from pvraft_tpu.analysis.kernels.check import kernel_spec_imports

    imports = kernel_spec_imports()
    assert set(spec_module_map()) == {n for n, mods in imports.items()
                                      if mods}
    assert registered_kernel_modules() == {
        m for mods in imports.values() for m in mods}


# --- planner ----------------------------------------------------------------

def test_plan_schema_and_kernel_coverage():
    plan = build_plan(COSTS)
    assert plan["schema"] == PLAN_SCHEMA
    names = {r["name"] for r in plan["kernels"]}
    assert names == set(spec_module_map())
    assert names == {"pallas_voxel_fwd", "pallas_voxel_grad",
                     "pallas_fused_lookup_fwd", "pallas_fused_lookup_grad",
                     "pallas_gru_iter_fwd", "pallas_gru_iter_grad"}
    for rec in plan["kernels"]:
        assert rec["bound"] in ("memory", "compute")
        assert rec["static_vmem_bytes"] < VMEM_BUDGET_BYTES
        assert rec["cross_validated"] is True


def test_static_vmem_agrees_with_mosaic_memory_analysis():
    """The acceptance pin: for EVERY kernel-tag ProgramSpec the static
    HBM estimate agrees with the real deviceless Mosaic
    memory_analysis within the pinned factor — and the forward kernels
    (no XLA DCE in play) agree essentially exactly."""
    with open(KERNELS_ARTIFACT) as f:
        compiled = {r["name"]: r for r in json.load(f)["programs"]}
    plan = build_plan(COSTS)
    assert set(compiled) == {r["name"] for r in plan["kernels"]}
    for rec in plan["kernels"]:
        mem = compiled[rec["name"]]["memory"]
        mosaic = (mem["argument_size_in_bytes"]
                  + mem["output_size_in_bytes"])
        ratio = rec["static_hbm_bytes"] / mosaic
        assert 1 / CROSS_VALIDATION_FACTOR <= ratio \
            <= CROSS_VALIDATION_FACTOR, (rec["name"], ratio)
        if rec["name"].endswith("_fwd"):
            assert abs(ratio - 1.0) < 1e-3, (rec["name"], ratio)


def test_fused_gru_residency_flagship_verdict():
    """The committed number ROADMAP item 1 cites: at K=512 the fused
    GRU iteration chain is VMEM-resident at tile=1024 with >= 3.9 MiB
    headroom — for both the 2048- and 8192-point scenes — and a full
    8192-point scene can NOT be resident (the tiling is mandatory)."""
    for n in (2048, 8192):
        rec = fused_gru_residency(n)
        assert rec["fits"] is True
        assert rec["tile_points"] == 1024
        assert rec["headroom_bytes"] >= 3 * 2**20
        assert rec["total_bytes"] <= VMEM_BUDGET_BYTES
        assert rec["full_scene_resident"] is False
        assert rec["candidate_traffic_reduction_factor"] == 32
        assert rec["n_points"] % rec["tile_points"] == 0


def test_fused_gru_residency_scales_with_k():
    """Smaller truncated-K buys bigger resident tiles; an absurd budget
    fits nothing and says so."""
    k512 = fused_gru_residency(8192, truncate_k=512)
    k128 = fused_gru_residency(8192, truncate_k=128)
    assert k128["tile_points"] > k512["tile_points"]
    broke = fused_gru_residency(8192, budget=1024)
    assert broke["fits"] is False and "no multiple-of-8" in broke["verdict"]


def test_plan_fails_on_cross_validation_breach(tmp_path):
    """A costs artifact whose compiled memory diverges past the pin
    must make the plan REFUSE to build (the lint stage's teeth)."""
    with open(COSTS) as f:
        doc = json.load(f)
    for r in doc["programs"]:
        if r["name"] == "pallas_voxel_fwd":
            r["memory"]["argument_size_in_bytes"] //= 8
    bad = tmp_path / "costs.json"
    bad.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="outside the pinned"):
        build_plan(str(bad))


# --- programs_kernels.json coverage pin -------------------------------------

class _FakeSpec:
    def __init__(self, name, tags=("kernel", "pallas"),
                 topology="v5e:2x2x1"):
        self.name = name
        self.tags = tags
        self.topology = topology


_KERNEL_SPECS = [_FakeSpec(n) for n in (
    "pallas_voxel_fwd", "pallas_voxel_grad",
    "pallas_fused_lookup_fwd", "pallas_fused_lookup_grad")]


def _kernels_doc():
    with open(KERNELS_ARTIFACT) as f:
        return json.load(f)


def test_committed_kernels_artifact_covers_registry():
    """Both directions against the LIVE registry — the lint.sh stage in
    test form; kernel compile evidence can no longer drift silently."""
    from pvraft_tpu.programs.compile import validate_kernels_file

    assert validate_kernels_file(KERNELS_ARTIFACT) == []


def test_kernels_artifact_missing_record_detected():
    doc = _kernels_doc()
    doc["programs"] = [r for r in doc["programs"]
                       if r["name"] != "pallas_voxel_grad"]
    problems = validate_kernels_artifact(doc, _KERNEL_SPECS)
    assert any("pallas_voxel_grad" in p and "no compile record" in p
               for p in problems)


def test_kernels_artifact_stale_record_detected():
    doc = _kernels_doc()
    doc["programs"].append({"name": "pallas_ghost_fwd", "ok": True,
                            "memory": {}})
    problems = validate_kernels_artifact(doc, _KERNEL_SPECS)
    assert any("pallas_ghost_fwd" in p and "stale" in p for p in problems)


def test_kernels_artifact_failed_compile_detected():
    doc = _kernels_doc()
    doc["programs"][0] = dict(doc["programs"][0], ok=False,
                              error="Mosaic lowering failed")
    problems = validate_kernels_artifact(doc, _KERNEL_SPECS)
    assert any("FAILED" in p for p in problems)


def test_kernels_artifact_wrong_topology_detected():
    doc = dict(_kernels_doc(), topology="v5e:8x8")
    problems = validate_kernels_artifact(doc, _KERNEL_SPECS)
    assert any("topology" in p for p in problems)
