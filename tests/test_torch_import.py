"""Torch->jax checkpoint conversion: synthesize a state_dict with the
reference's module tree / tensor layouts (``model/RAFTSceneFlow.py`` etc.)
and check the converted tree drops into our model params exactly."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.slow  # torch checkpoint converters (~1 min)

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.engine.checkpoint import import_torch_state_dict
from pvraft_tpu.models.raft import PVRaft


def _torch_style_state_dict(rng):
    """Mimic the reference RSF state_dict: keys and (out,in,1[,1]) conv
    layouts, GroupNorm/PReLU parameter shapes."""
    sd = {}

    def conv1d(name, cin, cout, bias=True):
        sd[name + ".weight"] = rng.normal(size=(cout, cin, 1)).astype(np.float32)
        if bias:
            sd[name + ".bias"] = rng.normal(size=(cout,)).astype(np.float32)

    def conv2d(name, cin, cout, bias=True):
        sd[name + ".weight"] = rng.normal(size=(cout, cin, 1, 1)).astype(np.float32)
        if bias:
            sd[name + ".bias"] = rng.normal(size=(cout,)).astype(np.float32)

    def gn(name, ch):
        sd[name + ".weight"] = rng.normal(size=(ch,)).astype(np.float32)
        sd[name + ".bias"] = rng.normal(size=(ch,)).astype(np.float32)

    def setconv(prefix, cin, cout):
        mid = (cout + cin) // 2 if cin % 2 == 0 else cout // 2
        conv2d(prefix + ".fc1", cin + 3, mid, bias=False)
        gn(prefix + ".gn1", mid)
        conv1d(prefix + ".fc2", mid, cout, bias=False)
        gn(prefix + ".gn2", cout)
        conv1d(prefix + ".fc3", cout, cout, bias=False)
        gn(prefix + ".gn3", cout)

    for enc in ("feature_extractor", "context_extractor"):
        setconv(enc + ".feat_conv1", 3, 32)
        setconv(enc + ".feat_conv2", 32, 64)
        setconv(enc + ".feat_conv3", 64, 128)

    # corr_block convs (model/corr.py:15-29)
    conv1d("corr_block.out_conv.0", 81, 128)
    gn("corr_block.out_conv.1", 128)
    sd["corr_block.out_conv.2.weight"] = np.asarray([0.25], np.float32)  # PReLU
    conv1d("corr_block.out_conv.3", 128, 64)
    conv2d("corr_block.knn_conv.0", 4, 64)
    gn("corr_block.knn_conv.1", 64)
    sd["corr_block.knn_conv.2.weight"] = np.asarray([0.25], np.float32)
    conv1d("corr_block.knn_out", 64, 64)

    # update block (model/update.py)
    conv1d("update_block.motion_encoder.conv_corr", 64, 64)
    conv1d("update_block.motion_encoder.conv_flow", 3, 64)
    conv1d("update_block.motion_encoder.conv", 128, 61)
    for g in ("convz", "convr", "convq"):
        conv1d(f"update_block.gru.{g}", 192, 64)
    conv1d("update_block.flow_head.conv1", 64, 64)
    setconv("update_block.flow_head.setconv", 64, 64)
    conv1d("update_block.flow_head.out_conv.0", 128, 64)
    conv1d("update_block.flow_head.out_conv.2", 64, 3)
    return sd


def test_import_matches_model_structure():
    rng = np.random.default_rng(0)
    sd = _torch_style_state_dict(rng)
    tree = import_torch_state_dict(sd)

    cfg = ModelConfig(truncate_k=16, corr_knn=8)
    model = PVRaft(cfg)
    xyz = jnp.asarray(rng.uniform(-1, 1, (1, 48, 3)).astype(np.float32))
    params = model.init(jax.random.key(0), xyz, xyz, 2)["params"]

    flat_ours = {
        jax.tree_util.keystr(k): v.shape
        for k, v in jax.tree_util.tree_leaves_with_path(params)
    }
    flat_imported = {
        jax.tree_util.keystr(k): np.asarray(v).shape
        for k, v in jax.tree_util.tree_leaves_with_path(tree)
    }
    assert flat_ours == flat_imported


def test_imported_params_run_and_match_values():
    rng = np.random.default_rng(1)
    sd = _torch_style_state_dict(rng)
    tree = import_torch_state_dict(sd)

    # Spot-check layout transposes: conv weight (out,in,1) -> kernel (in,out).
    w = sd["update_block.motion_encoder.conv_corr.weight"]
    k = tree["update_iter"]["update_block"]["motion_encoder"]["conv_corr"]["kernel"]
    np.testing.assert_allclose(np.asarray(k), w[..., 0].T)
    # GroupNorm weight -> scale.
    g = sd["feature_extractor.feat_conv1.gn1.weight"]
    s = tree["feature_extractor"]["conv1"]["gn1"]["scale"]
    np.testing.assert_allclose(np.asarray(s), g)

    cfg = ModelConfig(truncate_k=16, corr_knn=8)
    model = PVRaft(cfg)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 48, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 48, 3)).astype(np.float32))
    flows, _ = model.apply({"params": tree}, xyz1, xyz2, num_iters=2)
    assert flows.shape == (2, 1, 48, 3)
    assert np.all(np.isfinite(np.asarray(flows)))


def test_load_torch_checkpoint_file(tmp_path):
    """Round-trip through an actual torch-pickled .params file, including
    the DataParallel 'module.' prefix."""
    import torch

    from pvraft_tpu.engine.checkpoint import load_torch_checkpoint

    rng = np.random.default_rng(2)
    sd = _torch_style_state_dict(rng)
    prefixed = {"module." + k: torch.from_numpy(v) for k, v in sd.items()}
    path = str(tmp_path / "best_checkpoint.params")
    torch.save({"epoch": 11, "state_dict": prefixed}, path)

    tree, epoch = load_torch_checkpoint(path)
    assert epoch == 11
    w = sd["update_block.gru.convz.weight"]
    k = tree["update_iter"]["update_block"]["gru"]["convz"]["kernel"]
    np.testing.assert_allclose(np.asarray(k), w[..., 0].T)


def test_refine_checkpoint_import_and_eval(tmp_path):
    """RSF_refine-layout torch checkpoint -> PVRaftRefine params via the
    Evaluator (zero-shot eval parity path)."""
    import torch

    from pvraft_tpu.config import Config, DataConfig, ModelConfig, TrainConfig
    from pvraft_tpu.engine.evaluator import Evaluator

    rng = np.random.default_rng(3)
    sd = _torch_style_state_dict(rng)
    # Add the refine head (model/refine.py:11-14): SetConvs 3->32->64->128 + fc.
    def gn(name, ch):
        sd[name + ".weight"] = rng.normal(size=(ch,)).astype(np.float32)
        sd[name + ".bias"] = rng.normal(size=(ch,)).astype(np.float32)

    def conv(name, cin, cout, dims, bias):
        shape = (cout, cin) + (1,) * dims
        sd[name + ".weight"] = rng.normal(size=shape).astype(np.float32)
        if bias:
            sd[name + ".bias"] = rng.normal(size=(cout,)).astype(np.float32)

    for prefix, cin, cout in [("refine_block.ref_conv1", 3, 32),
                              ("refine_block.ref_conv2", 32, 64),
                              ("refine_block.ref_conv3", 64, 128)]:
        mid = (cout + cin) // 2 if cin % 2 == 0 else cout // 2
        conv(prefix + ".fc1", cin + 3, mid, 2, False)
        gn(prefix + ".gn1", mid)
        conv(prefix + ".fc2", mid, cout, 1, False)
        gn(prefix + ".gn2", cout)
        conv(prefix + ".fc3", cout, cout, 1, False)
        gn(prefix + ".gn3", cout)
    sd["refine_block.fc.weight"] = rng.normal(size=(3, 128)).astype(np.float32)
    sd["refine_block.fc.bias"] = rng.normal(size=(3,)).astype(np.float32)

    path = str(tmp_path / "refine.params")
    torch.save({"epoch": 5, "state_dict":
                {k: torch.from_numpy(v) for k, v in sd.items()}}, path)

    cfg = Config(
        model=ModelConfig(truncate_k=16, corr_knn=8, graph_k=8),
        data=DataConfig(dataset="synthetic", max_points=48, synthetic_size=2,
                        num_workers=0),
        train=TrainConfig(refine=True, eval_iters=2),
        exp_path=str(tmp_path / "exp"),
    )
    ev = Evaluator(cfg)
    ev.load_torch(path)
    means = ev.run()
    assert np.isfinite(means["epe3d"])
