"""The profiling subsystem: per-stage step profiler schema + CPU smoke."""

import jax.numpy as jnp
import pytest

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.profiling import (
    BREAKDOWN_STAGES,
    MEASUREMENTS,
    SCHEMA_VERSION,
    StepTimer,
    derive_breakdown,
    profile_step,
    validate_step_profile,
)


def _record(total=1.0):
    meas = {
        "encoder": {"sec": 0.1},
        "corr_cum": {"sec": 0.25},
        "fwd1": {"sec": 0.3},
        "fwdN": {"sec": 0.5},
        "gru_fused": {"sec": 0.45},
        "fwdbwd": {"sec": 0.9},
        "step": {"sec": total},
    }
    return {
        "schema": SCHEMA_VERSION,
        "platform": "cpu", "variant": "fp32",
        "points": 64, "batch": 1, "iters": 2, "truncate_k": 16,
        "host_synced": True,
        "measurements": meas,
        "breakdown_s": derive_breakdown(meas),
        "total_step_s": total,
    }


def test_breakdown_telescopes_to_total():
    r = _record()
    assert set(r["breakdown_s"]) == set(BREAKDOWN_STAGES)
    assert sum(r["breakdown_s"].values()) == pytest.approx(
        r["total_step_s"], rel=1e-6)
    assert validate_step_profile(r) == []


def test_validator_catches_missing_and_inconsistent():
    r = _record()
    del r["measurements"]["fwdbwd"]
    assert any("fwdbwd" in p for p in validate_step_profile(r))

    r = _record()
    r["breakdown_s"]["backward"] += 0.5      # no longer sums to total
    assert any("sums to" in p for p in validate_step_profile(r))

    r = _record()
    r["host_synced"] = False
    assert any("host_synced" in p for p in validate_step_profile(r))

    r = _record()
    r["breakdown_s"]["corr_init"] = -0.3     # beyond-noise negative
    r["breakdown_s"]["gru_forward"] += 0.3   # keep the sum intact
    assert any("negative" in p for p in validate_step_profile(r))


def test_profile_step_cpu_smoke():
    """The real instrument end to end on a tiny config: all measurements
    land, the breakdown telescopes, the validator passes (modulo noise
    flags, which the tolerance absorbs at these sizes only rarely —
    retry once on a pure-noise failure)."""
    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                      use_pallas=False)
    for attempt in range(2):
        record = profile_step(cfg, points=64, batch=1, iters=2, reps=1)
        assert set(MEASUREMENTS) <= set(record["measurements"])
        assert all(
            "sec" in record["measurements"][k] for k in MEASUREMENTS
        ), record["measurements"]
        problems = validate_step_profile(record, rel_tol=0.25)
        if not problems:
            break
        noise_only = all("negative" in p or "sums to" in p
                         for p in problems)
        assert noise_only, problems
    assert record["host_synced"] is True
    assert record["config"]["scatter_free_vjp"] is False


def test_step_timer_shim_import():
    # The legacy utils.profiling home must keep re-exporting.
    from pvraft_tpu.utils.profiling import StepTimer as LegacyTimer
    from pvraft_tpu.utils.profiling import trace_context  # noqa: F401

    assert LegacyTimer is StepTimer
    t = StepTimer()
    t.start()
    t.stop(jnp.zeros(()))
    assert t.mean >= 0.0
