"""True reference parity: run the ACTUAL reference torch model
(``/root/reference/model/RAFTSceneFlow.py``) on CPU — with a numpy/torch
``scatter_add`` shim standing in for the torch-scatter CUDA extension at
``model/corr.py:50`` — export its randomly-initialized state_dict, import it
through ``import_torch_state_dict``, and assert per-iteration flows of
``PVRaft`` match the reference within float tolerance.

This certifies the converter and every op's semantics against reality
instead of self-written oracles (``RAFTSceneFlow.py:22-50``,
``corr.py:31-100``, ``update.py:75-87``, ``gconv.py:38-85``,
``graph.py:27-89``). Skipped when the reference checkout is absent.
"""

import os
import sys
import types

import numpy as np
import pytest

REF_ROOT = "/root/reference"

pytestmark = [
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_ROOT, "model")),
        reason="reference checkout not available",
    ),
    pytest.mark.slow,  # torch reference models on CPU: minutes, not seconds
]


@pytest.fixture(scope="module")
def ref_rsf():
    """Import the reference RSF with a torch_scatter shim installed."""
    import torch

    if "torch_scatter" not in sys.modules:
        shim = types.ModuleType("torch_scatter")

        def scatter_add(src, index, dim=-1, dim_size=None):
            # Same contract as torch_scatter.scatter_add for the reference's
            # call sites (model/corr.py:64-65): out[..., i] = sum of src
            # where index == i, output sized to index.max()+1.
            n = int(index.max()) + 1 if dim_size is None else dim_size
            shape = list(src.shape)
            shape[dim] = n
            out = torch.zeros(shape, dtype=src.dtype, device=src.device)
            return out.scatter_add_(dim, index, src)

        shim.scatter_add = scatter_add
        sys.modules["torch_scatter"] = shim

    if REF_ROOT not in sys.path:
        sys.path.insert(0, REF_ROOT)
    from model.RAFTSceneFlow import RSF

    return RSF



def _cloud_pair(seed, n=256):
    rng = np.random.default_rng(seed)
    xyz1 = rng.uniform(-1, 1, (1, n, 3)).astype(np.float32)
    # pc2 = pc1 + small flow keeps voxel bins off rounding boundaries.
    xyz2 = (xyz1 + 0.05 * rng.normal(size=(1, n, 3))).astype(np.float32)
    return xyz1, xyz2


def _ref_args(truncate_k=64):
    return types.SimpleNamespace(
        corr_levels=3, base_scales=0.25, truncate_k=truncate_k
    )


def _make_models(ref_rsf, truncate_k=64, seed=0):
    import torch

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.checkpoint import import_torch_state_dict
    from pvraft_tpu.models.raft import PVRaft

    args = types.SimpleNamespace(
        corr_levels=3, base_scales=0.25, truncate_k=truncate_k
    )
    torch.manual_seed(seed)
    tmodel = ref_rsf(args)
    tmodel.eval()

    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}
    tree = import_torch_state_dict(sd)

    cfg = ModelConfig(truncate_k=truncate_k)
    jmodel = PVRaft(cfg)
    return tmodel, jmodel, {"params": tree}


def test_forward_flows_match_reference(ref_rsf):
    """Same weights + same clouds -> same per-iteration flows (4 iters,
    N=256). This is the end-to-end semantics certificate for encoder,
    graph kNN, corr init/lookup (voxel + knn branches), and the GRU."""
    import torch

    import jax.numpy as jnp

    tmodel, jmodel, variables = _make_models(ref_rsf)

    rng = np.random.default_rng(42)
    n = 256
    xyz1 = rng.uniform(-1, 1, (1, n, 3)).astype(np.float32)
    # pc2 = pc1 + small flow: keeps voxel bin assignments away from the
    # +/-0.5 rounding boundaries that would flip under fp reordering.
    xyz2 = (xyz1 + 0.05 * rng.normal(size=(1, n, 3))).astype(np.float32)

    with torch.no_grad():
        t_flows = tmodel([torch.from_numpy(xyz1), torch.from_numpy(xyz2)],
                         num_iters=4)
    t_flows = np.stack([f.numpy() for f in t_flows])  # (T, B, N, 3)

    j_flows, _ = jmodel.apply(
        variables, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=4
    )
    j_flows = np.asarray(j_flows)

    assert j_flows.shape == t_flows.shape
    # Tolerance: fp32 reorderings accumulate over 4 GRU iterations; top-k
    # tie-breaks are improbable with continuous random features.
    np.testing.assert_allclose(j_flows, t_flows, atol=2e-4, rtol=1e-3)


def test_eval_metrics_match_reference(ref_rsf):
    """The reference eval protocol (test.py:120-126): final-iteration flow
    feeds EPE3D — both frameworks must agree on the metric values too."""
    import torch

    import jax.numpy as jnp

    from pvraft_tpu.engine.metrics import flow_metrics

    tmodel, jmodel, variables = _make_models(ref_rsf, seed=1)

    rng = np.random.default_rng(7)
    n = 256
    xyz1 = rng.uniform(-1, 1, (1, n, 3)).astype(np.float32)
    gt_flow = 0.1 * rng.normal(size=(1, n, 3)).astype(np.float32)
    xyz2 = xyz1 + gt_flow
    mask = np.ones((1, n), np.float32)

    with torch.no_grad():
        t_flow = tmodel([torch.from_numpy(xyz1), torch.from_numpy(xyz2)],
                        num_iters=4)[-1].numpy()
    j_flow = np.asarray(jmodel.apply(
        variables, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=4
    )[0][-1])

    m_t = flow_metrics(jnp.asarray(t_flow), jnp.asarray(mask), jnp.asarray(gt_flow))
    m_j = flow_metrics(jnp.asarray(j_flow), jnp.asarray(mask), jnp.asarray(gt_flow))
    for k in m_t:
        np.testing.assert_allclose(float(m_j[k]), float(m_t[k]), atol=1e-3)


def test_refine_flow_matches_reference(ref_rsf, tmp_path):
    """Stage 2: the ACTUAL reference ``RSF_refine``
    (``model/RAFTSceneFlowRefine.py:22-48``) vs ``PVRaftRefine`` with the
    same weights, round-tripped through a real ``.params`` file and
    ``load_torch_checkpoint(refine=True)`` — certifying the refine-head
    mapping (``model/refine.py:6-22``) and the backbone split."""
    import torch

    import jax.numpy as jnp

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.checkpoint import load_torch_checkpoint
    from pvraft_tpu.models.raft import PVRaftRefine

    from model.RAFTSceneFlowRefine import RSF_refine

    truncate_k = 64
    args = types.SimpleNamespace(
        corr_levels=3, base_scales=0.25, truncate_k=truncate_k
    )
    torch.manual_seed(3)
    tmodel = RSF_refine(args)
    tmodel.eval()

    path = str(tmp_path / "refine.params")
    torch.save({"epoch": 7, "state_dict": tmodel.state_dict()}, path)
    tree, epoch = load_torch_checkpoint(path, refine=True)
    assert epoch == 7

    jmodel = PVRaftRefine(ModelConfig(truncate_k=truncate_k))

    rng = np.random.default_rng(11)
    n = 256
    xyz1 = rng.uniform(-1, 1, (1, n, 3)).astype(np.float32)
    xyz2 = (xyz1 + 0.05 * rng.normal(size=(1, n, 3))).astype(np.float32)

    with torch.no_grad():
        t_flow = tmodel([torch.from_numpy(xyz1), torch.from_numpy(xyz2)],
                        num_iters=4).numpy()
    j_flow = np.asarray(jmodel.apply(
        {"params": tree}, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=4
    ))
    assert j_flow.shape == t_flow.shape
    np.testing.assert_allclose(j_flow, t_flow, atol=2e-4, rtol=1e-3)


def test_export_loads_into_reference_strict(ref_rsf):
    """Inverse interop: params trained HERE load into the actual reference
    RSF with strict=True and produce the same flows — train in this
    framework, evaluate in the reference."""
    import torch

    import jax
    import jax.numpy as jnp

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.checkpoint import export_torch_state_dict
    from pvraft_tpu.models.raft import PVRaft

    truncate_k = 64
    jmodel = PVRaft(ModelConfig(truncate_k=truncate_k))
    xyz1, xyz2 = _cloud_pair(21)
    variables = jmodel.init(
        jax.random.key(2), jnp.asarray(xyz1), jnp.asarray(xyz2), 2
    )

    sd = {k: torch.from_numpy(np.ascontiguousarray(v))
          for k, v in export_torch_state_dict(variables["params"]).items()}
    tmodel = ref_rsf(_ref_args(truncate_k))
    tmodel.load_state_dict(sd, strict=True)  # exact key+shape coverage
    tmodel.eval()

    j_flows, _ = jmodel.apply(
        variables, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=4
    )
    with torch.no_grad():
        t_flows = tmodel([torch.from_numpy(xyz1), torch.from_numpy(xyz2)],
                         num_iters=4)
    t_flows = np.stack([f.numpy() for f in t_flows])
    np.testing.assert_allclose(np.asarray(j_flows), t_flows,
                               atol=2e-4, rtol=1e-3)


def test_export_refine_loads_into_reference_strict(ref_rsf, tmp_path):
    """Stage-2 inverse interop, plus import(export(x)) == x round-trip."""
    import torch

    import jax
    import jax.numpy as jnp

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.checkpoint import (
        export_torch_state_dict,
        load_torch_checkpoint,
    )
    from pvraft_tpu.models.raft import PVRaftRefine

    from model.RAFTSceneFlowRefine import RSF_refine

    truncate_k = 64
    jmodel = PVRaftRefine(ModelConfig(truncate_k=truncate_k))
    xyz1, xyz2 = _cloud_pair(31)
    variables = jmodel.init(
        jax.random.key(5), jnp.asarray(xyz1), jnp.asarray(xyz2), 2
    )

    sd_np = export_torch_state_dict(variables["params"], refine=True)
    tmodel = RSF_refine(_ref_args(truncate_k))
    tmodel.load_state_dict(
        {k: torch.from_numpy(np.ascontiguousarray(v))
         for k, v in sd_np.items()},
        strict=True,
    )
    tmodel.eval()

    j_flow = np.asarray(jmodel.apply(
        variables, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=4
    ))
    with torch.no_grad():
        t_flow = tmodel([torch.from_numpy(xyz1), torch.from_numpy(xyz2)],
                        num_iters=4).numpy()
    np.testing.assert_allclose(j_flow, t_flow, atol=2e-4, rtol=1e-3)

    # Round-trip: exporting then importing reproduces the exact tree.
    path = str(tmp_path / "exported.params")
    torch.save({"epoch": 0, "state_dict": tmodel.state_dict()}, path)
    tree, _ = load_torch_checkpoint(path, refine=True)
    for (ka, a), (kb, b) in zip(
        sorted(jax.tree_util.tree_leaves_with_path(tree),
               key=lambda kv: jax.tree_util.keystr(kv[0])),
        sorted(jax.tree_util.tree_leaves_with_path(variables["params"]),
               key=lambda kv: jax.tree_util.keystr(kv[0])),
        strict=True,
    ):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_flagship_shape_flows_match_reference(ref_rsf):
    """Parity at the FLAGSHIP shape (8,192 points, truncate_k=512 — the
    run.sh training config) with the chunked/streaming paths engaged
    (``corr_chunk``/``graph_chunk``), which never fire at the small test
    sizes above. 2 GRU iterations keep CPU wall-clock tractable while
    still exercising corr init, both lookup branches, and the update GRU
    at scale.

    Tolerance: atol 5e-4 / rtol 1e-3 — looser than the 256-pt tests
    because fp32 reductions over 8k points accumulate more reordering
    error (chunked top-k is exact, so the only divergence source is fp
    summation order). Reference: model/RAFTSceneFlow.py:22-50,
    model/corr.py:31-100 at the run.sh shapes."""
    import torch

    import jax.numpy as jnp

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.engine.checkpoint import import_torch_state_dict
    from pvraft_tpu.models.raft import PVRaft

    truncate_k = 512
    torch.manual_seed(17)
    tmodel = ref_rsf(_ref_args(truncate_k))
    tmodel.eval()

    sd = {k: v.detach().numpy() for k, v in tmodel.state_dict().items()}
    tree = import_torch_state_dict(sd)

    # Chunked streaming on, exact top-k: semantics must be identical.
    cfg = ModelConfig(truncate_k=truncate_k, corr_chunk=2048,
                      graph_chunk=2048)
    jmodel = PVRaft(cfg)

    xyz1, xyz2 = _cloud_pair(99, n=8192)
    with torch.no_grad():
        t_flows = tmodel([torch.from_numpy(xyz1), torch.from_numpy(xyz2)],
                         num_iters=2)
    t_flows = np.stack([f.numpy() for f in t_flows])

    j_flows, _ = jmodel.apply(
        {"params": tree}, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=2
    )
    j_flows = np.asarray(j_flows)

    assert j_flows.shape == t_flows.shape
    np.testing.assert_allclose(j_flows, t_flows, atol=5e-4, rtol=1e-3)

    # The approximate-top-k variant (the TPU fast path: approx_max_k) is
    # allowed small selection differences; its final flow must stay close
    # to the reference in EPE terms rather than elementwise. approx is
    # dense-path only (corr_chunk's scan keeps an exact running top-k).
    cfg_a = ModelConfig(truncate_k=truncate_k, graph_chunk=2048,
                        approx_topk=True)
    ja_flows, _ = PVRaft(cfg_a).apply(
        {"params": tree}, jnp.asarray(xyz1), jnp.asarray(xyz2), num_iters=2
    )
    epe = float(np.linalg.norm(
        np.asarray(ja_flows)[-1] - t_flows[-1], axis=-1).mean())
    assert epe < 5e-3, f"approx-topk flow diverged: EPE {epe}"
