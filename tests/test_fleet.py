"""Fleet tier gate: EPE canary math, the deterministic interleave, the
fleet request ledger, the backend health state machine, router routing/
spillover/quarantine/hot-swap/canary against fake backend hosts, the
chaos-artifact validator (red + the committed evidence), and the
engine's drain-aware zero-recompile weight swap on a real AOT engine.

The fleet tier is jax-free by construction (it talks HTTP, never
tensors), so everything up to the last section runs against stdlib
doubles: `_FakeBackend` is a minimal ThreadingHTTPServer speaking the
slice of the serve-host protocol the router consumes (`/healthz`,
`/predict`, `/admin/reload`). Only the final section pays one tiny AOT
compile (1 bucket x 1 batch size) to pin the swap semantics the fakes
merely mimic."""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

from pvraft_tpu.fleet import (
    Backend,
    BackendClient,
    CanaryController,
    FleetConfig,
    FleetMetrics,
    build_fleet,
    flow_epe,
    validate_fleet_artifact,
)

REPO = Path(__file__).resolve().parents[1]


# ------------------------------------------------------------ canary math --


def test_flow_epe_known_values():
    cand = [[1.0, 0.0, 0.0], [0.0, 3.0, 4.0]]
    base = [[0.0, 0.0, 0.0], [0.0, 0.0, 0.0]]
    out = flow_epe(cand, base)
    assert out["epe"] == pytest.approx((1.0 + 5.0) / 2)
    assert out["mag"] == 0.0
    out = flow_epe(base, cand)                 # mag is the BASELINE's
    assert out["mag"] == pytest.approx(3.0)
    with pytest.raises(ValueError):
        flow_epe(cand, base[:1])               # shape mismatch
    with pytest.raises(ValueError):
        flow_epe([], [])                       # empty comparison


def test_canary_stride_is_deterministic_and_exact():
    """The interleave is a stride, not a coin flip: any window of N
    requests sends exactly floor-fraction of them to the canary, and a
    fresh controller replays the same sequence (no RNG stream)."""
    a = CanaryController(fraction=0.25, min_samples=4)
    b = CanaryController(fraction=0.25, min_samples=4)
    a.arm(1, 0)
    b.arm(1, 0)
    seq_a = [a.take() for _ in range(16)]
    seq_b = [b.take() for _ in range(16)]
    assert seq_a == seq_b
    assert sum(seq_a) == 4                     # exactly fraction * window
    assert a.verdict is None                   # no verdict from takes alone


def test_canary_verdict_once_and_bounds():
    c = CanaryController(fraction=1.0, min_samples=2, epe_bound=0.1,
                         rel_epe_bound=0.5)
    c.arm(1, 0)
    base = [[1.0, 0.0, 0.0]] * 4
    near = [[1.05, 0.0, 0.0]] * 4              # epe 0.05, rel 0.05
    assert c.record(near, base) is None        # below min_samples
    verdict = c.record(near, base)             # crosses min_samples: once
    assert verdict is not None
    assert verdict["verdict"] == "promote"
    assert verdict["epe"] == pytest.approx(0.05)
    assert verdict["rel_epe"] == pytest.approx(0.05)
    assert verdict["samples"] == 2
    assert c.record(near, base) is None        # window closed
    assert c.take() is False                   # no more canary routing
    # A swap that moves predictions past the bound is rejected.
    c.arm(1, 0)
    far = [[2.0, 0.0, 0.0]] * 4                # epe 1.0 > 0.1
    c.record(far, base)
    verdict = c.record(far, base)
    assert verdict["verdict"] == "reject"


def test_canary_arm_rejects_self_comparison():
    c = CanaryController(fraction=0.5, min_samples=2)
    with pytest.raises(ValueError):
        c.arm(1, 1)
    with pytest.raises(ValueError):
        CanaryController(fraction=0.0)         # fraction must be in (0, 1]


# ---------------------------------------------------------- fleet ledger --


def test_fleet_metrics_identity_and_per_backend():
    """requests_total == responses_total + sum(rejected) + in_flight at
    every snapshot — the identity the chaos run polls mid-load."""
    m = FleetMetrics()

    def identity(snap):
        return (snap["requests_total"]
                == snap["responses_total"]
                + sum(snap["rejected"].values()) + snap["in_flight"])

    m.record_submit()
    m.record_submit()
    m.record_submit()
    assert identity(m.snapshot()) and m.current_in_flight() == 3
    m.record_spillover()                       # dispatch fact, not ledger
    m.record_shadow()
    assert identity(m.snapshot())
    m.record_response(0, predicted_s=0.25)
    m.record_response(1, predicted_s=0.5, canary=True)
    m.record_failure("unavailable", backend=1)
    snap = m.snapshot()
    assert identity(snap) and snap["in_flight"] == 0
    assert snap["spillovers_total"] == 1
    assert snap["canary_total"] == 1 and snap["shadow_total"] == 1
    assert snap["predicted_device_seconds_total"] == pytest.approx(0.75)
    assert snap["per_backend"]["0"] == {"responses": 1, "failures": 0,
                                        "predicted_s": 0.25}
    assert snap["per_backend"]["1"]["failures"] == 1


def test_fleet_prometheus_one_hot_state():
    m = FleetMetrics()
    m.record_submit()
    m.record_response(0)
    rows = [{"backend": 0, "state": "healthy", "queue_depth": 2,
             "outstanding": 1},
            {"backend": 1, "state": "quarantined", "queue_depth": 0,
             "outstanding": 0}]
    text = m.prometheus(rows)
    assert "# TYPE pvraft_fleet_requests_total counter" in text
    assert "pvraft_fleet_requests_total 1" in text
    assert ('pvraft_fleet_backend_state{backend="0",state="healthy"} 1'
            in text)
    assert ('pvraft_fleet_backend_state{backend="1",state="healthy"} 0'
            in text)
    assert ('pvraft_fleet_backend_state{backend="1",state="quarantined"} 1'
            in text)
    assert 'pvraft_fleet_backend_queue_depth{backend="0"} 2' in text


# --------------------------------------------- backend health state walk --


def test_backend_state_machine_walk():
    """healthy -> degraded -> quarantined -> probing -> healthy, the
    supervisor vocabulary one tier up, with rotation membership tracking
    the states."""
    b = Backend(0, BackendClient("127.0.0.1", 1),
                degraded_after=1, quarantine_after=3)
    assert b.state == "healthy" and b.in_rotation
    assert b.begin_probe() is None             # only quarantined probes
    assert b.poll_failed() == ("healthy", "degraded")
    assert b.in_rotation                       # degraded still serves
    assert b.poll_failed() is None             # degraded -> degraded
    assert b.poll_failed() == ("degraded", "quarantined")
    assert not b.in_rotation
    assert b.begin_probe() == ("quarantined", "probing")
    assert b.poll_failed() == ("probing", "quarantined")   # failed probe
    assert b.begin_probe() == ("quarantined", "probing")
    health = {"in_flight": 4, "buckets": [32, 64], "dtype": "float32"}
    assert b.poll_succeeded(health) == ("probing", "healthy")
    assert b.in_rotation
    assert b.queue_depth == 4                  # polled load signal
    assert b.buckets() == [32, 64] and b.dtype() == "float32"
    snap = b.snapshot()
    assert snap["state"] == "healthy" and snap["polls_total"] == 5


def test_backend_load_score_orders_by_priced_queue():
    a = Backend(0, BackendClient("127.0.0.1", 1))
    b = Backend(1, BackendClient("127.0.0.1", 2))
    a.poll_succeeded({"in_flight": 5})
    b.poll_succeeded({"in_flight": 1})
    # Unpriced (no cost surface): raw counts break the tie, b wins.
    assert b.load_score(0.0) < a.load_score(0.0)
    # Priced: a's deeper queue costs 5 x 0.1 = 0.5 device-seconds, b's
    # open dispatch 0.5 + 1 x 0.1 = 0.6 — a wins despite more requests.
    b.begin_dispatch(0.5)
    assert a.load_score(0.1) < b.load_score(0.1)
    b.end_dispatch(0.5)
    assert b.load_score(0.0)[0] == 0.0


# ------------------------------------------------- fake backend protocol --


class _FakeBackendHandler(BaseHTTPRequestHandler):
    backend = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: A003 — stdlib signature
        pass

    def _json(self, code, doc, extra=()):
        payload = json.dumps(doc).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        for name, value in extra:
            self.send_header(name, value)
        self.end_headers()
        self.wfile.write(payload)

    def do_GET(self):  # noqa: N802 — stdlib handler naming
        f = self.backend
        if self.path.partition("?")[0] == "/healthz":
            self._json(200, {
                "status": "ok", "buckets": list(f.buckets),
                "dtype": f.dtype, "in_flight": f.in_flight_report,
                "weights": {"digest": f.digest, "epoch": 0, "swaps": 0},
                "pool": {"replicas": 1}})
            return
        self._json(404, {"error": "not_found"})

    def do_POST(self):  # noqa: N802 — stdlib handler naming
        f = self.backend
        length = int(self.headers.get("Content-Length", "0") or "0")
        doc = json.loads(self.rfile.read(length) or b"{}")
        path = self.path.partition("?")[0]
        if path == "/predict":
            with f.lock:
                f.predicts += 1
            if f.mode == "shed":
                self._json(503, {"error": "queue_full"},
                           extra=[("Retry-After", str(f.retry_after))])
                return
            if f.mode == "client_error":
                self._json(400, {"error": "too_small"})
                return
            n = len(doc.get("pc1") or [])
            self._json(200, {"flow": [[f.flow_value, 0.0, 0.0]] * n,
                             "n": n})
            return
        if path == "/admin/reload":
            with f.lock:
                f.reloads += 1
            prev, f.digest = f.digest, f"d-{Path(doc['ckpt']).name}"
            self._json(200, {
                "digest": f.digest, "previous_digest": prev, "epoch": 1,
                "path": doc["ckpt"], "replicas": 1, "drained": 0,
                "drained_in_time": True, "swap_ms": 0.1})
            return
        self._json(404, {"error": "not_found"})


class _FakeBackend:
    """One fake serve host. ``port=<old>`` revives it on the same port
    (HTTPServer sets allow_reuse_address — the chaos run's same-port
    revival shape)."""

    def __init__(self, flow=0.5, buckets=(32, 64), dtype="float32",
                 port=0):
        self.flow_value = flow
        self.mode = "ok"                   # ok | shed | client_error
        self.retry_after = 9
        self.in_flight_report = 0
        self.digest = "d-seed"
        self.buckets = tuple(buckets)
        self.dtype = dtype
        self.predicts = 0
        self.reloads = 0
        self.lock = threading.Lock()
        handler = type("BoundFakeBackendHandler", (_FakeBackendHandler,),
                       {"backend": self})
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.httpd.daemon_threads = True
        self.host = "127.0.0.1"
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def target(self):
        return f"{self.host}:{self.port}"

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(5.0)


def _tiny_cfg(**over):
    base = dict(poll_interval_s=0.05, poll_timeout_s=2.0,
                degraded_after=1, quarantine_after=2, retry_after_s=7,
                predict_timeout_s=10.0)
    base.update(over)
    return FleetConfig(**base)


def _predict_doc(n=20):
    return {"pc1": [[0.0, 0.0, 0.0]] * n, "pc2": [[0.0, 0.0, 0.0]] * n}


# ----------------------------------------------------- routing/spillover --


def test_router_least_loaded_then_spillover_then_total_shed():
    f0, f1 = _FakeBackend(), _FakeBackend()
    router = build_fleet([f0.target, f1.target], cfg=_tiny_cfg())
    try:
        f0.in_flight_report = 5                # f1 is the less loaded
        router.poll_once()
        assert router.bucket_for(20) == 32
        status, body, _ = router.route_predict(_predict_doc())
        assert status == 200 and len(body["flow"]) == 20
        assert (f0.predicts, f1.predicts) == (0, 1)

        # The preferred backend sheds: the request spills to the other
        # and still answers 200 — the client never sees the 503.
        f1.mode = "shed"
        status, body, _ = router.route_predict(_predict_doc())
        assert status == 200
        assert (f0.predicts, f1.predicts) == (1, 2)
        assert router.metrics.snapshot()["spillovers_total"] == 1

        # Every candidate sheds: 503 with a Retry-After no shorter than
        # the backends' own hint (9 > the router's configured 7).
        f0.mode = "shed"
        status, body, retry_after = router.route_predict(_predict_doc())
        assert status == 503 and body["error"] == "unavailable"
        assert retry_after == pytest.approx(9.0)

        snap = router.metrics.snapshot()
        assert snap["requests_total"] == 3
        assert (snap["responses_total"] + sum(snap["rejected"].values())
                + snap["in_flight"]) == 3
        assert snap["rejected"] == {"unavailable": 1}
    finally:
        f0.stop()
        f1.stop()


def test_router_client_errors_do_not_spill():
    """A 400 is deterministic — re-sending it to a second pool would
    just fail twice, so it terminates on the first backend."""
    f0, f1 = _FakeBackend(), _FakeBackend()
    router = build_fleet([f0.target, f1.target], cfg=_tiny_cfg())
    try:
        router.poll_once()
        f0.mode = f1.mode = "client_error"
        status, body, _ = router.route_predict(_predict_doc())
        assert status == 400
        assert f0.predicts + f1.predicts == 1  # exactly one attempt
        snap = router.metrics.snapshot()
        assert snap["spillovers_total"] == 0
        assert snap["rejected"] == {"too_small": 1}
    finally:
        f0.stop()
        f1.stop()


def test_router_quarantine_and_same_port_revival():
    f0, f1 = _FakeBackend(), _FakeBackend()
    router = build_fleet([f0.target, f1.target], cfg=_tiny_cfg())
    try:
        router.poll_once()
        port = f1.port
        f1.stop()                              # the mid-load kill
        router.poll_once()                     # 1 failure -> degraded
        assert router.backends[1].state == "degraded"
        assert router.backends[1].in_rotation  # degraded still routable
        router.poll_once()                     # 2 -> quarantined
        assert router.backends[1].state == "quarantined"
        assert not router.backends[1].in_rotation

        # Out of rotation: every request lands on the survivor.
        for _ in range(3):
            status, _, _ = router.route_predict(_predict_doc())
            assert status == 200
        assert f0.predicts == 3

        # Revival on the SAME port: the next poll probes and readmits.
        f1 = _FakeBackend(port=port)
        router.poll_once()
        assert router.backends[1].state == "healthy"
        assert router.backends[1].in_rotation
        assert router.health_doc()["status"] == "ok"
    finally:
        f0.stop()
        f1.stop()


# ----------------------------------------------------- hot-swap + canary --


def test_admin_reload_fans_out_and_validates():
    f0, f1 = _FakeBackend(), _FakeBackend()
    router = build_fleet([f0.target, f1.target], cfg=_tiny_cfg())
    try:
        router.poll_once()
        status, out = router.admin_reload_doc({})
        assert status == 400                   # no ckpt
        status, out = router.admin_reload_doc({"ckpt": "x", "backend": 9})
        assert status == 400                   # backend out of range
        status, out = router.admin_reload_doc({"ckpt": "x", "canary": True})
        assert status == 400                   # canary needs a backend

        status, out = router.admin_reload_doc(
            {"ckpt": "/ckpts/v2", "drain_timeout_s": 5.0})
        assert status == 200
        assert [r["backend"] for r in out["swapped"]] == [0, 1]
        for row in out["swapped"]:
            assert row["status"] == 200
            report = row["report"]
            assert report["digest"] == "d-v2"
            assert report["digest"] != report["previous_digest"]
        assert (f0.reloads, f1.reloads) == (1, 1)
    finally:
        f0.stop()
        f1.stop()


def test_canary_reload_interleaves_shadows_and_promotes():
    """The full canary story against fakes: a single-backend canary
    swap arms the gate, the stride sends the fraction to the canary,
    each canary answer is shadow-mirrored to the incumbent, and the
    verdict lands against the pinned bounds."""
    f0, f1 = _FakeBackend(flow=0.5), _FakeBackend(flow=0.51)
    cfg = _tiny_cfg(canary_fraction=1.0, canary_min_samples=3)
    router = build_fleet([f0.target, f1.target], cfg=cfg)
    try:
        router.poll_once()
        status, out = router.admin_reload_doc(
            {"ckpt": "/ckpts/v3", "backend": 1, "canary": True})
        assert status == 200
        assert out["canary"]["armed"] is True
        assert out["canary"]["canary_backend"] == 1
        assert out["canary"]["baseline_backend"] == 0
        assert router.backends[1].is_canary()
        assert f0.reloads == 0                 # restricted swap

        for _ in range(3):
            status, body, _ = router.route_predict(_predict_doc())
            assert status == 200
            assert body["flow"][0][0] == pytest.approx(0.51)  # canary-served

        cst = router.canary.status()
        # |0.51 - 0.5| = 0.01 epe, rel 0.02: inside the bf16-precedent
        # bounds, so the candidate promotes.
        assert cst["verdict"]["verdict"] == "promote"
        assert cst["verdict"]["samples"] == 3
        snap = router.metrics.snapshot()
        assert snap["canary_total"] == 3 and snap["shadow_total"] == 3
        assert (snap["requests_total"]
                == snap["responses_total"] + snap["in_flight"]
                + sum(snap["rejected"].values()))

        # Verdict in: the window is closed, traffic goes incumbent-only.
        before = f1.predicts
        status, _, _ = router.route_predict(_predict_doc())
        assert status == 200 and f1.predicts == before

        # A far-off candidate is rejected by the same gate.
        f1.flow_value = 2.0
        status, out = router.admin_canary_doc({"backend": 1})
        assert status == 200 and out["armed"] is True
        for _ in range(3):
            router.route_predict(_predict_doc())
        assert router.canary.status()["verdict"]["verdict"] == "reject"

        router.disarm_canary()
        assert not router.backends[1].is_canary()
    finally:
        f0.stop()
        f1.stop()


def test_canary_needs_an_incumbent():
    f0 = _FakeBackend()
    router = build_fleet([f0.target], cfg=_tiny_cfg())
    try:
        router.poll_once()
        status, out = router.admin_canary_doc({"backend": 0})
        assert status == 409 and out["error"] == "no_baseline"
    finally:
        f0.stop()
    with pytest.raises(ValueError):
        build_fleet([])


# ------------------------------------------------------ router HTTP face --


def _http(method, host, port, path, body=None):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=30)
    try:
        headers = ({"Content-Type": "application/json"}
                   if body is not None else {})
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def test_router_http_surface():
    """Started router end to end over real sockets: /predict, the
    aggregated /healthz (ledger embedded — the chaos run's one-poll
    identity check), JSON + Prometheus /metrics, and the 400/404 edges
    counted honestly."""
    f0, f1 = _FakeBackend(), _FakeBackend()
    router = build_fleet([f0.target, f1.target], cfg=_tiny_cfg())
    router.start()
    try:
        status, body, _ = _http(
            "POST", router.host, router.port, "/predict",
            json.dumps(_predict_doc()))
        assert status == 200
        assert len(json.loads(body)["flow"]) == 20

        status, body, _ = _http("GET", router.host, router.port,
                                "/healthz")
        health = json.loads(body)
        assert status == 200 and health["status"] == "ok"
        assert [r["state"] for r in health["backends"]] == ["healthy"] * 2
        assert health["backends"][0]["weights"]["digest"] == "d-seed"
        assert health["buckets"] == [32, 64]
        assert health["canary"]["armed"] is False
        m = health["metrics"]
        assert (m["requests_total"] == m["responses_total"]
                + sum(m["rejected"].values()) + m["in_flight"])

        status, body, _ = _http(
            "POST", router.host, router.port, "/predict", "not json")
        assert status == 400

        status, body, _ = _http("GET", router.host, router.port,
                                "/metrics")
        snap = json.loads(body)
        assert snap["requests_total"] == 2
        assert snap["rejected"] == {"bad_request": 1}

        status, body, headers = _http(
            "GET", router.host, router.port, "/metrics?format=prometheus")
        assert status == 200
        assert headers["Content-Type"].startswith("text/plain")
        assert b"pvraft_fleet_backend_state" in body

        status, _, _ = _http("GET", router.host, router.port, "/nope")
        assert status == 404
        status, _, _ = _http("POST", router.host, router.port, "/nope",
                             "{}")
        assert status == 404
    finally:
        router.shutdown()
        f0.stop()
        f1.stop()


# ------------------------------------------------------ chaos artifact --


def test_validate_fleet_artifact_red():
    assert validate_fleet_artifact([]) == ["<fleet_chaos>: not a JSON object"]
    assert any("schema" in p
               for p in validate_fleet_artifact({"schema": "nope"}))
    doc = {"schema": "pvraft_fleet_chaos/v1", "config": {"backends": 1},
           "phases": [], "recompiles": 3}
    problems = validate_fleet_artifact(doc)
    assert any("backends" in p for p in problems)       # fleet needs >= 2
    assert any("traffic_mix" in p for p in problems)
    assert any("load" in p for p in problems)
    assert any("spillovers" in p for p in problems)     # loss must re-route
    assert any("verdict" in p for p in problems)
    assert any("reconciliation" in p for p in problems)
    assert any("recompiles" in p for p in problems)     # must be 0


def test_committed_fleet_chaos_artifact_is_valid():
    """The committed evidence re-validates through the same gate the
    generator enforced — a hand-edited artifact cannot pass."""
    path = REPO / "artifacts" / "fleet_chaos.json"
    doc = json.loads(path.read_text())
    assert validate_fleet_artifact(doc, path=str(path)) == []
    assert doc["recompiles"] == 0 and doc["watchdog_trips"] == 0
    assert doc["phases"][1]["spillovers"] > 0
    assert doc["reconciliation"]["holds"] is True


# --------------------------------------- real-engine zero-recompile swap --


@pytest.fixture(scope="module")
def swap_engine():
    """One minimal AOT engine (1 bucket x 1 batch size — a single
    program compile) shared by the swap tests."""
    import jax
    import jax.numpy as jnp

    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models import PVRaft
    from pvraft_tpu.serve import InferenceEngine, ServeConfig

    model_cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)
    cfg = ServeConfig(model=model_cfg, buckets=(32,), batch_sizes=(1,),
                      num_iters=1, dtype="float32", replicas=1)
    rng = np.random.default_rng(0)
    model = PVRaft(model_cfg)
    pc = jnp.asarray(rng.uniform(-1, 1, (1, 24, 3)).astype(np.float32))
    params = model.init(jax.random.key(0), pc, pc, cfg.num_iters)
    return InferenceEngine(params, cfg), params


def test_engine_hot_swap_changes_weights_without_recompile(swap_engine,
                                                           tmp_path):
    """The tentpole property on a real engine: a swap changes the
    served weights (digest + predictions) while the AOT program table
    stays exactly as compiled."""
    import jax

    from pvraft_tpu.engine.checkpoint import save_checkpoint
    from pvraft_tpu.serve.engine import params_digest

    engine, params = swap_engine
    rng = np.random.default_rng(7)
    pc1 = rng.uniform(-1, 1, (20, 3)).astype(np.float32)
    pc2 = rng.uniform(-1, 1, (20, 3)).astype(np.float32)
    before = engine.predict(pc1, pc2)
    programs_before = len(engine.compile_report())
    info = engine.weights_info()
    assert info["digest"] == params_digest(params)
    assert info["swaps"] == 0

    bumped = jax.tree_util.tree_map(
        lambda x: x * 1.01 if np.issubdtype(np.asarray(x).dtype,
                                            np.floating) else x, params)
    save_checkpoint(str(tmp_path), bumped, None, 7, checkpoint_interval=0)
    report = engine.reload_checkpoint(
        str(tmp_path / "last_checkpoint.msgpack"))
    assert report["digest"] != report["previous_digest"]
    assert report["previous_digest"] == info["digest"]
    assert report["epoch"] == 7
    assert report["drained_in_time"] is True

    info = engine.weights_info()
    assert info["digest"] == report["digest"] and info["swaps"] == 1
    after = engine.predict(pc1, pc2)
    assert not np.allclose(before, after)      # new weights actually serve
    assert len(engine.compile_report()) == programs_before  # zero recompiles


def test_engine_swap_rejects_structure_mismatch(swap_engine):
    """A tree that doesn't match the compiled params signature would
    force a recompile — rejected up front, weights untouched."""
    engine, _ = swap_engine
    info = engine.weights_info()
    with pytest.raises(ValueError, match="swap rejected"):
        engine.swap_params({"nope": np.zeros(3, np.float32)})
    assert engine.weights_info()["digest"] == info["digest"]
