"""Retrace watchdog (obs/retrace.py): baseline learning, trip + event
emission, strict mode, and the sealed serve mode under real batcher
thread concurrency — the runtime complement of deepcheck GJ007."""

import json
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from pvraft_tpu.obs.retrace import (  # noqa: E402
    RetraceError,
    RetraceWatchdog,
    args_signature,
)


def _jitted():
    return jax.jit(lambda x: x * 2.0 + 1.0)


def test_args_signature_renders_shapes():
    sig = args_signature((np.zeros((2, 8, 3), np.float32),
                          {"m": np.zeros((2, 8), np.int32)}))
    assert sig == "float32[2,8,3],int32[2,8]"


def test_watchdog_learns_baseline_then_trips():
    events = []
    dog = RetraceWatchdog(emit=lambda **kw: events.append(kw))
    f = _jitted()
    dog.watch("prog", f)
    # Before any call: nothing to learn, nothing trips.
    assert dog.check() == []
    f(np.ones(4, np.float32))
    # First compile IS the program (warmup) — learned, not a trip.
    assert dog.check() == []
    f(np.ones(4, np.float32))
    assert dog.check() == []               # cache hit
    f(np.ones(5, np.float32))              # silent retrace
    trips = dog.check(signature=lambda: "float32[5]")
    assert [t["program"] for t in trips] == ["prog"]
    assert trips[0]["count"] == trips[0]["baseline"] + 1
    assert events[0]["signature"] == "float32[5]"
    assert events[0]["context"] == "train"
    # One growth = one event: the new size is the new baseline.
    assert dog.check() == []
    assert dog.trips == 1


def test_watchdog_strict_raises():
    dog = RetraceWatchdog(strict=True)
    f = _jitted()
    dog.watch("prog", f)
    f(np.ones(4, np.float32))
    dog.check()
    f(np.ones((2, 2), np.float32))
    with pytest.raises(RetraceError, match="prog.*recompiled after warmup"):
        dog.check(signature="float32[2,2]")


def test_watchdog_event_is_schema_valid(tmp_path):
    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.serve.events import ServeTelemetry

    tel = ServeTelemetry(str(tmp_path / "serve.events.jsonl"),
                         enabled=True)
    dog = RetraceWatchdog(emit=tel.emit_recompile, context="serve")
    f = _jitted()
    dog.watch("predict_b2048_bs1", f)
    f(np.ones(3, np.float32))
    dog.check()
    f(np.ones(6, np.float32))
    assert len(dog.check(signature="float32[6]")) == 1
    tel.close()
    path = str(tmp_path / "serve.events.jsonl")
    assert validate_events_file(path) == []
    records = [json.loads(l) for l in open(path)]
    rec = [r for r in records if r["type"] == "recompile"]
    assert rec and rec[0]["program"] == "predict_b2048_bs1"
    assert rec[0]["count"] == rec[0]["baseline"] + 1
    assert rec[0]["signature"] == "float32[6]"


def test_sealed_mode_counts_any_backend_compile():
    dog = RetraceWatchdog(context="serve")
    assert dog.seal()
    try:
        assert dog.check() == []           # nothing compiled since seal
        _jitted()(np.ones(7, np.float32))  # a compile from anywhere
        trips = dog.check(program="serve_dispatch_b2048")
        assert trips and trips[0]["program"] == "serve_dispatch_b2048"
        assert dog.check() == []           # re-baselined after the trip
    finally:
        dog.close()
    # Closed: further compiles are no longer watched.
    _jitted()(np.ones(9, np.float32))
    assert dog.check() == []


def test_sealed_window_one_compile_trips_once():
    """Two concurrent dispatches that both captured their window before
    one compile landed must report it ONCE: the first reporter's ratchet
    disarms the second's stale window."""
    dog = RetraceWatchdog(context="serve")
    assert dog.seal()
    try:
        dog.check()                          # settle the baseline
        window_a = dog.global_compiles()
        window_b = dog.global_compiles()     # both in flight
        _jitted()(np.ones(17, np.float32))   # one hidden compile
        assert len(dog.check(window_start=window_a)) == 1
        assert dog.check(window_start=window_b) == []
        assert dog.trips == 1
    finally:
        dog.close()


def test_sealed_window_ignores_co_resident_compiles():
    """The serve_ab two-leg pattern: another engine compiling its own
    startup table BETWEEN dispatches must not trip a windowed check —
    only compiles landing inside the dispatch window do."""
    dog = RetraceWatchdog(context="serve")
    assert dog.seal()
    try:
        _jitted()(np.ones(11, np.float32))  # co-resident leg compiles
        window = dog.global_compiles()      # dispatch begins AFTER it
        assert dog.check(window_start=window) == []
        # The ratchet also cleared the backlog for default checks.
        assert dog.check() == []
        window = dog.global_compiles()
        _jitted()(np.ones(13, np.float32))  # compile DURING the window
        trips = dog.check(program="serve_dispatch_b32",
                          window_start=window)
        assert trips and trips[0]["baseline"] == window
    finally:
        dog.close()


class _RetracingEngine:
    """Batcher double whose dispatch path hides a jit compile — the
    exact failure --strict-retrace exists to catch (a per-request
    compile stall on the 'AOT-only' serving path)."""

    def __init__(self):
        from types import SimpleNamespace

        self.cfg = SimpleNamespace(buckets=(32,), batch_sizes=(1,),
                                   min_points=4, coord_limit=100.0)
        self.calls = 0

    def validate_request(self, pc1, pc2):
        return 32

    def batch_size_for(self, n):
        return 1

    def predict_batch(self, requests, bucket):
        self.calls += 1
        if self.calls > 1:
            # A fresh program compiles mid-serving (shape varies per
            # call so the second dispatch really hits the backend).
            jax.jit(lambda x: x + float(self.calls))(
                np.ones(self.calls, np.float32))
        return [np.zeros((pc1.shape[0], 3), np.float32)
                for pc1, _ in requests]

    def compile_report(self):
        return []


def _submit_and_wait(batcher, n=8, seed=0):
    pc = np.random.default_rng(seed).uniform(-1, 1, (n, 3)).astype(
        np.float32)
    return batcher.submit(pc, pc + 0.1).wait(20.0)


def test_forced_recompile_trips_strict_retrace_threaded(tmp_path):
    """The acceptance path: a forced recompile inside the (threaded)
    serve dispatch emits a `recompile` event, bumps the Prometheus
    counter, and under --strict-retrace fails the request loudly."""
    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.serve.batcher import BatcherConfig, MicroBatcher
    from pvraft_tpu.serve.events import ServeTelemetry
    from pvraft_tpu.serve.metrics import ServeMetrics

    events_path = str(tmp_path / "serve.events.jsonl")
    tel = ServeTelemetry(events_path, enabled=True)
    metrics = ServeMetrics(buckets=(32,))
    dog = RetraceWatchdog(emit=tel.emit_recompile, strict=True,
                          context="serve")
    engine = _RetracingEngine()
    assert dog.seal()
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        telemetry=tel, metrics=metrics, watchdog=dog)
    try:
        # First dispatch: clean (no compile since seal).
        flow = _submit_and_wait(batcher, seed=1)
        assert flow.shape == (8, 3)
        # Second dispatch hides a compile -> the executor's watchdog
        # check raises and the batch fails with RetraceError.
        with pytest.raises(RetraceError, match="recompiled after warmup"):
            _submit_and_wait(batcher, seed=2)
    finally:
        batcher.shutdown(drain=True)
        dog.close()
        tel.close()
    assert metrics.recompiles_total == 1
    prom = metrics.prometheus()
    assert "pvraft_serve_recompiles_total 1" in prom
    assert validate_events_file(events_path) == []
    records = [json.loads(l) for l in open(events_path)]
    rec = [r for r in records if r["type"] == "recompile"]
    assert len(rec) == 1
    assert rec[0]["program"] == "serve_dispatch_b32"
    assert rec[0]["signature"] == "bucket=32 n=1"
    assert rec[0]["context"] == "serve"


def test_non_strict_observes_without_failing(tmp_path):
    """Without --strict-retrace the same forced recompile is recorded
    (event + counter) but the request still succeeds."""
    from pvraft_tpu.serve.batcher import BatcherConfig, MicroBatcher
    from pvraft_tpu.serve.events import ServeTelemetry
    from pvraft_tpu.serve.metrics import ServeMetrics

    events_path = str(tmp_path / "serve.events.jsonl")
    tel = ServeTelemetry(events_path, enabled=True)
    metrics = ServeMetrics(buckets=(32,))
    dog = RetraceWatchdog(emit=tel.emit_recompile, strict=False,
                          context="serve")
    engine = _RetracingEngine()
    assert dog.seal()
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        telemetry=tel, metrics=metrics, watchdog=dog)
    try:
        assert _submit_and_wait(batcher, seed=1).shape == (8, 3)
        assert _submit_and_wait(batcher, seed=2).shape == (8, 3)
    finally:
        batcher.shutdown(drain=True)
        dog.close()
        tel.close()
    assert metrics.recompiles_total == 1
    records = [json.loads(l) for l in open(events_path)]
    assert sum(r["type"] == "recompile" for r in records) == 1


def test_watchdog_threadsafe_check():
    """Concurrent checks from executor-like threads never double-count
    one growth."""
    dog = RetraceWatchdog()
    f = _jitted()
    dog.watch("prog", f)
    f(np.ones(4, np.float32))
    dog.check()
    f(np.ones((3, 3), np.float32))
    trips, barrier = [], threading.Barrier(4)

    def worker():
        barrier.wait(5)
        trips.extend(dog.check())

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    assert len(trips) == 1
    assert dog.trips == 1
