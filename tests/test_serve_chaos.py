"""Chaos acceptance suite (ISSUE 13): kill a replica mid-load under a
deterministic FaultPlan and prove the pool survives.

The flagship test drives a REAL 2-replica AOT engine over HTTP while an
armed FaultPlan permanently fails one replica's dispatches: every
request must resolve, the ``requests_total == responses_total +
Σrejected + in_flight`` identity must hold at every polled snapshot
(parsed from ONE atomic Prometheus render), the failed replica must be
quarantined and then revived by a probe after the fault clears, and
post-recovery throughput returns with ZERO recompiles (the sealed
retrace watchdog stays quiet — the probe runs through an
already-compiled program).

The deterministic-thread tests (degradation to ``rejected[unavailable]``,
Retry-After headers, compile_trip through the watchdog, client-side
loadgen retries) use a fake pool — real sockets and real threads, no
XLA. Multi-replica reality rides the conftest-pinned virtual device
count, like test_serve_pool.
"""

import json
import re
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.models import PVRaft
from pvraft_tpu.serve import (
    FaultPlan,
    FaultRule,
    InferenceEngine,
    ServeConfig,
    ServeTelemetry,
    build_service,
    faults,
)
from pvraft_tpu.serve.engine import RequestError
from pvraft_tpu.serve.loadgen import run_load, validate_load_artifact
from pvraft_tpu.serve.supervisor import SupervisorConfig

TINY_MODEL = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)
CHAOS_SERVE = ServeConfig(model=TINY_MODEL, buckets=(32,),
                          batch_sizes=(1, 2), num_iters=2,
                          dtype="float32", replicas=2)
TIGHT = SupervisorConfig(degraded_after=1, quarantine_after=2,
                         probe_interval_s=0.05, wedge_timeout_s=30.0)


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    faults.clear_plan()


@pytest.fixture(scope="module")
def chaos_pool():
    """One 2-replica fp32 AOT engine for the module (the replica > 0
    table compiles against the in-process executable cache, so this
    costs ~one table of wall clock — the test_serve_pool discipline)."""
    rng = np.random.default_rng(0)
    model = PVRaft(TINY_MODEL)
    pc = jnp.asarray(rng.uniform(-1, 1, (1, 24, 3)).astype(np.float32))
    params = model.init(jax.random.key(0), pc, pc, 2)
    return InferenceEngine(params, CHAOS_SERVE)


def _pc(n, seed=0):
    return np.random.default_rng(seed).uniform(
        -1, 1, (n, 3)).astype(np.float32)


def _poll(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return predicate()


def _http(method, host, port, path, body=None,
          ctype="application/json"):
    import http.client

    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        headers = {"Content-Type": ctype} if body is not None else {}
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        return resp.status, resp.read(), dict(resp.getheaders())
    finally:
        conn.close()


def _get_json(server, path):
    return json.loads(_http("GET", server.host, server.port, path)[1])


def _prom_counters(text):
    """{metric: value} for the identity's unlabeled samples plus the
    summed rejected counter — all read from ONE exposition render (the
    handler holds the metrics lock for the whole render, so this IS an
    atomic snapshot)."""
    out = {}
    for name in ("pvraft_serve_requests_total",
                 "pvraft_serve_responses_total",
                 "pvraft_serve_in_flight",
                 "pvraft_serve_recompiles_total",
                 "pvraft_serve_retries_total"):
        m = re.search(rf"^{name} (\S+)$", text, re.M)
        out[name] = float(m.group(1)) if m else 0.0
    out["rejected"] = sum(
        float(v) for v in re.findall(
            r'^pvraft_serve_rejected_total\{[^}]*\} (\S+)$', text, re.M))
    return out


# ------------------------------------------------- the acceptance test --


def test_chaos_replica_failure_quarantine_probe_recovery(
        chaos_pool, tmp_path):
    """THE ISSUE-13 acceptance scenario, on the real AOT pool."""
    events_path = str(tmp_path / "chaos.events.jsonl")
    telemetry = ServeTelemetry(events_path, cfg=CHAOS_SERVE)
    server = build_service(chaos_pool, max_wait_ms=2, queue_depth=32,
                           telemetry=telemetry, trace_sample_every=0,
                           supervisor_cfg=TIGHT)
    server.start()
    sup = server.supervisor
    assert sup is not None

    identity_violations = []
    stop_poll = threading.Event()

    def poller():
        while not stop_poll.is_set():
            _, body, _ = _http("GET", server.host, server.port,
                               "/metrics?format=prometheus")
            c = _prom_counters(body.decode())
            if c["pvraft_serve_requests_total"] != (
                    c["pvraft_serve_responses_total"] + c["rejected"]
                    + c["pvraft_serve_in_flight"]):
                identity_violations.append(c)
            time.sleep(0.01)

    poll_thread = threading.Thread(target=poller, daemon=True)
    poll_thread.start()
    statuses = []

    def drive(n, concurrency=3, seed=0):
        lock = threading.Lock()
        cursor = [0]

        def client():
            while True:
                with lock:
                    i = cursor[0]
                    if i >= n:
                        return
                    cursor[0] = i + 1
                pc1 = _pc(20, seed * 1000 + i)
                status, _, _ = _http(
                    "POST", server.host, server.port, "/predict",
                    json.dumps({"pc1": pc1.tolist(),
                                "pc2": (pc1 + 0.01).tolist()}))
                with lock:
                    statuses.append(status)

        threads = [threading.Thread(target=client) for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    try:
        # Phase A: healthy pool baseline.
        drive(6, seed=1)
        assert statuses.count(200) == 6

        # Phase B: permanently fail replica 1 mid-load. Every dispatch
        # that lands there raises; the batcher retries on replica 0, so
        # clients still see 200s while the supervisor walks replica 1
        # to quarantined.
        faults.install_plan(FaultPlan([
            FaultRule("replica_predict_error", nth=1, every=1,
                      replica=1)]))
        drive(12, seed=2)
        assert _poll(lambda: sup.state_of(1) == "quarantined"), \
            sup.states()
        health = _get_json(server, "/healthz")
        assert health["replicas"][1]["state"] == "quarantined"
        assert health["pool"]["state"] == "degraded"
        assert health["pool"]["healthy_replicas"] == 1
        assert health["faults"]["armed"] is True
        # The fault evidence is on the ledger, not folklore.
        assert health["faults"]["fired_total"] >= 2

        # Quarantined = out of rotation: the pool keeps answering.
        drive(6, seed=3)

        # Phase C: the fault clears; a probe (through replica 1's OWN
        # AOT program) revives it.
        faults.clear_plan()
        assert _poll(lambda: sup.state_of(1) == "healthy"), sup.states()
        health = _get_json(server, "/healthz")
        assert health["pool"]["state"] == "ok"
        assert health["pool"]["healthy_replicas"] == 2
        assert health["faults"]["armed"] is False

        # Phase D: post-recovery throughput, both replicas serving.
        drive(6, seed=4)

        # Every submitted request resolved as a 2xx (retry-once absorbed
        # every injected failure: replica 0 stayed healthy throughout).
        assert statuses.count(200) == len(statuses) == 30

        # Zero recompiles end to end: AOT programs + probe reuse, the
        # sealed watchdog never fired.
        _, body, _ = _http("GET", server.host, server.port,
                           "/metrics?format=prometheus")
        counters = _prom_counters(body.decode())
        assert counters["pvraft_serve_recompiles_total"] == 0
        assert counters["pvraft_serve_retries_total"] >= 1
        # Final reconciliation at quiescence.
        assert counters["pvraft_serve_requests_total"] == 30
        assert counters["pvraft_serve_responses_total"] == 30
        assert counters["rejected"] == 0
        assert counters["pvraft_serve_in_flight"] == 0

        # JSON /metrics stays byte-frozen in SHAPE with the supervisor
        # wired (fault tolerance is Prometheus/healthz-only).
        snap = _get_json(server, "/metrics")
        assert set(snap) == {
            "requests_total", "responses_total", "rejected",
            "batches_total", "batch_fill_mean", "per_bucket_requests",
            "latency", "queue_depth"}
    finally:
        stop_poll.set()
        poll_thread.join(5)
        server.shutdown(drain=True)
        telemetry.close()

    # The identity held at EVERY polled snapshot, not just quiescence.
    assert identity_violations == []

    # The full story is on the event stream and validates.
    from pvraft_tpu.obs.events import validate_events_file

    assert validate_events_file(events_path) == []
    recs = [json.loads(line) for line in open(events_path,
                                              encoding="utf-8")]
    states = [(r["from_state"], r["state"], r["reason"])
              for r in recs if r["type"] == "replica_state"]
    assert ("degraded", "quarantined", "InjectedFaultError") in states
    assert ("probing", "healthy", "probe_ok") in states
    injected = [r for r in recs if r["type"] == "fault_injected"]
    assert injected and all(
        r["point"] == "replica_predict_error" and r["replica"] == 1
        for r in injected)
    assert not [r for r in recs if r["type"] == "recompile"]


# ------------------------------------------------ fake pool (no XLA) --


class _Replica:
    def __init__(self, index):
        self.index = index
        self.device_id = index
        self.calls = 0

    def predict_batch(self, requests, bucket):
        self.calls += 1
        return [np.asarray(pc2[: pc1.shape[0]] - pc1, np.float32)
                for pc1, pc2 in requests]


class _Engine:
    def __init__(self, buckets=(32,), batch_sizes=(1, 2), n=2):
        self.cfg = SimpleNamespace(
            buckets=buckets, batch_sizes=batch_sizes, min_points=4,
            coord_limit=100.0, dtype="float32")
        self.replicas = [_Replica(i) for i in range(n)]

    def validate_request(self, pc1, pc2):
        m = max(pc1.shape[0], pc2.shape[0])
        for b in self.cfg.buckets:
            if m <= b:
                return b
        raise RequestError("too_large", "too large")

    def batch_size_for(self, n):
        for bs in self.cfg.batch_sizes:
            if n <= bs:
                return bs
        return self.cfg.batch_sizes[-1]

    def compile_report(self):
        return []

    def weights_info(self):
        return {"path": "", "digest": "fake", "epoch": -1, "swaps": 0}


def _fake_service(tmp_path, supervisor_cfg=TIGHT, queue_depth=16,
                  **kw):
    telemetry = ServeTelemetry(str(tmp_path / "chaos.events.jsonl"))
    server = build_service(_Engine(), max_wait_ms=2,
                           queue_depth=queue_depth, telemetry=telemetry,
                           trace_sample_every=0,
                           supervisor_cfg=supervisor_cfg, **kw)
    server.start()
    return server, telemetry


def test_all_replicas_down_degrades_to_unavailable(tmp_path):
    """Both replicas fail -> both quarantined -> 503 ``unavailable``
    with Retry-After (explicit shed, not a queue-timeout 504); clearing
    the fault lets the probes revive the whole pool."""
    cfg = SupervisorConfig(degraded_after=1, quarantine_after=1,
                           probe_interval_s=0.05)
    server, telemetry = _fake_service(tmp_path, supervisor_cfg=cfg)
    sup = server.supervisor
    try:
        with faults.injected(FaultPlan([
                FaultRule("replica_predict_error", nth=1, every=1)])):
            # First request: dispatch fails, the one retry fails on the
            # sibling -> 500; both replicas hit quarantine_after=1.
            pc = _pc(20)
            status, _, _ = _http(
                "POST", server.host, server.port, "/predict",
                json.dumps({"pc1": pc.tolist(), "pc2": pc.tolist()}))
            assert status == 500
            assert _poll(lambda: sup.serving_count() == 0), sup.states()
            assert _get_json(server, "/healthz")["pool"]["state"] == \
                "unavailable"
            # Degraded pool sheds at admission: explicit 503
            # unavailable + Retry-After, immediately.
            status, body, headers = _http(
                "POST", server.host, server.port, "/predict",
                json.dumps({"pc1": pc.tolist(), "pc2": pc.tolist()}))
            assert status == 503
            assert json.loads(body)["error"] == "unavailable"
            assert headers.get("Retry-After") == str(cfg.retry_after_s)
        # Fault cleared: probes bring the pool back without a restart.
        assert _poll(lambda: sup.serving_count() == 2), sup.states()
        status, _, _ = _http(
            "POST", server.host, server.port, "/predict",
            json.dumps({"pc1": pc.tolist(), "pc2": pc.tolist()}))
        assert status == 200
        snap = _get_json(server, "/metrics")
        # Identity at quiescence: 3 requests = 1 response + internal +
        # unavailable... plus the 200 -> 2 responses? No: 500 counted
        # rejected[internal], 503 rejected[unavailable], 200 response.
        assert snap["requests_total"] == 3
        assert snap["responses_total"] == 1
        assert snap["rejected"] == {"internal": 1, "unavailable": 1}
    finally:
        server.shutdown(drain=True)
        telemetry.close()


def test_queue_full_503_carries_retry_after(tmp_path):
    """Backpressure 503s advertise the probe cadence too: a shed client
    knows exactly when the pool's health is next re-evaluated."""
    cfg = SupervisorConfig(probe_interval_s=2.5)   # Retry-After: 3
    server, telemetry = _fake_service(tmp_path, supervisor_cfg=cfg,
                                      queue_depth=1)
    try:
        with faults.injected(FaultPlan([
                FaultRule("replica_latency_ms", nth=1, every=1,
                          value=400.0)])):
            # Saturate: 2 slow executors + batch queue + 1-deep bucket
            # queue; later submits shed. Barrier-start the clients so
            # the burst arrives together even on a loaded CPU, and
            # drive MORE requests than the pipeline can absorb even at
            # max grouping (2 exec groups + 2 batch-queue groups + 1
            # collector-held group, 2 requests each, + the 1-deep
            # queue = 11): with 16 in one burst at least 5 must shed
            # regardless of scheduler interleaving (the 8-client
            # version flaked under a loaded full-suite run).
            results = []
            burst = 16
            barrier = threading.Barrier(burst)
            lock = threading.Lock()

            def client(seed):
                pc = _pc(20, seed)
                payload = json.dumps({"pc1": pc.tolist(),
                                      "pc2": pc.tolist()})
                barrier.wait(10)
                r = _http("POST", server.host, server.port, "/predict",
                          payload)
                with lock:
                    results.append(r)

            threads = [threading.Thread(target=client, args=(s,))
                       for s in range(burst)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        shed = [(s, h) for s, _, h in results if s == 503]
        assert shed, [s for s, _, _ in results]
        assert all(h.get("Retry-After") == "3" for _, h in shed)
    finally:
        server.shutdown(drain=True)
        telemetry.close()


def test_compile_trip_flows_through_sealed_watchdog(tmp_path):
    """The ``compile_trip`` fault point simulates a hidden post-seal
    backend compile THROUGH the real watchdog: the Prometheus counter
    bumps and a ``recompile`` event lands, exactly as a genuine retrace
    would report."""
    server, telemetry = _fake_service(tmp_path)
    try:
        with faults.injected(FaultPlan([
                FaultRule("compile_trip", nth=1)])):
            pc = _pc(20)
            status, _, _ = _http(
                "POST", server.host, server.port, "/predict",
                json.dumps({"pc1": pc.tolist(), "pc2": pc.tolist()}))
            assert status == 200                 # observe-only mode
        _, body, _ = _http("GET", server.host, server.port,
                           "/metrics?format=prometheus")
        assert _prom_counters(
            body.decode())["pvraft_serve_recompiles_total"] == 1
    finally:
        server.shutdown(drain=True)
        telemetry.close()
    recs = [json.loads(line)
            for line in open(str(tmp_path / "chaos.events.jsonl"),
                             encoding="utf-8")]
    trips = [r for r in recs if r["type"] == "recompile"]
    assert len(trips) == 1 and trips[0]["program"].startswith(
        "serve_dispatch_b32")
    fired = [r for r in recs if r["type"] == "fault_injected"]
    assert [r["point"] for r in fired] == ["compile_trip"]


def test_strict_retrace_failure_not_attributed_to_replica(tmp_path):
    """Strict mode: an injected post-seal compile fails the batch (500)
    — but it is a PROCESS-wide event, not the replica's fault: no
    health transition, no retry (the retry would trip identically)."""
    server, telemetry = _fake_service(tmp_path, strict_retrace=True)
    sup = server.supervisor
    try:
        with faults.injected(FaultPlan([
                FaultRule("compile_trip", nth=1)])):
            pc = _pc(20)
            status, body, _ = _http(
                "POST", server.host, server.port, "/predict",
                json.dumps({"pc1": pc.tolist(), "pc2": pc.tolist()}))
        assert status == 500
        assert json.loads(body)["detail"].startswith("RetraceError")
        assert [r["state"] for r in sup.states()] == \
            ["healthy", "healthy"]
        assert server.batcher.counts["retries"] == 0
    finally:
        server.shutdown(drain=True)
        telemetry.close()


def test_loadgen_client_retries_record_attempts(tmp_path):
    """The loadgen satellite: ``retries`` re-attempts 503s with backoff
    honoring Retry-After, records every attempt per request
    (schema-additive), and keeps ok+rejected+errors == total."""
    cfg = SupervisorConfig(degraded_after=1, quarantine_after=1,
                           probe_interval_s=60.0)  # probes never revive
    server, telemetry = _fake_service(tmp_path, supervisor_cfg=cfg)
    sup = server.supervisor
    try:
        faults.install_plan(FaultPlan([
            FaultRule("replica_predict_error", nth=1, every=1)]))
        # Quarantine the whole pool first (one request's dispatch +
        # retry fail both replicas).
        pc = _pc(20)
        _http("POST", server.host, server.port, "/predict",
              json.dumps({"pc1": pc.tolist(), "pc2": pc.tolist()}))
        assert _poll(lambda: sup.serving_count() == 0)
        t0 = time.monotonic()
        m = run_load(server, n_requests=3, concurrency=3,
                     point_counts=[20], retries=1)
        elapsed = time.monotonic() - t0
    finally:
        faults.clear_plan()
        server.shutdown(drain=True)
        telemetry.close()
    # Every request: attempt 1 503-unavailable, jittered backoff (>=
    # 0.5 x Retry-After=1s), attempt 2 503 -> final status 503, counted
    # rejected; identity by construction.
    assert m["requests"] == {"total": 3, "ok": 0, "rejected": 3,
                             "errors": 0}
    assert elapsed >= 0.4
    for r in m["per_request"]:
        assert r["status"] == 503
        assert [a["status"] for a in r["attempts"]] == [503, 503]
    artifact = {"schema": "pvraft_serve_load/v1", "config": {},
                "compile": [], **m}
    assert validate_load_artifact(artifact) == []
    # The validator rejects a forged attempts trail.
    artifact["per_request"][0]["attempts"][-1]["status"] = 200
    assert validate_load_artifact(artifact)
