"""Fused Pallas corr-lookup vs the XLA reference path (interpret on CPU)."""

import numpy as np
import jax
import pytest

pytestmark = pytest.mark.slow  # interpret-mode Pallas on CPU (~2 min); compiled numerics certified on TPU by scripts/tpu_consistency.py
import jax.numpy as jnp

from pvraft_tpu.ops.corr import CorrState, knn_lookup
from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup
from pvraft_tpu.ops.voxel import voxel_bin_means


def _inputs(seed, b=2, n=16, k=24):
    rng = np.random.default_rng(seed)
    corr = rng.normal(size=(b, n, k)).astype(np.float32)
    xyz = rng.uniform(-1.5, 1.5, size=(b, n, k, 3)).astype(np.float32)
    coords = rng.uniform(-1, 1, size=(b, n, 3)).astype(np.float32)
    return jnp.asarray(corr), jnp.asarray(xyz), jnp.asarray(coords)


def test_fused_matches_reference_paths():
    corr, xyz, coords = _inputs(0)
    vox, kcorr, krel = fused_corr_lookup(corr, xyz, coords, 3, 0.25, 3, 8)

    rel = xyz - coords[:, :, None, :]
    vox_ref = voxel_bin_means(corr, rel, 3, 0.25, 3)
    kcorr_ref, krel_ref = knn_lookup(CorrState(corr, xyz), rel, 8)

    np.testing.assert_allclose(np.asarray(vox), np.asarray(vox_ref), atol=1e-5)
    # kNN selection order: both ascending-distance; values must match.
    np.testing.assert_allclose(np.asarray(kcorr), np.asarray(kcorr_ref), atol=1e-5)
    np.testing.assert_allclose(np.asarray(krel), np.asarray(krel_ref), atol=1e-5)


def test_fused_gradients_match_reference():
    corr, xyz, coords = _inputs(1)

    def f_fused(c):
        vox, kcorr, krel = fused_corr_lookup(c, xyz, coords, 2, 0.3, 3, 6)
        return jnp.sum(vox**2) + jnp.sum(jnp.sin(kcorr))

    def f_ref(c):
        rel = xyz - coords[:, :, None, :]
        vox = voxel_bin_means(c, rel, 2, 0.3, 3)
        kcorr, _ = knn_lookup(CorrState(c, xyz), rel, 6)
        return jnp.sum(vox**2) + jnp.sum(jnp.sin(kcorr))

    g1 = np.asarray(jax.grad(f_fused)(corr))
    g2 = np.asarray(jax.grad(f_ref)(corr))
    np.testing.assert_allclose(g1, g2, atol=1e-4)


def test_fused_no_grad_to_geometry():
    corr, xyz, coords = _inputs(2)

    def f(x, c):
        vox, kcorr, _ = fused_corr_lookup(corr, x, c, 2, 0.25, 3, 4)
        return jnp.sum(vox) + jnp.sum(kcorr)

    gx, gc = jax.grad(f, argnums=(0, 1))(xyz, coords)
    np.testing.assert_array_equal(np.asarray(gx), 0.0)
    np.testing.assert_array_equal(np.asarray(gc), 0.0)


def test_model_with_fused_kernel():
    from pvraft_tpu.config import ModelConfig
    from pvraft_tpu.models.raft import PVRaft

    rng = np.random.default_rng(3)
    xyz1 = jnp.asarray(rng.uniform(-1, 1, (1, 32, 3)).astype(np.float32))
    xyz2 = jnp.asarray(rng.uniform(-1, 1, (1, 32, 3)).astype(np.float32))
    cfg = ModelConfig(truncate_k=8, corr_knn=4, graph_k=4)
    cfgp = ModelConfig(truncate_k=8, corr_knn=4, graph_k=4, use_pallas=True)
    params = PVRaft(cfg).init(jax.random.key(0), xyz1, xyz2, 2)
    f_ref, _ = PVRaft(cfg).apply(params, xyz1, xyz2, num_iters=2)
    f_pal, _ = PVRaft(cfgp).apply(params, xyz1, xyz2, num_iters=2)
    np.testing.assert_allclose(np.asarray(f_ref), np.asarray(f_pal), atol=1e-4)

    # And the training gradient path.
    def loss(p, model):
        flows, _ = model.apply(p, xyz1, xyz2, num_iters=2)
        return jnp.mean(flows**2)

    g_ref = jax.grad(loss)(params, PVRaft(cfg))
    g_pal = jax.grad(loss)(params, PVRaft(cfgp))
    for a, b in zip(
        jax.tree_util.tree_leaves(g_ref), jax.tree_util.tree_leaves(g_pal)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)
