"""Lock-stepped training-trajectory parity vs the torch reference (slow).

The artifact run (``scripts/trajectory_parity.py``, 100 coupled steps +
a 60-step refine leg, ``artifacts/trajectory_parity.json``) is the
evidence of record; this test keeps a shortened 25-step version of the
same claim green in CI: identical imported weights + identical batch
stream -> per-step losses track, EPE descends the same, and the final
parameter gap stays far below the training motion.

Why a trajectory and not just one step (test_grad_parity.py): a
subtly-wrong optimizer accumulator or a stop_gradient asymmetry can pass
single-step bounds and still compound — this is the test that bounds the
compounding.
"""

import os

import pytest

REF_ROOT = "/root/reference"

pytestmark = [
    pytest.mark.skipif(
        not os.path.isdir(os.path.join(REF_ROOT, "model")),
        reason="reference checkout not available",
    ),
    pytest.mark.slow,
]


def test_training_trajectories_match_reference():
    from scripts.trajectory_parity import run

    # 25 steps: the chaotic-divergence envelope scales with steps, so the
    # full-run gates (calibrated at 100 steps) hold with extra margin.
    rec = run(seed=11, n=192, iters=3, truncate_k=64, steps=25)
    assert rec["ok"], {k: v for k, v in rec["checks"].items() if not v}
    assert rec["both_descend"]
    # The functional claim, asserted directly as well as via rec["ok"]:
    assert rec["loss"]["rel_delta_max"] <= 0.10, rec["loss"]
    assert rec["epe"]["abs_delta_max"] <= 0.03, rec["epe"]


def test_refine_trajectory_matches_reference():
    from scripts.trajectory_parity import run

    rec = run(seed=11, n=192, iters=3, truncate_k=64, steps=15, refine=True)
    assert rec["ok"], {k: v for k, v in rec["checks"].items() if not v}
    assert rec["loss"]["rel_delta_max"] <= 0.10, rec["loss"]
