"""Test environment: force an 8-device virtual CPU mesh before jax imports.

This is the JAX-idiomatic fake backend for exercising sharding/collectives
without TPU hardware (SURVEY.md §4). Benchmarks (bench.py) run on the real
chip instead.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# In this image jax is pre-imported at interpreter startup, so the platform
# env var is captured before conftest runs — override through the config API
# (this must happen before any backend is initialized, i.e. before tests run).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def tiny_trainer_cfg(tmp_path, refine=False, epochs=1):
    """Shared tiny synthetic Trainer config (4-sample dataset, 64 points)."""
    from pvraft_tpu.config import Config, DataConfig, ModelConfig, TrainConfig

    return Config(
        model=ModelConfig(truncate_k=16, corr_knn=8, graph_k=8),
        data=DataConfig(dataset="synthetic", max_points=64, synthetic_size=4,
                        num_workers=0),
        train=TrainConfig(batch_size=2, num_epochs=epochs, iters=2,
                          eval_iters=2, refine=refine, checkpoint_interval=1),
        exp_path=str(tmp_path / "exp"),
    )
