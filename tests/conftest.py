"""Test environment: force an 8-device virtual CPU mesh before jax imports.

This is the JAX-idiomatic fake backend for exercising sharding/collectives
without TPU hardware (SURVEY.md §4). Benchmarks (bench.py) run on the real
chip instead.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# In this image jax is pre-imported at interpreter startup, so the platform
# env var is captured before conftest runs — override through the config API
# (this must happen before any backend is initialized, i.e. before tests run).
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
