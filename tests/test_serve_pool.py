"""Replica-pool serving gate (ISSUE 9): multi-device data parallelism,
continuous batching, the bf16-by-default accuracy bound, per-replica
observability, and the bucket advisor.

Multi-replica tests rely on the virtual CPU device pool pinned by
``tests/conftest.py`` (``--xla_force_host_platform_device_count=8`` set
BEFORE the backend initializes) — the first test asserts that pin so a
conftest regression fails loudly here instead of silently collapsing
every pool test to one device.

The real-engine fixture compiles 2 tiny programs x 2 replicas once per
module (replica > 0 compiles hit the in-process executable cache);
deterministic concurrency properties (work-stealing, no head-of-line
blocking, live in-flight accounting) use gated fake replicas — real
thread interleavings, no XLA in the loop.
"""

import json
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.models import PVRaft
from pvraft_tpu.programs import geometries as g
from pvraft_tpu.serve import (
    BatcherConfig,
    InferenceEngine,
    MicroBatcher,
    ServeConfig,
    ServeHTTPServer,
    ServeMetrics,
    ServeTelemetry,
)

TINY_MODEL = ModelConfig(truncate_k=16, corr_knn=8, graph_k=4)
POOL_SERVE = ServeConfig(model=TINY_MODEL, buckets=(32,),
                         batch_sizes=(1, 2), num_iters=2,
                         dtype="float32", replicas=2)
ITERS = POOL_SERVE.num_iters


def _cloud(rng, n):
    return rng.uniform(-1, 1, (n, 3)).astype(np.float32)


@pytest.fixture(scope="module")
def pool():
    """(engine, params): one 2-replica fp32 engine for the module."""
    rng = np.random.default_rng(0)
    model = PVRaft(TINY_MODEL)
    pc = jnp.asarray(_cloud(rng, 24)[None])
    params = model.init(jax.random.key(0), pc, pc, ITERS)
    engine = InferenceEngine(params, POOL_SERVE)
    return engine, params


def test_forced_device_count_pin():
    """The multi-replica tests need >= 2 devices; conftest.py pins the
    virtual CPU pool (XLA_FLAGS, before backend init). If this fails,
    every pool test below is running degenerate — fix conftest first."""
    import os

    assert "xla_force_host_platform_device_count" in \
        os.environ.get("XLA_FLAGS", "")
    assert jax.device_count() >= 2


# ------------------------------------------------------------- replicas --


def test_replica_pool_devices_and_parity(pool):
    """Each replica executes on its own device and produces bit-identical
    flows (same program, same params, different device)."""
    engine, _ = pool
    assert len(engine.replicas) == 2
    ids = [r.device_id for r in engine.replicas]
    assert len(set(ids)) == 2
    rng = np.random.default_rng(1)
    req = (_cloud(rng, 20), _cloud(rng, 20))
    flows = [r.predict_batch([req], 32)[0] for r in engine.replicas]
    np.testing.assert_array_equal(flows[0], flows[1])
    assert flows[0].shape == (20, 3)


def test_replicas_exceeding_devices_rejected():
    with pytest.raises(ValueError):
        # jax.device_count() is 8 under conftest; 99 can never fit.
        cfg = ServeConfig(model=TINY_MODEL, buckets=(32,),
                          batch_sizes=(1,), num_iters=2,
                          dtype="float32", replicas=99)
        InferenceEngine({"params": {}}, cfg)


def test_pool_batcher_serves_exactly(pool):
    """Concurrent requests through the pool batcher come back as the
    exact single-path flows, and the per-replica served-batch counters
    account for every dispatch."""
    engine, _ = pool
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=5, queue_depth=32),
        metrics=metrics)
    rng = np.random.default_rng(2)
    reqs = [(_cloud(rng, 16 + i), _cloud(rng, 16 + i)) for i in range(8)]
    want = [engine.predict(pc1, pc2) for pc1, pc2 in reqs]
    handles = [None] * len(reqs)

    def client(i):
        handles[i] = batcher.submit(*reqs[i])

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(reqs))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, h in enumerate(handles):
        np.testing.assert_array_equal(h.wait(60), want[i])
    batcher.shutdown(drain=True)
    stats = batcher.replica_stats()
    assert [s["replica"] for s in stats] == [0, 1]
    assert len({s["device_id"] for s in stats}) == 2
    assert sum(s["batches_total"] for s in stats) == len(reqs)
    assert all(s["in_flight"] == 0 for s in stats)
    assert metrics.in_flight == 0
    snap = metrics.snapshot()
    assert snap["requests_total"] == len(reqs)
    assert snap["responses_total"] + sum(snap["rejected"].values()) \
        == snap["requests_total"]


# -------------------------------------------------- bf16 accuracy bound --


def test_bf16_default_within_pinned_accuracy_bound(pool):
    """The bf16-by-default serving dtype is held to a pinned EPE-style
    bound vs fp32 on the SAME params — the gate the default rides on
    (geometries.SERVE_BF16_EPE_BOUND). Measured on this geometry: mean
    EPE ~0.03 at mean flow magnitude ~0.7 (relative ~0.05); the pins
    leave ~3x headroom for toolchain drift while a real precision
    regression (a lost mantissa bit ~= 2x) still fails."""
    engine, params = pool
    bf16 = InferenceEngine(params, ServeConfig(
        model=TINY_MODEL, buckets=(32,), batch_sizes=(1,),
        num_iters=ITERS, dtype="bfloat16", replicas=1))
    assert bf16.cfg.dtype == "bfloat16"
    rng = np.random.default_rng(3)
    epe, mag = [], []
    for n in (18, 24, 32):
        pc1, pc2 = _cloud(rng, n), _cloud(rng, n)
        f32 = engine.predict(pc1, pc2)
        f16 = bf16.predict(pc1, pc2)
        assert f16.dtype == np.float32        # output stays f32
        epe.append(np.linalg.norm(f16 - f32, axis=1).mean())
        mag.append(np.linalg.norm(f32, axis=1).mean())
    mean_epe = float(np.mean(epe))
    rel = mean_epe / float(np.mean(mag))
    assert mean_epe <= g.SERVE_BF16_EPE_BOUND, (mean_epe, epe)
    assert rel <= g.SERVE_BF16_REL_EPE_BOUND, (rel, mean_epe, mag)


def test_bf16_program_names_are_dtype_qualified(pool):
    _, params = pool
    bf16 = InferenceEngine(params, ServeConfig(
        model=TINY_MODEL, buckets=(32,), batch_sizes=(1,),
        num_iters=ITERS, dtype="bfloat16", replicas=1))
    assert [r["name"] for r in bf16.compile_report()] == \
        ["predict_bf16_b32_bs1"]


# ------------------------------------- fake pool (deterministic threads) --


class _GateReplica:
    """Fake single-device executor: instant flows, per-bucket gates so a
    test can hold a chosen bucket's batch in flight deterministically."""

    def __init__(self, engine, index):
        self.engine = engine
        self.index = index
        self.device_id = index
        self.started = {b: threading.Event() for b in engine.cfg.buckets}

    def predict_batch(self, requests, bucket):
        self.started[bucket].set()
        self.engine.gates[bucket].wait(30)
        return [np.asarray(pc2[: pc1.shape[0]] - pc1, np.float32)
                for pc1, pc2 in requests]


class _PoolFakeEngine:
    """Pool-shaped engine double: real routing, gated fake replicas."""

    def __init__(self, buckets=(32, 64), batch_sizes=(1, 2), n_replicas=2):
        self.cfg = SimpleNamespace(
            buckets=buckets, batch_sizes=batch_sizes, min_points=4,
            coord_limit=100.0, dtype="float32")
        self.gates = {b: threading.Event() for b in buckets}
        for gate in self.gates.values():
            gate.set()
        self.replicas = [_GateReplica(self, i) for i in range(n_replicas)]

    def validate_request(self, pc1, pc2):
        from pvraft_tpu.serve.engine import RequestError

        n = max(pc1.shape[0], pc2.shape[0])
        for b in self.cfg.buckets:
            if n <= b:
                return b
        raise RequestError("too_large", "too large")

    def batch_size_for(self, n):
        for bs in self.cfg.batch_sizes:
            if n <= bs:
                return bs
        return self.cfg.batch_sizes[-1]

    def compile_report(self):
        return []

    def weights_info(self):
        return {"path": "", "digest": "fake", "epoch": -1, "swaps": 0}


def _pc(n, seed=0):
    return np.random.default_rng(seed).uniform(
        -1, 1, (n, 3)).astype(np.float32)


def _poll(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


def test_no_head_of_line_blocking():
    """ISSUE 9 satellite: a deliberately slow large-bucket batch in
    flight must not stall small-bucket requests — they keep completing
    through the other replica under a latency bound."""
    engine = _PoolFakeEngine(n_replicas=2)
    engine.gates[64].clear()               # large bucket: blocked
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=2, queue_depth=32))
    try:
        slow = batcher.submit(_pc(60), _pc(60))
        # Wait until some replica is actually inside the slow dispatch.
        assert _poll(lambda: any(r.started[64].is_set()
                                 for r in engine.replicas))
        t0 = time.monotonic()
        for seed in range(5):
            h = batcher.submit(_pc(20, seed), _pc(20, seed))
            assert h.wait(5).shape == (20, 3)
        elapsed = time.monotonic() - t0
        # 5 sequential instant dispatches through the free replica:
        # generous bound, but orders of magnitude under the 30 s the
        # blocked replica would impose if small requests queued behind it.
        assert elapsed < 2.0, elapsed
        assert not slow.done.is_set()      # the slow batch is STILL going
        stats = batcher.replica_stats()
        assert sum(s["in_flight"] for s in stats) == 1   # the slow one
    finally:
        engine.gates[64].set()
    assert slow.wait(30).shape == (60, 3)
    batcher.shutdown(drain=True)
    assert batcher.counts["served"] == 6


def test_eager_dispatch_vs_baseline_straggler_wait():
    """Continuous batching: with idle capacity a lone request dispatches
    immediately; the PR-7 baseline mode waits out the full straggler
    window first. The latency gap IS the A/B mechanism (BENCHMARKS.md)."""
    for eager, bound in ((True, lambda ms: ms < 150.0),
                         (False, lambda ms: ms >= 250.0)):
        engine = _PoolFakeEngine(n_replicas=1)
        batcher = MicroBatcher(
            engine, BatcherConfig(max_batch=2, max_wait_ms=300,
                                  queue_depth=8, eager_when_idle=eager))
        t0 = time.monotonic()
        h = batcher.submit(_pc(20), _pc(20))
        h.wait(10)
        ms = (time.monotonic() - t0) * 1000.0
        batcher.shutdown(drain=True)
        assert bound(ms), (eager, ms)


def _identity_holds(text):
    """requests == responses + Σrejected + in_flight, all parsed from
    ONE atomic exposition render."""
    import re

    def one(name):
        m = re.search(rf"^{name} (\S+)$", text, re.M)
        return float(m.group(1)) if m else 0.0

    rejected = sum(float(v) for v in re.findall(
        r'^pvraft_serve_rejected_total\{[^}]*\} (\S+)$', text, re.M))
    return one("pvraft_serve_requests_total") == (
        one("pvraft_serve_responses_total") + rejected
        + one("pvraft_serve_in_flight"))


def test_live_in_flight_reconciliation_and_prometheus():
    """While a request is mid-execute the /metrics identity holds with
    the live gauge: requests_total == responses_total + rejected +
    in_flight — and Prometheus exposes the per-replica decomposition.
    The cost plane is ARMED (ISSUE 14): the identity must hold on a
    render that ALSO carries the predicted/busy/utilization series."""
    from pvraft_tpu.programs.costs import CostSurface
    from pvraft_tpu.serve.costing import ServeCostModel

    engine = _PoolFakeEngine(n_replicas=2)
    engine.gates[32].clear()
    metrics = ServeMetrics(engine.cfg.buckets)
    surface = CostSurface({
        "schema": "pvraft_costs/v1",
        "programs": [
            {"name": f"serve_predict_fp32_b{b}_bs{bs}",
             "target": "v5e:2x2x1", "ok": True, "flops": 1e9 * b * bs,
             "bytes_accessed": 1e9, "optimal_seconds": 1e-4 * b * bs,
             "memory": {"live_bytes_estimate": 1}}
            for b in (32, 64) for bs in (1, 2)]})
    costing = ServeCostModel(surface, buckets=engine.cfg.buckets,
                             batch_sizes=engine.cfg.batch_sizes,
                             dtype="float32", platform="cpu",
                             metrics=metrics)
    metrics.arm_cost()
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        metrics=metrics, costing=costing)
    h = batcher.submit(_pc(20), _pc(20))
    assert _poll(lambda: any(r.started[32].is_set()
                             for r in engine.replicas))
    snap = metrics.snapshot()
    assert snap["requests_total"] == 1
    assert snap["responses_total"] == 0
    assert metrics.in_flight == 1
    text = metrics.prometheus(
        batcher.queue_depths(),
        replica_stats=batcher.replica_stats(),
        batch_queue_depth=batcher.batch_queue_depth())
    assert "pvraft_serve_in_flight 1" in text
    assert "pvraft_serve_replica_in_flight" in text
    assert "pvraft_serve_replica_batches_total" in text
    assert "pvraft_serve_batch_queue_depth" in text
    assert "pvraft_serve_predicted_device_seconds_total" in text
    assert _identity_holds(text)
    stats = batcher.replica_stats()
    assert sum(s["in_flight"] for s in stats) == 1
    engine.gates[32].set()
    h.wait(10)
    batcher.shutdown(drain=True)
    assert metrics.in_flight == 0
    text = metrics.prometheus(replica_stats=batcher.replica_stats())
    assert "pvraft_serve_in_flight 0" in text
    # Quiescent render: the priced dispatch landed on every cost
    # series, and the identity still holds on the same render.
    assert "pvraft_serve_device_busy_seconds_total{replica=" in text
    assert "pvraft_serve_replica_utilization{replica=" in text
    assert ('pvraft_serve_cost_calibration_ratio{batch="1",bucket="32",'
            'dtype="float32"}') in text
    assert _identity_holds(text)
    # The /healthz cost block tells the same story.
    cost = metrics.cost_snapshot()
    assert cost["calibration"][0]["n"] == 1
    assert cost["calibration"][0]["comparable"] is False  # CPU platform
    assert cost["predicted_device_seconds_total"] > 0


def test_outcome_recorded_exactly_once_under_timeout_race():
    """The 504-vs-resolve race cannot double-book the ledger: whoever
    wins the request's finalize() token records the outcome, the loser
    records nothing — so in_flight returns to exactly 0 instead of
    drifting negative (the every-snapshot identity's regression test)."""
    engine = _PoolFakeEngine(n_replicas=1)
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=1, max_wait_ms=0, queue_depth=8),
        metrics=metrics)
    # Case 1: dispatch wins (request served), then the handler-side
    # failure path fires anyway (simulating a waiter that timed out in
    # the instant the result landed): it must be a no-op.
    h = batcher.submit(_pc(20), _pc(20))
    h.wait(10)
    assert metrics.in_flight == 0
    batcher.record_failure_for(h, "timeout")
    snap = metrics.snapshot()
    assert snap["rejected"] == {}              # loser recorded nothing
    assert metrics.in_flight == 0
    assert snap["requests_total"] == snap["responses_total"] == 1
    # Case 2: the failure path wins (waiter gone before dispatch): the
    # request is counted once, as a timeout.
    engine.gates[32].clear()
    h2 = batcher.submit(_pc(20, 1), _pc(20, 1))
    with pytest.raises(TimeoutError):
        h2.wait(0.05)
    batcher.record_failure_for(h2, "timeout")
    engine.gates[32].set()
    batcher.shutdown(drain=True)
    snap = metrics.snapshot()
    assert snap["rejected"] == {"timeout": 1}
    assert metrics.in_flight == 0
    assert snap["requests_total"] == snap["responses_total"] + \
        sum(snap["rejected"].values())


def test_healthz_reports_replicas(tmp_path):
    """/healthz per-replica visibility (ISSUE 9 satellite): device id,
    in-flight, served-batch counter per replica, plus the serving dtype
    — while the JSON /metrics shape stays frozen."""
    import http.client

    engine = _PoolFakeEngine(n_replicas=2)
    telemetry = ServeTelemetry(str(tmp_path / "serve.events.jsonl"))
    metrics = ServeMetrics(engine.cfg.buckets)
    batcher = MicroBatcher(
        engine, BatcherConfig(max_batch=2, max_wait_ms=2, queue_depth=8),
        telemetry=telemetry, metrics=metrics)
    server = ServeHTTPServer(batcher, port=0, metrics=metrics)
    server.start()
    try:
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=30)
        conn.request("POST", "/predict", body=json.dumps(
            {"pc1": _pc(20).tolist(), "pc2": _pc(20, 1).tolist()}),
            headers={"Content-Type": "application/json"})
        assert conn.getresponse().status == 200
        conn.close()
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=30)
        conn.request("GET", "/healthz")
        health = json.loads(conn.getresponse().read())
        conn.close()
        assert health["dtype"] == "float32"
        assert health["in_flight"] == 0
        assert [r["replica"] for r in health["replicas"]] == [0, 1]
        assert all(set(r) == {"replica", "device_id", "in_flight",
                              "batches_total"}
                   for r in health["replicas"])
        assert sum(r["batches_total"] for r in health["replicas"]) == 1
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=30)
        conn.request("GET", "/metrics")
        snap = json.loads(conn.getresponse().read())
        conn.close()
        assert set(snap) == {
            "requests_total", "responses_total", "rejected",
            "batches_total", "batch_fill_mean", "per_bucket_requests",
            "latency", "queue_depth"}          # frozen pre-pool shape
    finally:
        server.shutdown(drain=True)
        telemetry.close()


# -------------------------------------------------------- bucket advisor --


def test_advisor_partition_dp_exact():
    from pvraft_tpu.serve.advisor import propose_buckets, score_buckets

    # 3 bins; with 2 buckets the DP must merge the two cheap-to-merge
    # small bins, not the expensive large one: candidates (128, 256,
    # 8192), counts (100, 100, 10). Merging 128->256 costs 100*128
    # extra; merging 256->8192 costs 100*7936. Optimal: [256, 8192].
    edges = [128.0, 256.0, 8192.0]
    counts = [100, 100, 10, 0]
    out = propose_buckets(edges, counts, 2)
    assert out["buckets"] == [256, 8192]
    assert out["requests"] == 210
    assert out["overflow_requests"] == 0
    expect = (200 * 256 + 10 * 8192) / 210
    assert out["points_per_request"] == pytest.approx(expect, abs=0.01)
    # One bucket: everything pads to the max.
    assert propose_buckets(edges, counts, 1)["buckets"] == [8192]
    # min_bucket floor folds small bins upward.
    assert propose_buckets(edges, counts, 2, min_bucket=200)["buckets"] \
        == [256, 8192]
    # Scoring an existing table reports rejection honestly.
    score = score_buckets([128], edges, counts)
    assert score["rejected_requests"] == 110
    assert score["served_requests"] == 100
    assert score["points_per_request"] == 128.0


def test_advisor_improvement_compares_same_population():
    """A strictly-more-capable proposal must not read as a regression:
    when the current table rejects part of the traffic, the improvement
    is computed on the traffic the CURRENT table serves (the extra
    capability shows up as the reject-fraction delta, not as cost)."""
    from pvraft_tpu.serve.advisor import build_advisor_report

    edges = [1024.0, 8192.0]
    counts = [100, 100, 0]
    report = build_advisor_report(edges, counts, current_buckets=[1024],
                                  n_buckets=2)
    # Proposed [1024, 8192] serves everything; on the shared population
    # (the <=1024 bin) it costs exactly what the current table costs.
    assert report["proposed"]["buckets"] == [1024, 8192]
    assert report["current"]["rejected_requests"] == 100
    assert report["improvement"]["points_per_request_saved"] == 0.0
    assert report["improvement"]["population"] == \
        "traffic served by the current table"


def test_advisor_report_on_committed_histogram():
    """The committed loadgen histogram (PR 7's adaptive-bucket seed
    data) produces a valid advisory whose proposal is no worse than the
    declared production table on the same traffic — the cross-check
    against geometries.py the ISSUE names."""
    import os

    from pvraft_tpu.serve.advisor import build_advisor_report

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = os.path.join(root, "artifacts", "serve_cpu_synthetic.json")
    doc = json.load(open(art, encoding="utf-8"))
    rp = doc["request_points"]
    report = build_advisor_report(rp["edges"], rp["counts"],
                                  g.SERVE_DEFAULT_BUCKETS, source=art)
    assert report["schema"] == "pvraft_bucket_advisor/v1"
    assert len(report["proposed"]["buckets"]) <= len(g.SERVE_DEFAULT_BUCKETS)
    assert report["current"]["buckets"] == sorted(g.SERVE_DEFAULT_BUCKETS)
    if report["current"]["points_per_request"] is not None:
        assert report["proposed"]["points_per_request"] <= \
            report["current"]["points_per_request"]


# ------------------------------------------------- committed A/B evidence --


def test_committed_ab_evidence():
    """The committed interleaved A/B (ISSUE 9 acceptance): both legs
    validate, the joint SLO report validates, the pool leg raises max
    QPS under the p99 SLO vs the baseline leg, and every leg's server
    metrics reconcile (requests == responses + rejected at quiescence)."""
    import os

    from pvraft_tpu.obs.events import validate_events_file
    from pvraft_tpu.obs.slo import validate_slo_report_file
    from pvraft_tpu.obs.trace import validate_trace_artifact_file
    from pvraft_tpu.serve.loadgen import validate_load_artifact_file

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prefix = os.path.join(root, "artifacts", "serve_ab")
    legs = {}
    for leg in ("baseline", "pool"):
        load = f"{prefix}_{leg}.json"
        assert validate_load_artifact_file(load) == []
        assert validate_events_file(f"{prefix}_{leg}.events.jsonl") == []
        assert validate_trace_artifact_file(
            f"{prefix}_{leg}.trace.json") == []
        doc = json.load(open(load, encoding="utf-8"))
        legs[leg] = doc
        sm = doc["server_metrics"]
        assert sm["requests_total"] == sm["responses_total"] + \
            sum(sm["rejected"].values())
    assert legs["baseline"]["config"]["replicas"] == 1
    assert legs["baseline"]["config"]["eager_when_idle"] is False
    assert legs["pool"]["config"]["replicas"] >= 2
    assert legs["pool"]["config"]["eager_when_idle"] is True

    slo = f"{prefix}.slo.json"
    assert validate_slo_report_file(slo) == []
    report = json.load(open(slo, encoding="utf-8"))
    rps = {}
    for run in report["runs"]:
        leg = "pool" if "pool" in run["load"] else "baseline"
        rps[leg] = (run["throughput_rps"], run["meets_slo"])
    # The tentpole claim: the pool sustains more QPS under the SLO.
    assert rps["pool"][1], "pool leg must meet the SLO"
    assert rps["baseline"][1], "baseline leg must meet the SLO"
    assert rps["pool"][0] > rps["baseline"][0]
    assert report["max_qps_under_slo"] == pytest.approx(rps["pool"][0])
