"""Orbax checkpoint backend: directory checkpoints with async persistence
(SURVEY.md §5: "orbax checkpointing with save-interval + auto-resume").
The msgpack backend keeps its own roundtrip test in test_engine.py; here we
certify the orbax path and that loads auto-detect the backend from the path.
"""

import dataclasses
import os

import pytest

import jax
import numpy as np
import optax

from pvraft_tpu.engine.checkpoint import (
    find_checkpoint,
    latest_checkpoint,
    load_checkpoint,
    save_checkpoint,
    wait_for_saves,
)
from pvraft_tpu.parallel.mesh import make_mesh


def test_orbax_roundtrip(tmp_path):
    params = {"a": np.arange(6, dtype=np.float32).reshape(2, 3),
              "b": {"c": np.ones(4, np.float32)}}
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    save_checkpoint(str(tmp_path), params, opt_state, epoch=4,
                    checkpoint_interval=5, best=True, backend="orbax")
    wait_for_saves()
    for name in ("last_checkpoint.orbax", "004.orbax", "best_checkpoint.orbax"):
        assert os.path.isdir(tmp_path / name), name

    # load_checkpoint detects the orbax backend from the directory path.
    tmpl = jax.tree_util.tree_map(np.zeros_like, params)
    p2, o2, epoch = load_checkpoint(
        str(tmp_path / "last_checkpoint.orbax"), tmpl, tx.init(tmpl)
    )
    assert epoch == 4
    np.testing.assert_array_equal(p2["a"], params["a"])
    np.testing.assert_array_equal(p2["b"]["c"], params["b"]["c"])
    for a, b in zip(jax.tree_util.tree_leaves(o2),
                    jax.tree_util.tree_leaves(opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # The name-class resolvers see orbax checkpoints too.
    assert latest_checkpoint(str(tmp_path)).endswith("last_checkpoint.orbax")
    assert find_checkpoint(str(tmp_path), "best_checkpoint").endswith(".orbax")


def test_orbax_overwrites_last(tmp_path):
    params = {"w": np.zeros(3, np.float32)}
    tx = optax.sgd(1e-2)
    for epoch in (0, 1):
        save_checkpoint(str(tmp_path), {"w": np.full(3, float(epoch))},
                        tx.init(params), epoch=epoch, checkpoint_interval=0,
                        backend="orbax")
    p, _, epoch = load_checkpoint(
        str(tmp_path / "last_checkpoint.orbax"),
        jax.tree_util.tree_map(np.zeros_like, params),
    )
    assert epoch == 1
    np.testing.assert_array_equal(p["w"], np.full(3, 1.0))


def test_unknown_backend_rejected(tmp_path):
    import pytest

    with pytest.raises(ValueError, match="backend"):
        save_checkpoint(str(tmp_path), {"w": np.zeros(1)}, None, epoch=0,
                        backend="pickle")

    # Config-level validation fails before any training happens.
    from pvraft_tpu.config import TrainConfig

    with pytest.raises(ValueError, match="ckpt_backend"):
        TrainConfig(ckpt_backend="msgpck")


def test_orbax_no_tmp_left_behind(tmp_path):
    """The overwrite path goes tmp-dir -> committed rename: after
    wait_for_saves the final name exists and no .tmp remains (the crash
    window of an in-place force-overwrite is what this guards against)."""
    params = {"w": np.zeros(3, np.float32)}
    tx = optax.sgd(1e-2)
    for epoch in (0, 1):
        save_checkpoint(str(tmp_path), params, tx.init(params), epoch=epoch,
                        checkpoint_interval=0, backend="orbax")
    wait_for_saves()
    names = set(os.listdir(tmp_path))
    assert "last_checkpoint.orbax" in names
    assert not any(n.endswith(".tmp") for n in names), names


def test_orbax_recovers_committed_tmp(tmp_path):
    """A run that dies after the async write commits but before the
    deferred promote leaves last_checkpoint.orbax.tmp; the next process
    must adopt it instead of resuming from the older epoch."""
    import pvraft_tpu.engine.checkpoint as ck

    params = {"w": np.zeros(2, np.float32)}
    tx = optax.sgd(1e-2)
    # Epoch 0: fully promoted.
    save_checkpoint(str(tmp_path), {"w": np.zeros(2, np.float32)},
                    tx.init(params), epoch=0, checkpoint_interval=0,
                    backend="orbax")
    wait_for_saves()
    # Epoch 1: committed by the writer but never promoted (process died).
    save_checkpoint(str(tmp_path), {"w": np.ones(2, np.float32)},
                    tx.init(params), epoch=1, checkpoint_interval=0,
                    backend="orbax")
    ck._orbax().wait_until_finished()
    ck._orbax_pending.clear()  # simulate death before promote
    assert os.path.isdir(tmp_path / "last_checkpoint.orbax.tmp")

    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found.endswith("last_checkpoint.orbax")
    p, _, epoch = load_checkpoint(
        found, jax.tree_util.tree_map(np.zeros_like, params))
    assert epoch == 1  # the committed-but-unpromoted epoch was adopted
    np.testing.assert_array_equal(p["w"], np.ones(2))
    assert not os.path.exists(tmp_path / "last_checkpoint.orbax.tmp")


def test_load_payload_both_backends(tmp_path):
    from pvraft_tpu.engine.checkpoint import load_payload

    params = {"w": np.arange(3, dtype=np.float32)}
    tx = optax.sgd(1e-2)
    for backend, name in [("msgpack", "last_checkpoint.msgpack"),
                          ("orbax", "last_checkpoint.orbax")]:
        d = tmp_path / backend
        save_checkpoint(str(d), params, tx.init(params), epoch=7,
                        checkpoint_interval=0, backend=backend)
        payload = load_payload(str(d / name))
        assert int(payload["epoch"]) == 7
        np.testing.assert_array_equal(payload["params"]["w"], params["w"])


@pytest.mark.slow
def test_trainer_orbax_backend(tmp_path):
    """Trainer trains, checkpoints, and resumes entirely through orbax."""
    from conftest import tiny_trainer_cfg
    from pvraft_tpu.engine.trainer import Trainer

    cfg = tiny_trainer_cfg(tmp_path)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, ckpt_backend="orbax")
    )
    tr = Trainer(cfg, mesh=make_mesh(n_data=1))
    tr.training(0)
    tr.val_test(0, "val")
    wait_for_saves()
    ckpts = set(os.listdir(os.path.join(cfg.exp_path, "checkpoints")))
    assert "last_checkpoint.orbax" in ckpts
    assert "best_checkpoint.orbax" in ckpts
    assert not any(c.endswith(".msgpack") for c in ckpts)

    tr2 = Trainer(cfg, mesh=make_mesh(n_data=1))
    last = latest_checkpoint(os.path.join(cfg.exp_path, "checkpoints"))
    tr2.load_weights(last, resume=True)
    assert tr2.begin_epoch == 1
    for a, b in zip(jax.tree_util.tree_leaves(tr.params),
                    jax.tree_util.tree_leaves(tr2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_orbax_recovery_recreates_extras_from_sidecar(tmp_path):
    """Dying after the async commit but before promote used to lose the
    NNN/best copies (only last_checkpoint was adopted); the extras sidecar
    written at save time lets recovery re-create them."""
    import pvraft_tpu.engine.checkpoint as ck

    params = {"w": np.zeros(2, np.float32)}
    tx = optax.sgd(1e-2)
    save_checkpoint(str(tmp_path), {"w": np.ones(2, np.float32)},
                    tx.init(params), epoch=4, checkpoint_interval=5,
                    best=True, backend="orbax")
    ck._orbax().wait_until_finished()
    ck._orbax_pending.clear()  # simulate death before promote
    assert os.path.isfile(
        tmp_path / "last_checkpoint.orbax.tmp.extras.json")

    found = latest_checkpoint(str(tmp_path))
    assert found.endswith("last_checkpoint.orbax")
    names = set(os.listdir(tmp_path))
    assert "004.orbax" in names and "best_checkpoint.orbax" in names, names
    assert not any(".tmp" in n for n in names), names
    p, _, epoch = load_checkpoint(
        str(tmp_path / "best_checkpoint.orbax"),
        jax.tree_util.tree_map(np.zeros_like, params))
    assert epoch == 4
    np.testing.assert_array_equal(p["w"], np.ones(2))


def test_orbax_recovery_sweeps_stale_old_dir(tmp_path):
    """A crash between _swap_in's final rename and its cleanup leaves a
    stale '<name>.orbax.old'; recovery removes it (dst is newer)."""
    import shutil

    params = {"w": np.zeros(2, np.float32)}
    tx = optax.sgd(1e-2)
    save_checkpoint(str(tmp_path), params, tx.init(params), epoch=0,
                    checkpoint_interval=0, backend="orbax")
    wait_for_saves()
    dst = tmp_path / "last_checkpoint.orbax"
    shutil.copytree(dst, tmp_path / "last_checkpoint.orbax.old")

    assert latest_checkpoint(str(tmp_path)).endswith("last_checkpoint.orbax")
    assert not os.path.exists(tmp_path / "last_checkpoint.orbax.old")


def test_orbax_recovery_adopts_orphaned_old_dir(tmp_path):
    """A crash between the aside-rename and tmp's rename (tmp since
    promoted/gone) can leave only '<name>.orbax.old': it is the sole
    surviving copy and must be adopted, not deleted."""
    params = {"w": np.full(2, 3.0, np.float32)}
    tx = optax.sgd(1e-2)
    save_checkpoint(str(tmp_path), params, tx.init(params), epoch=2,
                    checkpoint_interval=0, backend="orbax")
    wait_for_saves()
    os.replace(tmp_path / "last_checkpoint.orbax",
               tmp_path / "last_checkpoint.orbax.old")

    found = latest_checkpoint(str(tmp_path))
    assert found is not None and found.endswith("last_checkpoint.orbax")
    p, _, epoch = load_checkpoint(
        found, jax.tree_util.tree_map(np.zeros_like, params))
    assert epoch == 2
    np.testing.assert_array_equal(p["w"], np.full(2, 3.0))


def test_orbax_recovery_extras_after_partial_promote(tmp_path):
    """Death MID-promote — tmp already swapped into dst but the extras
    copies not yet made — leaves only the sidecar; recovery must still
    re-create the owed NNN/best from dst (whose epoch matches)."""
    import pvraft_tpu.engine.checkpoint as ck

    params = {"w": np.full(2, 5.0, np.float32)}
    tx = optax.sgd(1e-2)
    save_checkpoint(str(tmp_path), params, tx.init(params), epoch=9,
                    checkpoint_interval=5, best=True, backend="orbax")
    ck._orbax().wait_until_finished()
    ck._orbax_pending.clear()
    # Simulate the promote dying right after the dst swap.
    ck._swap_in(str(tmp_path / "last_checkpoint.orbax.tmp"),
                str(tmp_path / "last_checkpoint.orbax"))
    assert os.path.isfile(tmp_path / "last_checkpoint.orbax.tmp.extras.json")

    latest_checkpoint(str(tmp_path))
    names = set(os.listdir(tmp_path))
    assert "009.orbax" in names and "best_checkpoint.orbax" in names, names
    p, _, epoch = load_checkpoint(
        str(tmp_path / "best_checkpoint.orbax"),
        jax.tree_util.tree_map(np.zeros_like, params))
    assert epoch == 9
    np.testing.assert_array_equal(p["w"], np.full(2, 5.0))


def test_orbax_recovery_ignores_sidecar_for_stale_dst(tmp_path):
    """If the new payload never committed (no tmp) and dst holds an OLDER
    epoch than the sidecar owes, recovery must NOT record the old data
    under the owed NNN/best names."""
    import json

    params = {"w": np.zeros(2, np.float32)}
    tx = optax.sgd(1e-2)
    save_checkpoint(str(tmp_path), params, tx.init(params), epoch=3,
                    checkpoint_interval=0, backend="orbax")
    wait_for_saves()
    # Forge a sidecar owing epoch-7 extras; dst is epoch 3.
    with open(tmp_path / "last_checkpoint.orbax.tmp.extras.json", "w") as f:
        json.dump({"epoch": 7,
                   "extras": [str(tmp_path / "007.orbax")]}, f)

    latest_checkpoint(str(tmp_path))
    names = set(os.listdir(tmp_path))
    assert "007.orbax" not in names, names
    assert not os.path.isfile(tmp_path / "last_checkpoint.orbax.tmp.extras.json")


def test_orbax_half_written_copytmp_never_adopted(tmp_path):
    """A half-written .copytmp (non-atomic copytree) must never be swapped
    in as a checkpoint — only orbax-committed .tmp dirs are complete."""
    params = {"w": np.full(2, 2.0, np.float32)}
    tx = optax.sgd(1e-2)
    save_checkpoint(str(tmp_path), params, tx.init(params), epoch=1,
                    checkpoint_interval=0, best=True, backend="orbax")
    wait_for_saves()
    # Garbage copy-temp next to a good best_checkpoint.
    bad = tmp_path / "best_checkpoint.orbax.copytmp"
    bad.mkdir()
    (bad / "junk").write_text("partial")

    found = find_checkpoint(str(tmp_path), "best_checkpoint")
    p, _, epoch = load_checkpoint(
        found, jax.tree_util.tree_map(np.zeros_like, params))
    assert epoch == 1
    np.testing.assert_array_equal(p["w"], np.full(2, 2.0))
