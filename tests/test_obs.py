"""Run-telemetry subsystem: in-jit monitors, the telemetry-off jaxpr
guarantee, the divergence detector, snapshot round-trips, and the
forced-NaN -> snapshot -> run_doctor pipeline (the acceptance path)."""

import dataclasses
import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from pvraft_tpu.config import ModelConfig
from pvraft_tpu.obs import (
    DivergenceDetector,
    delta_flow_norms,
    dump_snapshot,
    global_norm,
    load_snapshot,
    nonfinite_count,
    telemetry_leaves,
    validate_events_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- monitors ---------------------------------------------------------------


def test_global_norm_matches_reference():
    tree = {"a": jnp.asarray([3.0, 4.0]), "b": {"c": jnp.zeros((2, 2))}}
    assert float(global_norm(tree)) == pytest.approx(5.0)
    assert float(global_norm({})) == 0.0


def test_nonfinite_count_counts_across_trees():
    a = jnp.asarray([1.0, np.nan, np.inf])
    b = {"x": jnp.asarray([[np.nan]]), "i": jnp.asarray([1, 2])}  # ints skip
    assert int(nonfinite_count(a, b)) == 3
    assert int(nonfinite_count(jnp.ones(4))) == 0


def test_delta_flow_norms_first_iter_is_absolute():
    flows = jnp.stack([jnp.full((1, 4, 3), 2.0), jnp.full((1, 4, 3), 5.0)])
    out = np.asarray(delta_flow_norms(flows))
    # iter 0 update = flows[0] - 0; iter 1 update = flows[1] - flows[0].
    np.testing.assert_allclose(out, [2.0, 3.0], rtol=1e-6)


def test_telemetry_leaves_shape_and_groups():
    params = {"params": {"enc": {"w": jnp.ones((3,))},
                         "gru": {"w": jnp.ones((2,))}}}
    grads = jax.tree_util.tree_map(lambda x: x * 0.1, params)
    updates = jax.tree_util.tree_map(lambda x: x * -0.01, params)
    flows = jnp.ones((2, 1, 4, 3))
    out = telemetry_leaves(params, grads, updates, jnp.float32(1.0), flows)
    assert sorted(out) == ["delta_flow_norm", "grad_norm",
                           "grad_norm_by_group", "nonfinite", "param_norm",
                           "update_ratio"]
    assert sorted(out["grad_norm_by_group"]) == ["enc", "gru"]
    assert out["delta_flow_norm"].shape == (2,)
    assert int(out["nonfinite"]) == 0
    ratio = float(out["update_ratio"])
    assert ratio == pytest.approx(0.01, rel=1e-4)


# --- divergence detector ----------------------------------------------------


def test_detector_trips_on_nonfinite():
    det = DivergenceDetector(window=8, zscore=0.0)
    assert det.update(1.0) is None
    trip = det.update(float("nan"))
    assert trip is not None and trip.reason == "nonfinite"
    trip = det.update(2.0, nonfinite=5)  # sentinel outranks a finite loss
    assert trip is not None and trip.reason == "nonfinite"


def test_detector_zscore_trip_and_recovery():
    det = DivergenceDetector(window=16, zscore=4.0, min_steps=4)
    rng = np.random.default_rng(0)
    for _ in range(10):
        assert det.update(1.0 + 0.01 * rng.standard_normal()) is None
    trip = det.update(50.0)
    assert trip is not None and trip.reason == "zscore" and trip.zscore > 4
    # The spike was NOT folded into the window: a healthy loss after it
    # is healthy, and a second identical spike still trips.
    assert det.update(1.0) is None
    assert det.update(50.0) is not None


def test_detector_min_steps_clamped_to_window():
    # A window smaller than the default min_steps must still arm the
    # z-score trigger (the deque can never exceed its maxlen).
    det = DivergenceDetector(window=4, zscore=4.0)
    for _ in range(4):
        assert det.update(1.0) is None
    assert det.update(100.0) is not None


def test_detector_zscore_disabled():
    det = DivergenceDetector(window=8, zscore=0.0)
    for loss in [1.0] * 6 + [1e9]:
        assert det.update(loss) is None  # only the sentinel is armed


# --- snapshots --------------------------------------------------------------


def test_snapshot_roundtrip(tmp_path):
    import optax

    params = {"params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    batch = {"pc1": np.ones((1, 4, 3), np.float32),
             "pc2": np.ones((1, 4, 3), np.float32),
             "flow": np.zeros((1, 4, 3), np.float32),
             "mask": np.ones((1, 4), np.float32)}
    path = dump_snapshot(
        str(tmp_path), batch, params, opt_state,
        step=7, epoch=1, reason="nonfinite", loss=float("nan"),
        cfg=None, extra_meta={"zscore": None},
    )
    assert os.path.basename(path) == "step_0000007"
    meta, batch2, params2, opt2 = load_snapshot(path)
    assert meta["step"] == 7 and meta["reason"] == "nonfinite"
    assert meta["loss"] == "NaN"
    np.testing.assert_array_equal(batch2["pc1"], batch["pc1"])
    np.testing.assert_array_equal(params2["params"]["w"],
                                  params["params"]["w"])
    # The opt_state round-trips through from_state_dict into a freshly
    # built structure (what run_doctor does).
    from flax import serialization

    restored = serialization.from_state_dict(tx.init(params), opt2)
    assert int(restored[0].count) == 0


def test_load_snapshot_rejects_wrong_schema(tmp_path):
    path = dump_snapshot(
        str(tmp_path), {"x": np.zeros(1)}, {"w": np.zeros(1)}, {},
        step=1, epoch=0, reason="zscore", loss=2.0)
    meta_path = os.path.join(path, "meta.json")
    meta = json.load(open(meta_path))
    meta["schema"] = "pvraft_snapshot/v0"
    json.dump(meta, open(meta_path, "w"))
    with pytest.raises(ValueError, match="schema"):
        load_snapshot(path)


# --- the telemetry-off jaxpr guarantee --------------------------------------


def _norm_addrs(s: str) -> str:
    # One shared normalization for every byte-identical-jaxpr golden
    # (also used by the deepcheck GJ007 determinism probe).
    from pvraft_tpu.analysis.jaxpr.rules import normalize_jaxpr_str

    return normalize_jaxpr_str(s)


def test_train_step_telemetry_off_jaxpr_identical():
    """With telemetry off the train-step jaxpr is byte-identical (modulo
    embedded object addresses) to the pre-telemetry step body, replicated
    here verbatim — the same golden the trace audit enforces
    (analysis/audit.py: engine.train_step[telemetry_off_jaxpr])."""
    import optax

    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.engine.metrics import epe_train
    from pvraft_tpu.engine.steps import make_train_step, maybe_cast_grads
    from pvraft_tpu.models import PVRaft

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                      use_pallas=False)
    model = PVRaft(cfg)
    tx = optax.adam(1e-3)
    sds = lambda *s: jax.ShapeDtypeStruct(s, "float32")
    pc1, pc2, mask, gt = sds(1, 32, 3), sds(1, 32, 3), sds(1, 32), sds(1, 32, 3)
    params = jax.eval_shape(
        lambda a, b: model.init(jax.random.key(0), a, b, 2), pc1, pc2)
    opt_state = jax.eval_shape(tx.init, params)
    batch = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": gt}

    def train_step(params, opt_state, batch):  # the pre-PR body, verbatim
        def loss_fn(p):
            flows, _ = model.apply(p, batch["pc1"], batch["pc2"], 2)
            loss = sequence_loss(flows, batch["mask"], batch["flow"], 0.8)
            return loss, flows

        (loss, flows), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        grads = maybe_cast_grads(grads, None)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        epe = epe_train(flows[-1], batch["mask"], batch["flow"])
        return params, opt_state, {"loss": loss, "epe": epe}

    got = make_train_step(model, tx, 0.8, 2, telemetry=False)
    want = jax.jit(train_step, donate_argnums=(0, 1))
    s_got = _norm_addrs(str(jax.make_jaxpr(got)(params, opt_state, batch)))
    s_want = _norm_addrs(str(jax.make_jaxpr(want)(params, opt_state, batch)))
    assert s_got == s_want


def test_train_step_telemetry_on_only_adds_leaves():
    """Telemetry on: identical loss/epe values, extra monitor leaves."""
    import optax

    from pvraft_tpu.engine.steps import make_train_step
    from pvraft_tpu.models import PVRaft

    cfg = ModelConfig(truncate_k=16, corr_knn=8, graph_k=8,
                      use_pallas=False)
    model = PVRaft(cfg)
    tx = optax.adam(1e-3)
    rng = np.random.default_rng(0)
    batch = {
        "pc1": jnp.asarray(rng.uniform(-1, 1, (1, 32, 3)).astype(np.float32)),
        "pc2": jnp.asarray(rng.uniform(-1, 1, (1, 32, 3)).astype(np.float32)),
        "mask": jnp.ones((1, 32), jnp.float32),
    }
    batch["flow"] = batch["pc2"] - batch["pc1"]
    params = model.init(jax.random.key(0), batch["pc1"], batch["pc2"], 2)
    opt_state = tx.init(params)

    p_off, o_off, m_off = make_train_step(
        model, tx, 0.8, 2, donate=False)(params, opt_state, batch)
    p_on, o_on, m_on = make_train_step(
        model, tx, 0.8, 2, donate=False, telemetry=True)(
            params, opt_state, batch)
    assert float(m_on["loss"]) == pytest.approx(float(m_off["loss"]))
    assert float(m_on["epe"]) == pytest.approx(float(m_off["epe"]))
    for a, b in zip(jax.tree_util.tree_leaves(p_on),
                    jax.tree_util.tree_leaves(p_off)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tel = m_on["telemetry"]
    assert int(tel["nonfinite"]) == 0
    assert float(tel["grad_norm"]) > 0
    assert tel["delta_flow_norm"].shape == (2,)
    assert "telemetry" not in m_off


# --- forced-NaN injection -> snapshot -> run_doctor (acceptance) ------------


@pytest.fixture(scope="module")
def nan_run(tmp_path_factory, monkeypatch_module):
    """ONE poisoned tiny training epoch shared by the assertions below
    (the Trainer compile dominates; rerunning it per test would blow the
    tier-1 budget)."""
    from conftest import tiny_trainer_cfg

    import pvraft_tpu.engine.trainer as trmod
    from pvraft_tpu.parallel.mesh import make_mesh

    tmp_path = tmp_path_factory.mktemp("nan_run")
    cfg = tiny_trainer_cfg(tmp_path)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, telemetry=True))

    real_build = trmod.build_datasets

    def poisoned_build(c):
        train, val, test = real_build(c)

        class Poisoned:
            def __getattr__(self, name):
                return getattr(train, name)

            def __len__(self):
                return len(train)

            def __getitem__(self, i):
                s = dict(train[i])
                if i == 2:  # one bad sample: NaN coordinates in pc1
                    s["pc1"] = s["pc1"].copy()
                    s["pc1"][0, :] = np.nan
                return s

        return Poisoned(), val, test

    monkeypatch_module.setattr(trmod, "build_datasets", poisoned_build)
    trainer = trmod.Trainer(cfg, mesh=make_mesh(n_data=1))
    metrics = trainer.training(0)
    snap_root = os.path.join(cfg.exp_path, "snapshots")
    snaps = sorted(os.listdir(snap_root)) if os.path.isdir(snap_root) else []
    trainer.close()
    return cfg, metrics, snap_root, snaps, trainer.snapshots_taken


@pytest.fixture(scope="module")
def monkeypatch_module():
    mp = pytest.MonkeyPatch()
    yield mp
    mp.undo()


def test_nan_injection_dumps_snapshot_and_events(nan_run):
    cfg, metrics, snap_root, snaps, taken = nan_run
    assert not np.isfinite(metrics["loss"])
    assert snaps and taken == len(snaps)
    # The event stream recorded the divergence and validates.
    events_path = os.path.join(cfg.exp_path, "train.events.jsonl")
    assert validate_events_file(events_path) == []
    records = [json.loads(l) for l in open(events_path)]
    kinds = [r["type"] for r in records]
    assert "divergence" in kinds and "snapshot" in kinds
    div = next(r for r in records if r["type"] == "divergence")
    assert div["reason"] == "nonfinite" and div["loss"] == "NaN"
    # Step events carry the in-jit monitor leaves, sentinel included.
    step_tel = [r["telemetry"] for r in records if r["type"] == "step"]
    assert step_tel and any(t["nonfinite"] > 0 for t in step_tel)


def test_halt_on_divergence_flushes_step_events(nan_run, tmp_path,
                                                monkeypatch_module):
    """--halt_on_divergence raises, but only AFTER the epoch's buffered
    step events (the trajectory into the trip) reach the event log.
    Rides nan_run's module monkeypatch + warm jit cache."""
    from conftest import tiny_trainer_cfg

    import pvraft_tpu.engine.trainer as trmod
    from pvraft_tpu.obs.divergence import DivergenceHalt
    from pvraft_tpu.parallel.mesh import make_mesh

    cfg = tiny_trainer_cfg(tmp_path)
    cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, telemetry=True,
                                       halt_on_divergence=True))
    trainer = trmod.Trainer(cfg, mesh=make_mesh(n_data=1))
    with pytest.raises(DivergenceHalt, match="diverged"):
        trainer.training(0)
    trainer.close()
    events_path = os.path.join(cfg.exp_path, "train.events.jsonl")
    records = [json.loads(l) for l in open(events_path)]
    kinds = [r["type"] for r in records]
    assert "divergence" in kinds
    assert "step" in kinds  # the flush happened before the raise
    assert "epoch_summary" not in kinds  # halted epoch: no summary/ckpt


def test_run_doctor_names_first_nonfinite_stage(nan_run):
    cfg, _, snap_root, snaps, _ = nan_run
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "run_doctor", os.path.join(REPO, "scripts", "run_doctor.py"))
    doctor = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(doctor)
    report = doctor.diagnose(os.path.join(snap_root, snaps[0]))
    # NaN was injected into pc1 itself: the batch is the first bad stage,
    # and the corruption propagates through encoder(pc1) but NOT pc2.
    assert report["first_nonfinite_stage"] == "batch"
    by_stage = {r["stage"]: r for r in report["stages"]}
    assert not by_stage["encoder(pc1)"]["finite"]
    assert by_stage["encoder(pc2)"]["finite"]
    assert not by_stage["loss"]["finite"]
    # CLI main prints and exits 0 on a readable snapshot.
    assert doctor.main([os.path.join(snap_root, snaps[0])]) == 0
