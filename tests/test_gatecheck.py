"""gatecheck: the GE rules red/green over the fixture corpus, the
claim grammar (extraction, field resolution, unit transforms, precision
matching), the declared VALIDATORS table's ordering invariants, the
markdown pragma path, the clean-tree zero-findings gate, the CLI, and
the engine-wide rule-id namespace."""

import contextlib
import io
import json
import os
import shutil

from pvraft_tpu.analysis.__main__ import main as analysis_main
from pvraft_tpu.analysis.engine import known_rule_ids
from pvraft_tpu.analysis.gate.check import check_repo
from pvraft_tpu.analysis.gate.evidence import (
    CLAIM_DOCS,
    VALIDATORS,
    ValidatorSpec,
    apply_unit,
    claim_matches,
    extract_claims,
    extract_citations,
    resolve_field,
)
from pvraft_tpu.analysis.gate.model import build_evidence_model, first_match
from pvraft_tpu.analysis.gate.rules import all_gate_rules
from pvraft_tpu.analysis.gate.stages import GATE_STAGES, GateStage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "gatecheck")

# Small declared tables the fixture trees are checked against — the real
# tables would drag the whole repo ledger into every assertion.
FIX_VALIDATORS = (
    ValidatorSpec(
        schema="pvraft_report/v1",
        globs=("artifacts/report.json", "artifacts/present.json"),
        stage="validate-report",
        note="fixture validator row",
    ),
    ValidatorSpec(
        schema="",
        globs=("artifacts/orphan.json",),
        stage="",
        note="fixture note row",
    ),
)
FIX_STAGES = (
    GateStage(name="validate-report", command="true", inputs=()),
)


def _fixture_check(name, rule, manifest_paths=()):
    diags, _ = check_repo(
        root=os.path.join(FIXTURES, name),
        rule_ids=(rule,),
        validators=FIX_VALIDATORS,
        stages=FIX_STAGES,
        manifest_paths=manifest_paths,
        use_git=False,
    )
    return diags


# ------------------------------------------------------------- rules ----


def test_ge001_red_dangling_citation_and_unindexed_artifact():
    diags = _fixture_check("ge001_red", "GE001")
    messages = [d.message for d in diags]
    assert any("artifacts/missing.json" in m for m in messages)
    assert any("artifacts/orphan.json" in m and "index row" in m
               for m in messages)
    assert all(d.rule_id == "GE001" for d in diags)


def test_ge001_green():
    assert _fixture_check("ge001_green", "GE001") == []


def test_ge002_red_uncovered_artifact():
    diags = _fixture_check("ge002_red", "GE002")
    assert len(diags) == 1
    assert diags[0].path == "artifacts/orphan_metric.json"
    assert "no" in diags[0].message and "VALIDATORS" in diags[0].message


def test_ge002_green():
    assert _fixture_check("ge002_green", "GE002") == []


def test_ge003_red_stale_claim():
    diags = _fixture_check("ge003_red", "GE003")
    assert len(diags) == 1
    d = diags[0]
    assert d.path == "README.md"
    assert "stale claim" in d.message
    assert "'99.9'" in d.message and "12.5" in d.message


def test_ge003_green_including_len_unit():
    assert _fixture_check("ge003_green", "GE003") == []


def test_ge004_red_unowned_schema():
    diags = _fixture_check("ge004_red", "GE004")
    assert len(diags) == 1
    assert diags[0].path == "artifacts/report.json"
    assert "pvraft_ghost/v1" in diags[0].message


def test_ge004_green():
    assert _fixture_check("ge004_green", "GE004") == []


def test_ge005_red_manifest_names_undeclared_stage():
    diags = _fixture_check(
        "ge005_red", "GE005", manifest_paths=("lint.sh",)
    )
    assert len(diags) == 1
    d = diags[0]
    assert d.path == "lint.sh"
    assert "phantom-stage" in d.message


def test_ge005_green():
    assert _fixture_check(
        "ge005_green", "GE005", manifest_paths=("lint.sh",)
    ) == []


def test_ge005_missing_expected_manifest_is_a_finding():
    # A deleted shim may not silently drop the identity check.
    diags = _fixture_check(
        "ge001_green", "GE005", manifest_paths=("lint.sh",)
    )
    assert any("missing" in d.message and d.path == "lint.sh" for d in diags)


def test_markdown_pragma_suppresses_but_clean_tree_carries_none(tmp_path):
    src = os.path.join(FIXTURES, "ge003_red")
    root = tmp_path / "tree"
    shutil.copytree(src, root)
    readme = root / "README.md"
    text = readme.read_text(encoding="utf-8")
    text = text.replace(
        "rps on the",
        "rps <!-- # graftlint: disable=GE003 -- fixture suppression --> on the",
    )
    readme.write_text(text, encoding="utf-8")
    diags, _ = check_repo(
        root=str(root), rule_ids=("GE003",), validators=FIX_VALIDATORS,
        stages=FIX_STAGES, manifest_paths=(), use_git=False,
    )
    assert diags == []


# ------------------------------------------------------- claim grammar ---


def test_extract_claims_segments_and_units():
    lines = [
        "p50 35.2 <!-- claim: artifacts/a.json#lat.p50 --> ms, "
        "32.1 <!-- claim: artifacts/a.json#rps --> rps",
        "95 <!-- claim: artifacts/b.json#leaves@len --> leaves",
    ]
    claims = extract_claims("DOC.md", lines)
    assert [(c.field, c.unit, c.quoted) for c in claims] == [
        ("lat.p50", "", "35.2"), ("rps", "", "32.1"), ("leaves", "len", "95"),
    ]


def test_extract_claims_skips_fenced_blocks():
    lines = [
        "```markdown",
        "10.0 <!-- claim: artifacts/x.json#f -->",
        "```",
        "real 1.5 <!-- claim: artifacts/y.json#g -->",
    ]
    claims = extract_claims("DOC.md", lines)
    assert [c.src for c in claims] == ["artifacts/y.json"]


def test_extract_citations_normalizes_templates():
    lines = ["see artifacts/run_<timestamp>.json and artifacts/a_{b,c}.json."]
    cites = extract_citations("DOC.md", lines)
    pats = [p for c in cites for p in c.patterns]
    assert "artifacts/run_*.json" in pats
    assert "artifacts/a_b.json" in pats and "artifacts/a_c.json" in pats


def test_resolve_field_walks_dicts_and_list_indices():
    obj = {"meshes": [{"scenes": [{"bytes": 7}]}]}
    assert resolve_field(obj, "meshes.0.scenes.0.bytes") == (True, 7)
    assert resolve_field(obj, "meshes.1.scenes") == (False, None)
    assert resolve_field(obj, "meshes.x") == (False, None)


def test_apply_unit_transforms():
    assert apply_unit(2 ** 30, "gib") == (True, 1.0)
    assert apply_unit(3 * 2 ** 20, "mib") == (True, 3.0)
    assert apply_unit([1, 2, 3], "len") == (True, 3)
    ok, _ = apply_unit("text", "gib")
    assert not ok


def test_claim_matches_at_prose_precision():
    assert claim_matches("10.46", 10.4634)
    assert not claim_matches("10.46", 10.47)
    assert claim_matches("192,034", 192034)
    assert claim_matches("29.3", 29.277)
    assert not claim_matches("29.3", 29.35001)
    assert not claim_matches("1", True)  # bools are not numbers


# --------------------------------------------------- registry invariants --


def test_validators_specific_rows_shadow_broad_serve_glob():
    # First-match order: the trace/slo/calibration rows must win over the
    # broad serve_*.json row (the artifact_budget.py discipline).
    for rel, schema in (
        ("artifacts/serve_ab.slo.json", "pvraft_slo/v1"),
        ("artifacts/serve_chaos.trace.json", "pvraft_trace/v1"),
        ("artifacts/serve_calibration.json", "pvraft_cost_calibration/v1"),
        ("artifacts/serve_cpu_synthetic.json", "pvraft_serve_load/v1"),
    ):
        spec = first_match(rel, VALIDATORS)
        assert spec is not None and spec.schema == schema, rel


def test_validators_schema_namespace_is_exactly_once():
    owned = [s.schema for s in VALIDATORS if s.schema]
    assert len(owned) == len(set(owned))


def test_rule_ids_are_the_declared_ge_family():
    assert [r.id for r in all_gate_rules()] == [
        "GE001", "GE002", "GE003", "GE004", "GE005",
    ]


def test_known_rule_ids_include_ge_family():
    ids = known_rule_ids()
    assert {"GE000", "GE001", "GE002", "GE003", "GE004", "GE005"} <= ids


# ------------------------------------------------------- clean tree & CLI --


def test_clean_tree_has_zero_findings_and_zero_ge_pragmas():
    diags, model = check_repo(root=REPO)
    assert diags == [], "\n".join(d.format() for d in diags)
    # The discipline is fixed-not-pragma'd: no GE suppression anywhere in
    # the claim docs.
    for doc, lines in model.docs.items():
        for line in lines:
            assert "disable=GE" not in line, doc


def test_clean_tree_model_is_populated():
    model = build_evidence_model(REPO)
    assert len(model.tracked) > 30
    assert len(model.claims) >= 15
    assert len(model.citations) > 50
    assert set(model.manifests) == {
        "scripts/lint.sh", ".github/workflows/ci.yml"
    }
    assert model.errors == []
    assert "artifacts/README.md" in model.docs
    assert CLAIM_DOCS[0] == "README.md"


def test_cli_rules_green_and_list_flags():
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf), contextlib.redirect_stderr(buf):
        rc = analysis_main(["gate", "--rules", "--root", REPO])
    assert rc == 0
    assert "gatecheck: 0 finding(s)" in buf.getvalue()

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["gate", "--list-rules"])
    assert rc == 0
    for rid in ("GE001", "GE002", "GE003", "GE004", "GE005"):
        assert rid in buf.getvalue()

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(["gate", "--list-stages"])
    assert rc == 0
    for stage in GATE_STAGES:
        assert stage.name in buf.getvalue()


def test_cli_rules_red_on_fixture(tmp_path):
    src = os.path.join(FIXTURES, "ge003_red")
    root = tmp_path / "tree"
    shutil.copytree(src, root)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = analysis_main(
            ["gate", "--rules", "--select", "GE003", "--root", str(root)]
        )
    assert rc == 1
    assert "GE003" in buf.getvalue()


def test_committed_gate_reports_are_valid_evidence():
    from pvraft_tpu.analysis.gate.runner import check_report_file

    for name in ("gate_cold.json", "gate_warm.json"):
        path = os.path.join(REPO, "artifacts", name)
        assert check_report_file(path) == [], name
    with open(os.path.join(REPO, "artifacts", "gate_warm.json"),
              encoding="utf-8") as fh:
        warm = json.load(fh)
    # The warm snapshot is the caching claim: most stages cached.
    assert warm["counts"]["cached"] >= warm["counts"]["ok"]
