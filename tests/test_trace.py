"""Trace plane gate: span trees + sampling (obs/trace.py), the
pvraft_trace/v1 artifact validator, the step-profile span bridge, the
pvraft_slo/v1 report build/validate, Prometheus exposition (with a
minimal text-format parser), and the frozen JSON /metrics shape.

Everything here is host-side pure Python — no AOT compiles, no model —
so the whole module costs seconds (tier-1 budget discipline)."""

import json
import re

import pytest

from pvraft_tpu.obs.events import validate_event
from pvraft_tpu.obs.slo import (
    build_slo_report,
    exact_quantile,
    validate_slo_report,
)
from pvraft_tpu.obs.trace import (
    SERVE_STAGES,
    RequestTrace,
    Tracer,
    collect_traces,
    trace_from_step_profile,
    validate_trace_artifact,
)
from pvraft_tpu.serve.metrics import ServeMetrics


# ------------------------------------------------------- tracer/sampling --


def test_tracer_sampling():
    assert Tracer(sample_every=0).begin() is None          # disabled
    assert Tracer(sample_every=1).begin() is not None      # everything
    t = Tracer(sample_every=3)
    hits = sum(t.begin() is not None for _ in range(30))
    assert hits == 10                                      # exactly 1-in-3
    with pytest.raises(ValueError):
        Tracer(sample_every=-1)


def test_request_trace_span_tree():
    trace = RequestTrace(t0=100.0)
    trace.mark("ingress", 100.0, 100.01)
    trace.mark("device_execute", 100.02, 100.5,
               attrs={"bucket": 32, "batch": 2, "n": 1})
    spans = trace.spans(t_end=100.6, root_attrs={"status": 200})
    assert [s["name"] for s in spans] == [
        "request", "ingress", "device_execute"]
    root = spans[0]
    assert "parent_id" not in root
    assert root["attrs"] == {"status": 200}
    assert all(s["parent_id"] == root["span_id"] for s in spans[1:])
    assert all(s["trace_id"] == trace.trace_id for s in spans)
    assert root["end_ms"] - root["start_ms"] == pytest.approx(600.0)
    durs = trace.stage_durations_ms()
    assert durs["device_execute"] == pytest.approx(480.0)
    # Every span is a valid pvraft_events/v1 record body.
    for i, s in enumerate(spans):
        rec = {"schema": "pvraft_events/v1", "type": "span", "time": 1.0,
               "seq": i, **s}
        assert validate_event(rec, seq=i) == [], s


# --------------------------------------------------------- span events --


def test_span_event_rejects_reversed_interval():
    rec = {"schema": "pvraft_events/v1", "type": "span", "time": 1.0,
           "seq": 0, "trace_id": "t", "span_id": "s", "name": "ingress",
           "start_ms": 10.0, "end_ms": 9.0}
    assert any("end_ms" in p for p in validate_event(rec, seq=0))
    rec["end_ms"] = 10.0                                   # zero-width ok
    assert validate_event(rec, seq=0) == []


def test_slo_report_event():
    rec = {"schema": "pvraft_events/v1", "type": "slo_report", "time": 1.0,
           "seq": 0, "path": "artifacts/x.slo.json", "slo_p99_ms": 5000.0,
           "max_qps_under_slo": 11.3, "programs": 2, "requests": 64}
    assert validate_event(rec, seq=0) == []
    del rec["slo_p99_ms"]
    assert any("slo_p99_ms" in p for p in validate_event(rec, seq=0))


# ------------------------------------------------------ trace artifact --


def _spans(trace_id="t1", stages=SERVE_STAGES, status=200):
    spans = [{"trace_id": trace_id, "span_id": "r", "name": "request",
              "start_ms": 0.0, "end_ms": 100.0,
              "attrs": {"status": status}}]
    for i, stage in enumerate(stages):
        span = {
            "trace_id": trace_id, "span_id": f"r.{i}", "parent_id": "r",
            "name": stage, "start_ms": float(i * 10),
            "end_ms": float(i * 10 + 10),
        }
        if stage == "device_execute":
            span["attrs"] = {"bucket": 32, "batch": 2, "n": 1}
        spans.append(span)
    return spans


def _records(spans):
    return [{"schema": "pvraft_events/v1", "type": "span", "time": 1.0,
             "seq": i, **s} for i, s in enumerate(spans)]


def test_collect_traces_complete_and_incomplete():
    recs = _records(_spans("t1") + _spans("t2", stages=("ingress",)))
    doc = collect_traces(recs, source="x.events.jsonl")
    assert doc["counts"] == {"traces": 2, "spans": len(SERVE_STAGES) + 3,
                             "complete": 1, "orphan_spans": 0}
    by_id = {t["trace_id"]: t for t in doc["traces"]}
    assert by_id["t1"]["complete"] and not by_id["t2"]["complete"]
    assert by_id["t1"]["duration_ms"] == 100.0
    assert validate_trace_artifact(doc) == []


def test_collect_traces_orphans():
    spans = _spans("t1")
    spans[3]["parent_id"] = "nonexistent"
    doc = collect_traces(_records(spans))
    assert doc["counts"]["orphan_spans"] == 1
    assert doc["counts"]["complete"] == 0
    assert validate_trace_artifact(doc) == []


def test_validate_trace_artifact_red():
    doc = collect_traces(_records(_spans()))
    bad = json.loads(json.dumps(doc))
    bad["traces"][0]["complete"] = False        # forged flag
    assert any("complete" in p for p in validate_trace_artifact(bad))
    bad = json.loads(json.dumps(doc))
    bad["counts"]["spans"] += 1                 # drifted counts
    assert any("counts" in p for p in validate_trace_artifact(bad))
    bad = json.loads(json.dumps(doc))
    bad["traces"][0]["spans"][1]["end_ms"] = -1.0   # reversed span
    assert any("end_ms" in p for p in validate_trace_artifact(bad))
    bad = json.loads(json.dumps(doc))
    bad["schema"] = "pvraft_trace/v0"
    assert any("schema" in p for p in validate_trace_artifact(bad))
    # expected_stages is pinned to a known vocabulary: emptying it (to
    # make completeness vacuous) fails, it cannot be forged alongside
    # the complete flags.
    bad = json.loads(json.dumps(doc))
    bad["expected_stages"] = []
    assert any("known stage vocabulary" in p
               for p in validate_trace_artifact(bad))
    # Malformed containers report problems, never traceback (the lint
    # gate runs this on hand-editable committed files).
    bad = json.loads(json.dumps(doc))
    bad["traces"] = 5
    assert validate_trace_artifact(bad)
    bad = json.loads(json.dumps(doc))
    bad["traces"][0]["spans"] = "abc"
    assert any("list of span objects" in p
               for p in validate_trace_artifact(bad))


# --------------------------------------------------- step-profile bridge --


def test_trace_from_step_profile():
    record = {
        "platform": "cpu", "variant": "fp32", "points": 2048, "batch": 2,
        "iters": 8, "total_step_s": 3.0,
        "breakdown_s": {"encoder": 0.5, "corr_init": 0.3,
                        "gru_forward": 0.4, "backward": 1.6,
                        "optimizer": 0.2},
    }
    spans = trace_from_step_profile(record)
    assert spans[0]["name"] == "train_step"
    assert spans[0]["end_ms"] == 3000.0
    assert [s["name"] for s in spans[1:]] == [
        "encoder", "corr_init", "gru_forward", "backward", "optimizer"]
    # Stages telescope: consecutive, gap-free, summing to the total.
    cursor = 0.0
    for s in spans[1:]:
        assert s["start_ms"] == pytest.approx(cursor)
        cursor = s["end_ms"]
    assert cursor == pytest.approx(3000.0)
    doc = collect_traces(
        _records(spans),
        expected_stages=tuple(record["breakdown_s"]))
    assert doc["counts"]["complete"] == 1
    with pytest.raises(ValueError, match="breakdown"):
        trace_from_step_profile({"measurements": {}})


# ------------------------------------------------------------ SLO report --


def test_exact_quantile():
    assert exact_quantile([], 0.99) is None
    samples = list(range(100))
    assert exact_quantile(samples, 0.50) == 50
    assert exact_quantile(samples, 0.99) == 99


def _load_doc(n=4, status=200, throughput=10.0):
    return {
        "schema": "pvraft_serve_load/v1",
        "config": {"compute_dtype": "float32"},
        "requests": {"total": n, "ok": n, "rejected": 0, "errors": 0},
        "throughput_rps": throughput,
        "per_request": [{"status": status, "ms": 100.0 + i,
                         "n": 20, "trace_id": f"t{i}"}
                        for i in range(n)],
    }


def test_build_slo_report_joins_and_quantifies():
    doc = _load_doc(n=3)
    records = []
    for i in range(3):
        spans = _spans(f"t{i}")
        for s in spans:
            if s["name"] == "device_execute":
                s["attrs"] = {"bucket": 32, "batch": 2, "n": 1}
        records += _records(spans)
    report = build_slo_report(
        [("load.json", doc, "load.events.jsonl", records)],
        slo_p99_ms=5000.0)
    assert validate_slo_report(report) == []
    assert report["totals"] == {"requests": 3, "ok": 3, "traced_ok": 3,
                                "complete": 3, "orphan_spans": 0}
    assert len(report["programs"]) == 1
    row = report["programs"][0]
    assert (row["bucket"], row["batch"], row["dtype"]) == (32, 2, "float32")
    assert row["requests"] == 3
    assert set(row["stages"]) == set(SERVE_STAGES)
    # Each synthetic stage is 10ms, e2e 100ms: 7 stages -> ratio 0.7.
    assert row["e2e"]["p99_ms"] == 100.0
    assert row["stage_p99_sum_ms"] == pytest.approx(70.0)
    assert row["stage_sum_ratio"] == pytest.approx(0.7)
    assert row["meets_slo"]
    assert report["max_qps_under_slo"] == 10.0


def test_build_slo_report_slo_miss_and_untraced():
    doc = _load_doc(n=2, throughput=50.0)
    doc["per_request"][1]["trace_id"] = None     # one untraced request
    report = build_slo_report(
        [("load.json", doc, "e.jsonl", _records(_spans("t0")))],
        slo_p99_ms=50.0)                          # SLO below the 100ms e2e
    assert report["totals"]["traced_ok"] == 1
    assert report["runs"][0]["meets_slo"] is False
    assert report["max_qps_under_slo"] is None
    assert validate_slo_report(report) == []


def test_validate_slo_report_red():
    report = build_slo_report(
        [("l.json", _load_doc(1), "e.jsonl", _records(_spans("t0")))],
        slo_p99_ms=5000.0)
    bad = json.loads(json.dumps(report))
    bad["schema"] = "pvraft_slo/v0"
    assert any("schema" in p for p in validate_slo_report(bad))
    bad = json.loads(json.dumps(report))
    del bad["max_qps_under_slo"]
    assert any("max_qps_under_slo" in p for p in validate_slo_report(bad))
    bad = json.loads(json.dumps(report))
    bad["totals"]["complete"] = 99               # complete > traced_ok
    assert any("complete" in p for p in validate_slo_report(bad))
    bad = json.loads(json.dumps(report))
    del bad["programs"][0]["stages"]["device_execute"]
    assert any("device_execute" in p for p in validate_slo_report(bad))
    bad = json.loads(json.dumps(report))
    for run in bad["runs"]:
        run["meets_slo"] = False                 # qps claim without a run
    assert any("qualifying runs" in p for p in validate_slo_report(bad))
    bad = json.loads(json.dumps(report))
    bad["max_qps_under_slo"] = 999999.0          # forged headline number
    assert any("qualifying runs" in p for p in validate_slo_report(bad))
    # Malformed containers report problems, never traceback.
    bad = json.loads(json.dumps(report))
    bad["totals"] = None
    assert any("totals" in p for p in validate_slo_report(bad))
    bad = json.loads(json.dumps(report))
    bad["programs"] = 5
    assert any("programs" in p for p in validate_slo_report(bad))


# ------------------------------------------------ Prometheus exposition --


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})? (\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text):
    """Minimal text-format 0.0.4 parser: {family: {"help", "type",
    "samples": [(name, labels-dict, float)]}}. Raises on any line that
    is neither a comment nor a well-formed sample."""
    families = {}
    for line in text.splitlines():
        if line.startswith("# HELP "):
            name, help_text = line[len("# HELP "):].split(" ", 1)
            families.setdefault(
                name, {"samples": []})["help"] = help_text
        elif line.startswith("# TYPE "):
            name, mtype = line[len("# TYPE "):].split(" ", 1)
            families.setdefault(name, {"samples": []})["type"] = mtype
        elif line.strip():
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            name, raw_labels, value = m.groups()
            family = re.sub(r"_(bucket|sum|count)$", "", name)
            key = family if family in families else name
            labels = dict(_LABEL_RE.findall(raw_labels or ""))
            families.setdefault(key, {"samples": []})["samples"].append(
                (name, labels, float(value.replace("+Inf", "inf"))))
    return families


def _metrics_with_data():
    m = ServeMetrics(buckets=(32, 64))
    m.record_submit(32, n_points=20)
    m.record_submit(64, n_points=48)
    m.record_reject("queue_full")
    m.record_batch(2, 0.5, [3.0, 7.5])
    m.record_stages(32, {"device_execute": 2.0})
    return m


def test_prometheus_exposition_names_help_type():
    fams = parse_prometheus(_metrics_with_data().prometheus({32: 0, 64: 1}))
    # Every family is namespaced, typed and documented.
    assert fams and all(name.startswith("pvraft_serve_") for name in fams)
    for name, fam in fams.items():
        assert fam.get("help"), f"{name} has no HELP"
        assert fam.get("type") in ("counter", "gauge", "histogram"), name
    assert fams["pvraft_serve_requests_total"]["samples"] == [
        ("pvraft_serve_requests_total", {}, 3.0)]
    assert ("pvraft_serve_rejected_total", {"reason": "queue_full"}, 1.0) \
        in fams["pvraft_serve_rejected_total"]["samples"]
    assert ("pvraft_serve_queue_depth", {"bucket": "64"}, 1.0) \
        in fams["pvraft_serve_queue_depth"]["samples"]


def test_prometheus_histograms_cumulative():
    fams = parse_prometheus(_metrics_with_data().prometheus())
    lat = fams["pvraft_serve_latency_ms"]["samples"]
    buckets = [(labels["le"], v) for n, labels, v in lat
               if n.endswith("_bucket")]
    # le edges ascend and counts are cumulative (never decrease).
    values = [v for _, v in buckets]
    assert values == sorted(values)
    assert buckets[-1][0] == "+Inf" and buckets[-1][1] == 2.0
    count = [v for n, _, v in lat if n.endswith("_count")][0]
    assert count == 2.0
    total = [v for n, _, v in lat if n.endswith("_sum")][0]
    assert total == pytest.approx(10.5)
    # The trace-fed per-(bucket, stage) family + request-size family.
    stage = fams["pvraft_serve_stage_latency_ms"]["samples"]
    assert any(l.get("stage") == "device_execute" and l.get("bucket") == "32"
               for _, l, _ in stage)
    points = fams["pvraft_serve_request_points"]["samples"]
    assert [v for n, _, v in points if n.endswith("_count")] == [2.0]


def test_json_metrics_snapshot_byte_compatible():
    """The default /metrics JSON is shape-frozen: new trace/size
    histograms are Prometheus-only. This pins the exact serialized
    bytes of a fixed interaction sequence — any key added, renamed or
    reordered (under sort_keys) fails here."""
    snap = _metrics_with_data().snapshot({32: 0, 64: 1})
    assert json.dumps(snap, sort_keys=True) == (
        '{"batch_fill_mean": 0.5, "batches_total": 1, "latency": '
        '{"bucket_counts": [0, 0, 1, 1, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0], '
        '"bucket_edges_ms": [1.0, 2.0, 5.0, 10.0, 20.0, 50.0, 100.0, '
        '200.0, 500.0, 1000.0, 2000.0, 5000.0, 10000.0, 30000.0], '
        '"count": 2, "max_ms": 7.5, "mean_ms": 5.25, "p50_ms": 5.0, '
        '"p95_ms": 10.0, "p99_ms": 10.0}, "per_bucket_requests": '
        '{"32": 1, "64": 1}, "queue_depth": {"32": 0, "64": 1}, '
        '"rejected": {"queue_full": 1}, "requests_total": 3, '
        '"responses_total": 2}')


def test_json_metrics_byte_frozen_with_cost_surface_armed():
    """ISSUE-14 pin: ARMING the cost surface (and recording priced
    dispatches) must not move a single byte of the frozen JSON
    /metrics — every cost series is Prometheus/healthz-only."""
    baseline = _metrics_with_data()
    armed = _metrics_with_data()
    armed.arm_cost()
    armed.record_cost(bucket=32, batch=1, dtype="float32", replica=0,
                      predicted_s=0.01, measured_s=0.02, t_start=0.0,
                      t_end=0.02, comparable=False, extrapolated=False)
    assert json.dumps(armed.snapshot({32: 0, 64: 1}), sort_keys=True) \
        == json.dumps(baseline.snapshot({32: 0, 64: 1}), sort_keys=True)
    # And the DISARMED exposition is byte-identical to a pre-surface
    # store: the cost families appear only once armed.
    assert baseline.prometheus() == _metrics_with_data().prometheus()
    assert "pvraft_serve_predicted_device_seconds_total" \
        not in baseline.prometheus()
    assert "pvraft_serve_predicted_device_seconds_total" \
        in armed.prometheus()
