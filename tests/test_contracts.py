"""@shapecheck contract layer: correct shapes pass, mismatches raise
readable errors, and with PVRAFT_CHECKS unset the decorator is a provable
no-op (same function object, same jaxpr)."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pvraft_tpu.analysis.contracts import (
    ContractSpec,
    ShapeError,
    checks_enabled,
    shapecheck,
    wrap_with_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- the zero-cost guarantee (PVRAFT_CHECKS unset in tier-1) --------------

def test_disabled_decorator_returns_function_unchanged():
    assert not checks_enabled()  # tier-1 runs without PVRAFT_CHECKS

    def f(x):
        return x * 2

    g = shapecheck("B N")(f)
    assert g is f                      # not a wrapper: byte-identical
    assert hasattr(g, "__shapecheck__")  # but the contract is recorded


def test_disabled_package_ops_are_unwrapped():
    from pvraft_tpu.ops.corr import corr_init, corr_volume
    from pvraft_tpu.ops.geometry import build_graph, knn_indices
    from pvraft_tpu.ops.voxel import voxel_bin_means

    for op in (corr_volume, corr_init, knn_indices, build_graph,
               voxel_bin_means):
        assert hasattr(op, "__shapecheck__"), op
        assert not hasattr(op, "__shapecheck_inner__"), (
            f"{op.__name__} is wrapped although PVRAFT_CHECKS is unset"
        )


def test_disabled_jaxpr_identical():
    from pvraft_tpu.ops.corr import corr_volume

    f1 = jnp.zeros((2, 8, 4))
    f2 = jnp.zeros((2, 6, 4))
    # The decorated op IS the undecorated function when checks are off,
    # so the jaxprs are trivially byte-identical — and wrapping the same
    # function by hand must not change the jaxpr either (checks only read
    # static metadata).
    wrapped = wrap_with_spec(corr_volume, corr_volume.__shapecheck__)
    assert str(jax.make_jaxpr(wrapped)(f1, f2)) == str(
        jax.make_jaxpr(corr_volume)(f1, f2)
    )


# --- enabled-mode semantics (wrap_with_spec: no env needed) ---------------

def _wrapped(fn, *specs, **kw):
    return wrap_with_spec(fn, ContractSpec(specs, kw.get("out"),
                                           kw.get("dtype")))


def test_pass_on_correct_shapes():
    g = _wrapped(lambda a, b: a @ b.T, "N D", "M D", out="N M")
    out = g(jnp.zeros((4, 3)), jnp.zeros((5, 3)))
    assert out.shape == (4, 5)


def test_rank_mismatch_message():
    g = _wrapped(lambda a: a, "B N 3")
    with pytest.raises(ShapeError, match=r"expected rank 3 \[B N 3\]"):
        g(jnp.zeros((4, 3)))


def test_literal_dim_mismatch_message():
    g = _wrapped(lambda a: a, "B N 3")
    with pytest.raises(ShapeError, match=r"axis 2 must be 3"):
        g(jnp.zeros((2, 4, 4)))


def test_binding_conflict_across_args():
    g = _wrapped(lambda a, b: (a, b), "B N 3", "B M 3")
    with pytest.raises(ShapeError, match=r"B=7.*conflicts with B=2"):
        g(jnp.zeros((2, 4, 3)), jnp.zeros((7, 5, 3)))


def test_output_contract_checked():
    g = _wrapped(lambda a: a[:, :2], "B N", out="B N")
    with pytest.raises(ShapeError, match="return value"):
        g(jnp.zeros((2, 5)))


def test_output_tuple_spec_with_none_skips():
    g = _wrapped(lambda a: (a, "aux"), "B N", out=("B N", None))
    out, aux = g(jnp.zeros((2, 5)))
    assert aux == "aux"


def test_keyword_passed_argument_is_checked():
    """A contracted arg passed by keyword must be checked exactly like a
    positional one (an unchecked kwarg is false confidence)."""
    g = _wrapped(lambda a, b: b, "N D", "M D")
    g(jnp.zeros((4, 3)), b=jnp.zeros((5, 3)))
    with pytest.raises(ShapeError, match=r"argument 1 expected rank 2"):
        g(jnp.zeros((4, 3)), b=jnp.zeros((9,)))
    with pytest.raises(ShapeError, match=r"argument 1 expected rank 2"):
        g(b=jnp.zeros((9,)), a=jnp.zeros((4, 3)))


def test_none_spec_skips_argument():
    g = _wrapped(lambda state, rel: rel, None, "B N 3")
    assert g({"any": "thing"}, jnp.zeros((1, 2, 3))).shape == (1, 2, 3)


def test_optional_none_default_arg_skipped_when_none():
    """A spec'd parameter whose default is None (optional mask args, e.g.
    corr_init's valid2) is only checked when a non-None value arrives —
    forwarding an explicit None through a call chain is 'absent', not a
    violated contract. A required param passing None still fails."""
    g = _wrapped(lambda a, mask=None: a, "B N", "B N")
    g(jnp.zeros((2, 3)))                       # absent
    g(jnp.zeros((2, 3)), mask=None)            # explicit None: absent
    g(jnp.zeros((2, 3)), None)                 # positional None: absent
    g(jnp.zeros((2, 3)), jnp.ones((2, 3)))     # real mask: checked
    with pytest.raises(ShapeError, match="argument 1"):
        g(jnp.zeros((2, 3)), jnp.ones((2, 9)))
    h = _wrapped(lambda a, b: a, "B N", "B N")
    with pytest.raises(ShapeError, match="argument 1 expected an array"):
        h(jnp.zeros((2, 3)), None)             # required param: still fails


def test_wildcard_dim():
    g = _wrapped(lambda a: a, "B _ 3")
    g(jnp.zeros((2, 99, 3)))  # any middle dim passes


def test_dtype_kind_check():
    g = _wrapped(lambda a: a, "B N", dtype="floating")
    g(jnp.zeros((2, 3), jnp.float32))
    with pytest.raises(ShapeError, match="expected dtype floating"):
        g(jnp.zeros((2, 3), jnp.int32))


def test_non_array_argument_rejected():
    g = _wrapped(lambda a: a, "B N")
    with pytest.raises(ShapeError, match="no .shape"):
        g([1, 2, 3])


def test_works_under_jit_and_eval_shape():
    g = _wrapped(lambda a, b: a @ b.T, "N D", "M D", out="N M")
    out = jax.jit(g)(jnp.ones((4, 3)), jnp.ones((5, 3)))
    np.testing.assert_allclose(np.asarray(out), 3.0)
    s = jax.eval_shape(g, jax.ShapeDtypeStruct((4, 3), "float32"),
                       jax.ShapeDtypeStruct((5, 3), "float32"))
    assert s.shape == (4, 5)


def test_enabled_jaxpr_identical_to_inner():
    # Even when checks run, they read only static metadata: the traced
    # computation is unchanged.
    def f(a, b):
        return a @ b.T

    g = _wrapped(f, "N D", "M D", out="N M")
    x, y = jnp.zeros((4, 3)), jnp.zeros((5, 3))
    assert str(jax.make_jaxpr(g)(x, y)) == str(jax.make_jaxpr(f)(x, y))


# --- decorator path with the env var actually set (subprocess) ------------

def test_env_enabled_package_op_enforces_contract():
    """PVRAFT_CHECKS=1 at import time wraps the shipped ops: good shapes
    pass, a K/3 axis swap raises ShapeError."""
    code = (
        "import jax.numpy as jnp\n"
        "from pvraft_tpu.ops.corr import corr_volume\n"
        "from pvraft_tpu.analysis.contracts import ShapeError\n"
        "assert hasattr(corr_volume, '__shapecheck_inner__')\n"
        "out = corr_volume(jnp.zeros((2, 8, 4)), jnp.zeros((2, 6, 4)))\n"
        "assert out.shape == (2, 8, 6)\n"
        "try:\n"
        "    corr_volume(jnp.zeros((2, 8, 4)), jnp.zeros((2, 6, 5)))\n"
        "except ShapeError as e:\n"
        "    assert 'conflicts with D=4' in str(e), e\n"
        "else:\n"
        "    raise SystemExit('no ShapeError on D mismatch')\n"
        "print('OK')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu", "PVRAFT_CHECKS": "1"},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "OK" in proc.stdout


# --- the eval_shape trace-compat audit ------------------------------------

def test_trace_audit_all_clean():
    from pvraft_tpu.analysis.audit import run_audit

    results = run_audit()
    assert len(results) >= 14
    bad = [r for r in results if not r.ok]
    assert not bad, "\n".join(f"{r.name}: {r.detail}" for r in bad)
