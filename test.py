#!/usr/bin/env python
"""Standalone evaluation entry point (equivalent of the reference
``test.py``): FT3D-test or zero-shot KITTI, batch size 1, 32 GRU iterations
(``test.py:92,120``), running-mean metrics, optional flow dump for
visualization (``visual.py`` layout)."""

from __future__ import annotations

import argparse

from pvraft_tpu.config import Config, DataConfig, ModelConfig, TrainConfig


def parse_args(argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser("pvraft_tpu test")
    p.add_argument("--root", default="")
    p.add_argument("--exp_path", default="experiments/default")
    p.add_argument("--dataset", default="FT3D",
                   choices=["FT3D", "KITTI", "synthetic"])
    p.add_argument("--max_points", type=int, default=8192)
    p.add_argument("--corr_levels", type=int, default=3)
    p.add_argument("--base_scales", type=float, default=0.25)
    p.add_argument("--truncate_k", type=int, default=512)
    p.add_argument("--corr_knn", type=int, default=32)
    p.add_argument("--eval_iters", type=int, default=32)
    p.add_argument("--eval_scan", type=int, default=1,
                   help="scan-fuse this many eval batches per compiled "
                        "dispatch (metrics only; a --dump_dir run falls "
                        "back to the per-batch path)")
    p.add_argument("--eval_batch", type=int, default=0,
                   help="scenes evaluated concurrently, sharded over the "
                        "mesh data axis with per-scene metrics (identical "
                        "running means; 0 = one scene per device, 1 = the "
                        "reference's serial bs=1 loop)")
    p.add_argument("--weights", required=False, default=None)
    p.add_argument("--torch_weights", default=None,
                   help="reference-published torch .params checkpoint")
    p.add_argument("--refine", action="store_true")
    p.add_argument("--use_pallas", action=argparse.BooleanOptionalAction,
                   default=None,
                   help="Pallas kernels vs XLA fallback (default: auto — "
                        "Pallas on TPU, XLA elsewhere)")
    p.add_argument("--corr_chunk", type=int, default=None)
    p.add_argument("--graph_chunk", type=int, default=None)
    p.add_argument("--approx_topk", action="store_true")
    p.add_argument("--approx_knn", action="store_true")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--num_workers", type=int, default=8)
    p.add_argument("--no_strict_sizes", action="store_true",
                   help="allow dataset subsets (skip the reference's size asserts)")
    p.add_argument("--dump_dir", default=None,
                   help="write result/<ds>/<idx>/{pc1,pc2,flow}.npy for visual.py")
    p.add_argument("--synthetic_size", type=int, default=16)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"],
                   help="force a jax platform (e.g. cpu for host debugging)")
    p.add_argument("--seq_parallel", type=int, default=1,
                   help="devices on the sequence mesh axis (ring correlation "
                        "+ kNN for clouds too large for one chip)")
    return p.parse_args(argv)


def main(argv=None) -> None:
    a = parse_args(argv)
    cfg = Config(
        model=ModelConfig(
            truncate_k=a.truncate_k, corr_knn=a.corr_knn,
            corr_levels=a.corr_levels,
            base_scale=a.base_scales, use_pallas=a.use_pallas,
            corr_chunk=a.corr_chunk, graph_chunk=a.graph_chunk,
            approx_topk=a.approx_topk, approx_knn=a.approx_knn,
            compute_dtype="bfloat16" if a.bf16 else "float32",
            seq_shard=a.seq_parallel > 1,
        ),
        data=DataConfig(dataset=a.dataset, root=a.root,
                        max_points=a.max_points, num_workers=a.num_workers,
                        synthetic_size=a.synthetic_size,
                        strict_sizes=not a.no_strict_sizes),
        train=TrainConfig(refine=a.refine, eval_iters=a.eval_iters,
                          eval_batch=a.eval_batch,
                          eval_scan=a.eval_scan),
        exp_path=a.exp_path,
    )

    if a.platform:
        import jax

        jax.config.update("jax_platforms", a.platform)

    from pvraft_tpu.engine.evaluator import Evaluator
    from pvraft_tpu.parallel.mesh import make_mesh

    mesh = None
    if a.seq_parallel > 1:
        mesh = make_mesh(n_data=1, n_seq=a.seq_parallel)
    ev = Evaluator(cfg, mesh=mesh)
    if a.weights:
        ev.load(a.weights)
    if a.torch_weights:
        ev.load_torch(a.torch_weights)
    means = ev.run(dump_dir=a.dump_dir)
    ev.close()
    print({k: round(v, 4) for k, v in sorted(means.items())})


if __name__ == "__main__":
    main()
