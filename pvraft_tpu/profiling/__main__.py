"""CLI for the profiling plane: validate committed step-profile evidence.

``python -m pvraft_tpu.profiling validate artifacts/step_profile.json``
schema-validates a ``pvraft_step_profile/v1`` record with
:func:`validate_step_profile` — the same check ``tests/test_profiling.py``
applies, exposed as a command so the gate runner's ``validate-profile``
stage covers the artifact (GE002) without importing test code.
"""

from __future__ import annotations

import argparse
import json
import sys

from pvraft_tpu.profiling.step_profiler import validate_step_profile


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m pvraft_tpu.profiling")
    sub = parser.add_subparsers(dest="cmd", required=True)
    val = sub.add_parser("validate", help="validate step-profile artifacts")
    val.add_argument("paths", nargs="+")
    args = parser.parse_args(argv)

    rc = 0
    for path in args.paths:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                record = json.load(fh)
        except (OSError, ValueError) as exc:
            print(f"{path}: unreadable ({exc})")
            rc = 1
            continue
        problems = validate_step_profile(record)
        if problems:
            rc = 1
            for p in problems:
                print(f"{path}: {p}")
        else:
            print(f"{path}: OK ({record.get('platform')}, "
                  f"total_step_s={record.get('total_step_s')})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
