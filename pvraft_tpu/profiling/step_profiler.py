"""Per-stage train-step profiler.

The instrument the round-5 perf correction demanded (BENCHMARKS.md): every
sub-second "device time" measured through the remote tunnel without a host
fetch is suspect — ``block_until_ready`` has been observed to return before
execution. This profiler times a ladder of cumulative programs, each
synced by the only thing the tunnel cannot fake (a host scalar fetch of a
value data-dependent on the program's output) and fed fresh (perturbed)
inputs per call so result memoization cannot serve cache hits.

Measured cumulative programs (flagship step anatomy):

    encoder    PointEncoder forward on ONE cloud (kNN graph + 3 SetConvs)
    corr_cum   both clouds encoded + the truncated correlation build
    fwd1/fwdN  full model forward at 1 / N GRU iterations
    gru_fused  fwdN with ModelConfig.fused_gru=True (the Pallas fused
               MotionEncoder+ConvGRU kernel) — fwdN vs gru_fused is the
               fused-kernel A/B; not part of the telescoped breakdown
    fwdbwd     value_and_grad of the sequence loss (no optimizer)
    step       the full train step (fwd + bwd + adam)

Their pairwise differences telescope into the per-stage breakdown the
artifact schema guarantees sums to the measured total step time:

    encoder     = 2 x encoder              (both clouds)
    corr_init   = corr_cum - 2 x encoder   (correlation build alone)
    gru_forward = fwdN - corr_cum          (GRU loop + context encoder
                                            + heads — the rest of fwd)
    backward    = fwdbwd - fwdN
    optimizer   = step - fwdbwd

Runs identically on CPU and TPU (the host-fetch sync is what makes the
TPU numbers honest; on CPU it is merely free). Individual derived stages
can go slightly negative under timing noise — the validator checks the
telescoped sum, which is exact by construction, and flags negatives.

The telescoped breakdown doubles as a trace: ``obs.trace.
trace_from_step_profile`` maps it onto the ``pvraft_trace/v1`` span
schema (one ``train_step`` root, consecutive stage spans), so the serve
request plane and the train step share one decomposition format
(``scripts/step_profile.py --events``).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from pvraft_tpu.rng import DEFAULT_SEED, derive, host_rng

SCHEMA_VERSION = "pvraft_step_profile/v1"

# Cumulative host-synced programs, in ladder order. The tuple is THE
# step-anatomy enumeration and lives in the registry's pure-data module:
# ``ladder_programs`` builds the measured programs in this order, and
# ``pvraft_tpu/programs/catalog.py`` registers one ``profile.<stage>``
# ProgramSpec per entry (without importing this jax-heavy module) so the
# registry's verify gate traces the same ladder the profiler times.
from pvraft_tpu.programs.geometries import (
    PROFILE_BREAKDOWN_STAGES,
    PROFILE_LADDER_STAGES,
)

MEASUREMENTS = PROFILE_LADDER_STAGES

# Derived per-stage breakdown; telescopes to measurements["step"]["sec"].
# Declared in geometries (pure data) so the trace plane's validator can
# share the vocabulary jax-free.
BREAKDOWN_STAGES = PROFILE_BREAKDOWN_STAGES


def derive_breakdown(measurements: Dict[str, dict]) -> Dict[str, float]:
    """Telescoped per-stage seconds from the cumulative measurements."""
    sec = {k: measurements[k]["sec"] for k in MEASUREMENTS}
    return {
        "encoder": round(2 * sec["encoder"], 6),
        "corr_init": round(sec["corr_cum"] - 2 * sec["encoder"], 6),
        "gru_forward": round(sec["fwdN"] - sec["corr_cum"], 6),
        "backward": round(sec["fwdbwd"] - sec["fwdN"], 6),
        "optimizer": round(sec["step"] - sec["fwdbwd"], 6),
    }


def validate_step_profile(record: dict, rel_tol: float = 0.02) -> List[str]:
    """Schema problems of a step-profile record ([] = valid).

    Checks the keys ``artifacts/README.md`` indexes and the one property
    the artifact exists to certify: the per-stage breakdown sums to the
    measured total step time (telescoping makes this exact up to
    rounding; ``rel_tol`` absorbs the rounding)."""
    problems: List[str] = []
    for key in ("schema", "platform", "variant", "points", "batch", "iters",
                "truncate_k", "host_synced", "measurements", "breakdown_s",
                "total_step_s"):
        if key not in record:
            problems.append(f"missing key {key!r}")
    if problems:
        return problems
    if record["schema"] != SCHEMA_VERSION:
        problems.append(
            f"schema {record['schema']!r} != {SCHEMA_VERSION!r}")
    if record["host_synced"] is not True:
        problems.append("host_synced must be true (non-synced timings are "
                        "dispatch rates, not device work)")
    for name in MEASUREMENTS:
        entry = record["measurements"].get(name)
        if entry is None:
            problems.append(f"missing measurement {name!r}")
        elif "sec" not in entry:
            problems.append(
                f"measurement {name!r} has no 'sec' "
                f"({entry.get('error', 'no error recorded')})")
        elif not entry["sec"] > 0:
            problems.append(f"measurement {name!r} sec={entry['sec']} <= 0")
    bd = record["breakdown_s"]
    if set(bd) != set(BREAKDOWN_STAGES):
        problems.append(
            f"breakdown stages {sorted(bd)} != {sorted(BREAKDOWN_STAGES)}")
    if problems:
        return problems
    total = record["total_step_s"]
    sum_bd = sum(bd.values())
    if abs(sum_bd - total) > max(rel_tol * abs(total), 1e-4):
        problems.append(
            f"breakdown sums to {sum_bd:.6f}s but total_step_s is "
            f"{total:.6f}s (|diff| > {rel_tol:.0%})")
    negatives = [
        k for k, v in bd.items() if v < -max(rel_tol * abs(total), 1e-4)
    ]
    if negatives:
        # More than tolerance-level negative: the measurement ladder is
        # inconsistent (not just sub-tolerance timing noise).
        problems.append(
            f"negative derived stages {negatives} (timing noise larger "
            "than the stage; increase reps)")
    return problems


def make_encoder(cfg):
    """The standalone PointEncoder exactly as the profiled model embeds
    it (one definition for profile_step AND the registry's profile.*
    specs, so the ladder's encoder stage cannot drift from the model's)."""
    from pvraft_tpu.config import compute_dtype
    from pvraft_tpu.models.encoder import PointEncoder

    return PointEncoder(cfg.encoder_width, cfg.graph_k,
                        dtype=compute_dtype(cfg),
                        graph_chunk=cfg.graph_chunk,
                        graph_approx=cfg.approx_knn,
                        dense_vjp=cfg.scatter_free_vjp)


def ladder_programs(cfg, model, enc, params, enc_params, tx, opt_state,
                    pc1, pc2, mask, gt, iters, gamma=0.8, grad_dtype=None):
    """The cumulative program ladder, as ``(name, fn)`` pairs in
    ``MEASUREMENTS`` order — the single enumeration of the step's
    anatomy. ``profile_step`` times these; ``programs/catalog.py``
    registers each stage as a ``profile.*`` ProgramSpec so the registry
    inventory and the profiler can never enumerate different programs.
    Each ``fn(eps)`` perturbs its inputs by ``eps`` (fresh values defeat
    result memoization) and returns a scalar whose host fetch is the
    sync."""
    import jax
    import jax.numpy as jnp
    import optax

    from pvraft_tpu.engine.loss import sequence_loss
    from pvraft_tpu.engine.steps import maybe_cast_grads
    from pvraft_tpu.ops.corr import corr_init

    @jax.jit
    def f_encoder(eps):
        fmap, _ = enc.apply(enc_params, pc1 + eps)
        return jnp.sum(fmap.astype(jnp.float32))

    @jax.jit
    def f_corr_cum(eps):
        fmap1, _ = enc.apply(enc_params, pc1 + eps)
        fmap2, _ = enc.apply(enc_params, pc2 + eps)
        st = corr_init(fmap1, fmap2, pc2 + eps, cfg.truncate_k,
                       cfg.corr_chunk, approx=cfg.approx_topk)
        return jnp.sum(st.corr.astype(jnp.float32))

    def fwd(n_iters):
        @jax.jit
        def f(eps):
            flows, _ = model.apply(params, pc1 + eps, pc2 + eps, n_iters)
            return jnp.sum(flows[-1].astype(jnp.float32))

        return f

    # fwdN with the fused MotionEncoder+ConvGRU kernel: the param tree is
    # identical by construction (models/update.py holder modules), so the
    # SAME params apply — the stage pair (fwdN, gru_fused) is a pure
    # kernel A/B. Excluded from the telescoped breakdown: it re-times a
    # rung, it is not a new cumulative layer.
    import dataclasses as _dc

    fused_model = type(model)(_dc.replace(cfg, fused_gru=True))

    @jax.jit
    def f_gru_fused(eps):
        flows, _ = fused_model.apply(params, pc1 + eps, pc2 + eps, iters)
        return jnp.sum(flows[-1].astype(jnp.float32))

    def loss_fn(p, eps):
        flows, _ = model.apply(p, pc1 + eps, pc2 + eps, iters)
        return sequence_loss(flows, mask, gt, gamma)

    @jax.jit
    def f_fwdbwd(eps):
        loss, grads = jax.value_and_grad(loss_fn)(params, eps)
        gsum = sum(jnp.sum(jnp.abs(g).astype(jnp.float32))
                   for g in jax.tree_util.tree_leaves(grads))
        return loss + 0.0 * gsum

    @jax.jit
    def f_step(eps):
        loss, grads = jax.value_and_grad(loss_fn)(params, eps)
        grads = maybe_cast_grads(grads, grad_dtype)
        updates, _ = tx.update(grads, opt_state)
        new_params = optax.apply_updates(params, updates)
        psum = sum(jnp.sum(jnp.abs(q).astype(jnp.float32))
                   for q in jax.tree_util.tree_leaves(new_params))
        return loss + 0.0 * psum

    builders = {
        "encoder": f_encoder,
        "corr_cum": f_corr_cum,
        "fwd1": fwd(1),
        "fwdN": fwd(iters),
        "gru_fused": f_gru_fused,
        "fwdbwd": f_fwdbwd,
        "step": f_step,
    }
    # Order (and membership) comes from the declared enumeration: a
    # stage added to PROFILE_LADDER_STAGES without a builder here — or
    # a builder no stage names — fails loudly instead of silently
    # desynchronizing the profiler from the registry's profile.* specs.
    if set(builders) != set(MEASUREMENTS):
        raise ValueError(
            f"ladder builders {sorted(builders)} != declared stages "
            f"{sorted(MEASUREMENTS)} (update geometries."
            f"PROFILE_LADDER_STAGES and ladder_programs together)")
    return [(name, builders[name]) for name in MEASUREMENTS]


def profile_step(
    cfg,
    points: int = 8192,
    batch: int = 2,
    iters: int = 8,
    reps: int = 2,
    gamma: float = 0.8,
    lr: float = 1e-3,
    grad_dtype: Optional[str] = None,
    variant: str = "custom",
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Profile the flagship train step stage by stage; return the record.

    ``cfg`` is a :class:`~pvraft_tpu.config.ModelConfig`; every knob that
    changes the step's content (scatter_free_vjp, remat_policy,
    compute_dtype, use_pallas, approx_topk, ...) is honored, so A/B runs
    are one config swap apart. ``grad_dtype`` mirrors
    ``TrainConfig.grad_dtype`` through the same ``engine/steps`` cast.
    """
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from pvraft_tpu.models import PVRaft

    say = log or (lambda msg: None)
    model = PVRaft(cfg)
    platform = jax.devices()[0].platform

    rng = host_rng(DEFAULT_SEED, "profile.data")
    pc1 = jnp.asarray(
        rng.uniform(-1, 1, (batch, points, 3)).astype(np.float32))
    pc2 = jnp.asarray(
        rng.uniform(-1, 1, (batch, points, 3)).astype(np.float32))
    mask = jnp.ones((batch, points), jnp.float32)
    gt = pc2 - pc1
    # Init on a small cloud (params are point-count independent) — but it
    # must still hold >= truncate_k candidate points for corr_init.
    n_init = min(points, max(256, cfg.truncate_k))
    params = model.init(
        derive(DEFAULT_SEED, "model.init"),
        pc1[:, :n_init], pc2[:, :n_init], 2)
    tx = optax.adam(lr)
    opt_state = tx.init(params)

    enc = make_encoder(cfg)
    enc_params = enc.init(
        derive(DEFAULT_SEED, "encoder.init"), pc1[:, :n_init])

    programs = ladder_programs(
        cfg, model, enc, params, enc_params, tx, opt_state,
        pc1, pc2, mask, gt, iters, gamma=gamma, grad_dtype=grad_dtype)

    eps_counter = [0.0]

    def fresh_eps():
        eps_counter[0] += 1e-6
        return jnp.float32(eps_counter[0])

    record = {
        "schema": SCHEMA_VERSION,
        "platform": platform,
        "variant": variant,
        "points": points, "batch": batch, "iters": iters,
        "truncate_k": cfg.truncate_k,
        "host_synced": True,
        "config": {
            "compute_dtype": cfg.compute_dtype,
            "use_pallas": cfg.use_pallas,
            "approx_topk": cfg.approx_topk,
            "approx_knn": cfg.approx_knn,
            "scatter_free_vjp": cfg.scatter_free_vjp,
            "remat": cfg.remat,
            "remat_policy": cfg.remat_policy,
            "grad_dtype": grad_dtype or "float32",
        },
        "measurements": {},
    }
    for name, fn in programs:
        entry: dict = {}
        try:
            t0 = time.perf_counter()
            # float(np.asarray(...)): the host fetch IS the sync.
            float(np.asarray(fn(fresh_eps())))  # compile + first run
            entry["first_call_s"] = round(time.perf_counter() - t0, 2)
            dts = []
            for _ in range(reps):
                t0 = time.perf_counter()
                float(np.asarray(fn(fresh_eps())))
                dts.append(time.perf_counter() - t0)
            entry["sec_reps"] = [round(d, 6) for d in dts]
            entry["sec"] = round(min(dts), 6)
        except Exception as e:  # noqa: BLE001 — keep profiling other stages
            entry["error"] = repr(e)[:300]
        record["measurements"][name] = entry
        say(f"[step_profile] {name}: {entry}")

    meas = record["measurements"]
    if all("sec" in meas.get(k, {}) for k in MEASUREMENTS):
        record["breakdown_s"] = derive_breakdown(meas)
        record["total_step_s"] = meas["step"]["sec"]
        if iters > 1:
            record["per_iter_s"] = round(
                (meas["fwdN"]["sec"] - meas["fwd1"]["sec"]) / (iters - 1), 6)
    return record
