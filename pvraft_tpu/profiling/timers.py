"""Wall-clock profiling hooks.

The reference's only tracing facility is a commented-out autograd profiler
block (``tools/engine.py:136-139``). Here tracing is first-class but
optional: a ``jax.profiler`` trace context (TensorBoard-viewable) and a
``block_until_ready``-based step timer (SURVEY.md §5)."""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, List, Optional

import jax


@contextlib.contextmanager
def trace_context(log_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` when a dir is given, no-op otherwise."""
    if log_dir:
        with jax.profiler.trace(log_dir):
            yield
    else:
        yield


class StepTimer:
    """Wall-clock step timing with device sync."""

    def __init__(self) -> None:
        self.times: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *sync_on) -> float:
        for x in sync_on:
            jax.block_until_ready(x)
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        self.times.append(dt)
        return dt

    @property
    def mean(self) -> float:
        return sum(self.times) / max(1, len(self.times))
