"""Profiling subsystem.

Promoted from ``pvraft_tpu/utils/profiling.py`` (which remains as a
re-export shim): the wall-clock :class:`StepTimer` / ``trace_context``
primitives plus the per-stage train-step profiler that produces the
``artifacts/step_profile.json`` evidence record (the instrument the
round-5 perf correction demanded — BENCHMARKS.md).
"""

from pvraft_tpu.profiling.step_profiler import (  # noqa: F401
    BREAKDOWN_STAGES,
    MEASUREMENTS,
    SCHEMA_VERSION,
    derive_breakdown,
    ladder_programs,
    make_encoder,
    profile_step,
    validate_step_profile,
)
from pvraft_tpu.profiling.timers import StepTimer, trace_context  # noqa: F401
