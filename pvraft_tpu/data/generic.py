"""Dataset base and batch collation.

Host-side numpy pipeline replacing ``datasets/generic.py``. Key behavior
preserved:

  * exact-N sampling — random permutation subsample to ``nb_points``
    (``generic.py:181-191``) and reject-and-advance when a sample has fewer
    points (``generic.py:101-110``): walk to the next index until one with
    at least ``nb_points`` is found. This guarantees static device shapes,
    which is exactly what XLA wants;
  * items are dicts of float32 arrays: ``pc1 (N,3)``, ``pc2 (M,3)``,
    ``mask (N,)``, ``flow (N,3)``;
  * ``collate`` stacks items along a new leading batch axis (the reference
    ``Batch`` concatenated pre-unsqueezed tensors, ``generic.py:21-27``).
"""

from __future__ import annotations

from typing import Dict, Iterator, Optional, Sequence

import numpy as np

from pvraft_tpu.rng import host_rng

Item = Dict[str, np.ndarray]


class SceneFlowDataset:
    """Base class: subclasses implement ``load_sequence(idx)`` returning
    ``(pc1, pc2, mask, flow)`` with variable point counts."""

    def __init__(self, nb_points: int, seed: Optional[int] = None):
        self.nb_points = int(nb_points)
        self._seed = 0 if seed is None else int(seed)
        self._epoch = 0

    def set_epoch(self, epoch: int) -> None:
        """Advance the subsample randomness between epochs. Subsampling is
        seeded per (seed, epoch, idx) so items are deterministic and
        thread-safe under the prefetching loader, while still being
        resampled every epoch like the reference's stateful np.random
        (``generic.py:183-190``)."""
        self._epoch = int(epoch)

    def __len__(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    def load_sequence(self, idx: int):  # pragma: no cover - abstract
        raise NotImplementedError

    def _subsample(self, arr: np.ndarray, n: int, perm: np.ndarray) -> np.ndarray:
        return arr[perm[:n]]

    def __getitem__(self, idx: int) -> Item:
        # Reject-and-advance until a sample with enough points is found
        # (generic.py:101-110 walked idx+1 on exact-size mismatch).
        for probe in range(len(self)):
            j = (idx + probe) % len(self)
            pc1, pc2, mask, flow = self.load_sequence(j)
            if pc1.shape[0] >= self.nb_points and pc2.shape[0] >= self.nb_points:
                break
        else:
            raise RuntimeError("no sample with enough points")

        n = self.nb_points
        rng = host_rng(self._seed, "data.subsample", self._epoch, j)
        perm1 = rng.permutation(pc1.shape[0])
        perm2 = rng.permutation(pc2.shape[0])
        return {
            "pc1": self._subsample(pc1, n, perm1).astype(np.float32),
            "pc2": self._subsample(pc2, n, perm2).astype(np.float32),
            "mask": self._subsample(mask, n, perm1).astype(np.float32),
            "flow": self._subsample(flow, n, perm1).astype(np.float32),
        }


def collate(items: Sequence[Item]) -> Item:
    """Stack items into (B, ...) arrays."""
    return {k: np.stack([it[k] for it in items], axis=0) for k in items[0]}


def batches(
    dataset: SceneFlowDataset,
    batch_size: int,
    shuffle: bool = False,
    drop_last: bool = True,
    seed: int = 0,
    epoch: int = 0,
) -> Iterator[Item]:
    """Lazy serial epoch iterator; one collated batch at a time.

    ``epoch`` is folded into the shuffle seed so successive epochs see
    different orders (the reference got this from DataLoader's per-epoch
    reshuffle). Thin wrapper over the serial path of
    ``pvraft_tpu.data.loader.PrefetchLoader`` so the order/shuffle logic
    has a single implementation.
    """
    from pvraft_tpu.data.loader import PrefetchLoader

    loader = PrefetchLoader(
        dataset, batch_size, shuffle=shuffle, drop_last=drop_last,
        num_workers=0, seed=seed,
    )
    yield from loader.epoch(epoch)
