"""Synthetic scene-flow dataset for tests, CI and benchmarking.

The reference has no test fixtures at all (SURVEY.md §4); this generator
fills that role: random clouds moved by a random rigid transform plus noise,
with index-aligned ground truth (flow = pc2 - pc1, mask all ones — the same
convention as the preprocessed FT3D data,
``datasets/flyingthings3d_hplflownet.py:104-107``).
"""

from __future__ import annotations

import numpy as np

from pvraft_tpu.data.generic import SceneFlowDataset
from pvraft_tpu.rng import host_rng


def _random_rotation(rng: np.random.Generator, max_angle: float) -> np.ndarray:
    angles = rng.uniform(-max_angle, max_angle, size=3)
    cx, cy, cz = np.cos(angles)
    sx, sy, sz = np.sin(angles)
    rx = np.array([[1, 0, 0], [0, cx, -sx], [0, sx, cx]])
    ry = np.array([[cy, 0, sy], [0, 1, 0], [-sy, 0, cy]])
    rz = np.array([[cz, -sz, 0], [sz, cz, 0], [0, 0, 1]])
    return (rx @ ry @ rz).astype(np.float32)


class SyntheticDataset(SceneFlowDataset):
    """``n_objects=1`` (default): one global rigid transform — the original
    fixture every committed trajectory/threshold is calibrated on.
    ``n_objects>1``: FT3D-like scenes — points cluster into spatial blobs,
    each moved by its OWN rigid transform, so the flow field is only
    piecewise rigid and the correlation volume must disambiguate
    independently moving objects (the structure of FT3D's multi-object
    scenes, ``datasets/flyingthings3d_hplflownet.py`` data)."""

    def __init__(
        self,
        size: int = 64,
        nb_points: int = 2048,
        extra_points: int = 0,
        max_angle: float = 0.1,
        max_shift: float = 0.3,
        noise: float = 0.0,
        seed: int = 0,
        n_objects: int = 1,
    ):
        super().__init__(nb_points=nb_points, seed=seed)
        self.size = size
        self.extra_points = extra_points
        self.max_angle = max_angle
        self.max_shift = max_shift
        self.noise = noise
        self.seed = seed
        if n_objects < 1:
            raise ValueError(f"n_objects must be >= 1, got {n_objects}")
        self.n_objects = n_objects

    def __len__(self) -> int:
        return self.size

    def load_sequence(self, idx: int):
        rng = host_rng(self.seed, "data.synthetic", idx)
        n = self.nb_points + (rng.integers(0, self.extra_points + 1) if self.extra_points else 0)
        if self.n_objects == 1:
            pc1 = rng.uniform(-1.0, 1.0, size=(n, 3)).astype(np.float32)
            rot = _random_rotation(rng, self.max_angle)
            shift = rng.uniform(-self.max_shift, self.max_shift, size=3)
            pc2 = pc1 @ rot.T + shift.astype(np.float32)
        else:
            # Blobs around random centers, each with its own rigid motion.
            # Rotation is applied about the object's center (a rotation
            # about the origin would fling off-center blobs far away).
            counts = np.full(self.n_objects, n // self.n_objects)
            counts[: n % self.n_objects] += 1
            parts1, parts2 = [], []
            for c in counts:
                center = rng.uniform(-0.8, 0.8, size=3).astype(np.float32)
                blob = (center + rng.normal(0, 0.2, size=(c, 3))).astype(
                    np.float32)
                rot = _random_rotation(rng, self.max_angle)
                shift = rng.uniform(-self.max_shift, self.max_shift, size=3)
                moved = (blob - center) @ rot.T + center + shift
                parts1.append(blob)
                parts2.append(moved.astype(np.float32))
            order = rng.permutation(n)  # no block structure in the index
            pc1 = np.concatenate(parts1)[order]
            pc2 = np.concatenate(parts2)[order]
        if self.noise:
            pc2 = pc2 + rng.normal(0, self.noise, size=pc2.shape).astype(np.float32)
        flow = (pc2 - pc1).astype(np.float32)
        mask = np.ones((n,), np.float32)
        return pc1.astype(np.float32), pc2.astype(np.float32), mask, flow
