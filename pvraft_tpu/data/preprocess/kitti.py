"""KITTI scene-flow 2015 raw data -> index-aligned pc1/pc2.npy scenes.

Behavioral equivalent of ``data_preprocess/process_kitti.py:25-89`` +
``kitti_utils.py``: read the left color camera projection (P_rect_02) from
the calibration file, convert disp_occ_0/disp_occ_1 to depths (baseline
0.54 m), back-project pc1 at the original pixel grid and pc2 at the
flow-advected grid, keep pixels valid in both disparities and the flow.
The reference's per-pixel python double loop (``process_kitti.py:56-69``)
is replaced by a vectorized ``np.where``.
"""

from __future__ import annotations

import argparse
import os
from concurrent.futures import ProcessPoolExecutor

import numpy as np

from pvraft_tpu.data.preprocess.io_formats import (
    read_kitti_disparity,
    read_kitti_flow,
)

BASELINE_M = 0.54


def read_calib(path: str) -> np.ndarray:
    """P_rect_02 (3, 4) from a KITTI calib_cam_to_cam-style file."""
    with open(path) as fd:
        lines = [ln for ln in fd.readlines() if ln.startswith("P_rect_02")]
    if len(lines) != 1:
        raise ValueError(f"{path}: expected exactly one P_rect_02 line")
    vals = np.array([float(x) for x in lines[0].split()[1:]], np.float32)
    p = vals.reshape(3, 4)
    if p[0, 0] != p[1, 1] or p[0, 1] != 0 or p[1, 0] != 0:
        raise ValueError(f"{path}: unexpected projection structure")
    return p


def disparity_to_depth(disp: np.ndarray, valid: np.ndarray, focal_px: float):
    depth = focal_px * BASELINE_M / (disp + 1e-5)
    depth[~valid] = -1.0
    return depth


def backproject_kitti(
    depth: np.ndarray, p_rect: np.ndarray, px=None, py=None
) -> np.ndarray:
    """Pinhole back-projection with the full P_rect (incl. cx/cy/tx terms),
    x/y sign-flipped into the dataset's frame (``kitti_utils.py:5-26``)."""
    f = p_rect[0, 0]
    h, w = depth.shape
    if px is None:
        px = np.broadcast_to(np.arange(w, dtype=np.float32)[None, :], (h, w))
    if py is None:
        py = np.broadcast_to(np.arange(h, dtype=np.float32)[:, None], (h, w))
    const_x = p_rect[0, 2] * depth + p_rect[0, 3]
    const_y = p_rect[1, 2] * depth + p_rect[1, 3]
    x = (px * (depth + p_rect[2, 3]) - const_x) / f
    y = (py * (depth + p_rect[2, 3]) - const_y) / f
    pc = np.stack([x, y, depth], axis=-1).astype(np.float32)
    pc[..., :2] *= -1.0
    return pc


def process_frame(
    disp0_root: str, disp1_root: str, flow_root: str, calib_root: str,
    save_root: str, idx: int,
) -> int:
    sidx = f"{idx:06d}"
    p_rect = read_calib(os.path.join(calib_root, sidx + ".txt"))
    focal = float(p_rect[0, 0])

    disp1, valid1 = read_kitti_disparity(os.path.join(disp0_root, sidx + "_10.png"))
    disp2, valid2 = read_kitti_disparity(os.path.join(disp1_root, sidx + "_10.png"))
    depth1 = disparity_to_depth(disp1, valid1, focal)
    depth2 = disparity_to_depth(disp2, valid2, focal)

    flow, valid_flow = read_kitti_flow(os.path.join(flow_root, sidx + "_10.png"))
    valid_disp = np.logical_and(valid1, valid2)
    ok = np.logical_and(valid_disp, valid_flow)

    h, w = depth1.shape
    u = np.broadcast_to(np.arange(w, dtype=np.float32)[None, :], (h, w))
    v = np.broadcast_to(np.arange(h, dtype=np.float32)[:, None], (h, w))
    px2 = np.where(ok, u + flow[..., 0], 0.0).astype(np.float32)
    py2 = np.where(ok, v + flow[..., 1], 0.0).astype(np.float32)

    pc1 = backproject_kitti(depth1, p_rect)
    pc2 = backproject_kitti(depth2, p_rect, px=px2, py=py2)

    out = os.path.join(save_root, sidx)
    os.makedirs(out, exist_ok=True)
    np.save(os.path.join(out, "pc1.npy"), pc1[ok])
    np.save(os.path.join(out, "pc2.npy"), pc2[ok])
    return int(ok.sum())


def process_kitti(
    raw_root: str, calib_root: str, save_root: str, workers: int = 4,
    n_frames: int = 200,
) -> int:
    disp0 = os.path.join(raw_root, "disp_occ_0")
    disp1 = os.path.join(raw_root, "disp_occ_1")
    flow = os.path.join(raw_root, "flow_occ")
    with ProcessPoolExecutor(max_workers=workers) as pool:
        futs = [
            pool.submit(process_frame, disp0, disp1, flow, calib_root, save_root, i)
            for i in range(n_frames)
        ]
        for f in futs:
            f.result()
    return n_frames


def main(argv=None) -> None:
    p = argparse.ArgumentParser("preprocess KITTI scene flow 2015")
    p.add_argument("--raw_data_path", required=True,
                   help="dir containing disp_occ_0/disp_occ_1/flow_occ")
    p.add_argument("--calib_path", required=True)
    p.add_argument("--save_path", required=True)
    p.add_argument("--workers", type=int, default=4)
    a = p.parse_args(argv)
    n = process_kitti(a.raw_data_path, a.calib_path, a.save_path, a.workers)
    print(f"processed {n} frames")


if __name__ == "__main__":
    main()
