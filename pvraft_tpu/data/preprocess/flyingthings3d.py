"""FlyingThings3D-subset raw data -> index-aligned pc1/pc2.npy scenes.

Behavioral equivalent of
``data_preprocess/process_flyingthings3d_subset.py:24-77`` +
``flyingthings3d_utils.py``: back-project the left-camera disparity into a
camera-frame cloud (f=-1050 px, cx=479.5, cy=269.5, unit baseline), advect
pixels by the into-future optical flow and disparity change for the t+1
cloud, drop pixels occluded in either disparity or flow, optionally keep
only near points (z > -35 m). Point i of pc1 corresponds to point i of pc2
(the property the FT3D loader relies on for gt flow = pc2 - pc1).
"""

from __future__ import annotations

import argparse
import os
from concurrent.futures import ProcessPoolExecutor
from typing import Optional, Tuple

import numpy as np

from pvraft_tpu.data.preprocess.io_formats import read_flo, read_pfm, read_png16

F_PX = -1050.0
CX = 479.5
CY = 269.5


def backproject(
    disparity: np.ndarray,
    flow: Optional[np.ndarray] = None,
    f: float = F_PX,
    cx: float = CX,
    cy: float = CY,
) -> np.ndarray:
    """Disparity (+ optional pixel flow) -> (H, W, 3) camera-frame points.

    With unit baseline: depth = -f/disp; x = -(u - cx [+ flow_u])/disp,
    y = (v - cy [+ flow_v])/disp (``flyingthings3d_utils.py:4-33``).
    """
    h, w = disparity.shape
    u = np.broadcast_to(np.arange(w, dtype=np.float32)[None, :], (h, w))
    v = np.broadcast_to(np.arange(h, dtype=np.float32)[:, None], (h, w))
    du = flow[..., 0] if flow is not None else 0.0
    dv = flow[..., 1] if flow is not None else 0.0
    depth = -f / disparity
    x = -(u - cx + du) / disparity
    y = (v - cy + dv) / disparity
    return np.stack([x, y, depth], axis=-1).astype(np.float32)


def process_scene(
    raw_root: str, save_root: str, split: str, name: str, save_near: bool = False
) -> Tuple[int, int]:
    """Convert one frame; returns the saved (n_points, n_points)."""
    disp1 = read_pfm(os.path.join(raw_root, split, "disparity", "left", name + ".pfm"))
    disp_occ = read_png16(
        os.path.join(raw_root, split, "disparity_occlusions", "left", name + ".png")
    )
    disp_change = read_pfm(
        os.path.join(
            raw_root, split, "disparity_change", "left", "into_future", name + ".pfm"
        )
    )
    flow = read_flo(
        os.path.join(raw_root, split, "flow", "left", "into_future", name + ".flo")
    )
    flow_occ = read_png16(
        os.path.join(
            raw_root, split, "flow_occlusions", "left", "into_future", name + ".png"
        )
    )

    pc1 = backproject(disp1)
    pc2 = backproject(disp1 + disp_change, flow)

    valid = np.logical_and(disp_occ == 0, flow_occ == 0)
    pc1, pc2 = pc1[valid], pc2[valid]
    if save_near:
        near = np.logical_and(pc1[..., -1] > -35.0, pc2[..., -1] > -35.0)
        pc1, pc2 = pc1[near], pc2[near]

    out = os.path.join(save_root, split, name)
    os.makedirs(out, exist_ok=True)
    np.save(os.path.join(out, "pc1.npy"), pc1)
    np.save(os.path.join(out, "pc2.npy"), pc2)
    return pc1.shape[0], pc2.shape[0]


def process_flyingthings3d(
    raw_root: str,
    save_root: str,
    save_near: bool = False,
    workers: int = 4,
    splits=("train", "val"),
) -> int:
    jobs = []
    for split in splits:
        listing = os.path.join(raw_root, split, "disparity_change", "left", "into_future")
        for item in sorted(os.listdir(listing)):
            jobs.append((split, item.split(".")[0]))
    done = 0
    with ProcessPoolExecutor(max_workers=workers) as pool:
        futs = [
            pool.submit(process_scene, raw_root, save_root, s, n, save_near)
            for s, n in jobs
        ]
        for f in futs:
            f.result()
            done += 1
    return done


def main(argv=None) -> None:
    p = argparse.ArgumentParser("preprocess FlyingThings3D subset")
    p.add_argument("--raw_data_path", required=True)
    p.add_argument("--save_path", required=True)
    p.add_argument("--only_save_near_pts", action="store_true")
    p.add_argument("--workers", type=int, default=4)
    a = p.parse_args(argv)
    n = process_flyingthings3d(
        a.raw_data_path, a.save_path, a.only_save_near_pts, a.workers
    )
    print(f"processed {n} scenes")


if __name__ == "__main__":
    main()
