"""Readers for the raw file formats of the FT3D/KITTI pipelines.

Standard formats, implemented directly from their specs (the reference
carries similar readers in ``data_preprocess/IO.py`` / ``python_pfm.py``):

  * PFM (Portable Float Map) — FT3D disparity / disparity change;
  * Middlebury ``.flo`` — FT3D optical flow;
  * 16-bit PNGs — KITTI disparity (uint16/256) and flow
    ((uint16-2^15)/64 with a validity plane).
"""

from __future__ import annotations

import re
from typing import Tuple

import numpy as np

FLO_MAGIC = 202021.25


def read_pfm(path: str) -> np.ndarray:
    """Read a PFM image as float32 (H, W) or (H, W, 3), top row first."""
    with open(path, "rb") as f:
        header = f.readline().decode("latin-1").strip()
        if header == "PF":
            channels = 3
        elif header == "Pf":
            channels = 1
        else:
            raise ValueError(f"{path}: not a PFM file (header {header!r})")
        dims = f.readline().decode("latin-1")
        m = re.match(r"^\s*(\d+)\s+(\d+)\s*$", dims)
        if not m:
            raise ValueError(f"{path}: bad PFM dimensions {dims!r}")
        width, height = int(m.group(1)), int(m.group(2))
        scale = float(f.readline().decode("latin-1").strip())
        endian = "<" if scale < 0 else ">"
        data = np.frombuffer(
            f.read(width * height * channels * 4), dtype=endian + "f4"
        )
    img = data.reshape(height, width, channels) if channels == 3 else data.reshape(
        height, width
    )
    # PFM stores rows bottom-up.
    return np.flipud(img).astype(np.float32).copy()


def read_flo(path: str) -> np.ndarray:
    """Read a Middlebury .flo optical flow file -> (H, W, 2) float32."""
    with open(path, "rb") as f:
        magic = np.frombuffer(f.read(4), np.float32)[0]
        if magic != FLO_MAGIC:
            raise ValueError(f"{path}: bad .flo magic {magic}")
        width = int(np.frombuffer(f.read(4), np.int32)[0])
        height = int(np.frombuffer(f.read(4), np.int32)[0])
        data = np.frombuffer(f.read(width * height * 2 * 4), np.float32)
    return data.reshape(height, width, 2).copy()


def read_png16(path: str) -> np.ndarray:
    """Read a PNG preserving 16-bit depth (PIL/imageio silently downconvert
    16-bit RGB, so prefer cv2 when present; channel order normalized to RGB)."""
    try:
        import cv2

        arr = cv2.imread(path, cv2.IMREAD_UNCHANGED)
        if arr is None:
            raise IOError(f"cv2 failed to read {path}")
        if arr.ndim == 3:
            arr = arr[..., ::-1]  # BGR -> RGB
        return np.ascontiguousarray(arr)
    except ImportError:
        import imageio.v2 as imageio

        return np.asarray(imageio.imread(path))


def read_kitti_disparity(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI disparity PNG: uint16/256.0; 0 marks invalid."""
    arr = read_png16(path)
    valid = arr > 0
    disp = arr.astype(np.float32) / 256.0
    disp[~valid] = -1.0
    return disp, valid


def read_kitti_flow(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """KITTI optical-flow PNG: channels (u, v, valid); (x-2^15)/64."""
    arr = read_png16(path)
    valid = arr[..., -1] == 1
    flow = (arr[..., :-1].astype(np.float32) - 2.0**15) / 64.0
    return flow, valid
