"""Offline preprocessing: raw FlyingThings3D / KITTI -> pc1/pc2.npy scenes.

Equivalents of the reference ``data_preprocess/`` scripts (run once on the
host; pure numpy — no accelerator involvement)."""

from pvraft_tpu.data.preprocess.io_formats import read_flo, read_pfm
from pvraft_tpu.data.preprocess.flyingthings3d import process_flyingthings3d
from pvraft_tpu.data.preprocess.kitti import process_kitti

__all__ = ["read_flo", "read_pfm", "process_flyingthings3d", "process_kitti"]
