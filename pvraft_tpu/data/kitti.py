"""KITTI scene-flow 2015 dataset (HPLFlowNet preprocessing).

Equivalent of ``datasets/kitti_hplflownet.py``: 200 preprocessed scene
directories, filtered to the 142 with a non-empty line in the KITTI raw
mapping (``kitti_hplflownet.py:43-52``); ground points (both frames
y < -1.4) and far points (either frame z >= 35 m) are removed
(``:81-87``); mask all-ones, gt flow = pc2 - pc1 (``:89-93``).

Eval-only, matching the reference (its Trainer raises for KITTI,
``tools/engine.py:40-41``; KITTI is used zero-shot via test.py).
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

from pvraft_tpu.data.generic import SceneFlowDataset

KITTI_SCENES = 200

# Scene indices (of the 200 preprocessed dirs) with a non-empty line in the
# KITTI raw-data mapping — the 142-scene eval subset used by HPLFlowNet and
# the reference (``kitti_hplflownet.py:43-52``). Only membership matters to
# the filter, so we embed the index set rather than the mapping text; an
# external mapping file can still be supplied via ``mapping_path``.
KITTI_EVAL_INDICES = frozenset(
    [2, 3]
    + list(range(7, 82))
    + [83, 84, 85, 86]
    + list(range(88, 99))
    + list(range(105, 133))
    + list(range(141, 151))
    + [155]
    + list(range(157, 165))
    + [168, 169, 199]
)


class KITTI(SceneFlowDataset):
    def __init__(
        self,
        root_dir: str,
        nb_points: int,
        strict_sizes: bool = True,
        mapping_path: Optional[str] = None,
        seed: Optional[int] = None,
    ):
        super().__init__(nb_points=nb_points, seed=seed)
        self.root_dir = root_dir
        self.paths = self._scene_list(strict_sizes, mapping_path)

    def _scene_list(self, strict: bool, mapping_path: Optional[str]):
        root = os.path.realpath(os.path.expanduser(self.root_dir))
        # Leaf directories (no subdirectories) are scenes.
        leaves = sorted(
            d for d, subdirs, _ in os.walk(root) if not subdirs
        )
        if strict and len(leaves) != KITTI_SCENES:
            raise RuntimeError(
                f"expected {KITTI_SCENES} KITTI scenes, found {len(leaves)}"
            )
        if mapping_path is not None:
            with open(mapping_path) as fd:
                lines = [ln.strip() for ln in fd.readlines()]
            keep = {i for i, ln in enumerate(lines) if ln != ""}
        else:
            keep = KITTI_EVAL_INDICES
        return [p for p in leaves if int(os.path.basename(p)) in keep]

    def __len__(self) -> int:
        return len(self.paths)

    def native_paths(self, idx: int):
        """(pc1_path, pc2_path, flip_xz, filter_mode) for the native batch
        loader. filter_mode 1 applies the ground/depth row filter
        (``kitti_hplflownet.py:81-87``) inside the C++ assembler, mirroring
        ``load_sequence`` below."""
        scene = self.paths[idx]
        return (
            os.path.join(scene, "pc1.npy"),
            os.path.join(scene, "pc2.npy"),
            False,
            1,
        )

    def load_sequence(self, idx: int):
        scene = self.paths[idx]
        pc1 = np.load(os.path.join(scene, "pc1.npy")).astype(np.float32)
        pc2 = np.load(os.path.join(scene, "pc2.npy")).astype(np.float32)

        not_ground = ~np.logical_and(pc1[:, 1] < -1.4, pc2[:, 1] < -1.4)
        pc1, pc2 = pc1[not_ground], pc2[not_ground]
        near = np.logical_and(pc1[:, 2] < 35.0, pc2[:, 2] < 35.0)
        pc1, pc2 = pc1[near], pc2[near]

        mask = np.ones((pc1.shape[0],), np.float32)
        flow = pc2 - pc1
        return pc1, pc2, mask, flow
