from pvraft_tpu.data.generic import SceneFlowDataset, batches, collate
from pvraft_tpu.data.synthetic import SyntheticDataset
from pvraft_tpu.data.flyingthings3d import FT3D
from pvraft_tpu.data.kitti import KITTI
from pvraft_tpu.data.loader import PrefetchLoader

__all__ = [
    "SceneFlowDataset",
    "batches",
    "collate",
    "SyntheticDataset",
    "FT3D",
    "KITTI",
    "PrefetchLoader",
]
