"""FlyingThings3D (HPLFlowNet preprocessing) dataset.

Equivalent of ``datasets/flyingthings3d_hplflownet.py``: scenes are
directories of ``pc1.npy``/``pc2.npy`` written by the offline preprocessing
(see ``pvraft_tpu.data.preprocess``). Conventions preserved:

  * train/val both list ``train/0*`` (19,640 scenes); val = 2,000 indices
    from ``np.linspace`` over the sorted list, train = the rest
    (``flyingthings3d_hplflownet.py:57-69``); test = ``val/0*`` (3,824);
  * x and z axes are sign-flipped on load (``:100-102``);
  * points are index-aligned across frames: mask is all-ones and
    gt flow = pc2 - pc1 (``:104-107``).

``strict_sizes=False`` relaxes the reference's hard dataset-size asserts so
subsets (e.g. a tiny local copy) can be used for smoke runs.
"""

from __future__ import annotations

import glob
import os
from typing import Optional

import numpy as np

from pvraft_tpu.data.generic import SceneFlowDataset

FT3D_TRAIN_SIZE = 19640
FT3D_TEST_SIZE = 3824
FT3D_VAL_COUNT = 2000


class FT3D(SceneFlowDataset):
    def __init__(
        self,
        root_dir: str,
        nb_points: int,
        mode: str,
        strict_sizes: bool = True,
        seed: Optional[int] = None,
    ):
        super().__init__(nb_points=nb_points, seed=seed)
        if mode not in ("train", "val", "test"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        self.root_dir = root_dir
        self.filenames = self._file_list(strict_sizes)

    def _file_list(self, strict: bool):
        pattern = "train/0*" if self.mode in ("train", "val") else "val/0*"
        names = sorted(glob.glob(os.path.join(self.root_dir, pattern)))
        if self.mode in ("train", "val"):
            if strict and len(names) != FT3D_TRAIN_SIZE:
                raise RuntimeError(
                    f"expected {FT3D_TRAIN_SIZE} train scenes, found {len(names)}"
                )
            total = len(names)
            n_val = min(FT3D_VAL_COUNT, max(1, total // 10)) if total < FT3D_TRAIN_SIZE else FT3D_VAL_COUNT
            val_idx = set(np.linspace(0, total - 1, n_val).astype(int).tolist())
            if self.mode == "val":
                keep = sorted(val_idx)
            else:
                keep = [i for i in range(total) if i not in val_idx]
            names = [names[i] for i in keep]
        elif strict and len(names) != FT3D_TEST_SIZE:
            raise RuntimeError(
                f"expected {FT3D_TEST_SIZE} test scenes, found {len(names)}"
            )
        return names

    def __len__(self) -> int:
        return len(self.filenames)

    def native_paths(self, idx: int):
        """(pc1_path, pc2_path, flip_xz, filter_mode) for the native batch
        loader (filter_mode 0: no row filter)."""
        scene = self.filenames[idx]
        return (
            os.path.join(scene, "pc1.npy"),
            os.path.join(scene, "pc2.npy"),
            True,
            0,
        )

    def load_sequence(self, idx: int):
        scene = self.filenames[idx]
        clouds = []
        for name in ("pc1.npy", "pc2.npy"):
            pc = np.load(os.path.join(scene, name)).astype(np.float32)
            pc[..., 0] *= -1.0
            pc[..., -1] *= -1.0
            clouds.append(pc)
        pc1, pc2 = clouds
        mask = np.ones((pc1.shape[0],), np.float32)
        flow = pc2 - pc1
        return pc1, pc2, mask, flow
