"""Threaded prefetching batch loader.

Host-side replacement for the reference's ``DataLoader(num_workers=8)``
(``tools/engine.py:43-48``). Three paths:

  * ``num_workers=0`` — serial numpy loading;
  * threaded — python threads release the GIL inside numpy IO;
  * native — the C++ batch assembler (``pvraft_tpu/native/npy_loader.cc``)
    reads and subsamples scenes with a thread pool into preallocated
    arrays (opt-in; available for datasets exposing ``native_paths``).
"""

from __future__ import annotations

import collections
import queue
import threading
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from pvraft_tpu.data.generic import Item, SceneFlowDataset, collate
from pvraft_tpu.rng import host_rng


def device_prefetch(
    batches: Iterable[Item], put: Callable[[Item], Item], depth: int = 2
) -> Iterator[Item]:
    """Keep ``depth`` batches in flight to the device.

    ``jax.device_put``/``jnp.asarray`` only *enqueue* the host->device
    copy, so issuing the next batch's transfer before the current step is
    consumed overlaps H2D with compute — the role the reference's
    ``pin_memory``/``non_blocking`` copies play (``datasets/generic.py:
    54-66``). ``depth<=1`` degenerates to the unpipelined loop."""
    buf: "collections.deque[Item]" = collections.deque()
    for b in batches:
        buf.append(put(b))
        if len(buf) >= max(1, depth):
            yield buf.popleft()
    while buf:
        yield buf.popleft()


class PrefetchLoader:
    """Iterate collated batches with worker threads and a bounded queue."""

    def __init__(
        self,
        dataset: SceneFlowDataset,
        batch_size: int,
        shuffle: bool = False,
        drop_last: bool = True,
        num_workers: int = 4,
        prefetch: int = 4,
        seed: int = 0,
        native: bool = False,
        native_max_rows: int = 400_000,
        shard: tuple = (0, 1),
    ):
        """``shard=(rank, world)`` gives this loader rank ``rank``'s
        ``batch_size``-row block of every global batch (after the seeded
        shuffle, which is identical across ranks): the multi-host split of
        an epoch, the role torch's DistributedSampler plays. Block-cyclic
        rather than element-strided on purpose — the global batch that
        ``make_array_from_process_local_data`` assembles then holds the
        SAME rows on the SAME devices as a single-process run of the same
        global batch size. Per-batch math is then identical up to the
        cross-process collective runtime's reduction order (~1e-7 —
        tests/test_two_process.py asserts the Adam-amplified bound),
        instead of differing by a whole row-permutation of the batch.
        Default (0, 1) = all samples."""
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.num_workers = max(0, num_workers)
        self.prefetch = prefetch
        self.seed = seed
        rank, world = shard
        if not (0 <= rank < world):
            raise ValueError(f"shard rank {rank} outside world {world}")
        if world > 1 and not drop_last and len(dataset) % (batch_size * world):
            # Sharded epochs keep only full GLOBAL batches (see epoch()):
            # ranks running different step counts would deadlock the
            # collectives. That silently supersedes drop_last=False — up
            # to batch_size*world-1 tail samples per epoch would vanish,
            # which for an eval loader means skipped scenes and biased
            # means. Refuse instead of biasing; callers that accept the
            # truncation should pass drop_last=True explicitly.
            raise ValueError(
                f"drop_last=False with shard world={world} requires "
                f"len(dataset) ({len(dataset)}) divisible by "
                f"batch_size*world ({batch_size * world}): the sharded "
                f"epoch keeps only full global batches, so the "
                f"{len(dataset) % (batch_size * world)}-sample tail would "
                f"be silently dropped; pass drop_last=True to accept "
                f"truncation or pad/shard the dataset exactly"
            )
        self.shard = (rank, world)
        self.native_max_rows = native_max_rows
        self.native = False
        if native and hasattr(dataset, "native_paths"):
            try:
                from pvraft_tpu import native as native_mod

                self.native = native_mod.native_available()
            except Exception:
                self.native = False

    def __len__(self) -> int:
        world = self.shard[1]
        if world > 1:
            # Only full GLOBAL batches survive the shard split (see
            # epoch()); identical on every rank by construction.
            return len(self.dataset) // (self.batch_size * world)
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else -(-n // self.batch_size)

    def epoch(self, epoch: int = 0) -> Iterator[Item]:
        self.dataset.set_epoch(epoch)
        order = np.arange(len(self.dataset))
        if self.shuffle:
            host_rng(self.seed, "data.shuffle", epoch).shuffle(order)
        rank, world = self.shard
        if world > 1:
            # Truncate to FULL GLOBAL batches before slicing so every rank
            # sees the same batch count per epoch — ranks running different
            # step counts would deadlock the collectives and desynchronize
            # the LR schedule across hosts. Block-cyclic slice: rank r
            # takes rows [r*L, (r+1)*L) of each global batch of
            # G = batch_size * world rows (see __init__ docstring for why
            # not [rank::world]).
            g = self.batch_size * world
            n_full = (len(order) // g) * g
            order = (order[:n_full].reshape(-1, world, self.batch_size)
                     [:, rank, :].reshape(-1))
        starts = list(range(0, len(order), self.batch_size))
        if self.drop_last:
            starts = [s for s in starts if s + self.batch_size <= len(order)]

        if self.native:
            yield from self._native_epoch(order, starts, epoch)
        elif self.num_workers == 0:
            for s in starts:
                idx = order[s : s + self.batch_size]
                yield collate([self.dataset[int(i)] for i in idx])
        else:
            yield from self._threaded_epoch(order, starts)

    # -- native path --------------------------------------------------------

    def _native_epoch(self, order, starts, epoch: int) -> Iterator[Item]:
        """C++ batch assembly: threaded npy reads + optional row filter +
        subsampling into preallocated arrays. The reject-and-advance policy
        (``generic.py:101-110``) is applied per item: only undersized scenes
        are re-requested (at idx+1), the rest of the batch is kept."""
        from pvraft_tpu import native as native_mod

        ds = self.dataset
        n_pts = ds.nb_points
        threads = max(1, self.num_workers)
        for s in starts:
            idxs = [int(i) for i in order[s : s + self.batch_size]]
            pending = list(range(len(idxs)))  # batch rows still unfilled
            out = None
            for _attempt in range(len(ds) + 1):
                quads = [ds.native_paths(idxs[p]) for p in pending]
                pc1, pc2, mask, flow, status = native_mod.load_scene_batch(
                    [q[0] for q in quads],
                    [q[1] for q in quads],
                    [idxs[p] for p in pending],
                    n_pts,
                    self.native_max_rows,
                    seed=ds._seed,
                    epoch=epoch,
                    flip_xz=quads[0][2],
                    filter_mode=quads[0][3],
                    n_threads=threads,
                )
                if np.any(status < 0):
                    bad = int(np.argmax(status < 0))
                    raise IOError(
                        f"native loader failed on {quads[bad][0]} "
                        f"(status {int(status[bad])})"
                    )
                if out is None:  # first pass covers the whole batch
                    out = {"pc1": pc1, "pc2": pc2, "mask": mask, "flow": flow}
                else:
                    for row, p in enumerate(pending):
                        out["pc1"][p] = pc1[row]
                        out["pc2"][p] = pc2[row]
                        out["mask"][p] = mask[row]
                        out["flow"][p] = flow[row]
                retry = [p for row, p in enumerate(pending)
                         if status[row] != 1]
                if not retry:
                    break
                for p in retry:
                    idxs[p] = (idxs[p] + 1) % len(ds)
                pending = retry
            else:
                raise RuntimeError("no scene with enough points")
            yield out

    # -- threaded python path ------------------------------------------------

    def _threaded_epoch(self, order, starts) -> Iterator[Item]:
        todo: "queue.Queue[Optional[int]]" = queue.Queue()
        done: "dict[int, Item]" = {}
        done_lock = threading.Condition()
        errors: list[BaseException] = []
        stop = False  # guarded-by done_lock; True once the epoch ends

        for rank, _ in enumerate(starts):
            todo.put(rank)
        for _ in range(self.num_workers):
            todo.put(None)

        def worker():
            while True:
                rank = todo.get()
                if rank is None:
                    return
                try:
                    s = starts[rank]
                    idx = order[s : s + self.batch_size]
                    batch = collate([self.dataset[int(i)] for i in idx])
                except BaseException as e:  # surface in the main thread
                    with done_lock:
                        errors.append(e)
                        done_lock.notify_all()
                    return
                with done_lock:
                    # Bounded prefetch: stall if we're too far ahead of the
                    # consumer (next_rank tracked via popped entries). The
                    # stop flag breaks the stall when the consumer abandons
                    # the generator mid-epoch — without it a worker parked
                    # here re-armed its 0.5 s wait forever (one leaked
                    # spinning thread per abandoned epoch; threadcheck
                    # daemon-spawn sweep).
                    while (not stop and rank - min(done.keys(), default=rank)
                           > self.prefetch + self.num_workers):
                        done_lock.wait(timeout=0.5)
                    if stop:
                        return
                    done[rank] = batch
                    done_lock.notify_all()

        workers = [
            threading.Thread(target=worker, daemon=True)
            for _ in range(self.num_workers)
        ]
        for t in workers:
            t.start()

        try:
            for rank in range(len(starts)):
                with done_lock:
                    while rank not in done:
                        if errors:
                            raise errors[0]
                        done_lock.wait(timeout=0.5)
                    batch = done.pop(rank)
                    done_lock.notify_all()
                yield batch
        finally:
            # Shut the pool down whether the epoch completed or the
            # consumer walked away: wake stalled workers, drain the work
            # queue, re-post the exit sentinels, and join. The join has a
            # bounded timeout (a worker can be mid-collate inside numpy
            # IO); any straggler is a daemon and exits at its next
            # sentinel/stop check instead of spinning.
            with done_lock:
                stop = True
                done_lock.notify_all()
            try:
                while True:
                    todo.get_nowait()
            except queue.Empty:
                pass
            for _ in workers:
                todo.put(None)
            for t in workers:
                t.join(timeout=5.0)
