"""GRU update block.

Equivalent of ``model/update.py``: motion encoder, 1x1-conv GRU, and a flow
head whose spatial mixing is a SetConv on the context graph. All 1x1 convs
are Dense layers on the channel-last layout.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from pvraft_tpu.models.layers import SetConv
from pvraft_tpu.ops.geometry import Graph


class MotionEncoder(nn.Module):
    """``model/update.py:8-21``: mixes correlation features with the current
    flow; output is 61 learned channels concatenated with the raw flow."""

    hidden: int = 64
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, flow: jnp.ndarray, corr: jnp.ndarray) -> jnp.ndarray:
        cor = jax.nn.relu(nn.Dense(self.hidden, dtype=self.dtype, name="conv_corr")(corr))
        flo = jax.nn.relu(nn.Dense(self.hidden, dtype=self.dtype, name="conv_flow")(flow))
        h = jnp.concatenate([cor, flo], axis=-1)
        h = jax.nn.relu(nn.Dense(self.hidden - 3, dtype=self.dtype, name="conv")(h))
        return jnp.concatenate([h, flow.astype(h.dtype)], axis=-1)


class ConvGRU(nn.Module):
    """``model/update.py:24-40``: z/r/q gates via 1x1 convs. The hidden
    state stays float32 across iterations (gate matmuls may run in
    ``dtype``)."""

    hidden: int = 64
    dtype: Optional[jnp.dtype] = None

    @nn.compact
    def __call__(self, h: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
        hx = jnp.concatenate([h, x.astype(h.dtype)], axis=-1)
        z = jax.nn.sigmoid(nn.Dense(self.hidden, dtype=self.dtype, name="convz")(hx))
        r = jax.nn.sigmoid(nn.Dense(self.hidden, dtype=self.dtype, name="convr")(hx))
        rhx = jnp.concatenate([(r * h.astype(r.dtype)).astype(h.dtype), x.astype(h.dtype)], axis=-1)
        q = jnp.tanh(nn.Dense(self.hidden, dtype=self.dtype, name="convq")(rhx))
        h32 = h.astype(jnp.float32)
        return ((1.0 - z) * h32 + z * q).astype(jnp.float32)


class _DenseParams(nn.Module):
    """Declares exactly ``nn.Dense``'s param tree (kernel + bias) without
    computing: the fused-GRU path reads the raw weights for
    ``pack_gru_weights`` while keeping the param paths — and therefore
    the per-path init RNG folds — identical to the unfused Dense, so
    checkpoints are interchangeable bit for bit."""

    features: int

    @nn.compact
    def __call__(self, in_features: int):
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (in_features, self.features))
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        return kernel, bias


class _MotionEncoderParams(nn.Module):
    """:class:`MotionEncoder`'s param tree, raw (fused path)."""

    hidden: int = 64

    @nn.compact
    def __call__(self, corr_ch: int):
        wc, bc = _DenseParams(self.hidden, name="conv_corr")(corr_ch)
        wf, bf = _DenseParams(self.hidden, name="conv_flow")(3)
        wh, bh = _DenseParams(self.hidden - 3, name="conv")(2 * self.hidden)
        return wc, bc, wf, bf, wh, bh


class _ConvGRUParams(nn.Module):
    """:class:`ConvGRU`'s param tree, raw (fused path)."""

    hidden: int = 64

    @nn.compact
    def __call__(self, hx_ch: int):
        wz, bz = _DenseParams(self.hidden, name="convz")(hx_ch)
        wr, br = _DenseParams(self.hidden, name="convr")(hx_ch)
        wq, bq = _DenseParams(self.hidden, name="convq")(hx_ch)
        return wz, bz, wr, br, wq, bq


class FlowHead(nn.Module):
    """``model/update.py:57-72``: parallel Dense + SetConv over the hidden
    state, fused to a 3-channel flow delta (delta emitted in float32)."""

    dtype: Optional[jnp.dtype] = None
    dense_vjp: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, graph: Graph,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        out = nn.Dense(64, dtype=self.dtype, name="conv1")(x)
        out_set = SetConv(64, dtype=self.dtype, dense_vjp=self.dense_vjp,
                          name="setconv")(x, graph, mask)
        h = jnp.concatenate([out_set.astype(out.dtype), out], axis=-1)
        h = jax.nn.relu(nn.Dense(64, dtype=self.dtype, name="out_conv1")(h))
        return nn.Dense(3, dtype=jnp.float32, name="out_conv2")(h)


class UpdateBlock(nn.Module):
    """``model/update.py:75-87``.

    ``fused_gru=True`` replaces the MotionEncoder + ConvGRU pair with
    the single Pallas kernel ``ops/pallas/gru_iter.fused_gru_update``
    (parity test-gated, ``tests/test_fused_gru.py``); the param tree is
    declared through the ``_*Params`` holders above so it stays
    byte-identical to the unfused modules. ``tile_k`` feeds the kernel's
    plan-certified point-tile selection (the model's ``truncate_k``).
    The FlowHead stays unfused either way — its SetConv gathers graph
    neighbors across the whole cloud, which no point tile can hold."""

    hidden: int = 64
    dtype: Optional[jnp.dtype] = None
    dense_vjp: bool = False
    fused_gru: bool = False
    tile_k: int = 512

    @nn.compact
    def __call__(
        self,
        net: jnp.ndarray,
        inp: jnp.ndarray,
        corr: jnp.ndarray,
        flow: jnp.ndarray,
        graph: Graph,
        mask: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        if self.fused_gru:
            from pvraft_tpu.ops.pallas.gru_iter import (
                fused_gru_update,
                pack_gru_weights,
                pad_flow,
            )

            me = _MotionEncoderParams(
                self.hidden, name="motion_encoder")(corr.shape[-1])
            gru = _ConvGRUParams(
                self.hidden, name="gru")(2 * self.hidden + inp.shape[-1])
            weights = pack_gru_weights(me, gru, self.hidden, inp.shape[-1])
            dtype_name = ("float32" if self.dtype is None
                          else jnp.dtype(self.dtype).name)
            net = fused_gru_update(net, inp, corr, pad_flow(flow),
                                   weights, dtype_name, self.tile_k)
        else:
            motion = MotionEncoder(self.hidden, dtype=self.dtype, name="motion_encoder")(flow, corr)
            x = jnp.concatenate([inp.astype(motion.dtype), motion], axis=-1)
            net = ConvGRU(self.hidden, dtype=self.dtype, name="gru")(net, x)
        delta = FlowHead(dtype=self.dtype, dense_vjp=self.dense_vjp,
                         name="flow_head")(net, graph, mask)
        return net, delta
