"""Shared neural building blocks.

Channel-last ``(B, N, C)`` layout throughout: every 1x1 Conv1d/Conv2d of the
reference becomes a Dense layer — one MXU matmul — and GroupNorm reduces over
all non-batch axes with the channel axis grouped, which is exactly the torch
semantics for the reference's ``(B, C, ..., N)`` layout.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pvraft_tpu.ops.geometry import Graph, gather_neighbors


class PReLU(nn.Module):
    """Parametric ReLU with one shared slope, init 0.25 (torch default;
    used by the reference correlation convs ``model/corr.py:18,26``)."""

    slope_init: float = 0.25

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        alpha = self.param(
            "alpha", lambda key: jnp.asarray([self.slope_init], jnp.float32)
        ).astype(x.dtype)
        return jnp.where(x >= 0, x, alpha * x)


def group_norm(
    x: jnp.ndarray, name: str, mask: Optional[jnp.ndarray] = None
) -> jnp.ndarray:
    """GroupNorm(8) matching torch defaults (eps 1e-5, affine).

    ``mask`` (broadcastable to ``x``, True = valid) excludes padding
    positions from the mean/variance — the serve path's padded buckets
    must not shift real points' statistics (GroupNorm reduces over the
    point axis, so unmasked padding would). ``mask=None`` calls the
    module exactly as before: the default jaxpr is untouched."""
    gn = nn.GroupNorm(num_groups=8, epsilon=1e-5, name=name)
    if mask is None:
        return gn(x)
    # flax reshapes the mask's channel axis into (groups, C/g): it must
    # arrive at full rank/width, so broadcast the (B, N, 1...) mask up.
    return gn(x, mask=jnp.broadcast_to(mask, x.shape))


class SetConv(nn.Module):
    """DGCNN/PointNet++-style edge convolution.

    Re-design of the reference ``SetConv`` (``model/flot/gconv.py:4-85``):
    per-edge features are (neighbor_feat - center_feat, relative xyz),
    projected, group-normalized, max-pooled over the k neighbors, then two
    more 1x1 projections. All gathers are batched ``(B, N, k)`` index ops;
    all projections are Dense (bias-free, as the reference's convs).

    ``dtype`` (e.g. bfloat16) sets the matmul compute precision; params and
    GroupNorm statistics stay float32.

    ``dense_vjp`` (opt-in via ``ModelConfig.scatter_free_vjp``) swaps the
    neighbor gather's scatter-add backward and the k-pool max backward for
    the scatter-free formulations in ``ops/scatter_free.py``; the forward
    values and the default-path jaxpr are unchanged.

    ``mask`` (B, N), True = valid point: excludes padding rows from the
    GroupNorm statistics (serve bucket padding). Real points' values are
    otherwise untouched — their neighbor gathers only ever reach real
    points when the caller pads geometrically far away. ``mask=None``
    (default) leaves the jaxpr byte-identical to the unmasked layer.
    """

    out_ch: int
    dtype: Optional[jnp.dtype] = None
    dense_vjp: bool = False

    @nn.compact
    def __call__(
        self, x: jnp.ndarray, graph: Graph,
        mask: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        b, n, c = x.shape
        m3 = m4 = None
        if mask is not None:
            m4 = mask[:, :, None, None]                  # over (B, N, k, C)
            m3 = mask[:, :, None]                        # over (B, N, C)
        # Width rule of gconv.py:21-24.
        mid = (self.out_ch + c) // 2 if c % 2 == 0 else self.out_ch // 2

        nb = gather_neighbors(x, graph.neighbors,
                              dense_vjp=self.dense_vjp)     # (B, N, k, C)
        edge = nb - x[:, :, None, :]
        h = jnp.concatenate([edge, graph.rel_pos.astype(x.dtype)], axis=-1)

        h = nn.Dense(mid, use_bias=False, dtype=self.dtype, name="fc1")(h)
        h = group_norm(h, "gn1", mask=m4)
        h = jax.nn.leaky_relu(h, 0.1)
        if self.dense_vjp:
            from pvraft_tpu.ops.scatter_free import max_pool_argmax

            h = max_pool_argmax(h)                           # pool over k
        else:
            h = jnp.max(h, axis=2)                           # pool over k

        h = nn.Dense(self.out_ch, use_bias=False, dtype=self.dtype, name="fc2")(h)
        h = group_norm(h, "gn2", mask=m3)
        h = jax.nn.leaky_relu(h, 0.1)

        h = nn.Dense(self.out_ch, use_bias=False, dtype=self.dtype, name="fc3")(h)
        h = group_norm(h, "gn3", mask=m3)
        h = jax.nn.leaky_relu(h, 0.1)
        return h
