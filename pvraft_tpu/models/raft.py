"""PV-RAFT flagship model, TPU-native.

Equivalent of ``model/RAFTSceneFlow.py`` (stage 1) and
``model/RAFTSceneFlowRefine.py`` (stage 2), with the iterative refinement
expressed as ``nn.scan`` over a shared-parameter update step:

  * per-iteration ``coords2.detach()`` (``RAFTSceneFlow.py:41``) becomes
    ``lax.stop_gradient`` at the top of the scanned body;
  * the correlation cache is the explicit ``CorrState`` carried as a
    broadcast input instead of module-state mutation (``corr.py:31-42``);
  * outputs are stacked per-iteration flows ``(T, B, N, 3)`` rather than a
    Python list;
  * optional ``remat`` wraps the scanned step in ``jax.checkpoint`` to trade
    FLOPs for HBM during backprop (SURVEY.md §7 hard-part 4).
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.analysis.contracts import shapecheck
from pvraft_tpu.config import ModelConfig, compute_dtype, resolve_remat_policy
from pvraft_tpu.models.corr_block import CorrLookup
from pvraft_tpu.models.encoder import PointEncoder
from pvraft_tpu.models.layers import SetConv
from pvraft_tpu.models.update import UpdateBlock
from pvraft_tpu.ops.corr import CorrState, corr_init
from pvraft_tpu.ops.geometry import Graph


# checkpoint_name tag of the per-iteration correlation-lookup output; the
# "save_corr" remat policy saves exactly these values so the gather-heavy
# lookup never reruns in the backward pass.
CORR_CKPT_NAME = "corr_lookup"


def _remat_policy_fn(name: str):
    """Map a ``ModelConfig.remat_policy`` name to a jax.checkpoint policy
    callable (None = save nothing, the blanket full remat)."""
    if name == "full":
        return None
    from pvraft_tpu.compat import checkpoint_policies

    cp = checkpoint_policies()
    return {
        "dots": cp.dots_saveable,
        "dots_no_batch": cp.dots_with_no_batch_dims_saveable,
        "save_corr": cp.save_only_these_names(CORR_CKPT_NAME),
    }[name]


class UpdateIter(nn.Module):
    """One GRU refinement step (body of ``RAFTSceneFlow.py:40-46``)."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, carry, state: CorrState, inp, graph: Graph,
                 mask: Optional[jnp.ndarray] = None):
        net, coords2, coords1 = carry
        coords2 = lax.stop_gradient(coords2)
        corr = CorrLookup(self.cfg, name="corr_lookup")(state, coords2, mask)
        if self.cfg.remat_policy == "save_corr":
            # Tagged only when the policy consumes the tag, so the default
            # jaxpr stays byte-identical with the flag off.
            from pvraft_tpu.compat import checkpoint_name

            corr = checkpoint_name(corr, CORR_CKPT_NAME)
        flow = coords2 - coords1
        net, delta = UpdateBlock(
            self.cfg.hidden_dim, dtype=compute_dtype(self.cfg),
            dense_vjp=self.cfg.scatter_free_vjp,
            fused_gru=self.cfg.fused_gru, tile_k=self.cfg.truncate_k,
            name="update_block"
        )(net, inp, corr, flow, graph, mask)
        coords2 = coords2 + delta
        return (net, coords2, coords1), coords2 - coords1


class PVRaft(nn.Module):
    """Stage-1 model (``model/RAFTSceneFlow.py:10-50``).

    ``__call__(xyz1, xyz2, num_iters)`` returns ``(flows, graph1)`` where
    ``flows`` is ``(num_iters, B, N, 3)`` and ``graph1`` is the pc1 feature
    graph (consumed by the stage-2 refine head).

    When ``cfg.seq_shard`` is set and a ``mesh`` with a >1 ``seq`` axis is
    attached, the correlation cache is built sequence-parallel: both point
    axes shard over ``seq`` and the truncated top-k is assembled with a
    ppermute ring (``parallel/ring.py``) under ``jax.shard_map`` — the
    (N, N) volume (256 MB fp32 at 8,192 pts, ``model/corr.py:96-99``) is
    never resident on any one chip.
    """

    cfg: ModelConfig
    mesh: Optional[jax.sharding.Mesh] = None

    def _corr_init(self, fmap1, fmap2, xyz2, valid2=None):
        cfg = self.cfg
        mesh = self.mesh
        seq = mesh.shape.get("seq", 1) if mesh is not None else 1
        if not (cfg.seq_shard and seq > 1):
            return corr_init(
                fmap1, fmap2, xyz2, cfg.truncate_k, cfg.corr_chunk,
                approx=cfg.approx_topk, valid2=valid2,
            )
        if valid2 is not None:
            raise ValueError(
                "valid2 masking is not supported with seq_shard: the ring "
                "correlation assembles exact top-k across shards without a "
                "padding mask; serve on the unsharded correlation path"
            )
        from jax.sharding import PartitionSpec as P

        from pvraft_tpu.compat import shard_map
        from pvraft_tpu.parallel.ring import ring_corr_init

        n1, n2 = fmap1.shape[1], fmap2.shape[1]
        if n1 % seq or n2 % seq:
            raise ValueError(
                f"seq_shard: the mesh seq axis ({seq}) must divide the "
                f"point counts ({n1}, {n2})"
            )
        # Keep the batch axis on "data" when that axis is real AND the
        # actual batch divides it (bs=1 eval batches are replicated —
        # test.py:92 protocol — and must not be force-split).
        n_data = mesh.shape.get("data", 1)
        bspec = "data" if n_data > 1 and fmap1.shape[0] % n_data == 0 else None
        ring = shard_map(
            lambda a, b, c: ring_corr_init(a, b, c, cfg.truncate_k, "seq"),
            mesh=mesh,
            in_specs=(P(bspec, "seq", None),) * 2 + (P(bspec, "seq", None),),
            out_specs=CorrState(
                corr=P(bspec, "seq", None), xyz=P(bspec, "seq", None, None)
            ),
            check_vma=False,
        )
        return ring(fmap1, fmap2, xyz2)

    @shapecheck("B N 3", "B M 3", None, "B N", "B M", out=("T B N 3", None))
    @nn.compact
    def __call__(
        self, xyz1: jnp.ndarray, xyz2: jnp.ndarray, num_iters: int = 8,
        valid1: Optional[jnp.ndarray] = None,
        valid2: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Graph]:
        """``valid1``/``valid2`` (B, N) / (B, M) bool masks, True = real
        point — the serve path's padded-bucket inference. They exclude
        padding from every GroupNorm statistic and from the correlation
        truncation; combined with geometrically-far padding (the serve
        engine's job: padding must never enter a real point's kNN set)
        real points' flows match unpadded inference to float-reassociation
        precision. ``None`` (default) leaves the jaxpr byte-identical."""
        cfg = self.cfg
        dtype = compute_dtype(cfg)
        enc_mesh = self.mesh if cfg.seq_shard else None
        feat = PointEncoder(
            cfg.encoder_width, cfg.graph_k, dtype=dtype,
            graph_chunk=cfg.graph_chunk, graph_approx=cfg.approx_knn,
            dense_vjp=cfg.scatter_free_vjp,
            mesh=enc_mesh, name="feature_extractor"
        )
        fmap1, graph1 = feat(xyz1, mask=valid1)
        fmap2, _ = feat(xyz2, mask=valid2)

        state = self._corr_init(fmap1, fmap2, xyz2, valid2)

        # The reference context encoder rebuilds pc1's 32-NN graph
        # (extractor.py:18 via RAFTSceneFlow.py:31); the graph is a pure
        # function of the cloud, so share the feature extractor's.
        fct, graph_ctx = PointEncoder(
            cfg.encoder_width, cfg.graph_k, dtype=dtype,
            graph_chunk=cfg.graph_chunk, graph_approx=cfg.approx_knn,
            dense_vjp=cfg.scatter_free_vjp,
            mesh=enc_mesh, name="context_extractor"
        )(xyz1, graph=graph1, mask=valid1)
        net, inp = jnp.split(fct, [cfg.hidden_dim], axis=-1)
        net = jnp.tanh(net)
        inp = jax.nn.relu(inp)

        step_cls = UpdateIter
        policy_name = resolve_remat_policy(cfg)
        if policy_name is not None:
            policy = _remat_policy_fn(policy_name)
            # Omit the kwarg entirely for the blanket policy so the legacy
            # remat=True jaxpr is untouched.
            remat_kwargs = {} if policy is None else {"policy": policy}
            step_cls = nn.remat(UpdateIter, prevent_cse=False, **remat_kwargs)
        # The mask joins the scan as one more broadcast input only when
        # present, so the default scan signature (and jaxpr) is untouched.
        scan_in = (nn.broadcast, nn.broadcast, nn.broadcast)
        scan_args = (state, inp, graph_ctx)
        if valid1 is not None:
            scan_in += (nn.broadcast,)
            scan_args += (valid1,)
        scan = nn.scan(
            step_cls,
            variable_broadcast="params",
            split_rngs={"params": False},
            in_axes=scan_in,
            out_axes=0,
            length=num_iters,
            unroll=min(cfg.scan_unroll, num_iters),
        )
        carry = (net, xyz1, xyz1)
        _, flows = scan(cfg, name="update_iter")(carry, *scan_args)
        return flows, graph1


class PVRaftRefine(nn.Module):
    """Stage-2 model (``model/RAFTSceneFlowRefine.py:10-48``): the full
    stage-1 pipeline under ``stop_gradient`` (its ``torch.no_grad``,
    ``:23``), then a trainable residual SetConv head on the final flow
    using the pc1 feature graph (``model/refine.py:6-22``)."""

    cfg: ModelConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @shapecheck("B N 3", "B M 3", None, "B N", "B M", out="B N 3")
    @nn.compact
    def __call__(
        self, xyz1: jnp.ndarray, xyz2: jnp.ndarray, num_iters: int = 32,
        valid1: Optional[jnp.ndarray] = None,
        valid2: Optional[jnp.ndarray] = None,
    ) -> jnp.ndarray:
        flows, graph1 = PVRaft(self.cfg, mesh=self.mesh, name="backbone")(
            xyz1, xyz2, num_iters, valid1, valid2
        )
        flow = lax.stop_gradient(flows[-1])
        graph1 = Graph(graph1.neighbors, lax.stop_gradient(graph1.rel_pos))

        n = self.cfg.encoder_width
        dtype = compute_dtype(self.cfg)
        dense = self.cfg.scatter_free_vjp
        x = SetConv(n, dtype=dtype, dense_vjp=dense,
                    name="ref_conv1")(flow, graph1, valid1)
        x = SetConv(2 * n, dtype=dtype, dense_vjp=dense,
                    name="ref_conv2")(x, graph1, valid1)
        x = SetConv(4 * n, dtype=dtype, dense_vjp=dense,
                    name="ref_conv3")(x, graph1, valid1)
        delta = nn.Dense(3, dtype=jnp.float32, name="fc")(x)
        return flow + delta
