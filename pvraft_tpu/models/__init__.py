from pvraft_tpu.models.layers import PReLU, SetConv
from pvraft_tpu.models.encoder import PointEncoder
from pvraft_tpu.models.corr_block import CorrLookup
from pvraft_tpu.models.update import ConvGRU, FlowHead, MotionEncoder, UpdateBlock
from pvraft_tpu.models.raft import PVRaft, PVRaftRefine

__all__ = [
    "PReLU",
    "SetConv",
    "PointEncoder",
    "CorrLookup",
    "ConvGRU",
    "FlowHead",
    "MotionEncoder",
    "UpdateBlock",
    "PVRaft",
    "PVRaftRefine",
]
