"""Point-cloud feature encoder.

Equivalent of the reference ``FlotEncoder`` (``model/extractor.py:7-23``):
one kNN graph per cloud, three stacked SetConvs widening 3 -> w -> 2w -> 4w
(default w=32, output 128 channels).

With a ``mesh`` attached (seq axis > 1), the kNN graph is built
sequence-parallel via the ppermute ring (``parallel/ring.py``) instead of
the dense (N, N) distance matrix.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from pvraft_tpu.models.layers import SetConv
from pvraft_tpu.ops.geometry import Graph, build_graph


class PointEncoder(nn.Module):
    width: int = 32
    graph_k: int = 32
    dtype: Optional[jnp.dtype] = None
    graph_chunk: Optional[int] = None
    graph_approx: bool = False
    dense_vjp: bool = False
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(
        self, pc: jnp.ndarray, graph: Optional[Graph] = None,
        mask: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Graph]:
        """``graph`` short-circuits the kNN build — callers encoding the
        same cloud twice (feature + context extractors on pc1,
        ``RAFTSceneFlow.py:25,31``) share one graph instead of relying on
        XLA CSE to deduplicate the two identical builds.

        ``mask`` (B, N) excludes padding rows from the SetConv GroupNorm
        statistics (serve padded buckets); the kNN build itself is left
        unmasked — the serve engine places padding geometrically far so
        real points' neighbor sets are exactly the unpadded ones."""
        if graph is None:
            if self.mesh is not None and self.mesh.shape.get("seq", 1) > 1:
                from pvraft_tpu.parallel.ring import seq_sharded_graph

                graph = seq_sharded_graph(pc, self.graph_k, self.mesh)
            else:
                graph = build_graph(pc, self.graph_k, chunk=self.graph_chunk,
                                    approx=self.graph_approx,
                                    dense_vjp=self.dense_vjp)
        x = SetConv(self.width, dtype=self.dtype,
                    dense_vjp=self.dense_vjp, name="conv1")(pc, graph, mask)
        x = SetConv(2 * self.width, dtype=self.dtype,
                    dense_vjp=self.dense_vjp, name="conv2")(x, graph, mask)
        x = SetConv(4 * self.width, dtype=self.dtype,
                    dense_vjp=self.dense_vjp, name="conv3")(x, graph, mask)
        return x, graph
