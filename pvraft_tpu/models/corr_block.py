"""Learned heads over the point-voxel correlation lookup.

The paper's core contribution, re-built functionally: the cached
``CorrState`` (see ``pvraft_tpu.ops.corr``) is queried at the current
coordinate estimate through two branches — voxel-pyramid means and a kNN
point branch — then projected to 64 channels and summed
(reference ``CorrBlock.__call__``/convs, ``model/corr.py:15-29,44-93``).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from pvraft_tpu.config import ModelConfig, compute_dtype, resolve_use_pallas
from pvraft_tpu.models.layers import PReLU, group_norm
from pvraft_tpu.ops.corr import CorrState, knn_lookup
from pvraft_tpu.ops.voxel import voxel_bin_means


class CorrLookup(nn.Module):
    """``mask`` (B, N) excludes padding pc1 rows from the head GroupNorm
    statistics (serve padded buckets). The lookup itself needs no mask:
    with a masked ``corr_init`` every truncated candidate of a real point
    is a real pc2 point, and both branches reduce only over the candidate
    axis — per-point, padding-invariant."""

    cfg: ModelConfig

    @nn.compact
    def __call__(self, state: CorrState, coords: jnp.ndarray,
                 mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        cfg = self.cfg
        dtype = compute_dtype(cfg)
        m3 = m4 = None
        if mask is not None:
            m3 = mask[:, :, None]
            m4 = mask[:, :, None, None]

        if resolve_use_pallas(cfg):
            # Fused kernel: one VMEM pass produces both branches; the
            # (B, N, K, 3) rel tensor never hits HBM.
            from pvraft_tpu.ops.pallas.corr_lookup import fused_corr_lookup

            vox, knn_corr, rel_xyz = fused_corr_lookup(
                state.corr, state.xyz, coords,
                cfg.corr_levels, cfg.base_scale, cfg.resolution, cfg.corr_knn,
            )
        else:
            rel = state.xyz - coords[:, :, None, :]        # (B, N, K, 3)
            vox = voxel_bin_means(
                state.corr, rel, cfg.corr_levels, cfg.base_scale, cfg.resolution
            )
            knn_corr, rel_xyz = knn_lookup(
                state, rel, cfg.corr_knn, dense_vjp=cfg.scatter_free_vjp
            )

        # Voxel head (corr.py:15-20).
        v = nn.Dense(128, dtype=dtype, name="out_conv1")(vox)
        v = group_norm(v, "out_gn", mask=m3)
        v = PReLU(name="out_prelu")(v)
        v = nn.Dense(64, dtype=dtype, name="out_conv2")(v)

        # kNN head (corr.py:23-29).
        kf = jnp.concatenate([knn_corr[..., None], rel_xyz], axis=-1)
        kf = nn.Dense(64, dtype=dtype, name="knn_conv")(kf)   # (B, N, k, 64)
        kf = group_norm(kf, "knn_gn", mask=m4)
        kf = PReLU(name="knn_prelu")(kf)
        kf = jnp.max(kf, axis=2)
        kf = nn.Dense(64, dtype=dtype, name="knn_out")(kf)

        return v + kf
