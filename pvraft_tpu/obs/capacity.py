"""Committed capacity planning: the ``pvraft_capacity/v1`` artifact.

"How many v5e chips for X QPS at this SLO?" was a guess; this module
makes it a COMPUTED, COMMITTED artifact — a pure function of three
committed inputs, regenerate-and-compare pinned in ``scripts/lint.sh``
exactly like ``kernel_plan.json``:

* the cost surface (``artifacts/programs_costs.json`` via
  :class:`~pvraft_tpu.programs.costs.CostSurface`) supplies predicted
  device-seconds per (bucket, batch) serve dispatch;
* the committed ``pvraft_serve_request_points`` histogram (a
  ``pvraft_serve_load/v1`` artifact) supplies the live traffic mix —
  which fraction of requests lands in which production bucket;
* the SLO report (``pvraft_slo/v1``) supplies the latency bar the plan
  is provisioned against and the measured max-QPS evidence beside it.

The model: each bucket's per-request device-seconds is the best
certified batch size's predicted seconds divided by its batch (an
uncertified bucket uses the surface's flagged linear extrapolation —
every row records ``basis`` and ``extrapolated``, so a plan built on
uncertified geometry says so). Demand at a target QPS is the
traffic-mix-weighted sum; a chip contributes one device-second per
second, derated by a declared ``utilization_ceiling`` (headroom for the
SLO tail — running a queueing system at 100% utilization violates any
latency bar). ``chips_needed = ceil(demand / ceiling)``.

Platform honesty (the ``pvraft_bench/v1`` lesson, carried through every
plane of ISSUE 14): the *predictions* are TPU-topology numbers, but the
*measured* evidence block carries its own ``comparable`` flag — a
CPU-synthetic SLO run is machinery evidence and the plan records it as
such; only a TPU-measured report may be enforced against the plan.

No timestamps, no toolchain, stable rounding: the committed
``artifacts/capacity_report.json`` is byte-deterministic and
``scripts/capacity_report.py --check`` regenerates and compares it.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

CAPACITY_SCHEMA = "pvraft_capacity/v1"

# Default provisioning knobs (recorded in the artifact — the plan is a
# pure function of inputs INCLUDING these).
DEFAULT_QPS_LADDER = (10.0, 100.0, 1000.0)
DEFAULT_UTILIZATION_CEILING = 0.7


def _round(x: float, sig: int = 6) -> float:
    """Stable significant-figure rounding (the kernel-plan discipline)
    so the committed artifact is byte-deterministic."""
    return float(f"{x:.{sig}g}")


def _bucket_for(n: float, buckets: Sequence[int]) -> Optional[int]:
    for b in buckets:
        if n <= b:
            return int(b)
    return None


def chips_needed(device_seconds_per_sec: float,
                 utilization_ceiling: float) -> int:
    """ceil(demand / ceiling) with a tolerance for float rounding — ONE
    expression shared by the builder and the validator's recompute, so
    the committed number and the gate cannot disagree."""
    return int(math.ceil(
        device_seconds_per_sec / utilization_ceiling - 1e-9))


def build_capacity_report(
    surface,
    load_doc: Dict[str, Any],
    slo_doc: Dict[str, Any],
    buckets: Sequence[int],
    batch_sizes: Sequence[int],
    dtype: str,
    qps_ladder: Sequence[float] = DEFAULT_QPS_LADDER,
    utilization_ceiling: float = DEFAULT_UTILIZATION_CEILING,
    inputs: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Join cost surface + traffic histogram + SLO report into the
    ``pvraft_capacity/v1`` plan. ``surface`` is a
    :class:`~pvraft_tpu.programs.costs.CostSurface`; ``load_doc`` /
    ``slo_doc`` are the parsed committed artifacts; ``inputs`` records
    the artifact paths so ``--check`` can regenerate from exactly the
    same files."""
    if not 0 < utilization_ceiling <= 1:
        raise ValueError("utilization_ceiling must be in (0, 1]")
    rp = load_doc.get("request_points")
    if not rp:
        raise ValueError(
            "load artifact carries no request_points histogram "
            "(pre-trace artifact?)")
    edges = [float(e) for e in rp["edges"]]
    counts = [int(c) for c in rp["counts"]]
    if len(counts) != len(edges) + 1:
        raise ValueError("request_points: len(counts) != len(edges) + 1")

    # Traffic mix: a request in bin i is only known to be <= edges[i],
    # so it is planned into the smallest bucket >= the bin's upper edge
    # (the bucket-advisor rule). The overflow bin (beyond the last edge)
    # is unservable by any table derived from this histogram.
    table = sorted(int(b) for b in buckets)
    per_bucket_requests: Dict[int, int] = {b: 0 for b in table}
    unservable = counts[-1]
    for i, count in enumerate(counts[:-1]):
        if not count:
            continue
        bucket = _bucket_for(edges[i], table)
        if bucket is None:
            unservable += count
        else:
            per_bucket_requests[bucket] += count
    total = sum(counts)
    served = sum(per_bucket_requests.values())

    # Per-bucket device-seconds per request: best certified batch size
    # (lowest per-slot seconds — the throughput configuration), via the
    # surface's flagged extrapolation when the exact geometry is
    # uncertified.
    bucket_rows: List[Dict[str, Any]] = []
    for bucket in table:
        best = None
        for bs in sorted(int(b) for b in batch_sizes):
            est = surface.estimate_serve(bucket, bs, dtype)
            if est is None:
                continue
            per_req = est.device_seconds / bs
            if best is None or per_req < best[0]:
                best = (per_req, bs, est)
        row: Dict[str, Any] = {
            "bucket": bucket,
            "requests": per_bucket_requests[bucket],
            "traffic_fraction": (_round(per_bucket_requests[bucket] / served)
                                 if served else 0.0),
        }
        if best is None:
            row["seconds_per_request"] = None
        else:
            per_req, bs, est = best
            row.update({
                "batch": bs,
                "program": est.name,
                "seconds_per_request": _round(per_req),
                "basis": est.basis,
                "extrapolated": est.extrapolated,
            })
            if est.extrapolated:
                row["extrapolation_scale"] = _round(est.scale)
        bucket_rows.append(row)

    # Mix-weighted device-seconds one average request costs.
    priced = [r for r in bucket_rows
              if r["seconds_per_request"] is not None and r["requests"]]
    mean_seconds = (
        sum(r["seconds_per_request"] * r["requests"] for r in priced)
        / sum(r["requests"] for r in priced)) if priced else None

    demand_rows: List[Dict[str, Any]] = []
    if mean_seconds is not None:
        for qps in qps_ladder:
            demand = _round(qps * mean_seconds)
            # chips from the ROUNDED demand, with the same epsilon the
            # validator's recompute uses — the committed number and the
            # gate's arithmetic must be one expression.
            demand_rows.append({
                "qps": float(qps),
                "device_seconds_per_sec": demand,
                "chips_needed": chips_needed(demand, utilization_ceiling),
            })

    slo = slo_doc.get("slo", {}) if isinstance(slo_doc, dict) else {}
    platform = (load_doc.get("config", {}) or {}).get("platform")
    return {
        "schema": CAPACITY_SCHEMA,
        "inputs": dict(inputs or {}),
        "bucket_table": table,
        "batch_sizes": sorted(int(b) for b in batch_sizes),
        "dtype": dtype,
        "utilization_ceiling": float(utilization_ceiling),
        "traffic": {
            "requests": total,
            "served_by_table": served,
            "unservable": unservable,
            "mean_device_seconds_per_request": (
                _round(mean_seconds) if mean_seconds is not None else None),
        },
        "per_bucket": bucket_rows,
        "demand": demand_rows,
        # The measured side, honesty-flagged: what the committed SLO/
        # loadgen evidence actually showed, on what platform. The
        # predictions above are TPU-topology numbers; only a TPU-
        # measured report may be enforced against them.
        "measured_evidence": {
            "slo_p99_ms": slo.get("p99_ms"),
            "max_qps_under_slo": slo_doc.get("max_qps_under_slo"),
            "platform": platform if isinstance(platform, str) else "unknown",
            "comparable": platform == "tpu",
        },
    }


# ---------------------------------------------------------------- validate --


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_capacity(doc: Any, path: str = "<capacity>") -> List[str]:
    """Schema problems of a ``pvraft_capacity/v1`` artifact ([] =
    valid). The headline numbers are RECOMPUTED, not trusted: a
    hand-edited chips_needed that contradicts its own demand row (or a
    traffic fraction that exceeds 1) fails the gate."""
    if not isinstance(doc, dict):
        return [f"{path}: artifact is {type(doc).__name__}, not an object"]
    problems: List[str] = []
    if doc.get("schema") != CAPACITY_SCHEMA:
        problems.append(
            f"{path}: schema {doc.get('schema')!r} != {CAPACITY_SCHEMA!r}")
    for key in ("inputs", "bucket_table", "dtype", "utilization_ceiling",
                "traffic", "per_bucket", "demand", "measured_evidence"):
        if key not in doc:
            problems.append(f"{path}: missing field {key!r}")
    if problems:
        return problems
    ceiling = doc["utilization_ceiling"]
    if not _is_num(ceiling) or not 0 < ceiling <= 1:
        problems.append(
            f"{path}: utilization_ceiling {ceiling!r} must be in (0, 1]")
    if not isinstance(doc["per_bucket"], list) or not doc["per_bucket"]:
        problems.append(f"{path}: per_bucket must be a non-empty list")
        return problems
    frac_total = 0.0
    for i, row in enumerate(doc["per_bucket"]):
        where = f"{path}: per_bucket[{i}]"
        if not isinstance(row, dict):
            problems.append(f"{where}: not an object")
            continue
        for key in ("bucket", "requests", "traffic_fraction",
                    "seconds_per_request"):
            if key not in row:
                problems.append(f"{where}: missing {key!r}")
        spr = row.get("seconds_per_request")
        if spr is not None:
            if not _is_num(spr) or spr <= 0:
                problems.append(
                    f"{where}: seconds_per_request {spr!r} must be a "
                    "positive number or null")
            if "extrapolated" in row \
                    and not isinstance(row["extrapolated"], bool):
                problems.append(
                    f"{where}: extrapolated must be a bool — an "
                    "uncertified-geometry prediction must say so")
        if _is_num(row.get("traffic_fraction")):
            frac_total += row["traffic_fraction"]
    # Tolerance scales with the per-row rounding granularity: each
    # fraction is _round()ed to 6 significant figures (absolute error
    # up to 5e-7 for values <= 1), so an n-row plan can legitimately
    # sum to 1 + n * 5e-7.
    if frac_total > 1.0 + 5e-7 * len(doc["per_bucket"]):
        problems.append(
            f"{path}: traffic fractions sum to {frac_total:.7f} > 1")
    if not isinstance(doc["demand"], list):
        problems.append(f"{path}: demand must be a list")
        return problems
    for i, row in enumerate(doc["demand"]):
        where = f"{path}: demand[{i}]"
        if not isinstance(row, dict) or not all(
                _is_num(row.get(k)) for k in
                ("qps", "device_seconds_per_sec", "chips_needed")):
            problems.append(
                f"{where}: must carry numeric qps / "
                "device_seconds_per_sec / chips_needed")
            continue
        if _is_num(ceiling) and 0 < ceiling <= 1:
            want = chips_needed(row["device_seconds_per_sec"], ceiling)
            if row["chips_needed"] != want:
                problems.append(
                    f"{where}: chips_needed {row['chips_needed']} != "
                    f"ceil({row['device_seconds_per_sec']} / {ceiling}) "
                    f"= {want}")
    ev = doc["measured_evidence"]
    if not isinstance(ev, dict) \
            or not isinstance(ev.get("comparable"), bool):
        problems.append(
            f"{path}: measured_evidence.comparable must be a bool")
    elif ev["comparable"] and ev.get("platform") != "tpu":
        problems.append(
            f"{path}: measured_evidence.comparable=true on platform "
            f"{ev.get('platform')!r} — only TPU-measured evidence may "
            "be enforced against the plan (the pvraft_bench/v1 rule)")
    return problems


def validate_capacity_file(path: str) -> List[str]:
    from pvraft_tpu.obs.loading import load_json_artifact

    doc, problems = load_json_artifact(path)
    if problems:
        return problems
    return validate_capacity(doc, path=path)
