"""Calibration evidence: the ``pvraft_cost_calibration/v1`` artifact.

The committed proof that the cost surface's predictions were measured
against a REAL loadgen run (``scripts/serve_calibration.py``): one
record per (bucket, batch, dtype) with predicted vs measured
device-seconds, the prediction's basis/extrapolation flags, and the
platform-honesty ``comparable`` flag — plus the identity ledger: the
``requests == responses + Σrejected + in_flight`` reconciliation was
polled from atomic Prometheus renders THROUGHOUT the run and must have
held at every snapshot (``identity.violations == 0`` is a schema
requirement, not a hope).

Platform honesty is structural (the ``pvraft_bench/v1`` lesson carried
through ISSUE 14): ``comparable: true`` is valid ONLY on platform
"tpu" — a CPU wall clock recorded beside an XLA optimal-seconds
prediction is evidence the machinery works, never evidence the model is
calibrated, and the validator makes the confusion unrepresentable.

``python -m pvraft_tpu.obs validate-calibration`` is the CLI (a
``scripts/lint.sh`` stage over the committed artifact).
"""

from __future__ import annotations

from typing import Any, List

CALIBRATION_SCHEMA = "pvraft_cost_calibration/v1"

_REQUIRED = ("schema", "surface", "platform", "dtype", "identity",
             "records", "config")
_RECORD_REQUIRED = ("bucket", "batch", "dtype", "n", "predicted_s",
                    "measured_s", "ratio", "comparable")


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_calibration(doc: Any,
                         path: str = "<calibration>") -> List[str]:
    """Schema problems of one calibration artifact ([] = valid)."""
    if not isinstance(doc, dict):
        return [f"{path}: artifact is {type(doc).__name__}, not an object"]
    problems: List[str] = []
    if doc.get("schema") != CALIBRATION_SCHEMA:
        problems.append(
            f"{path}: schema {doc.get('schema')!r} != "
            f"{CALIBRATION_SCHEMA!r}")
    for key in _REQUIRED:
        if key not in doc:
            problems.append(f"{path}: missing field {key!r}")
    if problems:
        return problems
    if not isinstance(doc["platform"], str) or not doc["platform"]:
        problems.append(f"{path}: platform must be a non-empty string")
    identity = doc["identity"]
    if not isinstance(identity, dict) \
            or not isinstance(identity.get("snapshots"), int) \
            or not isinstance(identity.get("violations"), int):
        problems.append(
            f"{path}: identity must carry int snapshots/violations")
    else:
        if identity["snapshots"] < 1:
            problems.append(
                f"{path}: identity.snapshots {identity['snapshots']} — "
                "evidence with no polled snapshots proves nothing")
        if identity["violations"] != 0:
            problems.append(
                f"{path}: identity.violations "
                f"{identity['violations']} != 0 — the reconciliation "
                "identity must hold at EVERY polled snapshot")
    records = doc["records"]
    if not isinstance(records, list) or not records:
        problems.append(f"{path}: records must be a non-empty list")
        return problems
    for i, rec in enumerate(records):
        where = f"{path}: records[{i}]"
        if not isinstance(rec, dict):
            problems.append(f"{where}: not an object")
            continue
        for key in _RECORD_REQUIRED:
            if key not in rec:
                problems.append(f"{where}: missing {key!r}")
        if not isinstance(rec.get("comparable"), bool):
            problems.append(f"{where}: comparable must be a bool")
        elif rec["comparable"] and doc.get("platform") != "tpu":
            problems.append(
                f"{where}: comparable=true on platform "
                f"{doc.get('platform')!r} — only TPU measurements may "
                "be enforced against the TPU-topology prediction")
        for key in ("predicted_s", "measured_s"):
            if key in rec and (not _is_num(rec[key]) or rec[key] < 0):
                problems.append(
                    f"{where}: {key}={rec.get(key)!r} must be a "
                    "number >= 0")
        # The ratio is recomputed, not trusted.
        if all(_is_num(rec.get(k)) for k in ("predicted_s", "measured_s",
                                             "ratio")) \
                and rec["predicted_s"] > 0:
            want = rec["measured_s"] / rec["predicted_s"]
            if abs(rec["ratio"] - want) > max(1e-3, 1e-3 * want):
                problems.append(
                    f"{where}: ratio {rec['ratio']} != measured/"
                    f"predicted = {want:.4f}")
        if isinstance(rec.get("n"), int) and rec["n"] < 1:
            problems.append(f"{where}: n must be >= 1")
    return problems


def validate_calibration_file(path: str) -> List[str]:
    from pvraft_tpu.obs.loading import load_json_artifact

    doc, problems = load_json_artifact(path)
    if problems:
        return problems
    return validate_calibration(doc, path=path)
