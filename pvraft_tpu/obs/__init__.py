"""Run telemetry subsystem.

Five pillars (ISSUEs 3 + 7 / ROADMAP "run-health telemetry"):

* :mod:`pvraft_tpu.obs.monitors` — in-jit numerics monitors returned as
  an extra metrics leaf of the train step (``TrainConfig.telemetry``
  gated; default-off jaxpr byte-identical);
* :mod:`pvraft_tpu.obs.events` — the ``pvraft_events/v1`` structured
  JSONL event log + validator, with :class:`RunTelemetry` fanning the
  same stream out to TensorBoard and the text log;
* :mod:`pvraft_tpu.obs.divergence` — trailing-window divergence
  detection and ``pvraft_snapshot/v1`` crash snapshots, replayed by
  ``scripts/run_doctor.py``;
* :mod:`pvraft_tpu.obs.trace` — request-level span tracing
  (``pvraft_trace/v1``): per-stage decomposition of serve requests and
  profiled train steps, riding the event stream as ``span`` records;
* :mod:`pvraft_tpu.obs.slo` — the ``pvraft_slo/v1`` evidence report
  joining loadgen artifacts with trace spans (per-(bucket, batch,
  dtype) stage quantiles, max QPS under a p99 SLO);
* the performance plane (ISSUE 10): :mod:`pvraft_tpu.obs.retrace`
  (recompile watchdog — ``recompile`` events, ``--strict_retrace``),
  :mod:`pvraft_tpu.obs.device_memory` (``device_memory`` events +
  ``pvraft_device_hbm_bytes`` gauge), and :mod:`pvraft_tpu.obs.bench`
  (the ``pvraft_bench/v1`` schema behind ``scripts/bench_compare.py``;
  the cost/HBM inventory lives with the registry in
  ``pvraft_tpu/programs/costs.py``);
* the cost-calibration plane (ISSUE 14):
  :mod:`pvraft_tpu.obs.capacity` (the ``pvraft_capacity/v1`` committed
  capacity plan — chips-needed-at-SLO as a pure function of the cost
  surface + committed traffic/SLO evidence),
  :mod:`pvraft_tpu.obs.calibration` (the
  ``pvraft_cost_calibration/v1`` predicted-vs-measured evidence
  schema), and :mod:`pvraft_tpu.obs.loading` (the shared committed-
  artifact file-contract loader every validator reads through).
"""

from pvraft_tpu.obs.bench import (  # noqa: F401
    BENCH_SCHEMA,
    validate_bench,
    validate_bench_file,
)
from pvraft_tpu.obs.calibration import (  # noqa: F401
    CALIBRATION_SCHEMA,
    validate_calibration,
    validate_calibration_file,
)
from pvraft_tpu.obs.capacity import (  # noqa: F401
    CAPACITY_SCHEMA,
    build_capacity_report,
    validate_capacity,
    validate_capacity_file,
)
from pvraft_tpu.obs.device_memory import (  # noqa: F401
    DeviceMemoryMonitor,
    sample_device_memory,
)
from pvraft_tpu.obs.divergence import (  # noqa: F401
    SNAPSHOT_SCHEMA,
    DivergenceDetector,
    Trip,
    dump_snapshot,
    load_snapshot,
)
from pvraft_tpu.obs.events import (  # noqa: F401
    EVENT_TYPES,
    SCHEMA_VERSION,
    EventLog,
    RunTelemetry,
    run_metadata,
    sanitize,
    validate_event,
    validate_events,
    validate_events_file,
)
from pvraft_tpu.obs.monitors import (  # noqa: F401
    TELEMETRY_LEAVES,
    delta_flow_norms,
    global_norm,
    nonfinite_count,
    telemetry_leaves,
)
from pvraft_tpu.obs.retrace import (  # noqa: F401
    RetraceError,
    RetraceWatchdog,
    args_signature,
)
from pvraft_tpu.obs.slo import (  # noqa: F401
    SLO_SCHEMA,
    build_slo_report,
    validate_slo_report,
    validate_slo_report_file,
)
from pvraft_tpu.obs.trace import (  # noqa: F401
    SERVE_STAGES,
    TRACE_SCHEMA,
    RequestTrace,
    Tracer,
    collect_traces,
    trace_from_step_profile,
    validate_trace_artifact,
    validate_trace_artifact_file,
)
