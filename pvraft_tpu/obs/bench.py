"""``pvraft_bench/v1``: schema + validator + comparison for bench.py.

``bench.py`` prints ONE JSON line; until now it was schema-less, the
platform lived inside a free-text ``note``, and nothing stopped a
CPU-fallback run from being ratioed against a TPU baseline — the
``BENCH_r05.json`` failure mode: ``vs_baseline: 0.0`` with the only
explanation buried in ``"note": "accelerator unreachable … cpu
fallback"``. This module makes the contract machine-checkable:

* ``platform`` and ``comparable`` are REQUIRED, first-class fields;
* ``comparable: false`` forces ``vs_baseline == 0.0`` (an incomparable
  run may never carry a ratio), and any non-TPU platform forces
  ``comparable: false`` (the baseline is the reference per-GPU rate —
  only a TPU chip measurement may be ratioed against it);
* :func:`compare` refuses cross-platform / config-mismatched pairs
  outright and applies an explicit noise band before calling anything a
  regression — ``scripts/bench_compare.py`` is the CLI, wired into
  ``scripts/lint.sh`` and CI over the committed baseline artifact.

The module itself is pure stdlib (no jax, no numpy); note that
importing it through the ``pvraft_tpu.obs`` package pays the package's
jax import — ``bench.py``'s jax-free parent doesn't import it at all
(it only WRITES the fields), and the consumers
(``scripts/bench_compare.py``, ``python -m pvraft_tpu.obs
validate-bench``) are separate processes where that import is fine.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

BENCH_SCHEMA = "pvraft_bench/v1"

REQUIRED_FIELDS = ("schema", "metric", "value", "unit", "vs_baseline",
                   "platform", "comparable")
OPTIONAL_FIELDS = (
    "variant", "step_strategy", "ab_flags", "dt_reps", "dt_spread",
    "timing_reps", "steps_per_rep", "eval_scenes_per_sec",
    "eval_scenes_per_sec_scanned", "eval_strategy", "eval_detail",
    "note", "baseline_note",
)

# Fields that must match between two artifacts for a comparison to mean
# anything: same chip family, same measured configuration (the unit
# string encodes points/iters/bs), same model variant, same armed A/B
# levers. ("step_strategy" is deliberately NOT here: the bench reports
# its best honest training loop, and a strategy change is a legitimate
# speedup/regression, not an apples/oranges error.)
COMPARE_KEYS = ("platform", "unit", "variant", "ab_flags")

# Noise floor for the regression band when neither artifact recorded a
# run-to-run spread: the CPU fallback's observed round-over-round drift
# was ~10% (round-3 verdict), and TPU runs carry dt_spread explicitly.
DEFAULT_NOISE = 0.10


def _is_num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def validate_bench(doc: Any, path: str = "<bench>") -> List[str]:
    """Schema problems of one bench artifact ([] = valid)."""
    if not isinstance(doc, dict):
        return [f"{path}: artifact is {type(doc).__name__}, not an object"]
    problems: List[str] = []
    for key in REQUIRED_FIELDS:
        if key not in doc:
            problems.append(f"{path}: missing required field {key!r}")
    if problems:
        return problems
    if doc["schema"] != BENCH_SCHEMA:
        problems.append(
            f"{path}: schema {doc['schema']!r} != {BENCH_SCHEMA!r}")
    if not isinstance(doc["metric"], str) or not doc["metric"]:
        problems.append(f"{path}: metric must be a non-empty string")
    if not _is_num(doc["value"]) or doc["value"] < 0:
        problems.append(
            f"{path}: value {doc['value']!r} must be a number >= 0")
    if not isinstance(doc["unit"], str) or not doc["unit"]:
        problems.append(f"{path}: unit must be a non-empty string")
    if not _is_num(doc["vs_baseline"]):
        problems.append(
            f"{path}: vs_baseline {doc['vs_baseline']!r} must be a number")
    if not isinstance(doc["platform"], str) or not doc["platform"]:
        problems.append(
            f"{path}: platform must be a non-empty string "
            "(the BENCH_r05 failure mode: a CPU fallback identifiable "
            "only by grepping a note)")
    if not isinstance(doc["comparable"], bool):
        problems.append(f"{path}: comparable must be a bool")
        return problems
    if not doc["comparable"] and _is_num(doc["vs_baseline"]) \
            and doc["vs_baseline"] != 0.0:
        problems.append(
            f"{path}: comparable=false but vs_baseline="
            f"{doc['vs_baseline']} — an incomparable run may never carry "
            "a baseline ratio")
    if doc["comparable"] and doc.get("platform") != "tpu":
        problems.append(
            f"{path}: comparable=true on platform "
            f"{doc.get('platform')!r} — the baseline is the reference "
            "per-GPU rate; only TPU measurements are ratioed against it")
    known = set(REQUIRED_FIELDS) | set(OPTIONAL_FIELDS)
    for key in doc:
        if key not in known:
            problems.append(f"{path}: unknown field {key!r}")
    if "dt_reps" in doc and (
            not isinstance(doc["dt_reps"], list)
            or not all(_is_num(v) and v > 0 for v in doc["dt_reps"])):
        problems.append(
            f"{path}: dt_reps must be a list of positive numbers")
    if "dt_spread" in doc and (
            not _is_num(doc["dt_spread"]) or doc["dt_spread"] < 0):
        problems.append(f"{path}: dt_spread must be a number >= 0")
    return problems


def load_bench_file(path: str):
    """``(doc, problems)``: the ONE-JSON-line file contract —
    ``validate_bench_file`` and ``scripts/bench_compare.py`` must agree
    on what parses, so both ride the shared artifact loader
    (``obs/loading.py``, where the capacity/calibration validators read
    their files too). ``doc`` is None when ``problems`` is non-empty;
    schema validation is separate (``validate_bench``)."""
    from pvraft_tpu.obs.loading import load_json_artifact

    # bench.py prints ONE JSON line; an artifact file holds exactly it.
    return load_json_artifact(path, one_line=True)


def validate_bench_file(path: str) -> List[str]:
    doc, problems = load_bench_file(path)
    if problems:
        return problems
    return validate_bench(doc, path=path)


def compare(baseline: Dict[str, Any], candidate: Dict[str, Any],
            noise: float = DEFAULT_NOISE,
            baseline_path: str = "<baseline>",
            candidate_path: str = "<candidate>"
            ) -> Tuple[str, List[str]]:
    """Regression verdict for candidate-vs-baseline.

    Returns ``(verdict, messages)`` with verdict one of:

    * ``"refused"`` — the pair is not comparable (schema problems,
      platform/config/variant/lever mismatch, or a zero measurement);
      comparing would manufacture a conclusion, so the gate fails;
    * ``"regression"`` — candidate is below baseline by more than the
      noise band;
    * ``"ok"`` — within the band (or better).

    The band is ``max(noise, dt_spread of either artifact)``: a run
    whose own repeat spread exceeds the configured band widens the band
    honestly rather than flagging its own jitter as a regression."""
    messages: List[str] = []
    problems = (validate_bench(baseline, baseline_path)
                + validate_bench(candidate, candidate_path))
    if problems:
        return "refused", problems
    for key in COMPARE_KEYS:
        bval, cval = baseline.get(key), candidate.get(key)
        if bval != cval:
            messages.append(
                f"refusing to compare: {key} mismatch "
                f"({baseline_path}: {bval!r} vs {candidate_path}: {cval!r})"
                + (" — a CPU-fallback run must never be ratioed against "
                   "a TPU measurement" if key == "platform" else ""))
    if baseline["metric"] != candidate["metric"]:
        messages.append(
            f"refusing to compare: metric mismatch "
            f"({baseline['metric']!r} vs {candidate['metric']!r})")
    if messages:
        return "refused", messages
    if baseline["value"] <= 0 or candidate["value"] <= 0:
        return "refused", [
            "refusing to compare: a zero/failed measurement "
            f"(baseline {baseline['value']}, candidate "
            f"{candidate['value']}) carries no information"]
    band = max(float(noise),
               float(baseline.get("dt_spread") or 0.0),
               float(candidate.get("dt_spread") or 0.0))
    ratio = candidate["value"] / baseline["value"]
    detail = (f"candidate/baseline = {ratio:.4f} "
              f"(band ±{band:.2%}, platform {candidate['platform']}, "
              f"variant {candidate.get('variant')!r})")
    if ratio < 1.0 - band:
        return "regression", [
            f"REGRESSION: {detail} — candidate "
            f"{candidate['value']:.1f} fell more than {band:.2%} below "
            f"baseline {baseline['value']:.1f}"]
    if ratio > 1.0 + band:
        messages.append(
            f"improvement beyond the noise band: {detail} — consider "
            "promoting the candidate to the committed baseline")
    else:
        messages.append(f"within the noise band: {detail}")
    return "ok", messages
