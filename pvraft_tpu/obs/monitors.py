"""In-jit numerics monitors.

The instrument behind ``TrainConfig.telemetry``: a pure function of the
train step's intermediates (pre-update params, grads, optax updates, the
per-iteration flow stack, the loss) returning a small pytree of scalars
that rides back to the host as one extra metrics leaf. Everything here is
ordinary traced jnp — no ``jax.debug.print``/``callback``, no host sync —
so the hot loop's dispatch pipeline is untouched and the only cost is the
handful of reductions XLA fuses into the step.

Gating discipline (same contract as ``scatter_free_vjp`` and the
``@shapecheck`` layer): the step factories call :func:`telemetry_leaves`
only when the flag is on, so the default-off jaxpr is byte-identical to
the pre-telemetry step (test-gated in ``tests/test_obs.py`` and audited
by ``analysis/audit.py:engine.train_step[telemetry_off_jaxpr]``).

What is monitored and why (PAPER.md: the GRU refinement is iterative, so
one bad step corrupts every later iteration):

* ``grad_norm`` / ``param_norm`` / ``update_ratio`` — the classic LR
  health triple: update/param ratio drifting above ~1e-2 is the earliest
  visible symptom of an LR spike, well before the loss moves.
* ``grad_norm_by_group`` — global l2 norm per top-level param group
  (feature_extractor, context_extractor, update_iter, ...): names WHICH
  subnetwork blew up, not just that something did.
* ``delta_flow_norm`` — RMS norm of each GRU iteration's flow update
  ``(T,)``: healthy runs contract (later iterations refine less);
  divergence shows as the tail growing instead.
* ``nonfinite`` — count of non-finite elements across loss + grads +
  flows: the sentinel the trainer's divergence detector trips on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

# Leaf names of the telemetry sub-dict every monitored step returns
# (``grad_norm_by_group`` is itself a dict keyed by param-group name).
TELEMETRY_LEAVES = (
    "grad_norm", "param_norm", "update_ratio", "grad_norm_by_group",
    "delta_flow_norm", "nonfinite",
)

_EPS = 1e-12


def global_norm(tree: Any) -> jnp.ndarray:
    """Global l2 norm over every leaf of a pytree, accumulated in f32
    (bf16 leaves must not square-overflow the reduction)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def nonfinite_count(*trees: Any) -> jnp.ndarray:
    """Total count of non-finite elements across all leaves of all trees
    (int32; 0 on a healthy step)."""
    total = jnp.zeros((), jnp.int32)
    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                continue
            total = total + jnp.sum(
                (~jnp.isfinite(leaf)).astype(jnp.int32)
            )
    return total


def _param_groups(tree: Any) -> Dict[str, Any]:
    """Top-level named param groups of a flax variable dict: the children
    of the ``params`` collection when present, else the tree's own
    top-level children, else the whole tree as one group."""
    if isinstance(tree, dict) and "params" in tree:
        tree = tree["params"]
    if isinstance(tree, dict) and tree:
        return dict(tree)
    return {"all": tree}


def delta_flow_norms(flows: jnp.ndarray) -> jnp.ndarray:
    """Per-GRU-iteration RMS update norm, shape ``(T,)``.

    ``flows`` is the stage-1 stacked ``(T, B, N, 3)`` output; iteration
    t's update is ``flows[t] - flows[t-1]`` (the first iteration starts
    from zero flow, ``models/raft.py`` carry init)."""
    prev = jnp.concatenate([jnp.zeros_like(flows[:1]), flows[:-1]], axis=0)
    delta = (flows - prev).astype(jnp.float32)
    return jnp.sqrt(jnp.mean(jnp.square(delta), axis=(1, 2, 3)))


def telemetry_leaves(
    params: Any,
    grads: Any,
    updates: Any,
    loss: jnp.ndarray,
    flows: Optional[jnp.ndarray] = None,
) -> Dict[str, Any]:
    """The in-jit telemetry pytree (see module docstring for the leaves).

    ``params`` must be the PRE-update params (the ratio denominates the
    state the update is applied to); ``flows`` is the stacked stage-1
    iteration output, or None on the refine step (single flow — there is
    no iteration trajectory to monitor)."""
    pnorm = global_norm(params)
    out: Dict[str, Any] = {
        "grad_norm": global_norm(grads),
        "param_norm": pnorm,
        "update_ratio": global_norm(updates) / (pnorm + _EPS),
        "grad_norm_by_group": {
            name: global_norm(sub)
            for name, sub in sorted(_param_groups(grads).items())
        },
    }
    monitored = [loss, grads] if flows is None else [loss, grads, flows]
    if flows is not None:
        out["delta_flow_norm"] = delta_flow_norms(flows)
    out["nonfinite"] = nonfinite_count(*monitored)
    return out
