"""Device-memory telemetry: periodic ``device.memory_stats()`` samples.

The compile-time HBM story is covered (XLA memory analysis in the AOT
evidence and the ``pvraft_costs/v1`` inventory); this module covers the
*runtime* side: what is actually resident on each device right now, as
``device_memory`` events on the ``pvraft_events/v1`` stream and as the
``pvraft_device_hbm_bytes{device}`` Prometheus gauge
(``serve/metrics.py``).

Backends without allocator stats (CPU returns ``None``) sample to an
empty list and emit nothing — the telemetry is zero-noise where it is
meaningless and automatic where it matters (TPU/GPU). Keys differ per
runtime, so rows normalize to the schema's vocabulary: ``bytes_in_use``
(required), ``peak_bytes_in_use``/``bytes_limit`` when the allocator
reports them.

Consumers:

* the Trainer emits one sample per epoch (``context="train"``);
* the serve pool runs a :class:`DeviceMemoryMonitor` thread
  (``--devmem_interval``) that feeds both the event stream and the
  Prometheus gauge.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock

# memory_stats() key -> schema key (first match wins; runtimes disagree
# on spelling).
_STAT_KEYS = (
    ("bytes_in_use", "bytes_in_use"),
    ("peak_bytes_in_use", "peak_bytes_in_use"),
    ("bytes_limit", "bytes_limit"),
    ("bytes_reservable_limit", "bytes_limit"),
)


def device_memory_row(device) -> Optional[Dict[str, Any]]:
    """One device's normalized sample row, or None when the backend has
    no allocator stats (CPU) or the probe fails (never raises — a
    telemetry sampler must not take down the run it observes)."""
    try:
        stats = device.memory_stats()
    except Exception:  # noqa: BLE001 — absent API == no stats
        return None
    if not stats:
        return None
    row: Dict[str, Any] = {
        "device_id": int(device.id),
        "platform": str(getattr(device, "platform", "unknown")),
    }
    for src, dst in _STAT_KEYS:
        if dst in row:
            continue
        value = stats.get(src)
        if value is not None:
            row[dst] = int(value)
    if "bytes_in_use" not in row:
        return None
    return row


def sample_device_memory(devices=None) -> List[Dict[str, Any]]:
    """Normalized rows for every local device that reports stats
    (possibly empty — CPU backends)."""
    if devices is None:
        import jax

        devices = jax.local_devices()
    rows = []
    for device in devices:
        row = device_memory_row(device)
        if row is not None:
            rows.append(row)
    return rows


class DeviceMemoryMonitor:
    """Background sampler for the serve pool: every ``interval_s``,
    sample all (or the given) devices, emit one ``device_memory`` event
    and push the gauge rows into ``metrics.record_device_memory``.

    ``interval_s <= 0`` disables without branching at the call sites
    (``start()`` becomes a no-op). The thread is a daemon and also
    samples once at ``stop()`` so even a short-lived service records a
    final watermark."""

    def __init__(self, emit: Optional[Callable[..., Any]] = None,
                 metrics=None, interval_s: float = 10.0,
                 devices=None, context: str = "serve"):
        self.emit = emit
        self.metrics = metrics
        self.interval_s = float(interval_s)
        self.devices = devices
        self.context = context
        self.samples = 0
        self._stop = threading.Event()
        # Lifecycle lock (threadcheck GC003): start/stop are a classic
        # test-then-assign pair on _thread — two concurrent callers both
        # passing the `_thread is None` check would double-start the
        # sampler (or stop() would join a thread start() already
        # replaced). The whole transition runs under one lock.
        self._state_lock = ordered_lock("DeviceMemoryMonitor._state_lock")
        self._thread: Optional[threading.Thread] = None  # guarded-by: _state_lock

    def sample_once(self) -> List[Dict[str, Any]]:
        rows = sample_device_memory(self.devices)
        if rows:
            self.samples += 1
            if self.metrics is not None:
                self.metrics.record_device_memory(rows)
            if self.emit is not None:
                self.emit(rows, context=self.context)
        return rows

    def start(self) -> None:
        with self._state_lock:
            if self.interval_s <= 0 or self._thread is not None:
                return
            self._stop.clear()  # restartable: stop() leaves the flag set
            # First sample happens on the thread (jax device probing can
            # block briefly; startup must not).
            self._thread = threading.Thread(
                target=self._run, name="pvraft-devmem", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — observe, never crash serving
                pass
            self._stop.wait(self.interval_s)

    def stop(self) -> None:
        # Join under the lifecycle lock: the sampler thread never takes
        # it, so this cannot deadlock — it only serializes a concurrent
        # start(), which must not spin up a replacement thread until the
        # old one is confirmed dead (and must then see _stop cleared).
        with self._state_lock:
            thread = self._thread
            if thread is None:
                return
            self._thread = None
            self._stop.set()
            thread.join(5.0)
        try:
            self.sample_once()  # final watermark
        except Exception:  # noqa: BLE001 — shutdown must complete
            pass
