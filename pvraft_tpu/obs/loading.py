"""Shared committed-artifact loaders: the file contracts, in ONE place.

Every validator and compare script in this repo reads a committed JSON
artifact off disk before judging it, and until now each grew its own
``open``/``json.loads`` wrapper — ``obs/bench.load_bench_file`` (the
ONE-JSON-line bench contract), ``obs/slo.validate_slo_report_file``,
``serve/loadgen.validate_load_artifact_file``, and the capacity /
calibration loaders would have been the next siblings. A drifted copy
of the line contract is exactly how a validator and its compare script
end up disagreeing about what parses, so both contracts live here:

* :func:`load_json_artifact` — a whole-file JSON document (the common
  committed-report shape); unreadable / malformed files come back as
  ``(None, [problem])``, never as a traceback (the lint gate runs these
  on hand-editable files, and a traceback is not a verdict);
* the same function with ``one_line=True`` — the ``bench.py`` contract:
  the file must hold EXACTLY one non-blank JSON line (a second line is
  a corrupted artifact, not extra data).

Pure stdlib (no jax, no numpy): safe to import from the jax-free CLIs
and from ``serve/loadgen.py`` alike.
"""

from __future__ import annotations

import json
from typing import Any, List, Optional, Tuple


def load_json_artifact(path: str, one_line: bool = False
                       ) -> Tuple[Optional[Any], List[str]]:
    """``(doc, problems)`` for one committed JSON artifact. ``doc`` is
    None exactly when ``problems`` is non-empty; schema validation is
    the caller's job — this owns only the file/parse contract."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return None, [f"{path}: unreadable: {e}"]
    if one_line:
        lines = [line for line in text.splitlines() if line.strip()]
        if len(lines) != 1:
            return None, [
                f"{path}: expected exactly one JSON line, got {len(lines)}"]
        text = lines[0]
    try:
        return json.loads(text), []
    except ValueError as e:
        return None, [f"{path}: not valid JSON: {e}"]
