"""Divergence detection + crash snapshots.

The trainer's last line of defense for the failure mode PAPER.md's
iterative refinement makes expensive: a single bad batch or LR spike
corrupts every downstream GRU iteration, and by the time ``Train/Loss``
reads ``nan`` the state that produced it is gone (the step donates its
input buffers). Two triggers:

* ``nonfinite`` — the in-jit sentinel (``obs/monitors.py``) counted a
  non-finite element in loss/grads/flows;
* ``zscore`` — the loss sits more than ``zscore`` trailing standard
  deviations above the trailing-window mean (an LR spike shows here
  steps before anything overflows).

On a trip the trainer dumps the OFFENDING step's inputs — the batch, and
the params/opt_state as they were BEFORE the update — to
``experiments/<exp>/snapshots/step_<n>/``; ``scripts/run_doctor.py``
replays that exact step on CPU and names the first non-finite stage.

Snapshot layout (``pvraft_snapshot/v1``):

    step_<n>/meta.json    schema, step/epoch/reason, loss, config
    step_<n>/batch.npz    pc1, pc2, flow, mask (host numpy)
    step_<n>/state.msgpack  flax-serialized {params, opt_state}
"""

from __future__ import annotations

import dataclasses
import json
import os
from collections import deque
from typing import Any, Dict, Optional

import numpy as np

SNAPSHOT_SCHEMA = "pvraft_snapshot/v1"


class DivergenceHalt(RuntimeError):
    """Raised by the trainer when ``halt_on_divergence`` is set and the
    detector trips. A distinct type so the training loop can flush the
    epoch's buffered step events (the trajectory leading INTO the trip —
    the context worth the most) before re-raising."""


@dataclasses.dataclass
class Trip:
    """One detector firing."""

    reason: str                  # "nonfinite" | "zscore"
    loss: float
    zscore: Optional[float] = None


class DivergenceDetector:
    """Trailing-window loss monitor (host-side, O(window) floats).

    ``update(loss, nonfinite)`` is called once per optimizer step with
    host scalars; returns a :class:`Trip` when the run looks unhealthy,
    else None. The window only accumulates healthy steps, so one spike
    does not inflate the trailing std and mask the next one."""

    def __init__(self, window: int = 64, zscore: float = 6.0,
                 min_steps: int = 8):
        if window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        self.window = window
        self.zscore = zscore
        # Clamp to the window: a min_steps the deque can never reach
        # would silently disarm the z-score trigger for the whole run.
        self.min_steps = min(max(2, min_steps), window)
        self.losses: deque = deque(maxlen=window)

    def update(self, loss: float, nonfinite: int = 0) -> Optional[Trip]:
        if nonfinite > 0 or not np.isfinite(loss):
            return Trip(reason="nonfinite", loss=float(loss))
        if self.zscore > 0 and len(self.losses) >= self.min_steps:
            mean = float(np.mean(self.losses))
            std = float(np.std(self.losses))
            # A flat-lined window (std ~ 0) would make any wiggle an
            # infinite z-score; floor the scale at 1e-6 of the mean.
            scale = max(std, 1e-6 * max(abs(mean), 1.0))
            z = (float(loss) - mean) / scale
            if z > self.zscore:
                return Trip(reason="zscore", loss=float(loss),
                            zscore=round(z, 2))
        self.losses.append(float(loss))
        return None


def dump_snapshot(
    snap_dir: str,
    batch: Dict[str, np.ndarray],
    params: Any,
    opt_state: Any,
    *,
    step: int,
    epoch: int,
    reason: str,
    loss: float,
    cfg=None,
    extra_meta: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Write one ``pvraft_snapshot/v1`` directory; returns its path
    (``None`` on non-zero ranks — snapshot dirs are process-0-only
    filesystem state, shardcheck GS004; the Trainer additionally never
    calls this on multi-process meshes, where the global batch is not
    host-addressable).

    ``params``/``opt_state`` must be host numpy trees captured BEFORE the
    offending update (the state the replay needs); ``batch`` the host
    batch that triggered it."""
    import jax

    from flax import serialization

    from pvraft_tpu.obs.events import sanitize

    if jax.process_index() != 0:
        return None
    out = os.path.join(snap_dir, f"step_{step:07d}")
    os.makedirs(out, exist_ok=True)
    np.savez(os.path.join(out, "batch.npz"),
             **{k: np.asarray(v) for k, v in batch.items()})
    # to_state_dict: optax states are NamedTuple chains msgpack cannot
    # pack; the state-dict form round-trips via from_state_dict against a
    # freshly built optimizer state (same move as engine/checkpoint.py).
    payload = {
        "params": serialization.to_state_dict(params),
        "opt_state": serialization.to_state_dict(opt_state),
    }
    tmp = os.path.join(out, "state.msgpack.tmp")
    with open(tmp, "wb") as f:
        f.write(serialization.msgpack_serialize(payload))
    os.replace(tmp, os.path.join(out, "state.msgpack"))
    meta = {
        "schema": SNAPSHOT_SCHEMA,
        "step": step,
        "epoch": epoch,
        "reason": reason,
        "loss": sanitize(float(loss)),
        "config": (
            sanitize(dataclasses.asdict(cfg))
            if dataclasses.is_dataclass(cfg) else sanitize(cfg or {})
        ),
    }
    if extra_meta:
        meta.update(sanitize(extra_meta))
    with open(os.path.join(out, "meta.json"), "w", encoding="utf-8") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    return out


def load_snapshot(path: str):
    """Load a snapshot dir -> (meta, batch dict, params, opt_state).

    ``opt_state`` comes back as the raw deserialized pytree (dicts/lists
    of numpy arrays) — structurally enough for the doctor's numerics
    replay; rebuilding the exact optax NamedTuple chain is the caller's
    job when it wants to run the real optimizer update."""
    from flax import serialization

    with open(os.path.join(path, "meta.json"), "r", encoding="utf-8") as f:
        meta = json.load(f)
    if meta.get("schema") != SNAPSHOT_SCHEMA:
        raise ValueError(
            f"{path}: schema {meta.get('schema')!r} != {SNAPSHOT_SCHEMA!r}")
    with np.load(os.path.join(path, "batch.npz")) as z:
        batch = {k: z[k] for k in z.files}
    with open(os.path.join(path, "state.msgpack"), "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    return meta, batch, payload["params"], payload["opt_state"]
