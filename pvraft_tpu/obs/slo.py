"""SLO evidence: join loadgen artifacts + trace spans -> ``pvraft_slo/v1``.

The ROADMAP serving north-star asks for "max sustainable QPS at a p99
latency SLO per (bucket, batch, dtype)". A loadgen artifact alone gives
end-to-end client latency and throughput; the trace plane
(:mod:`pvraft_tpu.obs.trace`) gives the per-stage decomposition. This
module joins the two by trace id into one report:

    {"schema": "pvraft_slo/v1",
     "slo": {"p99_ms": <threshold>},
     "sources": [{"load": ..., "events": ...}],
     "totals": {"requests", "ok", "traced_ok", "complete", "orphan_spans"},
     "programs": [{"bucket", "batch", "dtype", "requests",
                   "stages": {stage: {count, mean_ms, p50_ms, p95_ms,
                                      p99_ms}},
                   "e2e": {...same keys...},
                   "stage_p99_sum_ms", "stage_sum_ratio",
                   "meets_slo"}],
     "runs": [{"load", "throughput_rps", "client_p99_ms", "meets_slo"}],
     "max_qps_under_slo": <max throughput among SLO-compliant runs,
                           null if none qualifies>}

Program identity: ``(bucket, batch)`` comes from the request's
``device_execute`` span attrs (the dispatched AOT program), ``dtype``
from the loadgen artifact's model config — the same key space the
program registry certifies (``programs/geometries.SERVE_CERTIFIED``).

Quantiles are exact (computed from raw per-trace samples, like the
loadgen client side), not histogram upper bounds. ``stage_sum_ratio``
is the honesty check the acceptance bar names: the sum of per-stage
p99s over the end-to-end p99 — near 1.0 when the stage decomposition
accounts for the tail, drifting when un-instrumented gaps (thread
wakeups, scheduler stalls) eat it.

``validate_slo_report`` is the schema gate (``python -m pvraft_tpu.obs
validate-slo``, wired into ``scripts/lint.sh``); ``scripts/slo_report.py``
is the CLI.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.obs.trace import SERVE_STAGES, trace_shape

SLO_SCHEMA = "pvraft_slo/v1"

_STAT_KEYS = ("count", "mean_ms", "p50_ms", "p95_ms", "p99_ms")


def exact_quantile(samples: Sequence[float], q: float) -> Optional[float]:
    """Nearest-rank quantile over raw samples (None when empty) — the
    same estimator the loadgen client uses, so the two agree."""
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(q * len(ordered)))]


def _stats(samples: List[float]) -> Dict[str, Any]:
    return {
        "count": len(samples),
        "mean_ms": (round(sum(samples) / len(samples), 3)
                    if samples else None),
        "p50_ms": _r(exact_quantile(samples, 0.50)),
        "p95_ms": _r(exact_quantile(samples, 0.95)),
        "p99_ms": _r(exact_quantile(samples, 0.99)),
    }


def _r(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 3)


def _index_traces(records: Sequence[Dict[str, Any]]
                  ) -> Dict[str, List[Dict[str, Any]]]:
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("type") == "span":
            by_trace.setdefault(rec["trace_id"], []).append(rec)
    return by_trace


def build_slo_report(
    sources: Sequence[Tuple[str, Dict[str, Any], str,
                            Sequence[Dict[str, Any]]]],
    slo_p99_ms: float,
    ratio_band: Tuple[float, float] = (0.9, 1.1),
) -> Dict[str, Any]:
    """Build the report from ``(load_path, load_doc, events_path,
    event_records)`` tuples — one per loadgen run. Requests join to
    traces via the artifact's ``per_request[].trace_id`` (recorded from
    the server's ``X-Pvraft-Trace`` response header)."""
    totals = {"requests": 0, "ok": 0, "traced_ok": 0, "complete": 0,
              "orphan_spans": 0}
    # (bucket, batch, dtype) -> {"stages": {stage: [ms]}, "e2e": [ms]}
    programs: Dict[Tuple[int, int, str], Dict[str, Any]] = {}
    runs: List[Dict[str, Any]] = []

    for load_path, load_doc, events_path, records in sources:
        by_trace = _index_traces(records)
        dtype = (load_doc.get("config", {}) or {}).get(
            "compute_dtype", "float32")
        per_request = load_doc.get("per_request", [])
        totals["requests"] += load_doc.get("requests", {}).get(
            "total", len(per_request))
        ok_ms: List[float] = []
        for req in per_request:
            if req.get("status") != 200:
                continue
            totals["ok"] += 1
            if req.get("ms") is not None:
                ok_ms.append(req["ms"])
            spans = by_trace.get(req.get("trace_id") or "")
            if not spans:
                continue
            totals["traced_ok"] += 1
            # ONE completeness definition, shared with the trace
            # artifact builder/validator (obs.trace.trace_shape).
            roots, orphans, stages, complete = trace_shape(
                spans, SERVE_STAGES)
            totals["orphan_spans"] += len(orphans)
            totals["complete"] += complete
            if len(roots) != 1:
                continue
            exec_span = next(
                (s for s in spans if s["name"] == "device_execute"), None)
            attrs = (exec_span or {}).get("attrs", {})
            if "bucket" not in attrs or "batch" not in attrs:
                continue
            key = (int(attrs["bucket"]), int(attrs["batch"]), dtype)
            slot = programs.setdefault(
                key, {"stages": {s: [] for s in SERVE_STAGES}, "e2e": []})
            slot["e2e"].append(roots[0]["end_ms"] - roots[0]["start_ms"])
            for stage, dur in stages.items():
                if stage in slot["stages"]:
                    slot["stages"][stage].append(dur)
        client_p99 = _r(exact_quantile(ok_ms, 0.99))
        meets = client_p99 is not None and client_p99 <= slo_p99_ms
        runs.append({
            "load": load_path,
            "events": events_path,
            "throughput_rps": load_doc.get("throughput_rps"),
            "client_p99_ms": client_p99,
            "meets_slo": meets,
        })

    program_rows = []
    for (bucket, batch, dtype), slot in sorted(programs.items()):
        e2e = _stats(slot["e2e"])
        stage_stats = {s: _stats(ms) for s, ms in slot["stages"].items()}
        p99s = [st["p99_ms"] for st in stage_stats.values()
                if st["p99_ms"] is not None]
        stage_p99_sum = round(sum(p99s), 3) if p99s else None
        ratio = (round(stage_p99_sum / e2e["p99_ms"], 4)
                 if stage_p99_sum is not None and e2e["p99_ms"] else None)
        program_rows.append({
            "bucket": bucket, "batch": batch, "dtype": dtype,
            "requests": e2e["count"],
            "stages": stage_stats,
            "e2e": e2e,
            "stage_p99_sum_ms": stage_p99_sum,
            "stage_sum_ratio": ratio,
            "meets_slo": (e2e["p99_ms"] is not None
                          and e2e["p99_ms"] <= slo_p99_ms),
        })

    qualifying = [r["throughput_rps"] for r in runs
                  if r["meets_slo"] and r["throughput_rps"] is not None]
    return {
        "schema": SLO_SCHEMA,
        # ratio_band: the stage-sum honesty bar this report was held to
        # (checked by scripts/slo_report.py --check). [0.9, 1.1] is the
        # serialized-client bar; concurrency > 1 legitimately widens it
        # (independent scheduler stalls land in different stages' p99s).
        "slo": {"p99_ms": slo_p99_ms,
                "ratio_band": [ratio_band[0], ratio_band[1]]},
        "sources": [{"load": p, "events": e}
                    for p, _, e, _ in sources],
        "totals": totals,
        "programs": program_rows,
        "runs": runs,
        "max_qps_under_slo": max(qualifying) if qualifying else None,
    }


def validate_slo_report(doc: Any, path: str = "<report>") -> List[str]:
    """Schema problems of a ``pvraft_slo/v1`` report ([] = valid)."""
    if not isinstance(doc, dict):
        return [f"{path}: report is {type(doc).__name__}, not an object"]
    problems: List[str] = []
    if doc.get("schema") != SLO_SCHEMA:
        problems.append(
            f"{path}: schema {doc.get('schema')!r} != {SLO_SCHEMA!r}")
    for key in ("slo", "sources", "totals", "programs", "runs",
                "max_qps_under_slo"):
        if key not in doc:
            problems.append(f"{path}: missing field {key!r}")
    if problems:
        return problems
    if not isinstance(doc["slo"], dict) or not isinstance(
            doc["slo"].get("p99_ms"), (int, float)):
        problems.append(f"{path}: slo.p99_ms must be a number")
    # Malformed containers must surface as reported problems — the lint
    # gate runs this on hand-editable committed files, and a traceback
    # is not a verdict.
    for key, want in (("totals", dict), ("programs", list),
                      ("runs", list)):
        if not isinstance(doc[key], want):
            problems.append(
                f"{path}: {key} must be a {want.__name__}")
    if problems:
        return problems
    totals = doc["totals"]
    for key in ("requests", "ok", "traced_ok", "complete", "orphan_spans"):
        if not isinstance(totals.get(key), int):
            problems.append(f"{path}: totals.{key} must be an int")
    if isinstance(totals.get("traced_ok"), int) and isinstance(
            totals.get("complete"), int):
        if totals["complete"] > totals["traced_ok"]:
            problems.append(
                f"{path}: totals.complete {totals['complete']} > "
                f"traced_ok {totals['traced_ok']}")
    for i, row in enumerate(doc["programs"]):
        where = f"{path}: programs[{i}]"
        if not isinstance(row, dict):
            problems.append(f"{where}: not an object")
            continue
        for key in ("bucket", "batch", "dtype", "requests", "stages",
                    "e2e", "stage_p99_sum_ms", "stage_sum_ratio",
                    "meets_slo"):
            if key not in row:
                problems.append(f"{where}: missing {key!r}")
        stages = row.get("stages")
        if isinstance(stages, dict):
            missing = set(SERVE_STAGES) - set(stages)
            if missing:
                problems.append(
                    f"{where}: stages missing {sorted(missing)}")
            for stage, st in stages.items():
                if not isinstance(st, dict) or set(_STAT_KEYS) - set(st):
                    problems.append(
                        f"{where}: stages.{stage} must carry {_STAT_KEYS}")
        for block in ("e2e",):
            st = row.get(block)
            if isinstance(st, dict):
                order = [st.get(k) for k in ("p50_ms", "p95_ms", "p99_ms")]
                if all(isinstance(v, (int, float)) for v in order):
                    if not (order[0] <= order[1] <= order[2]):
                        problems.append(
                            f"{where}: {block} quantiles must be "
                            f"non-decreasing, got {order}")
    for i, run in enumerate(doc["runs"]):
        if not isinstance(run, dict) or "load" not in run or (
                "meets_slo" not in run):
            problems.append(
                f"{path}: runs[{i}] must carry load + meets_slo")
    mq = doc["max_qps_under_slo"]
    if mq is not None and not isinstance(mq, (int, float)):
        problems.append(
            f"{path}: max_qps_under_slo must be a number or null")
    # The headline number is recomputed, not trusted: it must equal the
    # max throughput among SLO-compliant runs (null when none qualifies)
    # — a hand-edited committed report cannot claim a QPS its runs never
    # delivered.
    qualifying = [r["throughput_rps"] for r in doc["runs"]
                  if isinstance(r, dict) and r.get("meets_slo")
                  and isinstance(r.get("throughput_rps"), (int, float))]
    want_mq = max(qualifying) if qualifying else None
    if (mq is None) != (want_mq is None) or (
            isinstance(mq, (int, float)) and want_mq is not None
            and abs(mq - want_mq) > 1e-9):
        problems.append(
            f"{path}: max_qps_under_slo={mq} but the qualifying runs "
            f"support {want_mq}")
    return problems


def validate_slo_report_file(path: str) -> List[str]:
    from pvraft_tpu.obs.loading import load_json_artifact

    doc, problems = load_json_artifact(path)
    if problems:
        return problems
    return validate_slo_report(doc, path=path)
