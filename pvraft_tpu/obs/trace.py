"""Request-level span tracing: the ``pvraft_trace/v1`` plane.

Every sampled serve request gets a trace id and a span tree

    request
      ├─ ingress          read + decode the HTTP body
      ├─ validate         engine contract check (serve/engine.py)
      ├─ queue_wait       enqueue -> worker dequeue
      ├─ batch_form       dequeue -> dispatch (straggler wait, grouping)
      ├─ device_execute   the AOT program incl. host fetch (bracketed by
      │                   ``jax.profiler.TraceAnnotation`` so it lines up
      │                   with XLA traces from ``/debug/trace``)
      ├─ serialize        flow -> JSON/msgpack payload
      └─ respond          socket write

recorded from low-overhead ``time.monotonic()`` stamps at the existing
hook points (``serve/server.py``, ``serve/batcher.py``). Train-side, the
step profiler's telescoped stage boundaries map onto the SAME span
schema (:func:`trace_from_step_profile`), so one decomposition format
covers both workloads.

Spans travel as ``pvraft_events/v1`` records of type ``span`` through
the existing lock-serialized telemetry writers — no new sink, one
validator. Timestamps are host-monotonic milliseconds: comparable
within one process (one trace never crosses processes), deliberately
NOT wall time (NTP steps would corrupt durations).

Sampling is an explicit knob (:class:`Tracer`): 100% under loadgen
(``scripts/serve_loadgen.py``), 1-in-N in production serve
(``python -m pvraft_tpu.serve serve --trace_sample N``), 0 = off. The
off path stamps nothing and allocates nothing per request beyond one
``None`` check — tracing is pure host-side and cannot perturb any jaxpr
(the ``engine.train_step[telemetry_off_jaxpr]`` guarantee is untouched).

``collect_traces`` groups span events into the committed
``pvraft_trace/v1`` artifact; ``validate_trace_artifact`` is its gate
(wired into ``scripts/lint.sh`` via ``python -m pvraft_tpu.obs
validate-trace``).
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock

TRACE_SCHEMA = "pvraft_trace/v1"

# The serve request decomposition, in pipeline order. The SLO report and
# the trace-artifact completeness check both key on this tuple.
SERVE_STAGES = (
    "ingress", "validate", "queue_wait", "batch_form", "device_execute",
    "serialize", "respond")

# Root span names per workload (the "request" tree is the serve one; the
# step profiler emits a "train_step" tree over its breakdown stages).
SERVE_ROOT = "request"
TRAIN_ROOT = "train_step"

# The train-side stage vocabulary = the step profiler's telescoped
# breakdown (single-sourced from the registry's pure-data geometry
# module, so the two cannot drift and this import stays jax-free).
# Together with SERVE_STAGES these are the only expected_stages a
# pvraft_trace/v1 artifact may declare — the validator pins this, or a
# hand-edited artifact could declare expected_stages=[] and mark
# everything complete.
from pvraft_tpu.programs.geometries import (  # noqa: E402
    PROFILE_BREAKDOWN_STAGES as TRAIN_STAGES,
)

KNOWN_STAGE_SETS = (tuple(SERVE_STAGES), tuple(TRAIN_STAGES))

# Span event fields (type "span" in pvraft_events/v1): required then
# optional — mirrored in obs/events.py EVENT_TYPES.
SPAN_REQUIRED = ("trace_id", "span_id", "name", "start_ms", "end_ms")
SPAN_OPTIONAL = ("parent_id", "attrs")


def _now_ms() -> float:
    return time.monotonic() * 1000.0


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class RequestTrace:
    """Monotonic stamp sheet for one traced request.

    Worker threads ``mark`` stage intervals as they happen (list append
    only — marks from the batcher worker happen-before the handler reads
    them, ordered by the request's completion event); the handler thread
    assembles the span tree once, at respond time, via :meth:`spans`.
    """

    __slots__ = ("trace_id", "t0", "_marks")

    def __init__(self, trace_id: Optional[str] = None,
                 t0: Optional[float] = None):
        self.trace_id = trace_id or new_trace_id()
        # Root start: monotonic SECONDS (converted to ms at build time,
        # matching the time.monotonic() stamps the hook points take).
        self.t0 = time.monotonic() if t0 is None else t0
        self._marks: List[Tuple[str, float, float,
                                Optional[Dict[str, Any]]]] = []

    def mark(self, name: str, t_start: float, t_end: float,
             attrs: Optional[Dict[str, Any]] = None) -> None:
        """Record one stage interval (monotonic seconds)."""
        self._marks.append((name, t_start, t_end, attrs))

    def spans(self, t_end: Optional[float] = None,
              root_name: str = SERVE_ROOT,
              root_attrs: Optional[Dict[str, Any]] = None
              ) -> List[Dict[str, Any]]:
        """The span tree: a root span covering [t0, t_end] plus one child
        per recorded mark, all parented to the root."""
        t_end = time.monotonic() if t_end is None else t_end
        root_id = uuid.uuid4().hex[:12]
        root: Dict[str, Any] = {
            "trace_id": self.trace_id, "span_id": root_id,
            "name": root_name,
            "start_ms": round(self.t0 * 1000.0, 3),
            "end_ms": round(t_end * 1000.0, 3),
        }
        if root_attrs:
            root["attrs"] = dict(root_attrs)
        out = [root]
        for i, (name, ts, te, attrs) in enumerate(self._marks):
            span: Dict[str, Any] = {
                "trace_id": self.trace_id,
                "span_id": f"{root_id}.{i}",
                "parent_id": root_id,
                "name": name,
                "start_ms": round(ts * 1000.0, 3),
                "end_ms": round(te * 1000.0, 3),
            }
            if attrs:
                span["attrs"] = dict(attrs)
            out.append(span)
        return out

    def stage_durations_ms(self) -> Dict[str, float]:
        """{stage: duration_ms} for the recorded marks (histogram feed)."""
        return {name: round((te - ts) * 1000.0, 3)
                for name, ts, te, _ in self._marks}


class Tracer:
    """Sampling decision + span emission, shared across handler threads.

    ``sample_every=1`` traces everything (loadgen), ``N`` traces 1-in-N
    (production serve), ``0`` disables tracing entirely. ``emit`` is the
    span sink — typically ``ServeTelemetry.emit_span`` (lock-serialized)
    or ``None`` to trace for metrics histograms only. ``sample_every``
    is mutable on purpose: the overhead A/B toggles it on a live server
    so off/on legs interleave within one process."""

    def __init__(self, sample_every: int = 1,
                 emit: Optional[Callable[..., Any]] = None):
        if sample_every < 0:
            raise ValueError("sample_every must be >= 0 (0 disables)")
        self.sample_every = int(sample_every)
        self.emit = emit
        self._lock = ordered_lock("Tracer._lock")
        self._n = 0  # guarded-by: _lock

    @property
    def enabled(self) -> bool:
        return self.sample_every > 0

    def begin(self) -> Optional[RequestTrace]:
        """A fresh :class:`RequestTrace` for a sampled request, else
        ``None`` (the entire per-request cost of the unsampled path)."""
        every = self.sample_every
        if every <= 0:
            return None
        if every > 1:
            with self._lock:
                self._n += 1
                if self._n % every:
                    return None
        return RequestTrace()

    def emit_spans(self, spans: Sequence[Dict[str, Any]]) -> None:
        if self.emit is None:
            return
        for span in spans:
            self.emit(**span)


# --------------------------------------------------------------- artifact --


def trace_shape(spans: Sequence[Dict[str, Any]],
                expected_stages: Sequence[str]
                ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]],
                           Dict[str, float], bool]:
    """The ONE definition of a trace's shape, shared by the artifact
    builder, its validator, and the SLO join: ``(roots, orphans,
    child stage durations ms, complete)``. *Complete* = exactly one
    root (no ``parent_id``), no orphan spans (every ``parent_id``
    resolves within the trace), every expected stage present among the
    children."""
    ids = {s.get("span_id") for s in spans}
    roots = [s for s in spans if "parent_id" not in s]
    orphans = [s for s in spans
               if "parent_id" in s and s["parent_id"] not in ids]
    stages = {s.get("name"): s.get("end_ms", 0.0) - s.get("start_ms", 0.0)
              for s in spans if "parent_id" in s}
    complete = (len(roots) == 1 and not orphans
                and set(expected_stages) <= set(stages))
    return roots, orphans, stages, complete


def collect_traces(records: Sequence[Dict[str, Any]],
                   expected_stages: Sequence[str] = SERVE_STAGES,
                   source: str = "<events>") -> Dict[str, Any]:
    """Group ``span`` events from a parsed ``pvraft_events/v1`` stream
    into a ``pvraft_trace/v1`` artifact (completeness per
    :func:`trace_shape`)."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for rec in records:
        if rec.get("type") != "span":
            continue
        span = {k: rec[k] for k in (*SPAN_REQUIRED, *SPAN_OPTIONAL)
                if k in rec}
        by_trace.setdefault(rec["trace_id"], []).append(span)
    traces, n_complete, n_orphans, n_spans = [], 0, 0, 0
    for trace_id, spans in by_trace.items():
        spans.sort(key=lambda s: (s["start_ms"], s["span_id"]))
        roots, orphans, _, complete = trace_shape(spans, expected_stages)
        n_complete += complete
        n_orphans += len(orphans)
        n_spans += len(spans)
        entry: Dict[str, Any] = {
            "trace_id": trace_id,
            "root": roots[0]["name"] if len(roots) == 1 else None,
            "complete": complete,
            "spans": spans,
        }
        if len(roots) == 1:
            entry["duration_ms"] = round(
                roots[0]["end_ms"] - roots[0]["start_ms"], 3)
        traces.append(entry)
    traces.sort(key=lambda t: t["trace_id"])
    return {
        "schema": TRACE_SCHEMA,
        "source": source,
        "expected_stages": list(expected_stages),
        "counts": {"traces": len(traces), "spans": n_spans,
                   "complete": n_complete, "orphan_spans": n_orphans},
        "traces": traces,
    }


def validate_trace_artifact(doc: Any,
                            path: str = "<artifact>") -> List[str]:
    """Schema problems of a ``pvraft_trace/v1`` artifact ([] = valid).
    Recomputes completeness/orphan counts from the spans themselves so a
    hand-edited ``complete`` flag cannot lie."""
    if not isinstance(doc, dict):
        return [f"{path}: artifact is {type(doc).__name__}, not an object"]
    problems: List[str] = []
    if doc.get("schema") != TRACE_SCHEMA:
        problems.append(
            f"{path}: schema {doc.get('schema')!r} != {TRACE_SCHEMA!r}")
    for key in ("expected_stages", "counts", "traces"):
        if key not in doc:
            problems.append(f"{path}: missing field {key!r}")
    if problems:
        return problems
    if not isinstance(doc["expected_stages"], list) or tuple(
            doc["expected_stages"]) not in KNOWN_STAGE_SETS:
        problems.append(
            f"{path}: expected_stages {doc['expected_stages']!r} is not a "
            f"known stage vocabulary (serve: {list(SERVE_STAGES)}, train: "
            f"{list(TRAIN_STAGES)}) — completeness would be meaningless")
        return problems
    if not isinstance(doc["traces"], list) or not isinstance(
            doc["counts"], dict):
        # A malformed container must become a reported problem, not an
        # unhandled traceback out of the lint gate.
        problems.append(
            f"{path}: traces must be a list and counts an object")
        return problems
    expected = set(doc["expected_stages"])
    n_complete = n_orphans = n_spans = 0
    for t_i, trace in enumerate(doc["traces"]):
        where = f"{path}: traces[{t_i}]"
        if (not isinstance(trace, dict)
                or not isinstance(trace.get("spans"), list)
                or not all(isinstance(s, dict) for s in trace["spans"])):
            problems.append(
                f"{where}: not an object with a list of span objects")
            continue
        spans = trace["spans"]
        for s_i, span in enumerate(spans):
            for key in SPAN_REQUIRED:
                if key not in span:
                    problems.append(
                        f"{where}.spans[{s_i}]: missing {key!r}")
            if "start_ms" in span and "end_ms" in span and (
                    span["end_ms"] < span["start_ms"]):
                problems.append(
                    f"{where}.spans[{s_i}]: end_ms {span['end_ms']} < "
                    f"start_ms {span['start_ms']}")
            if span.get("trace_id") != trace.get("trace_id"):
                problems.append(
                    f"{where}.spans[{s_i}]: trace_id "
                    f"{span.get('trace_id')!r} != {trace.get('trace_id')!r}")
        roots, orphans, stages, complete = trace_shape(spans, expected)
        if bool(trace.get("complete")) != complete:
            problems.append(
                f"{where}: complete={trace.get('complete')!r} but spans "
                f"say {complete} (roots={len(roots)}, "
                f"orphans={len(orphans)}, "
                f"missing={sorted(expected - set(stages))})")
        n_complete += complete
        n_orphans += len(orphans)
        n_spans += len(spans)
    want = {"traces": len(doc["traces"]), "spans": n_spans,
            "complete": n_complete, "orphan_spans": n_orphans}
    if doc["counts"] != want:
        problems.append(
            f"{path}: counts {doc['counts']} != recomputed {want}")
    return problems


def validate_trace_artifact_file(path: str) -> List[str]:
    import json

    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable: {e}"]
    return validate_trace_artifact(doc, path=path)


# ----------------------------------------------------------- train bridge --


def trace_from_step_profile(record: Dict[str, Any],
                            trace_id: Optional[str] = None
                            ) -> List[Dict[str, Any]]:
    """Map a ``pvraft_step_profile/v1`` record's telescoped per-stage
    breakdown onto the span schema: one ``train_step`` root of
    ``total_step_s`` plus consecutive child spans in breakdown order
    (the stages telescope, so laying them end-to-end IS the measured
    decomposition). Gives train and serve the same trace format without
    re-instrumenting the jitted step (which would break the
    telemetry-off jaxpr guarantee)."""
    if "breakdown_s" not in record or "total_step_s" not in record:
        raise ValueError(
            "step-profile record has no breakdown (incomplete ladder); "
            "cannot build a trace")
    tid = trace_id or new_trace_id()
    root_id = uuid.uuid4().hex[:12]
    spans: List[Dict[str, Any]] = [{
        "trace_id": tid, "span_id": root_id, "name": TRAIN_ROOT,
        "start_ms": 0.0,
        "end_ms": round(record["total_step_s"] * 1000.0, 3),
        "attrs": {"platform": record.get("platform"),
                  "variant": record.get("variant"),
                  "points": record.get("points"),
                  "batch": record.get("batch"),
                  "iters": record.get("iters")},
    }]
    cursor = 0.0
    for i, (stage, sec) in enumerate(record["breakdown_s"].items()):
        # Sub-noise stages can telescope slightly negative (validator
        # tolerance); clamp so the span stays schema-legal while the
        # profile artifact keeps the signed truth.
        dur_ms = max(0.0, sec * 1000.0)
        spans.append({
            "trace_id": tid, "span_id": f"{root_id}.{i}",
            "parent_id": root_id, "name": stage,
            "start_ms": round(cursor, 3),
            "end_ms": round(cursor + dur_ms, 3),
        })
        cursor += dur_ms
    return spans
