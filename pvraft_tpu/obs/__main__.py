"""CLI: validate ``pvraft_events/v1`` JSONL files.

    python -m pvraft_tpu.obs validate artifacts/*.events.jsonl

Exits non-zero on any schema problem — wired into ``scripts/lint.sh`` so
a malformed committed event log fails the standing gate, same as a lint
finding.
"""

from __future__ import annotations

import argparse
import sys

from pvraft_tpu.obs.events import validate_events_file


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("python -m pvraft_tpu.obs")
    sub = parser.add_subparsers(dest="cmd", required=True)
    val = sub.add_parser(
        "validate", help="validate pvraft_events/v1 JSONL files")
    val.add_argument("paths", nargs="+", help="event-log files")
    args = parser.parse_args(argv)

    failed = 0
    for path in args.paths:
        try:
            problems = validate_events_file(path)
        except OSError as e:
            problems = [f"{path}: unreadable: {e}"]
        if problems:
            failed += 1
            for p in problems:
                print(p, file=sys.stderr)
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
