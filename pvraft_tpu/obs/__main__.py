"""CLI: validate the obs subsystem's committed artifacts.

    python -m pvraft_tpu.obs validate artifacts/*.events.jsonl
    python -m pvraft_tpu.obs validate-trace artifacts/*.trace.json
    python -m pvraft_tpu.obs validate-slo artifacts/*.slo.json
    python -m pvraft_tpu.obs validate-bench artifacts/bench_baseline.json
    python -m pvraft_tpu.obs validate-capacity artifacts/capacity_report.json
    python -m pvraft_tpu.obs validate-calibration artifacts/serve_calibration.json

Each subcommand exits non-zero on any schema problem — all are wired
into ``scripts/lint.sh`` so a malformed committed event log, trace
artifact, SLO report, bench artifact, capacity plan or calibration
evidence fails the standing gate, same as a lint finding. (The
capacity plan's regenerate-and-compare half lives in
``scripts/capacity_report.py --check``.)
"""

from __future__ import annotations

import argparse
import sys

from pvraft_tpu.obs.bench import validate_bench_file
from pvraft_tpu.obs.calibration import validate_calibration_file
from pvraft_tpu.obs.capacity import validate_capacity_file
from pvraft_tpu.obs.events import validate_events_file
from pvraft_tpu.obs.slo import validate_slo_report_file
from pvraft_tpu.obs.trace import validate_trace_artifact_file


def _run(paths, validate) -> int:
    failed = 0
    for path in paths:
        try:
            problems = validate(path)
        except OSError as e:
            problems = [f"{path}: unreadable: {e}"]
        if problems:
            failed += 1
            for p in problems:
                print(p, file=sys.stderr)
        else:
            print(f"{path}: OK")
    return 1 if failed else 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("python -m pvraft_tpu.obs")
    sub = parser.add_subparsers(dest="cmd", required=True)
    val = sub.add_parser(
        "validate", help="validate pvraft_events/v1 JSONL files")
    val.add_argument("paths", nargs="+", help="event-log files")
    val.set_defaults(validate=validate_events_file)
    tr = sub.add_parser(
        "validate-trace", help="validate pvraft_trace/v1 artifacts")
    tr.add_argument("paths", nargs="+", help="trace artifacts")
    tr.set_defaults(validate=validate_trace_artifact_file)
    slo = sub.add_parser(
        "validate-slo", help="validate pvraft_slo/v1 reports")
    slo.add_argument("paths", nargs="+", help="SLO reports")
    slo.set_defaults(validate=validate_slo_report_file)
    bench = sub.add_parser(
        "validate-bench", help="validate pvraft_bench/v1 artifacts")
    bench.add_argument("paths", nargs="+", help="bench artifacts")
    bench.set_defaults(validate=validate_bench_file)
    cap = sub.add_parser(
        "validate-capacity", help="validate pvraft_capacity/v1 plans")
    cap.add_argument("paths", nargs="+", help="capacity plans")
    cap.set_defaults(validate=validate_capacity_file)
    cal = sub.add_parser(
        "validate-calibration",
        help="validate pvraft_cost_calibration/v1 evidence")
    cal.add_argument("paths", nargs="+", help="calibration artifacts")
    cal.set_defaults(validate=validate_calibration_file)
    args = parser.parse_args(argv)
    return _run(args.paths, args.validate)


if __name__ == "__main__":
    sys.exit(main())
