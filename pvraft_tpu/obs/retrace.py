"""Retrace watchdog: the runtime complement of deepcheck's static GJ007.

GJ007 proves a program's *build* is retrace-deterministic; nothing so
far observed retraces actually happening at runtime — a shape-polymorphic
batch, a python-scalar weak-type flip, or a config mutation mid-run each
silently recompile a multi-minute program and the only symptom is a
mysterious slow step. The watchdog makes that a first-class event:

* **Per-program jit-cache counting** (the Trainer step loop): every
  registered step program (``train_step``, ``packed_train_step``,
  ``multistep_train_step``, ``eval_step`` — the same pjit names the
  program registry audits) is watched via its jit cache entry count
  (``compat.jit_cache_size``). The first entry is warmup; any growth
  past the learned baseline emits a ``recompile`` event on the
  ``pvraft_events/v1`` stream with the offending program and the
  triggering call's abstract arg signature, and raises
  :class:`RetraceError` under ``--strict_retrace``.

* **Sealed mode** (the serve replica executors): after AOT startup the
  program set is closed — no compile is ever legitimate. ``seal()``
  registers a process-wide backend-compile listener
  (``compat.register_compile_listener``); any compile observed after the
  seal trips the next ``check()``. The listener only counts (no I/O, no
  locks beyond one int) — trips are reported from the calling thread so
  strict mode raises somewhere an executor can fail the batch loudly.

Cost when armed: one integer compare per watched program per check —
host-side only, no jaxpr anywhere changes (the
``engine.train_step[telemetry_off_jaxpr]`` guarantee is untouched).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from pvraft_tpu.analysis.concurrency.sanitizer import ordered_lock
from pvraft_tpu.compat import (
    jit_cache_size,
    register_compile_listener,
    unregister_compile_listener,
)


class RetraceError(RuntimeError):
    """A watched program recompiled after warmup under strict mode."""


def args_signature(args: Any) -> str:
    """Compact ``dtype[shape]`` rendering of a call's arg pytree — what
    the ``recompile`` event records so the offending geometry is on the
    stream, not lost to a log grep."""
    import jax
    import numpy as np

    def one(x) -> str:
        if hasattr(x, "shape") and hasattr(x, "dtype"):
            return (f"{np.dtype(x.dtype).name}"
                    f"[{','.join(map(str, x.shape))}]")
        return type(x).__name__
    leaves = jax.tree_util.tree_leaves(args)
    sig = ",".join(one(x) for x in leaves[:16])
    if len(leaves) > 16:
        sig += f",...(+{len(leaves) - 16} leaves)"
    return sig


class RetraceWatchdog:
    """Counts compiles after warmup; emits ``recompile`` events and
    (in strict mode) raises :class:`RetraceError` from ``check()``.

    ``emit`` is the event sink — ``RunTelemetry.emit_recompile`` or
    ``ServeTelemetry.emit_recompile`` (both lock-serialized), or None
    for count-only operation. Thread-safe: ``check`` may be called from
    batcher executors concurrently."""

    def __init__(self, emit: Optional[Callable[..., Any]] = None,
                 strict: bool = False, context: str = "train"):
        self.emit = emit
        self.strict = strict
        self.context = context
        self._lock = ordered_lock("RetraceWatchdog._lock")
        self.trips = 0  # guarded-by: _lock
        # name -> [jitted, baseline or None]; baseline None = warmup not
        # seen yet (the program's first cache entry is legitimate).
        self._watched: Dict[str, List[Any]] = {}  # guarded-by: _lock
        self._sealed = False  # guarded-by: _lock
        self._global_compiles = 0  # guarded-by: _lock
        self._global_baseline = 0  # guarded-by: _lock
        self._listener = None  # guarded-by: _lock

    # ---------------------------------------------------------- watching --

    def watch(self, name: str, jitted) -> None:
        """Track one jitted program by name. Programs whose jax no
        longer exposes a cache counter are skipped (the watchdog must
        never break training over an introspection API)."""
        if jit_cache_size(jitted) < 0:
            return
        with self._lock:
            self._watched[name] = [jitted, None]

    def seal(self) -> bool:
        """Close the program set (serve: after AOT startup). From here
        on ANY backend compile in the process is a trip. Returns False
        when the monitoring API is unavailable (caller logs that the
        watchdog is disarmed)."""
        def on_event(name: str, *args: Any, **kw: Any) -> None:
            if name.endswith("backend_compile_duration"):
                with self._lock:
                    self._global_compiles += 1

        if not register_compile_listener(on_event):
            return False
        with self._lock:
            self._listener = on_event
            self._sealed = True
            self._global_baseline = self._global_compiles
        return True

    def close(self) -> None:
        """Unhook the global listener (tests arm/disarm repeatedly).
        The swap runs under the lock (threadcheck GC003: the old
        test-then-assign let two concurrent closers both see the same
        listener and double-unregister it); the jax-side unregister call
        happens after release — it takes jax's own monitoring lock, and
        holding ours across a foreign lock is how order cycles start."""
        with self._lock:
            listener, self._listener = self._listener, None
            self._sealed = False
        if listener is not None:
            unregister_compile_listener(listener)

    def inject_compile(self) -> None:
        """Fault-injection hook (serve/faults.py ``compile_trip``):
        count one simulated backend compile, exactly as the monitoring
        listener would — so an injected trip exercises the REAL
        sealed-mode path (dispatch-window check -> recompile event ->
        strict-mode failure) instead of a parallel fake."""
        with self._lock:
            self._global_compiles += 1

    def global_compiles(self) -> int:
        """Current process-wide compile count (sealed mode). Dispatchers
        read this BEFORE running a program and pass it to ``check`` as
        ``window_start``, so only compiles that land DURING the dispatch
        window trip — a co-resident engine AOT-compiling its own table
        in the same process (the serve_ab.py two-leg pattern) must not
        false-trip an idle service's next dispatch."""
        with self._lock:
            return self._global_compiles

    # ---------------------------------------------------------- checking --

    def check(self, signature: Any = None,
              program: Optional[str] = None,
              window_start: Optional[int] = None) -> List[Dict[str, Any]]:
        """One watchdog pass: compare every watched program's cache size
        against its baseline (learning the baseline at first sight), and
        in sealed mode compare the global compile counter. Returns the
        trip records (after emitting them); raises :class:`RetraceError`
        in strict mode when anything tripped. ``signature`` may be a
        string or a zero-arg callable (resolved only on a trip, so the
        hot-loop cost of a no-trip check stays one int compare).

        ``window_start`` (sealed mode): a :meth:`global_compiles` value
        read before the dispatch — only compiles landing AFTER it trip,
        so a co-resident engine compiling its own startup table between
        dispatches is not pinned on the next request. Without it, the
        baseline is the previous check (every compile since then trips)."""
        trips: List[Dict[str, Any]] = []
        with self._lock:
            for name, slot in self._watched.items():
                size = jit_cache_size(slot[0])
                if size < 0:
                    continue
                if slot[1] is None:
                    if size > 0:
                        slot[1] = size  # warmup: first compile is the program
                    continue
                if size > slot[1]:
                    trips.append({"program": name, "count": size,
                                  "baseline": slot[1]})
                    # One growth = one event; the new size becomes the
                    # baseline so a persistently re-tracing program does
                    # not flood the stream with one event per step.
                    slot[1] = size
            if self._sealed:
                # max() with the ratchet: two concurrent dispatches that
                # both captured a window BEFORE one compile landed must
                # not both trip on it — the first reporter ratchets the
                # baseline past the compile, disarming the second's
                # stale window.
                start = (max(window_start, self._global_baseline)
                         if window_start is not None
                         else self._global_baseline)
                if self._global_compiles > start:
                    trips.append({
                        "program": program or "<sealed>",
                        "count": self._global_compiles,
                        "baseline": start,
                    })
                # Ratchet past everything seen either way: already-
                # reported (or out-of-window) compiles must not re-trip
                # a later default-baseline check.
                self._global_baseline = self._global_compiles
            self.trips += len(trips)
        if trips and callable(signature):
            signature = signature()
        for trip in trips:
            if self.emit is not None:
                self.emit(program=trip["program"], count=trip["count"],
                          baseline=trip["baseline"], signature=signature,
                          context=self.context)
        if trips and self.strict:
            worst = trips[0]
            raise RetraceError(
                f"program {worst['program']!r} recompiled after warmup "
                f"(jit cache {worst['baseline']} -> {worst['count']}"
                + (f", args {signature}" if signature else "")
                + ") — a retrace on the hot path recompiles a multi-"
                "minute program per occurrence; find the varying "
                "shape/dtype/static-arg (deepcheck GJ007 probes the "
                "static cases) or drop --strict_retrace to observe only")
        return trips
