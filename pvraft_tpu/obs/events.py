"""Structured run events: the ``pvraft_events/v1`` JSONL schema.

One run = one append-only JSONL file whose first record is a
``run_header`` (config + git + device metadata) followed by typed events.
The schema is the machine-readable ledger of run health: every consumer
— TensorBoard scalars, the text log, the divergence doctor, future
dashboards — reads the SAME stream instead of each subsystem logging its
own private format (``RunTelemetry`` below is that fan-out).

Schema (every record):

    {"schema": "pvraft_events/v1", "type": <event type>, "time": <unix>,
     "seq": <monotonic per-file index>, ...type-specific fields}

Event types and their required fields:

    run_header  run_id, mode, config, git{commit,dirty}, devices
                {platform, device_count, process_index, process_count},
                versions{jax}
    step        epoch, step, loss, epe        [+ telemetry{...}]
    epoch_summary  epoch, steps               [+ loss, epe, step_ms,
                cost{program, basis, predicted_step_ms, step_ratio,
                hw_utilization, platform, comparable} — the cost-
                surface honesty block: measured step time vs the
                inventory's flagship-geometry prediction; comparable
                may be true only on platform "tpu"]
    eval        mode, epoch, scenes, metrics
    checkpoint  epoch, kind                   [+ path]
    trace_window  action ("start"|"stop"), trace_dir, epoch
    divergence  epoch, step, reason ("nonfinite"|"zscore"), loss
                [+ zscore, snapshot]
    snapshot    epoch, step, path, reason

Serving events (``pvraft_tpu/serve``) share the stream — ONE validator
covers training and serving telemetry:

    serve_compile  bucket, batch, lower_s, compile_s  [+ memory, dtype,
                   replica, device_id]
    serve_batch    bucket, batch, n, fill, latency_ms [+ queue_depth,
                   replica, device_id]
    serve_reject   reason ("queue_full"|"too_large"|"too_small"|
                   "bad_request"|"shutdown"|"timeout"|"internal"|
                   "unavailable")                     [+ bucket, queue_depth]
    serve_shutdown served, rejected, drained
    cost_calibration bucket, batch, dtype, predicted_s, measured_s,
                   platform, comparable  [+ replica, basis, extrapolated,
                   program] — one dispatch priced through the cost
                   surface (serve/costing.py) next to its measured
                   wall-seconds. ``comparable`` may be true ONLY on
                   platform "tpu" (the pvraft_bench/v1 lesson: a CPU
                   wall clock next to an XLA optimal-seconds prediction
                   is recorded but never enforceable — the schema makes
                   the silent-CPU-fallback comparison unrepresentable)

Fault-tolerance events (``pvraft_tpu/serve/supervisor.py``,
``pvraft_tpu/serve/faults.py``) ride the same stream:

    replica_state  replica, state   [+ from_state, reason, device_id] —
                one supervisor state-machine transition; ``state`` (and
                ``from_state`` when present) must be one of
                ``REPLICA_STATES`` (healthy|degraded|quarantined|probing)
    fault_injected point            [+ replica, bucket, traversal,
                fires, value] — one deterministic fault-point firing
                (an armed FaultPlan rule matched this traversal);
                ``point`` must be one of ``FAULT_POINTS``

Tracing events (``pvraft_tpu/obs/trace.py``) ride the same stream:

    span        trace_id, span_id, name, start_ms, end_ms
                [+ parent_id, attrs] — one request/step stage interval;
                ``end_ms >= start_ms`` is enforced (a reversed span is a
                clock bug, not data)
    slo_report  path, slo_p99_ms    [+ max_qps_under_slo, programs,
                requests] — pointer to a written pvraft_slo/v1 report

Fleet events (``pvraft_tpu/fleet``) ride the same stream — the router
tier emits next to the backends it fans out over:

    fleet_route   backend, reason  [+ bucket, queue_depth, predicted_s,
                attempts, canary, status] — one routing decision: which
                backend got a request and why; ``reason`` must be one of
                ``FLEET_ROUTE_REASONS`` (least_loaded = normal pick,
                spillover = first choice shed and the request was
                re-offered, canary = interleaved onto the canary
                backend, shadow = the mirrored reference copy of a
                canary request)
    weight_swap   digest, epoch    [+ path, previous_digest, replicas,
                swap_ms, drained] — one zero-downtime hot-swap: the
                params pointer of every replica was replaced (no
                recompile — AOT programs take params as arguments);
                ``epoch`` carries the checkpoint's epoch or the ``-1``
                epoch-less sentinel (engine/checkpoint.load_params)
    canary_verdict verdict, epe, bound  [+ rel_epe, rel_bound, samples,
                fraction, canary_backend, baseline_backend] — the
                router's promotion gate fired: mean EPE between canary
                and incumbent flows over the interleaved sample versus
                the pinned bound (the bf16-promotion precedent);
                ``verdict`` must be one of ``CANARY_VERDICTS``

Performance-plane events (``pvraft_tpu/obs/retrace.py``,
``pvraft_tpu/obs/device_memory.py``) ride the same stream:

    recompile   program, count     [+ baseline, signature, context] —
                the retrace watchdog saw a registered program's jit
                cache grow past its post-warmup baseline (or, in the
                sealed serve mode, ANY backend compile after AOT
                startup); ``signature`` is the triggering call's
                abstract arg shapes/dtypes when known
    device_memory  devices         [+ context] — one periodic
                ``device.memory_stats()`` sample: a list of per-device
                rows, each ``{device_id, bytes_in_use[,
                peak_bytes_in_use, bytes_limit, platform]}``; byte
                counts must be >= 0 and ``device_id`` a non-negative
                integer (an unknown device is a writer bug, not data)

Non-finite floats are encoded as the strings ``"NaN"``/``"Infinity"``/
``"-Infinity"`` (JSON has no spelling for them; a diverging run's whole
point is to record them faithfully). ``validate_events`` accepts those
spellings anywhere a number is required.

Writing is process-0-only under multi-process JAX (every process calls
``emit``; non-zero ranks no-op) so a pod run produces ONE event file, not
``process_count`` interleaved ones.

Validate from the command line (wired into ``scripts/lint.sh``):

    python -m pvraft_tpu.obs validate artifacts/*.events.jsonl
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, IO, List, Optional

SCHEMA_VERSION = "pvraft_events/v1"

# type -> (required fields, optional fields). "seq"/"schema"/"type"/"time"
# are stamped by EventLog and required on every record.
EVENT_TYPES: Dict[str, tuple] = {
    "run_header": (
        ("run_id", "mode", "config", "git", "devices", "versions"), ()),
    "step": (("epoch", "step", "loss", "epe"), ("telemetry",)),
    "epoch_summary": (("epoch", "steps"),
                      ("loss", "epe", "step_ms", "cost")),
    "eval": (("mode", "epoch", "scenes", "metrics"), ()),
    "checkpoint": (("epoch", "kind"), ("path",)),
    "trace_window": (("action", "trace_dir", "epoch"), ()),
    "divergence": (("epoch", "step", "reason", "loss"),
                   ("zscore", "snapshot")),
    "snapshot": (("epoch", "step", "path", "reason"), ()),
    "serve_compile": (("bucket", "batch", "lower_s", "compile_s"),
                      ("memory", "dtype", "replica", "device_id")),
    "serve_batch": (("bucket", "batch", "n", "fill", "latency_ms"),
                    ("queue_depth", "replica", "device_id")),
    "serve_reject": (("reason",), ("bucket", "queue_depth")),
    "serve_shutdown": (("served", "rejected", "drained"), ()),
    "cost_calibration": (("bucket", "batch", "dtype", "predicted_s",
                          "measured_s", "platform", "comparable"),
                         ("replica", "basis", "extrapolated", "program")),
    "span": (("trace_id", "span_id", "name", "start_ms", "end_ms"),
             ("parent_id", "attrs")),
    "slo_report": (("path", "slo_p99_ms"),
                   ("max_qps_under_slo", "programs", "requests")),
    "recompile": (("program", "count"),
                  ("baseline", "signature", "context")),
    "device_memory": (("devices",), ("context",)),
    "replica_state": (("replica", "state"),
                      ("from_state", "reason", "device_id")),
    "fault_injected": (("point",),
                       ("replica", "bucket", "traversal", "fires",
                        "value")),
    "fleet_route": (("backend", "reason"),
                    ("bucket", "queue_depth", "predicted_s", "attempts",
                     "canary", "status")),
    "weight_swap": (("digest", "epoch"),
                    ("path", "previous_digest", "replicas", "swap_ms",
                     "drained")),
    "canary_verdict": (("verdict", "epe", "bound"),
                       ("rel_epe", "rel_bound", "samples", "fraction",
                        "canary_backend", "baseline_backend")),
}

# serve_reject.reason vocabulary (validated like divergence.reason).
# "timeout"/"internal" are accepted-then-failed outcomes (504/500): the
# request passed submit but never produced a response. "unavailable" is
# the graceful-degradation shed: every replica is quarantined, so the
# pool rejects at admission instead of queue-timeout 504s.
SERVE_REJECT_REASONS = (
    "queue_full", "too_large", "too_small", "bad_request", "shutdown",
    "timeout", "internal", "unavailable")

# replica_state.state vocabulary — the supervisor's health state machine
# (serve/supervisor.py): healthy -> degraded -> quarantined -> probing
# -> healthy. Lives here (with SERVE_REJECT_REASONS) so the jax-free
# validator pins it without importing the serve package.
REPLICA_STATES = ("healthy", "degraded", "quarantined", "probing")

# fault_injected.point vocabulary — the named fault points the serve
# plane threads through its executor/batcher/server (serve/faults.py
# imports THIS, not the other way round, so the validator stays
# serve-import-free).
FAULT_POINTS = (
    "replica_predict_error", "replica_latency_ms", "replica_wedge",
    "queue_stall", "compile_trip")

# fleet_route.reason vocabulary — why the router sent a request where it
# did (pvraft_tpu/fleet/router.py imports THIS, same direction as
# FAULT_POINTS, so the validator stays fleet-import-free).
FLEET_ROUTE_REASONS = ("least_loaded", "spillover", "canary", "shadow")

# canary_verdict.verdict vocabulary — the promotion gate's two outcomes.
CANARY_VERDICTS = ("promote", "reject")

_BASE_FIELDS = ("schema", "type", "time", "seq")

# Fields that must hold a number (or the non-finite string spellings).
_NUMERIC_FIELDS = {
    "step": ("epoch", "step", "loss", "epe"),
    "epoch_summary": ("epoch", "steps"),
    "eval": ("epoch", "scenes"),
    "checkpoint": ("epoch",),
    "trace_window": ("epoch",),
    "divergence": ("epoch", "step", "loss"),
    "snapshot": ("epoch", "step"),
    "serve_compile": ("bucket", "batch", "lower_s", "compile_s",
                      "replica", "device_id"),
    "serve_batch": ("bucket", "batch", "n", "fill", "latency_ms",
                    "queue_depth", "replica", "device_id"),
    "serve_reject": ("bucket", "queue_depth"),
    "serve_shutdown": ("served", "rejected", "drained"),
    "cost_calibration": ("bucket", "batch", "predicted_s", "measured_s",
                         "replica"),
    "span": ("start_ms", "end_ms"),
    "slo_report": ("slo_p99_ms", "max_qps_under_slo", "programs",
                   "requests"),
    "recompile": ("count", "baseline"),
    "replica_state": ("replica", "device_id"),
    "fault_injected": ("replica", "bucket", "traversal", "fires",
                       "value"),
    "fleet_route": ("backend", "bucket", "queue_depth", "predicted_s",
                    "attempts", "status"),
    "weight_swap": ("epoch", "replicas", "swap_ms", "drained"),
    "canary_verdict": ("epe", "bound", "rel_epe", "rel_bound", "samples",
                       "fraction", "canary_backend", "baseline_backend"),
}

# device_memory per-device row shape: required/optional keys and which
# of them are byte counts (>= 0 enforced — a negative watermark is a
# writer bug, not data).
DEVICE_MEMORY_REQUIRED = ("device_id", "bytes_in_use")
DEVICE_MEMORY_OPTIONAL = ("peak_bytes_in_use", "bytes_limit", "platform")
_DEVICE_MEMORY_BYTES = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")

_NONFINITE_STRINGS = ("NaN", "Infinity", "-Infinity")


def sanitize(value: Any) -> Any:
    """Make a value JSON-strict: non-finite floats become their string
    spellings, numpy scalars/arrays become python numbers/lists, dicts
    and lists recurse. (``json.dumps`` would happily emit bare ``NaN``,
    which is NOT valid JSON and breaks strict parsers downstream.)"""
    if isinstance(value, dict):
        return {str(k): sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [sanitize(v) for v in value]
    if hasattr(value, "tolist") and not isinstance(value, (str, bytes)):
        return sanitize(value.tolist())
    if isinstance(value, float):
        if value != value:
            return "NaN"
        if value == float("inf"):
            return "Infinity"
        if value == float("-inf"):
            return "-Infinity"
    return value


def _is_number(value: Any) -> bool:
    if isinstance(value, bool):
        return False
    if isinstance(value, (int, float)):
        return True
    return isinstance(value, str) and value in _NONFINITE_STRINGS


def validate_event(record: Any, seq: Optional[int] = None) -> List[str]:
    """Schema problems of one event record ([] = valid)."""
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, not an object"]
    problems: List[str] = []
    for key in _BASE_FIELDS:
        if key not in record:
            problems.append(f"missing base field {key!r}")
    if problems:
        return problems
    if record["schema"] != SCHEMA_VERSION:
        problems.append(
            f"schema {record['schema']!r} != {SCHEMA_VERSION!r}")
    etype = record["type"]
    if etype not in EVENT_TYPES:
        problems.append(f"unknown event type {etype!r}")
        return problems
    if not _is_number(record["time"]):
        problems.append(f"time {record['time']!r} is not a number")
    if seq is not None and record["seq"] != seq:
        problems.append(f"seq {record['seq']!r} != expected {seq}")
    required, optional = EVENT_TYPES[etype]
    for key in required:
        if key not in record:
            problems.append(f"{etype}: missing field {key!r}")
    known = set(_BASE_FIELDS) | set(required) | set(optional)
    for key in record:
        if key not in known:
            problems.append(f"{etype}: unknown field {key!r}")
    for key in _NUMERIC_FIELDS.get(etype, ()):
        if key in record and not _is_number(record[key]):
            problems.append(
                f"{etype}: field {key!r}={record[key]!r} is not a number")
    if etype == "divergence" and record.get("reason") not in (
            "nonfinite", "zscore"):
        problems.append(
            f"divergence: reason {record.get('reason')!r} must be "
            "'nonfinite' or 'zscore'")
    if etype == "trace_window" and record.get("action") not in (
            "start", "stop"):
        problems.append(
            f"trace_window: action {record.get('action')!r} must be "
            "'start' or 'stop'")
    if etype == "serve_reject" and record.get("reason") not in (
            SERVE_REJECT_REASONS):
        problems.append(
            f"serve_reject: reason {record.get('reason')!r} must be one "
            f"of {SERVE_REJECT_REASONS}")
    if etype == "replica_state":
        if record.get("state") not in REPLICA_STATES:
            problems.append(
                f"replica_state: state {record.get('state')!r} must be "
                f"one of {REPLICA_STATES}")
        if "from_state" in record \
                and record["from_state"] not in REPLICA_STATES:
            problems.append(
                f"replica_state: from_state {record['from_state']!r} "
                f"must be one of {REPLICA_STATES}")
        replica = record.get("replica")
        if _is_number(replica) and isinstance(replica, (int, float)) \
                and replica < 0:
            problems.append(
                f"replica_state: replica {replica} must be >= 0")
    if etype == "epoch_summary" and "cost" in record:
        cost = record["cost"]
        if not isinstance(cost, dict):
            problems.append("epoch_summary: cost must be an object")
        else:
            if not isinstance(cost.get("comparable"), bool):
                problems.append(
                    "epoch_summary: cost.comparable must be a bool")
            if cost.get("comparable") is True \
                    and cost.get("platform") != "tpu":
                problems.append(
                    f"epoch_summary: cost.comparable=true on platform "
                    f"{cost.get('platform')!r} — only a TPU step time "
                    "may be enforced against the inventory prediction")
    if etype == "cost_calibration":
        if not isinstance(record.get("comparable"), bool):
            problems.append(
                "cost_calibration: comparable must be a bool (the "
                "platform-honesty flag is first-class, never inferred)")
        if not isinstance(record.get("platform"), str) \
                or not record.get("platform"):
            problems.append(
                "cost_calibration: platform must be a non-empty string")
        if record.get("comparable") is True \
                and record.get("platform") != "tpu":
            problems.append(
                f"cost_calibration: comparable=true on platform "
                f"{record.get('platform')!r} — only a TPU measurement "
                "may be enforced against the TPU-topology prediction "
                "(the pvraft_bench/v1 rule)")
        for key in ("predicted_s", "measured_s"):
            v = record.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v < 0:
                problems.append(
                    f"cost_calibration: {key}={v} must be >= 0")
        if not isinstance(record.get("dtype"), str) \
                or not record.get("dtype"):
            problems.append(
                "cost_calibration: dtype must be a non-empty string")
        if "basis" in record and record["basis"] not in (
                "xla_optimal", "roofline"):
            problems.append(
                f"cost_calibration: basis {record['basis']!r} must be "
                "'xla_optimal' or 'roofline'")
        if "extrapolated" in record \
                and not isinstance(record["extrapolated"], bool):
            problems.append(
                "cost_calibration: extrapolated must be a bool")
    if etype == "fleet_route":
        if record.get("reason") not in FLEET_ROUTE_REASONS:
            problems.append(
                f"fleet_route: reason {record.get('reason')!r} must be "
                f"one of {FLEET_ROUTE_REASONS}")
        backend = record.get("backend")
        if _is_number(backend) and isinstance(backend, (int, float)) \
                and backend < 0:
            problems.append(
                f"fleet_route: backend {backend} must be >= 0")
        if "canary" in record and not isinstance(record["canary"], bool):
            problems.append("fleet_route: canary must be a bool")
    if etype == "weight_swap":
        if not isinstance(record.get("digest"), str) \
                or not record.get("digest"):
            problems.append(
                "weight_swap: digest must be a non-empty string (the "
                "params-content fingerprint a hot-swap is observable by)")
        for key in ("replicas", "swap_ms", "drained"):
            v = record.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v < 0:
                problems.append(f"weight_swap: {key}={v} must be >= 0")
    if etype == "canary_verdict":
        if record.get("verdict") not in CANARY_VERDICTS:
            problems.append(
                f"canary_verdict: verdict {record.get('verdict')!r} "
                f"must be one of {CANARY_VERDICTS}")
        for key in ("epe", "bound", "rel_epe", "rel_bound", "samples",
                    "fraction"):
            v = record.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool) \
                    and v < 0:
                problems.append(
                    f"canary_verdict: {key}={v} must be >= 0")
    if etype == "fault_injected" and record.get("point") not in (
            FAULT_POINTS):
        problems.append(
            f"fault_injected: point {record.get('point')!r} must be one "
            f"of {FAULT_POINTS}")
    if etype == "recompile":
        if not isinstance(record.get("program"), str) or not record.get(
                "program"):
            problems.append(
                "recompile: program must name the offending program")
        count = record.get("count")
        if _is_number(count) and isinstance(count, (int, float)) \
                and count < 0:
            problems.append(
                f"recompile: count {count} must be >= 0")
    if etype == "device_memory":
        rows = record.get("devices")
        if not isinstance(rows, list) or not rows:
            problems.append(
                "device_memory: devices must be a non-empty list of "
                "per-device rows")
        else:
            for i, row in enumerate(rows):
                if not isinstance(row, dict):
                    problems.append(
                        f"device_memory: devices[{i}] is not an object")
                    continue
                dev = row.get("device_id")
                if not isinstance(dev, int) or isinstance(dev, bool) \
                        or dev < 0:
                    problems.append(
                        f"device_memory: devices[{i}].device_id {dev!r} "
                        "is not a known device (non-negative integer id)")
                for key in DEVICE_MEMORY_REQUIRED[1:]:
                    if key not in row:
                        problems.append(
                            f"device_memory: devices[{i}] missing {key!r}")
                known = set(DEVICE_MEMORY_REQUIRED) | set(
                    DEVICE_MEMORY_OPTIONAL)
                for key in row:
                    if key not in known:
                        problems.append(
                            f"device_memory: devices[{i}] unknown field "
                            f"{key!r}")
                for key in _DEVICE_MEMORY_BYTES:
                    v = row.get(key)
                    if v is None:
                        continue
                    if not isinstance(v, (int, float)) \
                            or isinstance(v, bool) or v < 0:
                        problems.append(
                            f"device_memory: devices[{i}].{key}={v!r} "
                            "must be a number >= 0")
    if etype == "span":
        start, end = record.get("start_ms"), record.get("end_ms")
        if (isinstance(start, (int, float)) and isinstance(end, (int, float))
                and not isinstance(start, bool) and not isinstance(end, bool)
                and end < start):
            problems.append(
                f"span: end_ms {end} < start_ms {start} (reversed span — "
                "a clock bug, not data)")
    return problems


def validate_events(lines: List[str], path: str = "<events>") -> List[str]:
    """Problems of a whole event stream ([] = valid): every line strict
    JSON + per-record schema, first record a ``run_header``, ``seq``
    strictly sequential from 0."""
    problems: List[str] = []
    records: List[Any] = []
    for i, line in enumerate(lines):
        if not line.strip():
            problems.append(f"{path}:{i + 1}: blank line")
            continue
        try:
            # parse_constant rejects the bare NaN/Infinity tokens that a
            # naive json.dumps emits — those are NOT valid JSON and the
            # writer must use the string spellings instead.
            records.append(json.loads(
                line, parse_constant=lambda c: (_ for _ in ()).throw(
                    ValueError(f"bare {c} token (invalid strict JSON)"))))
        except ValueError as e:
            problems.append(f"{path}:{i + 1}: not strict JSON: {e}")
            records.append(None)
    if not records:
        problems.append(f"{path}: empty event stream")
        return problems
    if isinstance(records[0], dict) and records[0].get("type") != "run_header":
        problems.append(
            f"{path}:1: first record must be run_header, got "
            f"{records[0].get('type')!r}")
    for i, record in enumerate(records):
        if record is None:
            continue
        for p in validate_event(record, seq=i):
            problems.append(f"{path}:{i + 1}: {p}")
    return problems


def validate_events_file(path: str) -> List[str]:
    with open(path, "r", encoding="utf-8") as f:
        return validate_events(f.read().splitlines(), path=path)


def _git_metadata(repo_dir: Optional[str] = None) -> Dict[str, Any]:
    """Best-effort {commit, dirty}; never raises (training must not fail
    because the run dir is not a git checkout)."""
    import subprocess

    cwd = repo_dir or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        commit = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, capture_output=True,
            text=True, timeout=5, check=True).stdout.strip()
        dirty = bool(subprocess.run(
            ["git", "status", "--porcelain"], cwd=cwd, capture_output=True,
            text=True, timeout=5, check=True).stdout.strip())
        return {"commit": commit, "dirty": dirty}
    except Exception:
        return {"commit": None, "dirty": None}


def run_metadata(cfg=None, mode: str = "train") -> Dict[str, Any]:
    """The run_header payload: config, git, devices, versions."""
    import dataclasses

    import jax

    config = (
        sanitize(dataclasses.asdict(cfg)) if dataclasses.is_dataclass(cfg)
        else sanitize(cfg or {})
    )
    return {
        "run_id": f"{mode}-{os.getpid()}-{int(time.time())}",
        "mode": mode,
        "config": config,
        "git": _git_metadata(),
        "devices": {
            "platform": jax.devices()[0].platform,
            "device_count": jax.device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        },
        "versions": {"jax": jax.__version__},
    }


class EventLog:
    """Append-only ``pvraft_events/v1`` JSONL writer.

    Process-0-only by default: non-zero ranks construct fine and every
    ``emit`` is a no-op, so callers never branch on rank. Each record is
    validated on emit — an invalid event is a programmer error and raises
    immediately rather than poisoning the file."""

    def __init__(self, path: str, enabled: Optional[bool] = None):
        if enabled is None:
            import jax

            enabled = jax.process_index() == 0
        self.path = path
        self.enabled = bool(enabled)
        self.seq = 0
        self._f: Optional[IO[str]] = None
        if self.enabled:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            needs_newline = False
            if os.path.exists(path) and os.path.getsize(path) > 0:
                # Resumed run (train.py --resume reuses the exp dir):
                # continue the seq chain where the previous process left
                # off, or appended records would fail their own
                # validator ('seq != expected'). A crash can leave a
                # partial final line (no trailing newline); terminate it
                # so the new records don't merge onto it — that one
                # truncated record stays invalid (its bytes are gone),
                # but the seq chain and every later record stay clean.
                with open(path, "rb") as f:
                    data = f.read()
                newlines = data.count(b"\n")
                needs_newline = not data.endswith(b"\n")
                self.seq = newlines + (1 if needs_newline else 0)
            self._f = open(path, "a", encoding="utf-8")
            if needs_newline:
                self._f.write("\n")
                self._f.flush()

    def emit(self, etype: str, **fields: Any) -> Optional[Dict[str, Any]]:
        if not self.enabled:
            return None
        record: Dict[str, Any] = {
            "schema": SCHEMA_VERSION,
            "type": etype,
            "time": round(time.time(), 3),
            "seq": self.seq,
        }
        record.update(sanitize(fields))
        problems = validate_event(record, seq=self.seq)
        if problems:
            raise ValueError(
                f"invalid {etype!r} event: {problems} (record={record!r})")
        assert self._f is not None
        self._f.write(json.dumps(record, sort_keys=True) + "\n")
        self._f.flush()
        self.seq += 1
        return record

    def close(self) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None
        self.enabled = False

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class RunTelemetry:
    """The unified run sink: ONE ``emit`` call per happening, fanned out
    to the structured event log (JSONL), TensorBoard scalars, and the
    text ``ExperimentLog`` — the pre-existing consumers re-plumbed over
    the event stream instead of each being called ad hoc.

    TB tag mapping (reference tag names preserved, ``tools/engine.py:
    149-158,209-234``): ``step`` events write ``Train/Loss``+``Train/EPE``
    at the global step; ``eval`` events write ``<Mode>/<Metric>`` at the
    epoch; telemetry sub-leaves write under ``telemetry/...``."""

    # eval metric key -> reference TB tag suffix.
    _EVAL_TAGS = (
        ("loss", "Loss"), ("epe3d", "EPE"), ("outlier", "Outlier"),
        ("acc3d_relax", "Acc3dRelax"), ("acc3d_strict", "Acc3dStrict"),
    )

    def __init__(self, exp_path: str, mode: str = "Train",
                 dataset: str = "", events_name: Optional[str] = None):
        from pvraft_tpu.utils.logging import ExperimentLog, TBWriter

        self.log = ExperimentLog(exp_path, mode, dataset)
        self.tb = TBWriter(os.path.join(exp_path, "logs"))
        name = events_name or f"{mode.lower()}.events.jsonl"
        self.events = EventLog(os.path.join(exp_path, name))

    def info(self, msg: str) -> None:
        self.log.info(msg)

    def emit_header(self, cfg=None, mode: str = "train") -> None:
        self.events.emit("run_header", **run_metadata(cfg, mode=mode))

    def emit_step(self, epoch: int, step: int, loss: float, epe: float,
                  telemetry: Optional[Dict[str, Any]] = None) -> None:
        fields: Dict[str, Any] = {
            "epoch": epoch, "step": step, "loss": loss, "epe": epe}
        if telemetry is not None:
            fields["telemetry"] = telemetry
        self.events.emit("step", **fields)
        self.tb.add_scalar("Train/Loss", loss, step)
        self.tb.add_scalar("Train/EPE", epe, step)
        if telemetry is not None:
            for key in ("grad_norm", "update_ratio"):
                if key in telemetry:
                    self.tb.add_scalar(
                        f"telemetry/{key}", telemetry[key], step)

    def emit_epoch_summary(self, epoch: int, steps: int, **extra) -> None:
        self.events.emit("epoch_summary", epoch=epoch, steps=steps, **extra)

    def emit_eval(self, mode: str, epoch: int, scenes: int,
                  metrics: Dict[str, float]) -> None:
        self.events.emit("eval", mode=mode, epoch=epoch, scenes=scenes,
                         metrics=metrics)
        tag = mode.capitalize()
        for key, suffix in self._EVAL_TAGS:
            if key in metrics:
                self.tb.add_scalar(f"{tag}/{suffix}", metrics[key], epoch)

    def emit_checkpoint(self, epoch: int, kind: str,
                        path: Optional[str] = None) -> None:
        fields: Dict[str, Any] = {"epoch": epoch, "kind": kind}
        if path is not None:
            fields["path"] = path
        self.events.emit("checkpoint", **fields)

    def emit_trace_window(self, action: str, trace_dir: str,
                          epoch: int) -> None:
        self.events.emit("trace_window", action=action,
                         trace_dir=trace_dir, epoch=epoch)

    def emit_divergence(self, epoch: int, step: int, reason: str,
                        loss: float, zscore: Optional[float] = None,
                        snapshot: Optional[str] = None) -> None:
        fields: Dict[str, Any] = {
            "epoch": epoch, "step": step, "reason": reason, "loss": loss}
        if zscore is not None:
            fields["zscore"] = zscore
        if snapshot is not None:
            fields["snapshot"] = snapshot
        self.events.emit("divergence", **fields)
        self.log.info(
            f"DIVERGENCE at epoch {epoch} step {step}: {reason} "
            f"(loss={loss})" + (f" snapshot={snapshot}" if snapshot else ""))

    def emit_snapshot(self, epoch: int, step: int, path: str,
                      reason: str) -> None:
        self.events.emit("snapshot", epoch=epoch, step=step, path=path,
                         reason=reason)

    def emit_span(self, **span: Any) -> None:
        """One ``span`` record (pvraft_trace/v1 plane) — the train-side
        twin of ``ServeTelemetry.emit_span``; the step profiler's stage
        boundaries arrive here via ``obs.trace.trace_from_step_profile``."""
        self.events.emit("span", **span)

    def emit_recompile(self, program: str, count: int,
                       baseline: Optional[int] = None,
                       signature: Optional[str] = None,
                       context: Optional[str] = None) -> None:
        """The retrace watchdog (obs/retrace.py) caught a registered
        program's jit cache growing past its post-warmup baseline."""
        fields: Dict[str, Any] = {"program": program, "count": count}
        if baseline is not None:
            fields["baseline"] = baseline
        if signature is not None:
            fields["signature"] = signature
        if context is not None:
            fields["context"] = context
        self.events.emit("recompile", **fields)
        self.log.info(
            f"RECOMPILE: {program} jit cache grew to {count}"
            + (f" (baseline {baseline})" if baseline is not None else "")
            + (f" on {signature}" if signature else ""))

    def emit_device_memory(self, devices: list,
                           context: Optional[str] = None) -> None:
        """One periodic ``device.memory_stats()`` sample
        (obs/device_memory.py builds the per-device rows)."""
        fields: Dict[str, Any] = {"devices": devices}
        if context is not None:
            fields["context"] = context
        self.events.emit("device_memory", **fields)

    def close(self) -> None:
        self.events.close()
        self.tb.close()
        self.log.close()
