"""Version-fragile jax imports, resolved in ONE place.

jax moves things: ``shard_map`` lived in ``jax.experimental.shard_map``
(<= 0.4.x), became ``jax.shard_map`` later and renamed its replication
check from ``check_rep`` to ``check_vma`` along the way;
``multihost_utils`` and ``pallas`` still live under ``jax.experimental``
with no stability promise. Every such import in this package goes through
this module so a jax upgrade is a one-file change — and so the package
imports (and fails) identically on every pinned version instead of
exploding lazily at first use on some code path.

The lint rule ``GL004 fragile-jax-import`` (``pvraft_tpu.analysis``)
enforces this: it flags ``jax.experimental`` imports and known moved
symbols anywhere outside this file.
"""

from __future__ import annotations

import inspect
import os
from typing import Any

import jax


def force_host_device_count(n: int) -> None:
    """Arrange ``n`` virtual host CPU devices for the replica pool —
    must run BEFORE the jax backend initializes (the flag is read at
    backend init, not jax import). Shared by the loadgen and A/B CLIs;
    a caller-set count in XLA_FLAGS wins. Lives here so every
    determinism-relevant backend flag (detcheck GD004) is written from
    one declared owner."""
    if not n:
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}").strip()


def _resolve_shard_map():
    """The shard_map callable of the running jax, wherever it lives."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as fn  # jax <= 0.4.x

    return fn


_shard_map_impl = _resolve_shard_map()
# The replication-check kwarg was renamed check_rep -> check_vma.
_SHARD_MAP_PARAMS = frozenset(
    inspect.signature(_shard_map_impl).parameters
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool | None = None,
              **kwargs) -> Any:
    """``jax.shard_map`` on any supported jax version.

    Call with the MODERN spelling (``check_vma``); on older jax the flag is
    translated to its ``check_rep`` predecessor. Extra kwargs pass through
    untouched.
    """
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_PARAMS:
            kwargs["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_PARAMS:
            kwargs["check_rep"] = check_vma
        # Neither spelling known: drop the flag rather than TypeError —
        # it only relaxes an internal consistency check.
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


def axis_size(axis_name) -> int:
    """Static size of a mapped mesh axis, inside shard_map/pmap bodies.

    ``lax.axis_size`` only exists on newer jax; older versions spell it
    with the constant-folding ``psum(1, axis)`` idiom.
    """
    from jax import lax

    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn(axis_name)
    return lax.psum(1, axis_name)


# Resolved EAGERLY so a jax upgrade that moves the module fails here, at
# import time, with one obvious file to fix — not hours into a multi-host
# run at the first checkpoint barrier (where a one-process ImportError
# strands every other process in the collective).
try:
    from jax.experimental import multihost_utils as _multihost
except ImportError:  # pragma: no cover - exercised only on future jax
    _multihost = None


def _require_multihost():
    if _multihost is None:
        raise ImportError(
            "jax.experimental.multihost_utils is gone on this jax version; "
            "update pvraft_tpu/compat.py with its new home"
        )
    return _multihost


def sync_global_devices(tag: str) -> None:
    """``multihost_utils.sync_global_devices`` (cross-process barrier)."""
    _require_multihost().sync_global_devices(tag)


def process_allgather(x, *, tiled: bool = False):
    """``multihost_utils.process_allgather`` (host-level allgather)."""
    return _require_multihost().process_allgather(x, tiled=tiled)


def import_pallas():
    """The pallas module (``jax.experimental.pallas`` on current jax)."""
    from jax.experimental import pallas  # no stable home yet

    return pallas


def import_pallas_tpu():
    """The pallas TPU extension module (``jax.experimental.pallas.tpu``).

    Home of the TPU-only memory-space constructors (``pltpu.VMEM`` /
    ``pltpu.SMEM``) used for persistent scratch allocations in
    multi-phase kernels. No stable home yet, so routed here like
    :func:`import_pallas`."""
    from jax.experimental.pallas import tpu as pallas_tpu

    return pallas_tpu


def checkpoint_policies():
    """``jax.checkpoint_policies`` — the rematerialization policy
    namespace. Routed here because the remat utilities have moved homes
    before (``jax.remat`` -> ``jax.checkpoint``, ``checkpoint_name`` out
    of ``jax.experimental``)."""
    ns = getattr(jax, "checkpoint_policies", None)
    if ns is None:  # pragma: no cover - exercised only on future jax
        raise ImportError(
            "jax.checkpoint_policies is gone on this jax version; update "
            "pvraft_tpu/compat.py with its new home"
        )
    return ns


def checkpoint_name(x, name: str):
    """``jax.ad_checkpoint.checkpoint_name``: tag a value so a
    ``save_only_these_names`` remat policy can save exactly it."""
    from jax.ad_checkpoint import checkpoint_name as fn

    return fn(x, name)


def jit_cache_size(jitted) -> int:
    """Entries in a ``jax.jit`` wrapper's compiled-program cache, or -1
    when the running jax no longer exposes the counter.

    The retrace watchdog (``pvraft_tpu/obs/retrace.py``) counts these
    per registered program after warmup: growth means a silent retrace
    (new shapes/dtypes/static args), the runtime complement of
    deepcheck's static GJ007. ``_cache_size`` is private-but-stable
    (jax's own tests use it) — routed here so a rename degrades the
    watchdog to "unavailable" instead of crashing the train loop."""
    fn = getattr(jitted, "_cache_size", None)
    if fn is None:  # pragma: no cover - exercised only on future jax
        return -1
    try:
        return int(fn())
    except Exception:  # pragma: no cover - exercised only on future jax
        return -1


def register_compile_listener(callback) -> bool:
    """Register ``callback(event_name, duration_s)`` for jax's
    ``/jax/core/compile/backend_compile_duration`` monitoring events —
    the serve-side retrace watchdog's "anything compiled at all" signal
    (after AOT startup no compile is ever legitimate). Returns False
    when the monitoring API is unavailable (the watchdog reports itself
    disarmed instead of silently watching nothing)."""
    register = getattr(getattr(jax, "monitoring", None),
                       "register_event_duration_secs_listener", None)
    if register is None:  # pragma: no cover - exercised only on future jax
        return False
    register(callback)
    return True


def unregister_compile_listener(callback) -> None:
    """Best-effort removal of a :func:`register_compile_listener`
    callback (tests arm and disarm watchdogs repeatedly; the public
    monitoring API has no unregister yet)."""
    try:
        from jax._src import monitoring as _monitoring

        _monitoring._unregister_event_duration_listener_by_callback(
            callback)
    except Exception:  # pragma: no cover - listener leak is benign
        pass


def eqn_user_frame(source_info):
    """``(file_name, line)`` of the first non-jax frame that issued a
    jaxpr equation, or ``None``.

    The deepcheck analyzer (``pvraft_tpu.analysis.jaxpr``) uses this to
    anchor jaxpr-level findings to the source line that emitted the
    primitive, so the standard ``# graftlint: disable=...`` suppressions
    apply. ``source_info_util`` is a private jax module with no stable
    home — routed here so an upgrade that moves it degrades anchoring
    (findings fall back to the audit-entry site) instead of breaking the
    analyzer."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(source_info)
    except Exception:  # pragma: no cover - exercised only on future jax
        return None
    if frame is None:
        return None
    return frame.file_name, frame.start_line
