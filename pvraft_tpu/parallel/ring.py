"""Ring sequence-parallel truncated correlation.

Long-context path (SURVEY.md §5): the memory wall of PV-RAFT is the dense
(N1, N2) correlation volume (``model/corr.py:96-99`` — 256 MB fp32 at 8,192
points, 1 GB at 16,384). Here both point axes are sharded over the ``seq``
mesh axis and the N2 chunks circulate around the ring with ``ppermute``
(the ring-attention pattern applied to correlation): each device holds
fmap1/N1-shard permanently, receives one fmap2/xyz2 chunk per ring step,
folds it into a running top-k of size K, and forwards the chunk over ICI
— P-1 hops total; the chunk held at the final fold is not sent onward
(its receive would be dead, deepcheck rule GJ002).
Peak memory per device: O(N1/P * (K + N2/P)) — the full volume is never
materialized anywhere.

Compose with ``shard_map``: call inside a shard-mapped function whose specs
shard fmap1 rows and fmap2/xyz2 rows over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.compat import axis_size
from pvraft_tpu.ops.corr import CorrState, merge_topk_xyz


def ring_knn_indices(
    query: jnp.ndarray,
    db: jnp.ndarray,
    k: int,
    axis_name: str,
) -> jnp.ndarray:
    """Global kNN indices via a ppermute ring — the sequence-parallel
    equivalent of ``ops.geometry.knn_indices`` (dense (N, N) matrix at
    ``model/flot/graph.py:53-57``; 1 GB fp32 at 16,384 points).

    query: (B, Nq/P, 3) — this device's query rows (resident).
    db: (B, Nd/P, 3) — this device's candidate chunk (circulates).
    Returns (B, Nq/P, k) int32 indices into the GLOBAL db ordering,
    nearest first (self included when query is db — ``graph.py:60``).
    """
    p = axis_size(axis_name)
    me = lax.axis_index(axis_name)
    b, nq, _ = query.shape
    chunk = db.shape[1]
    perm = [(i, (i + 1) % p) for i in range(p)]
    q2 = jnp.sum(query * query, axis=-1, keepdims=True)      # (B, Nq, 1)

    def fold(i, best_v, best_i, db_c):
        src = (me - i) % p          # shard this chunk originated from
        p2 = jnp.sum(db_c * db_c, axis=-1)[:, None, :]       # (B, 1, chunk)
        # f32 accumulation pinned: neighbor selection must match the
        # dense path (ops/geometry.pairwise_sqdist) bit for bit under
        # any compute_dtype — precision-flow discipline, deepcheck GJ006.
        cross = jnp.einsum(
            "bnc,bmc->bnm", query, db_c, preferred_element_type=jnp.float32
        )
        negd = -(q2 + p2 - 2.0 * cross)                      # (B, Nq, chunk)
        gidx = jnp.broadcast_to(
            (src * chunk + jnp.arange(chunk, dtype=jnp.int32))[None, None, :],
            negd.shape,
        )
        cand_v = jnp.concatenate([best_v, negd], axis=-1)
        cand_i = jnp.concatenate([best_i, gidx], axis=-1)
        new_v, sel = lax.top_k(cand_v, k)
        new_i = jnp.take_along_axis(cand_i, sel, axis=-1)
        return new_v, new_i

    def body(i, state):
        best_v, best_i, db_c = state
        best_v, best_i = fold(i, best_v, best_i, db_c)
        db_c = lax.ppermute(db_c, axis_name, perm)
        return best_v, best_i, db_c

    state = (
        # f32 like the fold output (pinned accumulation), matching
        # ring_corr_init's init_v — a bf16 query must not give the loop
        # a carry-dtype mismatch.
        jnp.full((b, nq, k), -jnp.inf, jnp.float32),
        jnp.zeros((b, nq, k), jnp.int32),
        db,
    )
    # p-1 fold+forward iterations, then the final fold OUTSIDE the loop:
    # the last chunk needs no onward send, so the ring issues p-1 hops,
    # not p (the p-th permute's result was dead — deepcheck GJ002).
    if p > 1:
        state = lax.fori_loop(0, p - 1, body, state)
    _, best_i = fold(p - 1, *state)
    return best_i


def seq_sharded_graph(pc: jnp.ndarray, k: int, mesh) -> "Graph":
    """kNN graph of a cloud with itself, computed sequence-parallel over
    the mesh ``seq`` axis (``shard_map`` + :func:`ring_knn_indices`).
    Returns the same global ``Graph`` as ``ops.geometry.build_graph``."""
    from jax.sharding import PartitionSpec as P

    from pvraft_tpu.compat import shard_map
    from pvraft_tpu.ops.geometry import Graph, gather_neighbors

    seq = mesh.shape["seq"]
    n = pc.shape[1]
    if n % seq:
        raise ValueError(
            f"seq_shard: the mesh seq axis ({seq}) must divide the point "
            f"count ({n})"
        )
    n_data = mesh.shape.get("data", 1)
    bspec = "data" if n_data > 1 and pc.shape[0] % n_data == 0 else None
    idx = shard_map(
        lambda q, d: ring_knn_indices(q, d, k, "seq"),
        mesh=mesh,
        in_specs=(P(bspec, "seq", None), P(bspec, "seq", None)),
        out_specs=P(bspec, "seq", None),
        check_vma=False,
    )(pc, pc)
    nb = gather_neighbors(pc, idx)
    return Graph(neighbors=idx, rel_pos=nb - pc[:, :, None, :])


def ring_corr_init(
    fmap1: jnp.ndarray,
    fmap2: jnp.ndarray,
    xyz2: jnp.ndarray,
    truncate_k: int,
    axis_name: str,
) -> CorrState:
    """Per-shard truncated correlation cache via a ppermute ring.

    fmap1: (B, N1/P, D) — this device's query rows (stay resident).
    fmap2: (B, N2/P, D), xyz2: (B, N2/P, 3) — this device's candidate chunk
    (circulates). Returns a CorrState for the local N1 rows whose top-k is
    global over all N2 — bitwise-comparable to the single-device
    ``corr_init`` up to top-k tie order.
    """
    p = axis_size(axis_name)
    b, n1, d = fmap1.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    perm = [(i, (i + 1) % p) for i in range(p)]

    def fold(carry, chunk_f2, chunk_x2):
        best_v, best_x = carry
        part = jnp.einsum(
            "bnd,bcd->bnc", fmap1, chunk_f2, preferred_element_type=jnp.float32
        ) * scale
        chunk = chunk_x2.shape[1]
        part_x = jnp.broadcast_to(chunk_x2[:, None], (b, n1, chunk, 3))
        return merge_topk_xyz(best_v, best_x, part, part_x, truncate_k)

    def body(i, state):
        best_v, best_x, f2, x2 = state
        best_v, best_x = fold((best_v, best_x), f2, x2)
        # Forward the chunk to the next ring neighbor over ICI for the
        # NEXT fold; the final fold runs outside the loop so the last
        # chunk is never sent onward (deepcheck GJ002: that permute's
        # result was dead — one full hop of wasted ring traffic).
        f2 = lax.ppermute(f2, axis_name, perm)
        x2 = lax.ppermute(x2, axis_name, perm)
        return best_v, best_x, f2, x2

    init_v = jnp.full((b, n1, truncate_k), -jnp.inf, jnp.float32)
    init_x = jnp.zeros((b, n1, truncate_k, 3), xyz2.dtype)
    state = (init_v, init_x, fmap2, xyz2)
    if p > 1:
        state = lax.fori_loop(0, p - 1, body, state)
    best_v, best_x = fold((state[0], state[1]), state[2], state[3])
    return CorrState(corr=best_v, xyz=best_x)
