"""Ring sequence-parallel truncated correlation.

Long-context path (SURVEY.md §5): the memory wall of PV-RAFT is the dense
(N1, N2) correlation volume (``model/corr.py:96-99`` — 256 MB fp32 at 8,192
points, 1 GB at 16,384). Here both point axes are sharded over the ``seq``
mesh axis and the N2 chunks circulate around the ring with ``ppermute``
(the ring-attention pattern applied to correlation): each device holds
fmap1/N1-shard permanently, receives one fmap2/xyz2 chunk per ring step,
folds it into a running top-k of size K, and forwards the chunk over ICI.
Peak memory per device: O(N1/P * (K + N2/P)) — the full volume is never
materialized anywhere.

Compose with ``shard_map``: call inside a shard-mapped function whose specs
shard fmap1 rows and fmap2/xyz2 rows over ``seq``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from pvraft_tpu.ops.corr import CorrState, merge_topk_xyz


def ring_corr_init(
    fmap1: jnp.ndarray,
    fmap2: jnp.ndarray,
    xyz2: jnp.ndarray,
    truncate_k: int,
    axis_name: str,
) -> CorrState:
    """Per-shard truncated correlation cache via a ppermute ring.

    fmap1: (B, N1/P, D) — this device's query rows (stay resident).
    fmap2: (B, N2/P, D), xyz2: (B, N2/P, 3) — this device's candidate chunk
    (circulates). Returns a CorrState for the local N1 rows whose top-k is
    global over all N2 — bitwise-comparable to the single-device
    ``corr_init`` up to top-k tie order.
    """
    p = lax.axis_size(axis_name)
    b, n1, d = fmap1.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    perm = [(i, (i + 1) % p) for i in range(p)]

    def fold(carry, chunk_f2, chunk_x2):
        best_v, best_x = carry
        part = jnp.einsum(
            "bnd,bcd->bnc", fmap1, chunk_f2, preferred_element_type=jnp.float32
        ) * scale
        chunk = chunk_x2.shape[1]
        part_x = jnp.broadcast_to(chunk_x2[:, None], (b, n1, chunk, 3))
        return merge_topk_xyz(best_v, best_x, part, part_x, truncate_k)

    def body(i, state):
        best_v, best_x, f2, x2 = state
        best_v, best_x = fold((best_v, best_x), f2, x2)
        # Forward the chunk to the next ring neighbor over ICI; the last
        # fold needs no send, but a uniform loop keeps the schedule static.
        f2 = lax.ppermute(f2, axis_name, perm)
        x2 = lax.ppermute(x2, axis_name, perm)
        return best_v, best_x, f2, x2

    init_v = jnp.full((b, n1, truncate_k), -jnp.inf, jnp.float32)
    init_x = jnp.zeros((b, n1, truncate_k, 3), xyz2.dtype)
    best_v, best_x, _, _ = lax.fori_loop(
        0, p, body, (init_v, init_x, fmap2, xyz2)
    )
    return CorrState(corr=best_v, xyz=best_x)
