"""Device mesh and sharding helpers.

The TPU-native replacement for the reference's single-process
``nn.DataParallel`` (``tools/engine.py:51-55,63-64``): a ``jax.sharding.Mesh``
with a ``data`` axis (batch sharding / gradient all-reduce over ICI) and an
optional ``seq`` axis (sequence parallelism over the point dimension of the
correlation volume — see ``pvraft_tpu.parallel.ring``). Multi-host extends
the same mesh over DCN via ``jax.distributed.initialize`` — no NCCL/MPI-style
backend code; XLA emits the collectives.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SEQ_AXIS = "seq"


def make_mesh(
    n_data: Optional[int] = None,
    n_seq: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, seq) mesh. Defaults to all devices on the data axis.

    With the default device list, a mesh smaller than the host's device
    count takes the first ``n_data * n_seq`` devices (handy for tests and
    single-chip runs); an explicit ``devices`` list must match exactly.
    """
    explicit = devices is not None
    devices = list(devices if explicit else jax.devices())
    if n_data is None or n_data < 0:
        n_data = len(devices) // n_seq
    want = n_data * n_seq
    if want <= 0:
        raise ValueError(f"mesh {n_data}x{n_seq} must have >= 1 device")
    if want != len(devices):
        if explicit or want > len(devices):
            raise ValueError(
                f"mesh {n_data}x{n_seq} does not cover {len(devices)} devices"
            )
        devices = devices[:want]
    arr = np.asarray(devices).reshape(n_data, n_seq)
    return Mesh(arr, (DATA_AXIS, SEQ_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading axis sharded over data."""
    return NamedSharding(mesh, P(DATA_AXIS))


def batch_contract(per_device_batch: int, mesh: Mesh) -> tuple:
    """``(global_batch, local_batch)`` — THE per-host vs global batch
    relationship, in one place (shardcheck GS005 bans re-deriving it
    elsewhere): the global batch is ``per_device_batch`` per chip of the
    mesh data axis; each process loads the slice its local devices
    consume. Raises when the process count cannot split the global
    batch evenly — a ragged per-host share would assemble a global
    array whose rows disagree across hosts."""
    n_data = mesh.shape[DATA_AXIS]
    global_batch = per_device_batch * n_data
    n_proc = max(1, jax.process_count())
    if global_batch % n_proc != 0:
        raise ValueError(
            f"global batch {global_batch} (= {per_device_batch}/device x "
            f"{n_data} devices) must be a multiple of the process count "
            f"({n_proc})"
        )
    return global_batch, global_batch // n_proc


def eval_scene_shard(n_scenes: int, eval_batch: int, mesh: Mesh) -> tuple:
    """``(rank, world)`` for scene-sharding an eval loader across processes.

    Shards only when every per-process step is a full, locally-shardable
    batch: the scene count must divide ``eval_batch * process_count`` (no
    partial tail) and ``eval_batch`` must be a multiple of the per-process
    slice of the mesh data axis (so batches truly shard). Anything else
    returns ``(0, 1)`` — all processes feed the same scenes, which is
    redundant but exact; a partial or indivisible batch would instead
    fall into ``shard_batch``'s "replicate" path and assemble
    per-process-DISTINCT rows under a sharding JAX believes is replicated
    (silent divergence)."""
    n_proc = jax.process_count()
    local_data = max(1, mesh.shape[DATA_AXIS] // max(1, n_proc))
    if (n_proc > 1
            and n_scenes % (eval_batch * n_proc) == 0
            and eval_batch % local_data == 0):
        return (jax.process_index(), n_proc)
    return (0, 1)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch: Any, mesh: Mesh, on_indivisible: str = "warn") -> Any:
    """Place every array of a batch dict with its batch axis over ``data``.

    A leading axis that does not divide the data axis cannot be sharded.
    ``on_indivisible`` controls what happens then:

      * ``"error"``     — raise (the training path: silent replication would
        run the full batch on every chip — correct but N× the FLOPs, the
        worst failure mode on a throughput-scored project);
      * ``"warn"``      — replicate and ``warnings.warn`` (default);
      * ``"replicate"`` — replicate silently (the reference's batch-size-1
        eval protocol, ``test.py:92``, where replication is intended).
    """
    n_data = mesh.shape[DATA_AXIS]
    sharded = batch_sharding(mesh)
    repl = replicated_sharding(mesh)
    # Multi-host: each process holds only its local slice of the global
    # batch (PrefetchLoader shard=(rank, world)); assemble the global array
    # from the per-process data. device_put to a non-addressable sharding
    # is not allowed, so this is the only correct multi-host path. The
    # replicated case requires every process to feed identical data (the
    # unsharded val/test loaders guarantee that).
    n_proc = jax.process_count()
    multiproc = n_proc > 1
    if multiproc and n_data % n_proc != 0:
        raise ValueError(
            f"mesh data axis ({n_data}) must be a multiple of the process "
            f"count ({n_proc}) for multi-host batch sharding"
        )
    local_data = n_data // n_proc if multiproc else n_data

    def put(x):
        dim = x.shape[0] if getattr(x, "ndim", 0) >= 1 else 0
        ok = dim >= 1 and dim % local_data == 0
        if not ok and n_data > 1:
            msg = (
                f"batch leading axis {getattr(x, 'shape', ())} does not "
                f"divide the per-process share of the mesh data axis "
                f"({local_data} of {n_data}); replicating instead of "
                f"sharding — no batch parallelism"
            )
            if on_indivisible == "error":
                raise ValueError(msg)
            if multiproc and on_indivisible != "replicate":
                # Replication assembles each process's (different, sharded-
                # loader) rows into an array JAX believes is replicated —
                # silent cross-host divergence. Only an explicit
                # "replicate" (caller guarantees identical data on every
                # process, e.g. the unsharded bs=1 eval loaders) is safe.
                raise ValueError(msg + " (unsafe on multi-host: per-process "
                                 "data would silently diverge)")
            if on_indivisible == "warn":
                import warnings

                warnings.warn(msg, stacklevel=3)
        if multiproc:
            return jax.make_array_from_process_local_data(
                sharded if ok else repl, np.asarray(x)
            )
        return jax.device_put(x, sharded if ok else repl)

    return jax.tree_util.tree_map(put, batch)


def device_batch(batch: Any, mesh: Mesh, on_indivisible: str = "warn") -> Any:
    """Host batch dict (numpy) -> device arrays with batch-axis sharding."""
    if jax.process_count() > 1:
        # make_array_from_process_local_data consumes host arrays directly;
        # a jnp.asarray here would add a device round-trip per step.
        return shard_batch(batch, mesh, on_indivisible)
    import jax.numpy as jnp

    return shard_batch(
        {k: jnp.asarray(v) for k, v in batch.items()}, mesh, on_indivisible
    )


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Replicate a pytree over the whole mesh. Multi-host processes each
    contribute their (identical — same seed/checkpoint) local copy, since
    ``device_put`` cannot target a non-addressable sharding."""
    sharding = replicated_sharding(mesh)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            ),
            tree,
        )
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
