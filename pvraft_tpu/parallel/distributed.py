"""Multi-host distributed initialization.

The reference's only scaling mechanism is single-process DataParallel
(``tools/engine.py:63-64``); there is no multi-node path at all. Here
multi-host is the same code path as single-host: initialize the JAX
distributed runtime (one process per host, all devices join one global
mesh), then build the ``(data, seq)`` mesh over ``jax.devices()`` as usual —
XLA routes collectives over ICI within a slice and DCN across slices.
No NCCL/MPI-style backend code exists anywhere in this framework; the
"communication backend" is the XLA runtime itself.

``train.py`` calls :func:`initialize` at startup (before any other JAX
use), so a pod launch is just ``python train.py ...`` on every host; for
custom drivers call it yourself first — in the SAME process that will run
the computation::

    initialize()                                   # env-driven on TPU pods
    initialize(coordinator_address="host0:1234",   # or explicit
               num_processes=4, process_id=rank)
"""

from __future__ import annotations

from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize ``jax.distributed`` (idempotent; no-op on single host).

    With no arguments, relies on the TPU pod environment variables that
    JAX reads natively. Returns True when the distributed runtime is
    (already) initialized, False when running single-process.
    """
    import jax

    if num_processes is None and coordinator_address is None:
        # Single-host unless the environment advertises a multi-host pod.
        import os

        hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        multi = len([h for h in hosts.split(",") if h.strip()]) > 1
        if "JAX_COORDINATOR_ADDRESS" not in os.environ and not multi:
            return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        return True
    except RuntimeError as e:
        msg = str(e).lower()
        if "already" in msg:
            return True
        if "must be called before" in msg:
            # Backend already initialized single-process (e.g. interactive
            # use); not fatal — collectives stay single-host.
            return False
        raise
