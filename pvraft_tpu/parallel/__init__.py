from pvraft_tpu.parallel.mesh import (
    DATA_AXIS,
    SEQ_AXIS,
    batch_sharding,
    make_mesh,
    replicate,
    replicated_sharding,
    shard_batch,
)
from pvraft_tpu.parallel.ring import ring_corr_init

__all__ = [
    "DATA_AXIS",
    "SEQ_AXIS",
    "make_mesh",
    "batch_sharding",
    "replicated_sharding",
    "replicate",
    "shard_batch",
    "ring_corr_init",
]
