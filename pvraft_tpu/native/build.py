"""Build the native data-plane library.

``python -m pvraft_tpu.native.build`` compiles ``npy_loader.cc`` into
``libpvraft_native.so`` next to this file. Requires g++ (baked into the
image); everything degrades gracefully to numpy when the .so is absent.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "npy_loader.cc")
LIB = os.path.join(HERE, "libpvraft_native.so")


def build(force: bool = False) -> str:
    if os.path.exists(LIB) and not force:
        src_m = os.path.getmtime(SRC)
        if os.path.getmtime(LIB) >= src_m:
            return LIB
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        SRC, "-o", LIB,
    ]
    subprocess.run(cmd, check=True)
    return LIB


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(path)
