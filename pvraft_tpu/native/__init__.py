"""ctypes bindings for the native data-plane (see ``npy_loader.cc``).

``native_available()`` gates every use; all call sites fall back to the
numpy implementations when the library has not been built.
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "libpvraft_native.so")
    if not os.path.exists(path):
        # Build on first use when a compiler is present; stay silent and
        # fall back to numpy otherwise.
        try:
            from pvraft_tpu.native.build import build

            path = build()
        except Exception:
            return None
    lib = ctypes.CDLL(path)
    lib.pvraft_npy_shape.restype = ctypes.c_long
    lib.pvraft_npy_shape.argtypes = [ctypes.c_char_p,
                                     ctypes.POINTER(ctypes.c_long)]
    lib.pvraft_npy_read_f32.restype = ctypes.c_long
    lib.pvraft_npy_read_f32.argtypes = [
        ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        ctypes.c_long,
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.pvraft_load_scene_batch.restype = None
    lib.pvraft_load_scene_batch.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p,
        np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
        ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS"),
        np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
        ctypes.c_long,
    ]
    _LIB = lib
    return _LIB


def native_available() -> bool:
    return _load() is not None


def npy_shape(path: str) -> Tuple[int, int]:
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    cols = ctypes.c_long(0)
    rows = lib.pvraft_npy_shape(path.encode(), ctypes.byref(cols))
    if rows < 0:
        raise IOError(f"pvraft_npy_shape({path}) failed: {rows}")
    return int(rows), int(cols.value)


def npy_read(path: str) -> np.ndarray:
    """Read a float .npy as float32 via the native reader."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    rows, cols = npy_shape(path)
    out = np.empty(rows * cols, np.float32)
    cols_out = ctypes.c_long(0)
    got = lib.pvraft_npy_read_f32(path.encode(), out, out.size,
                                  ctypes.byref(cols_out))
    if got < 0:
        raise IOError(f"pvraft_npy_read_f32({path}) failed: {got}")
    return out.reshape(rows, cols) if cols > 1 else out


def load_scene_batch(
    pc1_paths: Sequence[str],
    pc2_paths: Sequence[str],
    scene_indices: Sequence[int],
    n_points: int,
    max_rows: int,
    seed: int,
    epoch: int,
    flip_xz: bool,
    filter_mode: int = 0,
    n_threads: int = 4,
):
    """Threaded native batch assembly. Returns (pc1, pc2, mask, flow,
    status) — status[i]: 1 ok, 0 too-few-points, <0 error. filter_mode:
    0 none, 1 KITTI ground/depth row filter (kitti_hplflownet.py:81-87)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    n = len(pc1_paths)
    out_pc1 = np.empty((n, n_points, 3), np.float32)
    out_pc2 = np.empty((n, n_points, 3), np.float32)
    out_mask = np.empty((n, n_points), np.float32)
    out_flow = np.empty((n, n_points, 3), np.float32)
    status = np.zeros((n,), np.int32)
    idx = np.asarray(scene_indices, np.int64)
    lib.pvraft_load_scene_batch(
        b"\0".join(p.encode() for p in pc1_paths) + b"\0",
        b"\0".join(p.encode() for p in pc2_paths) + b"\0",
        idx, n, n_points, max_rows, seed, epoch, int(flip_xz),
        int(filter_mode),
        out_pc1, out_pc2, out_mask, out_flow, status, n_threads,
    )
    return out_pc1, out_pc2, out_mask, out_flow, status
