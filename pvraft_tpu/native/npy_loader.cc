// Native data-plane for pvraft_tpu: .npy scene IO + threaded batch assembly.
//
// Role: the host-side runtime tier of the framework (the reference leans on
// torch DataLoader worker *processes* for this, tools/engine.py:43-48; here
// a C++ thread pool fills pinned numpy buffers in place, exposed to Python
// via ctypes — no pickling, no process forks, no per-batch allocations).
//
// Scope: float32/float64 little-endian C-order .npy (v1.0/2.0), the only
// layout the preprocessing pipeline emits (pc1/pc2 arrays of shape (N, 3)).
//
// Build: python -m pvraft_tpu.native.build  (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

struct NpyInfo {
  long rows = 0;
  long cols = 0;
  long word = 0;      // bytes per element (4 or 8)
  long offset = 0;    // data start within the file
  bool ok = false;
};

// Parse a v1.0/v2.0 .npy header. Returns header info; data follows at
// `offset`. Only little-endian float ('<f4'/'<f8') C-order arrays of rank
// 1 or 2 are accepted.
NpyInfo parse_header(FILE* f) {
  NpyInfo info;
  unsigned char magic[8];
  if (fread(magic, 1, 8, f) != 8) return info;
  if (memcmp(magic, "\x93NUMPY", 6) != 0) return info;
  const int major = magic[6];
  unsigned long hlen = 0;
  unsigned char lenbuf[4];
  if (major == 1) {
    if (fread(lenbuf, 1, 2, f) != 2) return info;
    hlen = lenbuf[0] | (lenbuf[1] << 8);
    info.offset = 10 + static_cast<long>(hlen);
  } else {
    if (fread(lenbuf, 1, 4, f) != 4) return info;
    hlen = lenbuf[0] | (lenbuf[1] << 8) | (lenbuf[2] << 16) |
           (static_cast<unsigned long>(lenbuf[3]) << 24);
    info.offset = 12 + static_cast<long>(hlen);
  }
  std::string header(hlen, '\0');
  if (fread(header.data(), 1, hlen, f) != hlen) return info;

  if (header.find("'fortran_order': True") != std::string::npos) return info;
  if (header.find("'<f4'") != std::string::npos) {
    info.word = 4;
  } else if (header.find("'<f8'") != std::string::npos) {
    info.word = 8;
  } else {
    return info;
  }

  const auto spos = header.find("'shape':");
  if (spos == std::string::npos) return info;
  const auto open = header.find('(', spos);
  const auto close = header.find(')', open);
  if (open == std::string::npos || close == std::string::npos) return info;
  std::string dims = header.substr(open + 1, close - open - 1);
  long vals[2] = {0, 1};
  int n = 0;
  const char* p = dims.c_str();
  while (*p != '\0' && n < 2) {
    while (*p == ' ' || *p == ',') ++p;
    if (*p == '\0') break;
    char* end = nullptr;
    const long v = strtol(p, &end, 10);
    if (end == p) break;
    vals[n++] = v;
    p = end;
  }
  if (n == 0) return info;
  info.rows = vals[0];
  info.cols = (n == 2) ? vals[1] : 1;
  info.ok = true;
  return info;
}

// Read one .npy file into `out` (float32, capacity elements). Returns
// rows on success, negative error code otherwise.
long read_npy_f32(const char* path, float* out, long capacity, long* cols_out) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  NpyInfo info = parse_header(f);
  if (!info.ok) {
    fclose(f);
    return -2;
  }
  const long total = info.rows * info.cols;
  if (total > capacity) {
    fclose(f);
    return -3;
  }
  if (fseek(f, info.offset, SEEK_SET) != 0) {
    fclose(f);
    return -4;
  }
  if (info.word == 4) {
    if (fread(out, 4, total, f) != static_cast<size_t>(total)) {
      fclose(f);
      return -5;
    }
  } else {
    std::vector<double> tmp(total);
    if (fread(tmp.data(), 8, total, f) != static_cast<size_t>(total)) {
      fclose(f);
      return -5;
    }
    for (long i = 0; i < total; ++i) out[i] = static_cast<float>(tmp[i]);
  }
  fclose(f);
  if (cols_out != nullptr) *cols_out = info.cols;
  return info.rows;
}

// xorshift128+ — deterministic, seedable per (seed, epoch, index).
struct XorShift {
  uint64_t s0, s1;
  explicit XorShift(uint64_t seed) {
    s0 = seed * 0x9E3779B97F4A7C15ULL + 1;
    s1 = (seed ^ 0xDEADBEEFCAFEF00DULL) * 0xBF58476D1CE4E5B9ULL + 1;
    for (int i = 0; i < 8; ++i) next();
  }
  uint64_t next() {
    uint64_t x = s0;
    const uint64_t y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  // Unbiased-enough bounded draw for shuffles.
  long below(long n) { return static_cast<long>(next() % static_cast<uint64_t>(n)); }
};

// Fisher-Yates prefix shuffle: writes a random n_take-subset permutation of
// [0, n) into idx (first n_take entries valid).
void sample_indices(long n, long n_take, uint64_t seed, std::vector<long>* idx) {
  idx->resize(n);
  for (long i = 0; i < n; ++i) (*idx)[i] = i;
  XorShift rng(seed);
  const long limit = n_take < n ? n_take : n;
  for (long i = 0; i < limit; ++i) {
    const long j = i + rng.below(n - i);
    std::swap((*idx)[i], (*idx)[j]);
  }
}

}  // namespace

extern "C" {

// Shape probe: rows/cols of a .npy without reading the payload.
long pvraft_npy_shape(const char* path, long* cols_out) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return -1;
  NpyInfo info = parse_header(f);
  fclose(f);
  if (!info.ok) return -2;
  if (cols_out != nullptr) *cols_out = info.cols;
  return info.rows;
}

long pvraft_npy_read_f32(const char* path, float* out, long capacity,
                         long* cols_out) {
  return read_npy_f32(path, out, capacity, cols_out);
}

// Assemble one batch of scenes in parallel.
//
// For each scene i (paths are NUL-separated in pc1_paths/pc2_paths):
//   * read pc1 (N, 3) and pc2 (M, 3);
//   * draw independent subsample permutations of size n_points for each
//     cloud, seeded by (seed, epoch, scene_index[i]) — the semantics of
//     datasets/generic.py:183-190 with deterministic per-item seeding;
//   * write pc1 rows into out_pc1[i], pc2 rows into out_pc2[i], and
//     flow = pc2_full[perm1] - pc1_full[perm1] into out_flow[i]
//     (index-aligned gt, flyingthings3d_hplflownet.py:104-107);
//   * mask is all ones (out_mask[i]).
//
// filter_mode selects an optional row filter applied to the index-aligned
// clouds before subsampling:
//   0 — none (FT3D);
//   1 — KITTI eval filter (kitti_hplflownet.py:81-87): drop rows where both
//       frames are ground (y < -1.4) or either frame is far (z >= 35 m).
//       Requires pc1/pc2 row counts to match (they are index-aligned).
//
// Scenes whose clouds have fewer than n_points rows are reported in
// status[i] = 0 (caller applies the reject-and-advance policy); success is
// status[i] = 1, IO/parse errors are negative (-3: filter_mode=1 with
// misaligned clouds).
void pvraft_load_scene_batch(
    const char* pc1_paths, const char* pc2_paths, const long* scene_indices,
    long n_scenes, long n_points, long max_rows, uint64_t seed, uint64_t epoch,
    int flip_xz, int filter_mode, float* out_pc1, float* out_pc2,
    float* out_mask, float* out_flow, int* status, long n_threads) {
  std::vector<const char*> p1(n_scenes), p2(n_scenes);
  {
    const char* c1 = pc1_paths;
    const char* c2 = pc2_paths;
    for (long i = 0; i < n_scenes; ++i) {
      p1[i] = c1;
      p2[i] = c2;
      c1 += strlen(c1) + 1;
      c2 += strlen(c2) + 1;
    }
  }

  auto work = [&](long i) {
    std::vector<float> buf1(max_rows * 3), buf2(max_rows * 3);
    long cols = 0;
    long n1 = read_npy_f32(p1[i], buf1.data(), max_rows * 3, &cols);
    if (n1 < 0 || cols != 3) {
      status[i] = -1;
      return;
    }
    long n2 = read_npy_f32(p2[i], buf2.data(), max_rows * 3, &cols);
    if (n2 < 0 || cols != 3) {
      status[i] = -2;
      return;
    }
    if (filter_mode == 1) {
      if (n1 != n2) {
        status[i] = -3;
        return;
      }
      long w = 0;
      for (long r = 0; r < n1; ++r) {
        const bool ground =
            buf1[r * 3 + 1] < -1.4f && buf2[r * 3 + 1] < -1.4f;
        const bool near =
            buf1[r * 3 + 2] < 35.0f && buf2[r * 3 + 2] < 35.0f;
        if (ground || !near) continue;
        for (int c = 0; c < 3; ++c) {
          buf1[w * 3 + c] = buf1[r * 3 + c];
          buf2[w * 3 + c] = buf2[r * 3 + c];
        }
        ++w;
      }
      n1 = n2 = w;
    }
    if (n1 < n_points || n2 < n_points) {
      status[i] = 0;  // caller walks to the next scene
      return;
    }
    if (flip_xz != 0) {  // FT3D axis convention (flyingthings3d_hplflownet.py:100-102)
      for (long r = 0; r < n1; ++r) {
        buf1[r * 3 + 0] = -buf1[r * 3 + 0];
        buf1[r * 3 + 2] = -buf1[r * 3 + 2];
      }
      for (long r = 0; r < n2; ++r) {
        buf2[r * 3 + 0] = -buf2[r * 3 + 0];
        buf2[r * 3 + 2] = -buf2[r * 3 + 2];
      }
    }
    const uint64_t item_seed =
        seed * 1000003ULL + epoch * 7919ULL + static_cast<uint64_t>(scene_indices[i]);
    std::vector<long> perm1, perm2;
    sample_indices(n1, n_points, item_seed, &perm1);
    sample_indices(n2, n_points, item_seed ^ 0x5851F42D4C957F2DULL, &perm2);

    float* o1 = out_pc1 + i * n_points * 3;
    float* o2 = out_pc2 + i * n_points * 3;
    float* om = out_mask + i * n_points;
    float* of = out_flow + i * n_points * 3;
    for (long r = 0; r < n_points; ++r) {
      const long s1 = perm1[r];
      const long s2 = perm2[r];
      for (int c = 0; c < 3; ++c) {
        o1[r * 3 + c] = buf1[s1 * 3 + c];
        o2[r * 3 + c] = buf2[s2 * 3 + c];
        // gt flow follows pc1's permutation (generic.py:185-187).
        of[r * 3 + c] = buf2[s1 * 3 + c] - buf1[s1 * 3 + c];
      }
      om[r] = 1.0f;
    }
    status[i] = 1;
  };

  if (n_threads <= 1 || n_scenes <= 1) {
    for (long i = 0; i < n_scenes; ++i) work(i);
    return;
  }
  std::vector<std::thread> pool;
  std::vector<long> next(1, 0);
  // Simple static partition: thread t handles scenes t, t+T, t+2T, ...
  const long T = n_threads < n_scenes ? n_threads : n_scenes;
  pool.reserve(T);
  for (long t = 0; t < T; ++t) {
    pool.emplace_back([&, t]() {
      for (long i = t; i < n_scenes; i += T) work(i);
    });
  }
  for (auto& th : pool) th.join();
}

}  // extern "C"
