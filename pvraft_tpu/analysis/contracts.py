"""Shape/dtype contracts: the ``@shapecheck`` decorator.

The PV-RAFT pipeline is a chain of shape-contracted ops — ``pc1 (B,N,3)``
-> truncated correlation ``(B,N,K)`` -> flow ``(B,N,3)`` — and the
point/voxel branches drift apart silently until a TPU run explodes.
``@shapecheck`` states the contract at the def site and (when enabled)
verifies it at trace time on CPU, with readable errors.

Zero-cost guarantee: unless ``PVRAFT_CHECKS=1`` is set **at import
time**, the decorator returns the original function object — not a
wrapper — so jaxprs, ids, and call overhead are byte-identical to the
undecorated code (tested in ``tests/test_contracts.py``). Even when
enabled, checks read only static metadata (``x.shape``/``x.dtype``), so
the traced computation — the jaxpr — is unchanged; enabling contracts
can never change numerics.

Spec grammar (one space-separated token per axis)::

    @shapecheck("B N D", "B M D", "B M 3", out=("B N K", "B N K 3"))
    def corr_init(fmap1, fmap2, xyz2, truncate_k, ...): ...

  * ``3``      — literal: the axis must be exactly 3;
  * ``N``      — named: bound on first sight, must match everywhere else
                 in the same call (inputs AND outputs);
  * ``_``      — wildcard: any size;
  * spec ``None`` — skip that argument (non-array / unconstrained);
  * an argument whose parameter defaults to ``None`` is only checked
    when a non-None value arrives (optional array args, e.g. masks);
  * ``out=``   — a spec for the return value, or a tuple of specs zipped
                 against a tuple return (``None`` entries skipped).

``dtype=`` optionally constrains checked args: a jnp dtype-like
(``"float32"``) for an exact match, or the strings ``"floating"`` /
``"integer"`` for a kind check.

No jax import happens at decoration time when checks are off — this
module stays importable (and free) everywhere.
"""

from __future__ import annotations

import functools
import os
from typing import Any, Dict, Optional, Sequence, Tuple, Union

ENV_VAR = "PVRAFT_CHECKS"


def checks_enabled() -> bool:
    """Contracts are on iff ``PVRAFT_CHECKS=1`` (evaluated at import /
    decoration time — the zero-cost path returns undecorated functions)."""
    return os.environ.get(ENV_VAR, "") == "1"


class ShapeError(ValueError):
    """A violated shape/dtype contract, with enough context to act on."""


_Spec = Optional[str]


def _parse(spec: str) -> Tuple[Union[int, str], ...]:
    dims = []
    for tok in spec.split():
        dims.append(int(tok) if tok.lstrip("-").isdigit() else tok)
    if not dims:
        raise ValueError(f"empty shape spec {spec!r}")
    return tuple(dims)


def _shape_of(x: Any):
    return getattr(x, "shape", None)


def _check_one(
    x: Any,
    spec: str,
    bindings: Dict[str, int],
    where: str,
    fn_name: str,
) -> None:
    shape = _shape_of(x)
    if shape is None:
        raise ShapeError(
            f"{fn_name}: {where} expected an array of shape [{spec}], got "
            f"{type(x).__name__} (no .shape)"
        )
    dims = _parse(spec)
    if len(shape) != len(dims):
        raise ShapeError(
            f"{fn_name}: {where} expected rank {len(dims)} [{spec}], got "
            f"rank {len(shape)} shape {tuple(shape)}"
        )
    for axis, (want, got) in enumerate(zip(dims, shape)):
        if want == "_":
            continue
        if isinstance(want, int):
            if got != want:
                raise ShapeError(
                    f"{fn_name}: {where} axis {axis} must be {want} "
                    f"(spec [{spec}]), got shape {tuple(shape)}"
                )
        else:
            bound = bindings.setdefault(want, got)
            if bound != got:
                raise ShapeError(
                    f"{fn_name}: {where} axis {axis} ({want}={got}) "
                    f"conflicts with {want}={bound} bound earlier in this "
                    f"call (spec [{spec}], shape {tuple(shape)}; "
                    f"bindings {bindings})"
                )


def _check_dtype(x: Any, dtype: str, where: str, fn_name: str) -> None:
    got = getattr(x, "dtype", None)
    if got is None:
        return
    import jax.numpy as jnp

    if dtype == "floating":
        ok = jnp.issubdtype(got, jnp.floating)
    elif dtype == "integer":
        ok = jnp.issubdtype(got, jnp.integer)
    else:
        ok = got == jnp.dtype(dtype)
    if not ok:
        raise ShapeError(
            f"{fn_name}: {where} expected dtype {dtype}, got {got}"
        )


class ContractSpec:
    """Parsed decorator arguments, attached to the function as
    ``__shapecheck__`` whether or not checks are enabled (the trace-compat
    audit and tests read it)."""

    def __init__(self, arg_specs, out, dtype):
        self.arg_specs: Tuple[_Spec, ...] = arg_specs
        self.out = out
        self.dtype = dtype

    def __repr__(self):
        return (f"ContractSpec(args={self.arg_specs!r}, out={self.out!r}, "
                f"dtype={self.dtype!r})")


def _check_call(
    spec: ContractSpec, fn_name: str, values
) -> Dict[str, int]:
    """``values``: per-spec ``(present, value)`` pairs (absent = defaulted)."""
    bindings: Dict[str, int] = {}
    for i, (s, (present, value)) in enumerate(zip(spec.arg_specs, values)):
        if s is None or not present:
            continue
        where = f"argument {i}"
        _check_one(value, s, bindings, where, fn_name)
        if spec.dtype is not None:
            _check_dtype(value, spec.dtype, where, fn_name)
    return bindings


def _check_out(spec: ContractSpec, fn_name: str, bindings, result) -> None:
    out = spec.out
    if out is None:
        return
    if isinstance(out, str):
        _check_one(result, out, bindings, "return value", fn_name)
        return
    if not isinstance(result, tuple) or len(result) < len(out):
        raise ShapeError(
            f"{fn_name}: return value expected a tuple of >= {len(out)} "
            f"elements for out specs {out!r}, got {type(result).__name__}"
        )
    for i, s in enumerate(out):
        if s is None:
            continue
        _check_one(result[i], s, bindings, f"return value [{i}]", fn_name)


def wrap_with_spec(fn, spec: ContractSpec):
    """The checking wrapper for ``fn`` (used directly by tests; normal
    code gets it via ``@shapecheck`` when ``PVRAFT_CHECKS=1``)."""
    import inspect

    # Specs align with the function's parameters after `self`; a
    # contracted argument is checked however it is passed — positionally
    # OR by keyword (an unchecked kwarg would be false confidence).
    sig = None
    param_names: Tuple[str, ...] = ()
    try:
        sig = inspect.signature(fn)
        param_names = tuple(sig.parameters)
        if param_names and param_names[0] == "self":
            param_names = param_names[1:]
    except (TypeError, ValueError):
        pass

    def _values(args, kwargs):
        if sig is not None:
            try:
                bound = sig.bind(*args, **kwargs)
            except TypeError:
                bound = None  # fn will raise its own, better error
            if bound is not None:
                values = []
                for name in param_names[: len(spec.arg_specs)]:
                    present = name in bound.arguments
                    value = bound.arguments.get(name)
                    # An optional-None parameter (default None) passed an
                    # explicit None is ABSENT, not a violated contract —
                    # optional mask args (e.g. corr_init's valid2) forward
                    # None through call chains. Required params passing
                    # None still fail: their default is not None.
                    if (present and value is None
                            and sig.parameters[name].default is None):
                        present = False
                    values.append((present, value))
                return values
        # No usable signature: positional-only fallback.
        return [
            (i < len(args), args[i] if i < len(args) else None)
            for i in range(len(spec.arg_specs))
        ]

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        bindings = _check_call(
            spec, fn.__qualname__, _values(args, kwargs)
        )
        result = fn(*args, **kwargs)
        _check_out(spec, fn.__qualname__, bindings, result)
        return result

    wrapper.__shapecheck__ = spec
    wrapper.__shapecheck_inner__ = fn
    return wrapper


def shapecheck(
    *arg_specs: _Spec,
    out: Union[None, str, Tuple[_Spec, ...]] = None,
    dtype: Optional[str] = None,
):
    """Declare (and, under ``PVRAFT_CHECKS=1``, enforce) a shape contract.

    See the module docstring for the grammar. Positional specs align with
    the function's positional parameters (``self`` auto-skipped); trailing
    parameters without specs are unconstrained.
    """
    spec = ContractSpec(arg_specs, out, dtype)

    def deco(fn):
        if not checks_enabled():
            fn.__shapecheck__ = spec  # visible to the audit + tests
            return fn
        return wrap_with_spec(fn, spec)

    return deco
