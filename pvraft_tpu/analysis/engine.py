"""Lint engine: rule registry, suppression handling, file walking.

A rule is a subclass of :class:`Rule` registered with :func:`register`.
Each rule sees the parsed module once (``check(ctx)``) and yields
:class:`Diagnostic` findings. The engine owns everything rule-agnostic:
parsing, per-line/per-file suppression comments, path walking, and
stable ordering of the output.

Suppression syntax (checked literally, like the tools it imitates):

    x = something()          # graftlint: disable=GL001
    x = something_else()     # graftlint: disable=GL001,GL004 -- reason
    # graftlint: disable-next=GL004 -- reason
    from jax.experimental import topologies
    # graftlint: disable-file=GL004 -- pinned-version escape hatch

``disable=...`` silences the named rules on that source line only;
``disable-next=...`` (a comment on its own line) on the line directly
below it; ``disable-file=...`` (anywhere in the file) for the whole
file. ``disable=all`` exists for fixtures and emergencies. Text after
``--`` is a free-form reason and is encouraged.

Only stdlib ``ast``/``re`` here — no jax import — so linting stays fast
and runnable on hosts with no accelerator stack at all.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, Iterator, List, Sequence, Set, Tuple, Type


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One finding: ``path:line:col: rule_id message``."""

    path: str
    line: int
    col: int
    rule_id: str
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule_id} {self.message}"


class LintContext:
    """Per-file state handed to every rule."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # Absolute, forward-slash path so rules scoping by package area
        # ("pvraft_tpu/data/", the compat.py exemption) behave the same
        # whether the lint was invoked on a directory, a relative path,
        # or a bare filename from inside the package.
        if path == "<string>":
            self.norm_path = path
        else:
            self.norm_path = os.path.abspath(path).replace(os.sep, "/")

    def diag(self, node: ast.AST, rule_id: str, message: str) -> Diagnostic:
        return Diagnostic(
            path=self.path,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            rule_id=rule_id,
            message=message,
        )


class Rule:
    """Base class: subclasses set ``id``/``title`` and implement ``check``.

    ``id`` is the stable suppression key (``GLxxx``); ``title`` a short
    slug; the class docstring is the human explanation printed by
    ``lint --list-rules``.
    """

    id: str = ""
    title: str = ""

    def check(self, ctx: LintContext) -> Iterable[Diagnostic]:
        raise NotImplementedError


_REGISTRY: List[Type[Rule]] = []


def register(cls: Type[Rule]) -> Type[Rule]:
    if not cls.id or not cls.title:
        raise ValueError(f"rule {cls.__name__} must set id and title")
    if any(r.id == cls.id for r in _REGISTRY):
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY.append(cls)
    return cls


def all_rules() -> Tuple[Type[Rule], ...]:
    # Rules live in pvraft_tpu.analysis.rules; import lazily to avoid a
    # circular import at package-init time.
    import pvraft_tpu.analysis.rules  # noqa: F401

    return tuple(sorted(_REGISTRY, key=lambda r: r.id))


# --- suppression comments -------------------------------------------------

# ONE pragma grammar, shared by the suppression engine and the debt
# report (`lint --stats`) — what is honored is exactly what is counted.
# The reason parses from the tail; trailing text without the `-- `
# marker still activates the suppression but does NOT count as a reason.
_PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable(?P<kind>-next|-file)?="
    r"(?P<ids>[A-Za-z0-9_,]+)(?P<tail>[^\n]*)"
)
_REASON_RE = re.compile(r"^\s*--\s*(?P<reason>\S.*?)\s*$")


def _parse_pragma(text: str):
    """``(kind, ids, reason)`` of the suppression pragma in a comment,
    or None. kind is "line" | "next" | "file"; reason is "" when the
    pragma gives none."""
    m = _PRAGMA_RE.search(text)
    if not m:
        return None
    kind = {None: "line", "-next": "next", "-file": "file"}[m.group("kind")]
    ids = tuple(i for i in m.group("ids").split(",") if i)
    rm = _REASON_RE.match(m.group("tail") or "")
    return kind, ids, rm.group("reason") if rm else ""


def _comment_tokens(source: str) -> List[Tuple[int, str]]:
    """(lineno, text) of REAL comment tokens — a suppression example shown
    inside a docstring or string literal must never disable anything."""
    out: List[Tuple[int, str]] = []
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out.append((tok.start[0], tok.string))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # partial tokenization still yielded the comments before it
    return out


def _suppressions(source: str):
    """(per-line {lineno: ids}, file-level ids) from suppression comments."""
    per_line: dict = {}
    file_ids: set = set()
    for i, text in _comment_tokens(source):
        parsed = _parse_pragma(text)
        if parsed is None:
            continue
        kind, ids, _reason = parsed
        if kind == "file":
            file_ids.update(ids)
        elif kind == "next":
            per_line.setdefault(i + 1, set()).update(ids)
        else:
            per_line.setdefault(i, set()).update(ids)
    return per_line, file_ids


def _suppressed(d: Diagnostic, per_line, file_ids) -> bool:
    if "all" in file_ids or d.rule_id in file_ids:
        return True
    ids = per_line.get(d.line, ())
    return "all" in ids or d.rule_id in ids


def _expand_decorated_regions(tree: ast.Module, per_line: dict) -> None:
    """Make ``disable-next`` work on decorated definitions.

    A diagnostic on a decorated def/class anchors at the ``def`` line,
    but ``# graftlint: disable-next=...`` placed above the decorator
    targets the decorator's line — so the suppression silently missed.
    Treat the whole header (first decorator through the last signature
    line) as one region: a suppression on any line of it covers all of
    it."""
    for node in ast.walk(tree):
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        if not node.decorator_list:
            continue
        start = min(d.lineno for d in node.decorator_list)
        end = node.body[0].lineno - 1 if node.body else node.lineno
        end = max(end, node.lineno)
        ids: set = set()
        for line in range(start, end + 1):
            ids |= set(per_line.get(line, ()))
        if ids:
            for line in range(start, end + 1):
                per_line.setdefault(line, set()).update(ids)


# --- entry points ---------------------------------------------------------

def lint_source(
    source: str, path: str = "<string>", rule_ids: Sequence[str] = ()
) -> List[Diagnostic]:
    """Lint one source string. ``rule_ids`` restricts to those rules."""
    # A UTF-8 BOM is legal in a Python file but chokes ast.parse when the
    # bytes were decoded as plain utf-8; tolerate it here so BOM'd files
    # get linted instead of reported as syntax errors.
    source = source.lstrip("\ufeff")
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [
            Diagnostic(path, e.lineno or 1, e.offset or 0, "GL000",
                       f"syntax error: {e.msg}")
        ]
    ctx = LintContext(path, source, tree)
    per_line, file_ids = _suppressions(source)
    _expand_decorated_regions(tree, per_line)
    out: List[Diagnostic] = []
    for rule_cls in all_rules():
        if rule_ids and rule_cls.id not in rule_ids:
            continue
        for d in rule_cls().check(ctx):
            if not _suppressed(d, per_line, file_ids):
                out.append(d)
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(
                    d for d in dirs
                    if d not in ("__pycache__", ".git", ".pytest_cache")
                )
                for f in sorted(files):
                    if f.endswith(".py"):
                        yield os.path.join(root, f)
        else:
            raise FileNotFoundError(p)


def lint_paths(
    paths: Sequence[str], rule_ids: Sequence[str] = ()
) -> Tuple[List[Diagnostic], int]:
    """Lint files/directories. Returns (diagnostics, files_checked)."""
    out: List[Diagnostic] = []
    n = 0
    for f in iter_py_files(paths):
        n += 1
        # utf-8-sig: decode (and drop) a BOM if present; identical to
        # utf-8 otherwise. Text mode gives universal newlines, so CRLF
        # sources lint like LF ones.
        with open(f, "r", encoding="utf-8-sig") as fh:
            out.extend(lint_source(fh.read(), path=f, rule_ids=rule_ids))
    out.sort(key=lambda d: (d.path, d.line, d.col, d.rule_id))
    return out, n


# --- external diagnostics (deepcheck) -------------------------------------

def filter_file_suppressions(
    diags: Sequence[Diagnostic],
) -> Tuple[List[Diagnostic], int]:
    """Apply in-file ``# graftlint: disable`` pragmas to externally
    produced diagnostics — deepcheck findings anchored at real source
    lines. Same semantics as the AST path, including the decorated-def
    header regions (a GJ finding anchored at an ``@audit_entry`` line is
    suppressible from anywhere in that header). Unreadable/virtual
    anchor paths suppress nothing. Returns ``(kept, n_suppressed)``."""
    cache: Dict[str, Tuple[dict, set]] = {}
    kept: List[Diagnostic] = []
    suppressed = 0
    for d in diags:
        if d.path not in cache:
            try:
                with open(d.path, "r", encoding="utf-8-sig") as fh:
                    source = fh.read()
            except OSError:
                cache[d.path] = ({}, set())
            else:
                per_line, file_ids = _suppressions(source)
                try:
                    tree = ast.parse(source.lstrip("\ufeff"), filename=d.path)
                except SyntaxError:
                    pass  # pragmas still apply line-exact
                else:
                    _expand_decorated_regions(tree, per_line)
                cache[d.path] = (per_line, file_ids)
        per_line, file_ids = cache[d.path]
        if _suppressed(d, per_line, file_ids):
            suppressed += 1
        else:
            kept.append(d)
    return kept, suppressed


# --- suppression-debt report (`lint --stats`) -----------------------------

@dataclasses.dataclass(frozen=True)
class Pragma:
    """One active suppression comment found in a source file."""

    path: str
    line: int
    kind: str           # "line" | "next" | "file"
    ids: Tuple[str, ...]
    reason: str         # "" when the pragma gives none


def collect_suppressions(paths: Sequence[str]) -> List[Pragma]:
    """Every active suppression pragma under ``paths`` — the gate's
    enumerable blind spots. Real comment tokens only (the docstring
    examples in this file don't count), same discipline as the
    suppression engine itself."""
    out: List[Pragma] = []
    for f in iter_py_files(paths):
        with open(f, "r", encoding="utf-8-sig") as fh:
            source = fh.read()
        for lineno, text in _comment_tokens(source):
            parsed = _parse_pragma(text)
            if parsed is None:
                continue
            kind, ids, reason = parsed
            out.append(Pragma(path=f, line=lineno, kind=kind, ids=ids,
                              reason=reason))
    out.sort(key=lambda p: (p.path, p.line))
    return out


def known_rule_ids() -> Set[str]:
    """Ids of every registered rule: AST (GL), jaxpr (GJ), concurrency
    (GC), kernel (GK), sharding (GS), determinism (GD) and gate (GE)
    families — one namespace for the shared pragma grammar, so ``lint
    --stats`` counts every engine's suppressions and flags none of them
    as unknown."""
    ids = {r.id for r in all_rules()}
    try:
        from pvraft_tpu.analysis.jaxpr.rules import all_jaxpr_rules

        ids |= {r.id for r in all_jaxpr_rules()}
    except ImportError:  # pragma: no cover - partial checkouts only
        pass
    try:
        from pvraft_tpu.analysis.concurrency.rules import (
            all_concurrency_rules,
        )

        ids |= {r.id for r in all_concurrency_rules()}
        ids.add("GC000")  # the checker's syntax-error diagnostic
    except ImportError:  # pragma: no cover - partial checkouts only
        pass
    try:
        from pvraft_tpu.analysis.kernels.rules import all_kernel_rules

        ids |= {r.id for r in all_kernel_rules()}
        ids.add("GK000")  # the model-incomplete/syntax diagnostic
    except ImportError:  # pragma: no cover - partial checkouts only
        pass
    try:
        from pvraft_tpu.analysis.sharding.rules import all_sharding_rules

        ids |= {r.id for r in all_sharding_rules()}
        ids.add("GS000")  # the checker's syntax-error diagnostic
    except ImportError:  # pragma: no cover - partial checkouts only
        pass
    try:
        from pvraft_tpu.analysis.determinism.rules import (
            all_determinism_rules,
        )

        ids |= {r.id for r in all_determinism_rules()}
        ids.add("GD000")  # the checker's syntax-error diagnostic
    except ImportError:  # pragma: no cover - partial checkouts only
        pass
    try:
        from pvraft_tpu.analysis.gate.rules import all_gate_rules

        ids |= {r.id for r in all_gate_rules()}
        ids.add("GE000")  # the evidence-model build-error diagnostic
    except ImportError:  # pragma: no cover - partial checkouts only
        pass
    return ids
