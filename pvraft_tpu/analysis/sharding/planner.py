"""Pod-scale memory/comms planner: the ``pvraft_pod_plan/v1`` artifact.

Joins the declared ``PARTITION_RULES`` ladder, the committed param-tree
leaf inventory (``artifacts/params_tree.json``) and the committed cost
inventory (``artifacts/programs_costs.json``) with the candidate
``(dp, sp)`` meshes into a machine-checked plan — the committed answer
to "which mesh does a 100k-point scene train on", which ROADMAP item 2
cites the way item 1 cites ``kernel_plan.json``:

* per mesh: per-device param/optimizer bytes honoring the partition
  rules (replicated leaves pay full freight on every chip — the plan
  shows exactly how little that costs at this model's size, and starts
  shrinking the day a rule shards);
* per (mesh, scene): per-device activation bytes (linear B x N scaling
  from the ``flagship_train_step_fp32_remat`` record — the supported
  fp32 path), the ring-fold transient under the declared chunking, the
  batch arrays, and the fits-16GiB verdict;
* ring comms: per-hop bytes x (p-1) hops from the ``ring.py`` geometry
  (the last fold's chunk is never forwarded — the deepcheck GJ002 fix)
  against per-step compute at the v5e roofline;
* an honesty cross-check against the committed ``dp_sp_2x2_train_step``
  compile record: the model's per-device estimate for that exact
  geometry must sit inside a pinned band of the real (un-remat'd)
  ``live_bytes_estimate`` — an axis mixup or a lost per-device division
  refuses the plan instead of committing fiction.

Everything is a pure function of committed inputs — no timestamps, no
toolchain — so ``artifacts/pod_plan.json`` is byte-deterministic and
``sharding --check`` regenerates and compares it exactly (the
``kernel_plan.json`` discipline, pinned in ``scripts/lint.sh``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pvraft_tpu.analysis.kernels.planner import (
    HBM_BYTES_PER_S,
    PEAK_FLOPS_F32,
    _round,
)
from pvraft_tpu.analysis.sharding.check import (
    check_paths,
    declared_axes,
    default_scope,
)
from pvraft_tpu.programs.geometries import (
    FLAGSHIP_BATCH,
    FLAGSHIP_POINTS,
    HBM_BYTES,
)
from pvraft_tpu.programs.partitioning import (
    PARTITION_RULES,
    leaf_bytes,
    load_params_tree,
    match_partition_rules,
    shard_factor,
)

PLAN_SCHEMA = "pvraft_pod_plan/v1"

# Candidate (dp, sp) meshes — data-parallel x sequence-parallel. 2x2 is
# the certified registry spec; the ladder extends it toward a v5e pod
# slice (32 chips at 8x4).
CANDIDATE_MESHES: Tuple[Tuple[int, int], ...] = ((2, 2), (4, 2), (4, 4),
                                                 (8, 4))

# Scene sizes the pod campaign must answer for: the serve buckets, the
# flagship, the 16k long-context target and the 100k stretch scene.
SCENE_POINTS: Tuple[int, ...] = (2048, 8192, 16384, 100000)

# The activation basis: the supported fp32 training path (remat'd GRU
# iterations — plain fp32 does not fit one chip, see the catalog's
# expect_failure record). Its temp bytes at (B=2, N=8192) scale
# linearly in B x N; the dense-pairwise transient baked into the basis
# makes the linear extrapolation mildly conservative for ring runs.
ACTIVATION_BASIS_PROGRAM = "flagship_train_step_fp32_remat"

# The cross-check target: the real compiled sharded step (un-remat'd).
SHARDED_STEP_PROGRAM = "dp_sp_2x2_train_step"

# The model's remat-basis estimate for the dp_sp geometry must sit in
# this band of the compiled un-remat'd live bytes: above 1.0 the
# "cheaper" remat model exceeds the real un-remat program (broken
# model); below 1/8 something lost a dimension or a per-device divide.
CROSS_CHECK_BAND = (1.0 / 8.0, 1.0)

# Pod scenario knobs (declared, recorded in the artifact):
PER_DEVICE_BATCH = 1          # one scene per data-row — the memory floor
ADAM_STATE_FACTOR = 2         # mu + nu mirror the param tree
RING_CHUNK = 4096             # corr_chunk for the ring fold (the config
#                               lever that bounds the (N/sp)^2 transient)
RING_FOLD_FACTOR = 3          # fold matrix + top-k concat + xyz planes
FEATURE_DIM_FALLBACK = 128

# v5e inter-chip interconnect: 1,600 Gbps aggregate per chip over 4
# links (public spec) — a ring hop rides one link, ~50 GB/s.
ICI_BYTES_PER_S = 50e9

_F32 = 4
# Batch arrays per scene row: pc1 + pc2 + gt (3 floats each) + mask.
_BATCH_FLOATS_PER_POINT = 10


def _feature_dim() -> int:
    try:
        from pvraft_tpu.config import ModelConfig

        return int(ModelConfig().feature_dim)
    except Exception:  # pragma: no cover - partial checkouts only
        return FEATURE_DIM_FALLBACK


def _cost_record(costs: Dict[str, Any], name: str) -> Optional[Dict[str, Any]]:
    for rec in costs.get("programs", ()):
        if isinstance(rec, dict) and rec.get("name") == name:
            return rec
    return None


# --- per-device byte accounting --------------------------------------------

def param_bytes_per_device(leaves: Sequence[Dict[str, Any]],
                           mesh_shape: Dict[str, int]) -> int:
    """Sum of leaf bytes / shard factor under the declared rules."""
    spec_of = match_partition_rules(
        PARTITION_RULES, [leaf["path"] for leaf in leaves])
    total = 0
    for leaf in leaves:
        factor = shard_factor(spec_of[leaf["path"]], mesh_shape)
        total += -(-leaf_bytes(leaf) // factor)  # ceil-divide
    return total


def activation_bytes_per_point(costs: Dict[str, Any]) -> float:
    """temp bytes of the remat'd flagship step per (batch x point)."""
    rec = _cost_record(costs, ACTIVATION_BASIS_PROGRAM)
    if rec is None or not rec.get("ok"):
        raise ValueError(
            f"costs artifact has no ok record for "
            f"{ACTIVATION_BASIS_PROGRAM!r} — regenerate "
            f"programs_costs.json")
    temp = int((rec.get("memory") or {}).get("temp_size_in_bytes", 0))
    if temp <= 0:
        raise ValueError(
            f"{ACTIVATION_BASIS_PROGRAM}: temp_size_in_bytes missing "
            f"from the costs record")
    return temp / float(FLAGSHIP_BATCH * FLAGSHIP_POINTS)


def ring_transient_bytes(points_per_device: int, chunk: int,
                         per_device_batch: int = PER_DEVICE_BATCH) -> int:
    """Fold-transient bytes of one ring step at the declared chunking:
    the (Nq_local x chunk) fold matrix plus the top-k concat and
    gathered xyz planes (RING_FOLD_FACTOR, declared)."""
    c = min(points_per_device, chunk)
    return (per_device_batch * points_per_device * c
            * _F32 * RING_FOLD_FACTOR)


def ring_comms(points_per_device: int, sp: int, feature_dim: int,
               per_device_batch: int = PER_DEVICE_BATCH) -> Dict[str, Any]:
    """Per-step ring traffic from the ``ring.py`` geometry: each hop
    forwards this device's circulating chunk; ``sp - 1`` hops per ring
    (the final fold's chunk is never sent — the GJ002 fix). Rings per
    step: two kNN graph rings (pc1, pc2 — xyz chunks, int indices stay
    local, no backward traffic) and one correlation ring (fmap2 + xyz2
    chunks, counted twice for the ppermute transpose in the backward)."""
    hops = max(0, sp - 1)
    knn_hop = per_device_batch * points_per_device * 3 * _F32
    corr_hop = per_device_batch * points_per_device * \
        (feature_dim + 3) * _F32
    total = hops * (2 * knn_hop + 2 * corr_hop)
    return {
        "hops": hops,
        "knn_per_hop_bytes": knn_hop,
        "knn_rings": 2,
        "corr_per_hop_bytes": corr_hop,
        "corr_rings_fwd_bwd": 2,
        "total_bytes_per_step": total,
    }


# --- plan assembly ----------------------------------------------------------

def build_plan(costs_path: str,
               params_path: str) -> Dict[str, Any]:
    """The full ``pvraft_pod_plan/v1`` document. Raises ValueError on
    any problem — shardcheck findings in the gate scope, a failed
    cross-check, missing basis records — so the plan is only
    committable when the checker and the pins agree."""
    with open(costs_path, "r", encoding="utf-8") as f:
        costs = json.load(f)
    tree = load_params_tree(params_path)
    leaves = tree["leaves"]
    leaf_paths = [leaf["path"] for leaf in leaves]

    problems: List[str] = []
    findings, _n = check_paths(list(default_scope()),
                               param_leaves=leaf_paths)
    problems.extend(f"shardcheck finding: {d.format()}" for d in findings)

    try:
        act_per_bn = activation_bytes_per_point(costs)
    except ValueError as e:
        problems.append(str(e))
        act_per_bn = 0.0
    feature_dim = _feature_dim()

    def scene_row(sp: int, n_points: int) -> Tuple[int, int, int, int]:
        pts = n_points // sp
        act = int(act_per_bn * PER_DEVICE_BATCH * pts)
        transient = ring_transient_bytes(pts, RING_CHUNK)
        batch = (PER_DEVICE_BATCH * pts
                 * _BATCH_FLOATS_PER_POINT * _F32)
        return pts, act, transient, batch

    meshes: List[Dict[str, Any]] = []
    for dp, sp in CANDIDATE_MESHES:
        mesh_shape = {"data": dp, "seq": sp}
        pbytes = param_bytes_per_device(leaves, mesh_shape)
        obytes = ADAM_STATE_FACTOR * pbytes
        rec: Dict[str, Any] = {
            "dp": dp,
            "sp": sp,
            "devices": dp * sp,
            "global_batch": PER_DEVICE_BATCH * dp,
            "params_bytes_per_device": pbytes,
            "optimizer_bytes_per_device": obytes,
            "scenes": [],
        }
        for n_points in SCENE_POINTS:
            if n_points % sp:
                rec["scenes"].append({
                    "n_points": n_points,
                    "fits_16GiB_hbm": False,
                    "verdict": f"seq axis {sp} does not divide "
                               f"{n_points} points",
                })
                continue
            pts, act, transient, batch = scene_row(sp, n_points)
            total = pbytes + obytes + act + transient + batch
            fits = total <= HBM_BYTES
            comms = ring_comms(pts, sp, feature_dim)
            flops_per_device = 0.0
            basis = _cost_record(costs, ACTIVATION_BASIS_PROGRAM) or {}
            flops_flagship = float(basis.get("flops", 0.0) or 0.0)
            if flops_flagship:
                scale = (PER_DEVICE_BATCH * dp * n_points) / float(
                    FLAGSHIP_BATCH * FLAGSHIP_POINTS)
                flops_per_device = flops_flagship * scale / (dp * sp)
            compute_s = (flops_per_device / PEAK_FLOPS_F32
                         if flops_per_device else 0.0)
            comm_s = comms["total_bytes_per_step"] / ICI_BYTES_PER_S
            scene: Dict[str, Any] = {
                "n_points": n_points,
                "points_per_device": pts,
                "activation_bytes": act,
                "ring_transient_bytes": transient,
                "batch_bytes": batch,
                "total_bytes_per_device": total,
                "fits_16GiB_hbm": fits,
                "ring": dict(comms, **{
                    "comm_seconds_per_step": _round(comm_s),
                    "compute_seconds_per_step": _round(compute_s),
                    "comm_compute_ratio": _round(
                        comm_s / compute_s if compute_s else 0.0),
                }),
                "verdict": (
                    f"{total / 2**30:.2f} GiB of "
                    f"{HBM_BYTES / 2**30:.0f} GiB per device — "
                    + ("fits" if fits else "does NOT fit")),
            }
            rec["scenes"].append(scene)
        meshes.append(rec)

    # Honesty cross-check vs the committed sharded-step compile record.
    cross: Dict[str, Any] = {"program": SHARDED_STEP_PROGRAM}
    ds = _cost_record(costs, SHARDED_STEP_PROGRAM)
    if ds is None or not ds.get("ok"):
        problems.append(
            f"costs artifact has no ok record for "
            f"{SHARDED_STEP_PROGRAM!r} — cross-check impossible")
    elif act_per_bn:
        live = int((ds.get("memory") or {}).get("live_bytes_estimate", 0))
        # The dp_sp program's OWN geometry, not the scenario knobs:
        # global B=FLAGSHIP_BATCH over dp=2, N=FLAGSHIP_POINTS over
        # sp=2 — so every byte term below uses the same b_loc even if
        # PER_DEVICE_BATCH is ever re-declared.
        b_loc = max(1, FLAGSHIP_BATCH // 2)
        pts = FLAGSHIP_POINTS // 2
        pbytes = param_bytes_per_device(leaves, {"data": 2, "seq": 2})
        model_total = (pbytes + ADAM_STATE_FACTOR * pbytes
                       + int(act_per_bn * b_loc * pts)
                       + ring_transient_bytes(pts, RING_CHUNK,
                                              per_device_batch=b_loc)
                       + b_loc * pts * _BATCH_FLOATS_PER_POINT * _F32)
        ratio = model_total / live if live else float("inf")
        lo, hi = CROSS_CHECK_BAND
        cross.update({
            "compiled_live_bytes_per_device": live,
            "model_bytes_per_device": model_total,
            "model_vs_compiled_ratio": _round(ratio),
            "band": [lo, hi],
            "note": ("the compiled record is the un-remat'd step; the "
                     "remat-basis model must come in below it but not "
                     "vanish — outside the band the byte model has "
                     "diverged from the real program"),
        })
        if not (lo <= ratio <= hi):
            problems.append(
                f"{SHARDED_STEP_PROGRAM}: model estimate {model_total} B "
                f"vs compiled live {live} B — ratio {ratio:.3f} outside "
                f"the pinned [{lo:g}, {hi:g}] band; the pod byte model "
                f"has diverged from the real sharded program")

    if problems:
        raise ValueError("pod plan cannot be built:\n  "
                         + "\n  ".join(problems))

    # Headline verdicts ROADMAP item 2 cites.
    scene_verdicts: Dict[str, str] = {}
    for n_points in SCENE_POINTS:
        fitting = [f"{m['dp']}x{m['sp']}" for m in meshes
                   if any(s["n_points"] == n_points
                          and s.get("fits_16GiB_hbm") for s in m["scenes"])]
        scene_verdicts[str(n_points)] = (
            f"fits per-device on: {', '.join(fitting)}" if fitting
            else "fits NO candidate mesh — a bigger seq axis or a "
                 "smaller ring chunk is required")

    return {
        "schema": PLAN_SCHEMA,
        "topology": costs.get("topology"),
        "costs_artifact": os.path.basename(costs_path),
        "params_artifact": os.path.basename(params_path),
        "declared_axes": sorted(declared_axes() or ("data", "seq")),
        "partition_rules": [[pat, list(spec)]
                            for pat, spec in PARTITION_RULES],
        "params": {
            "leaves": len(leaves),
            "total_parameters": tree["total_parameters"],
            "total_bytes": tree["total_bytes"],
        },
        "scenario": {
            "per_device_batch": PER_DEVICE_BATCH,
            "remat_policy": "dots",
            "activation_basis": ACTIVATION_BASIS_PROGRAM,
            "activation_bytes_per_batch_point": _round(act_per_bn),
            "ring_chunk": RING_CHUNK,
            "ring_fold_factor": RING_FOLD_FACTOR,
            "adam_state_factor": ADAM_STATE_FACTOR,
            "feature_dim": feature_dim,
        },
        "interconnect": {
            "ici_bytes_per_s": ICI_BYTES_PER_S,
            "peak_flops_f32": PEAK_FLOPS_F32,
            "hbm_bytes_per_s": HBM_BYTES_PER_S,
            "basis": "public TPU v5e specs (one ICI link per ring hop)",
        },
        "hbm_limit_bytes": HBM_BYTES,
        "meshes": meshes,
        "sharded_step_cross_check": cross,
        "scene_verdicts": scene_verdicts,
    }


def write_plan(plan: Dict[str, Any], out_path: str) -> None:
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(plan, f, indent=1, sort_keys=True)
        f.write("\n")


def check_plan_file(path: str, costs_path: str,
                    params_path: str) -> List[str]:
    """Regenerate the plan from the committed inputs and compare — a
    stale or hand-edited artifact fails here (the kernel_plan.json
    discipline). Returns problems ([] = up to date)."""
    try:
        with open(path, "r", encoding="utf-8") as f:
            committed = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable: {e}"]
    if not isinstance(committed, dict):
        return [f"{path}: artifact is {type(committed).__name__}, not a "
                f"{PLAN_SCHEMA} object — regenerate"]
    try:
        fresh = build_plan(costs_path, params_path)
    except (OSError, ValueError) as e:
        return [f"{path}: cannot rebuild plan: {e}"]
    if committed != fresh:
        drift = [k for k in sorted(set(committed) | set(fresh))
                 if committed.get(k) != fresh.get(k)]
        return [
            f"{path}: committed plan drifted from the regenerated one "
            f"(differing keys: {', '.join(drift)}) — regenerate: "
            f"python -m pvraft_tpu.analysis sharding --plan --out {path}"]
    return []
